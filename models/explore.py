"""Exhaustive small-model explorer: the TLA+ pillar's teeth.

The reference model-checks its protocol specs with TLC over tiny
geometries and bounded behaviors (``tla+/multipaxos_smr_style/
MultiPaxos.tla``, ``tla+/tlc_model_check.sh``).  The kernels here are
pure functions of ``(state, netstate, inputs)``, which makes the same
exhaustion directly executable: enumerate EVERY fault schedule over a
bounded horizon at a tiny geometry (G=1, R=3, W=4), stepping the real
jitted kernel — not a re-modeled abstraction of it — and assert the
safety invariants at every reached node:

- **agreement**: no two replicas commit different values for a slot;
- **durability**: a binding committed in the parent never changes in the
  child (edge-local along every path).

The network is made deterministic (fixed delay, no jitter, no drops) so
nondeterminism comes only from the enumerated fault alphabet: per round
(2 lockstep ticks) one of {all-up, kill r, isolate r | r in replicas} —
7 actions, explored breadth-first with state-hash deduplication over
``(kernel state, network state)``.  Window wraps, go-back-N rewinds,
elections (timeouts are shrunk to fire within the horizon) and the
install-snapshot heal plane all engage at W=4, which is exactly the
regime where the sweep found the rspaxos exec-lag step-up bug.

The sweep doubles as the **soundness oracle for the range prover**
(``analysis/ranges.py``): every state the exploration visits must
satisfy every proven per-leaf interval invariant and pairwise fact for
the exact kernel instance being stepped.  The prover's documented
no-wrap abstraction (saturating interval arithmetic) and its jaxpr
walk are thereby cross-validated against concretely reached states —
a violated invariant fails the run and names the leaf, the claimed
interval, the witness bounds and the fault schedule step that reached
it.

Scope note: durability is checked edge-locally against each path's own
accumulator; converging paths dedup on state hash PLUS a digest of the
accumulator's out-of-window portion.  Identical states imply identical
windows, so in-window rewrites cannot hide behind dedup — but two paths
can reach the same (state, netstate) having committed *different* values
for slots that already slid out of every window; without the accumulator
digest the second path would be pruned and its divergent history never
checked against descendants.  Folding the out-of-window bindings into
the key keeps both paths explored (at the cost of some extra expansion).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import sys
from collections import deque
from typing import Any, Dict, Iterable, List, Tuple

# runnable as `python models/explore.py` from the repo root (script
# mode puts models/ — not the repo root — on sys.path)
sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

import jax
import jax.numpy as jnp
import numpy as np

from summerset_tpu.core import Engine, NetConfig
from summerset_tpu.protocols import make_protocol

G = 1  # one group: the fault alphabet acts on all groups identically


def _actions(R: int) -> List[Tuple[str, np.ndarray, np.ndarray]]:
    """(name, alive [G,R], link_up [G,R,R]) fault alphabet."""
    acts = []
    up = np.ones((G, R), bool)
    full = np.ones((G, R, R), bool)
    acts.append(("up", up, full))
    for r in range(R):
        alive = up.copy()
        alive[:, r] = False
        acts.append((f"kill{r}", alive, full))
    for r in range(R):
        link = full.copy()
        link[:, r, :] = link[:, :, r] = False
        link[:, r, r] = True
        acts.append((f"iso{r}", up, link))
    return acts


def _state_hash(state: Dict[str, Any], ns: Any) -> bytes:
    h = hashlib.blake2b(digest_size=16)
    for k in sorted(state):
        h.update(k.encode())
        h.update(np.asarray(state[k]).tobytes())
    for leaf in jax.tree_util.tree_leaves(ns):
        h.update(np.asarray(leaf).tobytes())
    return h.digest()


def _oow_digest(acc: Dict[int, int], visible: Dict[int, int]) -> bytes:
    """Digest of the accumulator's out-of-window portion: committed
    bindings no longer re-derivable from any replica's window.  Folded
    into the dedup key so two paths converging on the same state with
    different slid-out histories are both kept (module docstring)."""
    items = [(s, v) for s, v in sorted(acc.items()) if s not in visible]
    if not items:
        return b""
    h = hashlib.blake2b(digest_size=8)
    for s, v in items:
        h.update(s.to_bytes(8, "little") + v.to_bytes(8, "little"))
    return h.digest()


def _committed(state: Dict[str, np.ndarray], R: int, W: int) -> Dict[int, int]:
    """Merged {slot: value} over replicas' windows; raises on divergence."""
    merged: Dict[int, int] = {}
    for r in range(R):
        cb = int(state["commit_bar"][0, r])
        absw = state["win_abs"][0, r]
        valw = state["win_val"][0, r]
        for p in range(W):
            a = int(absw[p])
            if 0 <= a < cb:
                v = int(valw[p])
                if a in merged and merged[a] != v:
                    raise AssertionError(
                        f"agreement violated: slot {a}: {merged[a]} != {v} "
                        f"(replica {r})"
                    )
                merged[a] = v
    return merged


@dataclasses.dataclass
class ExploreResult:
    protocol: str
    depth: int
    round_ticks: int
    nodes_expanded: int
    dedup_hits: int
    max_committed_slots: int
    violations: List[str]
    # quorum-tally transport the kernel was compiled with
    # (core/quorum.py): "pairwise" or "collective"
    tally: str = "pairwise"
    # range-prover oracle (module docstring): how many proven leaf
    # invariants / pairwise facts were asserted at every visited state
    # (0 = oracle off); violations land in `violations` like the
    # safety properties
    range_leaves: int = 0
    range_pairs: int = 0

    def as_json(self) -> dict:
        return dataclasses.asdict(self)


def explore(protocol: str = "multipaxos", R: int = 3, W: int = 4,
            depth: int = 6, round_ticks: int = 2,
            config_overrides: Dict[str, Any] | None = None,
            tally: str = "pairwise", range_oracle: bool = True,
            progress: bool = False) -> ExploreResult:
    """Breadth-first exhaustion of all fault schedules of ``depth`` rounds."""
    # probe the config type at a wide window (tiny W would trip the
    # default max_proposals_per_tick guard before we can shrink it)
    base = make_protocol(protocol, G, R, 64)
    overrides = dict(config_overrides or {})
    if tally != "pairwise":
        if not hasattr(base.config, "tally"):
            # fail fast: a silently-downgraded exhaustion would let a
            # MODELCHECK regen claim collective coverage that never ran
            raise ValueError(
                f"protocol {protocol!r} has no quorum-tally knob; "
                f"cannot explore tally={tally!r}"
            )
        overrides.setdefault("tally", tally)
    cfg = dataclasses.replace(
        base.config,
        max_proposals_per_tick=1,
        # elections must be reachable within the horizon
        hear_timeout_lo=4,
        hear_timeout_hi=6,
        retry_interval=2,
        **overrides,
    )
    kernel = make_protocol(protocol, G, R, W, cfg)
    # range-prover oracle: derive the proven invariants for THIS exact
    # kernel instance (same geometry, same shrunken-timeout config the
    # exploration steps), then assert them at every visited state.  The
    # engine runs the telemetry-free compile of the same step, so leaves
    # absent from the stepped state (``telem``) are skipped.
    inv_items: List[Tuple[str, Tuple[int, int]]] = []
    pair_items: Tuple[Tuple[str, str], ...] = ()
    if range_oracle:
        from summerset_tpu.analysis.ranges import analyze_kernel_ranges

        ra = analyze_kernel_ranges(kernel)
        inv_items = sorted(ra.invariants.items())
        pair_items = ra.pairs

    def check_ranges(np_state: Dict[str, np.ndarray],
                     where: str) -> List[str]:
        out = []
        for leaf, (lo, hi) in inv_items:
            a = np_state.get(leaf)
            if a is None:
                continue
            mn, mx = int(a.min()), int(a.max())
            if mn < lo or mx > hi:
                out.append(
                    f"range invariant violated: {leaf} proven in "
                    f"[{lo}, {hi}] but witness state at {where} has "
                    f"[{mn}, {mx}]"
                )
        for x, y in pair_items:
            ax, ay = np_state.get(x), np_state.get(y)
            if ax is None or ay is None:
                continue
            if not bool(np.all(ax <= ay)):
                i = int(np.argmax(np.ravel(ax > ay)))
                out.append(
                    f"range pair violated: {x} <= {y} proven but "
                    f"witness state at {where} has {x}="
                    f"{int(np.ravel(ax)[i])} > {y}={int(np.ravel(ay)[i])} "
                    f"(flat index {i})"
                )
        return out

    eng = Engine(kernel, netcfg=NetConfig(delay_ticks=1), seed=0)
    state0, ns0 = eng.init()
    # drop the metric-lane block (core/telemetry.py): presence is a
    # static compile condition, so popping it compiles the lane-free
    # kernel — exploration neither asserts on the lanes nor wants a
    # [G,R,K] int32 block stored per node
    state0.pop("telem", None)
    acts = _actions(R)

    def run_round(state, ns, alive, link, vbase):
        for t in range(round_ticks):
            inputs = {
                "n_proposals": jnp.ones((G,), jnp.int32),
                "value_base": jnp.full((G,), vbase + t, jnp.int32),
                "alive": jnp.asarray(alive),
                "link_up": jnp.asarray(link),
            }
            state, ns, _ = eng.tick(state, ns, inputs)
        return state, ns

    nodes = deque()
    np0 = {k: np.asarray(v) for k, v in state0.items()}
    acc0 = _committed(np0, R, W)
    nodes.append((state0, ns0, acc0, 0))
    seen = {_state_hash(state0, ns0) + _oow_digest(acc0, acc0)}
    expanded = 0
    dedup = 0
    max_committed = 0
    violations: List[str] = []
    violations.extend(check_ranges(np0, "init"))

    while nodes:
        state, ns, acc, d = nodes.popleft()
        if d >= depth:
            continue
        for name, alive, link in acts:
            vbase = 1 + d * round_ticks  # unique value per (depth, tick)
            s2, n2 = run_round(state, ns, alive, link, vbase)
            expanded += 1
            np2 = {k: np.asarray(v) for k, v in s2.items()}
            try:
                cm = _committed(np2, R, W)
                for slot, v in acc.items():
                    if slot in cm and cm[slot] != v:
                        raise AssertionError(
                            f"durability violated: slot {slot}: "
                            f"{v} -> {cm[slot]} after {name}@d{d}"
                        )
            except AssertionError as e:
                violations.append(str(e))
                continue
            rv = check_ranges(np2, f"{name}@d{d}")
            if rv:
                violations.extend(rv)
                continue
            acc2 = dict(acc)
            acc2.update(cm)
            max_committed = max(max_committed, len(acc2))
            h = _state_hash(s2, n2) + _oow_digest(acc2, cm)
            if h in seen:
                dedup += 1
                continue
            seen.add(h)
            nodes.append((s2, n2, acc2, d + 1))
        if progress and expanded % 500 < len(acts):
            print(f"  d<{depth} expanded={expanded} frontier={len(nodes)} "
                  f"dedup={dedup}", flush=True)

    return ExploreResult(
        protocol=protocol, depth=depth, round_ticks=round_ticks,
        nodes_expanded=expanded, dedup_hits=dedup,
        max_committed_slots=max_committed, violations=violations,
        tally=getattr(cfg, "tally", "pairwise"),
        range_leaves=len(inv_items), range_pairs=len(pair_items),
    )


# per-protocol config overrides for CLI runs (rspaxos with an extra
# required ack actually exercises the commit_k/full-quorum veto paths;
# ft=0 would be the degenerate plain-majority configuration; crossword
# pins the reactive assignment policy off so the enumerated fault
# alphabet — not liveness-countdown feedback — is the only
# nondeterminism source, and ft=0 keeps commit_k = majority at R=3,
# the smallest geometry where diagonal shard slicing is live)
CLI_PRESETS: Dict[str, Dict[str, Any]] = {
    "rspaxos": {"fault_tolerance": 1},
    "crossword": {"fault_tolerance": 0, "assignment_adaptive": False},
}


def main() -> None:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--protocols",
        default="multipaxos:6,raft:6,rspaxos:6,crossword:5,"
                "multipaxos+collective:5,crossword+collective:5",
        help="comma list of name[+collective][:depth]; this default "
             "regenerates the committed MODELCHECK.json in one "
             "invocation (crossword runs one level shallower: its "
             "per-slot shard tallies give it the largest per-node "
             "state, and depth 5 already covers election + window-wrap "
             "+ gossip under every schedule; the +collective rows "
             "exhaust the in-mesh tally transport of core/quorum.py "
             "at depth 5 — the equivalence gate already proves "
             "byte-identity with pairwise, so these rows are the "
             "independent safety exhaustion, one level shallower to "
             "bound the regen budget)",
    )
    ap.add_argument("--depth", type=int, default=6,
                    help="depth for entries without an explicit :depth")
    ap.add_argument("--round-ticks", type=int, default=2)
    ap.add_argument("--no-range-oracle", action="store_true",
                    help="skip asserting the range prover's invariants "
                         "at every visited state")
    ap.add_argument("--out", default="")
    args = ap.parse_args()
    results = []
    for spec in args.protocols.split(","):
        name, _, d = spec.strip().partition(":")
        name, _, mode = name.partition("+")
        r = explore(name, depth=int(d) if d else args.depth,
                    round_ticks=args.round_ticks,
                    config_overrides=CLI_PRESETS.get(name),
                    tally=mode or "pairwise",
                    range_oracle=not args.no_range_oracle,
                    progress=True)
        print(json.dumps(r.as_json()))
        results.append(r.as_json())
        assert not r.violations, r.violations
    if args.out:
        with open(args.out, "w") as f:
            json.dump(results, f, indent=1)


if __name__ == "__main__":
    main()
