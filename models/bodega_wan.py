"""Bodega WAN analytical model: read/write latencies per client site
under different read-serving strategies, on a ring-world geography.

Parity role: reference ``models/bodega/calc_wan_delays.py`` (ring world
of sites; per-strategy delay calculator) and the spirit of
``plot_wan_quorums.py`` — re-derived, not translated: sites live on a
ring of ``ticks`` positions, one-way delay between sites is proportional
to ring distance, and each serving strategy maps a client site to the
round trips its reads/writes take.

Strategies compared (the design space Bodega sits in):
- ``leader_reads``:   all ops to the leader (MultiPaxos baseline).
- ``quorum_reads``:   reads contact a majority quorum nearest the client.
- ``lease_local``:    reads served by the nearest roster responder
                      (Bodega); writes pay leader + responder coverage.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List


@dataclasses.dataclass
class RingWorld:
    """Sites on a ring; distance = min ring hops (reference RingWorld)."""

    ticks: int = 24
    servers: List[int] = dataclasses.field(
        default_factory=lambda: [3, 0, 18, 14, 12]
    )
    clients: List[int] = dataclasses.field(
        default_factory=lambda: list(range(4)) + list(range(11, 20))
    )
    leader_idx: int = 4
    ms_per_tick: float = 10.0

    @property
    def leader(self) -> int:
        return self.servers[self.leader_idx]

    def distance(self, a: int, b: int) -> int:
        d = abs(a - b) % self.ticks
        return min(d, self.ticks - d)

    def delay_ms(self, a: int, b: int) -> float:
        return self.distance(a, b) * self.ms_per_tick

    def nearest_server(self, origin: int) -> int:
        return min(self.servers, key=lambda s: self.distance(origin, s))

    def quorum_rtt_ms(self, origin: int, size: int) -> float:
        """RTT to the ``size``-th nearest server (parallel fan-out)."""
        ds = sorted(self.distance(origin, s) for s in self.servers)
        return 2 * ds[size - 1] * self.ms_per_tick

    def quorum_incl_rtt_ms(self, origin: int, size: int,
                           includes: List[int]) -> float:
        """RTT of a quorum that must include ``includes`` (write barrier
        covering every lease holder)."""
        base = self.quorum_rtt_ms(origin, size)
        incl = max(
            (2 * self.delay_ms(origin, s) for s in includes), default=0.0
        )
        return max(base, incl)


def site_latencies(world: RingWorld, strategy: str,
                   responders: List[int] | None = None
                   ) -> Dict[int, Dict[str, float]]:
    """Per client site: read and write latency in ms for a strategy."""
    n = len(world.servers)
    maj = n // 2 + 1
    resp = responders if responders is not None else list(world.servers)
    out: Dict[int, Dict[str, float]] = {}
    for c in world.clients:
        to_leader = 2 * world.delay_ms(c, world.leader)
        if strategy == "leader_reads":
            r = to_leader
            w = to_leader + world.quorum_rtt_ms(world.leader, maj)
        elif strategy == "quorum_reads":
            r = world.quorum_rtt_ms(c, maj)
            w = to_leader + world.quorum_rtt_ms(world.leader, maj)
        elif strategy == "lease_local":
            near = min(resp, key=lambda s: world.distance(c, s))
            r = 2 * world.delay_ms(c, near)
            # writes must reach the leader, then cover a quorum AND every
            # responder of the key (bodega localread.rs:32-56)
            w = to_leader + world.quorum_incl_rtt_ms(
                world.leader, maj, resp
            )
        else:
            raise ValueError(strategy)
        out[c] = {"read_ms": r, "write_ms": w}
    return out


def mean_latency_ms(world: RingWorld, strategy: str,
                    put_ratio: float = 0.1,
                    responders: List[int] | None = None) -> float:
    per = site_latencies(world, strategy, responders)
    acc = [
        put_ratio * v["write_ms"] + (1 - put_ratio) * v["read_ms"]
        for v in per.values()
    ]
    return sum(acc) / len(acc)


if __name__ == "__main__":
    w = RingWorld()
    for strat in ("leader_reads", "quorum_reads", "lease_local"):
        print(
            f"{strat:13s}: mean op latency "
            f"{mean_latency_ms(w, strat):7.1f} ms "
            f"(put_ratio 0.1)"
        )
