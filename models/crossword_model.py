"""Crossword analytical model: the (quorum size, shards-per-replica)
constraint frontier and critical-path response-time distribution.

Parity role: reference ``models/crossword/{plot_cstr_bounds,
prob_calculation}.py`` — an analytical companion to the protocol, used to
reason about which assignments are valid and which minimize expected
commit latency under heavy-tailed per-link delay.  Re-derived here (not
translated): same constraint algebra, same Pareto-jitter delay model,
matplotlib plotting optional (the environment is headless; the numbers
are the product).
"""

from __future__ import annotations

import math
import random
from typing import Dict, List, Optional, Tuple


def valid_assignments(n: int, d: int,
                      fault_tolerance: Optional[int] = None,
                      shards_per_disjoint: int = 1
                      ) -> List[Tuple[int, int]]:
    """(commit-ack count q, shards-per-replica spr) pairs per Crossword's
    commit condition: q = max(majority, f + 1 + ceil((d - spr) / dj)) —
    quorum AND worst-case f+1-survivor coverage of all d shards (the
    kernel's ``_commit_need``, crossword.py; ref messages.rs:15-62).
    ``fault_tolerance=None`` uses the orchestration scripts' default
    f = (n // 2) // 2 (local_cluster.py protocol_defaults)."""
    maj = n // 2 + 1
    f = (n // 2) // 2 if fault_tolerance is None else fault_tolerance
    dj = shards_per_disjoint
    out = []
    for spr in range(1, d + 1):
        cov = f + 1 + max(0, -((-(d - spr)) // dj))
        out.append((max(maj, cov), spr))
    return out


def shard_loss_tolerance(n: int, d: int, spr: int) -> int:
    """How many replica losses keep d distinct shards available
    (round-robin assignment): f such that any n-f replicas still cover
    all d shards."""
    for f in range(n, -1, -1):
        # worst case: the f lost replicas are consecutive in the ring —
        # the survivors still cover every shard iff n - f >= d - spr + 1
        if n - f >= d - spr + 1 and n - f >= n // 2 + 1:
            return f
    return 0


def rand_link_time_ms(
    size_kb: float, spr: int, d: int,
    delay_ms: float, bw_gbps: float, jitter_pct: float,
    rng: random.Random, pareto_alpha: float = 1.16,
) -> float:
    """One peer's delivery time: min delay + Pareto-tail jitter +
    serialization of its spr/d slice of the instance."""
    pareto = rng.paretovariate(pareto_alpha)
    while pareto > 10:
        pareto = rng.paretovariate(pareto_alpha)
    t = delay_ms + delay_ms * (jitter_pct / 100.0) * (pareto - 1)
    t += (size_kb * spr / d) / (bw_gbps * 1024 / 8)  # KB over Gbps -> ms
    return t


def response_time_sample(
    n: int, q: int, spr: int, d: int, size_kb: float,
    delay_ms: float, bw_gbps: float, jitter_pct: float,
    rng: random.Random,
) -> float:
    """Leader-side commit time: the (q-1)-th fastest of n-1 peer
    deliveries (the leader acks itself)."""
    times = sorted(
        rand_link_time_ms(size_kb, spr, d, delay_ms, bw_gbps,
                          jitter_pct, rng)
        for _ in range(n - 1)
    )
    return times[q - 2] if q >= 2 else 0.0


def expected_commit_ms(
    n: int, d: int, size_kb: float, delay_ms: float, bw_gbps: float,
    jitter_pct: float = 25.0, trials: int = 2000, seed: int = 7,
) -> Dict[Tuple[int, int], float]:
    """Mean commit latency per valid (q, spr) assignment — the table the
    adaptive policy optimizes over."""
    rng = random.Random(seed)
    out = {}
    for q, spr in valid_assignments(n, d):
        acc = 0.0
        for _ in range(trials):
            acc += response_time_sample(
                n, q, spr, d, size_kb, delay_ms, bw_gbps, jitter_pct, rng
            )
        out[(q, spr)] = acc / trials
    return out


def best_assignment(
    n: int, d: int, size_kb: float, delay_ms: float, bw_gbps: float,
    **kw,
) -> Tuple[int, int]:
    table = expected_commit_ms(n, d, size_kb, delay_ms, bw_gbps, **kw)
    return min(table, key=table.get)


if __name__ == "__main__":
    n, d = 5, 3
    print("valid (q, spr):", valid_assignments(n, d))
    for size in (8, 256, 4096):
        for delay, bw in ((10, 100), (50, 10), (120, 1)):
            tbl = expected_commit_ms(n, d, size, delay, bw)
            best = min(tbl, key=tbl.get)
            print(
                f"size {size:5}KB delay {delay:3}ms bw {bw:3}Gbps -> "
                f"best (q, spr) = {best}, "
                + " ".join(
                    f"{k}:{v:.1f}ms" for k, v in sorted(tbl.items())
                )
            )
