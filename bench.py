"""Headline benchmark: batched MultiPaxos commit throughput.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline (BASELINE.json north star): >= 10M committed log slots/sec across
4096 five-replica MultiPaxos groups on a TPU v5e-8; this runs on however
many chips are visible (one under the axon tunnel) and reports per-run
throughput, with vs_baseline = value / 10e6.

The workload mirrors the reference's open-loop bench client at unlimited
frequency (summerset_client/src/clients/bench.rs) with the host I/O plane
detached: every tick each group is offered `P` new commands; the measured
quantity is committed consensus slots (quorum-replicated, in-order) per
wall-clock second.

Pod-scale mesh (``--mesh GxR`` / env ``BENCH_MESH``): shards the group
axis (and optionally the replica axis) over a ``(group, replica)``
device mesh (core/sharding.py) and runs the steady-state windows with
the scan carry DONATED — ticks are device-resident, the host never
round-trips the ``[G, R, ...]`` state.  The artifact stamps the mesh
shape, per-device group count, and the donation introspection, and its
``ok`` self-verdict fails a mesh run whose carry was not actually
aliased.  On CPU, ``--mesh`` builds the virtual host-platform mesh
(utils/jaxcompat set_cpu_devices) so the multi-device path stays
reproducible while the TPU tunnel is down.
"""

import argparse
import json
import os
import subprocess
import sys
import time

# NOTE: jax is imported lazily inside main(), AFTER _probe_backend().  When
# the axon TPU tunnel is down, `import jax` itself hangs (the tunnel is
# dialed from sitecustomize at interpreter startup, before JAX_PLATFORMS is
# consulted) — so the only safe fail-fast probe is a bounded subprocess.
BACKEND_PROBE_TIMEOUT_S = int(os.environ.get("BENCH_BACKEND_TIMEOUT", "150"))

# Shapes are env-overridable for A/B sweeps (pack_lanes, window retries)
# and for fast happy-path verification on CPU; defaults are the headline
# TPU shape.
GROUPS = int(os.environ.get("BENCH_GROUPS", "4096"))
POPULATION = int(os.environ.get("BENCH_POPULATION", "5"))
# W=128/P=32 doubles commit throughput over the r2/r3 shape (W=64/P=16)
# at the SAME ~2.1 ms/tick: the ring window, not the tick cost, was the
# binding constraint (see PERF.md round-4 sweep)
WINDOW = int(os.environ.get("BENCH_WINDOW", "128"))
PROPOSALS_PER_TICK = int(os.environ.get("BENCH_PROPS", "32"))
TICKS = int(os.environ.get("BENCH_TICKS", "2048"))
RUNS = int(os.environ.get("BENCH_RUNS", "3"))
BASELINE = 10_000_000.0


def _probe_backend(timeout_s=BACKEND_PROBE_TIMEOUT_S):
    """Check that `import jax; jax.devices()` completes within timeout_s.

    Runs in a subprocess (inheriting the full env, including any tunnel
    dialing site hooks) so a dead backend makes THIS process exit fast with
    a clear error instead of hanging the whole capture window.
    Returns None on success or an error message on failure.
    """
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].platform)"],
            timeout=timeout_s, capture_output=True, text=True)
    except subprocess.TimeoutExpired:
        return (f"backend init timed out after {timeout_s}s "
                "(TPU tunnel down?)")
    if proc.returncode != 0:
        tail = proc.stderr.strip().splitlines()
        return tail[-1] if tail else f"probe exited {proc.returncode}"
    return None


def _cpu_fallback(err: str) -> int:
    """Degrade to the CPU-mesh path instead of rc=1 when the TPU tunnel
    is down (BENCH_r05 recorded 0 slots/s): re-exec this script as an
    explicit CPU run — which cannot hang on the tunnel — at a CPU-sized
    default shape, and pass its one-line JSON artifact through.  The
    artifact's ``backend``/``backend_note`` fields label the run
    unambiguously, so a degraded number can never masquerade as a TPU
    measurement."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["BENCH_BACKEND_NOTE"] = f"cpu fallback: {err}"
    # a requested mesh survives the fallback: the child builds the same
    # GxR shape as a virtual CPU mesh (argparse defaults from BENCH_MESH)
    # explicit BENCH_* overrides still win; otherwise shrink to a shape a
    # CPU finishes in seconds rather than the 4096-group TPU headline
    env.setdefault("BENCH_GROUPS", "256")
    env.setdefault("BENCH_TICKS", "256")
    env.setdefault("BENCH_RUNS", "1")
    try:
        # bounded: if the sitecustomize tunnel dial hangs even the
        # explicit-CPU child (it fires at interpreter startup, before
        # JAX_PLATFORMS is consulted), fall back to the labeled rc=1
        # artifact rather than hanging the capture window forever
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)], env=env,
            capture_output=True, text=True, timeout=900,
        )
    except subprocess.TimeoutExpired:
        print(json.dumps({
            "metric": "committed slots/sec, MultiPaxos "
                      "(backend unavailable, cpu fallback hung)",
            "value": 0.0,
            "unit": "slots/sec",
            "vs_baseline": 0.0,
            "backend": "none",
            # a dead capture fails its own artifact, loudly: BENCH_r05
            # shipped rc=1 with 0 slots/s and nothing noticed until a
            # reviewer read the JSON
            "ok": False,
            "error": f"{err}; cpu fallback timed out after 900s",
        }))
        return 1
    sys.stdout.write(proc.stdout)
    sys.stderr.write(proc.stderr)
    return proc.returncode


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--mesh", default=os.environ.get("BENCH_MESH", ""),
        help="GxR (group_shards x replica_shards) device mesh, e.g. 4x2 "
             "on a v5e-8; empty = the single-device legacy path.  On "
             "CPU a virtual host-platform mesh of that size is built.",
    )
    ap.add_argument(
        "--tally", default=os.environ.get("BENCH_TALLY", "pairwise"),
        choices=("pairwise", "collective"),
        help="quorum-tally transport (core/quorum.py): 'pairwise' = the "
             "R² accept-reply lanes through the delay line (digest-"
             "compatible default); 'collective' = per-source [G, R] "
             "tally records, one replica-axis gather on a sharded mesh",
    )
    args = ap.parse_args()
    # the fallback child re-execs without argv: carry the mode in env
    os.environ["BENCH_TALLY"] = args.tally
    mesh_shape = None
    if args.mesh:
        # the canonical jax-free grammar (summerset_tpu.utils.jaxcompat
        # — importing summerset_tpu.core here would initialize the
        # backend and lock the device count): a malformed spec fails
        # fast, before the probe/fallback machinery spins up
        from summerset_tpu.utils.jaxcompat import parse_mesh

        mesh_shape = parse_mesh(args.mesh)
        # the fallback child re-execs without argv: carry the spec in env
        os.environ["BENCH_MESH"] = args.mesh
    else:
        # an explicit --mesh "" must also override an inherited
        # BENCH_MESH for the fallback child, or parent and child would
        # disagree about the mesh
        os.environ.pop("BENCH_MESH", None)

    # An explicit CPU run (A/B sweeps, verification) can't hang on the
    # tunnel — skip the probe and its extra interpreter+backend bring-up.
    err = None
    if os.environ.get("JAX_PLATFORMS", "") != "cpu":
        err = _probe_backend()
    if err is not None:
        sys.exit(_cpu_fallback(err))

    if mesh_shape is not None and os.environ.get(
        "JAX_PLATFORMS", ""
    ) in ("", "cpu"):
        # grow the virtual CPU platform to the mesh size BEFORE anything
        # initializes the backend.  Also applied when the platform is
        # unset (a CPU-only host that passed the probe): the
        # host-platform device count is harmless on a real accelerator
        # backend and required on CPU.
        from summerset_tpu.utils.jaxcompat import set_cpu_devices

        need = mesh_shape[0] * mesh_shape[1]
        if need > 1:
            set_cpu_devices(need)

    import jax
    import numpy as np

    from summerset_tpu.core import Engine
    from summerset_tpu.core import sharding as shardlib
    from summerset_tpu.protocols import make_protocol
    from summerset_tpu.protocols.multipaxos import ReplicaConfigMultiPaxos

    # exec_follows_commit=False: commit_bar only advances past slots the
    # (synthetic, saturating) applier has released via exec_floor — the
    # measured slots are commit-AND-execute-eligible, not device-only
    cfg = ReplicaConfigMultiPaxos(
        max_proposals_per_tick=PROPOSALS_PER_TICK,
        chunk_size=PROPOSALS_PER_TICK * 2,
        exec_follows_commit=False,
        tally=args.tally,
    )
    kernel = make_protocol("multipaxos", GROUPS, POPULATION, WINDOW, cfg)
    mesh = None
    if mesh_shape is not None:
        mesh = shardlib.mesh_for(*mesh_shape)
    eng = Engine(kernel, mesh=mesh)  # sharded mode donates the carry
    state, ns = eng.init()
    carry_leaves = len(jax.tree.leaves((state, ns)))

    # AOT-compile the scanned window ONCE and reuse the executable for
    # warmup + every timed run: no recompile can land inside the timed
    # region, and the compiled artifact is what the donation stamp
    # introspects (profiling.donation_stats)
    comp = eng.lower_synthetic(state, ns, TICKS, PROPOSALS_PER_TICK) \
              .compile()
    state, ns = comp(state, ns)
    jax.block_until_ready(state["commit_bar"])

    rate = 0.0
    for _ in range(RUNS):
        start = np.asarray(state["commit_bar"]).max(axis=1).sum()
        t0 = time.perf_counter()
        state, ns = comp(state, ns)
        jax.block_until_ready(state["commit_bar"])
        dt = time.perf_counter() - t0
        end = np.asarray(state["commit_bar"]).max(axis=1).sum()
        rate = max(rate, float(end - start) / dt)
    ndev = (mesh_shape[0] * mesh_shape[1]) if mesh_shape else 1
    doc = {
        "metric": (
            f"committed slots/sec, MultiPaxos {POPULATION}-replica x "
            f"{GROUPS} groups, "
            + (f"{ndev} device(s) mesh {mesh_shape[0]}x{mesh_shape[1]}"
               if mesh_shape else "1 chip")
            + f" ({jax.devices()[0].platform})"
        ),
        "value": round(rate, 1),
        "unit": "slots/sec",
        "vs_baseline": round(rate / BASELINE, 4),
        "backend": jax.devices()[0].platform,
        # quorum-tally transport stamp (next to the mesh block): which
        # tally plane produced this number (core/quorum.py)
        "tally": args.tally,
        # the artifact judges itself: a capture that made no progress is
        # a FAILED capture even if the process exits 0 (the BENCH_r05
        # lesson — rc=1 with 0 slots/s sat unnoticed in the trajectory)
        "ok": rate > 0,
    }
    if mesh_shape is not None:
        from summerset_tpu.host.profiling import donation_stats

        gs, rs = mesh_shape
        don = donation_stats(comp)
        doc["mesh"] = dict(
            shardlib.mesh_stamp(gs, rs, GROUPS),
            donation=dict(don, carry_leaves=carry_leaves),
        )
        # a mesh capture whose carry was NOT donated is a failed capture:
        # it silently re-ships the [G, R, ...] state every window
        doc["ok"] = doc["ok"] and don["aliased_buffers"] == carry_leaves
    note = os.environ.get("BENCH_BACKEND_NOTE")
    if note:
        doc["backend_note"] = note
    # graftprof analytic stamp at the bench's own shape: cost/memory/
    # compile metrics are deterministic per backend, so the BENCH_r*
    # trajectory carries comparable numbers even when this box's
    # wall-clock is noisy (one extra single-tick compile, scan excluded)
    try:
        from summerset_tpu.host.profiling import analytic_block

        doc["graftprof"] = analytic_block(kernel, PROPOSALS_PER_TICK)
    except Exception as e:  # the stamp must never kill the bench
        doc["graftprof"] = {"error": f"{type(e).__name__}: {e}"}
    print(json.dumps(doc))


if __name__ == "__main__":
    main()
