"""Headline benchmark: batched MultiPaxos commit throughput on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline (BASELINE.json north star): >= 10M committed log slots/sec across
4096 five-replica MultiPaxos groups on a TPU v5e-8; this runs on however
many chips are visible (one under the axon tunnel) and reports per-run
throughput, with vs_baseline = value / 10e6.

The workload mirrors the reference's open-loop bench client at unlimited
frequency (summerset_client/src/clients/bench.rs) with the host I/O plane
detached: every tick each group is offered `P` new commands; the measured
quantity is committed consensus slots (quorum-replicated, in-order) per
wall-clock second.
"""

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

from summerset_tpu.core import Engine
from summerset_tpu.protocols import make_protocol
from summerset_tpu.protocols.multipaxos import ReplicaConfigMultiPaxos

GROUPS = 4096
POPULATION = 5
# W=128/P=32 doubles commit throughput over the r2/r3 shape (W=64/P=16)
# at the SAME ~2.1 ms/tick: the ring window, not the tick cost, was the
# binding constraint (see PERF.md round-4 sweep)
WINDOW = 128
PROPOSALS_PER_TICK = 32
TICKS = 2048
RUNS = 3
BASELINE = 10_000_000.0


def main():
    # exec_follows_commit=False: commit_bar only advances past slots the
    # (synthetic, saturating) applier has released via exec_floor — the
    # measured slots are commit-AND-execute-eligible, not device-only
    cfg = ReplicaConfigMultiPaxos(
        max_proposals_per_tick=PROPOSALS_PER_TICK,
        chunk_size=PROPOSALS_PER_TICK * 2,
        exec_follows_commit=False,
    )
    kernel = make_protocol("multipaxos", GROUPS, POPULATION, WINDOW, cfg)
    eng = Engine(kernel)
    state, ns = eng.init()

    # warmup with the SAME static (TICKS, P) so the timed calls below hit
    # the compile cache (a different tick count would recompile the scan
    # inside the timed region), and run reaches steady state
    state, ns = eng.run_synthetic(state, ns, TICKS, PROPOSALS_PER_TICK)
    jax.block_until_ready(state["commit_bar"])

    rate = 0.0
    for _ in range(RUNS):
        start = np.asarray(state["commit_bar"]).max(axis=1).sum()
        t0 = time.perf_counter()
        state, ns = eng.run_synthetic(state, ns, TICKS, PROPOSALS_PER_TICK)
        jax.block_until_ready(state["commit_bar"])
        dt = time.perf_counter() - t0
        end = np.asarray(state["commit_bar"]).max(axis=1).sum()
        rate = max(rate, float(end - start) / dt)
    print(
        json.dumps(
            {
                "metric": (
                    f"committed slots/sec, MultiPaxos {POPULATION}-replica x "
                    f"{GROUPS} groups, 1 chip ({jax.devices()[0].platform})"
                ),
                "value": round(rate, 1),
                "unit": "slots/sec",
                "vs_baseline": round(rate / BASELINE, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
