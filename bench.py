"""Headline benchmark: batched MultiPaxos commit throughput on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline (BASELINE.json north star): >= 10M committed log slots/sec across
4096 five-replica MultiPaxos groups on a TPU v5e-8; this runs on however
many chips are visible (one under the axon tunnel) and reports per-run
throughput, with vs_baseline = value / 10e6.

The workload mirrors the reference's open-loop bench client at unlimited
frequency (summerset_client/src/clients/bench.rs) with the host I/O plane
detached: every tick each group is offered `P` new commands; the measured
quantity is committed consensus slots (quorum-replicated, in-order) per
wall-clock second.
"""

import json
import os
import subprocess
import sys
import time

# NOTE: jax is imported lazily inside main(), AFTER _probe_backend().  When
# the axon TPU tunnel is down, `import jax` itself hangs (the tunnel is
# dialed from sitecustomize at interpreter startup, before JAX_PLATFORMS is
# consulted) — so the only safe fail-fast probe is a bounded subprocess.
BACKEND_PROBE_TIMEOUT_S = int(os.environ.get("BENCH_BACKEND_TIMEOUT", "150"))

# Shapes are env-overridable for A/B sweeps (pack_lanes, window retries)
# and for fast happy-path verification on CPU; defaults are the headline
# TPU shape.
GROUPS = int(os.environ.get("BENCH_GROUPS", "4096"))
POPULATION = int(os.environ.get("BENCH_POPULATION", "5"))
# W=128/P=32 doubles commit throughput over the r2/r3 shape (W=64/P=16)
# at the SAME ~2.1 ms/tick: the ring window, not the tick cost, was the
# binding constraint (see PERF.md round-4 sweep)
WINDOW = int(os.environ.get("BENCH_WINDOW", "128"))
PROPOSALS_PER_TICK = int(os.environ.get("BENCH_PROPS", "32"))
TICKS = int(os.environ.get("BENCH_TICKS", "2048"))
RUNS = int(os.environ.get("BENCH_RUNS", "3"))
BASELINE = 10_000_000.0


def _probe_backend(timeout_s=BACKEND_PROBE_TIMEOUT_S):
    """Check that `import jax; jax.devices()` completes within timeout_s.

    Runs in a subprocess (inheriting the full env, including any tunnel
    dialing site hooks) so a dead backend makes THIS process exit fast with
    a clear error instead of hanging the whole capture window.
    Returns None on success or an error message on failure.
    """
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].platform)"],
            timeout=timeout_s, capture_output=True, text=True)
    except subprocess.TimeoutExpired:
        return (f"backend init timed out after {timeout_s}s "
                "(TPU tunnel down?)")
    if proc.returncode != 0:
        tail = proc.stderr.strip().splitlines()
        return tail[-1] if tail else f"probe exited {proc.returncode}"
    return None


def _cpu_fallback(err: str) -> int:
    """Degrade to the CPU-mesh path instead of rc=1 when the TPU tunnel
    is down (BENCH_r05 recorded 0 slots/s): re-exec this script as an
    explicit CPU run — which cannot hang on the tunnel — at a CPU-sized
    default shape, and pass its one-line JSON artifact through.  The
    artifact's ``backend``/``backend_note`` fields label the run
    unambiguously, so a degraded number can never masquerade as a TPU
    measurement."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["BENCH_BACKEND_NOTE"] = f"cpu fallback: {err}"
    # explicit BENCH_* overrides still win; otherwise shrink to a shape a
    # CPU finishes in seconds rather than the 4096-group TPU headline
    env.setdefault("BENCH_GROUPS", "256")
    env.setdefault("BENCH_TICKS", "256")
    env.setdefault("BENCH_RUNS", "1")
    try:
        # bounded: if the sitecustomize tunnel dial hangs even the
        # explicit-CPU child (it fires at interpreter startup, before
        # JAX_PLATFORMS is consulted), fall back to the labeled rc=1
        # artifact rather than hanging the capture window forever
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)], env=env,
            capture_output=True, text=True, timeout=900,
        )
    except subprocess.TimeoutExpired:
        print(json.dumps({
            "metric": "committed slots/sec, MultiPaxos "
                      "(backend unavailable, cpu fallback hung)",
            "value": 0.0,
            "unit": "slots/sec",
            "vs_baseline": 0.0,
            "backend": "none",
            # a dead capture fails its own artifact, loudly: BENCH_r05
            # shipped rc=1 with 0 slots/s and nothing noticed until a
            # reviewer read the JSON
            "ok": False,
            "error": f"{err}; cpu fallback timed out after 900s",
        }))
        return 1
    sys.stdout.write(proc.stdout)
    sys.stderr.write(proc.stderr)
    return proc.returncode


def main():
    # An explicit CPU run (A/B sweeps, verification) can't hang on the
    # tunnel — skip the probe and its extra interpreter+backend bring-up.
    err = None
    if os.environ.get("JAX_PLATFORMS", "") != "cpu":
        err = _probe_backend()
    if err is not None:
        sys.exit(_cpu_fallback(err))

    import jax
    import numpy as np

    from summerset_tpu.core import Engine
    from summerset_tpu.protocols import make_protocol
    from summerset_tpu.protocols.multipaxos import ReplicaConfigMultiPaxos

    # exec_follows_commit=False: commit_bar only advances past slots the
    # (synthetic, saturating) applier has released via exec_floor — the
    # measured slots are commit-AND-execute-eligible, not device-only
    cfg = ReplicaConfigMultiPaxos(
        max_proposals_per_tick=PROPOSALS_PER_TICK,
        chunk_size=PROPOSALS_PER_TICK * 2,
        exec_follows_commit=False,
    )
    kernel = make_protocol("multipaxos", GROUPS, POPULATION, WINDOW, cfg)
    eng = Engine(kernel)
    state, ns = eng.init()

    # warmup with the SAME static (TICKS, P) so the timed calls below hit
    # the compile cache (a different tick count would recompile the scan
    # inside the timed region), and run reaches steady state
    state, ns = eng.run_synthetic(state, ns, TICKS, PROPOSALS_PER_TICK)
    jax.block_until_ready(state["commit_bar"])

    rate = 0.0
    for _ in range(RUNS):
        start = np.asarray(state["commit_bar"]).max(axis=1).sum()
        t0 = time.perf_counter()
        state, ns = eng.run_synthetic(state, ns, TICKS, PROPOSALS_PER_TICK)
        jax.block_until_ready(state["commit_bar"])
        dt = time.perf_counter() - t0
        end = np.asarray(state["commit_bar"]).max(axis=1).sum()
        rate = max(rate, float(end - start) / dt)
    doc = {
        "metric": (
            f"committed slots/sec, MultiPaxos {POPULATION}-replica x "
            f"{GROUPS} groups, 1 chip ({jax.devices()[0].platform})"
        ),
        "value": round(rate, 1),
        "unit": "slots/sec",
        "vs_baseline": round(rate / BASELINE, 4),
        "backend": jax.devices()[0].platform,
        # the artifact judges itself: a capture that made no progress is
        # a FAILED capture even if the process exits 0 (the BENCH_r05
        # lesson — rc=1 with 0 slots/s sat unnoticed in the trajectory)
        "ok": rate > 0,
    }
    note = os.environ.get("BENCH_BACKEND_NOTE")
    if note:
        doc["backend_note"] = note
    # graftprof analytic stamp at the bench's own shape: cost/memory/
    # compile metrics are deterministic per backend, so the BENCH_r*
    # trajectory carries comparable numbers even when this box's
    # wall-clock is noisy (one extra single-tick compile, scan excluded)
    try:
        from summerset_tpu.host.profiling import analytic_block

        doc["graftprof"] = analytic_block(kernel, PROPOSALS_PER_TICK)
    except Exception as e:  # the stamp must never kill the bench
        doc["graftprof"] = {"error": f"{type(e).__name__}: {e}"}
    print(json.dumps(doc))


if __name__ == "__main__":
    main()
