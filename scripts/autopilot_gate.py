#!/usr/bin/env python3
"""AUTOPILOT.json drift + closed-loop-autopilot gate (ci.sh).

Asserts, WITHOUT bringing up clusters (pure schedule regeneration over
the committed twin-soak artifact from scripts/autopilot_soak.py):

1. the committed ``autopilot_ab`` row passed (``ok``) and both twin
   cells' histories were linearizable with zero acked-and-shed values
   and a bounded recovery;
2. the schedule digest is byte-identical to what the current
   generators produce (both WorkloadPlan timelines, the FaultPlan, the
   shift/window axis, AND the policy knob line) — any change to the
   schedule or the policy's knobs must regenerate the artifact in the
   same PR (the drift gate); the per-plan digests must match too;
3. graceful degradation beat the static twin: the ON cell accepted
   >= ``MIN_WIN_RATIO`` x the OFF cell in EVERY post-shift window;
4. bounded convergence: the policy fired nothing after the schedule
   tail opened, total fires stayed under ``MAX_TOTAL_FIRES``, and the
   recorded per-window spend never exceeded the committed budget;
5. observe mode is byte-identical to off: the OFF cell's observing
   driver sent ZERO ctrl mutations;
6. actuator coverage: the ON cell fired >= 1 ``lead_move`` and >= 1
   ``batch`` actuation (the levers the schedule's shifts target).

Regenerate with:  python scripts/autopilot_soak.py

Usage:  python scripts/autopilot_gate.py [--json AUTOPILOT.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "scripts"))

from autopilot_soak import (  # noqa: E402  (scripts/ sibling import)
    MAX_TOTAL_FIRES, MIN_WIN_RATIO, QL_GROUPS, QL_MAX_TOTAL_FIRES,
    SHIFTS, WINDOWS, build_ql_schedule, build_schedule, make_policy,
    make_ql_policy, ql_schedule_digest, schedule_digest,
)


def check_autopilot_ab(row) -> list:
    errs = []
    if not row.get("ok"):
        errs.append(f"row not ok: {row.get('error')}")

    # ---- drift: the committed schedule must regenerate byte-for-byte
    wa, wb, fp = build_schedule()
    pol = make_policy()
    if row.get("wl_digest_a") != wa.digest():
        errs.append(f"workload plan A digest drift: committed "
                    f"{row.get('wl_digest_a')} vs {wa.digest()}")
    if row.get("wl_digest_b") != wb.digest():
        errs.append(f"workload plan B digest drift: committed "
                    f"{row.get('wl_digest_b')} vs {wb.digest()}")
    if row.get("fault_digest") != fp.digest():
        errs.append(f"fault plan digest drift: committed "
                    f"{row.get('fault_digest')} vs {fp.digest()}")
    if row.get("schedule_digest") != schedule_digest():
        errs.append(f"schedule digest drift: committed "
                    f"{row.get('schedule_digest')} vs "
                    f"{schedule_digest()}")
    if row.get("policy_config_digest") != pol.config_digest():
        errs.append(f"policy knob drift: committed "
                    f"{row.get('policy_config_digest')} vs "
                    f"{pol.config_digest()}")
    if list(row.get("shifts") or []) != list(SHIFTS):
        errs.append("shift axis drift")
    if [tuple(w) for w in (row.get("windows") or [])] != list(WINDOWS):
        errs.append("measurement window drift")

    # ---- both twin cells: linearizable, no lost acks, recovered
    for mode in ("off", "on"):
        sub = row.get(mode) or {}
        if not sub.get("linearizable"):
            errs.append(f"{mode} cell history not linearizable")
        if sub.get("ack_shed_overlap"):
            errs.append(f"{mode} cell lost acks to sheds: "
                        f"{sub['ack_shed_overlap']}")
        if not sub.get("recovered"):
            errs.append(f"{mode} cell never recovered post-schedule")

    # ---- graceful degradation after EVERY shift
    ratios = row.get("window_ratios") or []
    if len(ratios) != len(WINDOWS):
        errs.append(f"expected {len(WINDOWS)} window ratios, "
                    f"got {len(ratios)}")
    for i, r in enumerate(ratios):
        if r < MIN_WIN_RATIO:
            errs.append(f"W{i + 1} on/off ratio {r} < {MIN_WIN_RATIO}")

    on = row.get("on") or {}
    # ---- bounded convergence
    if on.get("tail_decisions") != 0:
        errs.append(f"policy still actuating in the tail: "
                    f"{on.get('tail_decisions')} decisions")
    total_fires = sum((on.get("fires") or {}).values())
    if total_fires > MAX_TOTAL_FIRES:
        errs.append(f"unbounded actuation: {total_fires} fires "
                    f"> {MAX_TOTAL_FIRES}")
    if on.get("max_window_spend", 0) > on.get("budget_per_window", 0):
        errs.append(
            f"window budget blown: spend {on.get('max_window_spend')} "
            f"> budget {on.get('budget_per_window')}"
        )

    # ---- observe mode byte-identical to off
    off = row.get("off") or {}
    if off.get("n_actuations") != 0:
        errs.append(f"observe-mode driver sent "
                    f"{off.get('n_actuations')} ctrl mutations")

    # ---- actuator coverage
    fires = on.get("fires") or {}
    if fires.get("lead_move", 0) < 1:
        errs.append("no lead_move actuation in the on cell")
    if fires.get("batch", 0) < 1:
        errs.append("no batch actuation in the on cell")
    return errs


def check_autopilot_ql(row) -> list:
    """The QuorumLeases multi-group twin row: lease-plane actuator
    coverage (conf_resize through a live ConfChange, reshard through a
    live range_change) with the same safety bar as the MultiPaxos row."""
    errs = []
    if not row.get("ok"):
        errs.append(f"ql row not ok: {row.get('error')}")

    # ---- drift: schedule + policy knobs regenerate byte-for-byte
    wplan = build_ql_schedule()
    pol = make_ql_policy()
    if row.get("wl_digest") != wplan.digest():
        errs.append(f"ql workload digest drift: committed "
                    f"{row.get('wl_digest')} vs {wplan.digest()}")
    if row.get("schedule_digest") != ql_schedule_digest():
        errs.append(f"ql schedule digest drift: committed "
                    f"{row.get('schedule_digest')} vs "
                    f"{ql_schedule_digest()}")
    if row.get("policy_config_digest") != pol.config_digest():
        errs.append(f"ql policy knob drift: committed "
                    f"{row.get('policy_config_digest')} vs "
                    f"{pol.config_digest()}")
    if row.get("num_groups") != QL_GROUPS:
        errs.append(f"ql group-count drift: {row.get('num_groups')}")

    # ---- both twin cells: linearizable, no lost acks, recovered
    for mode in ("off", "on"):
        sub = row.get(mode) or {}
        if not sub.get("linearizable"):
            errs.append(f"ql {mode} cell history not linearizable")
        if sub.get("ack_shed_overlap"):
            errs.append(f"ql {mode} cell lost acks to sheds: "
                        f"{sub['ack_shed_overlap']}")
        if not sub.get("recovered"):
            errs.append(f"ql {mode} cell never recovered")

    on = row.get("on") or {}
    off = row.get("off") or {}
    # ---- lease-plane actuator coverage, executed not just fired
    fires = on.get("fires") or {}
    if fires.get("conf_resize", 0) < 1:
        errs.append("no conf_resize actuation in the ql on cell")
    if fires.get("reshard", 0) < 1:
        errs.append("no reshard actuation in the ql on cell")
    if not any(c.get("ok") for c in (on.get("conf_log") or [])):
        errs.append("no responder conf re-installed live in the "
                    "ql on cell")
    if on.get("splits", 0) < 1:
        errs.append("no live split executed in the ql on cell")
    acts = on.get("actuations") or []
    if not any(a.startswith("conf_ctl") for a in acts):
        errs.append("ql actuation log carries no conf_ctl entry")
    if not any("range_change" in a for a in acts):
        errs.append("ql actuation log carries no range_change entry")

    # ---- bounded actuation + observe-mode cleanliness
    if sum(fires.values()) > QL_MAX_TOTAL_FIRES:
        errs.append(f"unbounded ql actuation: {fires}")
    if on.get("max_window_spend", 0) > on.get("budget_per_window", 0):
        errs.append("ql per-window actuation budget exceeded")
    if off.get("n_actuations") != 0:
        errs.append(f"ql observe-mode driver sent "
                    f"{off.get('n_actuations')} ctrl mutations")
    if off.get("splits", 0) or off.get("merges", 0):
        errs.append("ql off cell executed range changes")
    return errs


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--json",
                    default=os.path.join(REPO, "AUTOPILOT.json"))
    args = ap.parse_args()

    if not os.path.exists(args.json):
        print(f"FAIL: {args.json} missing — run "
              "scripts/autopilot_soak.py")
        return 1
    with open(args.json) as f:
        rows = json.load(f)
    ab = [r for r in rows if r.get("kind") == "autopilot_ab"]
    if len(ab) != 1:
        print(f"FAIL: expected exactly one autopilot_ab row, "
              f"found {len(ab)}")
        return 1
    ql = [r for r in rows if r.get("kind") == "autopilot_ql"]
    if len(ql) != 1:
        print(f"FAIL: expected exactly one autopilot_ql row, "
              f"found {len(ql)}")
        return 1
    errs = check_autopilot_ab(ab[0]) + check_autopilot_ql(ql[0])
    if errs:
        for e in errs:
            print(f"FAIL: {e}")
        return 1
    on = ab[0].get("on") or {}
    ql_on = ql[0].get("on") or {}
    print(f"autopilot gate OK: schedule {ab[0]['schedule_digest']}, "
          f"window ratios {ab[0].get('window_ratios')}, "
          f"fires {on.get('fires')}, "
          f"tail quiet, observe byte-identical; "
          f"ql schedule {ql[0]['schedule_digest']}, "
          f"ql fires {ql_on.get('fires')}, "
          f"splits {ql_on.get('splits')}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
