#!/usr/bin/env python3
"""graftprof gate (ci.sh tier 2h): hold the perf trajectory against the
committed PROFILE.json baseline.

Two regimes, matched to what each metric can promise:

- **Analytic metrics are gated STRICTLY.**  ``cost_analysis`` flops /
  bytes, ``memory_analysis`` buffer bytes, and HLO instruction counts
  (total + per declared phase) are deterministic per backend: the gate
  recompiles every protocol x variant cell at the committed shape and
  fails on ANY difference.  A kernel edit that changes the tick's cost
  profile must regenerate the baseline (``scripts/profile_run.py``) and
  commit the diff — exactly the LINT.json drift contract.
- **Wall-clock is gated with variance-aware tolerance + interleaved
  re-measure escalation.**  A shared CI box cannot promise 5%
  wall-clock stability, so the steady-tick time may drift up to
  ``--wall-tol`` (fractional) before failing — and an over-tolerance
  first measurement escalates into more re-measures (best-of wins, the
  trace_smoke pattern) before the gate calls it a regression.  A
  measurement FASTER than baseline never fails (it prints a
  regenerate-suggestion instead).
- The phase-scope instrumentation overhead is re-measured live
  (ablation A/B, ``core.protocol.set_phase_scopes``) and must stay
  under ``--max-overhead-pct`` — the same <5% budget the telemetry and
  tracing planes carry.
- **The mesh sweep is gated strictly too** (``check_mesh_sweep``): the
  committed per-mesh-shape baseline (PROFILE.json ``mesh_sweep`` —
  analytic tick metrics + carry-donation introspection per GxR mesh,
  captured on the 8-virtual-device CPU platform) is re-derived and
  compared field-for-field, every sharded point must show the scan
  carry fully donated, and both runs must have made consensus
  progress.  This keeps the pod-scale (MULTICHIP) trajectory
  regression-gated while the TPU tunnel is down.

Exit 0 = baseline reproduced; 1 = drift, regression, or a baseline
whose own ``ok`` fields record a bad capture (0 slots/s etc.).

Usage: python scripts/perf_gate.py --check [--wall-tol 0.5]
       [--max-rounds 3] [--max-overhead-pct 5.0] [--skip-wall]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")

import jax  # noqa: E402

jax.config.update(
    "jax_compilation_cache_dir", os.path.join(REPO, ".jax_cache")
)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

# summerset_tpu.host.profiling is resolved LAZILY, on first attribute
# use (which happens after main() has read the baseline's backend and
# configured the platform): importing it eagerly initializes the jax
# backend (module-level device constants), which would lock the platform
# AND the virtual-device count before the mesh-sweep gate can request
# its multi-device CPU platform.  A plain module proxy keeps the
# ``profiling.x`` spelling (and the test suite's monkeypatching) intact.
class _LazyProfiling:
    _mod = None

    def __getattr__(self, name):
        if _LazyProfiling._mod is None:
            from summerset_tpu.host import profiling as _p

            _LazyProfiling._mod = _p
        return getattr(_LazyProfiling._mod, name)


profiling = _LazyProfiling()

#: the analytic cell fields compared strictly (deterministic per
#: backend); everything wall-clock-ish is deliberately NOT here
STRICT_FIELDS = ("phases", "analytic", "memory", "shape")


def check_analytic_cell(committed: dict, errors: list) -> None:
    """Strict drift check for one protocol x variant cell."""
    name = committed["protocol"]
    variant = committed["variant"]
    shape = committed["shape"]
    cur = profiling.profile_cell(
        name, variant, G=shape["G"], R=shape["R"], W=shape["W"],
        with_device_trace=False, with_wall=False,
    )
    where = f"{name}[{variant}]"
    for field in STRICT_FIELDS:
        if cur.get(field) != committed.get(field):
            errors.append(
                f"{where}: analytic drift in {field!r}:\n"
                f"    committed: {json.dumps(committed.get(field), sort_keys=True)}\n"
                f"    current:   {json.dumps(cur.get(field), sort_keys=True)}"
            )


def wall_measure(committed: dict, ticks: int, reps: int) -> float:
    """One wall re-measure of a committed cell's steady tick."""
    from summerset_tpu.core import Engine

    shape = committed["shape"]
    kernel = profiling._build_cell_kernel(
        committed["protocol"], committed["variant"],
        shape["G"], shape["R"], shape["W"],
    )
    eng = Engine(kernel)
    state, ns = eng.init()
    comp = eng.lower_synthetic(state, ns, ticks, shape["P"]).compile()
    s_per_tick, _, _, _ = profiling.measure_steady_tick(
        comp, state, ns, ticks, reps
    )
    return s_per_tick


def check_wall_cell(committed: dict, tol: float, max_rounds: int,
                    errors: list, notes: list) -> None:
    """Variance-aware wall gate with re-measure escalation: the first
    over-tolerance reading triggers more measurement rounds (best-of
    all rounds is what gets compared), so one noisy window cannot fail
    CI by itself."""
    wall = committed.get("wall") or {}
    base = wall.get("s_per_tick")
    where = f"{committed['protocol']}[{committed['variant']}]"
    if not base or base <= 0:
        errors.append(f"{where}: committed wall.s_per_tick missing/zero")
        return
    ticks, reps = wall.get("ticks", 128), wall.get("reps", 3)
    best = float("inf")
    rounds = 0
    while rounds < max_rounds:
        rounds += 1
        best = min(best, wall_measure(committed, ticks, reps))
        if best <= base * (1.0 + tol):
            break
    ratio = best / base
    if ratio > 1.0 + tol:
        errors.append(
            f"{where}: steady tick regressed {ratio:.2f}x vs committed "
            f"({best*1e3:.3f} vs {base*1e3:.3f} ms/tick) after {rounds} "
            f"escalation round(s); tolerance {tol:.0%}"
        )
    elif ratio < 1.0 / (1.0 + tol):
        notes.append(
            f"{where}: steady tick IMPROVED {1/ratio:.2f}x vs committed "
            f"({best*1e3:.3f} ms/tick) — consider regenerating "
            "PROFILE.json to bank the win"
        )


#: mesh-sweep point fields compared strictly (deterministic per
#: backend); ``committed_slots`` is re-proved > 0 instead of compared
#: (it is a progress check, not an analytic metric)
MESH_STRICT_FIELDS = (
    "mesh", "group_shards", "replica_shards", "devices",
    "groups_per_device", "analytic", "memory", "donation", "donated",
)


def check_mesh_sweep(doc: dict, errors: list) -> None:
    """Strict per-mesh-shape gate: the committed multi-device (CPU-mesh)
    baseline — analytic tick metrics + the carry-donation introspection
    per mesh shape — must reproduce exactly, every sharded point must
    show the scan carry fully donated, and both the committed and the
    re-derived runs must have made consensus progress.  This is how
    MULTICHIP-style numbers become regression-gated like single-chip
    ones while the TPU tunnel is down."""
    ms = doc.get("mesh_sweep")
    if not ms:
        return
    shape = ms.get("shape", {})
    if not any(p.get("devices", 1) > 1 for p in ms["points"]):
        errors.append(
            "mesh_sweep: committed baseline has no multi-device point "
            "— the pod-scale trajectory is ungated"
        )
        return
    # skip the expensive re-derive only on errors from the COMMITTED
    # mesh points themselves — not on unrelated earlier gate errors in
    # the shared list (those must not mask a mesh-sweep regression)
    pre_errors = len(errors)
    for p in ms["points"]:
        where = f"mesh_sweep[{p.get('mesh')}]"
        if not p.get("ok", False):
            errors.append(f"{where}: committed point has ok=false")
        if not p.get("donated", False):
            errors.append(f"{where}: committed point shows an "
                          "undonated scan carry")
        if p.get("committed_slots", 1) <= 0:
            errors.append(f"{where}: committed capture made no progress")
    if len(errors) > pre_errors:
        return
    print("analytic: mesh sweep ...", flush=True)
    cur = profiling.mesh_sweep(
        ms["protocol"],
        meshes=tuple(p["mesh"] for p in ms["points"]),
        G=shape.get("G", profiling.MESH_SWEEP_SHAPE["G"]),
        R=shape.get("R", profiling.MESH_SWEEP_SHAPE["R"]),
        W=shape.get("W", profiling.MESH_SWEEP_SHAPE["W"]),
        ticks=shape.get("ticks", profiling.MESH_SWEEP_TICKS),
    )
    if cur["skipped"]:
        errors.append(
            f"mesh_sweep: cannot re-derive {cur['skipped']} — fewer "
            "devices visible than the committed baseline used"
        )
        return
    for com, new in zip(ms["points"], cur["points"]):
        where = f"mesh_sweep[{com['mesh']}]"
        if new.get("committed_slots", 1) <= 0 or not new.get("ok"):
            errors.append(f"{where}: re-derived run made no progress or "
                          "lost carry donation")
        for field in MESH_STRICT_FIELDS:
            if com.get(field) != new.get(field):
                errors.append(
                    f"{where}: drift in {field!r}:\n"
                    f"    committed: "
                    f"{json.dumps(com.get(field), sort_keys=True)}\n"
                    f"    current:   "
                    f"{json.dumps(new.get(field), sort_keys=True)}"
                )


#: tally-sweep point fields compared strictly (deterministic per
#: backend); ``committed_slots`` is re-proved > 0 AND equal across the
#: two tally modes of a point instead of compared to the baseline, and
#: the measured per-phase device time is never gated strictly
TALLY_STRICT_FIELDS = (
    "protocol", "tally", "mesh", "group_shards", "replica_shards",
    "devices", "groups_per_device", "analytic", "hlo_ops_by_phase",
    "memory", "tally_lane_shapes",
)


def check_tally_sweep(doc: dict, errors: list) -> None:
    """The quorum-tally gate (core/quorum.py): the committed pairwise
    vs collective cells must (a) reproduce exactly (analytic fields
    strict), (b) show the collective cell of every (protocol, mesh)
    point STRICTLY reducing the tally phase's HLO op count and the
    tick's flops/bytes vs its pairwise twin, (c) prove the R² pairwise
    lanes absent from the collective delay line (lane shapes [D, G, R],
    not [D, G, R, R]), and (d) make identical consensus progress in
    both modes — the analytic face of the byte-identical equivalence
    gate in tests/test_quorum_tally.py."""
    ts = doc.get("tally_sweep")
    if not ts:
        errors.append(
            "tally_sweep: missing from the committed baseline — the "
            "collective-tally trajectory is ungated (regenerate with "
            "scripts/profile_run.py)"
        )
        return
    points = ts.get("points", [])
    by_key = {}
    pre_errors = len(errors)
    for p in points:
        where = f"tally_sweep[{p.get('protocol')}@{p.get('mesh')}" \
                f":{p.get('tally')}]"
        if not p.get("ok", False) or p.get("committed_slots", 0) <= 0:
            errors.append(f"{where}: committed point made no progress")
        by_key.setdefault(
            (p.get("protocol"), p.get("mesh")), {}
        )[p.get("tally")] = p
    for (proto, mesh), modes in sorted(by_key.items()):
        where = f"tally_sweep[{proto}@{mesh}]"
        pw, co = modes.get("pairwise"), modes.get("collective")
        if pw is None or co is None:
            errors.append(f"{where}: missing a tally mode "
                          f"(have {sorted(modes)})")
            continue
        if pw["committed_slots"] != co["committed_slots"]:
            errors.append(
                f"{where}: collective progress diverges from pairwise "
                f"({co['committed_slots']} vs {pw['committed_slots']} "
                "slots) — the modes must be semantically identical"
            )
        for metric in ("tally_phase_ops", "flops", "bytes_accessed"):
            pv = pw["analytic"].get(metric)
            cv = co["analytic"].get(metric)
            if pv is None or cv is None or not cv < pv:
                errors.append(
                    f"{where}: collective {metric} not strictly below "
                    f"pairwise ({cv} vs {pv}) — the in-mesh tally "
                    "stopped paying for itself"
                )
        # delay-line lane geometry ([D, ...]): collective = [D, G, R]
        # per-source records; pairwise = [D, G, R, R] pair lanes
        for lane, shape in sorted(co["tally_lane_shapes"].items()):
            if len(shape) != 3:
                errors.append(
                    f"{where}: collective lane {lane} still pairwise-"
                    f"shaped on the delay line: {shape}"
                )
        for lane, shape in sorted(pw["tally_lane_shapes"].items()):
            if len(shape) != 4:
                errors.append(
                    f"{where}: pairwise lane {lane} has unexpected "
                    f"delay-line shape {shape}"
                )
    if len(errors) > pre_errors:
        return
    print("analytic: tally sweep ...", flush=True)
    shape = ts.get("shape", {})
    cur = profiling.tally_sweep(
        protocols=tuple(sorted({p["protocol"] for p in points})),
        meshes=tuple(dict.fromkeys(p["mesh"] for p in points)),
        G=shape.get("G", profiling.MESH_SWEEP_SHAPE["G"]),
        R=shape.get("R", profiling.MESH_SWEEP_SHAPE["R"]),
        W=shape.get("W", profiling.MESH_SWEEP_SHAPE["W"]),
        ticks=shape.get("ticks", profiling.MESH_SWEEP_TICKS),
        with_device_trace=False,
    )
    if cur["skipped"]:
        errors.append(
            f"tally_sweep: cannot re-derive {cur['skipped']} — fewer "
            "devices visible than the committed baseline used"
        )
        return
    cur_by = {
        (p["protocol"], p["mesh"], p["tally"]): p for p in cur["points"]
    }
    for com in points:
        key = (com["protocol"], com["mesh"], com["tally"])
        where = f"tally_sweep[{key[0]}@{key[1]}:{key[2]}]"
        new = cur_by.get(key)
        if new is None:
            errors.append(f"{where}: point missing from re-derived sweep")
            continue
        if new.get("committed_slots", 0) <= 0:
            errors.append(f"{where}: re-derived run made no progress")
        for field in TALLY_STRICT_FIELDS:
            if com.get(field) != new.get(field):
                errors.append(
                    f"{where}: drift in {field!r}:\n"
                    f"    committed: "
                    f"{json.dumps(com.get(field), sort_keys=True)}\n"
                    f"    current:   "
                    f"{json.dumps(new.get(field), sort_keys=True)}"
                )


def check_tputlat_pipeline_ab(path: str, errors: list) -> None:
    """The committed pipelined-tick-loop curve proof (TPUTLAT.json
    ``pipeline_ab``): the serial-vs-pipelined load sweep must be
    present and hold its inequalities on the committed numbers (same
    workload digest both legs, pipelined saturated tput strictly up,
    measured overlap > 0) — re-asserted here like every other drift
    gate, so a hand-edited block can't pass on ``ok: true`` alone."""
    from bench_tput_lat import check_tputlat_pipeline_ab as check_ab

    try:
        with open(path) as f:
            art = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        errors.append(f"tputlat: cannot read {path}: {e}")
        return
    ab = art.get("pipeline_ab")
    if not ab:
        errors.append(
            "tputlat: pipeline_ab block missing (run "
            "scripts/bench_tput_lat.py --pipeline-ab)"
        )
        return
    errors.extend(f"tputlat: {w}" for w in check_ab(ab))
    if not ab.get("ok"):
        errors.append("tputlat: pipeline_ab committed not ok")


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--profile", default=os.path.join(REPO, "PROFILE.json"))
    ap.add_argument("--tputlat", default=os.path.join(REPO, "TPUTLAT.json"))
    ap.add_argument("--check", action="store_true",
                    help="(the only mode; present for CI-invocation "
                         "symmetry with the other gates)")
    ap.add_argument("--wall-tol", type=float, default=0.5,
                    help="fractional steady-tick drift allowed before a "
                         "wall regression fails (default 0.5 = +50%%)")
    ap.add_argument("--max-rounds", type=int, default=3)
    ap.add_argument("--max-overhead-pct", type=float, default=5.0)
    ap.add_argument("--skip-wall", action="store_true")
    ap.add_argument("--skip-overhead", action="store_true")
    ap.add_argument("--wall-all-variants", action="store_true",
                    help="re-measure wall for host cells too (default: "
                         "device cells only; host cells stay "
                         "analytic-gated to bound CI time)")
    args = ap.parse_args()

    try:
        with open(args.profile) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(f"perf_gate: cannot read baseline {args.profile}: {e}")
        return 1

    # gate on the baseline's own backend: a cpu baseline (the committed
    # CI default) pins the cpu platform so the tunnel can't hang us; a
    # native capture (profile_run --backend native) is re-derived on
    # whatever chip is visible, and the backend-match check below fails
    # loudly when they disagree
    if doc.get("backend") == "cpu":
        jax.config.update("jax_platforms", "cpu")
        # the mesh-sweep cells need the same virtual multi-device CPU
        # platform profile_run captured on; must precede backend init
        from summerset_tpu.utils.jaxcompat import set_cpu_devices

        set_cpu_devices(8)

    errors: list = []
    notes: list = []

    backend = jax.devices()[0].platform
    if doc.get("backend") != backend:
        errors.append(
            f"baseline backend {doc.get('backend')!r} != current "
            f"{backend!r}: analytic metrics are only comparable per "
            "backend — regenerate on this backend"
        )

    cells = [
        cell
        for per in doc.get("protocols", {}).values()
        for cell in per.values()
    ]
    if not cells:
        errors.append("baseline has no protocol cells")

    # the baseline must record a GOOD capture: a committed artifact with
    # ok=false / 0 slots/s is itself a gate failure (the BENCH_r05
    # lesson — a dead capture must not pass silently)
    for cell in cells:
        where = f"{cell['protocol']}[{cell['variant']}]"
        if not cell.get("ok", False):
            errors.append(f"{where}: committed cell has ok=false")
        wall = cell.get("wall") or {}
        if wall and wall.get("committed_slots_per_s", 0) <= 0:
            errors.append(f"{where}: committed capture made no progress "
                          "(0 committed slots/s)")
        if doc.get("profiler_available") and \
                cell.get("phase_wall_us_per_tick") is None:
            errors.append(f"{where}: no per-phase device-time breakdown "
                          "although the profiler was available at "
                          "capture time")

    if not errors:
        for cell in cells:
            print(f"analytic: {cell['protocol']}[{cell['variant']}] ...",
                  flush=True)
            check_analytic_cell(cell, errors)

        sweep = doc.get("g_sweep")
        if sweep:
            print("analytic: g-sweep ...", flush=True)
            cur = profiling.g_sweep(
                sweep["protocol"],
                groups=tuple(p["G"] for p in sweep["points"]),
            )
            if cur["points"] != sweep["points"]:
                errors.append(
                    "g_sweep: analytic drift:\n"
                    f"    committed: {json.dumps(sweep['points'])}\n"
                    f"    current:   {json.dumps(cur['points'])}"
                )

        check_mesh_sweep(doc, errors)
        check_tally_sweep(doc, errors)
        check_tputlat_pipeline_ab(args.tputlat, errors)

    if not errors and not args.skip_wall:
        for cell in cells:
            if cell.get("variant") != "device" and \
                    not args.wall_all_variants:
                continue
            if not cell.get("wall"):
                continue
            print(f"wall: {cell['protocol']}[{cell['variant']}] ...",
                  flush=True)
            check_wall_cell(cell, args.wall_tol, args.max_rounds,
                            errors, notes)

    if not errors and not args.skip_overhead:
        committed_ov = doc.get("scope_overhead") or {}
        if committed_ov.get("pct", 0.0) > args.max_overhead_pct:
            errors.append(
                f"committed scope_overhead {committed_ov.get('pct')}% > "
                f"{args.max_overhead_pct}%"
            )
        else:
            print("overhead: phase-scope ablation A/B ...", flush=True)
            ov = profiling.measure_scope_overhead(
                max_pct=args.max_overhead_pct,
            )
            print(f"  live overhead {ov['pct']}% "
                  f"({ov['pairs']} interleaved pairs)")
            if ov["pct"] > args.max_overhead_pct:
                errors.append(
                    f"phase-scope instrumentation overhead {ov['pct']}% "
                    f"> {args.max_overhead_pct}% (after escalation)"
                )

    for n in notes:
        print(f"note: {n}")
    if errors:
        print(f"perf_gate: FAIL ({len(errors)} problem(s))")
        for e in errors:
            print(f"  - {e}")
        print("regenerate with: python scripts/profile_run.py "
              "(and commit the PROFILE.json diff with the change "
              "that caused it)")
        return 1
    print(f"perf_gate: PASS ({len(cells)} cells reproduced against "
          f"{args.profile})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
