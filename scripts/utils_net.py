#!/usr/bin/env python3
"""WAN emulation helpers for host clusters: tc-netem per interface.

Parity: reference ``scripts/utils/net.py`` — applies ``tc qdisc ...
netem delay/jitter/rate`` to each replica's (veth) interface so
WAN/geo experiments run on one Linux box, and clears them after.

Degradation: requires the ``sch_netem`` kernel module and CAP_NET_ADMIN;
``netem_available()`` probes first and every apply is a no-op-with-
warning without it (this build box has tc but no netem module).  Command
construction is pure and unit-testable (`netem_cmd`).

The device-level counterpart is ``core/netmodel.py`` (delay/jitter/drop
as tensor transforms), which is what the kernel test suites use; this
module exists for REAL host clusters on capable machines.
"""

from __future__ import annotations

import shutil
import subprocess
from typing import List, Optional


def netem_cmd(dev: str, delay_ms: float = 0.0, jitter_ms: float = 0.0,
              rate_gbps: float = 0.0, loss_pct: float = 0.0,
              replace: bool = True) -> List[str]:
    """Build the ``tc qdisc`` argv for a netem discipline (pure)."""
    cmd = [
        "tc", "qdisc", "replace" if replace else "add",
        "dev", dev, "root", "netem",
    ]
    if delay_ms > 0:
        cmd += ["delay", f"{delay_ms}ms"]
        if jitter_ms > 0:
            cmd += [f"{jitter_ms}ms", "distribution", "pareto"]
    if loss_pct > 0:
        cmd += ["loss", f"{loss_pct}%"]
    if rate_gbps > 0:
        cmd += ["rate", f"{rate_gbps}gbit"]
    return cmd


def clear_cmd(dev: str) -> List[str]:
    return ["tc", "qdisc", "del", "dev", dev, "root"]


def netem_available(dev: str = "lo") -> bool:
    """Probe: tc present AND the sch_netem module loadable."""
    if shutil.which("tc") is None:
        return False
    probe = subprocess.run(
        netem_cmd(dev, delay_ms=0.1), capture_output=True, text=True
    )
    if probe.returncode == 0:
        subprocess.run(clear_cmd(dev), capture_output=True)
        return True
    return False


def apply_netem(dev: str, delay_ms: float = 0.0, jitter_ms: float = 0.0,
                rate_gbps: float = 0.0, loss_pct: float = 0.0
                ) -> Optional[str]:
    """Apply a netem discipline; returns an error string instead of
    raising so orchestration scripts can degrade to no emulation."""
    r = subprocess.run(
        netem_cmd(dev, delay_ms, jitter_ms, rate_gbps, loss_pct),
        capture_output=True, text=True,
    )
    return None if r.returncode == 0 else (r.stderr.strip() or "tc failed")


def clear_netem(dev: str) -> None:
    subprocess.run(clear_cmd(dev), capture_output=True)


if __name__ == "__main__":
    import sys

    dev = sys.argv[1] if len(sys.argv) > 1 else "lo"
    if not netem_available(dev):
        print(f"netem unavailable on {dev} (sch_netem module or "
              "CAP_NET_ADMIN missing); commands it would run:")
        print(" ", " ".join(netem_cmd(dev, 10, 2, 1)))
        print(" ", " ".join(clear_cmd(dev)))
        raise SystemExit(1)
    err = apply_netem(dev, delay_ms=10, jitter_ms=2, rate_gbps=1)
    print("applied" if err is None else f"failed: {err}")
