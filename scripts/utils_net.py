#!/usr/bin/env python3
"""WAN emulation helpers for host clusters: tc-netem per interface.

Parity: reference ``scripts/utils/net.py`` — applies ``tc qdisc ...
netem delay/jitter/rate`` to each replica's (veth) interface so
WAN/geo experiments run on one Linux box, and clears them after.

Degradation: requires the ``sch_netem`` kernel module and CAP_NET_ADMIN;
``netem_available()`` probes first and every apply is a no-op-with-
warning without it (this build box has tc but no netem module).  Command
construction is pure and unit-testable (`netem_cmd`).

The device-level counterpart is ``core/netmodel.py`` (delay/jitter/drop
as tensor transforms), which is what the kernel test suites use; this
module exists for REAL host clusters on capable machines.
"""

from __future__ import annotations

import shutil
import subprocess
from typing import List, Optional


def netem_cmd(dev: str, delay_ms: float = 0.0, jitter_ms: float = 0.0,
              rate_gbps: float = 0.0, loss_pct: float = 0.0,
              replace: bool = True) -> List[str]:
    """Build the ``tc qdisc`` argv for a netem discipline (pure)."""
    cmd = [
        "tc", "qdisc", "replace" if replace else "add",
        "dev", dev, "root", "netem",
    ]
    if delay_ms > 0:
        cmd += ["delay", f"{delay_ms}ms"]
        if jitter_ms > 0:
            cmd += [f"{jitter_ms}ms", "distribution", "pareto"]
    if loss_pct > 0:
        cmd += ["loss", f"{loss_pct}%"]
    if rate_gbps > 0:
        cmd += ["rate", f"{rate_gbps}gbit"]
    return cmd


def clear_cmd(dev: str) -> List[str]:
    return ["tc", "qdisc", "del", "dev", dev, "root"]


def netem_available(dev: str = "lo") -> bool:
    """Probe: tc present AND the sch_netem module loadable."""
    if shutil.which("tc") is None:
        return False
    probe = subprocess.run(
        netem_cmd(dev, delay_ms=0.1), capture_output=True, text=True
    )
    if probe.returncode == 0:
        subprocess.run(clear_cmd(dev), capture_output=True)
        return True
    return False


def apply_netem(dev: str, delay_ms: float = 0.0, jitter_ms: float = 0.0,
                rate_gbps: float = 0.0, loss_pct: float = 0.0
                ) -> Optional[str]:
    """Apply a netem discipline; returns an error string instead of
    raising so orchestration scripts can degrade to no emulation."""
    r = subprocess.run(
        netem_cmd(dev, delay_ms, jitter_ms, rate_gbps, loss_pct),
        capture_output=True, text=True,
    )
    return None if r.returncode == 0 else (r.stderr.strip() or "tc failed")


def clear_netem(dev: str) -> None:
    subprocess.run(clear_cmd(dev), capture_output=True)


if __name__ == "__main__":
    import sys

    dev = sys.argv[1] if len(sys.argv) > 1 else "lo"
    if not netem_available(dev):
        print(f"netem unavailable on {dev} (sch_netem module or "
              "CAP_NET_ADMIN missing); commands it would run:")
        print(" ", " ".join(netem_cmd(dev, 10, 2, 1)))
        print(" ", " ".join(clear_cmd(dev)))
        raise SystemExit(1)
    err = apply_netem(dev, delay_ms=10, jitter_ms=2, rate_gbps=1)
    print("applied" if err is None else f"failed: {err}")


# --------------------------------------------------------------- netns/veth
# Per-replica network namespaces with veth uplinks into one bridge, so a
# single box gives every replica its own interface to shape with netem
# (parity: reference scripts/local_cluster.py --use-veth +
# scripts/utils/net.py).  Command construction is pure; application is
# gated on a capability probe (needs CAP_NET_ADMIN; this build box
# doesn't grant it, real hosts do).

BRIDGE = "smtpubr0"
SUBNET = "10.77.0"          # /24; bridge at .1, replica r at .(10+r)


def netns_name(idx: int) -> str:
    return f"smtpu{idx}"


def replica_ip(idx: int) -> str:
    return f"{SUBNET}.{10 + idx}"


def bridge_ip() -> str:
    return f"{SUBNET}.1"


def bridge_cmds() -> List[List[str]]:
    """Create the shared bridge in the root namespace (idempotent-ish:
    callers run teardown first)."""
    return [
        ["ip", "link", "add", BRIDGE, "type", "bridge"],
        ["ip", "addr", "add", f"{bridge_ip()}/24", "dev", BRIDGE],
        ["ip", "link", "set", BRIDGE, "up"],
    ]


def netns_cmds(idx: int) -> List[List[str]]:
    """Create namespace idx + veth pair bridged to the root namespace."""
    ns = netns_name(idx)
    host_if = f"veth{ns}"
    return [
        ["ip", "netns", "add", ns],
        ["ip", "link", "add", host_if, "type", "veth",
         "peer", "name", "eth0", "netns", ns],
        ["ip", "link", "set", host_if, "master", BRIDGE],
        ["ip", "link", "set", host_if, "up"],
        ["ip", "-n", ns, "addr", "add", f"{replica_ip(idx)}/24",
         "dev", "eth0"],
        ["ip", "-n", ns, "link", "set", "eth0", "up"],
        ["ip", "-n", ns, "link", "set", "lo", "up"],
    ]


def netns_teardown_cmds(n: int) -> List[List[str]]:
    cmds = [["ip", "netns", "del", netns_name(i)] for i in range(n)]
    cmds.append(["ip", "link", "del", BRIDGE])
    return cmds


def netns_exec_prefix(idx: int) -> List[str]:
    """argv prefix running a command inside replica idx's namespace."""
    return ["ip", "netns", "exec", netns_name(idx)]


def netns_available() -> bool:
    """Probe: `ip netns add` works (CAP_NET_ADMIN) — cleaned up after.
    A leftover probe namespace from a killed prior run is removed first
    so EEXIST can never read as a permanent capability failure."""
    if shutil.which("ip") is None:
        return False
    probe_ns = "smtpuprobe"
    subprocess.run(["ip", "netns", "del", probe_ns], capture_output=True)
    r = subprocess.run(["ip", "netns", "add", probe_ns],
                       capture_output=True, text=True)
    if r.returncode != 0:
        return False
    subprocess.run(["ip", "netns", "del", probe_ns], capture_output=True)
    return True


def _existing_smtpu_netns() -> List[str]:
    r = subprocess.run(["ip", "netns", "list"], capture_output=True,
                       text=True)
    if r.returncode != 0:
        return []
    return [
        line.split()[0] for line in r.stdout.splitlines()
        if line.split() and line.split()[0].startswith("smtpu")
    ]


def setup_veth_cluster(n: int) -> Optional[str]:
    """Create bridge + n namespaces; returns an error string on the
    first failing command (after attempting teardown) or None."""
    teardown_veth_cluster(n)  # clear leftovers from a dead run
    for cmd in bridge_cmds() + [c for i in range(n)
                                for c in netns_cmds(i)]:
        r = subprocess.run(cmd, capture_output=True, text=True)
        if r.returncode != 0:
            err = f"{' '.join(cmd)}: {r.stderr.strip() or 'failed'}"
            teardown_veth_cluster(n)
            return err
    return None


def teardown_veth_cluster(n: int) -> None:
    """Remove the bridge and EVERY smtpu* namespace — including ones
    beyond n left behind by a dead run with a larger replica count
    (their veths hold addresses in the same /24)."""
    names = set(_existing_smtpu_netns())
    names.update(netns_name(i) for i in range(n))
    names.discard("smtpuprobe")
    for ns in sorted(names):
        subprocess.run(["ip", "netns", "del", ns], capture_output=True)
    subprocess.run(["ip", "link", "del", BRIDGE], capture_output=True)


def shape_veth(idx: int, delay_ms: float = 0.0, jitter_ms: float = 0.0,
               rate_gbps: float = 0.0, loss_pct: float = 0.0
               ) -> Optional[str]:
    """Apply netem on replica idx's host-side veth (egress toward the
    replica); same knobs as apply_netem."""
    return apply_netem(f"veth{netns_name(idx)}", delay_ms, jitter_ms,
                       rate_gbps, loss_pct)
