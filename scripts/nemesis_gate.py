#!/usr/bin/env python3
"""NEMESIS.json drift + long-lived-matrix gate (ci.sh tier 2c).

Asserts, WITHOUT bringing up clusters (pure plan regeneration):

1. every committed matrix cell is linearizable (``ok``) with a bounded
   recovery (``recovery_ticks`` within the soak budget);
2. per-seed digests are byte-identical to what ``FaultPlan.generate``
   produces from the current code — the repro contract: a committed
   NEMESIS.json row can always be replayed with ``--seed N``, so any
   change to the schedule generator must regenerate the artifact in the
   same PR (this is the drift gate);
3. the matrix actually covers the long-lived classes: ``device_reset``,
   ``conf_change``, and ``take_snapshot`` each occur in at least one
   scheduled event across the matrix seeds, and the QuorumLeases row
   (the only conf-plane protocol in the matrix) is present;
4. end-of-soak boundedness was recorded: WAL sizes under the bound;
5. the gray-failure rows cover every fail-slow class x protocol as a
   mitigated/unmitigated twin pair: every cell ok against the canonical
   ``FaultPlan.failslow`` digest, the mitigated twin demoted its
   limping leader, and its fault-window throughput beat the
   unmitigated twin by the committed ratio bar;
6. the ``wire_ab`` and ``pipeline_ab`` equivalence rows are present and
   hold: one soak cell run twice (codec on/off, tick loop
   pipelined/serial), byte-identical FaultPlan digests across modes,
   both runs linearizable — the pipeline row's ``wal_torn``/
   ``wal_fsync`` events land between a step and its durability fence.

Usage:  python scripts/nemesis_gate.py [--json NEMESIS.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from nemesis_soak import (  # noqa: E402  (scripts/ sibling import)
    DEFAULT_BUDGET_TICKS, DEFAULT_TICKS, FAILSLOW_CLASSES,
    FAILSLOW_PROTOCOLS, FAILSLOW_SEED, FAILSLOW_TICKS,
    FAILSLOW_TPUT_RATIO, MATRIX_EXTRA, MATRIX_PROTOCOLS, MATRIX_SEEDS,
    SOAK_CLASSES, WAL_BOUND_BYTES,
)

from summerset_tpu.host.nemesis import FaultPlan  # noqa: E402

DEFAULT_REPLICAS = 3
LONG_LIVED = ("device_reset", "conf_change", "take_snapshot",
              "range_change")


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--json", default=os.path.join(REPO, "NEMESIS.json"))
    args = ap.parse_args()
    with open(args.json) as f:
        rows = json.load(f)

    failslow_rows = [r for r in rows if r.get("failslow")]
    wire_ab_rows = [r for r in rows if r.get("kind") == "wire_ab"]
    pipeline_ab_rows = [
        r for r in rows if r.get("kind") == "pipeline_ab"
    ]
    rows = [
        r for r in rows
        if not r.get("failslow")
        and r.get("kind") not in ("wire_ab", "pipeline_ab")
    ]

    failures = []

    # ---- wire-codec A/B row --------------------------------------------
    # one soak cell run codec-on AND codec-off: the seeded repro
    # contract must hold across wire formats — byte-identical FaultPlan
    # digests (and identical to what the current generator produces),
    # both runs linearizable with bounded recovery
    if not wire_ab_rows:
        failures.append("wire_ab row missing (run "
                        "scripts/nemesis_soak.py --wire-ab)")
    for row in wire_ab_rows:
        tag = f"wire_ab {row.get('protocol')} seed={row.get('seed')}"
        if not row.get("ok"):
            failures.append(f"{tag}: failed ({row.get('error')})")
        if not row.get("digests_identical"):
            failures.append(f"{tag}: plan digests diverged across "
                            "codec modes")
        want = FaultPlan.generate(
            row.get("seed"), DEFAULT_REPLICAS, DEFAULT_TICKS,
            classes=SOAK_CLASSES,
        ).digest()
        if row.get("digest") != want:
            failures.append(
                f"{tag}: digest drift — committed {row.get('digest')} "
                f"vs regenerated {want}"
            )
        for mode in ("codec_on", "codec_off"):
            sub = row.get(mode) or {}
            if not sub.get("ok"):
                failures.append(
                    f"{tag}: {mode} run failed ({sub.get('error')})"
                )
            if bool(sub.get("wire_codec")) != (mode == "codec_on"):
                failures.append(f"{tag}: {mode} ran with wire_codec="
                                f"{sub.get('wire_codec')}")

    # ---- pipelined-loop A/B row ----------------------------------------
    # one soak cell run pipelined AND serial: the seeded repro contract
    # must hold across tick-loop modes — byte-identical FaultPlan
    # digests (and identical to what the current generator produces),
    # both runs linearizable with bounded recovery.  The schedule's
    # wal_torn/wal_fsync events land between a pipelined step and its
    # durability fence, so this row is also the soak-scale fence proof.
    if not pipeline_ab_rows:
        failures.append("pipeline_ab row missing (run "
                        "scripts/nemesis_soak.py --pipeline-ab)")
    for row in pipeline_ab_rows:
        tag = (f"pipeline_ab {row.get('protocol')} "
               f"seed={row.get('seed')}")
        if not row.get("ok"):
            failures.append(f"{tag}: failed ({row.get('error')})")
        if not row.get("digests_identical"):
            failures.append(f"{tag}: plan digests diverged across "
                            "pipeline modes")
        want = FaultPlan.generate(
            row.get("seed"), DEFAULT_REPLICAS, DEFAULT_TICKS,
            classes=SOAK_CLASSES,
        ).digest()
        if row.get("digest") != want:
            failures.append(
                f"{tag}: digest drift — committed {row.get('digest')} "
                f"vs regenerated {want}"
            )
        for mode in ("pipeline_on", "pipeline_off"):
            sub = row.get(mode) or {}
            if not sub.get("ok"):
                failures.append(
                    f"{tag}: {mode} run failed ({sub.get('error')})"
                )
            if bool(sub.get("pipeline")) != (mode == "pipeline_on"):
                failures.append(f"{tag}: {mode} ran with pipeline="
                                f"{sub.get('pipeline')}")
    by_seed = {
        s: FaultPlan.generate(
            s, DEFAULT_REPLICAS, DEFAULT_TICKS, classes=SOAK_CLASSES
        )
        for s in MATRIX_SEEDS
    }
    want_cells = {
        (p, s)
        for p in MATRIX_PROTOCOLS + MATRIX_EXTRA for s in MATRIX_SEEDS
    }
    seen_cells = set()
    for row in rows:
        cell = (row.get("protocol"), row.get("seed"))
        seen_cells.add(cell)
        tag = f"{cell[0]} seed={cell[1]}"
        if not row.get("ok"):
            failures.append(f"{tag}: not linearizable/ok "
                            f"({row.get('error')})")
        rt = row.get("recovery_ticks")
        if rt is None or rt > DEFAULT_BUDGET_TICKS:
            failures.append(f"{tag}: recovery unbounded ({rt} ticks)")
        plan = by_seed.get(row.get("seed"))
        if plan is None:
            failures.append(f"{tag}: seed outside the matrix")
        elif row.get("digest") != plan.digest():
            failures.append(
                f"{tag}: digest drift — committed {row.get('digest')} "
                f"vs regenerated {plan.digest()}; rerun "
                "scripts/nemesis_soak.py --matrix and commit the diff"
            )
        for me, size in (row.get("wal_bytes") or {}).items():
            if size > WAL_BOUND_BYTES:
                failures.append(f"{tag}: replica {me} WAL {size}B over "
                                f"bound {WAL_BOUND_BYTES}")
    missing = want_cells - seen_cells
    if missing:
        failures.append(f"matrix cells missing: {sorted(missing)}")

    kinds = {ev.kind for p in by_seed.values() for ev in p.events}
    for cls in LONG_LIVED:
        if cls not in SOAK_CLASSES:
            failures.append(f"{cls} missing from SOAK_CLASSES")
        elif cls not in kinds:
            failures.append(
                f"{cls} never scheduled across matrix seeds "
                f"{MATRIX_SEEDS} — widen the horizon or reseed"
            )

    # ---- gray-failure (fail-slow) rows ---------------------------------
    # every class x protocol cell present as a mitigated/unmitigated twin
    # pair, every cell ok, digests byte-identical to the canonical
    # FaultPlan.failslow per (class, seed), the mitigated twin demoted at
    # least once, and its fault-window throughput >= the ratio bar
    fs = {}
    for r in failslow_rows:
        fs[(r.get("protocol"), r.get("class"),
            bool(r.get("mitigated")))] = r
    for cls in FAILSLOW_CLASSES:
        want_digest = FaultPlan.failslow(
            cls, FAILSLOW_SEED, DEFAULT_REPLICAS, FAILSLOW_TICKS
        ).digest()
        for proto in FAILSLOW_PROTOCOLS:
            pair = {}
            for mit in (True, False):
                tag = (f"failslow {proto}/{cls}/"
                       f"{'mit' if mit else 'unmit'}")
                row = fs.get((proto, cls, mit))
                if row is None:
                    failures.append(f"{tag}: cell missing — rerun "
                                    "scripts/nemesis_soak.py "
                                    "--failslow-matrix")
                    continue
                pair[mit] = row
                if not row.get("ok"):
                    failures.append(f"{tag}: not ok ({row.get('error')})")
                if row.get("digest") != want_digest:
                    failures.append(
                        f"{tag}: digest drift — committed "
                        f"{row.get('digest')} vs canonical {want_digest}"
                    )
                rt = row.get("recovery_ticks")
                if rt is None or rt > DEFAULT_BUDGET_TICKS:
                    failures.append(f"{tag}: recovery unbounded ({rt})")
            mitr = pair.get(True)
            if mitr is not None:
                if (mitr.get("demotions") or 0) < 1:
                    failures.append(
                        f"failslow {proto}/{cls}: mitigated twin never "
                        "demoted the limping leader"
                    )
                unmit = pair.get(False)
                if unmit is not None and unmit.get("tput_fault"):
                    ratio = (
                        (mitr.get("tput_fault") or 0.0)
                        / max(unmit["tput_fault"], 1e-9)
                    )
                    if ratio < FAILSLOW_TPUT_RATIO:
                        failures.append(
                            f"failslow {proto}/{cls}: mitigated "
                            f"throughput only {ratio:.2f}x the "
                            f"unmitigated twin "
                            f"(need >= {FAILSLOW_TPUT_RATIO}x)"
                        )

    if failures:
        print("NEMESIS gate FAIL:")
        for f_ in failures:
            print(f"  - {f_}")
        return 1
    print(
        f"NEMESIS gate OK: {len(rows)} matrix cells linearizable, "
        f"digests byte-identical per seed, recovery <= "
        f"{DEFAULT_BUDGET_TICKS} ticks, long-lived classes {LONG_LIVED} "
        f"all scheduled; {len(failslow_rows)} fail-slow cells "
        f"({FAILSLOW_CLASSES} x {FAILSLOW_PROTOCOLS} twin pairs) ok "
        f"with mitigated recovered throughput >= "
        f"{FAILSLOW_TPUT_RATIO}x the unmitigated twin"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
