#!/usr/bin/env python3
"""WORKLOADS.json drift + overload-survival gate (ci.sh tier 2g).

Asserts, WITHOUT bringing up clusters (pure plan regeneration):

1. every committed matrix cell passed (``ok``) with a bounded recovery;
2. per-seed digests are byte-identical to what the current generators
   produce (``WorkloadPlan.generate`` AND the cell's ``FaultPlan``) —
   the repro contract: any change to either schedule generator must
   regenerate the artifact in the same PR (the drift gate);
3. the matrix covers the workload classes: every class named in
   ``WL_MATRIX`` actually has a committed row, and at least one
   overload (``hot_burst``) row exists per protocol listed;
4. overload rows shed VISIBLY (client-observed sheds > 0 and the
   server-side ``api_shed`` counters agree) and BOUNDEDLY (progress
   was made: acked > 0, sheds < issued, and no value was ever both
   acked and shed);
5. overload rows stayed within the committed latency/recovery budgets
   (accepted-op p99 through the burst, post-burst throughput tail);
6. the wire-codec planes hold their inequalities in HOSTBENCH.json:
   the ``wire_ab`` block (10k-client bench codec on/off: peer-frame
   bytes/tick + p2p serialize us/op strictly down, tput held — see
   ``host_bench.check_wire_ab``) and the ``wire_bench`` microbench
   block (bytes down on every shape, time down on the tick shapes);
7. the pipelined-tick-loop A/B holds in HOSTBENCH.json: the
   ``pipeline_ab`` block (same workload digest serial vs pipelined,
   pipelined steady tput strictly up, measured overlap > 0 — see
   ``host_bench.check_pipeline_ab``).

Usage:  python scripts/workload_gate.py [--json WORKLOADS.json]
                                        [--hostbench HOSTBENCH.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "scripts"))

from workload_soak import (  # noqa: E402  (scripts/ sibling import)
    DEFAULT_BUDGET_TICKS, FAULT_CLASSES, P99_BUDGET_S,
    PROXY_AB_MIN_RATIO, PROXY_CELL, PROXY_COUNT, RECOVER_FRAC,
    RESHARD_GROUPS, SCAN_CELL_KINDS, SCAN_RESHARD_SEED, TRACE_FILE,
    WL_MATRIX, build_plans, build_proxy_plan, build_scan_plan,
)

DEFAULT_REPLICAS = 3


def check_proxy_ab(row) -> list:
    """Gate the fused-vs-proxy shed-point A/B row (serving-plane
    split): same WorkloadPlan digest on both sides, shed point up by
    >= PROXY_AB_MIN_RATIO, sheds attributed to the PROXY tier in the
    proxy run, both runs linearizable and inside the fused budgets."""
    from workload_soak import AB_SEED, DEFAULT_CLIENTS, DEFAULT_KEYS, \
        DEFAULT_HORIZON
    from summerset_tpu.host.workload import WorkloadPlan

    fails = []
    tag = "proxy_ab"
    if not row.get("ok"):
        fails.append(f"{tag}: failed ({row.get('error')})")
    wplan = WorkloadPlan.generate(
        AB_SEED, "hot_burst", clients=DEFAULT_CLIENTS,
        num_keys=DEFAULT_KEYS, horizon=DEFAULT_HORIZON,
    )
    if row.get("wl_digest") != wplan.digest():
        fails.append(
            f"{tag}: workload digest drift — committed "
            f"{row.get('wl_digest')} vs regenerated {wplan.digest()}"
        )
    if row.get("proxies", 0) < 2:
        fails.append(f"{tag}: needs >= 2 proxies "
                     f"(ran {row.get('proxies')})")
    ratio = row.get("shed_ratio") or 0.0
    if ratio < PROXY_AB_MIN_RATIO:
        fails.append(
            f"{tag}: shed point improved only {ratio}x "
            f"(need >= {PROXY_AB_MIN_RATIO})"
        )
    pshed = row.get("proxy_run_proxy_shed", 0)
    sshed = row.get("proxy_run_shard_shed", 0)
    if pshed <= 0 or pshed <= sshed:
        fails.append(
            f"{tag}: sheds not attributed to the proxy tier "
            f"(proxy {pshed} vs shard {sshed})"
        )
    for mode in ("fused", "proxy"):
        sub = row.get(mode) or {}
        if not sub.get("linearizable"):
            fails.append(f"{tag}: {mode} history not linearizable")
        if (sub.get("p99_s") or 1e9) > P99_BUDGET_S:
            fails.append(f"{tag}: {mode} accepted-op p99 "
                         f"{sub.get('p99_s')}s over budget")
        rec = sub.get("recover_tput")
        st = sub.get("offered_steady")
        if rec is None or st is None or rec < RECOVER_FRAC * st:
            fails.append(
                f"{tag}: {mode} post-burst throughput did not "
                f"recover ({rec}/s tail vs {st}/s offered steady)"
            )
    return fails


def check_reshard_ab(row) -> list:
    """Gate the live-resharding on/off A/B row: same WorkloadPlan AND
    FaultPlan digests regenerate byte-identically, >= 1 live split and
    >= 1 live merge executed (server-side adoption counters) in the on
    run while the faults played, zero values both acked and shed in
    either mode, and both runs linearizable inside the fused p99 +
    recovery budgets."""
    from workload_soak import AB_SEED, DEFAULT_CLIENTS, DEFAULT_KEYS, \
        DEFAULT_HORIZON
    from summerset_tpu.host.nemesis import FaultPlan
    from summerset_tpu.host.workload import WorkloadPlan

    fails = []
    tag = "reshard_ab"
    if not row.get("ok"):
        fails.append(f"{tag}: failed ({row.get('error')})")
    wplan = WorkloadPlan.generate(
        AB_SEED, "hot_burst", clients=DEFAULT_CLIENTS,
        num_keys=DEFAULT_KEYS, horizon=DEFAULT_HORIZON,
    )
    if row.get("wl_digest") != wplan.digest():
        fails.append(
            f"{tag}: workload digest drift — committed "
            f"{row.get('wl_digest')} vs regenerated {wplan.digest()}"
        )
    fdig = FaultPlan.generate(
        AB_SEED, DEFAULT_REPLICAS, DEFAULT_HORIZON,
        classes=FAULT_CLASSES,
    ).digest()
    if row.get("fault_digest") != fdig:
        fails.append(
            f"{tag}: fault digest drift — committed "
            f"{row.get('fault_digest')} vs regenerated {fdig}"
        )
    if row.get("num_groups") != RESHARD_GROUPS:
        fails.append(f"{tag}: ran over {row.get('num_groups')} groups "
                     f"(need {RESHARD_GROUPS})")
    on = row.get("on") or {}
    if on.get("splits", 0) < 1:
        fails.append(f"{tag}: no live split executed "
                     f"(adopted {on.get('splits')})")
    if on.get("merges", 0) < 1:
        fails.append(f"{tag}: no live merge executed "
                     f"(adopted {on.get('merges')})")
    off = row.get("off") or {}
    if off.get("splits", 0) or off.get("merges", 0):
        fails.append(f"{tag}: off run executed range changes")
    for mode in ("off", "on"):
        sub = row.get(mode) or {}
        if not sub.get("linearizable"):
            fails.append(f"{tag}: {mode} history not linearizable")
        if sub.get("ack_shed_overlap", 0) != 0:
            fails.append(f"{tag}: {mode} lost an ack to a shed "
                         "across the cutover")
        if (sub.get("p99_s") or 1e9) > P99_BUDGET_S:
            fails.append(f"{tag}: {mode} accepted-op p99 "
                         f"{sub.get('p99_s')}s over budget")
        rec = sub.get("recover_tput")
        st = sub.get("offered_steady")
        if rec is None or st is None or rec < RECOVER_FRAC * st:
            fails.append(
                f"{tag}: {mode} post-burst throughput did not "
                f"recover ({rec}/s tail vs {st}/s offered steady)"
            )
        if not sub.get("recovered"):
            fails.append(f"{tag}: {mode} no bounded recovery write")
    return fails


def check_scan_row(row) -> list:
    """Gate one range-read cell row.  Shared obligations: the row
    passed, its plan digest regenerates byte-identically (for the trace
    cell that means RE-PARSING the committed fixture file — same bytes,
    same normalized rows, same digest AND trace sha), the multi-key
    history was linearizable with zero values both acked and shed,
    scans were actually acked, p99 + bounded recovery held.  Cell-
    specific: the QuorumLeases cells must show scans VISIBLY served
    from the learner read tier (``read_tier_scans`` > 0); the
    scan_reshard cell must have EXECUTED >= 1 live split under scan
    load (server-side adoption counters) over ``RESHARD_GROUPS``
    groups."""
    from summerset_tpu.host.workload import WorkloadPlan
    from workload_soak import DEFAULT_CLIENTS, DEFAULT_HORIZON, \
        DEFAULT_KEYS

    kind = row.get("kind")
    tag = kind
    fails = []
    if not row.get("ok"):
        fails.append(f"{tag}: failed ({row.get('error')})")
    if kind == "scan_reshard":
        wplan = WorkloadPlan.generate(
            SCAN_RESHARD_SEED, "ycsb_e", clients=DEFAULT_CLIENTS,
            num_keys=DEFAULT_KEYS, horizon=DEFAULT_HORIZON,
        )
    else:
        try:
            wplan = build_scan_plan(kind)
        except (OSError, ValueError) as e:
            return fails + [f"{tag}: plan regeneration failed ({e!r})"]
    if row.get("wl_digest") != wplan.digest():
        fails.append(
            f"{tag}: workload digest drift — committed "
            f"{row.get('wl_digest')} vs regenerated {wplan.digest()}; "
            "rerun scripts/workload_soak.py --scan-cells and commit "
            "the diff"
        )
    if kind == "trace":
        # byte-reproducibility is the trace cell's contract: the
        # committed fixture must still normalize to the committed rows
        if row.get("trace_file") != TRACE_FILE:
            fails.append(f"{tag}: unexpected trace file "
                         f"{row.get('trace_file')}")
        if row.get("trace_sha") != wplan.trace_sha():
            fails.append(
                f"{tag}: trace sha drift — committed "
                f"{row.get('trace_sha')} vs re-parsed "
                f"{wplan.trace_sha()}"
            )
        if row.get("trace_rows") != len(wplan.trace):
            fails.append(
                f"{tag}: trace row count drift — committed "
                f"{row.get('trace_rows')} vs re-parsed "
                f"{len(wplan.trace)}"
            )
    if not row.get("linearizable"):
        fails.append(f"{tag}: history not linearizable")
    if row.get("ack_shed_overlap", 0) != 0:
        fails.append(f"{tag}: {row['ack_shed_overlap']} values both "
                     "acked and shed")
    if row.get("scans_acked", 0) <= 0:
        fails.append(f"{tag}: no scan ever acked")
    if (row.get("p99_s") or 1e9) > P99_BUDGET_S:
        fails.append(f"{tag}: accepted-op p99 {row.get('p99_s')}s "
                     f"over the {P99_BUDGET_S}s budget")
    rt = row.get("recovery_ticks")
    if not row.get("recovered") or rt is None \
            or rt > DEFAULT_BUDGET_TICKS:
        fails.append(f"{tag}: recovery unbounded ({rt} ticks)")
    if kind in ("ycsb_e", "trace"):
        if row.get("read_tier_scans", 0) <= 0:
            fails.append(
                f"{tag}: no scan served from the learner read tier "
                "(read_tier_scans == 0)"
            )
    else:
        if row.get("num_groups") != RESHARD_GROUPS:
            fails.append(f"{tag}: ran over {row.get('num_groups')} "
                         f"groups (need {RESHARD_GROUPS})")
        if row.get("splits", 0) < 1:
            fails.append(f"{tag}: no live split executed under scan "
                         f"load (adopted {row.get('splits')})")
        if sum((row.get("scan_served") or {}).values()) <= 0:
            fails.append(f"{tag}: servers served no scans")
    return fails


def check_hostbench_wire(path: str) -> list:
    """The committed wire-codec proof rows in HOSTBENCH.json: the
    10k-client A/B block and the microbench block must both be present
    and hold their inequalities (re-asserted on the committed numbers,
    like every other drift gate here)."""
    from host_bench import check_wire_ab

    fails = []
    try:
        with open(path) as f:
            art = json.load(f)
    except OSError:
        return [f"hostbench: {path} missing"]
    ab = art.get("wire_ab")
    if not ab:
        fails.append("hostbench: wire_ab block missing (run "
                     "scripts/host_bench.py --wire-ab)")
    else:
        fails.extend(check_wire_ab(ab))
        if not ab.get("ok"):
            fails.append("hostbench: wire_ab committed not ok")
    wb = art.get("wire_bench")
    if not wb:
        fails.append("hostbench: wire_bench block missing (run "
                     "scripts/wire_bench.py --commit)")
    else:
        from wire_bench import verdict as wb_verdict

        rows = wb.get("rows") or {}
        ok, wfails = wb_verdict(rows)
        fails.extend(f"hostbench: {w}" for w in wfails)
        if not rows:
            fails.append("hostbench: wire_bench block has no rows")
        elif not wb.get("ok"):
            # a recorded-failing block must fail the gate even when the
            # committed rows themselves re-verify (verdict drift)
            fails.append("hostbench: wire_bench committed not ok")
    return fails


def check_hostbench_pipeline(path: str) -> list:
    """The committed pipelined-tick-loop proof row in HOSTBENCH.json:
    the serial-vs-pipelined A/B block must be present and hold its
    inequalities (same workload digest both modes, pipelined tput
    strictly up, measured overlap > 0 — ``host_bench
    .check_pipeline_ab``), re-asserted on the committed numbers."""
    from host_bench import check_pipeline_ab

    fails = []
    try:
        with open(path) as f:
            art = json.load(f)
    except OSError:
        return [f"hostbench: {path} missing"]
    ab = art.get("pipeline_ab")
    if not ab:
        fails.append("hostbench: pipeline_ab block missing (run "
                     "scripts/host_bench.py --pipeline-ab)")
    else:
        fails.extend(
            f"hostbench: {w}" for w in check_pipeline_ab(ab)
        )
        if not ab.get("ok"):
            fails.append("hostbench: pipeline_ab committed not ok")
    return fails


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--json",
                    default=os.path.join(REPO, "WORKLOADS.json"))
    ap.add_argument("--hostbench",
                    default=os.path.join(REPO, "HOSTBENCH.json"))
    args = ap.parse_args()
    with open(args.json) as f:
        rows = json.load(f)

    failures = []
    failures.extend(check_hostbench_wire(args.hostbench))
    failures.extend(check_hostbench_pipeline(args.hostbench))
    want = {(p, c, s): fs for p, c, s, fs in WL_MATRIX}
    seen = set()
    ab_rows = [r for r in rows if r.get("kind") == "proxy_ab"]
    if not ab_rows:
        failures.append("proxy_ab row missing (run "
                        "scripts/workload_soak.py --proxy-ab)")
    for ab in ab_rows:
        failures.extend(check_proxy_ab(ab))
    rab_rows = [r for r in rows if r.get("kind") == "reshard_ab"]
    if not rab_rows:
        failures.append("reshard_ab row missing (run "
                        "scripts/workload_soak.py --reshard-ab)")
    for rab in rab_rows:
        failures.extend(check_reshard_ab(rab))
    for kind in SCAN_CELL_KINDS:
        srows = [r for r in rows if r.get("kind") == kind]
        if not srows:
            failures.append(f"{kind} row missing (run "
                            "scripts/workload_soak.py --scan-cells)")
        for sr in srows:
            failures.extend(check_scan_row(sr))
    for row in rows:
        if row.get("kind") in ("proxy_ab", "reshard_ab") \
                or row.get("kind") in SCAN_CELL_KINDS:
            continue
        cell = (row.get("protocol"), row.get("wl_class"),
                row.get("seed"))
        seen.add(cell)
        tag = f"{cell[0]} {cell[1]} seed={cell[2]}"
        if not row.get("ok"):
            failures.append(f"{tag}: failed ({row.get('error')})")
        rt = row.get("recovery_ticks")
        if rt is None or rt > DEFAULT_BUDGET_TICKS:
            failures.append(f"{tag}: recovery unbounded ({rt} ticks)")
        if cell not in want:
            failures.append(f"{tag}: cell outside WL_MATRIX")
            continue
        wplan, fplan = build_plans(
            cell[0], cell[1], cell[2], want[cell], DEFAULT_REPLICAS
        )
        if row.get("wl_digest") != wplan.digest():
            failures.append(
                f"{tag}: workload digest drift — committed "
                f"{row.get('wl_digest')} vs regenerated "
                f"{wplan.digest()}; rerun scripts/workload_soak.py "
                "--matrix and commit the diff"
            )
        fdig = fplan.digest() if fplan is not None else None
        if row.get("fault_digest") != fdig:
            failures.append(
                f"{tag}: fault digest drift — committed "
                f"{row.get('fault_digest')} vs regenerated {fdig}"
            )
        if (cell[0], cell[1]) == PROXY_CELL:
            # the proxied overload cell: proxies up + the canonical
            # proxy_crash plan's digest must regenerate byte-identically
            if row.get("proxies", 0) != PROXY_COUNT:
                failures.append(
                    f"{tag}: expected {PROXY_COUNT} proxies on the "
                    f"proxied overload cell (ran {row.get('proxies')})"
                )
            pdig = build_proxy_plan(
                cell[0], cell[1], cell[2], DEFAULT_REPLICAS
            ).digest()
            if row.get("proxy_fault_digest") != pdig:
                failures.append(
                    f"{tag}: proxy_crash digest drift — committed "
                    f"{row.get('proxy_fault_digest')} vs regenerated "
                    f"{pdig}"
                )
        if cell[1] == "hot_burst":
            shed = row.get("shed", 0)
            # post-run scrape + the burst-peak pre-crash scrape: the
            # crashed leader's counter dies with its incarnation
            api_shed = sum((row.get("api_shed") or {}).values()) + sum(
                (row.get("api_shed_pre") or {}).values()
            )
            if shed <= 0 or api_shed <= 0:
                failures.append(
                    f"{tag}: overload row without visible shedding "
                    f"(client {shed}, server {api_shed})"
                )
            if row.get("acked", 0) <= 0 or shed >= row.get("issued", 0):
                failures.append(f"{tag}: shedding unbounded (no "
                                "progress through the burst)")
            if row.get("ack_shed_overlap", 0) != 0:
                failures.append(f"{tag}: an ack was lost to a shed")
            bp = row.get("burst_p99_s")
            if bp is None or bp > P99_BUDGET_S:
                failures.append(
                    f"{tag}: accepted-op p99 {bp}s over the "
                    f"{P99_BUDGET_S}s budget"
                )
            rec = row.get("recover_tput")
            st = row.get("offered_steady")
            if rec is None or st is None or rec < RECOVER_FRAC * st:
                failures.append(
                    f"{tag}: throughput did not recover "
                    f"({rec}/s tail vs {st}/s offered steady)"
                )

    missing = set(want) - seen
    if missing:
        failures.append(f"matrix cells missing: {sorted(missing)}")
    classes_want = {c for _, c, _, _ in WL_MATRIX}
    classes_seen = {c for _, c, _ in seen}
    if classes_want - classes_seen:
        failures.append(
            f"workload classes uncovered: "
            f"{sorted(classes_want - classes_seen)}"
        )
    protos_want = {p for p, c, _, _ in WL_MATRIX if c == "hot_burst"}
    protos_seen = {p for p, c, _ in seen if c == "hot_burst"}
    if protos_want - protos_seen:
        failures.append(
            f"overload rows missing for: "
            f"{sorted(protos_want - protos_seen)}"
        )

    if failures:
        print("WORKLOADS gate FAIL:")
        for f_ in failures:
            print(f"  - {f_}")
        return 1
    n_over = sum(1 for _, c, _ in seen if c == "hot_burst")
    print(
        f"WORKLOADS gate OK: {len(rows)} cells passed, digests "
        f"byte-identical per seed, {sorted(classes_seen)} covered, "
        f"{n_over} overload rows shed visibly and recovered"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
