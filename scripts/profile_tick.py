"""Ablation profiler for the MultiPaxos tick: times run_synthetic variants
to localize the per-tick cost (HBM traffic vs phase compute vs dispatch).

Run on the real chip (leave JAX_PLATFORMS unset):
    python scripts/profile_tick.py [--ticks N] [--deep]

``--deep`` adds the phase-stub ablations (empty step floor, no accept
ingest, ...) used for the historical PERF.md breakdowns.  Since round 9
the committed per-phase numbers come from the graftprof phase registry
instead (``scripts/profile_run.py`` -> PROFILE.json: named-scope
attribution of measured device time, no stub subclasses needed); this
script remains the quick interactive ablation tool, sharing graftprof's
steady-state timing discipline (``host/profiling.measure_steady_tick``).

Note: variants that stub prepare-reply work override
``_gated_prepare_reply`` (not ``_ingest_prepare_reply``) — the production
kernel wraps the latter in a ``lax.cond`` that never fires in steady
state, so overriding the inner method would measure nothing.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from summerset_tpu.core import Engine
from summerset_tpu.core.protocol import StepEffects
from summerset_tpu.host.profiling import measure_steady_tick
from summerset_tpu.protocols import make_protocol
from summerset_tpu.protocols.multipaxos import (
    MultiPaxosKernel,
    ReplicaConfigMultiPaxos,
)


def time_engine(eng, ticks, proposals, telemetry=True, reps=2):
    state, ns = eng.init()
    if not telemetry:
        # the ablation: without the metric-lane leaf the kernel compiles
        # its lane-free variant (presence is a static condition)
        state.pop("telem", None)
    # graftprof's shared timing discipline: AOT-compile the exact
    # (ticks, proposals) variant, absorb the first-call overhead with
    # untimed warm runs, then best-of-N (PERF.md round-2 lessons)
    compiled = eng.lower_synthetic(state, ns, ticks, proposals).compile()
    s_per_tick, _, _, _ = measure_steady_tick(
        compiled, state, ns, ticks, reps
    )
    return s_per_tick


def build(G=4096, R=5, W=64, P=16, kernel_cls=None, **kw):
    cfg = ReplicaConfigMultiPaxos(
        max_proposals_per_tick=P, chunk_size=P * 2, **kw
    )
    if kernel_cls is None:
        kernel = make_protocol("multipaxos", G, R, W, cfg)
    else:
        kernel = kernel_cls(G, R, W, cfg)
    return Engine(kernel)


class UngatedPrepareReply(MultiPaxosKernel):
    """Round-1 behavior: adoption tensors materialized every tick."""

    def _gated_prepare_reply(self, s, c):
        c.candidate = self._candidate_mask(s)
        self._ingest_prepare_reply(s, c)


class NoPrepareReply(MultiPaxosKernel):
    """Prepare-reply dropped entirely (even during campaigns)."""

    def _gated_prepare_reply(self, s, c):
        c.candidate = self._candidate_mask(s)


class EmptyStep(MultiPaxosKernel):
    """Floor: state passthrough + zero outbox (scan + netmodel only)."""

    def step(self, state, inbox, inputs):
        s = dict(state)
        s["commit_bar"] = s["commit_bar"] + inbox["acc_bal"][:, :, 0] * 0
        out = self.zero_outbox()
        fx = StepEffects(
            commit_bar=s["commit_bar"], exec_bar=s["exec_bar"],
            extra={"n_accepted": s["commit_bar"] * 0,
                   "is_leader": s["commit_bar"] > 0,
                   "snap_bar": s["exec_bar"]},
        )
        return s, out, fx


class NoAcceptIngest(MultiPaxosKernel):
    def _ingest_accept(self, s, c):
        G, R = self.G, self.R
        z = jnp.zeros((G, R), jnp.bool_)
        zi = jnp.zeros((G, R), jnp.int32)
        c.nack, c.nack_hint = z, zi
        c.a_ok, c.a_src, c.a_bal = z, zi, zi
        c.a_new_run, c.a_applied = z, z
        c.m_acc = jnp.zeros((G, R, self.W), jnp.bool_)
        c.a_lo, c.a_hi = zi, zi


class NoLeaderPropose(MultiPaxosKernel):
    def _leader_propose(self, s, c):
        G, R = self.G, self.R
        i_am_leader = (s["bal_prepared"] == s["bal_max"]) & (
            s["bal_prepared"] > 0
        )
        c.active_leader = i_am_leader & (s["leader"] == c.rid)
        c.n_new = jnp.zeros((G, R), jnp.int32)
        c.m_new = jnp.zeros((G, R, self.W), jnp.bool_)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ticks", type=int, default=512)
    ap.add_argument("--groups", type=int, default=4096)
    ap.add_argument("--deep", action="store_true")
    args = ap.parse_args()
    G, P = args.groups, 16
    print(f"platform={jax.devices()[0].platform} G={G} P={P}")

    variants = [
        ("gated baseline W=64", dict()),
        ("no telemetry lanes", dict(telemetry=False)),
        ("ungated (round-1) prepare-reply", dict(kernel_cls=UngatedPrepareReply)),
        ("no prepare-reply at all", dict(kernel_cls=NoPrepareReply)),
        ("W=32", dict(W=32)),
    ]
    if args.deep:
        variants += [
            ("empty step (scan+net floor)", dict(kernel_cls=EmptyStep)),
            ("no accept ingest", dict(kernel_cls=NoAcceptIngest)),
            ("no leader propose", dict(kernel_cls=NoLeaderPropose)),
            ("G x2", dict(G=G * 2)),
            ("G /2", dict(G=G // 2)),
        ]
    base = None
    for name, kw in variants:
        g = kw.pop("G", G)
        telem = kw.pop("telemetry", True)
        eng = build(G=g, P=P, **kw)
        per = time_engine(eng, args.ticks, P, telemetry=telem)
        rate = g * P / per
        if base is None:
            base = per
        print(
            f"{name:34s} {per * 1e3:8.3f} ms/tick  "
            f"{rate / 1e6:7.2f}M slots/s  ({per / base * 100:5.1f}%)"
        )


if __name__ == "__main__":
    main()
