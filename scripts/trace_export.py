#!/usr/bin/env python3
"""graftscope exporter: merge per-server flight dumps into one Chrome
trace-event / Perfetto-loadable timeline.

Input: ``{server id: flight dump}`` — the ``flight_dump`` ctrl-plane
scrape (``summerset_tpu.client.endpoint.scrape_flight``) or a JSON file
of the same shape.  Output: one ``{"traceEvents": [...]}`` document,
openable in chrome://tracing or https://ui.perfetto.dev, with one
process per replica and one track per plane:

- **api**         — request spans (async ``b``/``e`` pairs keyed by
                    (client, req_id): api_ingress → api_reply);
- **device scan** — per-tick stage spans (the ``loop_stage_us``
                    stopwatches as child ``X`` spans; the ``step`` stage
                    is the device scan tick, so the device plane and the
                    host plane share one timeline) plus slot spans
                    (propose → commit, async pairs keyed by (g, vid)).
                    With ``--phase-profile PROFILE.json`` (graftprof),
                    every measured step span is further subdivided into
                    named ``phase:*`` child spans — the kernel phase
                    registry's steady-state attribution projected onto
                    the live timeline, clock-aligned with host spans by
                    construction;
- **transport**   — frame instants plus Chrome flow arrows (``s``/``f``)
                    from each tx to its paired rx on the RECEIVING
                    replica's track: tx/rx pair by (src, dst, seq) where
                    seq is the sender's tick number, which already rides
                    every frame — no wire-format change;
- **storage**     — wal_fsync ``X`` spans (duration + group-commit
                    batch) and wal_append instants;
- **ctrl**        — fault_ctl / demote / crash / restart instants, plus
                    the live-resharding cutover pair (range_seal /
                    range_adopt).

Cross-server clock alignment: monotonic bases are unrelated across
processes, so per-server offsets are estimated NTP-style from the paired
frame stamps (min one-way delta in each direction, midpoint) and applied
before merging.  In-process clusters share one clock and the estimate
collapses to ~0.

``validate_chrome`` is the schema gate CI runs on every export: events
sorted by timestamp, non-negative durations, every async ``b`` matched
by exactly one later ``e`` (and every sync ``B`` by an ``E``), every
flow start matched by a finish.

Usage:
    python scripts/trace_export.py --manager 127.0.0.1:52601 --out trace.json
    python scripts/trace_export.py --dumps flight.json --out trace.json
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional, Tuple

# plane -> tid (stable small ints; names attached via metadata events).
# "host loop" renders the PIPELINED tick's host stages: with the
# software pipeline on, the device step genuinely overlaps the host
# stages, so its span (from the drain-time device_step event) stays on
# the "device scan" track while the host stopwatches move to their own
# track — two X spans on one tid cannot overlap without the viewer
# nesting one under the other.
PLANES = ("api", "device scan", "transport", "storage", "ctrl", "proxy",
          "host loop")
TID = {name: i for i, name in enumerate(PLANES)}

_STAGE_ORDER = ("intake", "exchange", "step", "log", "apply")
# pipelined tick stage layout (ServerReplica._tick_pipelined execution
# order; "overlap" IS a wall segment here — the host work that ran
# while the dispatched scan was in flight)
_PIPE_STAGE_ORDER = ("intake", "exchange", "inbox", "dispatch",
                     "overlap", "device_wait", "apply", "log")


def _events(dump: dict) -> list:
    return dump.get("events", [])


def phase_fractions(profile: dict, protocol: str) -> List[Tuple[str, float]]:
    """Per-phase fractions of the device tick for one protocol, from a
    graftprof PROFILE.json doc — declared phase order, normalized.

    Prefers the host-variant cell's MEASURED per-phase device time
    (``phase_wall_us_per_tick``, the live-cluster serving config);
    falls back to the device cell, then to per-phase HLO op counts
    when no profiler capture is available.  Empty when the protocol
    has no cell — callers then skip the merge rather than guess."""
    per = (profile.get("protocols") or {}).get(protocol) or {}
    for variant in ("host", "device"):
        cell = per.get(variant)
        if not cell:
            continue
        order = cell.get("phases") or []
        w = cell.get("phase_wall_us_per_tick") or {}
        w = {k: v for k, v in w.items() if k in order and v > 0}
        if w:
            tot = sum(w.values())
            return [(ph, w[ph] / tot) for ph in order if ph in w]
        ops = (cell.get("analytic") or {}).get("hlo_ops_by_phase") or {}
        ops = {k: v for k, v in ops.items() if k in order and v > 0}
        if ops:
            tot = sum(ops.values())
            return [(ph, ops[ph] / tot) for ph in order if ph in ops]
    return []


def _phase_children(start: int, dur: int, fracs: List[Tuple[str, float]],
                    me: int, tick) -> List[dict]:
    """Child X spans subdividing one measured ``step`` stopwatch span
    by the profile's per-phase fractions.  The parent span is the
    MEASURED device-scan tick; the subdivision is the steady-state
    attribution PROJECTED onto it (args carry the provenance), emitted
    in declared phase order.  Each child runs between consecutive
    ROUNDED boundaries of the cumulative fraction — rounding start and
    duration independently would let adjacent siblings overlap by 1 us
    on short step spans, and the viewer would nest one under the other.
    Sub-microsecond phases round to their boundary and are dropped."""
    out: List[dict] = []
    pos = 0.0
    t0 = start
    for ph, frac in fracs:
        pos += frac * dur
        t1 = min(start + int(round(pos)), start + dur)
        d = t1 - t0
        if d > 0:
            out.append({
                "ph": "X", "name": f"phase:{ph}", "pid": me,
                "tid": TID["device scan"], "ts": t0, "dur": d,
                "args": {"tick": tick, "projected_from": "PROFILE.json"},
            })
        t0 = t1
    return out


# ------------------------------------------------------------- pairing --
def _request_spans(
    events: list,
) -> Dict[Tuple[int, int], List[Tuple[int, int, Optional[str]]]]:
    """Pair api_ingress/api_reply occurrences per (client, req_id).

    The key is NOT unique across a recording session — driver instances
    restart req ids at 0 on one shared endpoint — so joining the first
    ingress to the last reply would stitch DIFFERENT requests into one
    fictitious span.  Ring events are stamp-ordered, so each ingress
    pairs with the first not-yet-consumed reply at or after it.
    Returns ``{key: [(t_in, t_re, reply kind), ...]}`` in stamp order;
    an ingress with no later reply (still in flight at dump time) is
    simply absent."""
    ins: Dict[Tuple[int, int], List[int]] = {}
    res: Dict[Tuple[int, int], List[Tuple[int, Optional[str]]]] = {}
    for ev in events:
        if ev["type"] == "api_ingress":
            ins.setdefault(
                (ev["client"], ev["req_id"]), []
            ).append(ev["t_us"])
        elif ev["type"] == "api_reply":
            res.setdefault((ev["client"], ev["req_id"]), []).append(
                (ev["t_us"], ev.get("kind"))
            )
    spans: Dict[Tuple[int, int], List[Tuple[int, int, Optional[str]]]] = {}
    for key, tins in ins.items():
        rs = res.get(key, [])
        j = 0
        for t_in in tins:
            while j < len(rs) and rs[j][0] < t_in:
                j += 1
            if j >= len(rs):
                break
            spans.setdefault(key, []).append(
                (t_in, rs[j][0], rs[j][1])
            )
            j += 1
    return spans


def paired_frames(dumps: Dict[Any, dict]) -> List[dict]:
    """Match frame_tx/frame_rx across dumps by (src, dst, seq): seq is
    the sender's tick number, unique per (src, dst) frame WITHIN one
    incarnation — an ingress-dropped frame simply leaves its tx
    unmatched (exactly a packet loss).  A crash-restarted sender resets
    its tick counter and REUSES seqs while peers' rings still hold the
    old incarnation's rx events; pairing those would mint bogus
    rx-before-tx pairs and poison the clock-offset minima, so any rx
    stamped before the sender's recorder birth (``t_start_us``, fresh
    per incarnation) is skipped.  The guard assumes a shared monotonic
    domain (same-host clusters — every supported deployment); cross-host
    skew larger than the restart gap would need a boot epoch on the
    wire.  Returns ``[{src, dst, seq, t_tx_us, t_rx_us}]``."""
    tx: Dict[Tuple[int, int, int], int] = {}
    born: Dict[int, int] = {}
    for sid, dump in dumps.items():
        src = int(dump.get("me", sid))
        born[src] = int(dump.get("t_start_us", 0))
        for ev in _events(dump):
            if ev["type"] == "frame_tx":
                # first copy wins (dup faults re-send the same seq)
                tx.setdefault(
                    (src, int(ev["peer"]), int(ev["seq"])), ev["t_us"]
                )
    out = []
    for sid, dump in dumps.items():
        dst = int(dump.get("me", sid))
        for ev in _events(dump):
            if ev["type"] != "frame_rx":
                continue
            key = (int(ev["peer"]), dst, int(ev["seq"]))
            t_tx = tx.get(key)
            if t_tx is not None and ev["t_us"] >= born.get(key[0], 0):
                out.append({
                    "src": key[0], "dst": dst, "seq": key[2],
                    "t_tx_us": t_tx, "t_rx_us": ev["t_us"],
                })
    out.sort(key=lambda p: (p["t_tx_us"], p["src"], p["dst"], p["seq"]))
    return out


def clock_offsets(dumps: Dict[Any, dict],
                  pairs: Optional[List[dict]] = None) -> Dict[int, int]:
    """Per-replica clock offset (us to ADD to that replica's stamps),
    NTP-style from the paired frames: for each directed edge take the
    minimum (rx - tx) delta — the least-delayed frame — and for each
    undirected edge split the asymmetry at the midpoint.  Offsets
    propagate from the lowest replica id over the pairing graph;
    replicas with no paired frames stay at 0."""
    ids = sorted(int(d.get("me", s)) for s, d in dumps.items())
    mins: Dict[Tuple[int, int], int] = {}
    for p in (pairs if pairs is not None else paired_frames(dumps)):
        e = (p["src"], p["dst"])
        d = p["t_rx_us"] - p["t_tx_us"]
        if e not in mins or d < mins[e]:
            mins[e] = d
    # undirected edge -> offset(dst) - offset(src) estimate
    rel: Dict[Tuple[int, int], float] = {}
    for (a, b), d_ab in mins.items():
        if (b, a) in mins and (b, a) not in rel and (a, b) not in rel:
            rel[(a, b)] = (d_ab - mins[(b, a)]) / 2.0
    offsets: Dict[int, int] = {}
    if not ids:
        return offsets
    offsets[ids[0]] = 0
    # BFS the edge estimates out from the anchor
    frontier = [ids[0]]
    while frontier:
        cur = frontier.pop()
        for (a, b), off in rel.items():
            if a == cur and b not in offsets:
                offsets[b] = int(offsets[a] - off)
                frontier.append(b)
            elif b == cur and a not in offsets:
                offsets[a] = int(offsets[b] + off)
                frontier.append(a)
    for i in ids:
        offsets.setdefault(i, 0)
    return offsets


def find_request_chains(dumps: Dict[Any, dict]) -> List[dict]:
    """Connected causal chains api_ingress → propose → commit → apply →
    reply for sampled requests: the propose event is the junction that
    carries both the (client, req_id) request identity and the (g, vid)
    slot identity.  Only chains whose stamps are correctly ordered
    count — this is the acceptance check the tier-2f smoke gates on."""
    chains = []
    for sid, dump in dumps.items():
        me = int(dump.get("me", sid))
        commit: Dict[Tuple[int, int], int] = {}
        applied: Dict[Tuple[int, int], int] = {}
        proposes = []
        for ev in _events(dump):
            k = ev["type"]
            if k == "commit":
                commit.setdefault((ev["g"], ev["vid"]), ev["t_us"])
            elif k == "apply":
                applied.setdefault((ev["g"], ev["vid"]), ev["t_us"])
            elif k == "propose" and ev.get("client") is not None:
                proposes.append(ev)
        spans = _request_spans(_events(dump))
        for ev in proposes:
            rk = (ev["client"], ev["req_id"])
            sk = (ev["g"], ev["vid"])
            t_cm, t_ap = commit.get(sk), applied.get(sk)
            if t_cm is None or t_ap is None:
                continue
            # the ONE occurrence of this (client, req_id) that encloses
            # the slot's propose→apply window and ended in a commit
            # reply — not the first/last occurrence, which may belong to
            # a different request reusing the key
            span = next(
                (s for s in spans.get(rk, ())
                 if s[0] <= ev["t_us"] and s[1] >= t_ap
                 and s[2] == "reply"),
                None,
            )
            if span is None:
                continue
            t_in, t_re = span[0], span[1]
            if not (t_in <= ev["t_us"] <= t_cm <= t_ap <= t_re):
                continue
            chains.append({
                "sid": me, "client": ev["client"],
                "req_id": ev["req_id"], "g": ev["g"], "vid": ev["vid"],
                "t_ingress_us": t_in, "t_propose_us": ev["t_us"],
                "t_commit_us": t_cm, "t_apply_us": t_ap,
                "t_reply_us": t_re,
            })
    chains.sort(key=lambda c: (c["t_ingress_us"], c["sid"], c["req_id"]))
    return chains


# -------------------------------------------------------------- export --
def export_chrome(dumps: Dict[Any, dict], align: bool = True,
                  pairs: Optional[List[dict]] = None,
                  phase_profile: Optional[dict] = None) -> dict:
    """Merge per-server dumps into one Chrome trace-event document.
    ``pairs`` lets callers that already ran :func:`paired_frames` skip
    re-walking every event (the pairing scan is the expensive part).
    ``phase_profile`` (a graftprof PROFILE.json doc) additionally
    subdivides every measured device-scan tick span into named phase
    child spans — the kernel phase registry's steady-state attribution
    projected onto the live timeline, clock-aligned with the host spans
    by construction (they nest inside the measured ``step`` stopwatch)."""
    if pairs is None:
        pairs = paired_frames(dumps)
    offsets = clock_offsets(dumps, pairs=pairs) if align else {}
    # global zero: earliest (offset-adjusted) stamp across all dumps
    bases = [
        ev["t_us"] + offsets.get(int(d.get("me", s)), 0)
        for s, d in dumps.items() for ev in _events(d)
    ]
    t0 = min(bases) if bases else 0

    meta: List[dict] = []
    evs: List[dict] = []
    paired_keys = {(p["src"], p["dst"], p["seq"]) for p in pairs}
    flow_done: set = set()  # dup faults re-receive a seq: one arrow only

    # ---- proxy-hop pairing (serving-plane split, host/ingress.py):
    # a proxy's typed proxy_fwd/proxy_rcv events join the shard's
    # api_ingress/api_reply events where the shard-side client id IS the
    # proxy's forward identity and req_id IS the proxy-minted rid — so
    # the client→proxy→shard→reply chain renders as flow arrows with no
    # wire change, exactly like the transport tx/rx pairing.
    proxy_ids = {
        int(d.get("me", -1)) for d in dumps.values()
        if d.get("tier") == "proxy"
    }
    fwd_src: set = set()
    fwd_dst: set = set()
    rcv_src: set = set()
    rcv_dst: set = set()
    for s_, d_ in dumps.items():
        me_ = int(d_.get("me", -1))
        isp = d_.get("tier") == "proxy"
        for ev in _events(d_):
            k_ = ev.get("type")
            if isp and k_ == "proxy_fwd":
                fwd_src.add((ev.get("fwd_id", me_), ev.get("prid")))
            elif isp and k_ == "proxy_rcv":
                rcv_dst.add((me_, ev.get("prid")))
            elif not isp and k_ == "api_ingress" \
                    and ev.get("client") in proxy_ids:
                fwd_dst.add((ev.get("client"), ev.get("req_id")))
            elif not isp and k_ == "api_reply" \
                    and ev.get("client") in proxy_ids:
                rcv_src.add((ev.get("client"), ev.get("req_id")))
    hop_fwd = fwd_src & fwd_dst
    hop_rcv = rcv_src & rcv_dst

    for sid, dump in sorted(dumps.items(), key=lambda kv: str(kv[0])):
        me = int(dump.get("me", sid))
        is_proxy = dump.get("tier") == "proxy"
        off = offsets.get(me, 0)
        fracs = (
            phase_fractions(phase_profile, dump.get("protocol", ""))
            if phase_profile else []
        )

        def ts(t_us: int) -> int:
            return max(0, t_us + off - t0)

        meta.append({
            "ph": "M", "name": "process_name", "pid": me, "tid": 0,
            "args": {"name": (
                f"proxy {me}" if is_proxy
                else f"replica {me} ({dump.get('protocol', '?')})"
            )},
        })
        for plane, tid in TID.items():
            meta.append({
                "ph": "M", "name": "thread_name", "pid": me, "tid": tid,
                "args": {"name": plane},
            })
        if dump.get("device_lanes"):
            meta.append({
                "ph": "M", "name": "device_lanes", "pid": me, "tid": 0,
                "args": dict(dump["device_lanes"]),
            })

        # join maps for async span pairing within this dump.  Request
        # spans pair by OCCURRENCE (_request_spans): (client, req_id)
        # repeats across driver instances, so a key-level join would
        # fuse different requests into one bogus span.
        span_at: Dict[Tuple[int, int, int], Tuple[int, int]] = {}
        for rk, lst in _request_spans(_events(dump)).items():
            for idx, (t_in, t_re, _kind) in enumerate(lst):
                span_at.setdefault((rk[0], rk[1], t_in), (t_re, idx))
        commit: Dict[Tuple[int, int], int] = {}
        for ev in _events(dump):
            if ev["type"] == "commit":
                commit.setdefault((ev["g"], ev["vid"]), ev["t_us"])

        for ev in _events(dump):
            k = ev["type"]
            t = ts(ev["t_us"])
            if k == "api_ingress":
                # pop: a same-key same-stamp duplicate must not reuse
                # the async id (the validator counts opens per id)
                hit = span_at.pop(
                    (ev["client"], ev["req_id"], ev["t_us"]), None
                )
                if hit is not None:
                    t_re, idx = hit
                    aid = (f"req-{me}-{ev['client']}"
                           f"-{ev['req_id']}-{idx}")
                    name = f"req c{ev['client']}#{ev['req_id']}"
                    evs.append({
                        "ph": "b", "cat": "req", "id": aid, "name": name,
                        "pid": me, "tid": TID["api"], "ts": t,
                    })
                    evs.append({
                        "ph": "e", "cat": "req", "id": aid, "name": name,
                        "pid": me, "tid": TID["api"], "ts": ts(t_re),
                    })
                else:
                    evs.append({
                        "ph": "i", "s": "t", "name": "api_ingress",
                        "pid": me, "tid": TID["api"], "ts": t,
                        "args": {"client": ev["client"],
                                 "req_id": ev["req_id"]},
                    })
                hkey = (ev["client"], ev["req_id"])
                if not is_proxy and hkey in hop_fwd \
                        and ("phop-f", hkey) not in flow_done:
                    # proxy→shard hop lands here: finish the flow the
                    # proxy's proxy_fwd event started
                    flow_done.add(("phop-f", hkey))
                    evs.append({
                        "ph": "f", "bp": "e", "cat": "proxyhop",
                        "id": f"phop-{hkey[0]}-{hkey[1]}",
                        "name": "proxy_hop", "pid": me,
                        "tid": TID["api"], "ts": t,
                    })
            elif k == "api_reply":
                hkey = (ev.get("client"), ev.get("req_id"))
                if not is_proxy and hkey in hop_rcv \
                        and ("prep-s", hkey) not in flow_done:
                    # shard→proxy reply hop starts here (the reply event
                    # itself is consumed by the request-span pairing)
                    flow_done.add(("prep-s", hkey))
                    evs.append({
                        "ph": "s", "cat": "proxyhop",
                        "id": f"prep-{hkey[0]}-{hkey[1]}",
                        "name": "proxy_reply", "pid": me,
                        "tid": TID["api"], "ts": t,
                    })
            elif k == "proxy_fwd":
                evs.append({
                    "ph": "i", "s": "t", "name": "proxy_fwd",
                    "pid": me, "tid": TID["proxy"], "ts": t,
                    "args": {"sid": ev.get("sid"), "prid": ev.get("prid"),
                             "n": ev.get("n")},
                })
                hkey = (ev.get("fwd_id", me), ev.get("prid"))
                if hkey in hop_fwd and ("phop-s", hkey) not in flow_done:
                    flow_done.add(("phop-s", hkey))
                    evs.append({
                        "ph": "s", "cat": "proxyhop",
                        "id": f"phop-{hkey[0]}-{hkey[1]}",
                        "name": "proxy_hop", "pid": me,
                        "tid": TID["proxy"], "ts": t,
                    })
            elif k == "proxy_rcv":
                evs.append({
                    "ph": "i", "s": "t", "name": "proxy_rcv",
                    "pid": me, "tid": TID["proxy"], "ts": t,
                    "args": {"sid": ev.get("sid"), "prid": ev.get("prid"),
                             "kind": ev.get("kind")},
                })
                hkey = (me, ev.get("prid"))
                if hkey in hop_rcv and ("prep-f", hkey) not in flow_done:
                    flow_done.add(("prep-f", hkey))
                    evs.append({
                        "ph": "f", "bp": "e", "cat": "proxyhop",
                        "id": f"prep-{hkey[0]}-{hkey[1]}",
                        "name": "proxy_reply", "pid": me,
                        "tid": TID["proxy"], "ts": t,
                    })
            elif k == "read_serve":
                evs.append({
                    "ph": "i", "s": "t", "name": "read_serve",
                    "pid": me, "tid": TID["api"], "ts": t,
                    "args": {"client": ev.get("client"),
                             "req_id": ev.get("req_id"),
                             "seq": ev.get("seq")},
                })
            elif k == "api_shed":
                # ingress backpressure refused the request before it
                # entered the queue: an instant on the api track (there
                # is no span — nothing was proposed), carrying the hint
                # so overload windows are readable off the timeline
                evs.append({
                    "ph": "i", "s": "t", "name": "api_shed",
                    "pid": me, "tid": TID["api"], "ts": t,
                    "args": {"client": ev.get("client"),
                             "req_id": ev.get("req_id"),
                             "retry_ms": ev.get("retry_ms"),
                             "depth": ev.get("depth")},
                })
            elif k == "propose":
                sk = (ev["g"], ev["vid"])
                t_cm = commit.get(sk)
                name = f"slot g{ev['g']}/v{ev['vid']}"
                if t_cm is not None and t_cm >= ev["t_us"]:
                    aid = f"slot-{me}-{ev['g']}-{ev['vid']}"
                    args = {
                        "g": ev["g"], "vid": ev["vid"],
                        "tick": ev.get("tick"),
                        "client": ev.get("client"),
                        "req_id": ev.get("req_id"),
                    }
                    evs.append({
                        "ph": "b", "cat": "slot", "id": aid,
                        "name": name, "pid": me,
                        "tid": TID["device scan"], "ts": t, "args": args,
                    })
                    evs.append({
                        "ph": "e", "cat": "slot", "id": aid,
                        "name": name, "pid": me,
                        "tid": TID["device scan"], "ts": ts(t_cm),
                    })
                else:
                    evs.append({
                        "ph": "i", "s": "t", "name": name, "pid": me,
                        "tid": TID["device scan"], "ts": t,
                        "args": {"g": ev["g"], "vid": ev["vid"]},
                    })
            elif k == "tick":
                pipelined = bool(ev.get("pipelined"))
                order = _PIPE_STAGE_ORDER if pipelined else _STAGE_ORDER
                tid = TID["host loop" if pipelined else "device scan"]
                durs = [(st, int(ev.get(st, 0))) for st in order]
                start = t - sum(d for _, d in durs)
                for st, d in durs:
                    if d <= 0:
                        continue
                    evs.append({
                        "ph": "X",
                        "name": (
                            "device scan tick"
                            if st == "step" and not pipelined else st
                        ),
                        "pid": me, "tid": tid,
                        "ts": max(0, start), "dur": d,
                        "args": {
                            "tick": ev.get("tick"),
                            **({"overlap_us": ev.get("overlap")}
                               if pipelined else {}),
                        },
                    })
                    if st == "step" and not pipelined and fracs:
                        evs.extend(_phase_children(
                            max(0, start), d, fracs, me, ev.get("tick")
                        ))
                    start += d
            elif k == "device_step":
                # pipelined device span, recorded at drain time: the
                # step's true wall interval (dispatch -> results ready)
                # on the device track — genuinely overlapping the host
                # stages on the "host loop" track, never nested in them
                d = int(ev.get("dur_us", 0))
                evs.append({
                    "ph": "X", "name": "device scan tick",
                    "pid": me, "tid": TID["device scan"],
                    "ts": max(0, t - d), "dur": d,
                    "args": {"tick": ev.get("tick"),
                             "wait_us": ev.get("wait_us")},
                })
                if fracs:
                    evs.extend(_phase_children(
                        max(0, t - d), d, fracs, me, ev.get("tick")
                    ))
            elif k in ("frame_tx", "frame_rx"):
                evs.append({
                    "ph": "i", "s": "t", "name": k, "pid": me,
                    "tid": TID["transport"], "ts": t,
                    "args": {"peer": ev["peer"], "seq": ev["seq"],
                             "nbytes": ev.get("nbytes")},
                })
                fkey = (
                    (me, ev["peer"], ev["seq"]) if k == "frame_tx"
                    else (ev["peer"], me, ev["seq"])
                )
                if fkey in paired_keys and (k, fkey) not in flow_done:
                    flow_done.add((k, fkey))
                    evs.append({
                        "ph": "s" if k == "frame_tx" else "f",
                        "bp": "e", "cat": "frame",
                        "id": f"frame-{fkey[0]}-{fkey[1]}-{fkey[2]}",
                        "name": "frame", "pid": me,
                        "tid": TID["transport"], "ts": t,
                    })
            elif k == "wal_fsync":
                d = int(ev.get("dur_us", 0))
                evs.append({
                    "ph": "X", "name": "fsync (group commit)",
                    "pid": me, "tid": TID["storage"],
                    "ts": max(0, t - d), "dur": d,
                    "args": {"batch": ev.get("batch")},
                })
            elif k == "wal_append":
                evs.append({
                    "ph": "i", "s": "t", "name": "wal_append",
                    "pid": me, "tid": TID["storage"], "ts": t,
                })
            elif k in ("commit", "apply"):
                evs.append({
                    "ph": "i", "s": "t", "name": k, "pid": me,
                    "tid": TID["device scan"], "ts": t,
                    "args": {"g": ev["g"], "vid": ev["vid"]},
                })
            elif k == "transport_handshake_fail":
                evs.append({
                    "ph": "i", "s": "t", "name": k, "pid": me,
                    "tid": TID["transport"], "ts": t,
                    "args": {"error": ev.get("error")},
                })
            elif k in ("fault_ctl", "demote", "crash", "restart",
                       "range_seal", "range_adopt", "range_unseal",
                       "autopilot_act"):
                evs.append({
                    "ph": "i", "s": "p", "name": k, "pid": me,
                    "tid": TID["ctrl"], "ts": t,
                    "args": {
                        f: ev[f] for f in ev
                        if f not in ("n", "t_us", "type")
                    },
                })

    evs.sort(key=lambda e: (e["ts"], e["pid"], e["tid"]))
    return {
        "traceEvents": meta + evs,
        "displayTimeUnit": "ms",
        "otherData": {
            "generator": "scripts/trace_export.py",
            "replicas": sorted(
                int(d.get("me", s)) for s, d in dumps.items()
            ),
            "dropped_events": {
                str(d.get("me", s)): d.get("dropped", 0)
                for s, d in sorted(
                    dumps.items(), key=lambda kv: str(kv[0])
                )
            },
        },
    }


# ------------------------------------------------------------ validate --
def validate_chrome(doc: dict) -> List[str]:
    """Schema gate: returns a list of violations (empty = valid).

    Checks: timestamps sorted and non-negative, durations non-negative,
    sync ``B``/``E`` properly nested per (pid, tid), async ``b``/``e``
    matched per (cat, id, pid) with begin <= end, flow ``s``/``f``
    matched per id."""
    errors: List[str] = []
    evs = [e for e in doc.get("traceEvents", []) if e.get("ph") != "M"]
    last_ts = None
    stacks: Dict[Tuple, list] = {}
    async_open: Dict[Tuple, list] = {}
    flows: Dict[str, List[str]] = {}
    for i, e in enumerate(evs):
        ph = e.get("ph")
        ts = e.get("ts")
        if ts is None or ts < 0:
            errors.append(f"event {i}: bad ts {ts!r}")
            continue
        if last_ts is not None and ts < last_ts:
            errors.append(
                f"event {i}: non-monotone ts {ts} < {last_ts}"
            )
        last_ts = ts
        if e.get("dur", 0) < 0:
            errors.append(f"event {i}: negative dur {e['dur']}")
        if ph == "B":
            stacks.setdefault((e["pid"], e["tid"]), []).append(i)
        elif ph == "E":
            st = stacks.get((e["pid"], e["tid"]))
            if not st:
                errors.append(
                    f"event {i}: E without matching B on "
                    f"(pid={e['pid']}, tid={e['tid']})"
                )
            else:
                st.pop()
        elif ph == "b":
            async_open.setdefault(
                (e.get("cat"), e.get("id"), e["pid"]), []
            ).append(ts)
        elif ph == "e":
            key = (e.get("cat"), e.get("id"), e["pid"])
            st = async_open.get(key)
            if not st:
                errors.append(
                    f"event {i}: async e without b (id={e.get('id')})"
                )
            elif ts < st[-1]:
                errors.append(
                    f"event {i}: async span ends before it begins "
                    f"(id={e.get('id')})"
                )
            else:
                st.pop()
        elif ph in ("s", "f"):
            flows.setdefault(e.get("id"), []).append(ph)
    for key, st in stacks.items():
        if st:
            errors.append(f"unclosed B span(s) on {key}: {len(st)}")
    for key, st in async_open.items():
        if st:
            errors.append(
                f"unmatched async b (id={key[1]}): {len(st)} open"
            )
    for fid, phs in flows.items():
        if phs.count("s") != phs.count("f"):
            errors.append(
                f"flow {fid}: {phs.count('s')} start(s) vs "
                f"{phs.count('f')} finish(es)"
            )
    return errors


def validate_dumps(dumps: Dict[Any, dict]) -> List[str]:
    """Drop-accounting gate over raw flight dumps (empty = valid).

    Schema v2 dumps carry per-type accounting; a v2 dump whose drops
    don't reconcile is a recorder bug (the exact failure mode the
    per-type reserve rings exist to rule out: a silent, skewed ring
    where one chatty event type evicted everything else unreported).
    Checks per dump: ``recorded_by_type`` / ``dropped_by_type``
    present, ``sum(recorded_by_type) == count``,
    ``sum(dropped_by_type) == dropped``, and per type
    ``recorded - retained == dropped`` against the events actually in
    the dump.  v1 dumps (pre-accounting) pass untouched so old
    committed fixtures stay loadable.
    """
    errors: List[str] = []
    for sid, d in sorted(dumps.items(), key=lambda kv: str(kv[0])):
        if int(d.get("v", 1)) < 2:
            continue
        rec = d.get("recorded_by_type")
        drop = d.get("dropped_by_type")
        if rec is None or drop is None:
            errors.append(
                f"server {sid}: v{d['v']} dump missing per-type "
                "drop accounting"
            )
            continue
        if sum(rec.values()) != d.get("count", 0):
            errors.append(
                f"server {sid}: sum(recorded_by_type)="
                f"{sum(rec.values())} != count={d.get('count', 0)}"
            )
        if sum(drop.values()) != d.get("dropped", 0):
            errors.append(
                f"server {sid}: sum(dropped_by_type)="
                f"{sum(drop.values())} != dropped="
                f"{d.get('dropped', 0)} — drops unaccounted"
            )
        retained: Dict[str, int] = {}
        for ev in _events(d):
            t = ev["type"]
            retained[t] = retained.get(t, 0) + 1
        for t in sorted(set(rec) | set(retained) | set(drop)):
            want = rec.get(t, 0) - retained.get(t, 0)
            got = drop.get(t, 0)
            if want != got:
                errors.append(
                    f"server {sid}: type {t!r} recorded "
                    f"{rec.get(t, 0)} retained {retained.get(t, 0)} "
                    f"=> expected {want} dropped, accounting says {got}"
                )
    return errors


# ----------------------------------------------------------------- CLI --
def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    src = ap.add_mutually_exclusive_group(required=True)
    src.add_argument("--manager",
                     help="host:port of a live cluster's manager cli "
                          "endpoint (scrapes flight_dump)")
    src.add_argument("--dumps",
                     help="JSON file holding {server id: flight dump}")
    ap.add_argument("--last-n", type=int, default=None,
                    help="trim each replica's dump to its n newest "
                         "events before export")
    ap.add_argument("--no-align", action="store_true",
                    help="skip the NTP-style cross-server clock "
                         "alignment")
    ap.add_argument("--phase-profile", default=None, metavar="PROFILE",
                    help="graftprof PROFILE.json: subdivide each "
                         "measured device-scan tick span into named "
                         "phase child spans (the kernel phase "
                         "registry's steady-state attribution projected "
                         "onto the live timeline)")
    ap.add_argument("--out", default="trace.json")
    args = ap.parse_args(argv)

    phase_profile = None
    if args.phase_profile:
        with open(args.phase_profile) as f:
            phase_profile = json.load(f)

    if args.manager:
        import os
        sys.path.insert(0, os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        ))
        from summerset_tpu.client.endpoint import scrape_flight

        host, port = args.manager.rsplit(":", 1)
        dumps = scrape_flight((host, int(port)), last_n=args.last_n)
        if not dumps:
            print("no flight dumps scraped (manager unreachable?)")
            return 1
    else:
        with open(args.dumps) as f:
            dumps = json.load(f)
        if args.last_n is not None:
            for d in dumps.values():
                evs = d.get("events", [])
                d["events"] = (
                    evs[-args.last_n:] if args.last_n > 0 else []
                )
                # keep truncation VISIBLE: the dropped count must cover
                # this trim too, not just the ring's own overflow
                d["dropped"] = (
                    d.get("count", len(evs)) - len(d["events"])
                )
                # v2 dumps account drops per type — the trim must keep
                # that ledger balanced or validate_dumps below flags
                # the trimmed doc itself as a recorder bug
                if int(d.get("v", 1)) >= 2 and "recorded_by_type" in d:
                    retained: dict = {}
                    for ev in d["events"]:
                        t = ev["type"]
                        retained[t] = retained.get(t, 0) + 1
                    d["dropped_by_type"] = {
                        t: n - retained.get(t, 0)
                        for t, n in sorted(
                            d["recorded_by_type"].items()
                        )
                        if n - retained.get(t, 0) > 0
                    }

    acct_errors = validate_dumps(dumps)
    pairs = paired_frames(dumps)  # once; export reuses it
    doc = export_chrome(dumps, align=not args.no_align, pairs=pairs,
                        phase_profile=phase_profile)
    errors = validate_chrome(doc)
    chains = find_request_chains(dumps)
    with open(args.out, "w") as f:
        json.dump(doc, f)
    n_ev = len(doc["traceEvents"])
    print(f"wrote {args.out}: {n_ev} events, {len(chains)} connected "
          f"request chain(s), {len(pairs)} paired frame(s)")
    for e in acct_errors[:20]:
        print(f"DROPS {e}")
    for e in errors[:20]:
        print(f"SCHEMA {e}")
    return 1 if (errors or acct_errors) else 0


if __name__ == "__main__":
    sys.exit(main())
