#!/usr/bin/env python3
"""Telemetry plane gate (ci.sh tier 2d) + the committed TELEMETRY.json.

Two checks, both hard failures:

1. **Device-lane overhead ablation**: times the MultiPaxos synthetic
   scan with and without the in-kernel metric lanes (the ``telem`` state
   leaf — presence is a static compile condition, so the off-variant is
   genuinely lane-free).  Fails if the lanes cost more than
   ``--max-overhead-pct`` (default 5%) of a steady tick.  The asserted
   number is NOISE-GATED (scripts/ab_noise.py): the raw best-of delta
   and the measurement's own noise floor both ride the artifact, and a
   delta inside the floor gates as 0.0 instead of a nonsense negative.
2. **Metrics-scrape smoke**: brings up a real 3-replica MultiPaxos
   cluster (manager + TCP + WALs), serves a handful of checked writes
   and reads, scrapes every server through the ``metrics_dump`` ctrl
   plane, and fails if any DECLARED host metric name or device lane is
   missing, if no commits registered, or if the ticks-to-commit
   distribution is empty.
3. **Schema-drift gate**: every scraped base name must appear in the
   frozen ``scripts/metrics_manifest.json`` under the same category
   (counter/gauge/histogram), and the manifest must cover DECLARED.
   Adding, renaming, or retyping a metric therefore requires a
   same-PR manifest edit — silent telemetry schema drift fails CI.

The combined result is written to TELEMETRY.json at the repo root — a
live-cluster artifact carrying device metric lanes, host histograms
(fsync + request latency included), and the sampled ticks-to-commit
distribution, so "the serving story" is machine-verifiable rather than
builder-asserted.

Usage: python scripts/telemetry_smoke.py [--groups 1024] [--ticks 256]
       [--max-overhead-pct 5.0] [--out TELEMETRY.json]
"""

import argparse
import json
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update(
    "jax_compilation_cache_dir", os.path.join(REPO, ".jax_cache")
)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
from summerset_tpu.utils.jaxcompat import set_cpu_devices  # noqa: E402

set_cpu_devices(8)

sys.path.insert(0, os.path.join(REPO, "tests"))
sys.path.insert(0, os.path.join(REPO, "scripts"))


def ablation(groups: int, ticks: int, pairs: int = 6) -> dict:
    """Per-tick cost with vs without the metric lanes.

    Both variants compile up front, then samples run as TIGHTLY
    interleaved with/without pairs and the best of each side is
    compared.  On a small shared CI box this matters: back-to-back
    best-of-N blocks (or re-warming between samples) shift cache state
    between the sides and swing the apparent overhead by ±10%; tightly
    interleaved minima put the true lane cost within ~1%
    (cross-checked against a standalone accumulate micro-benchmark:
    ~75us/tick at G=1024, under 1% of the tick)."""
    import time as _time

    from profile_tick import build

    eng = build(G=groups)
    s_w, n_w = eng.init()
    s_wo, n_wo = eng.init()
    s_wo.pop("telem")
    # compile + steady-state both variants before any timed sample
    for _ in range(2):
        s_w, n_w = eng.run_synthetic(s_w, n_w, ticks, 16)
        jax.block_until_ready(s_w["commit_bar"])
        s_wo, n_wo = eng.run_synthetic(s_wo, n_wo, ticks, 16)
        jax.block_until_ready(s_wo["commit_bar"])
    w, wo = [], []
    for _ in range(pairs):
        t0 = _time.perf_counter()
        s_w, n_w = eng.run_synthetic(s_w, n_w, ticks, 16)
        jax.block_until_ready(s_w["commit_bar"])
        w.append((_time.perf_counter() - t0) / ticks)
        t0 = _time.perf_counter()
        s_wo, n_wo = eng.run_synthetic(s_wo, n_wo, ticks, 16)
        jax.block_until_ready(s_wo["commit_bar"])
        wo.append((_time.perf_counter() - t0) / ticks)
    from ab_noise import gated_overhead

    with_t, without = min(w), min(wo)
    # raw best-of deltas on this box can come out negative (noise
    # exceeding the true lane cost); the gate asserts the noise-gated
    # value, and the raw delta + floor ride the artifact for audit
    ov = gated_overhead(w, wo, mode="time")
    return {
        "groups": groups,
        "ticks": ticks,
        "tick_us_with": round(with_t * 1e6, 2),
        "tick_us_without": round(without * 1e6, 2),
        **ov,
    }


def scrape_smoke() -> dict:
    """Live-cluster scrape: every declared metric must be present."""
    from test_cluster import Cluster

    from summerset_tpu.client.drivers import DriverClosedLoop
    from summerset_tpu.client.endpoint import GenericEndpoint
    from summerset_tpu.core.telemetry import LANES
    from summerset_tpu.host.messages import CtrlRequest
    from summerset_tpu.host.telemetry import DECLARED

    tmp = tempfile.mkdtemp(prefix="telemetry_smoke_")
    cluster = Cluster(
        "MultiPaxos", 3, tmp, config={"trace_sample": 1}
    )
    try:
        ep = GenericEndpoint(cluster.manager_addr)
        ep.connect()
        drv = DriverClosedLoop(ep)
        for i in range(12):
            drv.checked_put(f"telk{i}", f"v{i}")
        for i in range(12):
            drv.checked_get(f"telk{i}", expect=f"v{i}")
        time.sleep(0.5)  # let followers apply + fsync the tail
        # the manager waits <=15s per fan-out reply; re-scrape if a
        # replica stalled behind a JIT recompile and missed the window
        for _ in range(4):
            rep = ep.ctrl.request(CtrlRequest("metrics_dump"), timeout=30)
            if rep.payloads and len(rep.payloads) == 3:
                break
            time.sleep(2.0)
        ep.leave()
        assert rep.payloads and len(rep.payloads) == 3, (
            f"scrape incomplete: {rep}"
        )
        # declared-name gate over the cluster-wide union: traffic-
        # dependent metrics (request latency, ticks_to_commit) only
        # exist where clients were served — the leader — but every
        # declared name must exist SOMEWHERE after real traffic, and
        # every device lane on every server
        union = set()
        by_part: dict = {"counters": set(), "gauges": set(),
                         "histograms": set()}
        missing = []
        for sid, snap in sorted(rep.payloads.items()):
            for part in ("counters", "gauges", "histograms"):
                names = {
                    k.split("{", 1)[0] for k in snap["host"][part]
                }
                by_part[part] |= names
                union |= names
            for lane in LANES:
                if lane not in snap["device"]["lanes"]:
                    missing.append((sid, f"device:{lane}"))
        missing += [n for n in DECLARED if n not in union]
        assert not missing, f"declared metrics missing: {missing}"
        # schema-drift gate: every scraped base name must be in the
        # frozen manifest under the SAME category, and the manifest
        # must cover every DECLARED name — so adding/renaming/retyping
        # a metric forces a same-PR scripts/metrics_manifest.json edit
        # that reviewers (and downstream dashboard owners) see
        manifest_path = os.path.join(
            REPO, "scripts", "metrics_manifest.json"
        )
        with open(manifest_path) as f:
            manifest = json.load(f)
        drift = []
        for part in ("counters", "gauges", "histograms"):
            allowed = set(manifest.get(part, []))
            drift += [
                f"{part}:{n}" for n in sorted(by_part[part] - allowed)
            ]
        m_union = {
            n for part in ("counters", "gauges", "histograms")
            for n in manifest.get(part, [])
        }
        drift += [
            f"declared-not-in-manifest:{n}"
            for n in DECLARED if n not in m_union
        ]
        assert not drift, (
            "metrics schema drift — register the new/renamed names in "
            f"scripts/metrics_manifest.json in the same PR: {drift}"
        )
        total_commits = sum(
            s["device"]["lanes"]["commits"] for s in rep.payloads.values()
        )
        assert total_commits > 0, "no commits in device lanes"
        ttc = [
            s["host"]["histograms"].get("ticks_to_commit", {"count": 0})
            for s in rep.payloads.values()
        ]
        assert any(h["count"] > 0 for h in ttc), (
            "empty ticks_to_commit distribution"
        )
        lat = [
            v
            for s in rep.payloads.values()
            for k, v in s["host"]["histograms"].items()
            if k.startswith("api_request_latency_us")
        ]
        assert any(h["count"] > 0 for h in lat), (
            "no request-latency samples"
        )
        fsync = [
            v
            for s in rep.payloads.values()
            for k, v in s["host"]["histograms"].items()
            if k.startswith("wal_fsync_us")
        ]
        assert any(h["count"] > 0 for h in fsync), "no fsync samples"
        return {
            "protocol": "MultiPaxos",
            "replicas": 3,
            "declared_ok": True,
            "manifest_ok": True,
            "servers": {
                str(sid): snap for sid, snap in sorted(rep.payloads.items())
            },
        }
    finally:
        cluster.stop()


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--groups", type=int, default=1024)
    ap.add_argument("--ticks", type=int, default=256)
    ap.add_argument("--max-overhead-pct", type=float, default=5.0)
    ap.add_argument("--skip-ablation", action="store_true")
    ap.add_argument("--out", default=os.path.join(REPO, "TELEMETRY.json"))
    args = ap.parse_args()

    out = {"platform": jax.devices()[0].platform}
    if not args.skip_ablation:
        ab = ablation(args.groups, args.ticks)
        print(json.dumps(ab), flush=True)
        out["ablation"] = ab
        if ab["overhead_pct"] > args.max_overhead_pct:
            print(
                f"FAIL: device metric lanes cost {ab['overhead_pct']}% "
                f"> {args.max_overhead_pct}% of a steady tick"
            )
            sys.exit(1)
    out["scrape"] = scrape_smoke()
    with open(args.out, "w") as f:
        json.dump(out, f, indent=1)
    print(f"telemetry smoke PASS -> {args.out}", flush=True)
    # daemon replica threads parked in XLA can std::terminate at normal
    # teardown (same rationale as nemesis_soak); results are on disk
    sys.stdout.flush()
    os._exit(0)


if __name__ == "__main__":
    main()
