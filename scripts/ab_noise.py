"""Shared A/B overhead arithmetic with an explicit noise floor.

Every instrumentation-overhead gate in the tree (telemetry lanes,
flight recorder, graftwatch streaming) compares best-of interleaved
with/without samples.  Raw best-of deltas on a shared CI box can come
out NEGATIVE (TRACE.json once committed -1.87% "overhead") — not
because instrumentation speeds anything up, but because the per-sample
noise exceeds the true cost.  Committing a negative overhead reads as
nonsense, and gating on the raw value lets noise mask a real
regression equally well.

``gated_overhead`` makes the noise explicit: the floor is the larger
side's best-to-median relative spread (how much the samples of ONE
variant disagree with themselves).  A raw delta inside the floor is
indistinguishable from noise and gates as 0.0; a delta above it gates
at face value.  The raw number and the floor both ride the artifact,
so "0.0%" is always auditable against what was actually measured.
"""

from typing import Dict, List


def _rel_spread_pct(samples: List[float], lower_is_better: bool) -> float:
    """Best-to-median spread of one side's samples, as a % of best."""
    if len(samples) < 2:
        return 0.0
    s = sorted(samples)
    best = s[0] if lower_is_better else s[-1]
    med = s[len(s) // 2]
    if not best:
        return 0.0
    return abs(med - best) / abs(best) * 100.0


def gated_overhead(on: List[float], off: List[float],
                   mode: str = "time") -> Dict[str, float]:
    """Overhead of the instrumented (``on``) side vs the bare (``off``)
    side, noise-gated.

    ``mode="time"``: samples are durations (lower is better, best-of is
    the min).  ``mode="rate"``: samples are throughputs (higher is
    better, best-of is the max).  Returns ``overhead_raw_pct`` (signed,
    exactly what best-of measured), ``noise_floor_pct`` (the larger
    side's own spread), and ``overhead_pct`` — the number gates assert
    against: 0.0 when the raw delta is within the floor, the raw value
    when it genuinely clears it, never negative.
    """
    if mode == "time":
        best_on, best_off = min(on), min(off)
        raw = (
            (best_on - best_off) / best_off * 100.0 if best_off else 0.0
        )
        floor = max(_rel_spread_pct(on, True), _rel_spread_pct(off, True))
    elif mode == "rate":
        best_on, best_off = max(on), max(off)
        raw = (
            (best_off - best_on) / best_off * 100.0 if best_off else 0.0
        )
        floor = max(_rel_spread_pct(on, False),
                    _rel_spread_pct(off, False))
    else:
        raise ValueError(f"unknown mode {mode!r}")
    gated = 0.0 if raw <= floor else raw
    return {
        "overhead_raw_pct": round(raw, 2),
        "noise_floor_pct": round(floor, 2),
        "overhead_pct": round(max(0.0, gated), 2),
    }
