"""RS-coding size-sweep performance bench.

Parity: reference ``benches/rse_bench.rs:17-26`` — criterion benchmark of
Reed-Solomon encode (compute_parity) and decode (reconstruct_data)
across value sizes 4KB..4MB at scheme (3, 2).  Here the kernel is the
bit-sliced GF(2^8) matmul (ops/rscoding.py), run on whatever platform
JAX selects (TPU under axon; set JAX_PLATFORMS=cpu to force CPU).

Prints one line per (op, size) with time/op and goodput, then a JSON
summary line.
"""

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--data-shards", type=int, default=3)
    ap.add_argument("--parity-shards", type=int, default=2)
    ap.add_argument("--sizes", default="4096,65536,1048576,4194304")
    ap.add_argument("--reps", type=int, default=20)
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()

    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax
    import jax.numpy as jnp

    from summerset_tpu.ops.rscoding import RSCode, pack_bytes

    d, p = args.data_shards, args.parity_shards
    code = RSCode(d, p)
    results = []
    for size in (int(s) for s in args.sizes.split(",")):
        buf = np.random.default_rng(7).integers(
            0, 256, size, dtype=np.uint8
        ).tobytes()
        data = jnp.asarray(pack_bytes(buf, d))

        def encode():
            return code.compute_parity(data)

        parity = jax.block_until_ready(encode())
        t0 = time.perf_counter()
        for _ in range(args.reps):
            jax.block_until_ready(encode())
        enc_us = (time.perf_counter() - t0) / args.reps * 1e6

        # decode: drop data shard 0, reconstruct from d survivors
        present = tuple(range(1, d)) + (d,)
        avail = jnp.concatenate([data[1:], parity[:1]], axis=0)

        def decode():
            return code.reconstruct_data(avail, present)

        jax.block_until_ready(decode())
        t0 = time.perf_counter()
        for _ in range(args.reps):
            jax.block_until_ready(decode())
        dec_us = (time.perf_counter() - t0) / args.reps * 1e6

        enc_gbps = size / (enc_us / 1e6) / 1e9
        dec_gbps = size / (dec_us / 1e6) / 1e9
        print(
            f"size {size:>8}B  encode {enc_us:9.1f}us ({enc_gbps:6.2f} GB/s)"
            f"  decode {dec_us:9.1f}us ({dec_gbps:6.2f} GB/s)",
            flush=True,
        )
        results.append({
            "size": size,
            "encode_us": round(enc_us, 1),
            "decode_us": round(dec_us, 1),
            "encode_gbps": round(enc_gbps, 3),
            "decode_gbps": round(dec_gbps, 3),
        })
    print(json.dumps({
        "scheme": [d, p],
        "platform": jax.devices()[0].platform,
        "sweep": results,
    }))


if __name__ == "__main__":
    main()
