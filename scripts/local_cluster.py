#!/usr/bin/env python3
"""Launch a local cluster: manager + N server replica processes.

Parity: reference ``scripts/local_cluster.py`` (:199-260) — spawns the
manager, waits for it, spawns servers with per-replica ports and config
strings, and waits for each replica's "accepting clients" readiness log
line (the de-facto API, ``workflow_test.py:57-68``).

Usage:
    python scripts/local_cluster.py -p MultiPaxos -n 3 [--base-port 52600]
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time

import utils_net

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def protocol_defaults(protocol: str, n: int) -> str:
    """Per-protocol default config strings (parity: local_cluster.py:35-54,
    e.g. RSPaxos gets fault_tolerance=(n//2)//2)."""
    p = protocol.lower()
    if p in ("rspaxos", "craft", "crossword"):
        return f"fault_tolerance={(n // 2) // 2}"
    return ""


def wait_for_line(log_path: str, needle: str, timeout: float) -> bool:
    """Tail a child's log file for a readiness line.  Children log to
    files, never PIPEs: an undrained pipe wedges the child once its 64KB
    buffer fills (first observed as replicas freezing after resets)."""
    deadline = time.monotonic() + timeout
    pos = 0
    while time.monotonic() < deadline:
        try:
            with open(log_path, "r") as f:
                f.seek(pos)
                chunk = f.read()
                pos = f.tell()
        except OSError:
            chunk = ""
        if chunk:
            sys.stderr.write(chunk)
            if needle in chunk:
                return True
        time.sleep(0.05)
    return False


def make_cluster_env() -> dict:
    """Child-process env for cluster processes.

    Forces JAX_PLATFORMS=cpu (override deliberately with
    SUMMERSET_CLUSTER_PLATFORM): the environment may preset the axon TPU
    tunnel platform, whose sitecustomize hook dials the tunnel at
    interpreter startup and hangs every child whenever the tunnel is
    down.  Only the hook's own PYTHONPATH entries are filtered out —
    other PYTHONPATH deps survive.
    """
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = env.get("SUMMERSET_CLUSTER_PLATFORM", "cpu")
    parts = [REPO]
    for entry in env.get("PYTHONPATH", "").split(os.pathsep):
        if not entry or entry == REPO:
            continue
        if env["JAX_PLATFORMS"] == "cpu" and os.path.exists(
            os.path.join(entry, "sitecustomize.py")
        ):
            continue  # the tunnel-dialing startup hook
        parts.append(entry)
    env["PYTHONPATH"] = os.pathsep.join(parts)
    env.setdefault("PYTHONUNBUFFERED", "1")
    return env


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("-p", "--protocol", default="MultiPaxos")
    ap.add_argument("-n", "--num-replicas", type=int, default=3)
    ap.add_argument("--base-port", type=int, default=52600)
    ap.add_argument("-c", "--config", default="")
    ap.add_argument("--backer-dir", default="/tmp/summerset_tpu/cluster")
    ap.add_argument("--fresh", action="store_true",
                    help="wipe backer dir before launch")
    ap.add_argument("--use-veth", action="store_true",
                    help="per-replica network namespace + veth uplink "
                         "(parity: reference local_cluster.py:249,308); "
                         "needs CAP_NET_ADMIN, probed before use")
    ap.add_argument("--netem", default="",
                    help="with --use-veth: delay_ms[,jitter_ms[,loss_pct]] "
                         "applied per replica veth")
    args = ap.parse_args()

    use_veth = False
    if args.use_veth:
        # validate --netem BEFORE creating any namespaces: a parse crash
        # after setup would leak the bridge + netns into the root ns
        try:
            netem_parts = [float(x) for x in
                           filter(None, args.netem.split(","))]
        except ValueError:
            print(f"invalid --netem {args.netem!r} (want "
                  "delay_ms[,jitter_ms[,loss_pct]])", file=sys.stderr)
            return 1

        if not utils_net.netns_available():
            print("--use-veth requested but `ip netns add` is not "
                  "permitted here (CAP_NET_ADMIN); falling back to "
                  "loopback", file=sys.stderr)
        else:
            err = utils_net.setup_veth_cluster(args.num_replicas)
            if err is not None:
                print(f"--use-veth setup failed ({err}); falling back "
                      "to loopback", file=sys.stderr)
            else:
                use_veth = True
                if netem_parts:
                    delay = netem_parts[0]
                    jitter = netem_parts[1] if len(netem_parts) > 1 else 0.0
                    loss = netem_parts[2] if len(netem_parts) > 2 else 0.0
                    for r in range(args.num_replicas):
                        e = utils_net.shape_veth(
                            r, delay_ms=delay, jitter_ms=jitter,
                            loss_pct=loss,
                        )
                        if e is not None:
                            print(f"netem on replica {r} veth failed: "
                                  f"{e}", file=sys.stderr)

    if args.fresh and os.path.isdir(args.backer_dir):
        import shutil

        shutil.rmtree(args.backer_dir)
    os.makedirs(args.backer_dir, exist_ok=True)

    env = make_cluster_env()

    bp = args.base_port
    procs = []
    logs = {}

    def spawn(name, mod, *argv, netns_idx=None):
        log_path = os.path.join(args.backer_dir, f"{name}.log")
        cmd = [sys.executable, "-m", mod, *argv]
        if netns_idx is not None:
            cmd = utils_net.netns_exec_prefix(netns_idx) + cmd
        proc = subprocess.Popen(
            cmd,
            env=env,
            stderr=open(log_path, "w", buffering=1),
        )
        procs.append(proc)
        logs[name] = log_path
        return log_path

    # under --use-veth the manager stays in the root namespace, reachable
    # from every replica ns at the bridge address; each server binds and
    # advertises its own namespace IP
    man_bind = []
    if use_veth:
        man_bind = ["--bind-ip", "0.0.0.0"]
    man_log = spawn(
        "manager",
        "summerset_tpu.cli.manager",
        "-p", args.protocol,
        "--srv-port", str(bp), "--cli-port", str(bp + 1),
        "-n", str(args.num_replicas),
        *man_bind,
    )
    def teardown():
        for p in procs:
            try:
                p.terminate()
            except OSError:
                pass
        if use_veth:
            utils_net.teardown_veth_cluster(args.num_replicas)

    if not wait_for_line(man_log, "manager up", 15):
        print("manager failed to start", file=sys.stderr)
        teardown()
        return 1

    cfg = args.config or protocol_defaults(args.protocol, args.num_replicas)
    server_logs = []
    for r in range(args.num_replicas):
        if use_veth:
            srv_net = [
                "--bind-ip", utils_net.replica_ip(r),
                "-m", f"{utils_net.bridge_ip()}:{bp}",
            ]
        else:
            srv_net = ["-m", f"127.0.0.1:{bp}"]
        server_logs.append(spawn(
            f"server{r}",
            "summerset_tpu.cli.server",
            "-p", args.protocol,
            "-a", str(bp + 10 + r),
            "-i", str(bp + 30 + r),
            *srv_net,
            "--backer-dir", args.backer_dir,
            *(["-c", cfg] if cfg else []),
            netns_idx=r if use_veth else None,
        ))
    for r, slog in enumerate(server_logs):
        if not wait_for_line(slog, "accepting clients", 90):
            print(f"server {r} failed to start", file=sys.stderr)
            teardown()
            return 1
    print(f"cluster ready: manager @ 127.0.0.1:{bp + 1} "
          f"({args.num_replicas} replicas)")

    def shutdown(code=0, *_):
        teardown()
        raise SystemExit(code)

    signal.signal(signal.SIGINT, lambda *_: shutdown(0))
    signal.signal(signal.SIGTERM, lambda *_: shutdown(0))
    # babysit: a child dying unexpectedly is a FAILURE exit, so wrapper
    # scripts checking the code see the crash
    while True:
        time.sleep(1)
        for p in procs:
            if p.poll() is not None:
                print("a cluster process exited; shutting down",
                      file=sys.stderr)
                shutdown(1)


if __name__ == "__main__":
    raise SystemExit(main())
