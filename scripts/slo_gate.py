#!/usr/bin/env python3
"""SLO burn-rate gate (ci.sh tier 2j) + the committed SLO.json.

Two modes over the same verdict code:

- ``--run``: live 3-replica MultiPaxos nemesis soak in three phases —
  steady (pre), injected leader fail-slow disk (fault), healed
  recovery (post) — with graftwatch streaming the whole time.  Phase
  boundaries are recorded as fleet WINDOW INDICES (widx, tick-derived,
  wallclock-free) and every phase is paced in windows, not seconds, so
  the gate is robust to box speed.  The manager's full fleet series
  rides the artifact and the verdicts are derived from it.  Also
  measures the streaming ON vs OFF serving-rate ablation (noise-gated,
  scripts/ab_noise.py) and runs an observe-mode autopilot with the
  SloPolicy attached to prove the attachment is mutation-free and
  digest-stable.  Writes SLO.json and exits nonzero on any verdict.

- default (check): load the committed SLO.json and RE-DERIVE every
  verdict from the committed frames — ``evaluate_series`` is a pure
  fold, so the same frames must yield the same alert timeline, the
  ablation must be under budget, and the observe-mode policy digest
  must be byte-identically reproducible from the recorded seed.  No
  cluster, deterministic, CI-cheap.

Soak traffic is paced at a fraction of the box's measured serving
capacity: an open-loop client driven above capacity turns every phase
into an overload test (queueing delay dominates, p99 never recovers),
which is a different experiment than "does the burn alert track an
injected gray failure".

Verdicts (all must hold):
  steady_ok        no objective alerts in the pre phase
  alert_fired      the expected objective latched during the fault
  alert_cleared    every objective un-latched within
                   ``recover_windows`` windows after the heal, and the
                   final window is alert-free
  coverage_ok      every replica streamed frames, and >= 80% of PRE
                   windows merged a frame from every replica (a
                   faulted replica's tick counter legitimately lags —
                   partial fault/post windows are visible by design,
                   so full coverage is only demanded of steady state)
  overhead_ok      streaming-ON ablation overhead_pct <= budget (3%)
  autopilot_ok     observe-mode actuations == 0 and the policy config
                   digest reproduces from (seed, population)

Usage:
    python scripts/slo_gate.py --run [--out SLO.json]   # regenerate
    python scripts/slo_gate.py [--path SLO.json]        # CI check
"""

import argparse
import json
import os
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "scripts"))

MAX_OVERHEAD_PCT = 3.0


# ------------------------------------------------------------- verdicts --
def derive_verdicts(doc: dict) -> dict:
    """Pure re-derivation of every gate verdict from the artifact —
    run mode calls this on the doc it just built, check mode on the
    committed file; both must agree because the inputs are identical."""
    from summerset_tpu.host.graftwatch import (
        DEFAULT_OBJECTIVES, evaluate_series, windows,
    )

    objectives = doc.get("objectives") or [
        dict(o) for o in DEFAULT_OBJECTIVES
    ]
    names = [o["name"] for o in objectives]
    res = evaluate_series(doc["fleet"], objectives=objectives)
    hist = res["history"]
    ph = doc["phases"]
    margin = 1  # boundary windows straddle a phase edge; score neither
    pre = [
        r for r in hist
        if ph["warm_end"] + margin <= r["widx"] <= ph["pre_end"] - margin
    ]
    # latching trails the injection by up to fast_windows, so the fault
    # span for "did it fire" extends a little past the heal boundary
    fault = [
        r for r in hist
        if ph["pre_end"] + margin <= r["widx"] <= ph["fault_end"] + 2
    ]
    recover_bound = ph["fault_end"] + int(doc.get("recover_windows", 8))
    settled = [r for r in hist if r["widx"] > recover_bound]

    expected = doc.get("expect_alert", "reply_p99")
    fired = {
        n: any(r[n]["alerting"] for r in fault) for n in names
    }

    ws = windows(doc["fleet"])
    n_rep = int(doc.get("replicas", 3))
    sids_seen = {sid for w in ws for sid in w["sids"]}
    pre_ws = [
        w for w in ws
        if ph["warm_end"] + margin <= w["widx"] <= ph["pre_end"] - margin
    ]
    full_pre = sum(1 for w in pre_ws if len(w["sids"]) >= n_rep)

    verdicts = {
        "n_windows": res["n_windows"],
        "pre_windows": len(pre),
        "fault_windows": len(fault),
        "settled_windows": len(settled),
        "alert_fired_by_objective": fired,
        "steady_ok": bool(pre) and all(
            not r[n]["alerting"] for r in pre for n in names
        ),
        "alert_fired": fired.get(expected, False),
        "alert_cleared": bool(settled) and all(
            not r[n]["alerting"] for r in settled for n in names
        ),
        "coverage_ok": (
            len(sids_seen) >= n_rep
            and bool(pre_ws)
            and full_pre >= 0.8 * len(pre_ws)
        ),
        "final_status": res["status"],
    }

    ab = doc.get("ablation")
    budget = float(doc.get("max_overhead_pct", MAX_OVERHEAD_PCT))
    verdicts["overhead_ok"] = (
        ab is not None and ab["overhead_pct"] <= budget
    )

    ap = doc.get("autopilot") or {}
    from summerset_tpu.host.autopilot import AutopilotPolicy

    redigest = AutopilotPolicy(
        seed=int(ap.get("seed", 0)),
        population=int(doc.get("replicas", 3)),
    ).config_digest()
    verdicts["autopilot_ok"] = (
        ap.get("mode") == "observe"
        and int(ap.get("actuations", -1)) == 0
        and redigest == ap.get("policy_config_digest")
    )
    verdicts["autopilot_digest_rederived"] = redigest
    return verdicts


def failures_of(verdicts: dict) -> list:
    return [
        k for k in ("steady_ok", "alert_fired", "alert_cleared",
                    "coverage_ok", "overhead_ok", "autopilot_ok")
        if not verdicts.get(k)
    ]


# ------------------------------------------------------------- run mode --
def _set_watch(cluster, enabled: bool) -> None:
    # in-process harness: the per-server WatchEmitter is directly
    # reachable; parking it on a side slot flips streaming off without
    # losing the delta cursor (re-enable emits one catch-up frame)
    for rep in list(cluster.replicas.values()):
        if enabled:
            saved = getattr(rep, "_watch_saved", None)
            if rep.watch is None and saved is not None:
                rep.watch = saved
        elif rep.watch is not None:
            rep._watch_saved = rep.watch
            rep.watch = None


def _bench_window(ep, secs: float, seed: int) -> float:
    from summerset_tpu.client.bench import ClientBench

    bench = ClientBench(
        ep, secs=secs, put_ratio=1.0, value_size="64", num_keys=4,
        interval=1e9, seed=seed,
    )
    return float(bench.run()["tput"])


def streaming_ablation(cluster, ep, pairs: int, window: float,
                       max_pct: float, max_pairs: int = 8) -> dict:
    """graftwatch ON vs OFF open-loop serving rate, tightly interleaved
    best-of with adaptive escalation (same discipline as the flight-
    recorder gate in trace_smoke.py) and a noise-gated verdict."""
    from ab_noise import gated_overhead

    on, off = [], []
    i = 0
    while True:
        _set_watch(cluster, True)
        on.append(_bench_window(ep, window, seed=100 + 2 * i))
        _set_watch(cluster, False)
        off.append(_bench_window(ep, window, seed=101 + 2 * i))
        i += 1
        ov = gated_overhead(on, off, mode="rate")
        if i >= pairs and (
            ov["overhead_pct"] <= max_pct or i >= max_pairs
        ):
            break
    _set_watch(cluster, True)
    return {
        "pairs": i,
        "window_s": window,
        "ops_s_on": [round(r, 1) for r in on],
        "ops_s_off": [round(r, 1) for r in off],
        "best_on": round(max(on), 1),
        "best_off": round(max(off), 1),
        **ov,
    }


def _cur_widx(addr) -> int:
    from summerset_tpu.client.endpoint import scrape_fleet

    export = scrape_fleet(addr) or {}
    widx = -1
    for s in export.get("series", []):
        for fr in s.get("frames", []):
            widx = max(widx, int(fr.get("widx", -1)))
    return widx


def _live_clear(addr, objectives) -> bool:
    """True when a full-history replay of the live ring shows every
    objective un-latched (warm-up latencies latch the reply alert; the
    pre phase must not start until that has genuinely cleared)."""
    from summerset_tpu.client.endpoint import scrape_fleet
    from summerset_tpu.host.graftwatch import evaluate_series

    export = scrape_fleet(addr)
    if not export or not export.get("series"):
        return False
    status = evaluate_series(export, objectives=objectives)["status"]
    return bool(status) and all(
        not v["alerting"] for v in status.values()
    )


def _wait_windows(addr, driver, target_widx: int,
                  timeout_s: float) -> int:
    """Block until the fleet's max widx reaches ``target_widx`` (or the
    timeout), stepping the observe-mode autopilot along the way (each
    step proves the slo_policy attachment is read-only — actuation_log
    must stay empty)."""
    deadline = time.monotonic() + timeout_s
    widx = _cur_widx(addr)
    while widx < target_widx and time.monotonic() < deadline:
        time.sleep(0.5)
        try:
            driver.step()
        except Exception:
            pass
        widx = _cur_widx(addr)
    return widx


def _traffic_loop(addr, freq: float, stop: threading.Event,
                  seed: int) -> None:
    """Paced open-loop client across all three phases.  Tolerates
    failover: redirects reconnect via the driver, a dead socket
    rebuilds the endpoint, and pacing debt is capped at one second so
    a stall never turns into a catch-up burst."""
    import random as _random

    from summerset_tpu.client.drivers import DriverOpenLoop
    from summerset_tpu.client.endpoint import GenericEndpoint
    from summerset_tpu.host.statemach import Command

    rng = _random.Random(seed)

    def fresh():
        e = GenericEndpoint(addr)
        e.connect()
        return e, DriverOpenLoop(e, timeout=0.05)

    try:
        ep, drv = fresh()
    except Exception:
        return
    pace = 1.0 / max(1.0, float(freq))
    t_next = time.monotonic()
    while not stop.is_set():
        now = time.monotonic()
        if now >= t_next:
            key = f"sk{rng.randrange(8)}"
            cmd = (
                Command("put", key, "x" * 64)
                if rng.random() < 0.5 else Command("get", key)
            )
            try:
                drv.issue(cmd)
            except Exception:
                try:
                    ep.leave()
                except Exception:
                    pass
                try:
                    ep, drv = fresh()
                except Exception:
                    time.sleep(0.5)
            t_next += pace
            if t_next < now - 1.0:
                t_next = now
        # drain EVERYTHING pending, not one reply per iteration — an
        # under-drained client inflates every measured latency with
        # its own receive backlog and the burn never clears
        while drv.wait_reply(timeout=0.002) is not None:
            pass
    try:
        ep.leave()
    except Exception:
        pass


def run(args) -> int:
    os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update(
        "jax_compilation_cache_dir", os.path.join(REPO, ".jax_cache")
    )
    jax.config.update(
        "jax_persistent_cache_min_compile_time_secs", 0.5
    )
    from summerset_tpu.utils.jaxcompat import set_cpu_devices

    set_cpu_devices(8)
    sys.path.insert(0, os.path.join(REPO, "tests"))

    from test_cluster import Cluster

    from summerset_tpu.client.drivers import DriverClosedLoop
    from summerset_tpu.client.endpoint import (
        GenericEndpoint, scrape_fleet,
    )
    from summerset_tpu.host.autopilot import (
        AutopilotDriver, AutopilotPolicy,
    )
    from summerset_tpu.host.graftwatch import (
        DEFAULT_OBJECTIVES, SloPolicy,
    )
    from summerset_tpu.host.messages import CtrlRequest

    tmp = tempfile.mkdtemp(prefix="slo_gate_")
    # fail-slow stays gray on purpose: health mitigation off so the
    # limping leader KEEPS serving (the burn must come from latency,
    # not from a demotion racing the fault window)
    cluster = Cluster(
        "MultiPaxos", 3, tmp,
        config={
            "watch_ticks": args.watch_ticks,
            "health_mitigation": False,
        },
        tick=args.tick,
    )
    fault_payload = {
        "wal": {"slow": 2.0, "slow_floor": args.fault_stall},
    }
    # the gate's objectives ride the artifact: DEFAULT thresholds are
    # tuned for dashboards, but on a loaded CI box the steady reply
    # tail routinely grazes 250ms — the gate needs a threshold the
    # healthy cluster clears with margin and the injected ~fault_stall
    # fsync limp blows through, or steady_ok measures box noise
    objectives = [dict(o) for o in DEFAULT_OBJECTIVES]
    for o in objectives:
        if o["name"] == "reply_p99":
            o["threshold_us"] = int(args.reply_threshold_ms * 1000)
    doc = {
        "v": 1,
        "protocol": "MultiPaxos",
        "replicas": 3,
        "seed": args.seed,
        "expect_alert": "reply_p99",
        "max_overhead_pct": args.max_overhead_pct,
        "recover_windows": args.recover_windows,
        "objectives": objectives,
    }
    addr = None
    try:
        doc["platform"] = jax.devices()[0].platform
        addr = cluster.manager_addr
        ep = GenericEndpoint(addr)
        ep.connect()
        drv = DriverClosedLoop(ep, timeout=10.0)
        drv.checked_put("warm", "1")  # jit warm-up before any timing
        capacity = _bench_window(ep, 1.0, seed=7)  # warm open-loop too

        if args.skip_ablation:
            doc["ablation"] = {
                "skipped": True, "overhead_pct": 0.0,
                "overhead_raw_pct": 0.0, "noise_floor_pct": 0.0,
            }
        else:
            doc["ablation"] = streaming_ablation(
                cluster, ep, args.pairs, args.window,
                max_pct=args.max_overhead_pct,
            )
            print(json.dumps(doc["ablation"]), flush=True)
            capacity = max(capacity, doc["ablation"]["best_on"])

        # soak pacing: a fixed fraction of measured capacity, so the
        # steady phase sits comfortably inside every latency budget
        # and the fault-phase backlog drains within the recovery bound
        freq = max(10.0, min(args.freq, 0.3 * capacity))
        doc["config"] = {
            "watch_ticks": args.watch_ticks,
            "tick": args.tick,
            "freq": round(freq, 1),
            "capacity_ops_s": round(capacity, 1),
            "fault": fault_payload,
            "pre_windows": args.pre_windows,
            "fault_windows": args.fault_windows,
        }

        # observe-mode autopilot with the burn senses attached: the
        # whole point is that this changes NOTHING (read-only scrapes,
        # zero actuations, same policy digest as without graftwatch)
        policy = AutopilotPolicy(seed=args.seed, population=3)
        ap_drv = AutopilotDriver(
            addr, policy, mode="observe",
            slo_policy=SloPolicy(objectives),
        )

        stop = threading.Event()
        t_traffic = threading.Thread(
            target=_traffic_loop, args=(addr, freq, stop, args.seed),
            daemon=True,
        )
        t_traffic.start()

        # warm gate: the benches above latched the reply alert (their
        # unpaced windows deliberately saturate the box) — the pre
        # phase starts only once a full-history replay is clean again
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            if (_live_clear(addr, doc["objectives"])
                    and _cur_widx(addr) >= 2):
                break
            time.sleep(0.5)
        phases = {"warm_end": _cur_widx(addr)}

        phases["pre_end"] = _wait_windows(
            addr, ap_drv, phases["warm_end"] + args.pre_windows,
            timeout_s=90.0,
        )

        # fault the FOLLOWERS, not the leader: a slow-WAL leader just
        # gets failed over (one hot window, then a healthy replica
        # takes the lease and service recovers — the protocol working
        # as designed defeats the burn latch).  Slow followers sit on
        # the majority-ack path of every commit no matter who leads,
        # so reply latency stays inflated for the whole fault phase,
        # while the healthy leader keeps ticking (frames keep
        # advancing widx) and keeps recording the slow replies.
        info = ep.ctrl.request(CtrlRequest("query_info"))
        leader = info.leader if info.leader is not None else 0
        victims = [sid for sid in range(3) if sid != leader]
        doc["victims"] = victims
        ep.ctrl.request(CtrlRequest(
            "inject_faults", servers=victims, payload=fault_payload,
        ))
        phases["fault_end"] = _wait_windows(
            addr, ap_drv, phases["pre_end"] + args.fault_windows,
            timeout_s=90.0,
        )
        ep.ctrl.request(CtrlRequest(
            "inject_faults", servers=victims,
            payload={"net": None, "wal": None},
        ))
        # post runs past the recovery bound plus slack, so the settled
        # span the verdict checks actually exists in the artifact
        _wait_windows(
            addr, ap_drv,
            phases["fault_end"] + args.recover_windows + 4,
            timeout_s=120.0,
        )
        stop.set()
        t_traffic.join(timeout=5.0)

        export = scrape_fleet(addr)
        assert export and export.get("series"), "empty fleet scrape"
        phases["final"] = _cur_widx(addr)
        doc["phases"] = phases
        # gauges and non-objective histograms don't feed any verdict
        # and dominate frame bytes — strip them from the COMMITTED
        # artifact (fleet_top reads the live ring, not this file)
        keep_hists = {
            o["metric"] for o in doc["objectives"] if "metric" in o
        }
        for s in export["series"]:
            for fr in s["frames"]:
                fr.pop("gauges", None)
                fr["hists"] = {
                    k: v for k, v in (fr.get("hists") or {}).items()
                    if k.split("{", 1)[0] in keep_hists
                }
        doc["fleet"] = export
        doc["autopilot"] = {
            "mode": "observe",
            "seed": args.seed,
            "policy_config_digest": policy.config_digest(),
            "actuations": len(ap_drv.actuation_log),
            "decisions": len(ap_drv.decision_log),
            "slo_alert_sensed": any(
                row[o["name"]]["alerting"]
                for row in ap_drv.slo_policy.history
                for o in objectives
            ),
        }
        ap_drv.close()
        ep.leave()
    finally:
        cluster.stop()

    doc["verdicts"] = derive_verdicts(doc)
    bad = failures_of(doc["verdicts"])
    with open(args.out, "w") as f:
        json.dump(doc, f, indent=1)
    print(json.dumps(doc["verdicts"], indent=1))
    if bad:
        print(f"FAIL: slo gate verdicts failed: {bad}")
    else:
        print(f"slo gate PASS -> {args.out}", flush=True)
    # daemon replica threads parked in XLA can std::terminate at normal
    # teardown (same rationale as nemesis_soak); results are on disk
    sys.stdout.flush()
    os._exit(1 if bad else 0)


# ----------------------------------------------------------- check mode --
def check(path: str) -> int:
    with open(path) as f:
        doc = json.load(f)
    verdicts = derive_verdicts(doc)
    bad = failures_of(verdicts)
    committed = doc.get("verdicts", {})
    drift = {
        k: (committed.get(k), verdicts[k])
        for k in ("steady_ok", "alert_fired", "alert_cleared",
                  "coverage_ok", "overhead_ok", "autopilot_ok",
                  "n_windows")
        if committed.get(k) != verdicts.get(k)
    }
    print(json.dumps(verdicts, indent=1))
    if drift:
        print(f"FAIL: committed verdicts drift from re-derivation: "
              f"{drift}")
        return 1
    if bad:
        print(f"FAIL: slo gate verdicts failed: {bad}")
        return 1
    print(f"slo gate check OK ({path}: {verdicts['n_windows']} "
          f"windows, alert fired and cleared)")
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--run", action="store_true",
                    help="regenerate SLO.json from a live soak "
                         "(default: check the committed artifact)")
    ap.add_argument("--path", default=os.path.join(REPO, "SLO.json"),
                    help="artifact to check (check mode)")
    ap.add_argument("--out", default=os.path.join(REPO, "SLO.json"))
    ap.add_argument("--seed", type=int, default=20)
    ap.add_argument("--pre-windows", type=int, default=8)
    ap.add_argument("--fault-windows", type=int, default=8)
    ap.add_argument("--tick", type=float, default=0.01)
    ap.add_argument("--watch-ticks", type=int, default=40)
    ap.add_argument("--freq", type=float, default=120.0)
    ap.add_argument("--fault-stall", type=float, default=0.75)
    ap.add_argument("--reply-threshold-ms", type=float, default=1000.0)
    ap.add_argument("--recover-windows", type=int, default=8)
    ap.add_argument("--pairs", type=int, default=3)
    ap.add_argument("--window", type=float, default=2.0)
    ap.add_argument("--max-overhead-pct", type=float,
                    default=MAX_OVERHEAD_PCT)
    ap.add_argument("--skip-ablation", action="store_true")
    args = ap.parse_args()
    if args.run:
        return run(args)
    return check(args.path)


if __name__ == "__main__":
    sys.exit(main())
