"""Standalone repro for the reset tester cases with full logging."""
import logging
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
import jax
jax.config.update("jax_platforms", "cpu")
from summerset_tpu.utils.jaxcompat import set_cpu_devices
set_cpu_devices(8)

logging.basicConfig(
    level=logging.INFO,
    format="%(asctime)s %(name)s %(message)s",
    stream=sys.stderr,
)

sys.path.insert(0, os.path.join(REPO, "tests"))
import tempfile
from test_cluster import Cluster
from summerset_tpu.client.tester import ClientTester

tmp = tempfile.mkdtemp(prefix="repro_reset_")
t0 = time.time()
c = Cluster("MultiPaxos", 3, tmp)
print(f"cluster up in {time.time()-t0:.1f}s", flush=True)

t = ClientTester(c.manager_addr, settle=2.5)
names = sys.argv[1:] or [
    "non_leader_reset", "leader_node_reset",
    "two_nodes_reset", "all_nodes_reset",
]
for name in names:
    t0 = time.time()
    results = t.run_tests([name])
    print(f"{name}: {results[name]} ({time.time()-t0:.1f}s)", flush=True)
    if results[name] != "PASS":
        for me, rep in sorted(c.replicas.items()):
            print(f"  replica {me}: {rep.debug_state()}", flush=True)
c.stop()
print("done", flush=True)
