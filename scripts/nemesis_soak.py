#!/usr/bin/env python3
"""Nemesis soak: seeded fault schedules against a live cluster, verified
by linearizability + bounded recovery.

Per (protocol, seed) run:

1. bring up an in-process cluster (manager + N ServerReplica loops over
   localhost TCP — the tier-2 harness from tests/test_cluster.py);
2. generate the seed's ``FaultPlan`` (crash + partition + message + disk
   fault classes) and verify regeneration is byte-identical (the repro
   contract);
3. start closed-loop recorder clients, play the schedule through the
   manager control plane (``NemesisRunner``), then force a final heal;
4. assert bounded recovery — a checked write completes within the tick
   budget after the heal — and full linearizability of the recorded
   history (``utils/linearize.check_history``).

On failure the fault timeline, executed action log, and full operation
history are dumped next to ``--out`` for offline diagnosis; re-running
with the same ``--seed`` replays the identical schedule.

Usage:
    python scripts/nemesis_soak.py --protocol MultiPaxos --seed 1
    python scripts/nemesis_soak.py --matrix          # CI tier 2c shape
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
from summerset_tpu.utils.jaxcompat import set_cpu_devices  # noqa: E402

set_cpu_devices(8)

sys.path.insert(0, os.path.join(REPO, "tests"))

# the acceptance matrix: 3 seeds x the leader-log / term-vote / coded
# protocol families (plus a QuorumLeases row for the conf plane's
# revoke-then-adopt barrier), under crash + partition + disk + clock +
# long-lived (durable reset / ConfChange / compaction) schedules
MATRIX_PROTOCOLS = ("MultiPaxos", "Raft", "RSPaxos")
# the QL row exists because conf_change is a no-op-ish failure reply on
# conf-less protocols; QuorumLeases drives real lease revoke-then-adopt
# barriers through the same schedules
MATRIX_EXTRA = ("QuorumLeases",)
MATRIX_SEEDS = (1, 2, 3)
SOAK_CLASSES = (
    "crash", "partition", "isolate", "one_way", "drop", "pause",
    "wal_torn", "wal_fsync", "clock_skew",
    # long-lived cluster classes: durable device/host crash-restart,
    # membership ConfChange under faults, compaction on the serving path
    "device_reset", "conf_change", "take_snapshot",
    # live resharding: a range split driven mid-schedule through the
    # ctrl plane (seal -> barrier -> adopt under partitions/crashes)
    "range_change",
)
# end-of-soak boundedness: compaction events must keep every survivor's
# WAL from growing without bound, and the device window ring can never
# be outrun by the host applier
WAL_BOUND_BYTES = 8 << 20
# argparse defaults shared with scripts/nemesis_gate.py (the gate
# regenerates plans at exactly these to check digest drift)
DEFAULT_TICKS = 120
DEFAULT_BUDGET_TICKS = 4000

# ---- gray-failure (fail-slow) matrix -----------------------------------
# Each fail-slow class runs against every protocol row TWICE: with the
# health plane's mitigation (leader demotion + read steering) armed, and
# a mitigation-disabled twin that only observes.  The victim is the LIVE
# leader at fire time (the placement that makes fail-slow a group-wide
# outage); both twins share the canonical FaultPlan.failslow digest.
# The headline assertion: the mitigated twin's recovered throughput —
# measured while the victim is STILL limping, after a detection budget —
# must beat the unmitigated twin by FAILSLOW_TPUT_RATIO.
FAILSLOW_CLASSES = ("slow_disk", "slow_peer", "mem_pressure")
FAILSLOW_PROTOCOLS = ("MultiPaxos", "Raft", "QuorumLeases")
FAILSLOW_SEED = 1
FAILSLOW_TICKS = 80
FAILSLOW_TPUT_RATIO = 2.0
# wall-clock phases of one fail-slow cell (seconds)
FAILSLOW_STEADY_S = 2.5    # pre-fault throughput baseline
FAILSLOW_DETECT_S = 10.0   # detection + demotion budget after onset
FAILSLOW_MEASURE_S = 8.0   # fault-active throughput window


def protocol_config(protocol: str) -> dict:
    if protocol in ("RSPaxos", "CRaft", "Crossword"):
        # 3-replica coded family: majority-quorum shards, no extra FT
        return {"fault_tolerance": 0}
    return {}


def fail_bundle_doc(result: dict, plan, runner, ops: list) -> dict:
    """The failure repro bundle document: the verdict row (including the
    ``flight`` per-replica recorder tails collected before teardown) +
    the byte-identical fault timeline + executed action log + the full
    timed operation history."""
    return {
        **result,
        "timeline": plan.timeline(),
        "executed": (
            runner.executed if runner is not None else []
        ),
        "history": [
            {
                "client": o.client, "kind": o.kind,
                "key": o.key, "value": o.value,
                "t_inv": o.t_inv,
                "t_resp": (
                    None if o.t_resp == float("inf") else o.t_resp
                ),
                "acked": o.acked,
            }
            for o in sorted(ops, key=lambda o: o.t_inv)
        ],
    }


def fleet_summary(manager_addr, tag: str = "") -> None:
    """graftwatch sidecar: one line per soak cell showing how many
    fleet windows the ctrl-plane stream captured during the run (and
    which sids contributed — a faulted replica's tick counter lags, so
    its missing windows are visible here, not silent).  Print-only:
    committed soak artifacts are unchanged."""
    try:
        from summerset_tpu.client.endpoint import scrape_fleet
        from summerset_tpu.host.graftwatch import windows

        ex = scrape_fleet(manager_addr)
        rows = windows(ex) if ex else []
        if rows:
            sids = sorted({s for w in rows for s in w["sids"]})
            print(
                f"    graftwatch{tag}: {len(rows)} fleet windows "
                f"(widx {rows[0]['widx']}..{rows[-1]['widx']}, "
                f"sids {sids})", flush=True,
            )
    except Exception:
        pass  # observability sidecar must never fail a soak cell


def run_one(protocol: str, seed: int, args) -> dict:
    from test_cluster import Cluster

    from summerset_tpu.client.drivers import DriverClosedLoop
    from summerset_tpu.client.endpoint import GenericEndpoint
    from summerset_tpu.client.tester import start_recorded_clients
    from summerset_tpu.host.nemesis import FaultPlan, NemesisRunner
    from summerset_tpu.utils.linearize import check_history

    plan = FaultPlan.generate(
        seed, args.replicas, args.ticks, classes=SOAK_CLASSES,
    )
    # the repro contract: same seed -> byte-identical timeline
    again = FaultPlan.generate(
        seed, args.replicas, args.ticks, classes=SOAK_CLASSES,
    )
    assert plan.timeline() == again.timeline(), "non-deterministic plan!"
    print(f"--- {protocol} seed={seed} digest={plan.digest()}")
    print(plan.timeline(), end="")

    from summerset_tpu.host.server import pipeline_default
    from summerset_tpu.utils import wirecodec

    tmp = tempfile.mkdtemp(prefix=f"nemsoak_{protocol.lower()}_{seed}_")
    result = {
        "protocol": protocol, "seed": seed, "digest": plan.digest(),
        "wire_codec": wirecodec.default_on(),
        "pipeline": pipeline_default(),
        "ok": False,
    }
    cluster = None
    stop = threading.Event()
    ops: list = []
    threads = []
    runner = None
    try:
        cluster = Cluster(
            protocol, args.replicas, tmp,
            config=protocol_config(protocol), tick=args.tick,
        )
        # warm the jit path before the schedule clock starts: the first
        # tick compiles for ~seconds and would eat the early events
        wep = GenericEndpoint(cluster.manager_addr)
        wep.connect()
        DriverClosedLoop(wep, timeout=10.0).checked_put("warm", "1")
        wep.leave()

        threads = start_recorded_clients(
            cluster.manager_addr, args.clients,
            [f"nem{i}" for i in range(3)], stop, ops, seed=seed,
        )
        runner = NemesisRunner(
            cluster.manager_addr, plan, tick_len=args.tick_len,
        )
        runner.play()
        runner.heal_all()

        # bounded recovery: after the final heal the cluster must serve
        # a checked write within the tick budget
        t_heal = time.monotonic()
        budget_s = args.budget_ticks * args.tick
        rep = GenericEndpoint(cluster.manager_addr)
        rep.connect()
        drv = DriverClosedLoop(rep, timeout=min(5.0, budget_s))
        recovered = False
        while time.monotonic() - t_heal < budget_s:
            r = drv.put("nem_recovery", f"s{seed}")
            if r.kind == "success":
                recovered = True
                break
            drv._failover(r)
        recovery_s = time.monotonic() - t_heal
        rep.leave()
        result["recovery_ticks"] = int(recovery_s / args.tick)
        if not recovered:
            result["error"] = (
                f"no recovery within {args.budget_ticks} ticks"
                f" ({budget_s:.1f}s)"
            )
            return result

        # keep the healthy tail running until the history is worth
        # checking, then stop the recorders and check linearizability
        deadline = time.monotonic() + 30
        while len(ops) <= args.min_ops and time.monotonic() < deadline:
            time.sleep(0.5)
        stop.set()
        for t in threads:
            t.join(timeout=30)
        # post-heal telemetry scrape: the committed NEMESIS.json rows
        # carry each survivor's server-side breakdown (device lanes +
        # fsync/request-latency histograms), not just the verdict
        from summerset_tpu.client.endpoint import scrape_metrics

        result["server_metrics"] = scrape_metrics(
            cluster.manager_addr, compact=True
        )
        fleet_summary(cluster.manager_addr)
        result["num_ops"] = len(ops)
        if len(ops) <= args.min_ops:
            result["error"] = f"history too small: {len(ops)}"
            return result
        # long-lived boundedness: with take_snapshot in the schedule the
        # WAL must stay bounded, and the live W-slot window span (propose
        # frontier minus host-applied floor) can never exceed the ring
        import numpy as np

        wal_bytes = {}
        spans = {}
        win = 32  # tests/test_cluster.Cluster serves window=32
        for me, r in sorted(cluster.replicas.items()):
            try:
                win = r.window
                wal_bytes[me] = int(r.wal.size)
                # live ring pressure: the highest frontier this replica
                # must keep in its W-slot windows (voted OR proposed —
                # a follower's next_slot idles at 0 while its vote_bar
                # tracks the leader) minus what the host applier has
                # released.  Negative (idle restarted row) clips to 0.
                fr = np.zeros(r.G, np.int64)
                for k in ("vote_bar", "next_slot", "log_end",
                          "prop_bar"):
                    if k in r.state:
                        fr = np.maximum(
                            fr, np.asarray(r.state[k])[:, r.me]
                        )
                spans[me] = max(
                    0, int((fr - np.asarray(r.applied, np.int64)).max())
                )
            except Exception:
                pass  # a replica mid-restart has no stable view
        result["wal_bytes"] = wal_bytes
        result["window_span"] = spans
        if not wal_bytes:
            # the gate must not fail open: post-recovery, at least one
            # replica should always be measurable — an empty read means
            # the attribute access broke or the whole cluster is down
            result["error"] = "boundedness unmeasurable: no replica " \
                              "contributed wal/window readings"
            return result
        over = {m: b for m, b in wal_bytes.items() if b > WAL_BOUND_BYTES}
        wide = {m: s for m, s in spans.items() if s > win}
        if over or wide:
            result["error"] = (
                f"unbounded growth: wal_bytes over {WAL_BOUND_BYTES} = "
                f"{over}, window spans over W = {wide}"
            )
            return result
        ok, diag = check_history(ops)
        result["ok"] = bool(ok)
        if not ok:
            result["error"] = diag
        return result
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
        if not result["ok"] and runner is not None:
            # graftscope: per-replica flight-recorder tails ride every
            # repro bundle — scraped BEFORE the runner's ctrl stub and
            # the cluster go down, or there is nothing left to ask
            result["flight"] = runner.flight_tails(last_n=256)
        if runner is not None:
            runner.close()
        if not result["ok"] and cluster is not None:
            # capture live replica states for wedge diagnosis BEFORE the
            # teardown empties cluster.replicas
            states = {}
            for me, r in sorted(cluster.replicas.items()):
                try:
                    states[me] = repr(r.debug_state())
                except Exception as e:
                    states[me] = f"unavailable: {e!r}"
            result["replica_states"] = states
        if cluster is not None:
            cluster.stop()
        if not result["ok"]:
            # dump the repro bundle: timeline + executed log + history
            dump = os.path.splitext(args.out)[0] + (
                f"_{protocol}_s{seed}_fail.json"
            )
            with open(dump, "w") as f:
                json.dump(fail_bundle_doc(result, plan, runner, ops),
                          f, indent=1)
            print(f"FAIL bundle -> {dump}")
        shutil.rmtree(tmp, ignore_errors=True)


def _failslow_spec(ev) -> dict:
    """The ``inject_faults`` payload for one fail-slow event (the same
    lowering ``FaultPlan.host_actions`` uses, keyed for a single
    retargeted victim)."""
    from summerset_tpu.host.nemesis import SLOW_PEER_BW

    # soak cells pin the per-op cost floors so the limp dominates the
    # box's natural tick (the >= 2x ratio's denominator) even on
    # tmpfs-backed test dirs, while staying under election timeouts on
    # fast boxes — gray, not dead.  The generated matrix keeps the
    # storage defaults (those cells assert survival, not a ratio).
    if ev.kind == "slow_disk":
        return {"wal": {"slow": ev.arg, "slow_floor": 0.002}}
    if ev.kind == "mem_pressure":
        return {"wal": {"mem": int(ev.arg), "mem_stall": 0.15}}
    # the raised stall_cap binds only when per-tick WORK is large (slow
    # boxes), where election timeouts are proportionally long in wall
    # time too — on fast boxes the starve share stays far below it
    return {"net": {"bw": SLOW_PEER_BW, "starve": ev.arg,
                    "stall_cap": 0.25}}


def _acked_in_window(ops, t0: float, t1: float) -> int:
    """Acked ops whose response landed inside [t0, t1] — the recorded
    clients' throughput meter (list append is atomic; a snapshot copy
    is safe against the live recorders)."""
    n = 0
    for o in list(ops):
        if o.acked and o.t_resp != float("inf") and t0 <= o.t_resp <= t1:
            n += 1
    return n


def run_failslow(protocol: str, cls: str, mitigated: bool, args) -> dict:
    """One gray-failure cell: inject ``cls`` at the live leader, give
    the health plane a detection budget, then measure throughput WHILE
    the victim limps.  Asserts linearizability + bounded recovery; the
    mitigated/unmitigated ratio is asserted by the caller across the
    twin pair."""
    from test_cluster import Cluster

    from summerset_tpu.client.drivers import DriverClosedLoop
    from summerset_tpu.client.endpoint import GenericEndpoint
    from summerset_tpu.client.tester import start_recorded_clients
    from summerset_tpu.host.messages import CtrlRequest
    from summerset_tpu.host.nemesis import FaultPlan
    from summerset_tpu.utils.linearize import check_history

    # pinned to the gate's canonical contract (FAILSLOW_SEED, 3
    # replicas, FAILSLOW_TICKS): nemesis_gate.py recomputes digests at
    # exactly these, so honoring --seed/--replicas here would write
    # rows the gate permanently rejects as drift
    seed = FAILSLOW_SEED
    replicas = 3
    plan = FaultPlan.failslow(cls, seed, replicas, FAILSLOW_TICKS)
    again = FaultPlan.failslow(cls, seed, replicas, FAILSLOW_TICKS)
    assert plan.timeline() == again.timeline(), "non-deterministic plan!"
    ev = plan.events[0]
    tag = f"{protocol}/{cls}/{'mitigated' if mitigated else 'unmitigated'}"
    print(f"--- failslow {tag} seed={seed} digest={plan.digest()}")
    print(plan.timeline(), end="")

    tmp = tempfile.mkdtemp(prefix=f"failslow_{cls}_{int(mitigated)}_")
    result = {
        "failslow": True, "protocol": protocol, "seed": seed,
        "class": cls, "mitigated": mitigated, "digest": plan.digest(),
        "ok": False,
    }
    cluster = None
    stop = threading.Event()
    ops: list = []
    threads = []
    ep = None
    try:
        cfg = dict(protocol_config(protocol))
        cfg["health_mitigation"] = mitigated
        cluster = Cluster(
            protocol, replicas, tmp, config=cfg, tick=args.tick,
        )
        ep = GenericEndpoint(cluster.manager_addr)
        ep.connect()
        drv = DriverClosedLoop(ep, timeout=10.0)
        drv.checked_put("warm", "1")
        if protocol == "QuorumLeases":
            # grant read leases to everyone first, so the mitigated
            # twin's demotion actually exercises the revoke-then-adopt
            # barrier (an empty-responders ConfChange) before abdicating
            drv.conf_change(
                {"responders": list(range(replicas))}, retries=4
            )
        threads = start_recorded_clients(
            cluster.manager_addr, args.clients,
            [f"fs{i}" for i in range(3)], stop, ops, seed=seed,
        )
        t0 = time.monotonic()
        time.sleep(FAILSLOW_STEADY_S)
        t1 = time.monotonic()
        tput_steady = _acked_in_window(ops, t0, t1) / (t1 - t0)

        info = ep.ctrl.request(CtrlRequest("query_info"))
        victim = info.leader if info.leader is not None else 0
        result["victim"] = victim
        ep.ctrl.request(CtrlRequest(
            "inject_faults", servers=[victim], payload=_failslow_spec(ev),
        ))
        # detection budget: the mitigated twin should demote AND hand
        # leadership to a healthy successor within it (the measure
        # window reads RECOVERED throughput, so it must not start while
        # clients are still failing over); the unmitigated twin just
        # waits the budget out, limping the whole time
        t_deadline = time.monotonic() + FAILSLOW_DETECT_S
        while time.monotonic() < t_deadline:
            time.sleep(0.5)
            vic = cluster.replicas.get(victim)
            if mitigated and vic is not None and vic.metrics.counter_value(
                "leader_demotions"
            ) > 0:
                cur = ep.ctrl.request(CtrlRequest("query_info")).leader
                if cur is not None and cur != victim:
                    break
        t2 = time.monotonic()
        time.sleep(FAILSLOW_MEASURE_S)
        t3 = time.monotonic()
        tput_fault = _acked_in_window(ops, t2, t3) / (t3 - t2)

        vic = cluster.replicas.get(victim)
        result["demotions"] = (
            0 if vic is None
            else vic.metrics.counter_value("leader_demotions")
        )
        result["health_score_victim"] = (
            None if vic is None
            else vic.metrics.gauge_value("health_score", None)
        )
        post = ep.ctrl.request(CtrlRequest("query_info"))
        result["leader_after"] = post.leader
        result["tput_steady"] = round(tput_steady, 2)
        result["tput_fault"] = round(tput_fault, 2)

        # heal + bounded recovery (same discipline as run_one)
        ep.ctrl.request(CtrlRequest(
            "inject_faults", servers=[victim],
            payload={"net": None, "wal": None},
        ))
        t_heal = time.monotonic()
        budget_s = args.budget_ticks * args.tick
        rdrv = DriverClosedLoop(ep, timeout=min(5.0, budget_s))
        recovered = False
        while time.monotonic() - t_heal < budget_s:
            r = rdrv.put("fs_recovery", f"s{seed}")
            if r.kind == "success":
                recovered = True
                break
            rdrv._failover(r)
        result["recovery_ticks"] = int(
            (time.monotonic() - t_heal) / args.tick
        )
        if not recovered:
            result["error"] = "no recovery after heal"
            return result

        stop.set()
        for t in threads:
            t.join(timeout=30)
        fleet_summary(cluster.manager_addr, tag="[failslow]")
        result["num_ops"] = len(ops)
        if mitigated:
            if result["demotions"] < 1:
                result["error"] = "mitigation armed but no demotion fired"
                return result
            if result["leader_after"] == victim:
                result["error"] = (
                    "demotion fired but the limping leader still leads"
                )
                return result
        ok, diag = check_history(ops)
        result["ok"] = bool(ok)
        if not ok:
            result["error"] = diag
        return result
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
        if ep is not None:
            try:
                ep.leave()
            except Exception:
                pass
        if cluster is not None:
            cluster.stop()
        if not result["ok"]:
            dump = os.path.splitext(args.out)[0] + (
                f"_failslow_{protocol}_{cls}_"
                f"{'m' if mitigated else 'u'}_fail.json"
            )
            with open(dump, "w") as f:
                json.dump(fail_bundle_doc(result, plan, None, ops),
                          f, indent=1)
            print(f"FAIL bundle -> {dump}")
        shutil.rmtree(tmp, ignore_errors=True)


def run_failslow_pairs(pairs, args) -> list:
    """Run (protocol, class) twin pairs and assert the mitigated twin
    recovers >= FAILSLOW_TPUT_RATIO x the unmitigated throughput."""
    rows = []
    for protocol, cls in pairs:
        mit = run_failslow(protocol, cls, True, args)
        unmit = run_failslow(protocol, cls, False, args)
        ratio = None
        if mit.get("tput_fault") is not None \
                and unmit.get("tput_fault") is not None:
            ratio = round(
                mit["tput_fault"] / max(unmit["tput_fault"], 1e-9), 2
            )
            mit["tput_ratio"] = ratio
            if mit["ok"] and ratio < FAILSLOW_TPUT_RATIO:
                mit["ok"] = False
                mit["error"] = (
                    f"mitigated throughput only {ratio}x the unmitigated "
                    f"twin (need >= {FAILSLOW_TPUT_RATIO}x)"
                )
        for r in (mit, unmit):
            status = "PASS" if r["ok"] else f"FAIL ({r.get('error')})"
            print(f"=== failslow {r['protocol']}/{r['class']}/"
                  f"{'mit' if r['mitigated'] else 'unmit'}: {status} "
                  f"(steady={r.get('tput_steady')} fault="
                  f"{r.get('tput_fault')} ratio={ratio} "
                  f"demotions={r.get('demotions')})")
        rows += [mit, unmit]
    return rows


def run_wire_ab(args) -> dict:
    """The wire-codec A/B cell: ONE soak cell (protocol, seed) run
    twice — codec-on and codec-off — flipped through the process-wide
    wirecodec default so every in-process tier (replicas, clients,
    runner stubs) follows.  The committed row asserts the repro
    contract holds across wire formats: byte-identical FaultPlan
    digests (the schedule is a pure function of the seed — the wire
    format must not leak into it) and both runs linearizable with
    bounded recovery."""
    from summerset_tpu.utils import wirecodec

    sub = {}
    for mode in (True, False):
        prev = wirecodec.set_default(mode)
        try:
            r = run_one(args.protocol, args.seed, args)
        finally:
            wirecodec.set_default(prev)
        r["wire_codec"] = mode
        tag = "codec_on" if mode else "codec_off"
        status = "PASS" if r["ok"] else f"FAIL ({r.get('error')})"
        print(f"=== wire_ab {args.protocol} seed={args.seed} "
              f"{tag}: {status} (ops={r.get('num_ops')}, "
              f"recovery={r.get('recovery_ticks')} ticks)")
        sub[tag] = r
    same = sub["codec_on"]["digest"] == sub["codec_off"]["digest"]
    row = {
        "kind": "wire_ab",
        "protocol": args.protocol,
        "seed": args.seed,
        "digest": sub["codec_on"]["digest"],
        "digests_identical": same,
        "ok": bool(
            same and sub["codec_on"]["ok"] and sub["codec_off"]["ok"]
        ),
        "codec_on": sub["codec_on"],
        "codec_off": sub["codec_off"],
    }
    if not same:
        row["error"] = "plan digests diverged across codec modes"
    return row


def run_pipeline_ab(args) -> dict:
    """The pipelined-loop A/B cell: ONE soak cell (protocol, seed) run
    twice — tick loop pipelined and serial — flipped through the
    process-wide server default so every in-process replica follows.
    The committed row asserts the repro contract holds across loop
    modes: byte-identical FaultPlan digests (the schedule is a pure
    function of the seed — the loop order must not leak into it) and
    both runs linearizable with bounded recovery.  The schedule's
    ``wal_torn``/``wal_fsync`` events land while pipelined steps are in
    flight, so the cell exercises exactly the crash window between a
    step and its durability fence."""
    from summerset_tpu.host import server as host_server

    sub = {}
    for mode in (True, False):
        prev = host_server.set_pipeline_default(mode)
        try:
            r = run_one(args.protocol, args.seed, args)
        finally:
            host_server.set_pipeline_default(prev)
        r["pipeline"] = mode
        tag = "pipeline_on" if mode else "pipeline_off"
        status = "PASS" if r["ok"] else f"FAIL ({r.get('error')})"
        print(f"=== pipeline_ab {args.protocol} seed={args.seed} "
              f"{tag}: {status} (ops={r.get('num_ops')}, "
              f"recovery={r.get('recovery_ticks')} ticks)")
        sub[tag] = r
    same = sub["pipeline_on"]["digest"] == sub["pipeline_off"]["digest"]
    row = {
        "kind": "pipeline_ab",
        "protocol": args.protocol,
        "seed": args.seed,
        "digest": sub["pipeline_on"]["digest"],
        "digests_identical": same,
        "ok": bool(
            same and sub["pipeline_on"]["ok"] and sub["pipeline_off"]["ok"]
        ),
        "pipeline_on": sub["pipeline_on"],
        "pipeline_off": sub["pipeline_off"],
    }
    if not same:
        row["error"] = "plan digests diverged across pipeline modes"
    return row


def _row_half(r: dict) -> str:
    """Which independently-regenerated artifact half a row belongs to."""
    if r.get("kind") in ("wire_ab", "pipeline_ab"):
        return r["kind"]
    return "failslow" if r.get("failslow") else "matrix"


def merge_rows(path: str, new_rows: list, replace: str) -> list:
    """Merge into an existing artifact: ``--failslow*`` runs replace the
    fail-slow rows, ``--matrix`` the 12-cell matrix, ``--wire-ab`` the
    codec A/B row — each half regenerates independently."""
    old: list = []
    if os.path.exists(path):
        try:
            with open(path) as f:
                old = json.load(f)
        except Exception:
            old = []
    kept = [r for r in old if _row_half(r) != replace]
    return kept + new_rows


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--protocol", default="MultiPaxos")
    ap.add_argument("--seed", type=int, default=1)
    ap.add_argument("--matrix", action="store_true",
                    help="run the CI seed matrix "
                         f"({MATRIX_SEEDS} x {MATRIX_PROTOCOLS} "
                         f"+ {MATRIX_EXTRA})")
    ap.add_argument("--replicas", type=int, default=3)
    ap.add_argument("--clients", type=int, default=3)
    ap.add_argument("--ticks", type=int, default=DEFAULT_TICKS,
                    help="schedule horizon in nemesis ticks (the "
                         "default gives every SOAK_CLASS at least one "
                         "event across the matrix seeds — "
                         "scripts/nemesis_gate.py asserts that "
                         "coverage)")
    ap.add_argument("--tick-len", type=float, default=0.25,
                    help="wall seconds per nemesis tick")
    ap.add_argument("--tick", type=float, default=0.005,
                    help="server tick interval")
    ap.add_argument("--budget-ticks", type=int,
                    default=DEFAULT_BUDGET_TICKS,
                    help="recovery budget in server ticks after heal")
    ap.add_argument("--min-ops", type=int, default=20)
    ap.add_argument("--failslow", default=None, metavar="CLASS",
                    help="run ONE gray-failure twin pair (mitigated + "
                         "mitigation-disabled) of this fail-slow class "
                         f"({FAILSLOW_CLASSES}) against --protocol")
    ap.add_argument("--failslow-matrix", action="store_true",
                    help="run the full gray-failure matrix: "
                         f"{FAILSLOW_CLASSES} x {FAILSLOW_PROTOCOLS}, "
                         "each as a mitigated/unmitigated twin pair; "
                         "rows merge into --out beside the fault matrix")
    ap.add_argument("--wire-ab", action="store_true",
                    help="run ONE (protocol, seed) soak cell twice — "
                         "wire codec on and off — and commit the "
                         "equivalence row (byte-identical plan digests, "
                         "both runs linearizable) beside the matrix")
    ap.add_argument("--pipeline-ab", action="store_true",
                    help="run ONE (protocol, seed) soak cell twice — "
                         "tick loop pipelined and serial — and commit "
                         "the equivalence row (byte-identical plan "
                         "digests incl. wal_torn/wal_fsync events "
                         "landing between step and fence, both runs "
                         "linearizable) beside the matrix")
    ap.add_argument("--out", default=os.path.join(REPO, "NEMESIS.json"))
    args = ap.parse_args()

    if args.pipeline_ab:
        row = run_pipeline_ab(args)
        results = [row]
        merged = merge_rows(args.out, results, replace="pipeline_ab")
    elif args.wire_ab:
        row = run_wire_ab(args)
        results = [row]
        merged = merge_rows(args.out, results, replace="wire_ab")
    elif args.failslow or args.failslow_matrix:
        pairs = (
            [(p, c) for c in FAILSLOW_CLASSES for p in FAILSLOW_PROTOCOLS]
            if args.failslow_matrix
            else [(args.protocol, args.failslow)]
        )
        for _p, c in pairs:
            if c not in FAILSLOW_CLASSES:
                ap.error(f"unknown fail-slow class {c!r}")
        results = run_failslow_pairs(pairs, args)
        merged = merge_rows(args.out, results, replace="failslow")
    else:
        runs = (
            [(p, s)
             for p in MATRIX_PROTOCOLS + MATRIX_EXTRA
             for s in MATRIX_SEEDS]
            if args.matrix else [(args.protocol, args.seed)]
        )
        results = []
        for protocol, seed in runs:
            r = run_one(protocol, seed, args)
            status = "PASS" if r["ok"] else f"FAIL ({r.get('error')})"
            print(f"=== {protocol} seed={seed}: {status} "
                  f"(ops={r.get('num_ops')}, "
                  f"recovery={r.get('recovery_ticks')} ticks)")
            results.append(r)
        merged = merge_rows(args.out, results, replace="matrix")
    with open(args.out, "w") as f:
        json.dump(merged, f, indent=1)
    print(f"wrote {args.out}")
    sys.stdout.flush()
    sys.stderr.flush()
    # hard exit: daemon replica threads frozen mid-C++ (XLA) at normal
    # interpreter teardown can std::terminate AFTER results are written,
    # flipping a PASS run to rc=134 — results are on disk, skip teardown
    os._exit(0 if all(r["ok"] for r in results) else 1)


if __name__ == "__main__":
    main()
