#!/usr/bin/env python3
"""Nemesis soak: seeded fault schedules against a live cluster, verified
by linearizability + bounded recovery.

Per (protocol, seed) run:

1. bring up an in-process cluster (manager + N ServerReplica loops over
   localhost TCP — the tier-2 harness from tests/test_cluster.py);
2. generate the seed's ``FaultPlan`` (crash + partition + message + disk
   fault classes) and verify regeneration is byte-identical (the repro
   contract);
3. start closed-loop recorder clients, play the schedule through the
   manager control plane (``NemesisRunner``), then force a final heal;
4. assert bounded recovery — a checked write completes within the tick
   budget after the heal — and full linearizability of the recorded
   history (``utils/linearize.check_history``).

On failure the fault timeline, executed action log, and full operation
history are dumped next to ``--out`` for offline diagnosis; re-running
with the same ``--seed`` replays the identical schedule.

Usage:
    python scripts/nemesis_soak.py --protocol MultiPaxos --seed 1
    python scripts/nemesis_soak.py --matrix          # CI tier 2c shape
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
from summerset_tpu.utils.jaxcompat import set_cpu_devices  # noqa: E402

set_cpu_devices(8)

sys.path.insert(0, os.path.join(REPO, "tests"))

# the acceptance matrix: 3 seeds x the leader-log / term-vote / coded
# protocol families (plus a QuorumLeases row for the conf plane's
# revoke-then-adopt barrier), under crash + partition + disk + clock +
# long-lived (durable reset / ConfChange / compaction) schedules
MATRIX_PROTOCOLS = ("MultiPaxos", "Raft", "RSPaxos")
# the QL row exists because conf_change is a no-op-ish failure reply on
# conf-less protocols; QuorumLeases drives real lease revoke-then-adopt
# barriers through the same schedules
MATRIX_EXTRA = ("QuorumLeases",)
MATRIX_SEEDS = (1, 2, 3)
SOAK_CLASSES = (
    "crash", "partition", "isolate", "one_way", "drop", "pause",
    "wal_torn", "wal_fsync", "clock_skew",
    # long-lived cluster classes: durable device/host crash-restart,
    # membership ConfChange under faults, compaction on the serving path
    "device_reset", "conf_change", "take_snapshot",
)
# end-of-soak boundedness: compaction events must keep every survivor's
# WAL from growing without bound, and the device window ring can never
# be outrun by the host applier
WAL_BOUND_BYTES = 8 << 20
# argparse defaults shared with scripts/nemesis_gate.py (the gate
# regenerates plans at exactly these to check digest drift)
DEFAULT_TICKS = 120
DEFAULT_BUDGET_TICKS = 4000


def protocol_config(protocol: str) -> dict:
    if protocol in ("RSPaxos", "CRaft", "Crossword"):
        # 3-replica coded family: majority-quorum shards, no extra FT
        return {"fault_tolerance": 0}
    return {}


def fail_bundle_doc(result: dict, plan, runner, ops: list) -> dict:
    """The failure repro bundle document: the verdict row (including the
    ``flight`` per-replica recorder tails collected before teardown) +
    the byte-identical fault timeline + executed action log + the full
    timed operation history."""
    return {
        **result,
        "timeline": plan.timeline(),
        "executed": (
            runner.executed if runner is not None else []
        ),
        "history": [
            {
                "client": o.client, "kind": o.kind,
                "key": o.key, "value": o.value,
                "t_inv": o.t_inv,
                "t_resp": (
                    None if o.t_resp == float("inf") else o.t_resp
                ),
                "acked": o.acked,
            }
            for o in sorted(ops, key=lambda o: o.t_inv)
        ],
    }


def run_one(protocol: str, seed: int, args) -> dict:
    from test_cluster import Cluster

    from summerset_tpu.client.drivers import DriverClosedLoop
    from summerset_tpu.client.endpoint import GenericEndpoint
    from summerset_tpu.client.tester import start_recorded_clients
    from summerset_tpu.host.nemesis import FaultPlan, NemesisRunner
    from summerset_tpu.utils.linearize import check_history

    plan = FaultPlan.generate(
        seed, args.replicas, args.ticks, classes=SOAK_CLASSES,
    )
    # the repro contract: same seed -> byte-identical timeline
    again = FaultPlan.generate(
        seed, args.replicas, args.ticks, classes=SOAK_CLASSES,
    )
    assert plan.timeline() == again.timeline(), "non-deterministic plan!"
    print(f"--- {protocol} seed={seed} digest={plan.digest()}")
    print(plan.timeline(), end="")

    tmp = tempfile.mkdtemp(prefix=f"nemsoak_{protocol.lower()}_{seed}_")
    result = {
        "protocol": protocol, "seed": seed, "digest": plan.digest(),
        "ok": False,
    }
    cluster = None
    stop = threading.Event()
    ops: list = []
    threads = []
    runner = None
    try:
        cluster = Cluster(
            protocol, args.replicas, tmp,
            config=protocol_config(protocol), tick=args.tick,
        )
        # warm the jit path before the schedule clock starts: the first
        # tick compiles for ~seconds and would eat the early events
        wep = GenericEndpoint(cluster.manager_addr)
        wep.connect()
        DriverClosedLoop(wep, timeout=10.0).checked_put("warm", "1")
        wep.leave()

        threads = start_recorded_clients(
            cluster.manager_addr, args.clients,
            [f"nem{i}" for i in range(3)], stop, ops, seed=seed,
        )
        runner = NemesisRunner(
            cluster.manager_addr, plan, tick_len=args.tick_len,
        )
        runner.play()
        runner.heal_all()

        # bounded recovery: after the final heal the cluster must serve
        # a checked write within the tick budget
        t_heal = time.monotonic()
        budget_s = args.budget_ticks * args.tick
        rep = GenericEndpoint(cluster.manager_addr)
        rep.connect()
        drv = DriverClosedLoop(rep, timeout=min(5.0, budget_s))
        recovered = False
        while time.monotonic() - t_heal < budget_s:
            r = drv.put("nem_recovery", f"s{seed}")
            if r.kind == "success":
                recovered = True
                break
            drv._failover(r)
        recovery_s = time.monotonic() - t_heal
        rep.leave()
        result["recovery_ticks"] = int(recovery_s / args.tick)
        if not recovered:
            result["error"] = (
                f"no recovery within {args.budget_ticks} ticks"
                f" ({budget_s:.1f}s)"
            )
            return result

        # keep the healthy tail running until the history is worth
        # checking, then stop the recorders and check linearizability
        deadline = time.monotonic() + 30
        while len(ops) <= args.min_ops and time.monotonic() < deadline:
            time.sleep(0.5)
        stop.set()
        for t in threads:
            t.join(timeout=30)
        # post-heal telemetry scrape: the committed NEMESIS.json rows
        # carry each survivor's server-side breakdown (device lanes +
        # fsync/request-latency histograms), not just the verdict
        from summerset_tpu.client.endpoint import scrape_metrics

        result["server_metrics"] = scrape_metrics(
            cluster.manager_addr, compact=True
        )
        result["num_ops"] = len(ops)
        if len(ops) <= args.min_ops:
            result["error"] = f"history too small: {len(ops)}"
            return result
        # long-lived boundedness: with take_snapshot in the schedule the
        # WAL must stay bounded, and the live W-slot window span (propose
        # frontier minus host-applied floor) can never exceed the ring
        import numpy as np

        wal_bytes = {}
        spans = {}
        win = 32  # tests/test_cluster.Cluster serves window=32
        for me, r in sorted(cluster.replicas.items()):
            try:
                win = r.window
                wal_bytes[me] = int(r.wal.size)
                # live ring pressure: the highest frontier this replica
                # must keep in its W-slot windows (voted OR proposed —
                # a follower's next_slot idles at 0 while its vote_bar
                # tracks the leader) minus what the host applier has
                # released.  Negative (idle restarted row) clips to 0.
                fr = np.zeros(r.G, np.int64)
                for k in ("vote_bar", "next_slot", "log_end",
                          "prop_bar"):
                    if k in r.state:
                        fr = np.maximum(
                            fr, np.asarray(r.state[k])[:, r.me]
                        )
                spans[me] = max(
                    0, int((fr - np.asarray(r.applied, np.int64)).max())
                )
            except Exception:
                pass  # a replica mid-restart has no stable view
        result["wal_bytes"] = wal_bytes
        result["window_span"] = spans
        if not wal_bytes:
            # the gate must not fail open: post-recovery, at least one
            # replica should always be measurable — an empty read means
            # the attribute access broke or the whole cluster is down
            result["error"] = "boundedness unmeasurable: no replica " \
                              "contributed wal/window readings"
            return result
        over = {m: b for m, b in wal_bytes.items() if b > WAL_BOUND_BYTES}
        wide = {m: s for m, s in spans.items() if s > win}
        if over or wide:
            result["error"] = (
                f"unbounded growth: wal_bytes over {WAL_BOUND_BYTES} = "
                f"{over}, window spans over W = {wide}"
            )
            return result
        ok, diag = check_history(ops)
        result["ok"] = bool(ok)
        if not ok:
            result["error"] = diag
        return result
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
        if not result["ok"] and runner is not None:
            # graftscope: per-replica flight-recorder tails ride every
            # repro bundle — scraped BEFORE the runner's ctrl stub and
            # the cluster go down, or there is nothing left to ask
            result["flight"] = runner.flight_tails(last_n=256)
        if runner is not None:
            runner.close()
        if not result["ok"] and cluster is not None:
            # capture live replica states for wedge diagnosis BEFORE the
            # teardown empties cluster.replicas
            states = {}
            for me, r in sorted(cluster.replicas.items()):
                try:
                    states[me] = repr(r.debug_state())
                except Exception as e:
                    states[me] = f"unavailable: {e!r}"
            result["replica_states"] = states
        if cluster is not None:
            cluster.stop()
        if not result["ok"]:
            # dump the repro bundle: timeline + executed log + history
            dump = os.path.splitext(args.out)[0] + (
                f"_{protocol}_s{seed}_fail.json"
            )
            with open(dump, "w") as f:
                json.dump(fail_bundle_doc(result, plan, runner, ops),
                          f, indent=1)
            print(f"FAIL bundle -> {dump}")
        shutil.rmtree(tmp, ignore_errors=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--protocol", default="MultiPaxos")
    ap.add_argument("--seed", type=int, default=1)
    ap.add_argument("--matrix", action="store_true",
                    help="run the CI seed matrix "
                         f"({MATRIX_SEEDS} x {MATRIX_PROTOCOLS} "
                         f"+ {MATRIX_EXTRA})")
    ap.add_argument("--replicas", type=int, default=3)
    ap.add_argument("--clients", type=int, default=3)
    ap.add_argument("--ticks", type=int, default=DEFAULT_TICKS,
                    help="schedule horizon in nemesis ticks (the "
                         "default gives every SOAK_CLASS at least one "
                         "event across the matrix seeds — "
                         "scripts/nemesis_gate.py asserts that "
                         "coverage)")
    ap.add_argument("--tick-len", type=float, default=0.25,
                    help="wall seconds per nemesis tick")
    ap.add_argument("--tick", type=float, default=0.005,
                    help="server tick interval")
    ap.add_argument("--budget-ticks", type=int,
                    default=DEFAULT_BUDGET_TICKS,
                    help="recovery budget in server ticks after heal")
    ap.add_argument("--min-ops", type=int, default=20)
    ap.add_argument("--out", default=os.path.join(REPO, "NEMESIS.json"))
    args = ap.parse_args()

    runs = (
        [(p, s)
         for p in MATRIX_PROTOCOLS + MATRIX_EXTRA for s in MATRIX_SEEDS]
        if args.matrix else [(args.protocol, args.seed)]
    )
    results = []
    for protocol, seed in runs:
        r = run_one(protocol, seed, args)
        status = "PASS" if r["ok"] else f"FAIL ({r.get('error')})"
        print(f"=== {protocol} seed={seed}: {status} "
              f"(ops={r.get('num_ops')}, "
              f"recovery={r.get('recovery_ticks')} ticks)")
        results.append(r)
    with open(args.out, "w") as f:
        json.dump(results, f, indent=1)
    print(f"wrote {args.out}")
    sys.stdout.flush()
    sys.stderr.flush()
    # hard exit: daemon replica threads frozen mid-C++ (XLA) at normal
    # interpreter teardown can std::terminate AFTER results are written,
    # flipping a PASS run to rc=134 — results are on disk, skip teardown
    os._exit(0 if all(r["ok"] for r in results) else 1)


if __name__ == "__main__":
    main()
