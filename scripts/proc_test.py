#!/usr/bin/env python3
"""Process-level CI test: real cluster processes + the tester client.

Parity: reference ``.github/workflow_test.py:37-120`` — build, launch a
3-replica local cluster, wait for every replica's "accepting clients"
readiness line, run ``summerset_client -u tester``, tear down; CI runs
it for MultiPaxos AND Raft (``tests_proc.yml:28-33``).

Usage:
    python scripts/proc_test.py [-p MultiPaxos,Raft] [--base-port 53300]
Exit code 0 iff every protocol's tester suite passes.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "scripts"))

from local_cluster import (  # noqa: E402
    make_cluster_env,
    protocol_defaults,
    wait_for_line,
)

TESTS = ",".join([
    "primitive_ops", "client_reconnect", "node_pause_resume",
    "non_leader_reset", "leader_node_reset",
])


def run_one(protocol: str, base_port: int) -> bool:
    backer = tempfile.mkdtemp(prefix=f"proc_test_{protocol.lower()}_")
    env = dict(os.environ)
    # FORCE cpu: the environment may preset JAX_PLATFORMS=axon (TPU
    # tunnel), which wedges server bring-up whenever the tunnel is down;
    # set SUMMERSET_CLUSTER_PLATFORM to override deliberately
    env["JAX_PLATFORMS"] = env.get("SUMMERSET_CLUSTER_PLATFORM", "cpu")
    if env["JAX_PLATFORMS"] == "cpu":
        # replace (not prepend) PYTHONPATH: the axon sitecustomize hook
        # dials the TPU tunnel at interpreter startup, which hangs every
        # child process whenever the tunnel is down
        env["PYTHONPATH"] = REPO
    else:
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("PYTHONUNBUFFERED", "1")
    procs = []

    def spawn(name, mod, *argv):
        log = os.path.join(backer, f"{name}.log")
        procs.append(subprocess.Popen(
            [sys.executable, "-m", mod, *argv],
            env=env, stderr=open(log, "w", buffering=1),
        ))
        return log

    ok = False
    try:
        man_log = spawn(
            "manager", "summerset_tpu.cli.manager",
            "-p", protocol, "--srv-port", str(base_port),
            "--cli-port", str(base_port + 1), "-n", "3",
        )
        if not wait_for_line(man_log, "manager up", 20):
            print(f"[{protocol}] manager failed to start")
            return False
        cfg = protocol_defaults(protocol, 3)
        slogs = [
            spawn(
                f"server{r}", "summerset_tpu.cli.server",
                "-p", protocol,
                "-a", str(base_port + 10 + r),
                "-i", str(base_port + 30 + r),
                "-m", f"127.0.0.1:{base_port}",
                "--backer-dir", backer,
                *(["-c", cfg] if cfg else []),
            )
            for r in range(3)
        ]
        for r, slog in enumerate(slogs):
            if not wait_for_line(slog, "accepting clients", 120):
                print(f"[{protocol}] server {r} failed to start")
                return False
        try:
            out = subprocess.run(
                [sys.executable, "-m", "summerset_tpu.cli.client",
                 "-u", "tester", "-m", f"127.0.0.1:{base_port + 1}",
                 "--tests", TESTS],
                env=env, capture_output=True, text=True, timeout=600,
            )
            line = next(
                (ln for ln in out.stdout.splitlines()
                 if ln.strip().startswith("{")), "{}",
            )
            results = json.loads(line)
        except (subprocess.TimeoutExpired, json.JSONDecodeError) as e:
            print(f"[{protocol}] tester failed: {e}")
            return False
        print(f"[{protocol}] {results}")
        ok = bool(results) and all(
            v == "PASS" for v in results.values()
        )
    finally:
        for p in procs:
            try:
                p.send_signal(signal.SIGTERM)
            except OSError:
                pass
        time.sleep(0.5)
        for p in procs:
            try:
                p.kill()
            except OSError:
                pass
        shutil.rmtree(backer, ignore_errors=True)
    return ok


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("-p", "--protocols", default="MultiPaxos,Raft")
    ap.add_argument("--base-port", type=int, default=53300)
    args = ap.parse_args()
    rc = 0
    for i, proto in enumerate(
        p for p in args.protocols.split(",") if p
    ):
        if not run_one(proto, args.base_port + 100 * i):
            rc = 1
    print("PROC TESTS", "PASS" if rc == 0 else "FAIL")
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
