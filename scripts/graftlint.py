#!/usr/bin/env python3
"""graftlint: kernel-contract verifier + host concurrency lint (CI tier 2e).

Runs the four static passes of ``summerset_tpu/analysis`` over the
whole repo and writes the deterministic ``LINT.json`` baseline:

1. contract  — every registered protocol kernel against the
               machine-readable ``KERNEL_CONTRACT`` rules (C1–C9);
2. ranges    — the inductive value-range prover: per-leaf interval
               invariants + pairwise facts per config variant
               (serialized into the report, drift-gated), plus
               ``RANGE_CLAIMS`` inductiveness (R2);
3. taint     — the flags-taint dataflow pass (T1, stale-suppression
               T9), with gate polarity decided by the range proofs
               (proven-vs-optimistic counts ride in the report);
4. host      — the AST concurrency lint over host/manager/utils
               (H101–H106, inline ``# graftlint: disable=... -- reason``
               suppressions).

Usage:
    python scripts/graftlint.py                # run all, write LINT.json
    python scripts/graftlint.py --check        # CI: fail on findings OR
                                               # drift vs committed LINT.json
    python scripts/graftlint.py --only taint --kernel Raft -v
    python scripts/graftlint.py --only ranges  # just the range proofs

Exit status: 0 = clean (and, with --check, baseline matches); 1 = any
finding, pass error, or baseline drift.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from summerset_tpu import protocols  # noqa: E402
from summerset_tpu.analysis import (  # noqa: E402
    assemble_report,
    dumps_report,
    lint_host,
    verify_kernel,
    verify_kernel_ranges,
    verify_kernel_taint,
)

PKG_ROOT = os.path.join(REPO, "summerset_tpu")


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default=os.path.join(REPO, "LINT.json"))
    ap.add_argument("--check", action="store_true",
                    help="compare against the committed baseline instead "
                         "of rewriting it; fail on findings or drift")
    ap.add_argument("--only", action="append",
                    choices=("contract", "ranges", "taint", "host"),
                    help="run a subset of passes (console only; LINT.json "
                         "is neither written nor checked)")
    ap.add_argument("--kernel", action="append",
                    help="restrict kernel passes to these protocol names")
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args()

    passes = set(args.only or ("contract", "ranges", "taint", "host"))
    partial = bool(args.only) or bool(args.kernel)
    if args.check and partial:
        ap.error("--check needs the full run: it compares the whole "
                 "LINT.json baseline, so it cannot be combined with "
                 "--only/--kernel")
    names = protocols.protocol_names()
    if args.kernel:
        want = {k.lower() for k in args.kernel}
        unknown = want - set(names)
        if unknown:
            ap.error(f"unknown kernels {sorted(unknown)}; have {names}")
        names = [n for n in names if n in want]

    kernels = {}
    n_findings = 0
    gates_proven = gates_optimistic = 0
    for lname in names:
        kres = {}
        if "contract" in passes:
            kres["contract"] = verify_kernel(protocols.make_protocol,
                                             lname)
        if "ranges" in passes:
            kres["ranges"] = verify_kernel_ranges(protocols.make_protocol,
                                                  lname)
        if "taint" in passes:
            kres["taint"] = verify_kernel_taint(protocols.make_protocol,
                                                lname)
        if not kres:
            continue
        # report under the registered display name, not the lowered key
        disp = protocols.protocol_display_name(lname)
        kernels[disp] = kres
        for pname, pres in sorted(kres.items()):
            status = "pass" if pres.ok else "FAIL"
            supp = f" ({len(pres.suppressed)} suppressed)" \
                if pres.suppressed else ""
            note = ""
            if pname == "ranges" and "variants" in pres.extra:
                nv = len(pres.extra["variants"])
                nl = sum(len(v["invariants"])
                         for v in pres.extra["variants"].values())
                np_ = sum(len(v["pairs"])
                          for v in pres.extra["variants"].values())
                note = f" ({nv} variants, {nl} leaves, {np_} pairs)"
            elif pname == "taint" and "gates_proven" in pres.extra:
                gp = pres.extra["gates_proven"]
                go = pres.extra["gates_optimistic"]
                gates_proven += gp
                gates_optimistic += go
                note = f" ({gp} proven / {go} optimistic gates)"
            print(f"{disp:>14s} {pname:<9s} {status}{supp}{note}")
            for f in pres.findings:
                n_findings += 1
                print(f"    {f.render()}")
            if pres.error:
                n_findings += 1
                print(f"    ERROR {pres.error}")
            if args.verbose:
                for f, reason in pres.suppressed:
                    print(f"    suppressed {f.render()}\n"
                          f"        reason: {reason}")
                for r in pres.extra.get("residuals", []):
                    print(f"    optimistic gate: {r['prim']} "
                          f"[{r['where']}] sources={r['sources']}")

    if "host" in passes:
        host, n_files = lint_host(PKG_ROOT)
        status = "pass" if host.ok else "FAIL"
        print(f"{'host-plane':>14s} astlint   {status} "
              f"({n_files} files, {len(host.suppressed)} suppressed)")
        for f in host.findings:
            n_findings += 1
            print(f"    {f.render()}")
        if args.verbose:
            for f, reason in host.suppressed:
                print(f"    suppressed {f.render()}\n"
                      f"        reason: {reason}")
    else:
        host, n_files = None, 0

    if "taint" in passes:
        print(f"{'':>14s} gate polarity: {gates_proven} proven, "
              f"{gates_optimistic} optimistic (residuals listed in "
              "LINT.json extra)")

    if partial:
        print(f"graftlint (partial): {n_findings} finding(s)")
        return 1 if n_findings else 0

    doc = assemble_report(kernels, host, n_files)
    text = dumps_report(doc)
    if args.check:
        try:
            with open(args.out, "r") as f:
                committed = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            print(f"graftlint --check: cannot read baseline "
                  f"{args.out}: {e}")
            return 1
        if committed != doc:
            print(f"graftlint --check: DRIFT against {args.out} — "
                  "regenerate with scripts/graftlint.py and commit the "
                  "diff with the change that caused it")
            _print_drift(committed, doc)
            return 1
        print(f"graftlint --check: baseline matches ({args.out})")
    else:
        with open(args.out, "w") as f:
            f.write(text)
        print(f"wrote {args.out}")
    clean = doc["summary"]["clean"]
    print(f"graftlint: {'CLEAN' if clean else 'FINDINGS'} "
          f"({doc['summary']['kernels_verified']} kernels, "
          f"{n_findings} finding(s))")
    return 0 if clean else 1


def _print_drift(old, new, path="") -> None:
    """Shallow recursive diff, enough to locate the drifting key."""
    if isinstance(old, dict) and isinstance(new, dict):
        for k in sorted(set(old) | set(new)):
            if k not in old:
                print(f"  + {path}/{k}")
            elif k not in new:
                print(f"  - {path}/{k}")
            elif old[k] != new[k]:
                _print_drift(old[k], new[k], f"{path}/{k}")
    elif isinstance(old, list) and isinstance(new, list):
        print(f"  ~ {path}: list differs "
              f"({len(old)} -> {len(new)} entries)")
    else:
        print(f"  ~ {path}: {old!r} -> {new!r}")


if __name__ == "__main__":
    sys.exit(main())
