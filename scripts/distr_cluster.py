#!/usr/bin/env python3
"""Launch a multi-host cluster over SSH.

Parity: reference ``scripts/distr_cluster.py`` + ``remote_hosts.toml`` +
``scripts/utils/proc.py run_process_over_ssh`` — the manager runs on the
first host, one server replica per listed host, all started through ssh
with the repo path and ports templated in.  Requires passwordless ssh to
every host and the repo checked out at the same path (the reference makes
the same assumptions).

Hosts file (TOML):
    repo = "/root/repo"
    [[hosts]]
    name = "host0"
    addr = "10.0.0.1"
    [[hosts]]
    name = "host1"
    addr = "10.0.0.2"
    ...

Usage:
    python scripts/distr_cluster.py -p MultiPaxos --hosts remote_hosts.toml
    python scripts/distr_cluster.py --hosts remote_hosts.toml --kill
"""

from __future__ import annotations

import argparse
import shlex
import subprocess
import sys
try:
    import tomllib
except ModuleNotFoundError:  # Python < 3.11: tomli is API-compatible
    import tomli as tomllib

SSH = ["ssh", "-o", "StrictHostKeyChecking=no",
       "-o", "BatchMode=yes"]


def run_over_ssh(addr: str, cmd: str, background: bool = True,
                 workdir: str = ""):
    """Start ``cmd`` on ``addr`` (parity: utils/proc.py
    run_process_over_ssh — nohup + setsid so the process survives the
    ssh session).  ``workdir`` is entered with a plain ``cd`` BEFORE the
    daemonizing wrapper: setsid/nohup must wrap the actual python
    process, not a shell builtin."""
    prefix = f"cd {shlex.quote(workdir)} && " if workdir else ""
    remote = (
        f"{prefix}setsid nohup {cmd} > /tmp/summerset_remote.log 2>&1 "
        "< /dev/null & echo $!"
        if background else f"{prefix}{cmd}"
    )
    return subprocess.run(
        SSH + [addr, remote], capture_output=True, text=True, timeout=60
    )


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("-p", "--protocol", default="MultiPaxos")
    ap.add_argument("--hosts", required=True)
    ap.add_argument("--srv-port", type=int, default=52600)
    ap.add_argument("--cli-port", type=int, default=52601)
    ap.add_argument("--api-port", type=int, default=52700)
    ap.add_argument("--p2p-port", type=int, default=52800)
    ap.add_argument("-g", "--num-groups", type=int, default=1)
    ap.add_argument("-c", "--config", default="")
    ap.add_argument("--kill", action="store_true",
                    help="stop all remote processes instead of launching")
    args = ap.parse_args()

    with open(args.hosts, "rb") as f:
        spec = tomllib.load(f)
    repo = spec.get("repo", "/root/repo")
    hosts = spec["hosts"]
    if not hosts:
        print("no hosts listed", file=sys.stderr)
        return 1

    if args.kill:
        for h in hosts:
            run_over_ssh(
                h["addr"],
                "pkill -f summerset_tpu.cli || true",
                background=False,
            )
            print(f"killed on {h['name']}")
        return 0

    man_host = hosts[0]
    py = f"env PYTHONPATH={shlex.quote(repo)} python"
    man_cmd = (
        f"{py} -m summerset_tpu.cli.manager -p {args.protocol} "
        f"--bind-ip 0.0.0.0 --srv-port {args.srv_port} "
        f"--cli-port {args.cli_port} -n {len(hosts)}"
    )
    r = run_over_ssh(man_host["addr"], man_cmd, workdir=repo)
    print(f"manager on {man_host['name']} ({man_host['addr']}): "
          f"pid {r.stdout.strip() or '?'}")

    for i, h in enumerate(hosts):
        cfg = f" -c {shlex.quote(args.config)}" if args.config else ""
        srv_cmd = (
            f"{py} -m summerset_tpu.cli.server -p {args.protocol} "
            f"--bind-ip 0.0.0.0 -a {args.api_port} -i {args.p2p_port} "
            f"-m {man_host['addr']}:{args.srv_port} "
            f"-g {args.num_groups}{cfg}"
        )
        r = run_over_ssh(h["addr"], srv_cmd, workdir=repo)
        print(f"server {i} on {h['name']} ({h['addr']}): "
              f"pid {r.stdout.strip() or '?'}")
    print(
        f"cluster launching; clients connect to "
        f"{man_host['addr']}:{args.cli_port}"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
