#!/usr/bin/env python3
"""Wire-codec microbench: encode/decode throughput + bytes per frame,
wirecodec vs pickle, over the repo's hot frame shapes.

Measures, per shape (tick frames at several [G, R] geometries with and
without payload piggybacks, hot api messages, proxy forward batches):

- ``bytes``     — one encoded frame's body size, both formats;
- ``enc_us`` / ``dec_us`` — best-of-rounds mean per-op wall time;
- ``enc_mbps`` / ``dec_mbps`` — the same as body-throughput (each
  format over ITS OWN body size — the codec moves fewer bytes AND
  less time, so MB/s alone under-sells it).

``--commit`` merges the result as the ``wire_bench`` block into
HOSTBENCH.json (everything else in the artifact is preserved), with an
``ok`` verdict asserting the codec's headline inequalities on the p2p
shapes: bytes strictly down AND enc+dec time strictly down on every
tick-frame shape.  ``scripts/workload_gate.py`` re-checks the committed
block (the drift gate for this plane).

Usage:
    python scripts/wire_bench.py [--rounds 5] [--iters 2000] [--commit]
"""

from __future__ import annotations

import argparse
import json
import os
import pickle
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from summerset_tpu.host.messages import ApiReply, ApiRequest  # noqa: E402
from summerset_tpu.host.statemach import Command, CommandResult  # noqa: E402
from summerset_tpu.utils import wirecodec  # noqa: E402


def tick_frame(g: int, r: int, pp_ops: int, seed: int = 7):
    """A representative transport tick frame: the kernel outbox lane
    dict (shapes/dtypes as MultiPaxos serves them) + the host payload
    keys that ride alongside."""
    rng = np.random.default_rng(seed)
    msg = {}
    for name in ("prep_bal", "prep_vbal", "acc_bal", "acc_val",
                 "commit_bar", "hb_bal"):
        msg[name] = rng.integers(0, 1 << 20, (g,)).astype(np.int32)
    for name in ("ar_bal", "ar_f", "ar_hint"):
        msg[name] = rng.integers(0, 1 << 20, (g, r)).astype(np.int32)
    msg["flags"] = rng.integers(0, 1 << 30, (g, r)).astype(np.uint32)
    pp = {}
    for i in range(pp_ops):
        pp[(i % g, 100 + i)] = [(5 + i, ApiRequest(
            "req", req_id=i,
            cmd=Command("put", f"key{i}", "v" * 64),
        ))]
    payload = {
        "msg": msg,
        "pp": pp,
        "kv_need": False,
        "need": [],
        "ts": 123.456,
        "hb": {"f": 12.5, "w": 3.25, "q": 0.5,
               "o": {p: 1.5 for p in range(r) if p != 0}},
    }
    return (4242, payload)


def shapes():
    return {
        # p2p plane (the gated rows): bench-fallback shape, the
        # serving-default shape, and the pod-scale shape
        "tick_g16_r3": ("p2p", tick_frame(16, 3, 2)),
        "tick_g64_r3": ("p2p", tick_frame(64, 3, 4)),
        "tick_g1024_r3": ("p2p", tick_frame(1024, 3, 4)),
        "tick_g16_r3_idle": ("p2p", tick_frame(16, 3, 0)),
        # api plane (reported): the steady-state client exchange
        "api_put_req": ("api", ApiRequest(
            "req", req_id=77, cmd=Command("put", "mykey123", "x" * 64),
        )),
        "api_get_req": ("api", ApiRequest(
            "req", req_id=78, cmd=Command("get", "mykey123"),
        )),
        "api_put_reply": ("api", ApiReply(
            "reply", req_id=77,
            result=CommandResult("put", old_value="y" * 64),
        )),
        "api_shed": ("api", ApiReply(
            "shed", req_id=3, success=False, retry_after_ms=120,
        )),
        # distinct per-op values, as real client fleets generate them —
        # identical repeated strings would hand pickle a memoization
        # advantage no live workload provides
        "proxy_batch16": ("api", ApiRequest(
            "batch", req_id=1, batch=[
                (i, Command("put", f"key{i}", f"v{i:03d}" * 16))
                for i in range(16)
            ],
        )),
        "feed_note8": ("api", ApiReply(
            "note", req_id=0, seq=42,
            notes=[(40 + i, f"k{i}", f"n{i:03d}" * 16)
                   for i in range(8)],
        )),
    }


def bench_fn(fn, iters: int, rounds: int) -> float:
    """Best-of-rounds mean microseconds per call."""
    best = float("inf")
    for _ in range(rounds):
        t0 = time.perf_counter()
        for _ in range(iters):
            fn()
        best = min(best, (time.perf_counter() - t0) / iters)
    return best * 1e6


def run(iters: int, rounds: int) -> dict:
    enc = wirecodec.FrameEncoder()
    out = {}
    for name, (plane, obj) in shapes().items():
        cbody = enc.encode_bytes(obj)
        pbody = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
        assert cbody[0] == wirecodec.MAGIC, f"{name} not codec-encoded"

        def enc_codec():
            enc.encode_frame_into(obj)
            enc.release()

        row = {
            "plane": plane,
            "codec_bytes": len(cbody),
            "pickle_bytes": len(pbody),
            "codec_enc_us": round(bench_fn(enc_codec, iters, rounds), 2),
            "pickle_enc_us": round(bench_fn(
                lambda: pickle.dumps(obj, pickle.HIGHEST_PROTOCOL),
                iters, rounds,
            ), 2),
            "codec_dec_us": round(bench_fn(
                lambda: wirecodec.decode_body(cbody), iters, rounds,
            ), 2),
            "pickle_dec_us": round(bench_fn(
                lambda: pickle.loads(pbody), iters, rounds,
            ), 2),
        }
        for fmt in ("codec", "pickle"):
            nb = row[f"{fmt}_bytes"]
            row[f"{fmt}_enc_mbps"] = round(
                nb / row[f"{fmt}_enc_us"], 1
            )
            row[f"{fmt}_dec_mbps"] = round(
                nb / row[f"{fmt}_dec_us"], 1
            )
        out[name] = row
    return out


def verdict(rows: dict) -> tuple:
    """The committed inequalities: every shape's bytes strictly down;
    on the p2p (tick frame) shapes, enc AND dec time strictly down."""
    failures = []
    for name, r in rows.items():
        if r["codec_bytes"] >= r["pickle_bytes"]:
            failures.append(
                f"{name}: codec bytes {r['codec_bytes']} >= pickle "
                f"{r['pickle_bytes']}"
            )
        if r["plane"] != "p2p":
            continue
        if r["codec_enc_us"] >= r["pickle_enc_us"]:
            failures.append(
                f"{name}: codec encode {r['codec_enc_us']}us >= pickle "
                f"{r['pickle_enc_us']}us"
            )
        if r["codec_dec_us"] >= r["pickle_dec_us"]:
            failures.append(
                f"{name}: codec decode {r['codec_dec_us']}us >= pickle "
                f"{r['pickle_dec_us']}us"
            )
    return (not failures), failures


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=2000)
    ap.add_argument("--rounds", type=int, default=5)
    ap.add_argument("--commit", action="store_true",
                    help="merge the block into HOSTBENCH.json")
    ap.add_argument("--out", default=os.path.join(REPO, "HOSTBENCH.json"))
    args = ap.parse_args()

    rows = run(args.iters, args.rounds)
    ok, failures = verdict(rows)
    block = {
        "iters": args.iters,
        "rounds": args.rounds,
        "rows": rows,
        "ok": ok,
        "failures": failures,
    }
    for name, r in rows.items():
        print(f"{name:18s} bytes {r['codec_bytes']:>7}/{r['pickle_bytes']:<7}"
              f" enc {r['codec_enc_us']:>7.2f}/{r['pickle_enc_us']:<7.2f}us"
              f" dec {r['codec_dec_us']:>7.2f}/{r['pickle_dec_us']:<7.2f}us"
              f"  (codec/pickle)")
    print(f"verdict: {'ok' if ok else failures}")

    if args.commit:
        art = {}
        if os.path.exists(args.out):
            with open(args.out) as f:
                art = json.load(f)
        art["wire_bench"] = block
        with open(args.out, "w") as f:
            json.dump(art, f, indent=1)
        print(f"wire_bench block committed into {args.out}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
