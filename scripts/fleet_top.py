#!/usr/bin/env python3
"""fleet_top: live graftwatch dashboard over the ctrl plane.

Scrapes the manager's ``watch_series`` ring (one round-trip to the
manager — no server fan-out; servers stream delta frames on their own
tick cadence), aligns the per-server frames into fleet windows, and
renders the last few windows as a text table plus the SLO burn-rate
status per declared objective.  Wallclock-free: columns are window
indices (``tick // span_ticks``), not timestamps, so the same scrape
renders identically anywhere.

Usage:
    python scripts/fleet_top.py --manager 127.0.0.1:52700          # live
    python scripts/fleet_top.py --manager 127.0.0.1:52700 --once   # one shot

``--once`` prints a single snapshot and exits 0 (exit 1 if the scrape
came back empty) — the mode scripts and CI drive.  Without it the
screen redraws every ``--interval`` seconds until interrupted.
"""

import argparse
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from summerset_tpu.host.graftwatch import (  # noqa: E402
    DEFAULT_OBJECTIVES, SloPolicy, windows,
)

# per-window fleet counters worth a column (deltas over the window)
COUNTER_COLS = (
    ("req", "api_requests_total"),
    ("shed", "api_shed"),
    ("commit", "commits_applied_total"),
    ("fsync", "wal_appends_total"),
    ("scan", "scan_served"),
)


def _p99_ms(win: dict, metric: str) -> str:
    h = win["hists"].get(metric)
    if h is None or h.count == 0:
        return "-"
    return f"{h.quantile(0.99) / 1e3:.1f}"


def render(export: dict, n_windows: int, tier=None) -> str:
    rows = windows(export, tier=tier)
    lines = []
    series = export.get("series", [])
    lines.append(
        f"graftwatch fleet  series={len(series)} "
        f"frames={export.get('frames_ingested', 0)} "
        f"retain={export.get('retain')}"
    )
    for s in series:
        lines.append(
            f"  sid={s['sid']} tier={s['tier']} group={s['group']} "
            f"frames={len(s['frames'])}"
        )
    if not rows:
        lines.append("  (no complete windows yet)")
        return "\n".join(lines)

    shown = rows[-n_windows:]
    hdr = (
        f"{'widx':>6} {'sids':>4} "
        + " ".join(f"{label:>8}" for label, _ in COUNTER_COLS)
        + f" {'p99ms':>8} {'fsync99':>8}"
    )
    lines.append("")
    lines.append(hdr)
    for w in shown:
        vals = " ".join(
            f"{w['counters'].get(name, 0):>8}"
            for _, name in COUNTER_COLS
        )
        lines.append(
            f"{w['widx']:>6} {len(w['sids']):>4} {vals} "
            f"{_p99_ms(w, 'api_request_latency_us'):>8} "
            f"{_p99_ms(w, 'wal_fsync_us'):>8}"
        )

    # burn-rate status: replay every aligned window through a fresh
    # policy so the rendered state is a pure function of the scrape
    pol = SloPolicy(DEFAULT_OBJECTIVES)
    for w in rows:
        pol.observe_window(w)
    lines.append("")
    lines.append("SLO burn rates (fast/slow window means, budget=1.0):")
    status = pol.status()
    for name in sorted(status):
        row = status[name]
        flag = "ALERT" if row.get("alerting") else "ok"
        lines.append(
            f"  {name:<16} burn={row.get('burn', 0.0):7.3f} "
            f"fast={row.get('fast', 0.0):7.3f} "
            f"slow={row.get('slow', 0.0):7.3f}  {flag}"
        )
    if not status:
        lines.append("  (no windows observed yet)")
    return "\n".join(lines)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--manager", required=True,
                    help="host:port of the cluster manager cli endpoint")
    ap.add_argument("--interval", type=float, default=2.0)
    ap.add_argument("--windows", type=int, default=8,
                    help="how many trailing fleet windows to show")
    ap.add_argument("--tier", default=None,
                    help="only merge frames from this tier "
                         "(shard/proxy); default: all")
    ap.add_argument("--once", action="store_true",
                    help="print one snapshot and exit")
    args = ap.parse_args()

    from summerset_tpu.client.endpoint import scrape_fleet

    host, port = args.manager.rsplit(":", 1)
    addr = (host, int(port))

    while True:
        export = scrape_fleet(addr)
        if export is None:
            print("fleet scrape failed (manager unreachable?)")
            if args.once:
                return 1
        else:
            text = render(export, args.windows, tier=args.tier)
            if not args.once:
                # ANSI clear + home: redraw in place like top(1)
                sys.stdout.write("\x1b[2J\x1b[H")
            print(text, flush=True)
            if args.once:
                return 0
        time.sleep(args.interval)


if __name__ == "__main__":
    sys.exit(main())
