"""Failover timeline: kill the leader mid-run, measure the throughput dip
and the recovery time.

Reference analog: ``scripts/bodega/bench_failover.py`` (SURVEY.md §6) —
clients stream ops while the leader is crash-restarted; the output is a
per-bin completion-rate timeline plus the measured gap until throughput
recovers to half its pre-kill average.

Writes FAILOVER.json at the repo root:
  {"protocol", "workload", "workload_seed", "workload_digest",
   "kill_at_s", "bins_ms", "timeline": [ops per bin, ...],
   "pre_kill_tput", "recovery_ms"}

Usage: python scripts/bench_failover.py [--protocol MultiPaxos]
       [--secs 12] [--kill-at 6] [--clients 4] [--bin-ms 100]
       [--workload <class>] [--workload-seed N]
(--workload runs the fleet under a seeded WorkloadPlan traffic class —
the ROADMAP "FAILOVER fleet per workload class" follow-on; the stamp
makes any timeline regenerable from class+seed)
"""

import argparse
import json
import os
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax

jax.config.update("jax_platforms", "cpu")
from summerset_tpu.utils.jaxcompat import set_cpu_devices
set_cpu_devices(8)

sys.path.insert(0, os.path.join(REPO, "tests"))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--protocol", default="MultiPaxos")
    ap.add_argument("--groups", type=int, default=1)
    ap.add_argument("--replicas", type=int, default=3)
    ap.add_argument("--clients", type=int, default=4)
    ap.add_argument("--secs", type=float, default=12.0)
    ap.add_argument("--kill-at", type=float, default=6.0)
    ap.add_argument("--bin-ms", type=int, default=100)
    ap.add_argument("--tick", type=float, default=0.002)
    ap.add_argument("--config", default="")
    ap.add_argument("--workload", default="uniform",
                    help="workload class (host/workload.py "
                         "WORKLOAD_CLASSES); uniform = the legacy "
                         "alternating put/get mix, so default "
                         "trajectories stay comparable")
    ap.add_argument("--workload-seed", type=int, default=1)
    ap.add_argument("--num-keys", type=int, default=64)
    ap.add_argument("--out", default=os.path.join(REPO, "FAILOVER.json"))
    args = ap.parse_args()

    from test_cluster import Cluster
    from summerset_tpu.client.drivers import DriverClosedLoop
    from summerset_tpu.client.endpoint import GenericEndpoint
    from summerset_tpu.host.messages import CtrlRequest
    from summerset_tpu.host.workload import WorkloadPlan

    # seeded-deterministic traffic class for the failover window — the
    # op/key/size sequence is a pure function of (plan, client index),
    # stamped into the artifact so any timeline is regenerable
    plan = None
    if args.workload != "uniform":
        plan = WorkloadPlan.generate(
            args.workload_seed, args.workload, clients=args.clients,
            num_keys=args.num_keys,
        )

    config = {}
    for kv in filter(None, args.config.split(",")):
        k, v = kv.split("=", 1)
        config[k] = json.loads(v)

    tmp = tempfile.mkdtemp(prefix="failover_")
    cluster = Cluster(args.protocol, args.replicas, tmp, config=config,
                      tick=args.tick, num_groups=args.groups)
    print("cluster up", flush=True)

    # warm the cluster before the timed window opens: the first tick
    # jit-compiles the kernel (~10s cold on this class of box) and the
    # server answers nothing meanwhile — without this barrier the
    # pre-kill window measures the compile stall, not the protocol
    wep = GenericEndpoint(cluster.manager_addr)
    wep.connect()
    DriverClosedLoop(wep).checked_put("warmup", "1")
    wep.leave()
    print("warmed up", flush=True)

    completions = []  # monotonic timestamps of successful ops
    stop = threading.Event()
    t_start = time.monotonic()

    def client(i):
        ep = GenericEndpoint(cluster.manager_addr)
        ep.connect()
        drv = DriverClosedLoop(ep, timeout=2.0)
        ops = plan.opstream(i) if plan is not None else None
        n = 0
        while not stop.is_set():
            if ops is not None:
                kind, key, size = ops.next()
                do_put = kind == "put"
                val = f"v{i}-{n}".ljust(size, "x")[:max(size, 1)]
            else:
                key = f"fo{(n + i) % 32}"
                do_put = bool(n % 2)
                val = f"v{i}-{n}"
            r = drv.put(key, val) if do_put else drv.get(key)
            if r.kind == "success":
                completions.append(time.monotonic())
            elif r.kind in ("timeout", "disconnect"):
                # dead/paused server or dead socket: move on (redirects
                # already reconnected inside the driver)
                drv._failover(r)
                time.sleep(0.02)
            elif r.kind == "failure":
                # server refused (leadership settling): retry in place —
                # rotating away here thrashes the endpoint around the
                # membership and can starve the whole run
                time.sleep(0.05)
            else:  # redirect: reconnected inside the driver; back off a
                time.sleep(0.02)  # beat so the loop can't starve servers
            n += 1
        try:
            ep.leave()
        except Exception:
            pass

    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(args.clients)]
    for t in threads:
        t.start()

    # kill (crash-restart) the current leader at kill_at
    ep = GenericEndpoint(cluster.manager_addr)
    ep.connect()
    time.sleep(args.kill_at)
    leader = ep.ctrl.request(CtrlRequest("query_info")).leader or 0
    t_kill = time.monotonic()
    print(f"killing leader {leader} at {t_kill - t_start:.2f}s", flush=True)
    threading.Thread(
        target=lambda: ep.ctrl.request(
            CtrlRequest("reset_servers", servers=[leader]), timeout=120,
        ),
        daemon=True,
    ).start()

    time.sleep(max(0.0, args.secs - args.kill_at))
    stop.set()
    for t in threads:
        t.join(timeout=15)
    try:
        ep.leave()
    except Exception:
        pass
    cluster.stop()

    # bin the completion timeline
    bin_s = args.bin_ms / 1e3
    nbins = int(args.secs / bin_s) + 1
    timeline = [0] * nbins
    for ts in completions:
        b = int((ts - t_start) / bin_s)
        if 0 <= b < nbins:
            timeline[b] += 1

    kill_bin = int((t_kill - t_start) / bin_s)
    pre = timeline[max(0, kill_bin - 20):kill_bin]
    pre_rate = sum(pre) / max(len(pre), 1)
    recovery_ms = None
    for b in range(kill_bin + 1, nbins):
        if timeline[b] >= 0.5 * pre_rate and pre_rate > 0:
            recovery_ms = int((b - kill_bin) * args.bin_ms)
            break

    out = {
        "protocol": args.protocol,
        "replicas": args.replicas,
        "clients": args.clients,
        "secs": args.secs,
        # workload stamp (like TPUTLAT/HOSTBENCH since PR 7): class +
        # seed + digest regenerate the exact per-client op streams
        "workload": args.workload,
        "workload_seed": args.workload_seed,
        "workload_digest": plan.digest() if plan is not None else None,
        "kill_at_s": round(t_kill - t_start, 3),
        "killed_leader": leader,
        "bins_ms": args.bin_ms,
        "timeline": timeline,
        "pre_kill_tput": round(pre_rate / bin_s, 1),
        "recovery_ms": recovery_ms,
        "total_ops": len(completions),
    }
    with open(args.out, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({k: v for k, v in out.items() if k != "timeline"}),
          flush=True)


if __name__ == "__main__":
    main()
