"""Throughput-latency curve: sweep offered load against a live cluster.

The reference's experiment fleets sweep per-client target frequency and
plot achieved tput vs p50/p99 (``scripts/crossword/bench_tput_lat.py``,
SURVEY.md §6).  Same shape here: one in-process cluster (real manager +
replica event loops + TCP), ClientBench clients paced at each offered
load, one JSON row per load point.

Writes TPUTLAT.json at the repo root:
  {"protocol", "groups", "clients", "points": [
     {"offered", "tput", "lat_p50_ms", "lat_p99_ms"}, ...]}

Usage: python scripts/bench_tput_lat.py [--protocol MultiPaxos]
       [--loads 50,100,200,400,0] [--secs 6] [--clients 4]
(load 0 = unlimited, the saturation point)
"""

import argparse
import json
import os
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax

jax.config.update("jax_platforms", "cpu")
from summerset_tpu.utils.jaxcompat import set_cpu_devices
set_cpu_devices(8)

sys.path.insert(0, os.path.join(REPO, "tests"))


def _wire_codec_on() -> bool:
    from summerset_tpu.utils import wirecodec

    return wirecodec.default_on()


def _pipeline_on() -> bool:
    from summerset_tpu.host.server import pipeline_default

    return pipeline_default()


def run_point(cluster, clients, secs, freq, put_ratio, value_size,
              num_keys, plan=None):
    from summerset_tpu.client.bench import ClientBench
    from summerset_tpu.client.endpoint import GenericEndpoint

    results = [None] * clients

    def one(i):
        ep = GenericEndpoint(cluster.manager_addr)
        ep.connect()
        bench = ClientBench(
            ep, secs=secs, freq=freq, put_ratio=put_ratio,
            value_size=value_size, num_keys=num_keys, interval=1e9,
            seed=100 + i,
            opgen=plan.opstream(i) if plan is not None else None,
        )
        results[i] = bench.run()
        ep.leave()

    threads = [threading.Thread(target=one, args=(i,), daemon=True)
               for i in range(clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=secs + 60)
    done = [r for r in results if r]
    return {
        "offered": freq * clients if freq > 0 else 0,
        "tput": round(sum(r["tput"] for r in done), 2),
        "lat_p50_ms": round(
            max((r["lat_p50_ms"] for r in done), default=0.0), 3),
        "lat_p99_ms": round(
            max((r["lat_p99_ms"] for r in done), default=0.0), 3),
    }


def _sweep_pipeline_metrics(points, server_metrics, plan) -> dict:
    """Distill one sweep leg for the ``pipeline_ab`` block: the
    saturated-throughput point (offered=0 when present, else the best
    achieved) plus the overlap attribution off the scraped
    ``loop_stage_us`` histograms (``host_bench.stage_overlap_sums`` —
    the one distillation both A/B drivers share)."""
    from host_bench import stage_overlap_sums

    sat = None
    for p in points:
        if p["offered"] == 0:
            sat = p
    if sat is None and points:
        sat = max(points, key=lambda p: p["tput"])
    ticks, sums = stage_overlap_sums(server_metrics)
    return {
        "ok": any(p["tput"] > 0 for p in points),
        "workload_digest": plan.digest() if plan is not None else None,
        "sat_tput": sat["tput"] if sat else 0.0,
        "sat_lat_p50_ms": sat["lat_p50_ms"] if sat else 0.0,
        "sat_lat_p99_ms": sat["lat_p99_ms"] if sat else 0.0,
        "ticks": ticks,
        "overlap_us_total": sums["overlap"][0],
        "overlap_us_per_tick": round(
            sums["overlap"][0] / max(sums["overlap"][1], 1), 1
        ),
        "device_wait_us_mean": round(
            sums["device_wait"][0] / max(sums["device_wait"][1], 1), 1
        ),
    }


def check_tputlat_pipeline_ab(block: dict) -> list:
    """The TPUTLAT pipelined-loop A/B gate (re-asserted by
    perf_gate.py --check): the one shared inequality set
    (``host_bench.check_pipeline_ab_core``) keyed on the saturated
    sweep point."""
    from host_bench import check_pipeline_ab_core

    return check_pipeline_ab_core(
        block.get("on") or {}, block.get("off") or {},
        "sat_tput", "saturated tput",
    )


def run_pipeline_ab(args, plan) -> None:
    """The pipelined-loop A/B: the full load sweep as INTERLEAVED
    serial/pipelined round pairs (leg order alternates per round,
    per-side medians gate — the PERF round-8 discipline shared with
    ``host_bench.run_pipeline_ab``: a single fixed-order pair is
    exposed to monotonic box drift), same ``WorkloadPlan`` every leg so
    the offered op streams are byte-identical (the committed digest
    attests it).

    The legs run on ``host_bench.ProcCluster`` (one PROCESS per
    replica, the deployment shape) instead of the in-process curve
    harness: the pipelined loop moves host-stage Python under the
    device step's wall window, so in a shared-interpreter cluster it
    steals GIL time from the bench's own client threads and the A/B
    would measure harness contention, not the serving path.  The
    ProcCluster path takes no server config dict, so config-shaped
    knobs (``--mesh``/``--tally``) are refused up front in main()
    rather than silently dropped."""
    import shutil as _shutil

    from host_bench import ProcCluster, summarize_ab_side

    from summerset_tpu.client.drivers import DriverClosedLoop
    from summerset_tpu.client.endpoint import (
        GenericEndpoint, scrape_metrics,
    )

    def one_leg(mode: bool, rnd: int) -> dict:
        tag = "on" if mode else "off"
        print(f"=== pipeline_ab round {rnd}: pipeline {tag} sweep ===",
              flush=True)
        os.environ["SMR_PIPELINE"] = "1" if mode else "0"
        tmp = tempfile.mkdtemp(prefix=f"tput_pl_{tag}_")
        cl = None
        try:
            t0 = time.time()
            cl = ProcCluster(
                args.protocol, args.replicas, tmp,
                tick=args.tick, groups=args.groups,
            )
            print(f"cluster up in {time.time() - t0:.1f}s "
                  f"({args.replicas} replica processes)", flush=True)
            # warm the jit path so the first load point measures the
            # serving tick, not XLA compile (same discipline both legs)
            wep = GenericEndpoint(cl.manager_addr)
            wep.connect()
            DriverClosedLoop(wep, timeout=30.0).checked_put("warm", "1")
            wep.leave()
            pts = []
            for load in [float(x) for x in args.loads.split(",")]:
                pt = run_point(cl, args.clients, args.secs, load,
                               args.put_ratio, args.value_size,
                               args.num_keys, plan=plan)
                print(json.dumps(pt), flush=True)
                pts.append(pt)
            metrics = scrape_metrics(cl.manager_addr)
        finally:
            os.environ.pop("SMR_PIPELINE", None)
            if cl is not None:
                cl.stop()
            _shutil.rmtree(tmp, ignore_errors=True)
        leg = _sweep_pipeline_metrics(pts, metrics, plan)
        leg["pipeline"] = mode
        return leg

    rounds = {"on": [], "off": []}
    for rnd in range(args.ab_rounds):
        order = (False, True) if rnd % 2 == 0 else (True, False)
        for mode in order:
            rounds["on" if mode else "off"].append(one_leg(mode, rnd))
    legs = {
        tag: summarize_ab_side(per) for tag, per in rounds.items()
    }
    block = {
        "protocol": args.protocol,
        "groups": args.groups,
        "replicas": args.replicas,
        "clients": args.clients,
        "loads": args.loads,
        "secs_per_point": args.secs,
        "workload": args.workload,
        "workload_seed": args.workload_seed,
        "ab_rounds": args.ab_rounds,
        "on": legs["on"],
        "off": legs["off"],
    }
    fails = check_tputlat_pipeline_ab(block)
    block["ok"] = not fails
    block["failures"] = fails
    art = {}
    if os.path.exists(args.out):
        try:
            with open(args.out) as f:
                art = json.load(f)
        except Exception:
            art = {}
    art["pipeline_ab"] = block
    with open(args.out, "w") as f:
        json.dump(art, f, indent=1)
    print("pipeline_ab: " + json.dumps({
        "ok": block["ok"],
        "sat_tput_on": legs["on"]["sat_tput"],
        "sat_tput_off": legs["off"]["sat_tput"],
        "overlap_us_per_tick": legs["on"]["overlap_us_per_tick"],
        "failures": fails,
    }), flush=True)
    sys.exit(0 if block["ok"] else 1)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--protocol", default="MultiPaxos")
    ap.add_argument("--groups", type=int, default=4)
    ap.add_argument("--replicas", type=int, default=3)
    ap.add_argument("--clients", type=int, default=4)
    ap.add_argument("--secs", type=float, default=6.0)
    ap.add_argument("--tick", type=float, default=0.002)
    ap.add_argument("--loads", default="50,100,200,400,0",
                    help="per-client req/s; 0 = unlimited")
    ap.add_argument("--num-keys", type=int, default=64)
    ap.add_argument("--value-size", default="64")
    ap.add_argument("--put-ratio", type=float, default=0.5)
    ap.add_argument("--config", default="",
                    help="k=v[,k=v...] extra cluster config")
    ap.add_argument("--workload", default="uniform",
                    help="workload class (host/workload.py "
                         "WORKLOAD_CLASSES); uniform = the legacy "
                         "bench mix, so default trajectories stay "
                         "comparable")
    ap.add_argument("--workload-seed", type=int, default=1)
    ap.add_argument("--trace", default="",
                    help="YCSB trace file replayed byte-reproducibly "
                         "via WorkloadPlan.from_trace (the plan's "
                         "digest + the raw file's sha are stamped "
                         "into the artifact); overrides --workload")
    ap.add_argument("--tally", default="pairwise",
                    choices=("pairwise", "collective"),
                    help="quorum-tally transport for every replica's "
                         "kernel (core/quorum.py): collective carries "
                         "accept-reply records as per-source [G, R] "
                         "broadcast lanes")
    ap.add_argument("--mesh", default="",
                    help="GxR device mesh for every replica's serving "
                         "state (ServerReplica device_mesh knob; the "
                         "group axis shards across this host's "
                         "devices — on CPU, the 8-virtual-device "
                         "platform above).  Empty = single-device.")
    ap.add_argument("--pipeline-ab", action="store_true",
                    help="run the full load sweep as interleaved "
                         "serial/pipelined round pairs (SMR_PIPELINE "
                         "into every replica process; per-side medians "
                         "gate) and commit the gated A/B block (same "
                         "workload digest, saturated tput strictly up "
                         "pipelined, measured overlap > 0) beside the "
                         "curve")
    ap.add_argument("--ab-rounds", type=int, default=2,
                    help="interleaved A/B round pairs for --pipeline-ab "
                         "(leg order alternates per round against box "
                         "drift; medians gate)")
    ap.add_argument("--out", default=os.path.join(REPO, "TPUTLAT.json"))
    args = ap.parse_args()

    if args.pipeline_ab and (args.mesh or args.tally != "pairwise"
                             or args.config):
        # the A/B legs run on host_bench.ProcCluster (real replica
        # processes, no server-config path) — refuse config-shaped
        # knobs instead of silently dropping them from both legs
        ap.error("--pipeline-ab runs on the ProcCluster harness and "
                 "does not take --mesh/--tally/--config")

    from test_cluster import Cluster

    from summerset_tpu.host.workload import WorkloadPlan

    plan = None
    if args.trace:
        # trace replay: the plan normalizes the YCSB rows once and
        # stamps both the raw file's sha and the plan digest, so two
        # curves over the same trace are byte-comparable
        plan = WorkloadPlan.from_trace(
            args.trace, seed=args.workload_seed, clients=args.clients,
        )
        args.workload = "trace"
    elif args.workload != "uniform":
        plan = WorkloadPlan.generate(
            args.workload_seed, args.workload, clients=args.clients,
            num_keys=args.num_keys,
        )

    config = {}
    for kv in filter(None, args.config.split(",")):
        k, v = kv.split("=", 1)
        config[k] = json.loads(v)
    if args.tally != "pairwise":
        # the kernel-config knob rides the server config dict (any key
        # matching a config dataclass field passes through)
        config["tally"] = args.tally
    mesh_shape = None
    if args.mesh:
        # fail fast on an infeasible mesh — malformed spec, more devices
        # than the (8-virtual-device) platform, or axes that don't
        # divide this cluster's groups/replicas.  Without this the
        # error would surface as every replica's bring-up retry loop
        # timing out ~120s later with a generic "cluster failed to
        # start".
        from summerset_tpu.core.sharding import (
            check_mesh, mesh_for, mesh_stamp, parse_mesh,
        )

        mesh_shape = parse_mesh(args.mesh)
        check_mesh(mesh_for(*mesh_shape), args.groups, args.replicas)
        config["device_mesh"] = args.mesh

    def run_sweep(sweep_config):
        """One cluster bring-up -> full load sweep -> scrape -> stop."""
        tmp = tempfile.mkdtemp(prefix="tput_lat_")
        t0 = time.time()
        cl = Cluster(args.protocol, args.replicas, tmp,
                     config=sweep_config, tick=args.tick,
                     num_groups=args.groups)
        print(f"cluster up in {time.time() - t0:.1f}s", flush=True)
        pts = []
        try:
            for load in [float(x) for x in args.loads.split(",")]:
                pt = run_point(cl, args.clients, args.secs, load,
                               args.put_ratio, args.value_size,
                               args.num_keys, plan=plan)
                print(json.dumps(pt), flush=True)
                pts.append(pt)
            # scrape once after the sweep: the snapshot's histograms
            # cover every load point (server-side breakdown for the
            # curve above)
            from summerset_tpu.client.endpoint import scrape_metrics

            metrics = scrape_metrics(cl.manager_addr)
        finally:
            cl.stop()
        return pts, metrics

    if args.pipeline_ab:
        run_pipeline_ab(args, plan)
        return

    points, server_metrics = run_sweep(config)

    out = {
        "protocol": args.protocol,
        "groups": args.groups,
        "replicas": args.replicas,
        "clients": args.clients,
        "secs_per_point": args.secs,
        # workload stamp: which traffic class produced this curve (and
        # the seed/digest to regenerate the exact op streams)
        "workload": args.workload,
        "workload_seed": args.workload_seed,
        "workload_digest": plan.digest() if plan is not None else None,
        # trace replay stamp: which raw YCSB file fed the plan (sha of
        # the parsed rows — same trace must reproduce the same digest)
        "trace_file": args.trace or None,
        "trace_sha": plan.trace_sha() if args.trace else None,
        # quorum-tally transport stamp (core/quorum.py), next to the
        # mesh block like bench.py
        "tally": args.tally,
        # wire-plane stamp (utils/wirecodec.py): which frame format the
        # cluster's hot planes served this curve with
        "wire_codec": _wire_codec_on(),
        # tick-loop stamp (host/server.py): pipelined (device step
        # overlapped with WAL fsync + apply/reply + frame exchange
        # behind the durability fence) or the strict serial order
        "pipeline": _pipeline_on(),
        # serving-mesh stamp: which device mesh each replica's [G, R]
        # state was sharded over (None = the single-device legacy path);
        # the canonical block shared with bench.py and PROFILE.json
        "mesh": (
            mesh_stamp(mesh_shape[0], mesh_shape[1], args.groups)
            if mesh_shape is not None else None
        ),
        "points": points,
        # the artifact judges itself: a curve where nothing ever
        # committed is a failed capture even when the process exits 0
        "ok": any(p["tput"] > 0 for p in points),
        "server_metrics": server_metrics,
    }
    # graftprof analytic stamp (host-serving config variant at this
    # cluster's shape): deterministic-per-backend cost/memory/compile
    # metrics so the TPUTLAT trajectory stays comparable when the box's
    # wall-clock is noisy
    try:
        from summerset_tpu.host.profiling import protocol_analytic_block

        out["graftprof"] = protocol_analytic_block(
            args.protocol.lower(), "host", args.groups, args.replicas, 64
        )
    except Exception as e:  # the stamp must never kill the bench
        out["graftprof"] = {"error": f"{type(e).__name__}: {e}"}
    # preserve the sibling A/B block the --pipeline-ab parent commits
    # into this artifact (regenerated independently of the curve body)
    if os.path.exists(args.out):
        try:
            with open(args.out) as f:
                prev = json.load(f)
            if "pipeline_ab" in prev:
                out["pipeline_ab"] = prev["pipeline_ab"]
        except Exception:
            pass
    with open(args.out, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({"out": args.out, "points": len(points)}), flush=True)


if __name__ == "__main__":
    main()
