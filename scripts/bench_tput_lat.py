"""Throughput-latency curve: sweep offered load against a live cluster.

The reference's experiment fleets sweep per-client target frequency and
plot achieved tput vs p50/p99 (``scripts/crossword/bench_tput_lat.py``,
SURVEY.md §6).  Same shape here: one in-process cluster (real manager +
replica event loops + TCP), ClientBench clients paced at each offered
load, one JSON row per load point.

Writes TPUTLAT.json at the repo root:
  {"protocol", "groups", "clients", "points": [
     {"offered", "tput", "lat_p50_ms", "lat_p99_ms"}, ...]}

Usage: python scripts/bench_tput_lat.py [--protocol MultiPaxos]
       [--loads 50,100,200,400,0] [--secs 6] [--clients 4]
(load 0 = unlimited, the saturation point)
"""

import argparse
import json
import os
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax

jax.config.update("jax_platforms", "cpu")
from summerset_tpu.utils.jaxcompat import set_cpu_devices
set_cpu_devices(8)

sys.path.insert(0, os.path.join(REPO, "tests"))


def _wire_codec_on() -> bool:
    from summerset_tpu.utils import wirecodec

    return wirecodec.default_on()


def run_point(cluster, clients, secs, freq, put_ratio, value_size,
              num_keys, plan=None):
    from summerset_tpu.client.bench import ClientBench
    from summerset_tpu.client.endpoint import GenericEndpoint

    results = [None] * clients

    def one(i):
        ep = GenericEndpoint(cluster.manager_addr)
        ep.connect()
        bench = ClientBench(
            ep, secs=secs, freq=freq, put_ratio=put_ratio,
            value_size=value_size, num_keys=num_keys, interval=1e9,
            seed=100 + i,
            opgen=plan.opstream(i) if plan is not None else None,
        )
        results[i] = bench.run()
        ep.leave()

    threads = [threading.Thread(target=one, args=(i,), daemon=True)
               for i in range(clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=secs + 60)
    done = [r for r in results if r]
    return {
        "offered": freq * clients if freq > 0 else 0,
        "tput": round(sum(r["tput"] for r in done), 2),
        "lat_p50_ms": round(
            max((r["lat_p50_ms"] for r in done), default=0.0), 3),
        "lat_p99_ms": round(
            max((r["lat_p99_ms"] for r in done), default=0.0), 3),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--protocol", default="MultiPaxos")
    ap.add_argument("--groups", type=int, default=4)
    ap.add_argument("--replicas", type=int, default=3)
    ap.add_argument("--clients", type=int, default=4)
    ap.add_argument("--secs", type=float, default=6.0)
    ap.add_argument("--tick", type=float, default=0.002)
    ap.add_argument("--loads", default="50,100,200,400,0",
                    help="per-client req/s; 0 = unlimited")
    ap.add_argument("--num-keys", type=int, default=64)
    ap.add_argument("--value-size", default="64")
    ap.add_argument("--put-ratio", type=float, default=0.5)
    ap.add_argument("--config", default="",
                    help="k=v[,k=v...] extra cluster config")
    ap.add_argument("--workload", default="uniform",
                    help="workload class (host/workload.py "
                         "WORKLOAD_CLASSES); uniform = the legacy "
                         "bench mix, so default trajectories stay "
                         "comparable")
    ap.add_argument("--workload-seed", type=int, default=1)
    ap.add_argument("--tally", default="pairwise",
                    choices=("pairwise", "collective"),
                    help="quorum-tally transport for every replica's "
                         "kernel (core/quorum.py): collective carries "
                         "accept-reply records as per-source [G, R] "
                         "broadcast lanes")
    ap.add_argument("--mesh", default="",
                    help="GxR device mesh for every replica's serving "
                         "state (ServerReplica device_mesh knob; the "
                         "group axis shards across this host's "
                         "devices — on CPU, the 8-virtual-device "
                         "platform above).  Empty = single-device.")
    ap.add_argument("--out", default=os.path.join(REPO, "TPUTLAT.json"))
    args = ap.parse_args()

    from test_cluster import Cluster

    from summerset_tpu.host.workload import WorkloadPlan

    plan = None
    if args.workload != "uniform":
        plan = WorkloadPlan.generate(
            args.workload_seed, args.workload, clients=args.clients,
            num_keys=args.num_keys,
        )

    config = {}
    for kv in filter(None, args.config.split(",")):
        k, v = kv.split("=", 1)
        config[k] = json.loads(v)
    if args.tally != "pairwise":
        # the kernel-config knob rides the server config dict (any key
        # matching a config dataclass field passes through)
        config["tally"] = args.tally
    mesh_shape = None
    if args.mesh:
        # fail fast on an infeasible mesh — malformed spec, more devices
        # than the (8-virtual-device) platform, or axes that don't
        # divide this cluster's groups/replicas.  Without this the
        # error would surface as every replica's bring-up retry loop
        # timing out ~120s later with a generic "cluster failed to
        # start".
        from summerset_tpu.core.sharding import (
            check_mesh, mesh_for, mesh_stamp, parse_mesh,
        )

        mesh_shape = parse_mesh(args.mesh)
        check_mesh(mesh_for(*mesh_shape), args.groups, args.replicas)
        config["device_mesh"] = args.mesh

    tmp = tempfile.mkdtemp(prefix="tput_lat_")
    t0 = time.time()
    cluster = Cluster(args.protocol, args.replicas, tmp, config=config,
                      tick=args.tick, num_groups=args.groups)
    print(f"cluster up in {time.time() - t0:.1f}s", flush=True)

    points = []
    server_metrics = {}
    try:
        for load in [float(x) for x in args.loads.split(",")]:
            pt = run_point(cluster, args.clients, args.secs, load,
                           args.put_ratio, args.value_size,
                           args.num_keys, plan=plan)
            print(json.dumps(pt), flush=True)
            points.append(pt)
        # scrape once after the sweep: the snapshot's histograms cover
        # every load point (server-side breakdown for the curve above)
        from summerset_tpu.client.endpoint import scrape_metrics

        server_metrics = scrape_metrics(cluster.manager_addr)
    finally:
        cluster.stop()

    out = {
        "protocol": args.protocol,
        "groups": args.groups,
        "replicas": args.replicas,
        "clients": args.clients,
        "secs_per_point": args.secs,
        # workload stamp: which traffic class produced this curve (and
        # the seed/digest to regenerate the exact op streams)
        "workload": args.workload,
        "workload_seed": args.workload_seed,
        "workload_digest": plan.digest() if plan is not None else None,
        # quorum-tally transport stamp (core/quorum.py), next to the
        # mesh block like bench.py
        "tally": args.tally,
        # wire-plane stamp (utils/wirecodec.py): which frame format the
        # cluster's hot planes served this curve with
        "wire_codec": _wire_codec_on(),
        # serving-mesh stamp: which device mesh each replica's [G, R]
        # state was sharded over (None = the single-device legacy path);
        # the canonical block shared with bench.py and PROFILE.json
        "mesh": (
            mesh_stamp(mesh_shape[0], mesh_shape[1], args.groups)
            if mesh_shape is not None else None
        ),
        "points": points,
        # the artifact judges itself: a curve where nothing ever
        # committed is a failed capture even when the process exits 0
        "ok": any(p["tput"] > 0 for p in points),
        "server_metrics": server_metrics,
    }
    # graftprof analytic stamp (host-serving config variant at this
    # cluster's shape): deterministic-per-backend cost/memory/compile
    # metrics so the TPUTLAT trajectory stays comparable when the box's
    # wall-clock is noisy
    try:
        from summerset_tpu.host.profiling import protocol_analytic_block

        out["graftprof"] = protocol_analytic_block(
            args.protocol.lower(), "host", args.groups, args.replicas, 64
        )
    except Exception as e:  # the stamp must never kill the bench
        out["graftprof"] = {"error": f"{type(e).__name__}: {e}"}
    with open(args.out, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({"out": args.out, "points": len(points)}), flush=True)


if __name__ == "__main__":
    main()
