"""Measured host-path serving throughput: a real manager + N replica
event loops over localhost TCP — now with an optional compartmentalized
serving plane (``--proxies N``: stateless ingress proxies + learner read
tiers, ``summerset_tpu/host/ingress.py``) and a selector-multiplexed
client fleet that sustains >= 10k concurrent closed-loop clients on one
box (``summerset_tpu/client/muxfleet.py``; thread-per-client topped out
two orders of magnitude earlier).

The client fleet runs in SUBPROCESS workers (``--fleet-procs``) so the
serving process's GIL never pays for client-side pickling — the
committed artifact's device-tick accounting would otherwise measure the
bench, not the serving plane.

Writes HOSTBENCH.json at the repo root with an ``ok`` self-verdict
(dead backend / empty fleet / collapsed tick rate fails the artifact
loudly — the BENCH_r05 lesson), the proxy count, the per-tier shed
scrape (shard ``api_shed`` vs proxy ``proxy_shed``), and the device
tick-rate ratio against a client-free baseline window.

Usage:
    python scripts/host_bench.py [--protocol MultiPaxos] [--groups 16]
        [--clients 4] [--secs 10] [--proxies 2] [--clients 10000]
"""

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def fleet_worker(spec_json: str) -> None:
    """Subprocess mode: run one multiplexed fleet slice and print its
    JSON summary.  Deliberately imports NO jax/cluster machinery — the
    worker is a pure socket client."""
    spec = json.loads(spec_json)
    from summerset_tpu.client.muxfleet import run_fleet
    from summerset_tpu.host.workload import WorkloadPlan

    plan = None
    if spec.get("trace"):
        # trace replay: every worker normalizes the same YCSB file with
        # the same seed/clients clamp, so the fleet-wide op streams are
        # exactly the plan the parent's digest attests
        plan = WorkloadPlan.from_trace(
            spec["trace"], seed=spec["workload_seed"],
            clients=spec["plan_clients"],
        )
    elif spec.get("workload") and spec["workload"] != "uniform":
        # plan_clients is the FLEET-WIDE clamp the parent stamped the
        # digest with — a per-worker share here would generate (and
        # run) a different plan than the artifact attests
        plan = WorkloadPlan.generate(
            spec["workload_seed"], spec["workload"],
            clients=spec["plan_clients"],
            num_keys=spec["num_keys"],
        )
    out = run_fleet(
        [tuple(a) for a in spec["addrs"]],
        spec["clients"], spec["secs"],
        put_ratio=spec["put_ratio"], value_size=spec["value_size"],
        num_keys=spec["num_keys"], seed=spec["seed"],
        op_timeout=spec["op_timeout"], id_base=spec["id_base"],
        plan=plan, think=spec.get("think", 0.0),
    )
    print("FLEET_RESULT " + json.dumps(out), flush=True)


if "--fleet-worker" in sys.argv:
    fleet_worker(sys.argv[sys.argv.index("--fleet-worker") + 1])
    sys.exit(0)

# --platform must be consumed BEFORE importing jax: the platform pin only
# works pre-backend-init.  "cpu" (default) is hermetic for CI boxes;
# "preset" leaves the environment's platform alone — on a TPU host that
# is the one-command TPU-in-the-loop serving bench.
_plat = "cpu"
for _i, _a in enumerate(sys.argv[1:], 1):
    if _a == "--platform" and _i + 1 < len(sys.argv):
        _plat = sys.argv[_i + 1]
    elif _a.startswith("--platform="):
        _plat = _a.split("=", 1)[1]

import jax  # noqa: E402

if _plat != "preset":
    jax.config.update("jax_platforms", _plat)
    if _plat == "cpu":
        from summerset_tpu.utils.jaxcompat import set_cpu_devices
        set_cpu_devices(8)

sys.path.insert(0, os.path.join(REPO, "tests"))


sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def _wait_line(path: str, needle: str, timeout: float) -> bool:
    """Positional readiness tail (quiet variant of local_cluster.py's
    wait_for_line, which echoes the child log to stderr — too noisy
    for a bench that launches nine processes)."""
    deadline = time.monotonic() + timeout
    pos = 0
    buf = ""
    while time.monotonic() < deadline:
        try:
            with open(path) as f:
                f.seek(pos)
                buf += f.read()
                pos = f.tell()
        except OSError:
            pass
        if needle in buf:
            return True
        time.sleep(0.2)
    return False


class ProcCluster:
    """A REAL multi-process cluster for the bench: one manager + N
    server replica processes through the cli entries (the
    local_cluster.py shape), each with its own GIL and XLA thread pool.
    The in-process tests/test_cluster harness shares one interpreter
    across replicas — fine for correctness, but its cross-replica GIL
    contention leaks into the device-scan stopwatch this artifact
    gates, so the bench measures the deployment shape instead."""

    def __init__(self, protocol: str, n: int, tmpdir: str,
                 tick: float, groups: int, window: int = 64,
                 platform: str = "cpu"):
        from test_cluster import free_ports  # shared bench/test helper
        from local_cluster import make_cluster_env  # env lessons live there

        ports = free_ports(2 + 2 * n)
        self.srv_port, self.cli_port = ports[0], ports[1]
        self.api_ports = ports[2:2 + n]
        self.p2p_ports = ports[2 + n:]
        self.manager_addr = ("127.0.0.1", self.cli_port)
        self.tmpdir = tmpdir
        self.procs = []
        # make_cluster_env owns the sitecustomize PYTHONPATH filter (a
        # TPU-tunnel startup hook hangs every child when the tunnel is
        # down) and the cpu default; --platform preset/tpu must reach
        # the replica processes too — the scan times this artifact
        # gates are THEIRS, not the parent's
        env = make_cluster_env()
        if platform == "preset":
            if "JAX_PLATFORMS" in os.environ:
                env["JAX_PLATFORMS"] = os.environ["JAX_PLATFORMS"]
            else:
                env.pop("JAX_PLATFORMS", None)
            env["PYTHONPATH"] = os.environ.get(
                "PYTHONPATH", env.get("PYTHONPATH", "")
            ) or env.get("PYTHONPATH", "")
        elif platform != "cpu":
            env["JAX_PLATFORMS"] = platform
        man_log = os.path.join(tmpdir, "manager.log")
        self.procs.append(subprocess.Popen(
            [sys.executable, "-m", "summerset_tpu.cli.manager",
             "-p", protocol, "--srv-port", str(self.srv_port),
             "--cli-port", str(self.cli_port), "-n", str(n)],
            stdout=open(man_log, "w"), stderr=subprocess.STDOUT,
            env=env, cwd=REPO,
        ))
        if not _wait_line(man_log, "manager up", 30):
            raise RuntimeError("manager never came up")
        logs = []
        for r in range(n):
            log = os.path.join(tmpdir, f"server{r}.log")
            logs.append(log)
            self.procs.append(subprocess.Popen(
                [sys.executable, "-m", "summerset_tpu.cli.server",
                 "-p", protocol, "-a", str(self.api_ports[r]),
                 "-i", str(self.p2p_ports[r]),
                 "-m", f"127.0.0.1:{self.srv_port}",
                 "-g", str(groups), "--window", str(window),
                 "--tick-interval", str(tick),
                 "--backer-dir", tmpdir],
                stdout=open(log, "w"), stderr=subprocess.STDOUT,
                env=env, cwd=REPO,
            ))
        for log in logs:
            if not _wait_line(log, "accepting clients", 180):
                self.stop()
                raise RuntimeError(f"server never ready ({log})")

    def stop(self) -> None:
        for p in self.procs:
            p.terminate()
        for p in self.procs:
            try:
                p.wait(timeout=10)
            except Exception:
                p.kill()


def _pin_worker(fleet_cores) -> None:
    """(child preexec) Deprioritize + pin a fleet worker to the carved
    client cores so it can never contend with the serving pool."""
    try:
        os.nice(10)
        if fleet_cores and hasattr(os, "sched_setaffinity"):
            os.sched_setaffinity(0, fleet_cores)
    except OSError:
        pass


def scrape_tick_marks(manager_addr) -> dict:
    """Per-replica (tick counter, device-cost histogram count/sum_us)
    marks.  Two marks bracket a window: the tick delta over wall time is
    the LOOP rate (informational — on this in-process CPU harness the
    loop also carries the host apply/WAL stages), while the device-cost
    delta gives the DEVICE tick cost: mean device-scan duration per
    tick, the thing that must stay flat under 10k clients (serving load
    belongs to the host stages and the proxy tier, never to the scan).
    The cost source is loop-mode aware: serial replicas time the scan
    in the fused ``step`` stage; pipelined replicas (the default) pay
    it as ``dispatch`` + ``device_wait`` (launch plus residual block —
    the host-paid share of the async scan), summed per tick here so
    the flatness ratio gates BOTH modes instead of reading 0 cost off
    a pipelined replica and failing every proxied bench."""
    from summerset_tpu.client.endpoint import scrape_metrics

    snap = scrape_metrics(manager_addr, timeout=15.0)
    out = {}
    for sid, s in (snap or {}).items():
        hists = s.get("host", {}).get("histograms", {})
        step = hists.get("loop_stage_us{stage=step}") or {}
        if not step.get("count"):
            # pipelined loop: the scan cost the host pays is the async
            # launch + the drain's residual block (same count per tick)
            n = c = 0
            for st in ("dispatch", "device_wait"):
                h = hists.get("loop_stage_us{stage=%s}" % st) or {}
                c = max(c, h.get("count", 0))
                n += h.get("sum", 0)
            step = {"count": c, "sum": n}
        out[sid] = (s["tick"], step.get("count", 0), step.get("sum", 0))
    return out


def window_stats(a: dict, b: dict, dt: float):
    """(mean loop ticks/s, mean device step us/tick) across replicas
    between two scrape marks."""
    rates, steps = [], []
    for sid in a:
        if sid not in b:
            continue
        rates.append((b[sid][0] - a[sid][0]) / max(dt, 1e-9))
        dn = b[sid][1] - a[sid][1]
        ds = b[sid][2] - a[sid][2]
        if dn > 0:
            steps.append(ds / dn)
    rate = sum(rates) / len(rates) if rates else 0.0
    step = sum(steps) / len(steps) if steps else 0.0
    return rate, step


#: wire A/B throughput tolerance: the 10k-client shape is think-time
#: limited (offered rate ~constant), so codec-on tput should match
#: codec-off to box noise; the gate allows 3% jitter and the committed
#: run is expected to hold plain >=
WIRE_AB_TPUT_FRAC = 0.97


def _wire_metrics(art: dict) -> dict:
    """Distill one bench artifact's wire-plane numbers: peer-frame
    bytes per device tick (transport egress over ticks served) and the
    mean p2p serialize/deserialize cost per frame, straight off the
    committed histograms."""
    tot_bytes = tot_ticks = 0
    sums = {"enc": [0, 0], "dec": [0, 0]}
    for _sid, s in (art.get("server_metrics") or {}).items():
        host = s.get("host", {})
        for k, v in host.get("counters", {}).items():
            if k.startswith("transport_bytes_sent"):
                tot_bytes += v
        tot_ticks += s.get("tick", 0)
        for k, h in host.get("histograms", {}).items():
            if "plane=p2p" not in k:
                continue
            if k.startswith("wire_encode_us"):
                sums["enc"][0] += h["sum"]
                sums["enc"][1] += h["count"]
            elif k.startswith("wire_decode_us"):
                sums["dec"][0] += h["sum"]
                sums["dec"][1] += h["count"]
    return {
        "wire_codec": art.get("wire_codec"),
        "ok": art.get("ok"),
        "tput": art.get("tput"),
        "lat_p50_ms": art.get("lat_p50_ms"),
        "lat_p99_ms": art.get("lat_p99_ms"),
        "acked": art.get("acked"),
        "clients_concurrent_min": art.get("clients_concurrent_min"),
        "peer_bytes_per_tick": round(tot_bytes / max(tot_ticks, 1), 1),
        "encode_us_mean": round(
            sums["enc"][0] / max(sums["enc"][1], 1), 2
        ),
        "decode_us_mean": round(
            sums["dec"][0] / max(sums["dec"][1], 1), 2
        ),
        "frames_timed": sums["enc"][1],
    }


def stage_overlap_sums(server_metrics) -> tuple:
    """Sum the pipeline-attribution ``loop_stage_us`` histograms across
    one metrics scrape: returns ``(ticks, sums)`` where ``sums`` maps
    stage -> ``[us_total, count]`` for overlap/device_wait/step.  The
    ONE distillation both A/B drivers (this file and bench_tput_lat.py)
    summarize their legs with."""
    ticks = 0
    sums = {"overlap": [0, 0], "device_wait": [0, 0], "step": [0, 0]}
    for _sid, s in (server_metrics or {}).items():
        ticks += s.get("tick", 0)
        hists = s.get("host", {}).get("histograms", {})
        for name, acc in sums.items():
            h = hists.get("loop_stage_us{stage=%s}" % name)
            if h:
                acc[0] += h.get("sum", 0)
                acc[1] += h.get("count", 0)
    return ticks, sums


def _pipeline_metrics(art: dict) -> dict:
    """Distill one bench artifact's pipeline-plane numbers: steady tput
    plus the overlap attribution straight off the committed
    ``loop_stage_us`` histograms — ``overlap`` is host-stage time spent
    while a device step was in flight (the pipelining win), and
    ``device_wait`` is the host's residual blocked share at drain."""
    ticks, sums = stage_overlap_sums(art.get("server_metrics"))
    return {
        "pipeline": art.get("pipeline"),
        "ok": art.get("ok"),
        "tput": art.get("tput"),
        "lat_p50_ms": art.get("lat_p50_ms"),
        "lat_p99_ms": art.get("lat_p99_ms"),
        "acked": art.get("acked"),
        "workload_digest": art.get("workload_digest"),
        "ticks": ticks,
        "overlap_us_total": sums["overlap"][0],
        "overlap_us_per_tick": round(
            sums["overlap"][0] / max(sums["overlap"][1], 1), 1
        ),
        "device_wait_us_mean": round(
            sums["device_wait"][0] / max(sums["device_wait"][1], 1), 1
        ),
        "serial_step_us_mean": round(
            sums["step"][0] / max(sums["step"][1], 1), 1
        ),
    }


def check_pipeline_ab_core(on: dict, off: dict, tput_key: str,
                           tput_name: str) -> list:
    """The ONE set of pipelined-loop A/B inequalities, shared by the
    HOSTBENCH block (``tput_key="tput"``) and the TPUTLAT block
    (``tput_key="sat_tput"``; bench_tput_lat.py): honest loop-mode
    labels, both legs ok, same workload digest, pipelined throughput
    STRICTLY above serial, measured overlap (host-stage time coincident
    with the in-flight device step) > 0 pipelined and absent serial."""
    fails = []
    if on.get("pipeline") is not True or off.get("pipeline") is not False:
        fails.append("pipeline_ab: runs not labeled pipeline on/off")
    for side, sub in (("on", on), ("off", off)):
        if not sub.get("ok"):
            fails.append(f"pipeline_ab: pipeline-{side} bench not ok")
    dig_on, dig_off = on.get("workload_digest"), off.get("workload_digest")
    if dig_on is None or dig_on != dig_off:
        fails.append(
            f"pipeline_ab: workload digests differ or missing "
            f"({dig_on} vs {dig_off})"
        )
    t_on = on.get(tput_key) or 0.0
    t_off = off.get(tput_key) or 0.0
    if not t_on > t_off:
        fails.append(
            f"pipeline_ab: pipelined {tput_name} {t_on} not strictly "
            f"above serial {t_off}"
        )
    if not (on.get("overlap_us_total") or 0) > 0:
        fails.append("pipeline_ab: no measured overlap on the "
                     "pipelined side")
    if (off.get("overlap_us_total") or 0) > 0:
        fails.append("pipeline_ab: serial side recorded overlap "
                     "(loop mode labels are wrong)")
    return fails


def check_pipeline_ab(block: dict) -> list:
    """The HOSTBENCH pipelined-loop A/B gate (shared with
    workload_gate.py) — see :func:`check_pipeline_ab_core`."""
    return check_pipeline_ab_core(
        block.get("on") or {}, block.get("off") or {},
        "tput", "tput",
    )


def _median(xs):
    xs = sorted(xs)
    n = len(xs)
    return xs[n // 2] if n % 2 else (xs[n // 2 - 1] + xs[n // 2]) / 2


def summarize_ab_side(per: list) -> dict:
    """Summarize one A/B side's per-round leg metrics: EVERY numeric
    field is a true per-key median (an arbitrary round's value beside
    genuine medians would present one possibly-outlier round as the
    summary), ``ok``/labels/digest must agree across rounds, and the
    raw rounds ride along for provenance.  Shared by both pipelined-
    loop A/B drivers (this file and bench_tput_lat.py)."""
    med: dict = {}
    for key in per[0]:
        vals = [p.get(key) for p in per]
        if all(isinstance(v, (int, float)) and not isinstance(v, bool)
               for v in vals):
            m = _median(vals)
            med[key] = round(m, 3) if isinstance(m, float) else m
        else:
            # non-numeric (mode labels, digest, ok): all rounds must
            # agree — a per-round mismatch is a broken A/B, surfaced
            # by the core checks downstream
            med[key] = vals[0] if all(v == vals[0] for v in vals) \
                else None
    med["ok"] = all(p.get("ok") for p in per)
    med["rounds"] = per
    return med


def run_pipeline_ab(args) -> None:
    """Parent mode: run the full bench as INTERLEAVED serial/pipelined
    round pairs (``SMR_PIPELINE`` into every child tier; the leg order
    alternates per round), same workload seed/digest every leg, and
    commit the gated A/B block into the existing artifact (the body
    itself is NOT replaced: the committed HOSTBENCH body stays the
    canonical 10k-client capture).

    Interleaved pairs + per-side medians are the PERF round-8 A/B
    discipline: a single off-then-on pair is exposed to monotonic box
    drift (the second leg always runs on a slower box — measured
    swinging the verdict by more than the effect under test), while
    alternating pairs put the drift on both sides and the median
    discards the outlier round."""
    child_argv = [sys.executable, os.path.abspath(__file__)]
    skip = 0
    for a in sys.argv[1:]:
        if skip:
            skip -= 1
            continue
        if a == "--pipeline-ab":
            continue
        if a in ("--out", "--ab-rounds"):
            skip = 1
            continue
        if a.startswith(("--out=", "--ab-rounds=")):
            continue
        child_argv.append(a)
    rounds = {"on": [], "off": []}
    tmp = tempfile.mkdtemp(prefix="pipeline_ab_")
    try:
        for rnd in range(args.ab_rounds):
            order = ("off", "on") if rnd % 2 == 0 else ("on", "off")
            for mode in order:
                out = os.path.join(tmp, f"hostbench_{mode}_{rnd}.json")
                env = dict(os.environ)
                env["SMR_PIPELINE"] = "1" if mode == "on" else "0"
                print(f"=== pipeline_ab round {rnd}: pipeline {mode} "
                      f"run ===", flush=True)
                r = subprocess.run(
                    child_argv + ["--out", out], env=env, cwd=REPO,
                )
                if not os.path.exists(out):
                    print(f"pipeline_ab: pipeline-{mode} round {rnd} "
                          f"produced no artifact (rc={r.returncode})",
                          flush=True)
                    sys.exit(1)
                with open(out) as f:
                    rounds[mode].append(json.load(f))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    sides = {
        mode: summarize_ab_side([_pipeline_metrics(r) for r in runs_m])
        for mode, runs_m in rounds.items()
    }
    first = rounds["on"][0]
    block = {
        "clients": first.get("clients"),
        "proxies": first.get("proxies"),
        "protocol": first.get("protocol"),
        "groups": first.get("groups"),
        "workload": first.get("workload"),
        "workload_seed": first.get("workload_seed"),
        "ab_rounds": args.ab_rounds,
        "on": sides["on"],
        "off": sides["off"],
    }
    fails = check_pipeline_ab(block)
    block["ok"] = not fails
    block["failures"] = fails
    art = {}
    if os.path.exists(args.out):
        try:
            with open(args.out) as f:
                art = json.load(f)
        except Exception:
            art = {}
    art["pipeline_ab"] = block
    with open(args.out, "w") as f:
        json.dump(art, f, indent=1)
    print("pipeline_ab: " + json.dumps(
        {k: v for k, v in block.items() if k != "failures"} | {
            "failures": fails,
        }
    ), flush=True)
    sys.exit(0 if block["ok"] else 1)


def check_wire_ab(block: dict) -> list:
    """The codec A/B inequalities (shared with workload_gate.py):
    peer-frame bytes/tick and p2p encode+decode us/op STRICTLY down
    codec-on vs codec-off, steady tput held, both runs ok."""
    on, off = block.get("on") or {}, block.get("off") or {}
    fails = []
    if on.get("wire_codec") is not True or off.get("wire_codec") \
            is not False:
        fails.append("wire_ab: runs not labeled codec on/off")
    for side, sub in (("on", on), ("off", off)):
        if not sub.get("ok"):
            fails.append(f"wire_ab: codec-{side} bench not ok")
    for key in ("peer_bytes_per_tick", "encode_us_mean",
                "decode_us_mean"):
        a, b = on.get(key), off.get(key)
        if a is None or b is None or not a < b:
            fails.append(
                f"wire_ab: {key} not strictly down ({a} vs {b})"
            )
    t_on, t_off = on.get("tput") or 0.0, off.get("tput") or 0.0
    if t_on < WIRE_AB_TPUT_FRAC * t_off:
        fails.append(
            f"wire_ab: codec-on tput {t_on} below codec-off {t_off}"
        )
    return fails


def run_wire_ab(args) -> None:
    """Parent mode: run the full bench twice as subprocesses — codec
    off then on, flipped through SMR_WIRE_CODEC so the replica, proxy,
    AND fleet processes all follow — and commit the gated A/B block.
    The codec-on run's full artifact becomes the new HOSTBENCH body
    (codec-on is the serving default), with ``wire_ab`` (and any
    committed ``wire_bench`` block) carried alongside."""
    child_argv = [
        sys.executable, os.path.abspath(__file__),
    ]
    skip = 0
    for a in sys.argv[1:]:
        if skip:
            skip -= 1
            continue
        if a == "--wire-ab":
            continue
        if a == "--out":
            skip = 1
            continue
        if a.startswith("--out="):
            continue
        child_argv.append(a)
    runs = {}
    tmp = tempfile.mkdtemp(prefix="wire_ab_")
    for mode in ("off", "on"):
        out = os.path.join(tmp, f"hostbench_{mode}.json")
        env = dict(os.environ)
        env["SMR_WIRE_CODEC"] = "1" if mode == "on" else "0"
        print(f"=== wire_ab: codec {mode} run ===", flush=True)
        r = subprocess.run(
            child_argv + ["--out", out], env=env, cwd=REPO,
        )
        if not os.path.exists(out):
            print(f"wire_ab: codec-{mode} run produced no artifact "
                  f"(rc={r.returncode})", flush=True)
            sys.exit(1)
        with open(out) as f:
            runs[mode] = json.load(f)
    block = {
        "clients": runs["on"].get("clients"),
        "proxies": runs["on"].get("proxies"),
        "protocol": runs["on"].get("protocol"),
        "groups": runs["on"].get("groups"),
        "on": _wire_metrics(runs["on"]),
        "off": _wire_metrics(runs["off"]),
    }
    fails = check_wire_ab(block)
    block["ok"] = not fails
    block["failures"] = fails
    art = dict(runs["on"])
    prev = {}
    if os.path.exists(args.out):
        try:
            with open(args.out) as f:
                prev = json.load(f)
        except Exception:
            prev = {}
    if "wire_bench" in prev:
        art["wire_bench"] = prev["wire_bench"]
    art["wire_ab"] = block
    with open(args.out, "w") as f:
        json.dump(art, f, indent=1)
    print("wire_ab: " + json.dumps(
        {k: v for k, v in block.items() if k != "failures"} | {
            "failures": fails,
        }
    ), flush=True)
    sys.exit(0 if block["ok"] else 1)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--protocol", default="MultiPaxos")
    ap.add_argument("--groups", type=int, default=16)
    ap.add_argument("--window", type=int, default=64,
                    help="per-group W-slot device window (the G x W "
                         "product sets the device-scan weight per "
                         "tick; the pipeline A/B runs a scan-heavy "
                         "shape so the overlap is measurable on CPU)")
    ap.add_argument("--replicas", type=int, default=3)
    ap.add_argument("--clients", type=int, default=4)
    ap.add_argument("--secs", type=float, default=10.0)
    ap.add_argument("--tick", type=float, default=0.002)
    ap.add_argument("--platform", default="cpu",
                    help="jax platform pin; 'preset' keeps the env's "
                         "backend (run on a TPU host for the "
                         "TPU-in-the-loop serving measurement)")
    ap.add_argument("--num-keys", type=int, default=64)
    ap.add_argument("--value-size", type=int, default=64)
    ap.add_argument("--put-ratio", type=float, default=0.5)
    ap.add_argument("--workload", default="uniform",
                    help="workload class (host/workload.py); uniform = "
                         "the legacy bench mix so default trajectories "
                         "stay comparable")
    ap.add_argument("--workload-seed", type=int, default=1)
    ap.add_argument("--trace", default="",
                    help="YCSB trace file replayed byte-reproducibly "
                         "via WorkloadPlan.from_trace (plan digest + "
                         "parsed-row sha stamped into the artifact); "
                         "overrides --workload")
    ap.add_argument("--proxies", type=int, default=0,
                    help="ingress proxies in front of the shards "
                         "(0 = fused single-process serving, the "
                         "default and the committed-trajectory mode)")
    ap.add_argument("--fleet-procs", type=int, default=0,
                    help="subprocess fleet workers (0 = auto: 1 for "
                         "small fleets, 4 from 1000 clients up)")
    ap.add_argument("--op-timeout", type=float, default=5.0)
    ap.add_argument("--think", type=float, default=0.0,
                    help="per-client think time between ops (jittered; "
                         "10k clients at think=30 offer ~330 ops/s — "
                         "the connection-scaling run controls offered "
                         "rate instead of saturating)")
    ap.add_argument("--tick-budget", type=float, default=0.9,
                    help="min loaded/baseline device tick-rate ratio "
                         "for the ok verdict when proxies are up")
    ap.add_argument("--wire-ab", action="store_true",
                    help="run the whole bench twice — wire codec off "
                         "then on (SMR_WIRE_CODEC into every child "
                         "tier) — and commit the gated A/B block "
                         "(bytes/tick + serialize us/op strictly "
                         "down, tput held)")
    ap.add_argument("--pipeline-ab", action="store_true",
                    help="run the whole bench as interleaved serial/"
                         "pipelined round pairs (SMR_PIPELINE into "
                         "every child tier) and commit the gated A/B "
                         "block (same workload digest, median pipelined "
                         "tput strictly up, measured overlap > 0)")
    ap.add_argument("--ab-rounds", type=int, default=3,
                    help="interleaved A/B round pairs for --pipeline-ab "
                         "(medians gate; order alternates per round "
                         "against box drift)")
    ap.add_argument("--out", default=os.path.join(REPO, "HOSTBENCH.json"))
    args = ap.parse_args()

    if args.wire_ab:
        run_wire_ab(args)
        return
    if args.pipeline_ab:
        run_pipeline_ab(args)
        return

    from summerset_tpu.client.endpoint import scrape_metrics
    from summerset_tpu.host.workload import WorkloadPlan

    # CPU isolation for the co-located bench (deployment runs proxies +
    # clients on separate hosts): carve the box so the fleet/proxy
    # processes cannot contend with the serving process's XLA thread
    # pool — the device-scan flatness this artifact gates would
    # otherwise measure core theft by the bench's own client tier.
    # Must happen BEFORE the first jax backend touch (pool sizing).
    fleet_cores = None
    try:
        all_cores = sorted(os.sched_getaffinity(0))
        if args.proxies > 0 and len(all_cores) >= 8:
            split = max(4, len(all_cores) // 4)
            fleet_cores = set(all_cores[-split:])
            os.sched_setaffinity(0, set(all_cores[:-split]))
            print(f"cpu carve: serving {len(all_cores) - split} cores, "
                  f"fleet+proxies {split}", flush=True)
    except (AttributeError, OSError):
        pass

    plan_clients = max(4, min(64, args.clients))
    plan_digest = None
    trace_sha = None
    if args.trace:
        _tp = WorkloadPlan.from_trace(
            args.trace, seed=args.workload_seed, clients=plan_clients,
        )
        plan_digest = _tp.digest()
        trace_sha = _tp.trace_sha()
        args.workload = "trace"
    elif args.workload != "uniform":
        plan_digest = WorkloadPlan.generate(
            args.workload_seed, args.workload,
            clients=plan_clients, num_keys=args.num_keys,
        ).digest()

    tmp = tempfile.mkdtemp(prefix="host_bench_")
    t0 = time.time()
    cluster = ProcCluster(
        args.protocol, args.replicas, tmp,
        tick=args.tick, groups=args.groups, window=args.window,
        platform=_plat,
    )
    print(f"cluster up in {time.time() - t0:.1f}s "
          f"({args.replicas} replica processes x {args.groups} groups)",
          flush=True)

    plane = None
    if args.proxies > 0:
        from summerset_tpu.host.ingress import ServingPlane

        # process mode: the proxies are REAL separate processes (the
        # deployment shape) — the serving process's GIL never pays for
        # the 10k-socket client plane
        plane = ServingPlane(
            cluster.manager_addr, proxies=args.proxies,
            mode="process", cpus=fleet_cores,
        ).start()
        print(f"serving plane up: {args.proxies} proxy processes @ "
              f"{plane.addrs}", flush=True)
        targets = plane.addrs
    else:
        targets = [("127.0.0.1", p) for p in cluster.api_ports]

    # warm the jit path first — an un-warmed baseline measures XLA
    # compile time, not the serving tick
    from summerset_tpu.client.drivers import DriverClosedLoop
    from summerset_tpu.client.endpoint import GenericEndpoint

    wep = GenericEndpoint(cluster.manager_addr)
    wep.connect()
    DriverClosedLoop(wep, timeout=30.0).checked_put("warm", "1")
    wep.leave()

    # client-free baseline window: same scrape, same window shape as
    # the loaded measurement below
    m0 = scrape_tick_marks(cluster.manager_addr)
    t_b0 = time.monotonic()
    time.sleep(4.0)
    m1 = scrape_tick_marks(cluster.manager_addr)
    base_rate, base_step = window_stats(
        m0, m1, time.monotonic() - t_b0
    )
    print(f"client-free: loop {base_rate:.1f} ticks/s, device scan "
          f"{base_step:.0f} us/tick", flush=True)

    procs = args.fleet_procs or (4 if args.clients >= 1000 else 1)
    procs = max(1, min(procs, args.clients))
    share = [args.clients // procs] * procs
    for i in range(args.clients % procs):
        share[i] += 1
    workers = []
    for w, n in enumerate(share):
        spec = {
            "addrs": [list(a) for a in targets],
            "clients": n,
            "secs": args.secs,
            "put_ratio": args.put_ratio,
            "value_size": args.value_size,
            "num_keys": args.num_keys,
            "seed": args.workload_seed * 131 + w,
            "op_timeout": args.op_timeout,
            "id_base": 10_000_000 + w * 1_000_000,
            "plan_clients": plan_clients,
            "think": args.think,
            "workload": args.workload,
            "workload_seed": args.workload_seed,
            "trace": args.trace or None,
        }
        workers.append(subprocess.Popen(
            [sys.executable, os.path.abspath(__file__),
             "--fleet-worker", json.dumps(spec)],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            text=True, cwd=REPO,
            # the client fleet must never steal CPU from the device
            # scan it is measuring — same-box co-location is a bench
            # convenience, not the deployment shape
            preexec_fn=(lambda fc=fleet_cores: _pin_worker(fc)),
        ))
    # loaded tick window: marks snapped AFTER the connect storm settles
    # (a one-time fleet ramp is not the steady serving state the 10%
    # budget is about), closed before the fleet drains
    settle = min(3.0, args.secs / 3)
    time.sleep(settle)
    marks_a = scrape_tick_marks(cluster.manager_addr)
    t_load0 = time.monotonic()
    time.sleep(max(0.5, args.secs - settle - 1.0))
    marks_b = scrape_tick_marks(cluster.manager_addr)
    t_load = time.monotonic() - t_load0
    results = []
    for p in workers:
        out, _ = p.communicate(timeout=args.secs + 120)
        for line in (out or "").splitlines():
            if line.startswith("FLEET_RESULT "):
                results.append(json.loads(line[len("FLEET_RESULT "):]))
    loaded_rate, loaded_step = window_stats(marks_a, marks_b, t_load)
    # interleaved post-baseline (the PERF round-8 A/B discipline): a
    # single pre-baseline is exposed to slow system drift (freq
    # scaling, cache state) over the minutes between windows; the
    # client-free reference is the FASTER of the windows bracketing the
    # loaded one, so drift shows up as noise, not as a phantom slowdown
    time.sleep(1.0)
    m2 = scrape_tick_marks(cluster.manager_addr)
    t_p0 = time.monotonic()
    time.sleep(4.0)
    m3 = scrape_tick_marks(cluster.manager_addr)
    _post_rate, post_step = window_stats(
        m2, m3, time.monotonic() - t_p0
    )
    if post_step > 0:
        # the SLOWER client-free window is the drift-honest reference:
        # if the whole box slowed between windows, the post-baseline
        # slowed with it and the ratio isolates the client effect; if
        # clients alone slowed the scan, the post-baseline recovers and
        # the ratio still catches it
        base_step = max(base_step, post_step)
    # the gated ratio: DEVICE scan throughput (1 / mean step-stage
    # duration) under full client load vs client-free.  The serving
    # plane's claim is that client fan-in rides the host tiers (proxy
    # processes + the host intake/log/apply stages), never the device
    # scan itself — the loop wall rate is stamped alongside for
    # transparency but on this in-process CPU harness it also carries
    # the host apply/WAL stages, which grow with throughput by design.
    tick_ratio = (
        base_step / loaded_step
        if loaded_step > 0 and base_step > 0 else 0.0
    )

    tput = sum(r["tput"] for r in results)
    acked = sum(r["acked"] for r in results)
    connected = sum(r["connected_peak"] for r in results)
    # per-worker minima sum to a lower bound of SIMULTANEOUS
    # concurrency over the whole post-ramp window — peaks taken at
    # different instants would overstate it
    connected_min = sum(r.get("connected_min", 0) for r in results)
    p50 = max((r["lat_p50_ms"] for r in results), default=0.0)
    p99 = max((r["lat_p99_ms"] for r in results), default=0.0)

    # per-tier shed attribution: shard api_shed off the post-run scrape,
    # proxy proxy_shed off the in-process plane handles
    server_metrics = scrape_metrics(cluster.manager_addr)
    shard_shed = {
        sid: snap.get("host", {}).get("counters", {}).get("api_shed", 0)
        for sid, snap in (server_metrics or {}).items()
    }
    proxy_scrape = plane.scrape() if plane is not None else {}
    proxy_shed = (
        plane.shed_counts() if plane is not None else {}
    )

    failures = []
    if _plat != "preset" and jax.devices()[0].platform != _plat:
        failures.append("backend mismatch")
    if not results or acked <= 0 or tput <= 0:
        failures.append("no acked ops (dead serving path)")
    if connected_min < 0.95 * args.clients:
        failures.append(
            f"fleet under target: only {connected_min}/{args.clients} "
            "simultaneously established through the window"
        )
    if args.proxies > 0 and tick_ratio < args.tick_budget:
        failures.append(
            f"device scan slowed under clients: "
            f"{tick_ratio:.2f}x baseline throughput "
            f"< {args.tick_budget}"
        )

    from summerset_tpu.host.server import pipeline_default
    from summerset_tpu.utils import wirecodec

    out = {
        "protocol": args.protocol,
        "groups": args.groups,
        "replicas": args.replicas,
        "clients": args.clients,
        "wire_codec": wirecodec.default_on(),
        "pipeline": pipeline_default(),
        "clients_concurrent_peak": connected,
        "clients_concurrent_min": connected_min,
        "fleet": "mux",             # selector-multiplexed closed loop
        "fleet_procs": procs,
        "proxies": args.proxies,
        "secs": args.secs,
        "think_s": args.think,
        "platform": jax.devices()[0].platform,
        "workload": args.workload,
        "workload_seed": args.workload_seed,
        "workload_digest": plan_digest,
        # trace replay stamp: raw YCSB file + parsed-row sha — the same
        # trace must reproduce the same plan digest on any box
        "trace_file": args.trace or None,
        "trace_sha": trace_sha,
        "tput": round(tput, 2),
        "lat_p50_ms": round(p50, 3),
        "lat_p99_ms": round(p99, 3),
        "issued": sum(r["issued"] for r in results),
        "acked": acked,
        "shed": sum(r["shed"] for r in results),
        "timeouts": sum(r["timeouts"] for r in results),
        # device-plane accounting: serving must ride on top of a live
        # tick, not displace it — the compartmentalization claim is
        # client fan-in WITHOUT device-plane cost
        "tick_rate_baseline": round(base_rate, 2),
        "tick_rate_loaded": round(loaded_rate, 2),
        "device_step_us_baseline": round(base_step, 1),
        "device_step_us_loaded": round(loaded_step, 1),
        "tick_ratio": round(tick_ratio, 3),
        "tick_budget": args.tick_budget,
        # per-tier shed attribution (the compartmentalization receipt:
        # with proxies up, overload lands on the proxy tier)
        "api_shed": shard_shed,
        "proxy_shed": proxy_shed,
        "proxy_metrics": {
            pid: {
                "counters": snap["host"]["counters"],
                "gauges": snap["host"]["gauges"],
            }
            for pid, snap in proxy_scrape.items()
        },
        "ok": not failures,
        "failures": failures,
        "server_metrics": server_metrics,
    }
    # preserve sibling blocks other tools commit into this artifact
    # (wire_bench microbench rows, the wire_ab parent's A/B block)
    if os.path.exists(args.out):
        try:
            with open(args.out) as f:
                prev = json.load(f)
            for k in ("wire_bench", "wire_ab", "pipeline_ab"):
                if k in prev:
                    out[k] = prev[k]
        except Exception:
            pass
    with open(args.out, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({
        k: v for k, v in out.items()
        if k not in ("server_metrics", "proxy_metrics")
    }), flush=True)
    if plane is not None:
        plane.stop()
    cluster.stop()
    if failures:
        print(f"HOSTBENCH NOT OK: {failures}", flush=True)
        os._exit(1)
    os._exit(0)


if __name__ == "__main__":
    main()
