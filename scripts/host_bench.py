"""Measured host-path throughput: a real manager + N replica event loops
over localhost TCP sockets, G consensus groups served end-to-end, driven
by open-loop ClientBench clients (VERDICT r3 #5: publish a real-socket
ops/sec number; parity: summerset_client/src/clients/bench.rs:44-130).

Writes HOSTBENCH.json at the repo root:
  {"protocol", "groups", "clients", "tput", "lat_p50_ms", "lat_p99_ms"}

Usage: python scripts/host_bench.py [--protocol MultiPaxos] [--groups 16]
       [--clients 4] [--secs 10] [--tick 0.002]
"""

import argparse
import json
import os
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# --platform must be consumed BEFORE importing jax: the platform pin only
# works pre-backend-init.  "cpu" (default) is hermetic for CI boxes;
# "preset" leaves the environment's platform alone — on a TPU host that
# is the one-command TPU-in-the-loop serving bench (the kernel ticks on
# the chip while the client/WAL/apply planes run host-side).
_plat = "cpu"
for _i, _a in enumerate(sys.argv[1:], 1):
    if _a == "--platform" and _i + 1 < len(sys.argv):
        _plat = sys.argv[_i + 1]
    elif _a.startswith("--platform="):
        _plat = _a.split("=", 1)[1]

import jax

if _plat != "preset":
    jax.config.update("jax_platforms", _plat)
    if _plat == "cpu":
        from summerset_tpu.utils.jaxcompat import set_cpu_devices
        set_cpu_devices(8)

sys.path.insert(0, os.path.join(REPO, "tests"))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--protocol", default="MultiPaxos")
    ap.add_argument("--groups", type=int, default=16)
    ap.add_argument("--replicas", type=int, default=3)
    ap.add_argument("--clients", type=int, default=4)
    ap.add_argument("--secs", type=float, default=10.0)
    ap.add_argument("--tick", type=float, default=0.002)
    ap.add_argument("--platform", default="cpu",
                    help="jax platform pin; 'preset' keeps the env's "
                         "backend (run on a TPU host for the "
                         "TPU-in-the-loop serving measurement)")
    ap.add_argument("--num-keys", type=int, default=64)
    ap.add_argument("--value-size", default="64")
    ap.add_argument("--put-ratio", type=float, default=0.5)
    ap.add_argument("--workload", default="uniform",
                    help="workload class (host/workload.py "
                         "WORKLOAD_CLASSES); uniform = the legacy "
                         "bench mix, so default trajectories stay "
                         "comparable")
    ap.add_argument("--workload-seed", type=int, default=1)
    ap.add_argument("--out", default=os.path.join(REPO, "HOSTBENCH.json"))
    args = ap.parse_args()

    from test_cluster import Cluster  # reuses the in-process harness
    from summerset_tpu.client.bench import ClientBench
    from summerset_tpu.client.endpoint import (
        GenericEndpoint, scrape_metrics,
    )
    from summerset_tpu.host.workload import WorkloadPlan

    plan = None
    if args.workload != "uniform":
        plan = WorkloadPlan.generate(
            args.workload_seed, args.workload, clients=args.clients,
            num_keys=args.num_keys,
        )

    tmp = tempfile.mkdtemp(prefix="host_bench_")
    t0 = time.time()
    cluster = Cluster(
        args.protocol, args.replicas, tmp,
        tick=args.tick, num_groups=args.groups,
    )
    print(f"cluster up in {time.time() - t0:.1f}s "
          f"({args.replicas} replicas x {args.groups} groups)", flush=True)

    results = [None] * args.clients

    def one_client(i: int) -> None:
        ep = GenericEndpoint(cluster.manager_addr)
        ep.connect()
        bench = ClientBench(
            ep,
            secs=args.secs,
            put_ratio=args.put_ratio,
            value_size=args.value_size,
            num_keys=args.num_keys,
            interval=1e9,  # suppress per-interval prints
            seed=i,
            opgen=plan.opstream(i) if plan is not None else None,
        )
        results[i] = bench.run()
        ep.leave()

    threads = [
        threading.Thread(target=one_client, args=(i,), daemon=True)
        for i in range(args.clients)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=args.secs + 60)

    done = [r for r in results if r]
    tput = sum(r["tput"] for r in done)
    p50 = max(r["lat_p50_ms"] for r in done) if done else 0.0
    p99 = max(r["lat_p99_ms"] for r in done) if done else 0.0
    out = {
        "protocol": args.protocol,
        "groups": args.groups,
        "replicas": args.replicas,
        "clients": len(done),
        "secs": args.secs,
        "platform": jax.devices()[0].platform,
        # workload stamp: which traffic class produced this number
        "workload": args.workload,
        "workload_seed": args.workload_seed,
        "workload_digest": plan.digest() if plan is not None else None,
        "tput": round(tput, 2),
        "lat_p50_ms": round(p50, 3),
        "lat_p99_ms": round(p99, 3),
        # server-side breakdown: the metrics_dump scrape (device metric
        # lanes + host histograms incl. fsync/request latency/loop
        # stages + sampled ticks-to-commit) rides the committed artifact
        # so the client percentiles above carry their own explanation
        "server_metrics": scrape_metrics(cluster.manager_addr),
    }
    with open(args.out, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({k: v for k, v in out.items()
                      if k != "server_metrics"}), flush=True)
    cluster.stop()


if __name__ == "__main__":
    main()
