#!/usr/bin/env python3
"""Workload x nemesis joint soak: seeded adversarial traffic against a
live cluster, with overload survival asserted end to end.

Per (protocol, workload class, seed) cell:

1. bring up an in-process cluster (the tier-2 harness from
   tests/test_cluster.py) with a DELIBERATELY small ingress tier:
   ``api_max_batch`` caps what one tick drains, which pins the ingress
   capacity at ``api_max_batch / tick`` ops/s, and ``api_max_pending``
   bounds the queue so overload must surface as explicit shedding;
2. generate the seed's ``WorkloadPlan`` (zipfian hot keys, mixes, value
   sizes, multi-tenant ranges, open-loop burst phases) and — for joint
   cells — a ``FaultPlan`` (partition / drop / one_way) sharing the
   same logical tick axis; both regenerate byte-identically (the repro
   contract);
3. drive open-loop recorder clients through the plan's arrival phases
   (``hot_burst`` offers ~2x ingress capacity mid-run) while the
   nemesis schedule plays; overload rows additionally crash the LIVE
   leader mid-burst (queried at fire time — a seeded plan cannot know
   election outcomes);
4. assert: linearizability of the recorded history (shed puts excluded
   on the server's never-proposed guarantee — a get observing a shed
   value FAILS), visible-and-bounded shedding on overload rows (client
   sheds > 0, server ``api_shed`` > 0, progress still made, and no
   value both acked and shed), bounded accepted-op p99 through the
   burst, throughput recovery to the pre-burst steady state, and a
   bounded post-heal recovery write.

Results land in WORKLOADS.json (gated by scripts/workload_gate.py: per
-seed digest drift, shed > 0 on overload rows, class coverage).  On
failure both timelines + the executed fault log + the full operation
history are dumped next to ``--out``; re-running with the same seeds
replays identical schedules.

Usage:
    python scripts/workload_soak.py                   # the overload row
    python scripts/workload_soak.py --matrix          # full joint matrix
    python scripts/workload_soak.py --protocol Raft \\
        --wl-class hot_burst --seed 2
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
from summerset_tpu.utils.jaxcompat import set_cpu_devices  # noqa: E402

set_cpu_devices(8)

sys.path.insert(0, os.path.join(REPO, "tests"))

# the joint acceptance matrix: every non-uniform workload class at least
# once, two overload (hot_burst) rows across protocol families.  Row
# shape: (protocol, wl_class, workload seed, fault seed | None).
# hot_burst rows are the OVERLOAD rows: burst ~2x ingress capacity +
# a live leader crash mid-burst; they must shed visibly.
WL_MATRIX = (
    ("MultiPaxos", "read_mostly", 1, 1),
    ("MultiPaxos", "write_heavy", 2, 2),
    ("MultiPaxos", "value_mix", 3, None),
    ("MultiPaxos", "multi_tenant", 2, 3),
    ("MultiPaxos", "hot_burst", 1, 1),
    ("Raft", "hot_burst", 2, 2),
)
# message-plane fault classes for the joint cells (crash pressure comes
# from the explicit mid-burst leader crash instead of the generator:
# manager-serialized crash-restarts are wall-heavy and would slide the
# whole burst window)
FAULT_CLASSES = ("partition", "drop", "one_way")

# ingress tier sizing: api_max_batch caps per-tick drain, so the
# NOMINAL capacity is API_MAX_BATCH / tick — but on a loaded CI box the
# effective tick is compute-bound well past its interval, so the soak
# MEASURES the real drain rate (calibrate_capacity) and scales the
# plan's rate_x phases against that: "2x ingress capacity" means 2x
# what this box actually drains, on every box.  The queue bound is
# small so a 2x burst (net fill ~= capacity) overflows it — and starts
# shedding — within the first second of the burst, BEFORE the leader
# crash stirs election noise into the window.
API_MAX_BATCH = 2
API_MAX_PENDING = 8
# shared with scripts/workload_gate.py (digest regeneration)
DEFAULT_CLIENTS = 3
DEFAULT_KEYS = 24
DEFAULT_HORIZON = 120      # workload/fault schedule ticks
DEFAULT_TICK_LEN = 0.1     # wall seconds per schedule tick
DEFAULT_BUDGET_TICKS = 4000
P99_BUDGET_S = 3.5         # accepted-op p99 bound through the burst
RECOVER_FRAC = 0.5         # post-burst tput must reach this x steady


def protocol_config(protocol: str) -> dict:
    cfg = {"api_max_batch": API_MAX_BATCH,
           "api_max_pending": API_MAX_PENDING}
    if protocol in ("RSPaxos", "CRaft", "Crossword"):
        cfg["fault_tolerance"] = 0
    return cfg


def build_plans(protocol: str, wl_class: str, seed: int,
                fault_seed, replicas: int):
    """The cell's two schedules — one seeded generator call each, so
    the gate can regenerate digests without a cluster."""
    from summerset_tpu.host.nemesis import FaultPlan
    from summerset_tpu.host.workload import WorkloadPlan

    wplan = WorkloadPlan.generate(
        seed, wl_class, clients=DEFAULT_CLIENTS,
        num_keys=DEFAULT_KEYS, horizon=DEFAULT_HORIZON,
    )
    fplan = None
    if fault_seed is not None:
        fplan = FaultPlan.generate(
            fault_seed, replicas, DEFAULT_HORIZON,
            classes=FAULT_CLASSES,
        )
    return wplan, fplan


def calibrate_capacity(manager_addr, clients: int, secs: float = 2.5,
                       flood: float = 800.0,
                       timeout: float = 5.0) -> float:
    """Measured ingress capacity: open-loop put flood on dedicated
    ``cal*`` keys (disjoint from every workload key, so the checked
    history never observes calibration values); with the bounded queue
    saturated, the acked rate over the tail window IS the serving
    path's drain rate on this box."""
    import random

    from summerset_tpu.client.drivers import DriverOpenLoopPaced
    from summerset_tpu.client.endpoint import GenericEndpoint

    acks = [0] * clients
    t_end = time.monotonic() + secs
    t_meas = time.monotonic() + 0.5  # let the queue fill first

    def one(ci: int) -> None:
        rng = random.Random(1000 + ci)
        try:
            ep = GenericEndpoint(manager_addr)
            ep.connect()
        except Exception:
            return
        drv = DriverOpenLoopPaced(ep, timeout=timeout, seed=ci)
        t_next = time.monotonic()
        while True:
            now = time.monotonic()
            if now >= t_end:
                break
            drv.expired()
            if now >= t_next:
                if not drv.gated(now):
                    drv.issue("put", f"cal{ci}",
                              f"cal-{ci}-{drv.next_req}")
                t_next = now + rng.expovariate(flood / clients)
            for info, rep in drv.poll(
                min(max(t_next - now, 0.0005), 0.01)
            ):
                if rep.kind == "success" and now >= t_meas:
                    acks[ci] += 1
        try:
            ep.leave()
        except Exception:
            pass

    ths = [threading.Thread(target=one, args=(ci,), daemon=True)
           for ci in range(clients)]
    for t in ths:
        t.start()
    for t in ths:
        t.join(timeout=secs + timeout + 10)
    return max(sum(acks) / max(secs - 0.5, 0.1), 5.0)


def phase_window(wplan, idx: int, t0: float, tick_len: float):
    p = wplan.phases[idx]
    return (t0 + p.tick * tick_len,
            t0 + (p.tick + p.ticks) * tick_len)


def accepted_in(ops, lo: float, hi: float):
    return [o for o in ops
            if o.acked and not o.shed and lo <= o.t_resp < hi]


def p99(xs):
    if not xs:
        return 0.0
    s = sorted(xs)
    return s[min(len(s) - 1, int(len(s) * 0.99))]


def fail_bundle_doc(result: dict, wplan, fplan, runner, ops) -> dict:
    return {
        **result,
        "workload_timeline": wplan.timeline(),
        "fault_timeline": fplan.timeline() if fplan else None,
        "executed": runner.executed if runner is not None else [],
        "history": [
            {
                "client": o.client, "kind": o.kind, "key": o.key,
                "value": o.value, "t_inv": o.t_inv,
                "t_resp": (
                    None if o.t_resp == float("inf") else o.t_resp
                ),
                "acked": o.acked, "shed": o.shed,
            }
            for o in sorted(ops, key=lambda o: o.t_inv)
        ],
    }


def run_one(protocol: str, wl_class: str, seed: int, fault_seed,
            args) -> dict:
    from test_cluster import Cluster

    from summerset_tpu.client.drivers import DriverClosedLoop
    from summerset_tpu.client.endpoint import (
        GenericEndpoint, scrape_metrics,
    )
    from summerset_tpu.client.tester import start_workload_clients
    from summerset_tpu.host.messages import CtrlRequest
    from summerset_tpu.host.nemesis import NemesisRunner
    from summerset_tpu.utils.linearize import check_history

    wplan, fplan = build_plans(
        protocol, wl_class, seed, fault_seed, args.replicas
    )
    # the repro contract: same seeds -> byte-identical timelines
    w2, f2 = build_plans(
        protocol, wl_class, seed, fault_seed, args.replicas
    )
    assert wplan.timeline() == w2.timeline(), "non-deterministic wplan!"
    assert fplan is None or fplan.timeline() == f2.timeline()
    overload = wl_class == "hot_burst"
    cap_nominal = API_MAX_BATCH / args.tick  # ops/s if ticks were free
    print(f"--- {protocol} {wl_class} seed={seed} "
          f"wdigest={wplan.digest()} "
          f"fdigest={fplan.digest() if fplan else None} "
          f"nominal_capacity={cap_nominal:.0f}/s")
    print(wplan.timeline(), end="")
    if fplan is not None:
        print(fplan.timeline(), end="")

    tmp = tempfile.mkdtemp(
        prefix=f"wlsoak_{protocol.lower()}_{wl_class}_{seed}_"
    )
    result = {
        "protocol": protocol, "wl_class": wl_class, "seed": seed,
        "fault_seed": fault_seed, "wl_digest": wplan.digest(),
        "fault_digest": fplan.digest() if fplan else None,
        "overload": overload, "ok": False,
    }
    cluster = None
    stop = threading.Event()
    ops: list = []
    stats: list = []
    threads: list = []
    runner = None
    nem_thread = None
    try:
        cluster = Cluster(
            protocol, args.replicas, tmp,
            config=protocol_config(protocol), tick=args.tick,
        )
        # warm the jit path before the schedule clock starts
        wep = GenericEndpoint(cluster.manager_addr)
        wep.connect()
        DriverClosedLoop(wep, timeout=10.0).checked_put("warm", "1")
        wep.leave()

        # measured ingress capacity: the plan's rate_x multipliers are
        # relative to what THIS box actually drains, so the burst is
        # genuinely ~2x capacity whether the tick runs at its interval
        # or compute-bound past it
        cap = calibrate_capacity(
            cluster.manager_addr, wplan.clients,
            timeout=args.op_timeout,
        )
        result["capacity_ops_s"] = round(cap, 1)
        result["capacity_nominal_ops_s"] = cap_nominal
        print(f"calibrated ingress capacity: {cap:.1f} ops/s "
              f"(nominal {cap_nominal:.0f})")
        # let the calibration flood's queued tail drain before the
        # schedule clock starts, or steady-phase latencies inherit it
        time.sleep(min(2.0, API_MAX_PENDING / cap + 0.3))

        t0 = time.monotonic()

        def rate_total_of() -> float:
            tick = (time.monotonic() - t0) / args.tick_len
            return wplan.rate_x_at(tick) * cap

        threads = start_workload_clients(
            cluster.manager_addr, wplan, rate_total_of, stop, ops,
            stats, timeout=args.op_timeout,
        )
        if fplan is not None:
            runner = NemesisRunner(
                cluster.manager_addr, fplan, tick_len=args.tick_len,
            )
            nem_thread = threading.Thread(
                target=runner.play, daemon=True
            )
            nem_thread.start()
        crash_log: list = []
        if overload:
            # live leader crash mid-burst: the victim is whoever leads
            # AT FIRE TIME (a seeded plan cannot know election
            # outcomes), so the crash is guaranteed to hit the serving
            # path while the queue is at ~2x capacity
            burst = wplan.phases[1]
            # ~1.2s into the burst: the bounded queue has demonstrably
            # overflowed (shed onset ~ API_MAX_PENDING / capacity into
            # the burst) before the crash lands on top of it
            fire_at = t0 + (burst.tick + 12) * args.tick_len

            def crash_leader() -> None:
                lag = fire_at - time.monotonic()
                if lag > 0:
                    time.sleep(lag)
                try:
                    # burst-peak scrape FIRST: the victim's api_shed
                    # counter dies with its incarnation, so the
                    # while-overloaded evidence must be captured before
                    # the crash wipes it
                    pre = scrape_metrics(
                        cluster.manager_addr, timeout=10.0
                    )
                    result["api_shed_pre"] = {
                        sid: snap.get("host", {})
                                 .get("counters", {})
                                 .get("api_shed", 0)
                        for sid, snap in (pre or {}).items()
                    }
                    ep = GenericEndpoint(cluster.manager_addr)
                    info = ep.ctrl.request(CtrlRequest("query_info"))
                    victim = (
                        info.leader if info.leader is not None
                        else sorted(info.servers)[0]
                    )
                    crash_log.append(
                        {"victim": victim,
                         "at_tick": round(
                             (time.monotonic() - t0) / args.tick_len,
                             1)}
                    )
                    ep.ctrl.request(
                        CtrlRequest("reset_servers", servers=[victim],
                                    durable=True),
                        timeout=240.0,
                    )
                    ep.ctrl.close()
                except Exception as e:
                    crash_log.append({"error": repr(e)})

            ct = threading.Thread(target=crash_leader, daemon=True)
            ct.start()
            threads.append(ct)

        horizon_s = wplan.horizon() * args.tick_len
        time.sleep(max(0.0, t0 + horizon_s - time.monotonic()))
        time.sleep(2.0)   # drain inflight past the horizon
        stop.set()
        for t in threads:
            t.join(timeout=60)
        if nem_thread is not None:
            nem_thread.join(timeout=120)
        if runner is not None:
            runner.heal_all()
        result["leader_crash"] = crash_log

        # bounded recovery: a checked write within the tick budget
        t_heal = time.monotonic()
        budget_s = args.budget_ticks * args.tick
        rep = GenericEndpoint(cluster.manager_addr)
        rep.connect()
        drv = DriverClosedLoop(rep, timeout=min(5.0, budget_s))
        recovered = False
        while time.monotonic() - t_heal < budget_s:
            r = drv.put("wl_recovery", f"s{seed}")
            if r.kind == "success":
                recovered = True
                break
            drv._retry_pause(r)
        recovery_s = time.monotonic() - t_heal
        rep.leave()
        result["recovery_ticks"] = int(recovery_s / args.tick)
        if not recovered:
            result["error"] = (
                f"no recovery within {args.budget_ticks} ticks"
            )
            return result

        # ------------------------------------------------ verdict math
        result["num_ops"] = len(ops)
        result["clients"] = sorted(stats, key=lambda s: s["ci"])
        issued = sum(s["issued"] for s in stats)
        acked = sum(s["acked"] for s in stats)
        shed = sum(s["shed"] for s in stats)
        held = sum(s["held"] for s in stats)
        result["issued"], result["acked"] = issued, acked
        result["shed"], result["held"] = shed, held
        # server-side shed accounting: the api_shed counters must agree
        # that shedding happened (scraped full, committed trimmed)
        full = scrape_metrics(cluster.manager_addr)
        api_shed = {}
        for sid, snap in (full or {}).items():
            ctr = snap.get("host", {}).get("counters", {})
            api_shed[sid] = ctr.get("api_shed", 0)
        result["api_shed"] = api_shed
        result["server_metrics"] = {
            sid: {
                "tick": snap["tick"],
                "counters": {
                    k: v
                    for k, v in snap["host"]["counters"].items()
                    if k.startswith("api_")
                },
                "histograms": {
                    k: v
                    for k, v in snap["host"]["histograms"].items()
                    if k.split("{", 1)[0] in (
                        "api_request_latency_us", "ticks_to_commit",
                    )
                },
            }
            for sid, snap in (full or {}).items()
        }
        if len(ops) < args.min_ops:
            result["error"] = f"history too small: {len(ops)}"
            return result
        if acked == 0:
            result["error"] = "no op ever acked"
            return result

        # no ack lost to a shed: a value must never be both acked and
        # negatively acked (values are globally unique per op instance,
        # so any overlap is a protocol bug, not a collision)
        acked_vals = {o.value for o in ops
                      if o.kind == "put" and o.acked and not o.shed}
        shed_vals = {o.value for o in ops if o.shed}
        overlap = acked_vals & shed_vals
        result["ack_shed_overlap"] = len(overlap)
        if overlap:
            result["error"] = (
                f"{len(overlap)} values both acked and shed: "
                f"{sorted(overlap)[:4]}"
            )
            return result

        # phase stats: steady / (burst / recover for overload rows)
        win_steady = phase_window(wplan, 0, t0, args.tick_len)
        # skip the first 20% of steady: election/jit settling
        s_lo = win_steady[0] + 0.2 * (win_steady[1] - win_steady[0])
        steady_acc = accepted_in(ops, s_lo, win_steady[1])
        steady_tput = len(steady_acc) / max(win_steady[1] - s_lo, 1e-9)
        result["steady_tput"] = round(steady_tput, 1)
        # the steady phases offer rate_x[0] x capacity on both sides of
        # the burst; recovery is judged against this OFFERED rate (the
        # measured steady window carries calibration-drain transients
        # and, at these op counts, real expovariate noise)
        offered_steady = wplan.phases[0].rate_x * cap
        result["offered_steady"] = round(offered_steady, 1)
        lat_all = [o.t_resp - o.t_inv
                   for o in ops if o.acked and not o.shed]
        result["p99_s"] = round(p99(lat_all), 3)
        if overload:
            win_burst = phase_window(wplan, 1, t0, args.tick_len)
            win_rec = phase_window(wplan, 2, t0, args.tick_len)
            burst_acc = accepted_in(ops, *win_burst)
            result["burst_tput"] = round(
                len(burst_acc)
                / max(win_burst[1] - win_burst[0], 1e-9), 1)
            burst_lat = [o.t_resp - o.t_inv for o in burst_acc
                         if win_burst[0] <= o.t_inv]
            result["burst_p99_s"] = round(p99(burst_lat), 3)
            # recovery tail: the last 40% of the recover phase, clear
            # of the crash-election window at its start
            r_lo = win_rec[0] + 0.6 * (win_rec[1] - win_rec[0])
            rec_acc = accepted_in(ops, r_lo, win_rec[1])
            rec_tput = len(rec_acc) / max(win_rec[1] - r_lo, 1e-9)
            result["recover_tput"] = round(rec_tput, 1)

            # server-visible shedding: the post-run scrape PLUS the
            # burst-peak scrape taken just before the leader crash
            # (the victim's counter does not survive its restart)
            server_shed = sum(api_shed.values()) + sum(
                (result.get("api_shed_pre") or {}).values()
            )
            if shed == 0 or server_shed == 0:
                result["error"] = (
                    "overload row shed nothing: client sheds "
                    f"{shed}, server api_shed {api_shed} "
                    f"(pre-crash {result.get('api_shed_pre')})"
                )
                return result
            if len(burst_acc) < 10:
                # crash + election eat a slice of the burst; what must
                # hold is PROGRESS, not a tput floor (the tput floor is
                # the recover-phase assertion below)
                result["error"] = (
                    f"burst made no progress: {len(burst_acc)} acked"
                )
                return result
            if shed >= issued:
                result["error"] = "everything shed, nothing served"
                return result
            if result["burst_p99_s"] > args.p99_budget:
                result["error"] = (
                    f"accepted-op p99 {result['burst_p99_s']}s over "
                    f"budget {args.p99_budget}s through the burst"
                )
                return result
            if rec_tput < args.recover_frac * offered_steady:
                result["error"] = (
                    f"throughput did not recover: {rec_tput:.1f}/s "
                    f"tail vs {offered_steady:.1f}/s offered steady "
                    f"(need >= {args.recover_frac}x)"
                )
                return result

        ok, diag = check_history(ops)
        result["ok"] = bool(ok)
        if not ok:
            result["error"] = diag
        return result
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
        if not result["ok"] and runner is not None:
            result["flight"] = runner.flight_tails(last_n=256)
        if runner is not None:
            runner.close()
        if cluster is not None:
            cluster.stop()
        if not result["ok"]:
            dump = os.path.splitext(args.out)[0] + (
                f"_{protocol}_{wl_class}_s{seed}_fail.json"
            )
            with open(dump, "w") as f:
                json.dump(
                    fail_bundle_doc(result, wplan, fplan, runner, ops),
                    f, indent=1,
                )
            print(f"FAIL bundle -> {dump}")
        shutil.rmtree(tmp, ignore_errors=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--protocol", default="MultiPaxos")
    ap.add_argument("--wl-class", default="hot_burst")
    ap.add_argument("--seed", type=int, default=1)
    ap.add_argument("--matrix", action="store_true",
                    help="run the full joint matrix (WL_MATRIX)")
    ap.add_argument("--replicas", type=int, default=3)
    ap.add_argument("--tick", type=float, default=0.005,
                    help="server tick interval (with api_max_batch="
                         f"{API_MAX_BATCH} this pins ingress capacity)")
    ap.add_argument("--tick-len", type=float, default=DEFAULT_TICK_LEN,
                    help="wall seconds per workload/fault tick")
    ap.add_argument("--op-timeout", type=float, default=5.0)
    ap.add_argument("--min-ops", type=int, default=60)
    ap.add_argument("--p99-budget", type=float, default=P99_BUDGET_S)
    ap.add_argument("--recover-frac", type=float, default=RECOVER_FRAC)
    ap.add_argument("--budget-ticks", type=int,
                    default=DEFAULT_BUDGET_TICKS)
    ap.add_argument("--out",
                    default=os.path.join(REPO, "WORKLOADS.json"))
    args = ap.parse_args()

    if args.matrix:
        runs = list(WL_MATRIX)
    else:
        match = [
            row for row in WL_MATRIX
            if row[0] == args.protocol and row[1] == args.wl_class
            and row[2] == args.seed
        ]
        runs = match or [
            (args.protocol, args.wl_class, args.seed, args.seed)
        ]
    results = []
    for protocol, wl_class, seed, fseed in runs:
        r = run_one(protocol, wl_class, seed, fseed, args)
        status = "PASS" if r["ok"] else f"FAIL ({r.get('error')})"
        print(f"=== {protocol} {wl_class} seed={seed}: {status} "
              f"(ops={r.get('num_ops')}, acked={r.get('acked')}, "
              f"shed={r.get('shed')}, p99={r.get('p99_s')}s)")
        results.append(r)
    with open(args.out, "w") as f:
        json.dump(results, f, indent=1)
    print(f"wrote {args.out}")
    sys.stdout.flush()
    sys.stderr.flush()
    # hard exit: same rationale as nemesis_soak (daemon replica threads
    # frozen mid-XLA can std::terminate after results are written)
    os._exit(0 if all(r["ok"] for r in results) else 1)


if __name__ == "__main__":
    main()
