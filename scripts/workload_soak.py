#!/usr/bin/env python3
"""Workload x nemesis joint soak: seeded adversarial traffic against a
live cluster, with overload survival asserted end to end.

Per (protocol, workload class, seed) cell:

1. bring up an in-process cluster (the tier-2 harness from
   tests/test_cluster.py) with a DELIBERATELY small ingress tier:
   ``api_max_batch`` caps what one tick drains, which pins the ingress
   capacity at ``api_max_batch / tick`` ops/s, and ``api_max_pending``
   bounds the queue so overload must surface as explicit shedding;
2. generate the seed's ``WorkloadPlan`` (zipfian hot keys, mixes, value
   sizes, multi-tenant ranges, open-loop burst phases) and — for joint
   cells — a ``FaultPlan`` (partition / drop / one_way) sharing the
   same logical tick axis; both regenerate byte-identically (the repro
   contract);
3. drive open-loop recorder clients through the plan's arrival phases
   (``hot_burst`` offers ~2x ingress capacity mid-run) while the
   nemesis schedule plays; overload rows additionally crash the LIVE
   leader mid-burst (queried at fire time — a seeded plan cannot know
   election outcomes);
4. assert: linearizability of the recorded history (shed puts excluded
   on the server's never-proposed guarantee — a get observing a shed
   value FAILS), visible-and-bounded shedding on overload rows (client
   sheds > 0, server ``api_shed`` > 0, progress still made, and no
   value both acked and shed), bounded accepted-op p99 through the
   burst, throughput recovery to the pre-burst steady state, and a
   bounded post-heal recovery write.

Results land in WORKLOADS.json (gated by scripts/workload_gate.py: per
-seed digest drift, shed > 0 on overload rows, class coverage).  On
failure both timelines + the executed fault log + the full operation
history are dumped next to ``--out``; re-running with the same seeds
replays identical schedules.

Usage:
    python scripts/workload_soak.py                   # the overload row
    python scripts/workload_soak.py --matrix          # full joint matrix
    python scripts/workload_soak.py --protocol Raft \\
        --wl-class hot_burst --seed 2
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
from summerset_tpu.utils.jaxcompat import set_cpu_devices  # noqa: E402

set_cpu_devices(8)

sys.path.insert(0, os.path.join(REPO, "tests"))

# the joint acceptance matrix: every non-uniform workload class at least
# once, two overload (hot_burst) rows across protocol families.  Row
# shape: (protocol, wl_class, workload seed, fault seed | None).
# hot_burst rows are the OVERLOAD rows: burst ~2x ingress capacity +
# a live leader crash mid-burst; they must shed visibly.
WL_MATRIX = (
    ("MultiPaxos", "read_mostly", 1, 1),
    ("MultiPaxos", "write_heavy", 2, 2),
    ("MultiPaxos", "value_mix", 3, None),
    ("MultiPaxos", "multi_tenant", 2, 3),
    ("MultiPaxos", "hot_burst", 1, 1),
    ("Raft", "hot_burst", 2, 2),
)
# message-plane fault classes for the joint cells (crash pressure comes
# from the explicit mid-burst leader crash instead of the generator:
# manager-serialized crash-restarts are wall-heavy and would slide the
# whole burst window)
FAULT_CLASSES = ("partition", "drop", "one_way")

# ingress tier sizing: api_max_batch caps per-tick drain, so the
# NOMINAL capacity is API_MAX_BATCH / tick — but on a loaded CI box the
# effective tick is compute-bound well past its interval, so the soak
# MEASURES the real drain rate (calibrate_capacity) and scales the
# plan's rate_x phases against that: "2x ingress capacity" means 2x
# what this box actually drains, on every box.  The queue bound is
# small so a 2x burst (net fill ~= capacity) overflows it — and starts
# shedding — within the first second of the burst, BEFORE the leader
# crash stirs election noise into the window.
API_MAX_BATCH = 2
API_MAX_PENDING = 8

# ---- compartmentalized serving plane (host/ingress.py) --------------
# One overload cell runs behind ingress proxies WITH a mid-burst
# proxy_crash (kill + restart; clients rediscover via the manager
# re-announce), and the proxy_ab row measures the fused-vs-proxy shed
# point on the same WorkloadPlan digest.  The proxy knobs are sized so
# the tier's capacity gain over the fused shard is REAL but bounded —
# bounded forward batches and upstream windows mean a sustained ramp
# must eventually shed AT THE PROXY (front door), which is exactly the
# attribution the A/B asserts: api_shed stays on the floor while
# proxy_shed absorbs the overload.
PROXY_CELL = ("MultiPaxos", "hot_burst")   # the proxied overload cell
PROXY_COUNT = 2
PROXY_CFG = {
    "forward_batch": 8,     # cmds per forwarded batch (fan-in factor)
    "upstream_window": 2,   # un-acked batches per shard
    "max_pending": 16,      # proxy front-door queue bound
    "backlog_limit": 8,     # internal forward backlog bound
}
# proxy_crash timing inside the proxied cell: derived from the wplan's
# burst phase (deterministic per seed — the gate regenerates the digest
# with the same formula), restart after ~1s of schedule time
PROXY_CRASH_OFFSET = 6
PROXY_CRASH_RESTART = 10
# proxy_ab: the shed-point ramp sweeps offered rate from 1x to
# RAMP_MAX_X the FUSED calibrated capacity across the burst window;
# shed point := offered rate at the first client-observed shed
AB_SEED = 1
RAMP_MAX_X = 8.0
PROXY_AB_MIN_RATIO = 1.5
# ---- live resharding A/B (host/resharding.py) -----------------------
# The reshard_ab row runs the hot_burst overload cell TWICE over a
# 4-group keyspace on the same WorkloadPlan digest — resharding off,
# then on with the heat-driven ResharderPolicy live — while the cell's
# message-plane FaultPlan plays in both modes.  The "on" run must
# execute >= 1 live split and >= 1 live merge through the seal/adopt
# cutover with zero acked-and-shed overlap and the fused p99/recovery
# budgets held in BOTH modes (sheds allowed during cutover, lost acks
# never).  The policy consumes per-interval heat DELTAS (cumulative
# counts never cool; the delta is the live "cold" signal).
RESHARD_GROUPS = 4
RESHARD_HOT_FRAC = 0.15    # split when a key draws this much heat
RESHARD_COLD_FRAC = 0.05   # merge a moved key back below this
RESHARD_SCRAPE_S = 1.2     # policy scrape/decide interval
# ---- ordered range reads (ycsb_e / trace / scan_reshard cells) ------
# The scan cells exercise the range-read plane end to end: ycsb_e and
# trace run QuorumLeases behind a learner-read-tier proxy (scans must
# be VISIBLY served lease-local: read_tier_scans > 0), scan_reshard
# runs YCSB-E traffic over a 4-group keyspace and splits a hot range
# mid-scan-storm over the ctrl plane (>= 1 executed split, zero values
# both acked and shed, both histories linearizable-with-sheds).  The
# trace cell replays the committed fixture below; same bytes => same
# normalized rows => same plan digest, enforced live AND by the gate.
SCAN_SEED = 2              # ycsb_e cell's workload seed
TRACE_SEED = 1             # trace cell's client-stride salt
SCAN_RESHARD_SEED = 3      # scan_reshard cell's workload seed
SCAN_PROXIES = 1           # learner read tier size for the QL cells
TRACE_FILE = os.path.join("scripts", "data", "ycsb_e_sample.trace")
SCAN_CELL_KINDS = ("ycsb_e", "trace", "scan_reshard")
# shared with scripts/workload_gate.py (digest regeneration)
DEFAULT_CLIENTS = 3
DEFAULT_KEYS = 24
DEFAULT_HORIZON = 120      # workload/fault schedule ticks
DEFAULT_TICK_LEN = 0.1     # wall seconds per schedule tick
DEFAULT_BUDGET_TICKS = 4000
P99_BUDGET_S = 3.5         # accepted-op p99 bound through the burst
RECOVER_FRAC = 0.5         # post-burst tput must reach this x steady


def protocol_config(protocol: str) -> dict:
    cfg = {"api_max_batch": API_MAX_BATCH,
           "api_max_pending": API_MAX_PENDING}
    if protocol in ("RSPaxos", "CRaft", "Crossword"):
        cfg["fault_tolerance"] = 0
    return cfg


def build_plans(protocol: str, wl_class: str, seed: int,
                fault_seed, replicas: int):
    """The cell's two schedules — one seeded generator call each, so
    the gate can regenerate digests without a cluster."""
    from summerset_tpu.host.nemesis import FaultPlan
    from summerset_tpu.host.workload import WorkloadPlan

    wplan = WorkloadPlan.generate(
        seed, wl_class, clients=DEFAULT_CLIENTS,
        num_keys=DEFAULT_KEYS, horizon=DEFAULT_HORIZON,
    )
    fplan = None
    if fault_seed is not None:
        fplan = FaultPlan.generate(
            fault_seed, replicas, DEFAULT_HORIZON,
            classes=FAULT_CLASSES,
        )
    return wplan, fplan


def build_proxy_plan(protocol: str, wl_class: str, seed: int,
                     replicas: int):
    """The proxied overload cell's proxy_crash plan, derived
    deterministically from the cell's own WorkloadPlan (crash lands
    mid-burst) — regenerable by the gate without a cluster."""
    from summerset_tpu.host.nemesis import FaultPlan
    from summerset_tpu.host.workload import WorkloadPlan

    wplan = WorkloadPlan.generate(
        seed, wl_class, clients=DEFAULT_CLIENTS,
        num_keys=DEFAULT_KEYS, horizon=DEFAULT_HORIZON,
    )
    burst = wplan.phases[1]
    return FaultPlan.proxy_crash(
        seed, replicas, DEFAULT_HORIZON, proxies=PROXY_COUNT,
        at=burst.tick + PROXY_CRASH_OFFSET,
        restart_after=PROXY_CRASH_RESTART,
    )


def calibrate_capacity(manager_addr, clients: int, secs: float = 2.5,
                       flood: float = 800.0,
                       timeout: float = 5.0) -> float:
    """Measured ingress capacity: open-loop put flood on dedicated
    ``cal*`` keys (disjoint from every workload key, so the checked
    history never observes calibration values); with the bounded queue
    saturated, the acked rate over the tail window IS the serving
    path's drain rate on this box."""
    import random

    from summerset_tpu.client.drivers import DriverOpenLoopPaced
    from summerset_tpu.client.endpoint import GenericEndpoint

    acks = [0] * clients
    t_end = time.monotonic() + secs
    t_meas = time.monotonic() + 0.5  # let the queue fill first

    def one(ci: int) -> None:
        rng = random.Random(1000 + ci)
        try:
            ep = GenericEndpoint(manager_addr)
            ep.connect()
        except Exception:
            return
        drv = DriverOpenLoopPaced(ep, timeout=timeout, seed=ci)
        t_next = time.monotonic()
        while True:
            now = time.monotonic()
            if now >= t_end:
                break
            drv.expired()
            if now >= t_next:
                if not drv.gated(now):
                    drv.issue("put", f"cal{ci}",
                              f"cal-{ci}-{drv.next_req}")
                t_next = now + rng.expovariate(flood / clients)
            for info, rep in drv.poll(
                min(max(t_next - now, 0.0005), 0.01)
            ):
                if rep.kind == "success" and now >= t_meas:
                    acks[ci] += 1
        try:
            ep.leave()
        except Exception:
            pass

    ths = [threading.Thread(target=one, args=(ci,), daemon=True)
           for ci in range(clients)]
    for t in ths:
        t.start()
    for t in ths:
        t.join(timeout=secs + timeout + 10)
    return max(sum(acks) / max(secs - 0.5, 0.1), 5.0)


def phase_window(wplan, idx: int, t0: float, tick_len: float):
    p = wplan.phases[idx]
    return (t0 + p.tick * tick_len,
            t0 + (p.tick + p.ticks) * tick_len)


def accepted_in(ops, lo: float, hi: float):
    return [o for o in ops
            if o.acked and not o.shed and lo <= o.t_resp < hi]


def p99(xs):
    if not xs:
        return 0.0
    s = sorted(xs)
    return s[min(len(s) - 1, int(len(s) * 0.99))]


def fail_bundle_doc(result: dict, wplan, fplan, runner, ops) -> dict:
    return {
        **result,
        "workload_timeline": wplan.timeline(),
        "fault_timeline": fplan.timeline() if fplan else None,
        "executed": runner.executed if runner is not None else [],
        "history": [
            {
                "client": o.client, "kind": o.kind, "key": o.key,
                "value": o.value, "t_inv": o.t_inv,
                "t_resp": (
                    None if o.t_resp == float("inf") else o.t_resp
                ),
                "acked": o.acked, "shed": o.shed,
            }
            for o in sorted(ops, key=lambda o: o.t_inv)
        ],
    }


def run_one(protocol: str, wl_class: str, seed: int, fault_seed,
            args) -> dict:
    from test_cluster import Cluster

    from summerset_tpu.client.drivers import DriverClosedLoop
    from summerset_tpu.client.endpoint import (
        GenericEndpoint, scrape_metrics,
    )
    from summerset_tpu.client.tester import start_workload_clients
    from summerset_tpu.host.messages import CtrlRequest
    from summerset_tpu.host.nemesis import NemesisRunner
    from summerset_tpu.utils.linearize import check_history

    wplan, fplan = build_plans(
        protocol, wl_class, seed, fault_seed, args.replicas
    )
    # the repro contract: same seeds -> byte-identical timelines
    w2, f2 = build_plans(
        protocol, wl_class, seed, fault_seed, args.replicas
    )
    assert wplan.timeline() == w2.timeline(), "non-deterministic wplan!"
    assert fplan is None or fplan.timeline() == f2.timeline()
    overload = wl_class == "hot_burst"
    cap_nominal = API_MAX_BATCH / args.tick  # ops/s if ticks were free
    print(f"--- {protocol} {wl_class} seed={seed} "
          f"wdigest={wplan.digest()} "
          f"fdigest={fplan.digest() if fplan else None} "
          f"nominal_capacity={cap_nominal:.0f}/s")
    print(wplan.timeline(), end="")
    if fplan is not None:
        print(fplan.timeline(), end="")

    # the proxied overload cell: ingress proxies in front of the shards
    # plus a mid-burst proxy kill/restart (clients rediscover through
    # the manager re-announce) — the serving-plane split under the SAME
    # schedule digests as the fused cells
    proxied = (protocol, wl_class) == PROXY_CELL
    pplan = (
        build_proxy_plan(protocol, wl_class, seed, args.replicas)
        if proxied else None
    )

    tmp = tempfile.mkdtemp(
        prefix=f"wlsoak_{protocol.lower()}_{wl_class}_{seed}_"
    )
    result = {
        "protocol": protocol, "wl_class": wl_class, "seed": seed,
        "fault_seed": fault_seed, "wl_digest": wplan.digest(),
        "fault_digest": fplan.digest() if fplan else None,
        "overload": overload, "ok": False,
        "proxies": PROXY_COUNT if proxied else 0,
        "proxy_fault_digest": pplan.digest() if pplan else None,
    }
    cluster = None
    plane = None
    stop = threading.Event()
    ops: list = []
    stats: list = []
    threads: list = []
    runner = None
    prunner = None
    nem_thread = None
    try:
        cluster = Cluster(
            protocol, args.replicas, tmp,
            config=protocol_config(protocol), tick=args.tick,
        )
        if proxied:
            from summerset_tpu.host.ingress import ServingPlane

            plane = ServingPlane(
                cluster.manager_addr, proxies=PROXY_COUNT,
                proxy_config=dict(PROXY_CFG),
            ).start()
            print(f"serving plane up: {PROXY_COUNT} proxies "
                  f"(crash plan {pplan.digest()})")
        # warm the jit path before the schedule clock starts
        wep = GenericEndpoint(cluster.manager_addr)
        wep.connect()
        DriverClosedLoop(wep, timeout=10.0).checked_put("warm", "1")
        wep.leave()

        # measured ingress capacity: the plan's rate_x multipliers are
        # relative to what THIS box actually drains, so the burst is
        # genuinely ~2x capacity whether the tick runs at its interval
        # or compute-bound past it
        cap = calibrate_capacity(
            cluster.manager_addr, wplan.clients,
            timeout=args.op_timeout,
        )
        result["capacity_ops_s"] = round(cap, 1)
        result["capacity_nominal_ops_s"] = cap_nominal
        print(f"calibrated ingress capacity: {cap:.1f} ops/s "
              f"(nominal {cap_nominal:.0f})")
        # let the calibration flood's queued tail drain before the
        # schedule clock starts, or steady-phase latencies inherit it
        time.sleep(min(2.0, API_MAX_PENDING / cap + 0.3))

        t0 = time.monotonic()

        def rate_total_of() -> float:
            tick = (time.monotonic() - t0) / args.tick_len
            return wplan.rate_x_at(tick) * cap

        threads = start_workload_clients(
            cluster.manager_addr, wplan, rate_total_of, stop, ops,
            stats, timeout=args.op_timeout,
        )
        if fplan is not None:
            runner = NemesisRunner(
                cluster.manager_addr, fplan, tick_len=args.tick_len,
            )
            nem_thread = threading.Thread(
                target=runner.play, daemon=True
            )
            nem_thread.start()
        if pplan is not None:
            prunner = NemesisRunner(
                cluster.manager_addr, pplan, tick_len=args.tick_len,
            )

            def _proxy_ctl(action: str, spec: dict) -> None:
                for idx in spec.get("proxies", ()):
                    i = int(idx) % PROXY_COUNT
                    if action == "proxy_crash":
                        plane.crash_proxy(i)
                    else:
                        plane.restart_proxy(i)

            prunner.proxy_ctl = _proxy_ctl
            pthread = threading.Thread(target=prunner.play, daemon=True)
            pthread.start()
            threads.append(pthread)
        crash_log: list = []
        if overload:
            # live leader crash mid-burst: the victim is whoever leads
            # AT FIRE TIME (a seeded plan cannot know election
            # outcomes), so the crash is guaranteed to hit the serving
            # path while the queue is at ~2x capacity
            burst = wplan.phases[1]
            # ~1.2s into the burst: the bounded queue has demonstrably
            # overflowed (shed onset ~ API_MAX_PENDING / capacity into
            # the burst) before the crash lands on top of it
            fire_at = t0 + (burst.tick + 12) * args.tick_len

            def crash_leader() -> None:
                lag = fire_at - time.monotonic()
                if lag > 0:
                    time.sleep(lag)
                try:
                    # burst-peak scrape FIRST: the victim's api_shed
                    # counter dies with its incarnation, so the
                    # while-overloaded evidence must be captured before
                    # the crash wipes it
                    pre = scrape_metrics(
                        cluster.manager_addr, timeout=10.0
                    )
                    result["api_shed_pre"] = {
                        sid: snap.get("host", {})
                                 .get("counters", {})
                                 .get("api_shed", 0)
                        for sid, snap in (pre or {}).items()
                    }
                    if plane is not None:
                        # likewise the proxy tier's burst-peak sheds —
                        # the proxy_crash victim's counter dies with
                        # its incarnation exactly like the leader's
                        result["proxy_shed_pre"] = plane.shed_counts()
                    ep = GenericEndpoint(cluster.manager_addr)
                    info = ep.ctrl.request(CtrlRequest("query_info"))
                    victim = (
                        info.leader if info.leader is not None
                        else sorted(info.servers)[0]
                    )
                    crash_log.append(
                        {"victim": victim,
                         "at_tick": round(
                             (time.monotonic() - t0) / args.tick_len,
                             1)}
                    )
                    ep.ctrl.request(
                        CtrlRequest("reset_servers", servers=[victim],
                                    durable=True),
                        timeout=240.0,
                    )
                    ep.ctrl.close()
                except Exception as e:
                    crash_log.append({"error": repr(e)})

            ct = threading.Thread(target=crash_leader, daemon=True)
            ct.start()
            threads.append(ct)

        horizon_s = wplan.horizon() * args.tick_len
        time.sleep(max(0.0, t0 + horizon_s - time.monotonic()))
        time.sleep(2.0)   # drain inflight past the horizon
        stop.set()
        for t in threads:
            t.join(timeout=60)
        if nem_thread is not None:
            nem_thread.join(timeout=120)
        if runner is not None:
            runner.heal_all()
        result["leader_crash"] = crash_log

        # bounded recovery: a checked write within the tick budget
        t_heal = time.monotonic()
        budget_s = args.budget_ticks * args.tick
        rep = GenericEndpoint(cluster.manager_addr)
        rep.connect()
        drv = DriverClosedLoop(rep, timeout=min(5.0, budget_s))
        recovered = False
        while time.monotonic() - t_heal < budget_s:
            r = drv.put("wl_recovery", f"s{seed}")
            if r.kind == "success":
                recovered = True
                break
            drv._retry_pause(r)
        recovery_s = time.monotonic() - t_heal
        rep.leave()
        result["recovery_ticks"] = int(recovery_s / args.tick)
        if not recovered:
            result["error"] = (
                f"no recovery within {args.budget_ticks} ticks"
            )
            return result

        # ------------------------------------------------ verdict math
        result["num_ops"] = len(ops)
        result["clients"] = sorted(stats, key=lambda s: s["ci"])
        issued = sum(s["issued"] for s in stats)
        acked = sum(s["acked"] for s in stats)
        shed = sum(s["shed"] for s in stats)
        held = sum(s["held"] for s in stats)
        result["issued"], result["acked"] = issued, acked
        result["shed"], result["held"] = shed, held
        # server-side shed accounting: the api_shed counters must agree
        # that shedding happened (scraped full, committed trimmed)
        from nemesis_soak import fleet_summary
        fleet_summary(cluster.manager_addr)
        full = scrape_metrics(cluster.manager_addr)
        api_shed = {}
        for sid, snap in (full or {}).items():
            ctr = snap.get("host", {}).get("counters", {})
            api_shed[sid] = ctr.get("api_shed", 0)
        result["api_shed"] = api_shed
        if plane is not None:
            result["proxy_shed"] = plane.shed_counts()
            result["proxy_metrics"] = {
                pid: {
                    "counters": snap["host"]["counters"],
                }
                for pid, snap in plane.scrape().items()
            }
        result["server_metrics"] = {
            sid: {
                "tick": snap["tick"],
                "counters": {
                    k: v
                    for k, v in snap["host"]["counters"].items()
                    if k.startswith("api_")
                },
                "histograms": {
                    k: v
                    for k, v in snap["host"]["histograms"].items()
                    if k.split("{", 1)[0] in (
                        "api_request_latency_us", "ticks_to_commit",
                    )
                },
            }
            for sid, snap in (full or {}).items()
        }
        if len(ops) < args.min_ops:
            result["error"] = f"history too small: {len(ops)}"
            return result
        if acked == 0:
            result["error"] = "no op ever acked"
            return result

        # no ack lost to a shed: a value must never be both acked and
        # negatively acked (values are globally unique per op instance,
        # so any overlap is a protocol bug, not a collision)
        acked_vals = {o.value for o in ops
                      if o.kind == "put" and o.acked and not o.shed}
        shed_vals = {o.value for o in ops if o.shed}
        overlap = acked_vals & shed_vals
        result["ack_shed_overlap"] = len(overlap)
        if overlap:
            result["error"] = (
                f"{len(overlap)} values both acked and shed: "
                f"{sorted(overlap)[:4]}"
            )
            return result

        # phase stats: steady / (burst / recover for overload rows)
        win_steady = phase_window(wplan, 0, t0, args.tick_len)
        # skip the first 20% of steady: election/jit settling
        s_lo = win_steady[0] + 0.2 * (win_steady[1] - win_steady[0])
        steady_acc = accepted_in(ops, s_lo, win_steady[1])
        steady_tput = len(steady_acc) / max(win_steady[1] - s_lo, 1e-9)
        result["steady_tput"] = round(steady_tput, 1)
        # the steady phases offer rate_x[0] x capacity on both sides of
        # the burst; recovery is judged against this OFFERED rate (the
        # measured steady window carries calibration-drain transients
        # and, at these op counts, real expovariate noise)
        offered_steady = wplan.phases[0].rate_x * cap
        result["offered_steady"] = round(offered_steady, 1)
        lat_all = [o.t_resp - o.t_inv
                   for o in ops if o.acked and not o.shed]
        result["p99_s"] = round(p99(lat_all), 3)
        if overload:
            win_burst = phase_window(wplan, 1, t0, args.tick_len)
            win_rec = phase_window(wplan, 2, t0, args.tick_len)
            burst_acc = accepted_in(ops, *win_burst)
            result["burst_tput"] = round(
                len(burst_acc)
                / max(win_burst[1] - win_burst[0], 1e-9), 1)
            burst_lat = [o.t_resp - o.t_inv for o in burst_acc
                         if win_burst[0] <= o.t_inv]
            result["burst_p99_s"] = round(p99(burst_lat), 3)
            # recovery tail: the last 40% of the recover phase, clear
            # of the crash-election window at its start
            r_lo = win_rec[0] + 0.6 * (win_rec[1] - win_rec[0])
            rec_acc = accepted_in(ops, r_lo, win_rec[1])
            rec_tput = len(rec_acc) / max(win_rec[1] - r_lo, 1e-9)
            result["recover_tput"] = round(rec_tput, 1)

            # server-visible shedding: the post-run scrape PLUS the
            # burst-peak scrape taken just before the leader crash
            # (the victim's counter does not survive its restart).
            # Proxied cells count the proxy tier's front-door sheds as
            # server-side evidence too — that is where the overload is
            # SUPPOSED to land once the tiers are split
            server_shed = sum(api_shed.values()) + sum(
                (result.get("api_shed_pre") or {}).values()
            ) + sum(
                (result.get("proxy_shed") or {}).values()
            ) + sum(
                (result.get("proxy_shed_pre") or {}).values()
            )
            if shed == 0 or server_shed == 0:
                result["error"] = (
                    "overload row shed nothing: client sheds "
                    f"{shed}, server api_shed {api_shed} "
                    f"(pre-crash {result.get('api_shed_pre')})"
                )
                return result
            if len(burst_acc) < 10:
                # crash + election eat a slice of the burst; what must
                # hold is PROGRESS, not a tput floor (the tput floor is
                # the recover-phase assertion below)
                result["error"] = (
                    f"burst made no progress: {len(burst_acc)} acked"
                )
                return result
            if shed >= issued:
                result["error"] = "everything shed, nothing served"
                return result
            if result["burst_p99_s"] > args.p99_budget:
                result["error"] = (
                    f"accepted-op p99 {result['burst_p99_s']}s over "
                    f"budget {args.p99_budget}s through the burst"
                )
                return result
            if rec_tput < args.recover_frac * offered_steady:
                result["error"] = (
                    f"throughput did not recover: {rec_tput:.1f}/s "
                    f"tail vs {offered_steady:.1f}/s offered steady "
                    f"(need >= {args.recover_frac}x)"
                )
                return result

        ok, diag = check_history(ops)
        result["ok"] = bool(ok)
        if not ok:
            result["error"] = diag
        return result
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
        if not result["ok"] and runner is not None:
            result["flight"] = runner.flight_tails(last_n=256)
        if runner is not None:
            runner.close()
        if prunner is not None:
            prunner.close()
        if plane is not None:
            plane.stop()
        if cluster is not None:
            cluster.stop()
        if not result["ok"]:
            dump = os.path.splitext(args.out)[0] + (
                f"_{protocol}_{wl_class}_s{seed}_fail.json"
            )
            with open(dump, "w") as f:
                json.dump(
                    fail_bundle_doc(result, wplan, fplan, runner, ops),
                    f, indent=1,
                )
            print(f"FAIL bundle -> {dump}")
        shutil.rmtree(tmp, ignore_errors=True)


def run_shed_ab(args) -> dict:
    """Fused-vs-proxy shed-point A/B on the hot_burst overload row:
    the SAME WorkloadPlan (same seed, same digest) runs twice — direct
    against the shards, then through >= 2 ingress proxies — with the
    burst phase's rate replaced by a linear offered-rate ramp from 1x to
    ``RAMP_MAX_X`` the FUSED calibrated capacity.  The shed point is the
    offered rate at the first client-observed shed; the proxy tier must
    move it up by >= ``PROXY_AB_MIN_RATIO`` with the sheds landing at
    the proxy front door (``proxy_shed``) instead of the shards
    (``api_shed``), while accepted-op p99 and the post-burst recovery
    tail stay inside the fused budgets.  Committed as the
    ``kind == "proxy_ab"`` WORKLOADS.json row, gated by
    scripts/workload_gate.py."""
    from test_cluster import Cluster

    from summerset_tpu.client.drivers import DriverClosedLoop
    from summerset_tpu.client.endpoint import (
        GenericEndpoint, scrape_metrics,
    )
    from summerset_tpu.client.tester import start_workload_clients
    from summerset_tpu.host.workload import WorkloadPlan
    from summerset_tpu.utils.linearize import check_history

    wplan = WorkloadPlan.generate(
        AB_SEED, "hot_burst", clients=DEFAULT_CLIENTS,
        num_keys=DEFAULT_KEYS, horizon=DEFAULT_HORIZON,
    )
    burst = wplan.phases[1]
    row = {
        "kind": "proxy_ab", "protocol": "MultiPaxos", "seed": AB_SEED,
        "wl_digest": wplan.digest(), "ramp_max_x": RAMP_MAX_X,
        "proxies": PROXY_COUNT, "proxy_cfg": dict(PROXY_CFG),
        "ok": False,
    }
    cap_unit = None

    def run_mode(mode: str) -> dict:
        nonlocal cap_unit
        sub = {"mode": mode}
        tmp = tempfile.mkdtemp(prefix=f"wlab_{mode}_")
        cluster = None
        plane = None
        stop = threading.Event()
        ops: list = []
        stats: list = []
        threads: list = []
        try:
            cluster = Cluster(
                "MultiPaxos", args.replicas, tmp,
                config=protocol_config("MultiPaxos"), tick=args.tick,
            )
            if mode == "proxy":
                from summerset_tpu.host.ingress import ServingPlane

                plane = ServingPlane(
                    cluster.manager_addr, proxies=PROXY_COUNT,
                    proxy_config=dict(PROXY_CFG),
                ).start()
            wep = GenericEndpoint(cluster.manager_addr)
            wep.connect()
            DriverClosedLoop(wep, timeout=10.0).checked_put("warm", "1")
            wep.leave()
            if cap_unit is None:
                # the FUSED run calibrates once; both runs share that
                # offered-rate axis so "shed point" compares 1:1
                cap_unit = calibrate_capacity(
                    cluster.manager_addr, wplan.clients,
                    timeout=args.op_timeout,
                )
                row["capacity_ops_s"] = round(cap_unit, 1)
                time.sleep(min(2.0, API_MAX_PENDING / cap_unit + 0.3))
            print(f"--- proxy_ab {mode}: ramp 1x..{RAMP_MAX_X}x of "
                  f"{cap_unit:.1f} ops/s across the burst window")
            t0 = time.monotonic()

            def offered_at(tick: float) -> float:
                if burst.tick <= tick < burst.tick + burst.ticks:
                    frac = (tick - burst.tick) / burst.ticks
                    return (1.0 + frac * (RAMP_MAX_X - 1.0)) * cap_unit
                return wplan.rate_x_at(tick) * cap_unit

            def rate_total_of() -> float:
                return offered_at(
                    (time.monotonic() - t0) / args.tick_len
                )

            threads = start_workload_clients(
                cluster.manager_addr, wplan, rate_total_of, stop, ops,
                stats, timeout=args.op_timeout,
            )
            horizon_s = wplan.horizon() * args.tick_len
            time.sleep(max(0.0, t0 + horizon_s - time.monotonic()))
            time.sleep(2.0)
            stop.set()
            for t in threads:
                t.join(timeout=60)

            sub["issued"] = sum(s["issued"] for s in stats)
            sub["acked"] = sum(s["acked"] for s in stats)
            sub["shed"] = sum(s["shed"] for s in stats)
            shed_invs = [o.t_inv for o in ops if o.shed]
            if shed_invs:
                tick_at = (min(shed_invs) - t0) / args.tick_len
                if tick_at >= burst.tick + burst.ticks:
                    sp = RAMP_MAX_X * cap_unit  # survived the ramp
                else:
                    sp = offered_at(tick_at)
                sub["first_shed_tick"] = round(tick_at, 1)
            else:
                sp = RAMP_MAX_X * cap_unit
                sub["first_shed_tick"] = None
            sub["shed_point_ops_s"] = round(sp, 1)

            # budget checks shared with the overload cells
            lat = [o.t_resp - o.t_inv
                   for o in ops if o.acked and not o.shed]
            sub["p99_s"] = round(p99(lat), 3)
            win_rec = phase_window(wplan, 2, t0, args.tick_len)
            r_lo = win_rec[0] + 0.6 * (win_rec[1] - win_rec[0])
            rec_acc = accepted_in(ops, r_lo, win_rec[1])
            rec_tput = len(rec_acc) / max(win_rec[1] - r_lo, 1e-9)
            sub["recover_tput"] = round(rec_tput, 1)
            sub["offered_steady"] = round(
                wplan.phases[0].rate_x * cap_unit, 1
            )

            full = scrape_metrics(cluster.manager_addr)
            sub["api_shed"] = {
                sid: snap.get("host", {}).get("counters", {})
                         .get("api_shed", 0)
                for sid, snap in (full or {}).items()
            }
            if plane is not None:
                sub["proxy_shed"] = plane.shed_counts()
            ok, diag = check_history(ops)
            sub["linearizable"] = bool(ok)
            if not ok:
                sub["error"] = diag
            return sub
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=10)
            if plane is not None:
                plane.stop()
            if cluster is not None:
                cluster.stop()
            shutil.rmtree(tmp, ignore_errors=True)

    row["fused"] = run_mode("fused")
    row["proxy"] = run_mode("proxy")
    sp_f = row["fused"]["shed_point_ops_s"]
    sp_p = row["proxy"]["shed_point_ops_s"]
    row["shed_point_fused"] = sp_f
    row["shed_point_proxy"] = sp_p
    row["shed_ratio"] = round(sp_p / sp_f, 2) if sp_f > 0 else 0.0
    proxy_shed = sum((row["proxy"].get("proxy_shed") or {}).values())
    shard_shed = sum((row["proxy"].get("api_shed") or {}).values())
    row["proxy_run_proxy_shed"] = proxy_shed
    row["proxy_run_shard_shed"] = shard_shed
    errs = []
    if not (row["fused"]["linearizable"]
            and row["proxy"]["linearizable"]):
        errs.append("history not linearizable")
    if row["fused"]["shed"] <= 0:
        errs.append("fused run never shed — ramp too low to measure")
    if row["shed_ratio"] < PROXY_AB_MIN_RATIO:
        errs.append(
            f"shed point improved only {row['shed_ratio']}x "
            f"(need >= {PROXY_AB_MIN_RATIO})"
        )
    if proxy_shed <= shard_shed or proxy_shed <= 0:
        errs.append(
            f"sheds not attributed to the proxy tier "
            f"(proxy {proxy_shed} vs shard {shard_shed})"
        )
    for mode in ("fused", "proxy"):
        if row[mode]["p99_s"] > args.p99_budget:
            errs.append(f"{mode} accepted-op p99 over budget")
        if row[mode]["recover_tput"] < (
            args.recover_frac * row[mode]["offered_steady"]
        ):
            errs.append(f"{mode} post-burst throughput did not recover")
    row["ok"] = not errs
    if errs:
        row["error"] = "; ".join(errs)
    return row


def run_reshard_ab(args) -> dict:
    """Live-resharding A/B on the hot_burst overload row: the SAME
    WorkloadPlan (same seed, same digest) runs twice over a 4-group
    keyspace — resharding off, then on — while the cell's message-plane
    FaultPlan plays in both modes.  In the "on" run a ResharderPolicy
    driver scrapes the servers' per-key ``range_heat`` gauges, feeds
    per-interval deltas to ``decide``, and issues the resulting
    ``range_change`` requests over the ctrl plane: >= 1 live split and
    >= 1 live merge must execute (server-side ``reshard_splits`` /
    ``reshard_merges`` counters) through the seal/adopt cutover, with
    both histories linearizable-with-sheds, zero values both acked and
    shed, and accepted-op p99 + post-burst recovery inside the fused
    budgets in BOTH modes.  Committed as the ``kind == "reshard_ab"``
    WORKLOADS.json row, gated by scripts/workload_gate.py."""
    import zlib

    from test_cluster import Cluster

    from summerset_tpu.client.drivers import DriverClosedLoop
    from summerset_tpu.client.endpoint import (
        GenericEndpoint, scrape_metrics,
    )
    from summerset_tpu.client.tester import start_workload_clients
    from summerset_tpu.host.messages import CtrlRequest
    from summerset_tpu.host.nemesis import FaultPlan, NemesisRunner
    from summerset_tpu.host.resharding import (
        RangeChange, ResharderPolicy, single_key_range,
    )
    from summerset_tpu.host.workload import WorkloadPlan
    from summerset_tpu.utils.linearize import check_history

    wplan = WorkloadPlan.generate(
        AB_SEED, "hot_burst", clients=DEFAULT_CLIENTS,
        num_keys=DEFAULT_KEYS, horizon=DEFAULT_HORIZON,
    )
    fplan = FaultPlan.generate(
        AB_SEED, args.replicas, DEFAULT_HORIZON, classes=FAULT_CLASSES,
    )
    burst = wplan.phases[1]
    row = {
        "kind": "reshard_ab", "protocol": "MultiPaxos",
        "seed": AB_SEED, "wl_digest": wplan.digest(),
        "fault_digest": fplan.digest(),
        "num_groups": RESHARD_GROUPS, "ok": False,
    }
    cap_unit = None

    def hash_group(key: str) -> int:
        # mirrors ServerReplica.group_of — the hash-home placement the
        # policy splits away from and merges back to
        return zlib.crc32(key.encode()) % RESHARD_GROUPS

    def run_mode(mode: str) -> dict:
        nonlocal cap_unit
        sub = {"mode": mode}
        tmp = tempfile.mkdtemp(prefix=f"wlreshard_{mode}_")
        cluster = None
        stop = threading.Event()
        ops: list = []
        stats: list = []
        threads: list = []
        runner = None
        nem_thread = None
        try:
            cluster = Cluster(
                "MultiPaxos", args.replicas, tmp,
                config=protocol_config("MultiPaxos"), tick=args.tick,
                num_groups=RESHARD_GROUPS,
            )
            wep = GenericEndpoint(cluster.manager_addr)
            wep.connect()
            DriverClosedLoop(wep, timeout=10.0).checked_put("warm", "1")
            wep.leave()
            if cap_unit is None:
                # the OFF run calibrates once; both runs share the
                # offered-rate axis so the budgets compare 1:1
                cap_unit = calibrate_capacity(
                    cluster.manager_addr, wplan.clients,
                    timeout=args.op_timeout,
                )
                row["capacity_ops_s"] = round(cap_unit, 1)
                time.sleep(min(2.0, API_MAX_PENDING / cap_unit + 0.3))
            print(f"--- reshard_ab {mode}: hot_burst over "
                  f"{RESHARD_GROUPS} groups at {cap_unit:.1f} ops/s, "
                  f"faults {fplan.digest()}")
            t0 = time.monotonic()

            def rate_total_of() -> float:
                tick = (time.monotonic() - t0) / args.tick_len
                return wplan.rate_x_at(tick) * cap_unit

            threads = start_workload_clients(
                cluster.manager_addr, wplan, rate_total_of, stop, ops,
                stats, timeout=args.op_timeout,
            )
            runner = NemesisRunner(
                cluster.manager_addr, fplan, tick_len=args.tick_len,
            )
            nem_thread = threading.Thread(target=runner.play,
                                          daemon=True)
            nem_thread.start()

            issued = {"split": 0, "merge": 0}
            moved: list = []   # keys split off their hash-home
            if mode == "on":
                def drive_policy() -> None:
                    from summerset_tpu.host.autopilot import (
                        AutopilotPolicy,
                    )

                    pol = ResharderPolicy(
                        RESHARD_GROUPS, hash_group,
                        hot_frac=RESHARD_HOT_FRAC,
                        cold_frac=RESHARD_COLD_FRAC, min_total=10,
                    )
                    # PR 17: reshard decisions answer to an autopilot's
                    # actuation budget (streaks, cooldowns, one change
                    # per group per window) instead of firing on every
                    # scrape — the AutopilotPolicy ctor installs
                    # pol.budget_gate.  Short streak/cooldown: the
                    # scrape cadence is 1.2s against a ~10s burst.
                    ap = AutopilotPolicy(
                        seed=AB_SEED, population=args.replicas,
                        num_groups=RESHARD_GROUPS, streak_need=2,
                        cooldown_rounds=2, window_rounds=4,
                        budget_per_window=2, resharder=pol,
                    )
                    prev: dict = {}
                    ep = GenericEndpoint(cluster.manager_addr)

                    def request(ch) -> None:
                        try:
                            rep = ep.ctrl.request(
                                CtrlRequest("range_change",
                                            payload=ch.as_dict()),
                                timeout=60.0,
                            )
                        except Exception as e:
                            sub.setdefault("ctrl_errors", []).append(
                                repr(e))
                            return
                        if rep is None or rep.kind == "error":
                            return
                        issued[ch.op] += 1
                        if ch.op == "split":
                            moved.append(ch.start)
                        elif ch.start in moved:
                            moved.remove(ch.start)

                    while not stop.is_set():
                        time.sleep(RESHARD_SCRAPE_S)
                        if stop.is_set():
                            break
                        try:
                            full = scrape_metrics(
                                cluster.manager_addr, timeout=10.0)
                        except Exception:
                            continue
                        cum: dict = {}
                        for sid, snap in (full or {}).items():
                            gauges = (snap.get("host", {})
                                          .get("gauges", {}) or {})
                            for name, v in gauges.items():
                                if name.startswith("range_heat{key="):
                                    k = name[len("range_heat{key="):-1]
                                    cum[k] = cum.get(k, 0) + int(v)
                        delta = {k: max(0, v - prev.get(k, 0))
                                 for k, v in cum.items()}
                        prev = cum
                        tick = (time.monotonic() - t0) / args.tick_len
                        # one autopilot round per scrape: quorum senses
                        # from query_info, heat deltas as the reshard
                        # signal; pol.decide runs INSIDE evaluate, past
                        # the streak + budget admission
                        try:
                            info = ep.ctrl.request(
                                CtrlRequest("query_info"), timeout=10.0,
                            )
                        except Exception:
                            continue
                        alive = len(getattr(info, "servers", None)
                                    or {})
                        decisions = ap.evaluate({
                            "population": args.replicas,
                            "alive": alive,
                            "leader": getattr(info, "leader", None),
                            "heat": delta,
                        })
                        ch = None
                        for d in decisions:
                            if d.actuator == "reshard":
                                ch = RangeChange(
                                    d.arg["op"], d.arg["start"],
                                    d.arg.get("end"),
                                    int(d.arg["dst_group"]),
                                )
                                break
                        if (ch is None and not issued["split"] and cum
                                and tick >= burst.tick
                                + burst.ticks // 2):
                            # backstop split: mid-burst with nothing
                            # moved yet, split the cumulatively hottest
                            # key (scrape cadence must not flake the
                            # >= 1 live split acceptance)
                            hot = max(cum.items(),
                                      key=lambda t: t[1])[0]
                            if hot not in moved:
                                s, e = single_key_range(hot)
                                ch = RangeChange(
                                    "split", s, e,
                                    (hash_group(hot) + 1)
                                    % RESHARD_GROUPS,
                                )
                        if (ch is None and moved
                                and tick >= burst.tick + burst.ticks
                                + 8):
                            # cool-down merge: the burst is over, move
                            # still-split ranges back to their
                            # hash-home (early in the recover phase so
                            # the cutover shed clears the measured
                            # recovery tail)
                            key = moved[0]
                            s, e = single_key_range(key)
                            ch = RangeChange("merge", s, e,
                                             hash_group(key))
                        if ch is not None:
                            request(ch)
                    try:
                        ep.ctrl.close()
                    except Exception:
                        pass

                pt = threading.Thread(target=drive_policy, daemon=True)
                pt.start()
                threads.append(pt)

            horizon_s = wplan.horizon() * args.tick_len
            time.sleep(max(0.0, t0 + horizon_s - time.monotonic()))
            time.sleep(2.0)
            stop.set()
            for t in threads:
                t.join(timeout=60)
            if nem_thread is not None:
                nem_thread.join(timeout=120)
            runner.heal_all()

            # bounded recovery: a checked write within the tick budget
            t_heal = time.monotonic()
            budget_s = args.budget_ticks * args.tick
            rep_ep = GenericEndpoint(cluster.manager_addr)
            rep_ep.connect()
            drv = DriverClosedLoop(rep_ep, timeout=min(5.0, budget_s))
            recovered = False
            while time.monotonic() - t_heal < budget_s:
                r = drv.put("reshard_recovery", f"m-{mode}")
                if r.kind == "success":
                    recovered = True
                    break
                drv._retry_pause(r)
            rep_ep.leave()
            sub["recovered"] = recovered
            sub["recovery_ticks"] = int(
                (time.monotonic() - t_heal) / args.tick)

            sub["num_ops"] = len(ops)
            sub["issued"] = sum(s["issued"] for s in stats)
            sub["acked"] = sum(s["acked"] for s in stats)
            sub["shed"] = sum(s["shed"] for s in stats)
            sub["splits_issued"] = issued["split"]
            sub["merges_issued"] = issued["merge"]

            # server-side evidence that cutovers EXECUTED (adoption
            # applied), not just that requests were issued
            full = scrape_metrics(cluster.manager_addr)
            splits, merges = {}, {}
            api_shed = {}
            for sid, snap in (full or {}).items():
                ctr = snap.get("host", {}).get("counters", {})
                splits[sid] = ctr.get("reshard_splits", 0)
                merges[sid] = ctr.get("reshard_merges", 0)
                api_shed[sid] = ctr.get("api_shed", 0)
            sub["reshard_splits"] = splits
            sub["reshard_merges"] = merges
            sub["api_shed"] = api_shed
            sub["splits"] = max(splits.values(), default=0)
            sub["merges"] = max(merges.values(), default=0)

            # no ack lost to a shed across the cutover: a value must
            # never be both acked and negatively acked
            acked_vals = {o.value for o in ops
                          if o.kind == "put" and o.acked and not o.shed}
            shed_vals = {o.value for o in ops if o.shed}
            sub["ack_shed_overlap"] = len(acked_vals & shed_vals)

            lat = [o.t_resp - o.t_inv
                   for o in ops if o.acked and not o.shed]
            sub["p99_s"] = round(p99(lat), 3)
            win_rec = phase_window(wplan, 2, t0, args.tick_len)
            r_lo = win_rec[0] + 0.6 * (win_rec[1] - win_rec[0])
            rec_acc = accepted_in(ops, r_lo, win_rec[1])
            rec_tput = len(rec_acc) / max(win_rec[1] - r_lo, 1e-9)
            sub["recover_tput"] = round(rec_tput, 1)
            sub["offered_steady"] = round(
                wplan.phases[0].rate_x * cap_unit, 1)

            ok, diag = check_history(ops)
            sub["linearizable"] = bool(ok)
            if not ok:
                sub["error"] = diag
            return sub
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=10)
            if runner is not None:
                if not sub.get("linearizable"):
                    sub["flight"] = runner.flight_tails(last_n=256)
                runner.close()
            if cluster is not None:
                cluster.stop()
            shutil.rmtree(tmp, ignore_errors=True)

    row["off"] = run_mode("off")
    row["on"] = run_mode("on")
    errs = []
    for mode in ("off", "on"):
        sub = row[mode]
        if not sub.get("linearizable"):
            errs.append(f"{mode} history not linearizable "
                        f"({sub.get('error')})")
        if sub.get("ack_shed_overlap"):
            errs.append(f"{mode}: {sub['ack_shed_overlap']} values "
                        "both acked and shed")
        if sub.get("num_ops", 0) < args.min_ops:
            errs.append(f"{mode} history too small: "
                        f"{sub.get('num_ops')}")
        if sub.get("p99_s", 1e9) > args.p99_budget:
            errs.append(f"{mode} accepted-op p99 {sub.get('p99_s')}s "
                        f"over budget {args.p99_budget}s")
        if sub.get("recover_tput", 0.0) < (
            args.recover_frac * sub.get("offered_steady", 1e9)
        ):
            errs.append(f"{mode} post-burst throughput did not recover")
        if not sub.get("recovered"):
            errs.append(f"{mode} no recovery within budget")
    if row["on"].get("splits", 0) < 1:
        errs.append("no live split executed in the on run")
    if row["on"].get("merges", 0) < 1:
        errs.append("no live merge executed in the on run")
    if row["off"].get("splits", 0) or row["off"].get("merges", 0):
        errs.append("off run executed range changes")
    row["ok"] = not errs
    if errs:
        row["error"] = "; ".join(errs)
    return row


def build_scan_plan(kind: str):
    """The scan cells' plans — regenerable by the gate without a
    cluster (ycsb_e from its seed, trace by re-parsing the committed
    fixture file; same bytes => same digest)."""
    from summerset_tpu.host.workload import WorkloadPlan

    if kind == "trace":
        return WorkloadPlan.from_trace(
            os.path.join(REPO, TRACE_FILE), seed=TRACE_SEED,
            clients=DEFAULT_CLIENTS, horizon=DEFAULT_HORIZON,
        )
    return WorkloadPlan.generate(
        SCAN_SEED, "ycsb_e", clients=DEFAULT_CLIENTS,
        num_keys=DEFAULT_KEYS, horizon=DEFAULT_HORIZON,
    )


def run_scan_cell(kind: str, args) -> dict:
    """One learner-tier scan cell (``kind`` in {"ycsb_e", "trace"}):
    QuorumLeases behind a learner-read-tier proxy with read leases
    granted everywhere, driven by YCSB-E traffic (generated or trace
    replay).  Asserts the range-read plane end to end: scans VISIBLY
    served lease-local (``read_tier_scans`` > 0), the whole history —
    multi-key cuts included — linearizable-with-sheds, zero values both
    acked and shed, accepted-op p99 and the post-run recovery write
    inside the fused budgets.  Committed as the ``kind`` WORKLOADS.json
    row, gated by scripts/workload_gate.py (digest regeneration included
    — the trace row's digest must match a re-parse of the committed
    fixture)."""
    from test_cluster import Cluster

    from summerset_tpu.client.drivers import DriverClosedLoop
    from summerset_tpu.client.endpoint import (
        GenericEndpoint, scrape_metrics,
    )
    from summerset_tpu.client.tester import start_workload_clients
    from summerset_tpu.host.ingress import ServingPlane
    from summerset_tpu.utils.linearize import check_history

    wplan = build_scan_plan(kind)
    w2 = build_scan_plan(kind)
    # the repro contract — for the trace cell this IS the
    # same-trace-same-digest guarantee, enforced live
    assert wplan.timeline() == w2.timeline(), "non-deterministic wplan!"
    row = {
        "kind": kind, "protocol": "QuorumLeases",
        "seed": wplan.seed, "wl_digest": wplan.digest(),
        "proxies": SCAN_PROXIES, "ok": False,
    }
    if kind == "trace":
        row["trace_file"] = TRACE_FILE
        row["trace_sha"] = wplan.trace_sha()
        row["trace_rows"] = len(wplan.trace)
    tmp = tempfile.mkdtemp(prefix=f"wlscan_{kind}_")
    cluster = None
    plane = None
    stop = threading.Event()
    ops: list = []
    stats: list = []
    threads: list = []
    try:
        cluster = Cluster(
            "QuorumLeases", args.replicas, tmp,
            config=protocol_config("QuorumLeases"), tick=args.tick,
        )
        plane = ServingPlane(
            cluster.manager_addr, proxies=SCAN_PROXIES,
        ).start()
        wep = GenericEndpoint(cluster.manager_addr)
        wep.connect()
        drv = DriverClosedLoop(wep, timeout=10.0)
        drv.checked_put("warm", "1")
        # grant read leases everywhere: lease-local scans need the
        # installed responders conf (probes refuse until the grant
        # lands, harmlessly — the learner falls back to forwarding)
        drv.conf_change(
            {"responders": list(range(args.replicas))}
        )
        wep.leave()
        time.sleep(2.0)  # learner subscribe + lease grants settle
        cap = calibrate_capacity(
            cluster.manager_addr, wplan.clients,
            timeout=args.op_timeout,
        )
        row["capacity_ops_s"] = round(cap, 1)
        time.sleep(min(2.0, API_MAX_PENDING / cap + 0.3))
        print(f"--- {kind} scan cell: QuorumLeases + {SCAN_PROXIES} "
              f"proxies, wdigest={wplan.digest()}, "
              f"capacity {cap:.1f} ops/s")
        print(wplan.timeline(), end="")
        t0 = time.monotonic()

        def rate_total_of() -> float:
            tick = (time.monotonic() - t0) / args.tick_len
            return wplan.rate_x_at(tick) * cap

        threads = start_workload_clients(
            cluster.manager_addr, wplan, rate_total_of, stop, ops,
            stats, timeout=args.op_timeout,
        )
        horizon_s = wplan.horizon() * args.tick_len
        time.sleep(max(0.0, t0 + horizon_s - time.monotonic()))
        time.sleep(2.0)
        stop.set()
        for t in threads:
            t.join(timeout=60)

        # bounded recovery: a checked write within the tick budget
        t_heal = time.monotonic()
        budget_s = args.budget_ticks * args.tick
        rep = GenericEndpoint(cluster.manager_addr)
        rep.connect()
        drv = DriverClosedLoop(rep, timeout=min(5.0, budget_s))
        recovered = False
        while time.monotonic() - t_heal < budget_s:
            r = drv.put("wl_recovery", f"scan-{kind}")
            if r.kind == "success":
                recovered = True
                break
            drv._retry_pause(r)
        rep.leave()
        row["recovered"] = recovered
        row["recovery_ticks"] = int(
            (time.monotonic() - t_heal) / args.tick)

        row["num_ops"] = len(ops)
        row["issued"] = sum(s["issued"] for s in stats)
        row["acked"] = sum(s["acked"] for s in stats)
        row["shed"] = sum(s["shed"] for s in stats)
        row["scans_acked"] = sum(
            1 for o in ops if o.kind == "scan")
        row["scan_keys_observed"] = sum(
            len(o.items or ()) for o in ops if o.kind == "scan")

        # serving attribution: the learner tier's scan counters are the
        # cell's POINT — scans served lease-local, off the quorum path
        full = scrape_metrics(cluster.manager_addr)
        srv = {"scan_served": {}, "scan_shed": {}, "api_shed": {}}
        for sid, snap in (full or {}).items():
            ctr = snap.get("host", {}).get("counters", {})
            for name in srv:
                srv[name][sid] = ctr.get(name, 0)
        row.update(srv)
        tier = {"read_tier_scans": 0, "read_tier_served": 0,
                "proxy_shed": 0}
        for pid, snap in plane.scrape().items():
            ctr = snap.get("host", {}).get("counters", {})
            for name in tier:
                tier[name] += ctr.get(name, 0)
        row.update(tier)

        acked_vals = {o.value for o in ops
                      if o.kind == "put" and o.acked and not o.shed}
        shed_vals = {o.value for o in ops if o.shed}
        row["ack_shed_overlap"] = len(acked_vals & shed_vals)
        lat = [o.t_resp - o.t_inv
               for o in ops if o.acked and not o.shed]
        row["p99_s"] = round(p99(lat), 3)

        ok, diag = check_history(ops)
        row["linearizable"] = bool(ok)
        errs = []
        if not ok:
            errs.append(f"history not linearizable: {diag}")
        if row["num_ops"] < args.min_ops:
            errs.append(f"history too small: {row['num_ops']}")
        if row["scans_acked"] <= 0:
            errs.append("no scan ever acked")
        if row["read_tier_scans"] <= 0:
            errs.append("no scan served from the learner read tier")
        if row["ack_shed_overlap"]:
            errs.append(f"{row['ack_shed_overlap']} values both "
                        "acked and shed")
        if row["p99_s"] > args.p99_budget:
            errs.append(f"accepted-op p99 {row['p99_s']}s over "
                        f"budget {args.p99_budget}s")
        if not recovered:
            errs.append("no recovery within budget")
        row["ok"] = not errs
        if errs:
            row["error"] = "; ".join(errs)
        return row
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
        if plane is not None:
            plane.stop()
        if cluster is not None:
            cluster.stop()
        if not row["ok"]:
            dump = os.path.splitext(args.out)[0] + (
                f"_scan_{kind}_fail.json"
            )
            with open(dump, "w") as f:
                json.dump(
                    fail_bundle_doc(row, wplan, None, None, ops),
                    f, indent=1,
                )
            print(f"FAIL bundle -> {dump}")
        shutil.rmtree(tmp, ignore_errors=True)


def run_scan_reshard(args) -> dict:
    """The adversarial scan cell: YCSB-E traffic (a scan storm — ~95%
    ordered range reads) over a ``RESHARD_GROUPS``-group keyspace while
    ``range_change`` splits the plan's hot range live over the ctrl
    plane, then merges it back.  Scans straddling the cutover must shed
    OR serve a consistent cut — never an inconsistent one and never
    acked-then-shed — so the asserts are: >= 1 split EXECUTED server-
    side (``reshard_splits``), zero values both acked and shed, the
    whole multi-key history linearizable, scans still acked (the storm
    survives the migration point), p99 + recovery inside the fused
    budgets.  Committed as ``kind == "scan_reshard"``."""
    import random
    import zlib

    from test_cluster import Cluster

    from summerset_tpu.client.drivers import DriverClosedLoop
    from summerset_tpu.client.endpoint import (
        GenericEndpoint, scrape_metrics,
    )
    from summerset_tpu.client.tester import start_workload_clients
    from summerset_tpu.host.messages import CtrlRequest
    from summerset_tpu.host.resharding import (
        RangeChange, single_key_range,
    )
    from summerset_tpu.host.workload import WorkloadPlan
    from summerset_tpu.utils.linearize import check_history

    wplan = WorkloadPlan.generate(
        SCAN_RESHARD_SEED, "ycsb_e", clients=DEFAULT_CLIENTS,
        num_keys=DEFAULT_KEYS, horizon=DEFAULT_HORIZON,
    )
    w2 = WorkloadPlan.generate(
        SCAN_RESHARD_SEED, "ycsb_e", clients=DEFAULT_CLIENTS,
        num_keys=DEFAULT_KEYS, horizon=DEFAULT_HORIZON,
    )
    assert wplan.timeline() == w2.timeline(), "non-deterministic wplan!"
    # the plan's hot-key order (OpStream's shared shuffle): the split
    # victim when the heat scrape has nothing yet — zipfian scan STARTS
    # concentrate here, so splitting it lands mid-scan-storm by
    # construction
    order = list(range(wplan.num_keys))
    random.Random((wplan.seed << 8) | 0xA5).shuffle(order)
    hot_keys = [f"w{i}" for i in order]

    def hash_group(key: str) -> int:
        return zlib.crc32(key.encode()) % RESHARD_GROUPS

    row = {
        "kind": "scan_reshard", "protocol": "MultiPaxos",
        "seed": SCAN_RESHARD_SEED, "wl_digest": wplan.digest(),
        "num_groups": RESHARD_GROUPS, "ok": False,
    }
    tmp = tempfile.mkdtemp(prefix="wlscan_reshard_")
    cluster = None
    stop = threading.Event()
    ops: list = []
    stats: list = []
    threads: list = []
    changes: list = []
    try:
        cluster = Cluster(
            "MultiPaxos", args.replicas, tmp,
            config=protocol_config("MultiPaxos"), tick=args.tick,
            num_groups=RESHARD_GROUPS,
        )
        wep = GenericEndpoint(cluster.manager_addr)
        wep.connect()
        DriverClosedLoop(wep, timeout=10.0).checked_put("warm", "1")
        wep.leave()
        cap = calibrate_capacity(
            cluster.manager_addr, wplan.clients,
            timeout=args.op_timeout,
        )
        row["capacity_ops_s"] = round(cap, 1)
        time.sleep(min(2.0, API_MAX_PENDING / cap + 0.3))
        print(f"--- scan_reshard: ycsb_e over {RESHARD_GROUPS} groups "
              f"at {cap:.1f} ops/s, wdigest={wplan.digest()}, "
              f"hot={hot_keys[0]}")
        t0 = time.monotonic()
        horizon_s = wplan.horizon() * args.tick_len

        def rate_total_of() -> float:
            tick = (time.monotonic() - t0) / args.tick_len
            return wplan.rate_x_at(tick) * cap

        threads = start_workload_clients(
            cluster.manager_addr, wplan, rate_total_of, stop, ops,
            stats, timeout=args.op_timeout,
        )

        def drive_changes() -> None:
            """Mid-storm split of the hottest range (heat-scraped, plan
            fallback), a second split, then a merge back — all live
            over the ctrl plane while scans are in flight."""
            ep = GenericEndpoint(cluster.manager_addr)
            moved: list = []

            def hottest(exclude) -> str:
                try:
                    full = scrape_metrics(
                        cluster.manager_addr, timeout=10.0)
                except Exception:
                    full = None
                cum: dict = {}
                for sid, snap in (full or {}).items():
                    gauges = (snap.get("host", {})
                                  .get("gauges", {}) or {})
                    for name, v in gauges.items():
                        if name.startswith("range_heat{key="):
                            k = name[len("range_heat{key="):-1]
                            cum[k] = cum.get(k, 0) + int(v)
                for k, _ in sorted(cum.items(),
                                   key=lambda t: -t[1]):
                    if k not in exclude and k.startswith("w"):
                        return k
                return next(k for k in hot_keys if k not in exclude)

            def request(op: str, key: str, dst: int) -> None:
                s, e = single_key_range(key)
                try:
                    rep = ep.ctrl.request(
                        CtrlRequest("range_change",
                                    payload=RangeChange(
                                        op, s, e, dst).as_dict()),
                        timeout=60.0,
                    )
                except Exception as exc:
                    changes.append({"op": op, "key": key,
                                    "error": repr(exc)})
                    return
                ok = rep is not None and rep.kind != "error"
                changes.append({
                    "op": op, "key": key, "dst": dst, "ok": ok,
                    "at_tick": round(
                        (time.monotonic() - t0) / args.tick_len, 1),
                })
                if ok and op == "split":
                    moved.append(key)
                elif ok and key in moved:
                    moved.remove(key)

            for frac, act in ((0.35, "split"), (0.55, "split"),
                              (0.80, "merge")):
                lag = t0 + frac * horizon_s - time.monotonic()
                if lag > 0:
                    stop.wait(lag)
                if stop.is_set():
                    break
                if act == "split":
                    key = hottest(moved)
                    request("split", key,
                            (hash_group(key) + 1) % RESHARD_GROUPS)
                elif moved:
                    key = moved[0]
                    request("merge", key, hash_group(key))
            try:
                ep.ctrl.close()
            except Exception:
                pass

        ct = threading.Thread(target=drive_changes, daemon=True)
        ct.start()
        threads.append(ct)

        time.sleep(max(0.0, t0 + horizon_s - time.monotonic()))
        time.sleep(2.0)
        stop.set()
        for t in threads:
            t.join(timeout=60)

        # bounded recovery: a checked write within the tick budget
        t_heal = time.monotonic()
        budget_s = args.budget_ticks * args.tick
        rep_ep = GenericEndpoint(cluster.manager_addr)
        rep_ep.connect()
        drv = DriverClosedLoop(rep_ep, timeout=min(5.0, budget_s))
        recovered = False
        while time.monotonic() - t_heal < budget_s:
            r = drv.put("wl_recovery", "scan-reshard")
            if r.kind == "success":
                recovered = True
                break
            drv._retry_pause(r)
        rep_ep.leave()
        row["recovered"] = recovered
        row["recovery_ticks"] = int(
            (time.monotonic() - t_heal) / args.tick)

        row["num_ops"] = len(ops)
        row["issued"] = sum(s["issued"] for s in stats)
        row["acked"] = sum(s["acked"] for s in stats)
        row["shed"] = sum(s["shed"] for s in stats)
        row["scans_acked"] = sum(1 for o in ops if o.kind == "scan")
        row["changes"] = changes
        row["splits_issued"] = sum(
            1 for c in changes if c.get("op") == "split"
            and c.get("ok"))
        row["merges_issued"] = sum(
            1 for c in changes if c.get("op") == "merge"
            and c.get("ok"))

        # server-side evidence: cutovers EXECUTED and scans were served
        # (and shed) at the shards across the migration point
        full = scrape_metrics(cluster.manager_addr)
        srv = {"reshard_splits": {}, "reshard_merges": {},
               "scan_served": {}, "scan_shed": {}, "api_shed": {}}
        for sid, snap in (full or {}).items():
            ctr = snap.get("host", {}).get("counters", {})
            for name in srv:
                srv[name][sid] = ctr.get(name, 0)
        row.update(srv)
        row["splits"] = max(srv["reshard_splits"].values(), default=0)
        row["merges"] = max(srv["reshard_merges"].values(), default=0)

        acked_vals = {o.value for o in ops
                      if o.kind == "put" and o.acked and not o.shed}
        shed_vals = {o.value for o in ops if o.shed}
        row["ack_shed_overlap"] = len(acked_vals & shed_vals)
        lat = [o.t_resp - o.t_inv
               for o in ops if o.acked and not o.shed]
        row["p99_s"] = round(p99(lat), 3)

        ok, diag = check_history(ops)
        row["linearizable"] = bool(ok)
        errs = []
        if not ok:
            errs.append(f"history not linearizable: {diag}")
        if row["num_ops"] < args.min_ops:
            errs.append(f"history too small: {row['num_ops']}")
        if row["scans_acked"] <= 0:
            errs.append("no scan ever acked")
        if row["splits"] < 1:
            errs.append("no live split executed under scan load")
        if row["ack_shed_overlap"]:
            errs.append(f"{row['ack_shed_overlap']} values both "
                        "acked and shed across the cutover")
        if row["p99_s"] > args.p99_budget:
            errs.append(f"accepted-op p99 {row['p99_s']}s over "
                        f"budget {args.p99_budget}s")
        if not recovered:
            errs.append("no recovery within budget")
        row["ok"] = not errs
        if errs:
            row["error"] = "; ".join(errs)
        return row
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
        if cluster is not None:
            cluster.stop()
        if not row["ok"]:
            dump = os.path.splitext(args.out)[0] + (
                "_scan_reshard_fail.json"
            )
            with open(dump, "w") as f:
                json.dump(
                    fail_bundle_doc(row, wplan, None, None, ops),
                    f, indent=1,
                )
            print(f"FAIL bundle -> {dump}")
        shutil.rmtree(tmp, ignore_errors=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--protocol", default="MultiPaxos")
    ap.add_argument("--wl-class", default="hot_burst")
    ap.add_argument("--seed", type=int, default=1)
    ap.add_argument("--matrix", action="store_true",
                    help="run the full joint matrix (WL_MATRIX) plus "
                         "the fused-vs-proxy shed-point A/B row")
    ap.add_argument("--proxy-ab", action="store_true",
                    help="run ONLY the fused-vs-proxy shed-point A/B "
                         "(appends/replaces the proxy_ab row)")
    ap.add_argument("--reshard-ab", action="store_true",
                    help="run ONLY the live-resharding on/off A/B "
                         "(appends/replaces the reshard_ab row)")
    ap.add_argument("--scan-cells", action="store_true",
                    help="run ONLY the range-read cells (ycsb_e + "
                         "trace replay + scan_reshard; appends/"
                         "replaces those rows)")
    ap.add_argument("--replicas", type=int, default=3)
    ap.add_argument("--tick", type=float, default=0.005,
                    help="server tick interval (with api_max_batch="
                         f"{API_MAX_BATCH} this pins ingress capacity)")
    ap.add_argument("--tick-len", type=float, default=DEFAULT_TICK_LEN,
                    help="wall seconds per workload/fault tick")
    ap.add_argument("--op-timeout", type=float, default=5.0)
    ap.add_argument("--min-ops", type=int, default=60)
    ap.add_argument("--p99-budget", type=float, default=P99_BUDGET_S)
    ap.add_argument("--recover-frac", type=float, default=RECOVER_FRAC)
    ap.add_argument("--budget-ticks", type=int,
                    default=DEFAULT_BUDGET_TICKS)
    ap.add_argument("--out",
                    default=os.path.join(REPO, "WORKLOADS.json"))
    args = ap.parse_args()

    if args.proxy_ab or args.reshard_ab or args.scan_cells:
        runs = []
    elif args.matrix:
        runs = list(WL_MATRIX)
    else:
        match = [
            row for row in WL_MATRIX
            if row[0] == args.protocol and row[1] == args.wl_class
            and row[2] == args.seed
        ]
        runs = match or [
            (args.protocol, args.wl_class, args.seed, args.seed)
        ]
    results = []
    for protocol, wl_class, seed, fseed in runs:
        r = run_one(protocol, wl_class, seed, fseed, args)
        status = "PASS" if r["ok"] else f"FAIL ({r.get('error')})"
        print(f"=== {protocol} {wl_class} seed={seed}: {status} "
              f"(ops={r.get('num_ops')}, acked={r.get('acked')}, "
              f"shed={r.get('shed')}, p99={r.get('p99_s')}s)")
        results.append(r)
    if args.matrix or args.proxy_ab:
        ab = run_shed_ab(args)
        status = "PASS" if ab["ok"] else f"FAIL ({ab.get('error')})"
        print(f"=== proxy_ab: {status} (shed point "
              f"{ab.get('shed_point_fused')} -> "
              f"{ab.get('shed_point_proxy')} ops/s, "
              f"{ab.get('shed_ratio')}x; proxy sheds "
              f"{ab.get('proxy_run_proxy_shed')} vs shard "
              f"{ab.get('proxy_run_shard_shed')})")
        if args.proxy_ab and os.path.exists(args.out):
            # surgical update: keep the committed matrix rows, swap the
            # proxy_ab row
            with open(args.out) as f:
                results = [
                    r for r in json.load(f)
                    if r.get("kind") != "proxy_ab"
                ]
        results.append(ab)
    if args.matrix or args.reshard_ab:
        rab = run_reshard_ab(args)
        status = "PASS" if rab["ok"] else f"FAIL ({rab.get('error')})"
        on = rab.get("on") or {}
        print(f"=== reshard_ab: {status} (splits={on.get('splits')}, "
              f"merges={on.get('merges')}, "
              f"p99 off={rab.get('off', {}).get('p99_s')}s / "
              f"on={on.get('p99_s')}s)")
        if args.reshard_ab and os.path.exists(args.out):
            # surgical update: keep every committed row, swap the
            # reshard_ab row
            with open(args.out) as f:
                results = [
                    r for r in json.load(f)
                    if r.get("kind") != "reshard_ab"
                ]
        results.append(rab)
    if args.matrix or args.scan_cells:
        scan_rows = [
            run_scan_cell("ycsb_e", args),
            run_scan_cell("trace", args),
            run_scan_reshard(args),
        ]
        for sr in scan_rows:
            status = "PASS" if sr["ok"] else f"FAIL ({sr.get('error')})"
            print(f"=== {sr['kind']}: {status} "
                  f"(scans={sr.get('scans_acked')}, "
                  f"tier_scans={sr.get('read_tier_scans')}, "
                  f"splits={sr.get('splits')}, "
                  f"shed={sr.get('shed')}, p99={sr.get('p99_s')}s)")
        if args.scan_cells and os.path.exists(args.out):
            # surgical update: keep every committed row, swap the
            # range-read cells
            with open(args.out) as f:
                results = [
                    r for r in json.load(f)
                    if r.get("kind") not in SCAN_CELL_KINDS
                ]
        results.extend(scan_rows)
    with open(args.out, "w") as f:
        json.dump(results, f, indent=1)
    print(f"wrote {args.out}")
    sys.stdout.flush()
    sys.stderr.flush()
    # hard exit: same rationale as nemesis_soak (daemon replica threads
    # frozen mid-XLA can std::terminate after results are written)
    os._exit(0 if all(r["ok"] for r in results) else 1)


if __name__ == "__main__":
    main()
