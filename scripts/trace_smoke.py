#!/usr/bin/env python3
"""graftscope gate (ci.sh tier 2f) + the committed TRACE.json.

Three checks against a live 3-replica MultiPaxos cluster, all hard
failures:

1. **Recorder overhead**: open-loop (pipelined) serving rate — the
   HOSTBENCH bench client — with the flight recorder on vs off,
   measured as TIGHTLY interleaved on/off window pairs on the same live
   cluster (per the HOSTBENCH guidance for this box: back-to-back A/B
   blocks swing with cache/fsync state, so the sides alternate and the
   best window of each side is compared; closed-loop puts here run ~1/s
   on the fsync tail, too quantized to resolve a 5% delta).  Fails if
   recorder-on costs more than ``--max-overhead-pct`` (default 5%).
2. **Causal-chain smoke**: serve checked writes/reads with
   ``trace_sample=1``, scrape every server through the ``flight_dump``
   ctrl plane, export one merged Chrome trace
   (``scripts/trace_export.py``), and fail unless (a) the export passes
   schema validation (sorted stamps, matched span pairs), (b) at least
   one sampled request has a CONNECTED chain api-ingress → propose →
   commit → apply → reply, and (c) at least one transport frame's tx/rx
   events paired across two different replicas' dumps.
3. **Dump plumbing**: all three replicas answer the scrape and report
   drop accounting.

The summary (overhead numbers + chain/pair counts + per-type event
counts) is committed as TRACE.json, like TELEMETRY.json for the
telemetry plane; the full Chrome trace itself goes to ``--trace-out``
(a temp file by default — open it in chrome://tracing or Perfetto).

Usage: python scripts/trace_smoke.py [--max-overhead-pct 5.0]
       [--pairs 4] [--window 1.25] [--out TRACE.json]
"""

import argparse
import json
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update(
    "jax_compilation_cache_dir", os.path.join(REPO, ".jax_cache")
)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
from summerset_tpu.utils.jaxcompat import set_cpu_devices  # noqa: E402

set_cpu_devices(8)

sys.path.insert(0, os.path.join(REPO, "tests"))
sys.path.insert(0, os.path.join(REPO, "scripts"))


def _set_recorders(cluster, enabled: bool) -> None:
    # the smoke runs the in-process cluster harness, so the per-server
    # FlightRecorder objects are directly reachable — one bool flip per
    # server covers every hub seam (they share the server's recorder)
    for rep in list(cluster.replicas.values()):
        rep.flight.enabled = enabled


def _bench_window(ep, secs: float, seed: int) -> float:
    """Open-loop (pipelined) put rate over one wall window — the same
    bench client HOSTBENCH uses.  Closed-loop puts on this box run at
    ~1/s (fsync-tail bound), far too quantized to resolve a 5% delta;
    the pipelined window commits dozens of ops per fsync batch."""
    from summerset_tpu.client.bench import ClientBench

    bench = ClientBench(
        ep, secs=secs, put_ratio=1.0, value_size="64", num_keys=4,
        interval=1e9, seed=seed,
    )
    return float(bench.run()["tput"])


def overhead_gate(cluster, ep, pairs: int, window: float,
                  max_pct: float, max_pairs: int = 8) -> dict:
    """Interleaved recorder-on/off A/B, best window of each side: on
    this box back-to-back A/B blocks swing with cache/fsync state
    (HOSTBENCH guidance), so the sides alternate and the minima-of-noise
    (max rate) are compared.

    Adaptive escalation: per-window rates on this box swing ±20% on the
    fsync tail while the true recorder cost is ~1-2%, so a small fixed
    pair count sometimes draws an unlucky on-side.  While the measured
    overhead exceeds ``max_pct``, more pairs run (up to ``max_pairs``).
    Best-of is monotone in the window count, so extra pairs can only
    RESCUE a spurious failure — a true regression's on-side max stays
    low no matter how many windows run, and still fails the gate."""
    from ab_noise import gated_overhead

    on, off = [], []
    i = 0
    while True:
        _set_recorders(cluster, True)
        on.append(_bench_window(ep, window, seed=2 * i))
        _set_recorders(cluster, False)
        off.append(_bench_window(ep, window, seed=2 * i + 1))
        i += 1
        best_on, best_off = max(on), max(off)
        # the gate asserts the noise-gated overhead: the raw best-of
        # delta here used to come out negative on lucky on-sides, and
        # committing that as "overhead" reads as nonsense
        ov = gated_overhead(on, off, mode="rate")
        if i >= pairs and (
            ov["overhead_pct"] <= max_pct or i >= max_pairs
        ):
            break
    _set_recorders(cluster, True)
    return {
        "pairs": i,
        "window_s": window,
        "ops_s_on": [round(r, 1) for r in on],
        "ops_s_off": [round(r, 1) for r in off],
        "best_on": round(best_on, 1),
        "best_off": round(best_off, 1),
        **ov,
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--max-overhead-pct", type=float, default=5.0)
    ap.add_argument("--pairs", type=int, default=3)
    ap.add_argument("--window", type=float, default=3.0)
    ap.add_argument("--skip-overhead", action="store_true")
    ap.add_argument("--out", default=os.path.join(REPO, "TRACE.json"))
    ap.add_argument("--trace-out", default=None,
                    help="where to write the merged Chrome trace "
                         "(default: a temp file)")
    args = ap.parse_args()

    from test_cluster import Cluster

    import trace_export
    from summerset_tpu.client.drivers import DriverClosedLoop
    from summerset_tpu.client.endpoint import (
        GenericEndpoint, scrape_flight,
    )

    tmp = tempfile.mkdtemp(prefix="trace_smoke_")
    cluster = Cluster("MultiPaxos", 3, tmp, config={"trace_sample": 1})
    out = {"platform": jax.devices()[0].platform}
    try:
        ep = GenericEndpoint(cluster.manager_addr)
        ep.connect()
        drv = DriverClosedLoop(ep)
        drv.checked_put("warm", "1")  # jit warm-up before any timing

        if not args.skip_overhead:
            ov = overhead_gate(cluster, ep, args.pairs, args.window,
                               max_pct=args.max_overhead_pct)
            print(json.dumps(ov), flush=True)
            out["overhead"] = ov
            if ov["overhead_pct"] > args.max_overhead_pct:
                print(
                    f"FAIL: flight recorder costs "
                    f"{ov['overhead_pct']}% > {args.max_overhead_pct}% "
                    "of the pipelined (open-loop) serving rate"
                )
                sys.exit(1)

        # fresh sampled traffic for the causal-chain check (recorder is
        # back on; trace_sample=1 samples every proposed batch)
        for i in range(12):
            drv.checked_put(f"trk{i}", f"v{i}")
        for i in range(12):
            drv.checked_get(f"trk{i}", expect=f"v{i}")
        time.sleep(0.5)  # let followers apply + fsync the tail

        # the manager waits <=15s per fan-out reply; re-scrape if a
        # replica stalled behind a JIT recompile and missed the window
        for _ in range(4):
            dumps = scrape_flight(cluster.manager_addr)
            if len(dumps) == 3:
                break
            time.sleep(2.0)
        ep.leave()
        assert len(dumps) == 3, f"flight scrape incomplete: {dumps.keys()}"

        # graftprof join: subdivide every measured device-scan tick span
        # into named phase child spans from the committed PROFILE.json
        # (clock-aligned with the host spans by construction — they nest
        # inside the measured step stopwatch)
        phase_profile = None
        profile_path = os.path.join(REPO, "PROFILE.json")
        if os.path.exists(profile_path):
            with open(profile_path) as f:
                phase_profile = json.load(f)

        # drop accounting must be self-consistent per dump (schema v2:
        # sum of dropped_by_type == dropped) before anything downstream
        # trusts the per-type counts
        acct_errors = trace_export.validate_dumps(dumps)
        assert not acct_errors, (
            f"drop accounting violations: {acct_errors[:10]}"
        )

        pairs = trace_export.paired_frames(dumps)  # once; export reuses
        doc = trace_export.export_chrome(dumps, pairs=pairs,
                                         phase_profile=phase_profile)
        errors = trace_export.validate_chrome(doc)
        assert not errors, f"schema violations: {errors[:10]}"
        phase_spans = [
            e for e in doc["traceEvents"]
            if str(e.get("name", "")).startswith("phase:")
        ]
        if phase_profile is not None:
            assert phase_spans, (
                "PROFILE.json present but no device phase spans landed "
                "in the export"
            )
        chains = trace_export.find_request_chains(dumps)
        assert chains, "no connected api→propose→commit→apply→reply chain"
        cross = {(p["src"], p["dst"]) for p in pairs}
        assert pairs and all(s != d for s, d in cross), (
            f"no cross-replica tx/rx pairing: {sorted(cross)[:5]}"
        )

        trace_out = args.trace_out or os.path.join(tmp, "trace.json")
        with open(trace_out, "w") as f:
            json.dump(doc, f)
        print(f"chrome trace -> {trace_out} "
              f"({len(doc['traceEvents'])} events)")

        by_type: dict = {}
        for d in dumps.values():
            for ev in d.get("events", []):
                by_type[ev["type"]] = by_type.get(ev["type"], 0) + 1
        c0 = chains[0]
        out["smoke"] = {
            "protocol": "MultiPaxos",
            "replicas": 3,
            "schema_ok": True,
            "chains": len(chains),
            "chain_example": {
                "sid": c0["sid"], "g": c0["g"], "vid": c0["vid"],
                "client": c0["client"], "req_id": c0["req_id"],
                "ingress_to_reply_us": (
                    c0["t_reply_us"] - c0["t_ingress_us"]
                ),
            },
            "paired_frames": len(pairs),
            "device_phase_spans": len(phase_spans),
            "phase_names": sorted({
                str(e["name"])[len("phase:"):] for e in phase_spans
            }),
            "cross_replica_edges": sorted(
                f"{s}->{d}" for s, d in cross
            ),
            "events_by_type": dict(sorted(by_type.items())),
            "dropped": {
                sid: d.get("dropped", 0)
                for sid, d in sorted(dumps.items())
            },
            "dropped_by_type": {
                sid: dict(sorted(d.get("dropped_by_type", {}).items()))
                for sid, d in sorted(dumps.items())
            },
        }
    finally:
        cluster.stop()

    with open(args.out, "w") as f:
        json.dump(out, f, indent=1)
    print(f"trace smoke PASS -> {args.out}", flush=True)
    # daemon replica threads parked in XLA can std::terminate at normal
    # teardown (same rationale as nemesis_soak); results are on disk
    sys.stdout.flush()
    os._exit(0)


if __name__ == "__main__":
    main()
