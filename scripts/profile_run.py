#!/usr/bin/env python3
"""graftprof capture: write the committed PROFILE.json baseline.

Captures, per protocol x config variant at the canonical shape plus an
analytic G-sweep (``summerset_tpu/host/profiling.py``):

- XLA analytic cost model: ``cost_analysis()`` flops / bytes accessed,
  ``memory_analysis()`` argument/output/temp buffer bytes, compile wall
  time, HLO instruction counts (total and per declared phase);
- steady-state wall-clock (best-of-N, shape-matched warmup) and the
  committed-slot rate over the best window;
- MEASURED per-phase device time via ``jax.profiler`` programmatic
  trace capture joined to the phase registry's named scopes;
- the phase-scope instrumentation ablation A/B (< 5% budget);
- the mesh-shape sweep (``mesh_sweep``): per GxR device mesh at a
  fixed global shape, the sharded engine's analytic tick metrics plus
  the scan carry's donation introspection and a progress check — the
  pod-scale judging curve (per-device flops ~linear in groups/device,
  HLO op count flat), captured on the 8-virtual-device CPU platform
  so it stays reproducible with the TPU tunnel down.

PERF.md rounds >= 9 are produced from this file's output
(``--markdown`` prints the breakdown table to paste), not by hand; the
committed PROFILE.json is gated by ``scripts/perf_gate.py`` in ci.sh
tier 2h (analytic metrics strictly, wall-clock variance-aware).

Usage:
    python scripts/profile_run.py                 # write PROFILE.json
    python scripts/profile_run.py --markdown      # + print PERF table
    python scripts/profile_run.py --backend native  # real chip capture
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default=os.path.join(REPO, "PROFILE.json"))
    ap.add_argument("--protocols", default="multipaxos,raft,rspaxos")
    ap.add_argument("--groups", type=int, default=None)
    ap.add_argument("--replicas", type=int, default=None)
    ap.add_argument("--window", type=int, default=None)
    ap.add_argument("--ticks", type=int, default=None)
    ap.add_argument("--reps", type=int, default=None)
    ap.add_argument("--backend", choices=("cpu", "native"), default="cpu",
                    help="'cpu' (the CI/committed baseline backend) or "
                         "'native' (whatever chip is visible — for TPU "
                         "captures that are NOT committed as the gated "
                         "baseline unless CI also runs on that backend)")
    ap.add_argument("--no-overhead", action="store_true")
    ap.add_argument("--no-sweep", action="store_true")
    ap.add_argument("--no-tally-sweep", action="store_true",
                    help="skip the quorum-tally before/after sweep "
                         "(pairwise vs collective per mesh shape — "
                         "core/quorum.py)")
    ap.add_argument("--no-mesh-sweep", action="store_true",
                    help="skip the mesh-shape sweep (analytic + carry-"
                         "donation introspection per GxR mesh; on the "
                         "cpu backend the 8-virtual-device platform "
                         "covers every canonical shape)")
    ap.add_argument("--mesh", default="",
                    help="comma-separated GxR mesh shapes for the sweep "
                         "(e.g. '1x1,4x2'), overriding the canonical "
                         "list — a native-backend capture sweeps the "
                         "shapes the visible pod actually has")
    ap.add_argument("--markdown", action="store_true",
                    help="print the generated PERF.md breakdown table")
    args = ap.parse_args()

    import jax

    if args.backend == "cpu":
        jax.config.update("jax_platforms", "cpu")
        # the mesh sweep needs the virtual multi-device platform; must
        # run before anything initializes the backend (importing
        # summerset_tpu.core below would)
        from summerset_tpu.utils.jaxcompat import set_cpu_devices

        set_cpu_devices(8)
    jax.config.update(
        "jax_compilation_cache_dir", os.path.join(REPO, ".jax_cache")
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    from summerset_tpu.host import profiling

    kw = {}
    if args.groups is not None:
        kw["G"] = args.groups
    if args.replicas is not None:
        kw["R"] = args.replicas
    if args.window is not None:
        kw["W"] = args.window
    if args.ticks is not None:
        kw["ticks"] = args.ticks
    if args.reps is not None:
        kw["reps"] = args.reps

    doc = profiling.build_profile(
        protocols=tuple(
            p.strip() for p in args.protocols.split(",") if p.strip()
        ),
        with_overhead=not args.no_overhead,
        with_sweep=not args.no_sweep,
        with_mesh_sweep=not args.no_mesh_sweep,
        with_tally_sweep=not args.no_tally_sweep,
        mesh_shapes=tuple(
            m.strip() for m in args.mesh.split(",") if m.strip()
        ) or None,
        log=lambda m: print(m, flush=True),
        **kw,
    )
    with open(args.out, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    print(f"wrote {args.out}")
    ov = doc.get("scope_overhead")
    if ov:
        print(f"phase-scope overhead: {ov['pct']}% "
              f"({ov['pairs']} interleaved pairs)")
    if args.markdown:
        print()
        print(profiling.phase_table_markdown(doc))
    return 0


if __name__ == "__main__":
    sys.exit(main())
