#!/usr/bin/env python3
"""Autopilot twin soak: the same shifting workload x nemesis schedule
runs against two identical clusters — autopilot OFF (static knobs,
observe-mode driver attached) and autopilot ON (an act-mode
AutopilotDriver closing the sense -> decide -> actuate loop) — and the
ON cell must degrade gracefully and re-tune itself past every shift.

The schedule (one logical tick axis, ``TICK_LEN`` wall seconds per
tick, shared by workload, faults, and measurement windows):

- ``@0``   steady:    ~0.4x calibrated ingress capacity, plan-A hot keys
- ``@30``  shift 1:   rate jumps to ~2.4x capacity AND the zipfian hot
           key set flips (plan-B streams, different seed) — the lever
           the autopilot has is ``api_max_batch`` retuning on the shed
           EWMA streak (2 -> 4 -> 8 ...), which multiplies the ingress
           tier's per-tick drain; the static twin keeps shedding
- ``@60``  shift 2:   fail-slow injection: ``slow_peer`` (egress
           bandwidth cap + CPU starve) lands on the LIVE leader at fire
           time — the lever is the health_score-sensed ``lead_move``
           (targeted voluntary demotion through the kernel's own
           election); the static twin limps behind its gray leader
- ``@90``  shift 3:   the slow_peer heals and ``slow_disk`` (inflated
           fsync) lands on the CURRENT live leader — lead_move again,
           now from a different signal floor
- measurement windows W1/W2/W3 start 12 ticks after each shift
  (re-tune convergence time) and close at the next shift

Acceptance (gated by scripts/autopilot_gate.py on the committed
AUTOPILOT.json):

- both cells' histories are linearizable (shed puts excluded on the
  never-proposed guarantee) with zero acked-and-shed values;
- the ON cell accepts >= ``MIN_WIN_RATIO`` x the OFF cell in EVERY
  post-shift window;
- bounded convergence: the policy stops actuating in the schedule tail
  (no fired decision after the last window opens + settle), total fires
  stay bounded, and the per-window actuation budget was never exceeded
  (recorded spend <= budget);
- the OFF cell's observe-mode driver logged decisions but sent ZERO
  ctrl mutations (``actuation_log`` empty — byte-identical-to-off);
- actuator coverage: >= 1 ``lead_move`` and >= 1 ``batch`` actuation in
  the ON cell;
- the whole schedule (both workload plans, the fault plan, the shift
  ticks, and the policy knob line) regenerates byte-identically from
  ``AP_SEED`` (``schedule_digest``).

Usage:
    python scripts/autopilot_soak.py            # writes AUTOPILOT.json
"""

from __future__ import annotations

import argparse
import dataclasses
import hashlib
import json
import os
import shutil
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
from summerset_tpu.utils.jaxcompat import set_cpu_devices  # noqa: E402

set_cpu_devices(8)

sys.path.insert(0, os.path.join(REPO, "tests"))

AP_SEED = 1
REPLICAS = 3
# the deliberately small ingress tier from workload_soak: api_max_batch
# caps per-tick drain, which is exactly the knob the autopilot retunes
API_MAX_BATCH = 2
API_MAX_PENDING = 8
CLIENTS = 3
NUM_KEYS = 24
HORIZON = 140            # schedule ticks
TICK_LEN = 0.25          # wall seconds per schedule tick
SHIFTS = (30, 65, 100)   # schedule ticks of the three regime shifts
SETTLE_TICKS = 18        # re-tune convergence allowance per shift
WINDOWS = ((48, 64), (84, 98), (120, 138))
STEADY_X = 0.4           # offered rate, x calibrated capacity
# the overload must be deep enough that the static twin's REAL drain
# (calibration under-reads a steady box) still caps well below the
# offered rate — 4x keeps the achievable on/off contrast comfortably
# above MIN_WIN_RATIO even before the fail-slow shifts land
OVERLOAD_X = 4.0
MIN_WIN_RATIO = 1.5      # ON cell accepted-op floor vs OFF, per window
MAX_TOTAL_FIRES = 12     # convergence: bounded total actuations
AP_SCRAPE_S = 0.6        # autopilot sense cadence (wall seconds)
# fail-slow lowerings (the NemesisRunner constants, retargeted at fire
# time onto the LIVE leader — a seeded plan cannot know elections)
SLOW_PEER_BW = 48_000.0
SLOW_PEER_STARVE = 0.75
SLOW_DISK_X = 45.0

# ---- QuorumLeases multi-group twin cell (the autopilot_ql row) ------
# The lease-plane actuators (conf_resize via client ConfChange,
# reshard via range_change) only exist on lease protocols over a
# multi-group keyspace, which the MultiPaxos ab cell can never cover.
# This cell runs the same off/on twin shape on QuorumLeases x
# QL_GROUPS under zipfian-concentrated heat: the ON driver must LIVE-
# shrink the responder set (heat-concentrated conf_resize through the
# conf_ctl hook) and LIVE-split the hot range (embedded
# ResharderPolicy through the ctrl plane), the OFF observer must stay
# mutation-free, and both histories must stay linearizable with zero
# acked-and-shed values across every actuation.
QL_SEED = 5
QL_GROUPS = 2
QL_HORIZON = 80          # schedule ticks (x TICK_LEN wall seconds)
QL_STEADY_X = 0.5        # offered rate, x calibrated capacity
QL_HOT_SHARE = 0.2       # conf_resize heat-concentration threshold
QL_HEAT_MIN = 10         # min sensed heat delta per round
QL_RESHARD_HOT_FRAC = 0.15
QL_RESHARD_COLD_FRAC = 0.05
QL_MAX_TOTAL_FIRES = 8   # convergence bound for the QL cell


def protocol_config() -> dict:
    return {
        "api_max_batch": API_MAX_BATCH,
        "api_max_pending": API_MAX_PENDING,
        # BOTH cells score health but neither self-mitigates: leader
        # re-placement is the autopilot's actuation, so the contrast
        # measured is the closed loop, not the health plane's reflex
        "health_mitigation": False,
    }


def build_schedule():
    """The cell's three seeded schedules — regenerable by the gate
    without a cluster.  Plan A carries the steady + overload arrival
    phases; plan B is the same shape under a different stream seed (the
    hot-key flip at shift 1); the FaultPlan is the canonical record of
    the two fail-slow injections (targets empty = live leader at fire
    time)."""
    from summerset_tpu.host.nemesis import FaultEvent, FaultPlan
    from summerset_tpu.host.workload import WorkloadPhase, WorkloadPlan

    base = WorkloadPlan.generate(
        AP_SEED, "hot_burst", clients=CLIENTS, num_keys=NUM_KEYS,
        horizon=HORIZON,
    )
    phases = (
        WorkloadPhase(0, SHIFTS[0], STEADY_X),
        WorkloadPhase(SHIFTS[0], HORIZON - SHIFTS[0], OVERLOAD_X),
    )
    wplan_a = dataclasses.replace(base, phases=phases)
    # the hot-key flip: same knobs, different seed -> a different
    # zipfian hot-key identity from shift 1 on
    wplan_b = dataclasses.replace(wplan_a, seed=AP_SEED + 101)
    fplan = FaultPlan(
        seed=AP_SEED, population=REPLICAS, ticks=HORIZON,
        events=(
            FaultEvent(SHIFTS[1], "slow_peer", (), SHIFTS[2] - SHIFTS[1],
                       SLOW_PEER_STARVE),
            FaultEvent(SHIFTS[2], "slow_disk", (), HORIZON - SHIFTS[2],
                       SLOW_DISK_X),
        ),
    )
    return wplan_a, wplan_b, fplan


def make_policy(resharder=None):
    """The soak's policy knobs — shared with the gate so the committed
    ``policy_config_digest`` regenerates.  Cadence-scaled PR-10 style:
    at ``AP_SCRAPE_S`` rounds, streak 2 is ~1.2s of sustained signal,
    cooldown 3 is ~1.8s between fires of one actuator, and the window
    budget caps churn at 2 changes per ~2.4s."""
    from summerset_tpu.host.autopilot import AutopilotPolicy

    return AutopilotPolicy(
        seed=AP_SEED, population=REPLICAS, num_groups=1,
        streak_need=2, cooldown_rounds=3, window_rounds=4,
        budget_per_window=2, resharder=resharder,
    )


def schedule_digest() -> str:
    """One digest over everything the twin cells replay: both workload
    timelines, the fault timeline, the shift/window tick axis, and the
    policy knob line.  The gate regenerates this from source."""
    wa, wb, fp = build_schedule()
    pol = make_policy()
    blob = (
        wa.timeline() + wb.timeline() + fp.timeline()
        + f"shifts={SHIFTS} windows={WINDOWS} settle={SETTLE_TICKS}\n"
        + f"steady_x={STEADY_X:g} overload_x={OVERLOAD_X:g}"
        + f" scrape_s={AP_SCRAPE_S:g} tick_len={TICK_LEN:g}\n"
        + pol.config_line()
    )
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def ql_hash_group(key: str) -> int:
    """Mirrors ServerReplica.group_of over QL_GROUPS — the hash-home
    placement the embedded resharder splits away from."""
    import zlib

    return zlib.crc32(key.encode()) % QL_GROUPS


def build_ql_schedule():
    """The QL cell's workload: one steady phase of zipfian-hot
    read-mostly traffic (heat stays concentrated, so the lease-plane
    levers have a persistent signal to act on).  Regenerable by the
    gate without a cluster."""
    from summerset_tpu.host.workload import WorkloadPhase, WorkloadPlan

    base = WorkloadPlan.generate(
        QL_SEED, "read_mostly", clients=CLIENTS, num_keys=NUM_KEYS,
        horizon=QL_HORIZON,
    )
    return dataclasses.replace(
        base, phases=(WorkloadPhase(0, QL_HORIZON, QL_STEADY_X),)
    )


def make_ql_policy():
    """The QL cell's policy: lease thresholds sized to the cell's
    zipfian top-share (~0.25 over 24 keys) and its sensed heat volume,
    with the embedded resharder budget-gated exactly as the reshard
    soaks wire it.  Shared with the gate (config digest)."""
    from summerset_tpu.host.autopilot import AutopilotPolicy
    from summerset_tpu.host.resharding import ResharderPolicy

    return AutopilotPolicy(
        seed=QL_SEED, population=REPLICAS, num_groups=QL_GROUPS,
        streak_need=2, cooldown_rounds=3, window_rounds=4,
        budget_per_window=2, lease_hot_share=QL_HOT_SHARE,
        heat_min=QL_HEAT_MIN,
        resharder=ResharderPolicy(
            QL_GROUPS, ql_hash_group,
            hot_frac=QL_RESHARD_HOT_FRAC,
            cold_frac=QL_RESHARD_COLD_FRAC, min_total=QL_HEAT_MIN,
        ),
    )


def ql_schedule_digest() -> str:
    """Drift anchor for the QL cell: workload timeline + policy knob
    line + the cell's own axis constants."""
    wplan = build_ql_schedule()
    pol = make_ql_policy()
    blob = (
        wplan.timeline()
        + f"groups={QL_GROUPS} horizon={QL_HORIZON}"
        + f" steady_x={QL_STEADY_X:g} scrape_s={AP_SCRAPE_S:g}"
        + f" tick_len={TICK_LEN:g}\n"
        + pol.config_line()
    )
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


class _ShiftStream:
    """Per-client op stream that serves plan-A ops until the shift event
    fires, then plan-B ops — the hot-key flip, client-side."""

    def __init__(self, a, b, flip: threading.Event):
        self._a, self._b, self._flip = a, b, flip

    def next(self):
        return (self._b if self._flip.is_set() else self._a).next()


class _ShiftPlan:
    """The plan facade ``start_workload_clients`` drives: plan-A
    identity (seed/clients) with flip-aware streams."""

    def __init__(self, wplan_a, wplan_b, flip: threading.Event):
        self._a, self._b = wplan_a, wplan_b
        self._flip = flip
        self.clients = wplan_a.clients
        self.seed = wplan_a.seed

    def opstream(self, ci: int) -> _ShiftStream:
        return _ShiftStream(
            self._a.opstream(ci), self._b.opstream(ci), self._flip
        )


def calibrate_capacity(manager_addr, timeout: float = 5.0) -> float:
    from workload_soak import calibrate_capacity as _cal

    return _cal(manager_addr, CLIENTS, timeout=timeout)


def accepted_in(ops, lo: float, hi: float):
    return [o for o in ops
            if o.acked and not o.shed and lo <= o.t_resp < hi]


def run_cell(mode: str, args, shared: dict) -> dict:
    """One twin cell: ``mode`` is "off" (static knobs + observe-mode
    driver) or "on" (act-mode driver).  Identical schedule both ways."""
    from test_cluster import Cluster

    from summerset_tpu.client.drivers import DriverClosedLoop
    from summerset_tpu.client.endpoint import (
        GenericEndpoint, scrape_metrics,
    )
    from summerset_tpu.client.tester import start_workload_clients
    from summerset_tpu.host.autopilot import AutopilotDriver
    from summerset_tpu.host.messages import CtrlRequest
    from summerset_tpu.utils.linearize import check_history

    wplan_a, wplan_b, fplan = build_schedule()
    sub = {"mode": mode}
    tmp = tempfile.mkdtemp(prefix=f"apsoak_{mode}_")
    cluster = None
    stop = threading.Event()
    flip = threading.Event()
    ops: list = []
    stats: list = []
    threads: list = []
    driver = None
    fault_log: list = []
    try:
        cluster = Cluster(
            "MultiPaxos", REPLICAS, tmp, config=protocol_config(),
            tick=args.tick,
        )
        wep = GenericEndpoint(cluster.manager_addr)
        wep.connect()
        DriverClosedLoop(wep, timeout=10.0).checked_put("warm", "1")
        wep.leave()
        if shared.get("cap") is None:
            # the OFF cell calibrates once; both cells share the
            # offered-rate axis so the per-window ratio compares 1:1
            shared["cap"] = calibrate_capacity(
                cluster.manager_addr, timeout=args.op_timeout,
            )
            time.sleep(min(2.0, API_MAX_PENDING / shared["cap"] + 0.3))
        cap = shared["cap"]
        print(f"--- autopilot_ab {mode}: {cap:.1f} ops/s calibrated, "
              f"schedule {schedule_digest()}")

        pol = make_policy()
        driver = AutopilotDriver(
            cluster.manager_addr, pol,
            mode="act" if mode == "on" else "observe",
            scrape_s=AP_SCRAPE_S, timeout=8.0,
        )
        dthread = threading.Thread(
            target=driver.play, args=(stop,), daemon=True
        )

        t0 = time.monotonic()

        def tick_now() -> float:
            return (time.monotonic() - t0) / TICK_LEN

        def rate_total_of() -> float:
            return wplan_a.rate_x_at(tick_now()) * cap

        plan = _ShiftPlan(wplan_a, wplan_b, flip)
        threads = start_workload_clients(
            cluster.manager_addr, plan, rate_total_of, stop, ops,
            stats, timeout=args.op_timeout,
        )
        dthread.start()
        threads.append(dthread)

        ep = GenericEndpoint(cluster.manager_addr)

        def live_leader() -> int:
            info = ep.ctrl.request(CtrlRequest("query_info"),
                                   timeout=10.0)
            if info.leader is not None:
                return int(info.leader)
            return sorted(info.servers)[0]

        def inject(servers, payload, why) -> None:
            payload = dict(payload)
            payload.setdefault("seed", AP_SEED)
            try:
                ep.ctrl.request(
                    CtrlRequest("inject_faults", servers=servers,
                                payload=payload),
                    timeout=30.0,
                )
                fault_log.append(
                    {"tick": round(tick_now(), 1), "why": why,
                     "servers": list(servers)}
                )
            except Exception as e:
                fault_log.append({"why": why, "error": repr(e)})

        def at_tick(tick: int, fn) -> None:
            lag = t0 + tick * TICK_LEN - time.monotonic()
            if lag > 0:
                time.sleep(lag)
            fn()

        slow_victim: list = []

        def shift1() -> None:
            # rate jump happens in rate_total_of via the phase table;
            # this fires the client-side hot-key flip
            flip.set()
            fault_log.append({"tick": round(tick_now(), 1),
                              "why": "hot_key_flip"})

        def shift2() -> None:
            v = live_leader()
            slow_victim.append(v)
            inject([v], {"net": {"bw": SLOW_PEER_BW,
                                 "starve": SLOW_PEER_STARVE}},
                   "slow_peer@leader")

        def shift3() -> None:
            if slow_victim:
                inject([slow_victim[0]], {"net": None},
                       "slow_peer_heal")
            v = live_leader()
            inject([v], {"wal": {"slow": SLOW_DISK_X}},
                   "slow_disk@leader")

        for tick, fn in zip(SHIFTS, (shift1, shift2, shift3)):
            th = threading.Thread(target=at_tick, args=(tick, fn),
                                  daemon=True)
            th.start()
            threads.append(th)

        # convergence tail: the last window's settle point is the last
        # moment the policy is ALLOWED to actuate; ACTUATING decisions
        # fired after it count against convergence ("recommend" is
        # log-only advice, not an actuation — it may land anywhere)
        def n_actuating() -> int:
            return len([d for d in pol.decisions()
                        if d.actuator != "recommend"])

        tail_tick = WINDOWS[2][0]
        n_dec_at_tail: list = []
        threads.append(threading.Thread(
            target=at_tick,
            args=(tail_tick,
                  lambda: n_dec_at_tail.append(n_actuating())),
            daemon=True,
        ))
        threads[-1].start()

        horizon_s = HORIZON * TICK_LEN
        time.sleep(max(0.0, t0 + horizon_s - time.monotonic()))
        time.sleep(2.0)   # drain inflight past the horizon
        stop.set()
        for t in threads:
            t.join(timeout=60)

        # heal everything before the recovery write
        inject(None, {"net": None, "wal": None}, "heal_all")

        t_heal = time.monotonic()
        budget_s = args.budget_ticks * args.tick
        rep_ep = GenericEndpoint(cluster.manager_addr)
        rep_ep.connect()
        drv = DriverClosedLoop(rep_ep, timeout=min(5.0, budget_s))
        recovered = False
        while time.monotonic() - t_heal < budget_s:
            r = drv.put("ap_recovery", f"m-{mode}")
            if r.kind == "success":
                recovered = True
                break
            drv._retry_pause(r)
        rep_ep.leave()
        sub["recovered"] = recovered
        sub["recovery_ticks"] = int((time.monotonic() - t_heal)
                                    / args.tick)

        sub["num_ops"] = len(ops)
        sub["issued"] = sum(s["issued"] for s in stats)
        sub["acked"] = sum(s["acked"] for s in stats)
        sub["shed"] = sum(s["shed"] for s in stats)
        sub["fault_log"] = fault_log

        # per-window accepted ops (wall windows off the schedule axis)
        sub["window_accepted"] = [
            len(accepted_in(ops, t0 + lo * TICK_LEN, t0 + hi * TICK_LEN))
            for lo, hi in WINDOWS
        ]

        # no ack lost across any actuation: a value must never be both
        # acked and negatively acked
        acked_vals = {o.value for o in ops
                      if o.kind == "put" and o.acked and not o.shed}
        shed_vals = {o.value for o in ops if o.shed}
        sub["ack_shed_overlap"] = len(acked_vals & shed_vals)

        # policy telemetry: the decision trace is the cell's flight
        # recorder (seeded-deterministic given the sensed sequence)
        sub["decisions"] = [d.render() for d in pol.decisions()]
        sub["decision_digest"] = pol.digest()
        sub["policy_config_digest"] = pol.config_digest()
        sub["fires"] = pol.fires()
        sub["max_window_spend"] = pol.max_window_spend
        sub["budget_per_window"] = pol.budget_per_window
        sub["actuations"] = list(driver.actuation_log)
        sub["n_actuations"] = len(driver.actuation_log)
        sub["n_decisions_at_tail"] = (
            n_dec_at_tail[0] if n_dec_at_tail else None
        )
        sub["tail_decisions"] = (
            n_actuating() - n_dec_at_tail[0]
            if n_dec_at_tail else None
        )

        full = scrape_metrics(cluster.manager_addr)
        sub["api_shed"] = {
            sid: snap.get("host", {}).get("counters", {})
                     .get("api_shed", 0)
            for sid, snap in (full or {}).items()
        }
        sub["autopilot_actions"] = {
            sid: {
                k: v
                for k, v in snap.get("host", {})
                               .get("counters", {}).items()
                if k.startswith("autopilot_actions")
            }
            for sid, snap in (full or {}).items()
        }
        sub["api_max_batch_final"] = {
            sid: snap.get("api_max_batch")
            for sid, snap in (full or {}).items()
        }
        sub["leader_demotions"] = {
            sid: snap.get("host", {}).get("counters", {})
                     .get("leader_demotions", 0)
            for sid, snap in (full or {}).items()
        }
        try:
            ep.ctrl.close()
        except Exception:
            pass

        ok, diag = check_history(ops)
        sub["linearizable"] = bool(ok)
        if not ok:
            sub["error"] = diag
        return sub
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
        if driver is not None:
            driver.close()
        if cluster is not None:
            cluster.stop()
        if not sub.get("linearizable"):
            dump = os.path.splitext(args.out)[0] + f"_{mode}_fail.json"
            with open(dump, "w") as f:
                json.dump({
                    **{k: v for k, v in sub.items()},
                    "workload_timeline_a": wplan_a.timeline(),
                    "workload_timeline_b": wplan_b.timeline(),
                    "fault_timeline": fplan.timeline(),
                    "history": [
                        {
                            "client": o.client, "kind": o.kind,
                            "key": o.key, "value": o.value,
                            "t_inv": o.t_inv,
                            "t_resp": (None if o.t_resp == float("inf")
                                       else o.t_resp),
                            "acked": o.acked, "shed": o.shed,
                        }
                        for o in sorted(ops, key=lambda o: o.t_inv)
                    ],
                }, f, indent=1)
            print(f"FAIL bundle -> {dump}")
        shutil.rmtree(tmp, ignore_errors=True)


def run_ql_cell(mode: str, args, shared: dict) -> dict:
    """One QL twin cell: QuorumLeases over ``QL_GROUPS`` groups with
    the lease plane live (wide responder conf installed up front) under
    steady zipfian-hot traffic.  ``mode`` "off" attaches an observing
    driver (zero mutations); "on" closes the loop with the conf_ctl
    hook (live client ConfChange) and the ctrl plane (range_change)."""
    from test_cluster import Cluster

    from summerset_tpu.client.drivers import DriverClosedLoop
    from summerset_tpu.client.endpoint import (
        GenericEndpoint, scrape_metrics,
    )
    from summerset_tpu.client.tester import start_workload_clients
    from summerset_tpu.host.autopilot import AutopilotDriver
    from summerset_tpu.utils.linearize import check_history

    wplan = build_ql_schedule()
    sub = {"mode": mode}
    tmp = tempfile.mkdtemp(prefix=f"apql_{mode}_")
    cluster = None
    stop = threading.Event()
    ops: list = []
    stats: list = []
    threads: list = []
    driver = None
    conf_state = {"responders": sorted(range(REPLICAS)), "log": []}
    try:
        cluster = Cluster(
            "QuorumLeases", REPLICAS, tmp, config=protocol_config(),
            tick=args.tick, num_groups=QL_GROUPS,
        )
        wep = GenericEndpoint(cluster.manager_addr)
        wep.connect()
        wdrv = DriverClosedLoop(wep, timeout=10.0)
        wdrv.checked_put("warm", "1")
        # the lease plane must be LIVE in both modes (the ON cell's
        # lever is re-sizing it, not bootstrapping it): grant read
        # leases everywhere before the schedule clock starts
        wdrv.conf_change(
            {"responders": list(range(REPLICAS))}
        )
        wep.leave()
        time.sleep(2.0)  # lease grants settle
        if shared.get("ql_cap") is None:
            shared["ql_cap"] = calibrate_capacity(
                cluster.manager_addr, timeout=args.op_timeout,
            )
            time.sleep(
                min(2.0, API_MAX_PENDING / shared["ql_cap"] + 0.3)
            )
        cap = shared["ql_cap"]
        print(f"--- autopilot_ql {mode}: {cap:.1f} ops/s calibrated, "
              f"schedule {ql_schedule_digest()}")

        pol = make_ql_policy()

        def conf_ctl(target) -> None:
            # live responder re-size through a real client endpoint —
            # the same ConfChange transport an operator would drive
            try:
                cep = GenericEndpoint(cluster.manager_addr)
                cep.connect()
                r = DriverClosedLoop(cep, timeout=8.0).conf_change(
                    {"responders": [int(t) for t in target]}
                )
                cep.leave()
            except Exception as e:
                conf_state["log"].append(
                    {"target": list(target), "error": repr(e)}
                )
                return
            okc = r.kind == "success"
            conf_state["log"].append(
                {"target": sorted(int(t) for t in target), "ok": okc}
            )
            if okc:
                conf_state["responders"] = sorted(
                    int(t) for t in target
                )

        def sense_fn():
            # the live scrape carries no responder conf; overlay the
            # soak's tracked conf (updated on every successful
            # ConfChange) so _eval_conf_resize sees the installed set
            senses = driver._scrape()
            if senses is not None:
                senses["responders"] = list(conf_state["responders"])
            return senses

        driver = AutopilotDriver(
            cluster.manager_addr, pol,
            mode="act" if mode == "on" else "observe",
            scrape_s=AP_SCRAPE_S, timeout=8.0,
            conf_ctl=conf_ctl, sense_fn=sense_fn,
        )
        t0 = time.monotonic()

        def rate_total_of() -> float:
            tick = (time.monotonic() - t0) / TICK_LEN
            return wplan.rate_x_at(tick) * cap

        threads = start_workload_clients(
            cluster.manager_addr, wplan, rate_total_of, stop, ops,
            stats, timeout=args.op_timeout,
        )
        dthread = threading.Thread(
            target=driver.play, args=(stop,), daemon=True
        )
        dthread.start()
        threads.append(dthread)

        horizon_s = QL_HORIZON * TICK_LEN
        time.sleep(max(0.0, t0 + horizon_s - time.monotonic()))
        time.sleep(2.0)   # drain inflight past the horizon
        stop.set()
        for t in threads:
            t.join(timeout=60)

        t_heal = time.monotonic()
        budget_s = args.budget_ticks * args.tick
        rep_ep = GenericEndpoint(cluster.manager_addr)
        rep_ep.connect()
        drv = DriverClosedLoop(rep_ep, timeout=min(5.0, budget_s))
        recovered = False
        while time.monotonic() - t_heal < budget_s:
            r = drv.put("ql_recovery", f"m-{mode}")
            if r.kind == "success":
                recovered = True
                break
            drv._retry_pause(r)
        rep_ep.leave()
        sub["recovered"] = recovered
        sub["recovery_ticks"] = int((time.monotonic() - t_heal)
                                    / args.tick)

        sub["num_ops"] = len(ops)
        sub["issued"] = sum(s["issued"] for s in stats)
        sub["acked"] = sum(s["acked"] for s in stats)
        sub["shed"] = sum(s["shed"] for s in stats)
        sub["conf_log"] = conf_state["log"]
        sub["responders_final"] = conf_state["responders"]

        acked_vals = {o.value for o in ops
                      if o.kind == "put" and o.acked and not o.shed}
        shed_vals = {o.value for o in ops if o.shed}
        sub["ack_shed_overlap"] = len(acked_vals & shed_vals)

        sub["decisions"] = [d.render() for d in pol.decisions()]
        sub["decision_digest"] = pol.digest()
        sub["policy_config_digest"] = pol.config_digest()
        sub["fires"] = pol.fires()
        sub["max_window_spend"] = pol.max_window_spend
        sub["budget_per_window"] = pol.budget_per_window
        sub["actuations"] = list(driver.actuation_log)
        sub["n_actuations"] = len(driver.actuation_log)

        full = scrape_metrics(cluster.manager_addr)
        splits, merges, api_shed = {}, {}, {}
        for sid, snap in (full or {}).items():
            ctr = snap.get("host", {}).get("counters", {})
            splits[sid] = ctr.get("reshard_splits", 0)
            merges[sid] = ctr.get("reshard_merges", 0)
            api_shed[sid] = ctr.get("api_shed", 0)
        sub["reshard_splits"] = splits
        sub["reshard_merges"] = merges
        sub["api_shed"] = api_shed
        sub["splits"] = max(splits.values(), default=0)
        sub["merges"] = max(merges.values(), default=0)

        ok, diag = check_history(ops)
        sub["linearizable"] = bool(ok)
        if not ok:
            sub["error"] = diag
        return sub
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
        if driver is not None:
            driver.close()
        if cluster is not None:
            cluster.stop()
        if not sub.get("linearizable"):
            dump = os.path.splitext(args.out)[0] + (
                f"_ql_{mode}_fail.json"
            )
            with open(dump, "w") as f:
                json.dump({
                    **{k: v for k, v in sub.items()},
                    "workload_timeline": wplan.timeline(),
                    "history": [
                        {
                            "client": o.client, "kind": o.kind,
                            "key": o.key, "value": o.value,
                            "t_inv": o.t_inv,
                            "t_resp": (None if o.t_resp == float("inf")
                                       else o.t_resp),
                            "acked": o.acked, "shed": o.shed,
                        }
                        for o in sorted(ops, key=lambda o: o.t_inv)
                    ],
                }, f, indent=1)
            print(f"FAIL bundle -> {dump}")
        shutil.rmtree(tmp, ignore_errors=True)


def run_ql_ab(args) -> dict:
    """The QL twin row: off/on over the same QL schedule.  Acceptance:
    both histories linearizable with zero acked-and-shed values and a
    bounded recovery; the ON cell fired AND lowered >= 1 conf_resize
    (responder set actually re-installed through ConfChange) and >= 1
    reshard (split actually adopted server-side) with actuation still
    budget-bounded; the OFF observer sent zero mutations."""
    wplan = build_ql_schedule()
    pol = make_ql_policy()
    row = {
        "kind": "autopilot_ql", "protocol": "QuorumLeases",
        "seed": QL_SEED, "replicas": REPLICAS,
        "num_groups": QL_GROUPS,
        "wl_digest": wplan.digest(),
        "schedule_digest": ql_schedule_digest(),
        "policy_config": pol.config_line(),
        "policy_config_digest": pol.config_digest(),
        "ok": False,
    }
    shared: dict = {"ql_cap": None}
    row["off"] = run_ql_cell("off", args, shared)
    row["on"] = run_ql_cell("on", args, shared)
    row["capacity_ops_s"] = round(shared["ql_cap"] or 0.0, 1)

    on, off = row["on"], row["off"]
    errs = []
    for mode in ("off", "on"):
        sub = row[mode]
        if not sub.get("linearizable"):
            errs.append(f"{mode} history not linearizable "
                        f"({sub.get('error')})")
        if sub.get("ack_shed_overlap"):
            errs.append(f"{mode}: {sub['ack_shed_overlap']} values "
                        "both acked and shed")
        if sub.get("num_ops", 0) < args.min_ops:
            errs.append(f"{mode} history too small: "
                        f"{sub.get('num_ops')}")
        if not sub.get("recovered"):
            errs.append(f"{mode} no recovery within budget")
    fires = on.get("fires") or {}
    if fires.get("conf_resize", 0) < 1:
        errs.append("no conf_resize actuation fired in the on cell")
    if fires.get("reshard", 0) < 1:
        errs.append("no reshard actuation fired in the on cell")
    if not any(c.get("ok") for c in (on.get("conf_log") or [])):
        errs.append("no responder conf actually re-installed live")
    if on.get("splits", 0) < 1:
        errs.append("no live split executed in the on cell")
    if sum(fires.values()) > QL_MAX_TOTAL_FIRES:
        errs.append(f"unbounded actuation: {fires}")
    if on.get("max_window_spend", 0) > on.get("budget_per_window", 0):
        errs.append("per-window actuation budget exceeded")
    if off.get("n_actuations") != 0:
        errs.append(f"observe-mode driver sent "
                    f"{off.get('n_actuations')} ctrl mutations")
    if off.get("splits", 0) or off.get("merges", 0):
        errs.append("off cell executed range changes")
    row["ok"] = not errs
    if errs:
        row["error"] = "; ".join(errs)
    return row


def run_ab(args) -> dict:
    wplan_a, wplan_b, fplan = build_schedule()
    pol = make_policy()
    row = {
        "kind": "autopilot_ab", "protocol": "MultiPaxos",
        "seed": AP_SEED, "replicas": REPLICAS,
        "wl_digest_a": wplan_a.digest(),
        "wl_digest_b": wplan_b.digest(),
        "fault_digest": fplan.digest(),
        "schedule_digest": schedule_digest(),
        "policy_config": pol.config_line(),
        "policy_config_digest": pol.config_digest(),
        "shifts": list(SHIFTS),
        "windows": [list(w) for w in WINDOWS],
        "min_win_ratio": MIN_WIN_RATIO,
        "ok": False,
    }
    shared: dict = {"cap": None}
    row["off"] = run_cell("off", args, shared)
    row["on"] = run_cell("on", args, shared)
    row["capacity_ops_s"] = round(shared["cap"] or 0.0, 1)

    on, off = row["on"], row["off"]
    ratios = [
        round(a / max(b, 1), 2)
        for a, b in zip(on.get("window_accepted", []),
                        off.get("window_accepted", []))
    ]
    row["window_ratios"] = ratios
    errs = []
    for mode in ("off", "on"):
        sub = row[mode]
        if not sub.get("linearizable"):
            errs.append(f"{mode} history not linearizable "
                        f"({sub.get('error')})")
        if sub.get("ack_shed_overlap"):
            errs.append(f"{mode}: {sub['ack_shed_overlap']} values "
                        "both acked and shed")
        if sub.get("num_ops", 0) < args.min_ops:
            errs.append(f"{mode} history too small: "
                        f"{sub.get('num_ops')}")
        if not sub.get("recovered"):
            errs.append(f"{mode} no recovery within budget")
    # graceful degradation beats static knobs after EVERY shift
    for i, r in enumerate(ratios):
        if r < MIN_WIN_RATIO:
            errs.append(
                f"W{i + 1} on/off accepted ratio {r} < {MIN_WIN_RATIO}"
            )
    # bounded convergence: no actuation after the tail opens, bounded
    # total fires, budget never exceeded
    if on.get("tail_decisions") != 0:
        errs.append(f"policy still actuating in the schedule tail "
                    f"({on.get('tail_decisions')} decisions)")
    if sum((on.get("fires") or {}).values()) > MAX_TOTAL_FIRES:
        errs.append(f"unbounded actuation: {on.get('fires')}")
    if on.get("max_window_spend", 0) > on.get("budget_per_window", 0):
        errs.append("per-window actuation budget exceeded")
    # observe mode is byte-identical to off: decisions logged, zero
    # ctrl mutations sent
    if off.get("n_actuations") != 0:
        errs.append(f"observe-mode driver sent "
                    f"{off.get('n_actuations')} ctrl mutations")
    # actuator coverage in the ON cell
    fires = on.get("fires") or {}
    if fires.get("lead_move", 0) < 1:
        errs.append("no lead_move actuation fired in the on cell")
    if fires.get("batch", 0) < 1:
        errs.append("no batch actuation fired in the on cell")
    row["ok"] = not errs
    if errs:
        row["error"] = "; ".join(errs)
    return row


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--tick", type=float, default=0.005)
    ap.add_argument("--op-timeout", type=float, default=5.0)
    ap.add_argument("--min-ops", type=int, default=60)
    ap.add_argument("--budget-ticks", type=int, default=4000)
    ap.add_argument("--out",
                    default=os.path.join(REPO, "AUTOPILOT.json"))
    args = ap.parse_args()

    row = run_ab(args)
    status = "PASS" if row["ok"] else f"FAIL ({row.get('error')})"
    on = row.get("on") or {}
    print(f"=== autopilot_ab: {status} "
          f"(ratios={row.get('window_ratios')}, "
          f"fires={on.get('fires')}, "
          f"batch_final={on.get('api_max_batch_final')})")

    ql_row = run_ql_ab(args)
    ql_status = ("PASS" if ql_row["ok"]
                 else f"FAIL ({ql_row.get('error')})")
    ql_on = ql_row.get("on") or {}
    print(f"=== autopilot_ql: {ql_status} "
          f"(fires={ql_on.get('fires')}, "
          f"splits={ql_on.get('splits')}, "
          f"responders={ql_on.get('responders_final')})")

    with open(args.out, "w") as f:
        json.dump([row, ql_row], f, indent=1)
    print(f"wrote {args.out}")
    sys.stdout.flush()
    sys.stderr.flush()
    # hard exit: same rationale as workload_soak (daemon replica
    # threads frozen mid-XLA can std::terminate after results land)
    os._exit(0 if (row["ok"] and ql_row["ok"]) else 1)


if __name__ == "__main__":
    main()
