#!/usr/bin/env python3
"""Launch a fleet of bench/tester client processes against a running
cluster and aggregate their results.

Parity: reference ``scripts/local_clients.py`` — spawns M client
processes of a chosen utility, waits for all, merges their output
(summed throughput, max tail latency for bench; AND of pass/fail for
tester).

Usage:
    python scripts/local_clients.py -u bench -m 127.0.0.1:52601 \
        --num-clients 4 --secs 10 [--put-ratio 0.5] [--value-size 128]
    python scripts/local_clients.py -u tester -m 127.0.0.1:52601
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("-u", "--utility", default="bench",
                    choices=["bench", "tester"])
    ap.add_argument("-m", "--manager", default="127.0.0.1:52601")
    ap.add_argument("--num-clients", type=int, default=4)
    ap.add_argument("--secs", type=float, default=10.0)
    ap.add_argument("--freq", type=float, default=0.0)
    ap.add_argument("--put-ratio", type=float, default=0.5)
    ap.add_argument("--value-size", default="128")
    ap.add_argument("--num-keys", type=int, default=64)
    ap.add_argument("--trace-file", default=None)
    ap.add_argument("--tests", default="")
    args = ap.parse_args()

    env = dict(os.environ)
    env.setdefault("PYTHONPATH", REPO)
    cmd = [
        sys.executable, "-m", "summerset_tpu.cli.client",
        "-u", args.utility, "-m", args.manager,
    ]
    if args.utility == "bench":
        cmd += [
            "--secs", str(args.secs), "--freq", str(args.freq),
            "--put-ratio", str(args.put_ratio),
            "--value-size", str(args.value_size),
            "--num-keys", str(args.num_keys),
        ]
        if args.trace_file:
            cmd += ["--trace-file", args.trace_file]
    elif args.tests:
        cmd += ["--tests", args.tests]

    procs = [
        subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                         stderr=subprocess.DEVNULL, text=True)
        for _ in range(args.num_clients)
    ]
    outs = []
    rc = 0
    for p in procs:
        out, _ = p.communicate(timeout=args.secs + 300)
        rc |= p.returncode
        for line in out.splitlines():
            line = line.strip()
            if line.startswith("{"):
                outs.append(json.loads(line))

    if args.utility == "bench":
        agg = {
            "clients": len(outs),
            "tput": round(sum(o.get("tput", 0.0) for o in outs), 2),
            "lat_p50_ms": round(
                max((o.get("lat_p50_ms", 0.0) for o in outs), default=0), 3
            ),
            "lat_p99_ms": round(
                max((o.get("lat_p99_ms", 0.0) for o in outs), default=0), 3
            ),
        }
        print(json.dumps(agg))
    else:
        merged = {}
        for o in outs:
            for k, v in o.items():
                if merged.get(k, "PASS") == "PASS":
                    merged[k] = v
        print(json.dumps(merged))
        if any(v != "PASS" for v in merged.values()):
            rc |= 1
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
