"""Live keyspace resharding (host/resharding.py + the server's
seal/barrier/adopt path): unit coverage for the pure pieces
(RangeChange validation, RangeTable, RangeHeat, ResharderPolicy) plus
live seal-barrier edge cases on a 2-group cluster — writes in flight
at the seal slot, duplicate installs over the same range, merge back,
and crash-recovery around the cutover without losing acked writes."""

import threading
import time
import zlib

import pytest

from summerset_tpu.host.messages import CtrlRequest
from summerset_tpu.host.resharding import (
    RangeChange, RangeHeat, RangeTable, ResharderPolicy,
    single_key_range,
)
from summerset_tpu.utils.errors import SummersetError

GROUPS = 2


def home_of(key: str) -> int:
    return zlib.crc32(key.encode()) % GROUPS


def away_of(key: str) -> int:
    return (home_of(key) + 1) % GROUPS


# ---------------------------------------------------------------- units --
class TestRangeChange:
    def test_validate_accepts_split_and_merge(self):
        for op in ("split", "merge"):
            ch = RangeChange.from_payload(
                {"op": op, "start": "a", "end": "b", "dst_group": 1}
            )
            assert ch.op == op and ch.rc_id == 0

    def test_validate_rejects_bad_payloads(self):
        bad = (
            {"op": "rotate", "start": "a", "end": "b", "dst_group": 0},
            {"op": "split", "start": "b", "end": "a", "dst_group": 0},
            {"op": "split", "start": "a", "end": "a", "dst_group": 0},
            {"op": "split", "start": "a", "end": "b", "dst_group": -1},
            {"op": "split", "start": 7, "end": None, "dst_group": 0},
        )
        for payload in bad:
            with pytest.raises(SummersetError):
                RangeChange.from_payload(payload)

    def test_single_key_range_contains_exactly_the_key(self):
        start, end = single_key_range("wk")
        ch = RangeChange("split", start, end, 1)
        assert ch.contains("wk")
        assert not ch.contains("wk0") and not ch.contains("wj")
        assert not ch.contains("wka")

    def test_unbounded_end(self):
        ch = RangeChange.from_payload(
            {"op": "split", "start": "m", "end": None, "dst_group": 1}
        )
        assert ch.contains("zzz") and not ch.contains("a")


class TestRangeTable:
    def test_install_idempotent_per_rc_id(self):
        rt = RangeTable()
        e = {"rc_id": 1, "op": "split", "start": "a", "end": "b",
             "group": 1}
        assert rt.install(e) is True
        assert rt.install(dict(e)) is False  # duplicate adopt: no-op
        assert rt.group_for("a") == 1
        assert rt.group_for("b") is None     # miss -> hash fallback
        assert rt.has(1) and not rt.has(2)

    def test_later_install_overrides_overlap(self):
        rt = RangeTable()
        rt.install({"rc_id": 1, "op": "split", "start": "a",
                    "end": "c", "group": 1})
        rt.install({"rc_id": 2, "op": "merge", "start": "a",
                    "end": "b", "group": 0})
        assert rt.group_for("a") == 0   # merged back
        assert rt.group_for("b") == 1   # sliver still moved
        assert [e["rc_id"] for e in rt.entries()] == [1, 2]


class TestRangeHeat:
    def test_counts_and_top_ordering(self):
        h = RangeHeat()
        for _ in range(5):
            h.note("hot")
        h.note("warm", 2)
        h.note("cold")
        assert h.top(2) == [("hot", 5), ("warm", 2)]
        assert h.total() == 8

    def test_spill_bucket_bounds_cardinality(self):
        h = RangeHeat(cap=4)
        for i in range(10):
            h.note(f"k{i}")
        assert len(h._counts) <= 4 + 1
        assert h.total() == 10
        # the spill bucket never surfaces as a top key
        assert all(k != RangeHeat.SPILL for k, _ in h.top(10))


class TestResharderPolicy:
    def _pol(self, **kw):
        return ResharderPolicy(GROUPS, home_of, **kw)

    def test_splits_hot_key_once(self):
        pol = self._pol(hot_frac=0.25, min_total=10)
        heat = {"hot": 50, "a": 5, "b": 5}
        ch = pol.decide(heat)
        assert ch is not None and ch.op == "split"
        assert ch.contains("hot") and ch.dst_group == away_of("hot")
        # already moved: no duplicate split from the same heat
        assert pol.decide(heat) is None

    def test_merges_cooled_key_back(self):
        pol = self._pol(hot_frac=0.25, cold_frac=0.05, min_total=10)
        assert pol.decide({"hot": 50, "a": 5}).op == "split"
        # no single key hot enough to split, the moved key fully cold
        cooled = {"hot": 0, **{f"k{i}": 2 for i in range(10)}}
        ch = pol.decide(cooled)
        assert ch is not None and ch.op == "merge"
        assert ch.contains("hot") and ch.dst_group == home_of("hot")

    def test_below_min_total_or_single_group_is_quiet(self):
        pol = self._pol(min_total=100)
        assert pol.decide({"hot": 50}) is None
        one = ResharderPolicy(1, lambda k: 0)
        assert one.decide({"hot": 1000}) is None

    def test_resplit_after_merge_back(self):
        """Regression: a key that split, cooled, and merged back used to
        stay in the policy's moved-set (mapped to its hash-home), so a
        re-heat could never split it again — the heat loop permanently
        pinned it.  The merge must forget the key entirely."""
        pol = self._pol(hot_frac=0.25, cold_frac=0.05, min_total=10)
        assert pol.decide({"hot": 50, "a": 5}).op == "split"
        cooled = {"hot": 0, **{f"k{i}": 2 for i in range(10)}}
        assert pol.decide(cooled).op == "merge"
        assert "hot" not in pol._moved
        # the key re-heats: it must be eligible to split again
        ch = pol.decide({"hot": 50, "a": 5})
        assert ch is not None and ch.op == "split"
        assert ch.contains("hot") and ch.dst_group == away_of("hot")


class TestTailWritesRangeFamilies:
    """Regression: the adopt barrier's voted-tail scan must work for
    every kernel family — ballot families mark votes in ``win_bal``,
    the raft family in ``win_term`` (a Raft soak cell used to crash-
    loop on KeyError('win_bal') the moment a range_change sealed), and
    a family with neither linear-window leaf must read as permanently
    uninspectable (conservative True) rather than raise."""

    @staticmethod
    def _bare_server(marker_leaf, marker, win_abs, win_val):
        import numpy as np

        from summerset_tpu.host.payload import PayloadStore
        from summerset_tpu.host.server import ServerReplica as Server

        srv = Server.__new__(Server)
        srv.me = 0
        srv.G = 1
        srv.applied = [0]
        srv.payloads = PayloadStore(1)
        srv.state = {
            "win_abs": np.asarray([[win_abs]], dtype=np.int32),
            marker_leaf: np.asarray([[marker]], dtype=np.int32),
            "win_val": np.asarray([[win_val]], dtype=np.int32),
        }

        class _Ker:
            VALUE_WINDOW = "win_val"

        srv.kernel = _Ker()
        return srv

    @pytest.mark.parametrize("leaf", ["win_bal", "win_term"])
    def test_marker_leaf_per_family(self, leaf):
        from summerset_tpu.host.messages import ApiRequest
        from summerset_tpu.host.statemach import Command

        srv = self._bare_server(
            leaf, marker=[0, 0, 5, 0], win_abs=[0, 1, 2, 3],
            win_val=[0, 0, 7, 0],
        )
        srv.payloads._data[0][7] = [
            (0, ApiRequest("req", 0, Command("put", "mk", "v")))
        ]
        assert srv._tail_writes_range({"start": "mk", "end": "ml"}) is True
        assert srv._tail_writes_range({"start": "zz", "end": None}) is False

    def test_no_linear_window_is_conservative(self):
        srv = self._bare_server(
            "win_term", marker=[0], win_abs=[0], win_val=[0]
        )
        # epaxos-like state: no linear window leaves at all
        srv.state = {"abs2": srv.state["win_abs"]}
        assert srv._tail_writes_range({"start": "a", "end": None}) is True


class _Recorder:
    def __init__(self):
        self.events = []

    def record(self, kind, **kw):
        self.events.append((kind, kw))


class _Metrics:
    def __init__(self):
        self.counters = {}

    def counter_add(self, name, n=1, **kw):
        self.counters[name] = self.counters.get(name, 0) + n

    def observe(self, *a, **kw):
        pass


class _Ctrl:
    def __init__(self):
        self.inbox = []
        self.sent = []

    def try_recv_ctrl(self):
        return self.inbox.pop(0) if self.inbox else None

    def send_ctrl(self, msg):
        self.sent.append(msg)


def _reshard_server(state_leaves=("win_bal",)):
    """A bare 2-group replica with just enough wiring for the seal/
    adopt/re-announce plane (no transport, no kernel step)."""
    import types

    import numpy as np

    from summerset_tpu.host.payload import PayloadStore
    from summerset_tpu.host.resharding import RangeTable
    from summerset_tpu.host.server import ServerReplica as Server

    srv = Server.__new__(Server)
    srv.me = 0
    srv.G = 2
    srv.applied = [0, 0]
    srv.tick = 0
    srv._epaxos = False
    srv.payloads = PayloadStore(2)
    srv.state = {
        k: np.zeros((2, 1, 4), np.int32)
        for k in ("win_abs", "win_val") + tuple(state_leaves)
    }

    class _Ker:
        VALUE_WINDOW = "win_val"

    srv.kernel = _Ker()
    srv.rangetab = RangeTable()
    srv._range_sealed = {}
    srv._range_adopted = set()
    srv._range_override = set()
    srv._range_seq_seen = 0
    srv._range_adopt_mark = {}
    srv._range_adopt_ready = []
    srv.seal_ttl_ticks = 2400
    srv._range_adopt_granted = set()
    srv._range_expired = set()
    srv._range_intent_sent = {}
    srv._range_expire_sent = {}
    srv._is_leader = np.asarray([True, True])
    srv._wslot = {}
    srv._subs = {}
    srv._sub_seq = 0
    srv._sub_notes = []
    srv.statemach = types.SimpleNamespace(_kv={})
    srv.flight = _Recorder()
    srv.metrics = _Metrics()
    srv.ctrl = _Ctrl()
    srv._wal_append = lambda rec: None
    return srv


class TestReannounceAdoptInterplay:
    """Regression (REVIEW r16 high): the manager's install_ranges
    re-announce used to add rc_id to the ADOPTED idempotency set, so
    when the replicated adopt command later executed at this replica's
    destination-group slot, _apply_adopt early-returned and the
    handed-off KV/wslot merge was silently skipped — a replica that saw
    the re-announce first (plus below-floor source slots it ack-skips)
    had NO path to the moved keys' committed values and diverged
    permanently.  The re-announce may only install the routing
    OVERRIDE; the log-replayed adopt must still merge."""

    ENTRY = {"rc_id": 7, "op": "split", "start": "mk", "end": "mk\x00",
             "group": 1, "floors": [3, 0]}
    ADOPT_VAL = {"rc_id": 7, "op": "split", "start": "mk",
                 "end": "mk\x00", "dst_group": 1,
                 "kv": {"mk": "moved-v"}, "wslots": {"mk": 9},
                 "floors": [3, 0]}

    def _announce(self, srv, seq=1, installed=(), pending=()):
        from summerset_tpu.host.messages import CtrlMsg

        srv.ctrl.inbox.append(CtrlMsg("install_ranges", {
            "seq": seq, "installed": list(installed),
            "pending": list(pending),
        }))
        assert srv._handle_ctrl() is None

    def test_reannounce_does_not_suppress_adopt_merge(self):
        srv = _reshard_server()
        self._announce(srv, installed=[dict(self.ENTRY)])
        # the override routed, but the rc_id is NOT marked adopted
        assert srv.rangetab.group_for("mk") == 1
        assert 7 in srv._range_override
        assert 7 not in srv._range_adopted
        # the replicated adopt executes at its slot: the merge must land
        srv._apply_adopt(dict(self.ADOPT_VAL), announce=False)
        assert srv.statemach._kv.get("mk") == "moved-v"
        assert srv._wslot.get("mk") == 9
        assert 7 in srv._range_adopted
        assert 7 not in srv._range_override
        # ... and adoption stays idempotent for a duplicate re-propose
        srv.statemach._kv["mk"] = "newer"
        srv._apply_adopt(dict(self.ADOPT_VAL), announce=False)
        assert srv.statemach._kv["mk"] == "newer"

    def test_reannounce_unseals_and_blocks_reseal(self):
        srv = _reshard_server()
        srv._range_begin({"rc_id": 7, "op": "split", "start": "mk",
                          "end": "mk\x00", "dst_group": 1})
        assert 7 in srv._range_sealed
        self._announce(srv, installed=[dict(self.ENTRY)])
        assert 7 not in srv._range_sealed
        # a straggling seal fan-out for the same rc_id must not re-seal
        srv._range_begin({"rc_id": 7, "op": "split", "start": "mk",
                          "end": "mk\x00", "dst_group": 1})
        assert 7 not in srv._range_sealed

    def test_snapshot_meta_keeps_override_distinct(self):
        """An override learned from a re-announce must survive recovery
        as an override (adopt replay still merges), not get promoted to
        adopted by the snapshot round-trip."""
        srv = _reshard_server()
        self._announce(srv, installed=[dict(self.ENTRY)])
        meta_ranges = srv.rangetab.entries()
        meta_radopted = sorted(srv._range_adopted)
        assert meta_radopted == []  # what _take_snapshot would persist
        # a recovered replica restores the same split sets
        srv2 = _reshard_server()
        radopted = {int(r) for r in meta_radopted}
        for entry in meta_ranges:
            rc_id = int(entry["rc_id"])
            if rc_id in radopted:
                srv2._range_adopted.add(rc_id)
            else:
                srv2._range_override.add(rc_id)
            srv2.rangetab.install(entry)
        srv2._apply_adopt(dict(self.ADOPT_VAL), announce=False)
        assert srv2.statemach._kv.get("mk") == "moved-v"


class TestSealRefusalAndTwoPhase:
    CH = {"rc_id": 3, "op": "split", "start": "mk", "end": "mk\x00",
          "dst_group": 1}

    def test_no_vote_window_family_refuses_seal(self):
        """Regression (REVIEW r16): kernels with neither win_bal nor
        win_term (chain_rep / simple_push / rep_nothing) used to accept
        the seal while _tail_writes_range stayed conservatively True
        forever — the range shed every op permanently.  The seal must be
        refused up front, like the epaxos leaderless refusal."""
        srv = _reshard_server(state_leaves=())
        srv._range_begin(dict(self.CH))
        assert srv._range_sealed == {}

    def test_epaxos_still_refuses(self):
        srv = _reshard_server()
        srv._epaxos = True
        srv._range_begin(dict(self.CH))
        assert srv._range_sealed == {}

    def test_progress_gates_on_cluster_wide_seal_confirmation(self):
        """Regression (REVIEW r16): the adopt barrier inspected only the
        LOCAL vote window, so the destination leader could propose the
        adopt before every replica had processed the seal fan-out — a
        write admitted by a not-yet-sealed replica could then commit
        above the handoff floor and overwrite a newer destination-group
        value after cutover.  The proposal must wait for the manager's
        seal-complete grant (every server acked)."""
        from summerset_tpu.host.messages import CtrlMsg

        srv = _reshard_server()
        srv._range_begin(dict(self.CH))
        assert 3 in srv._range_sealed
        srv._range_progress()
        assert srv._range_adopt_ready == []       # unconfirmed: held
        assert not any(m.kind == "adopt_intent" for m in srv.ctrl.sent)
        srv._range_sealed[3]["sealed_ok"] = True  # manager re-announce
        # barrier cleared: the leader first asks the manager for the
        # adopt grant (pins the change against seal-TTL expiry) ...
        srv._range_progress()
        assert srv._range_adopt_ready == []
        assert any(m.kind == "adopt_intent"
                   and m.payload["rc_id"] == 3 for m in srv.ctrl.sent)
        # ... and only proposes once the grant lands
        srv.ctrl.inbox.append(
            CtrlMsg("adopt_decision", {"rc_id": 3, "ok": True})
        )
        assert srv._handle_ctrl() is None
        srv._range_progress()
        assert len(srv._range_adopt_ready) == 1
        dst, areq = srv._range_adopt_ready[0]
        assert dst == 1 and areq.cmd.kind == "adopt"
        assert areq.cmd.value["rc_id"] == 3

    def test_install_ranges_pending_updates_seal_flag(self):
        from summerset_tpu.host.messages import CtrlMsg

        srv = _reshard_server()
        srv._range_begin(dict(self.CH))
        srv.ctrl.inbox.append(CtrlMsg("install_ranges", {
            "seq": 1, "installed": [],
            "pending": [dict(self.CH, sealed_ok=True)],
        }))
        assert srv._handle_ctrl() is None
        assert srv._range_sealed[3].get("sealed_ok") is True


# ------------------------------------------------------------- live tier --
@pytest.fixture(scope="module")
def reshard_cluster(tmp_path_factory):
    """One 3-replica MultiPaxos cluster over a 2-group keyspace."""
    from test_cluster import Cluster

    c = Cluster(
        "MultiPaxos", 3, tmp_path_factory.mktemp("reshard_cluster"),
        num_groups=GROUPS,
    )
    yield c
    c.stop()


def _ep(cluster):
    from summerset_tpu.client.endpoint import GenericEndpoint

    ep = GenericEndpoint(cluster.manager_addr)
    ep.connect()
    return ep


def _issue(cluster, op, key, dst, timeout=60.0):
    from summerset_tpu.client.endpoint import GenericEndpoint

    start, end = single_key_range(key)
    ep = GenericEndpoint(cluster.manager_addr)
    rep = ep.ctrl.request(
        CtrlRequest("range_change", payload={
            "op": op, "start": start, "end": end, "dst_group": dst,
        }),
        timeout=timeout,
    )
    ep.ctrl.close()
    assert rep is not None and rep.kind != "error"
    rc_id = (rep.conf or {}).get("rc_id")
    assert rc_id
    return rc_id


def _wait_adopted(cluster, rc_id, timeout=30.0):
    from summerset_tpu.client.endpoint import GenericEndpoint

    ep = GenericEndpoint(cluster.manager_addr)
    t_end = time.monotonic() + timeout
    try:
        while time.monotonic() < t_end:
            info = ep.ctrl.request(CtrlRequest("query_info"))
            installed = {
                e.get("rc_id")
                for e in (getattr(info, "ranges", None) or ())
            }
            if rc_id in installed:
                return
            time.sleep(0.1)
    finally:
        ep.ctrl.close()
    raise AssertionError(f"rc_id {rc_id} never adopted")


def _put_until_acked(drv, key, val, budget=30.0):
    """One write, retried through cutover sheds until acked."""
    t_end = time.monotonic() + budget
    while time.monotonic() < t_end:
        r = drv.put(key, val)
        if r.kind == "success":
            return
        drv._retry_pause(r)
    raise AssertionError(f"put {key}={val} never acked")


class TestLiveCutover:
    def test_split_with_writes_in_flight_at_seal(
        self, reshard_cluster,
    ):
        """Writes race the seal slot: everything acked before, during
        (retried through sheds), and after the cutover must survive —
        the final read observes the last acked value."""
        from summerset_tpu.client.drivers import DriverClosedLoop
        from summerset_tpu.client.endpoint import scrape_metrics

        key = "rs_mk"
        ep = _ep(reshard_cluster)
        drv = DriverClosedLoop(ep, timeout=10.0)
        drv.checked_put(key, "v0")

        acked = ["v0"]
        stop = threading.Event()

        def writer():
            wep = _ep(reshard_cluster)
            wdrv = DriverClosedLoop(wep, timeout=10.0)
            i = 0
            while not stop.is_set():
                val = f"v{i + 1}"
                r = wdrv.put(key, val)
                if r.kind == "success":
                    acked.append(val)
                    i += 1
                else:
                    # cutover shed: client-visible backpressure,
                    # never a lost ack
                    wdrv._retry_pause(r)
            wep.leave()

        wt = threading.Thread(target=writer, daemon=True)
        wt.start()
        time.sleep(0.3)   # writes demonstrably in flight
        rc_id = _issue(reshard_cluster, "split", key, away_of(key))
        _wait_adopted(reshard_cluster, rc_id)
        time.sleep(0.3)   # writes land on the destination group too
        stop.set()
        wt.join(timeout=30)
        assert len(acked) > 1

        drv.checked_get(key, expect=acked[-1])
        # post-cutover the range still serves writes
        drv.checked_put(key, "after-split")
        drv.checked_get(key, expect="after-split")
        # server-side evidence the adoption executed
        full = scrape_metrics(reshard_cluster.manager_addr)
        splits = max(
            snap.get("host", {}).get("counters", {})
                .get("reshard_splits", 0)
            for snap in (full or {}).values()
        )
        assert splits >= 1
        ep.leave()

    def test_duplicate_install_and_merge_back(self, reshard_cluster):
        """A second install over the SAME range (fresh rc_id) is
        absorbed — adoption is idempotent per range content — and the
        merge moves it back to the hash-home without losing state."""
        from summerset_tpu.client.drivers import DriverClosedLoop
        from summerset_tpu.client.endpoint import scrape_metrics

        key = "rs_dup"
        ep = _ep(reshard_cluster)
        drv = DriverClosedLoop(ep, timeout=10.0)
        drv.checked_put(key, "d0")

        rc1 = _issue(reshard_cluster, "split", key, away_of(key))
        _wait_adopted(reshard_cluster, rc1)
        drv.checked_get(key, expect="d0")
        _put_until_acked(drv, key, "d1")

        # duplicate: same range, same destination, new rc_id
        rc2 = _issue(reshard_cluster, "split", key, away_of(key))
        assert rc2 != rc1
        _wait_adopted(reshard_cluster, rc2)
        drv.checked_get(key, expect="d1")

        # merge back to the hash-home
        rc3 = _issue(reshard_cluster, "merge", key, home_of(key))
        _wait_adopted(reshard_cluster, rc3)
        drv.checked_get(key, expect="d1")
        _put_until_acked(drv, key, "d2")
        drv.checked_get(key, expect="d2")
        full = scrape_metrics(reshard_cluster.manager_addr)
        merges = max(
            snap.get("host", {}).get("counters", {})
                .get("reshard_merges", 0)
            for snap in (full or {}).values()
        )
        assert merges >= 1
        ep.leave()

    def test_follower_crash_between_seal_and_adopt(
        self, reshard_cluster,
    ):
        """A durable follower restart racing the seal->adopt window:
        WAL replay re-seals (or replays the adopt) and the manager's
        install_ranges re-announce reconciles the rest — no acked
        write lost, cutover completes cluster-wide."""
        from summerset_tpu.client.drivers import DriverClosedLoop

        key = "rs_ck"
        ep = _ep(reshard_cluster)
        drv = DriverClosedLoop(ep, timeout=10.0)
        drv.checked_put(key, "c0")

        info = ep.ctrl.request(CtrlRequest("query_info"))
        leader = info.leader if info.leader is not None else 0
        victim = next(
            s for s in sorted(info.servers) if s != leader
        )
        rc_id = _issue(reshard_cluster, "split", key, away_of(key))
        # crash the follower immediately — its seal is WAL-durable,
        # the adopt may or may not have reached it yet
        ep.ctrl.request(
            CtrlRequest("reset_servers", servers=[victim],
                        durable=True),
            timeout=180.0,
        )
        _wait_adopted(reshard_cluster, rc_id, timeout=60.0)
        time.sleep(1.0)
        ep.reconnect()
        drv = DriverClosedLoop(ep, timeout=10.0)
        drv.checked_get(key, expect="c0")
        _put_until_acked(drv, key, "c1")
        drv.checked_get(key, expect="c1")
        ep.leave()

    @pytest.mark.slow
    def test_leader_crash_between_seal_and_adopt(
        self, reshard_cluster,
    ):
        """The adopting proposer dies after the seal fan-out: the next
        leader re-drives the cutover from its own durable seal state
        (every replica sealed and WAL-logged the change) — acked
        writes survive, the range eventually serves again."""
        from summerset_tpu.client.drivers import DriverClosedLoop

        key = "rs_lk"
        ep = _ep(reshard_cluster)
        drv = DriverClosedLoop(ep, timeout=10.0)
        drv.checked_put(key, "l0")

        info = ep.ctrl.request(CtrlRequest("query_info"))
        leader = info.leader if info.leader is not None else 0
        rc_id = _issue(reshard_cluster, "split", key, away_of(key))
        ep.ctrl.request(
            CtrlRequest("reset_servers", servers=[leader],
                        durable=True),
            timeout=180.0,
        )
        _wait_adopted(reshard_cluster, rc_id, timeout=120.0)
        time.sleep(1.0)
        ep.reconnect()
        drv = DriverClosedLoop(ep, timeout=10.0)
        t_end = time.monotonic() + 60.0
        while time.monotonic() < t_end:
            r = drv.get(key)
            if r.kind == "success":
                assert r.result and r.result.value == "l0"
                break
            drv._retry_pause(r)
        else:
            raise AssertionError("read never recovered post-crash")
        _put_until_acked(drv, key, "l1", budget=60.0)
        drv.checked_get(key, expect="l1")
        ep.leave()


class TestSealTtlServerSide:
    """The server half of the seal-TTL escape hatch (PR 17): TTL
    tracking rides _range_progress, expiry requests are rate-limited,
    a granted adopt intent pins the seal, and the manager's expired
    re-announce (or an adopt refusal) unseals and blocks re-sealing."""

    CH = {"rc_id": 5, "op": "split", "start": "mk", "end": "mk\x00",
          "dst_group": 1}

    def _sealed_server(self, ttl=100):
        import numpy as np

        srv = _reshard_server()
        srv.seal_ttl_ticks = ttl
        # not a destination leader: the pre-grant leaderless window
        srv._is_leader = np.asarray([False, False])
        srv._range_begin(dict(self.CH))
        assert 5 in srv._range_sealed
        return srv

    def test_ttl_sends_range_expire_rate_limited(self):
        srv = self._sealed_server(ttl=100)
        srv.tick = 100
        srv._range_progress()   # exactly at TTL: not yet past it
        assert not any(m.kind == "range_expire" for m in srv.ctrl.sent)
        srv.tick = 101
        srv._range_progress()
        expires = [m for m in srv.ctrl.sent if m.kind == "range_expire"]
        assert len(expires) == 1 and expires[0].payload["rc_id"] == 5
        assert 5 in srv._range_sealed  # still sealed until the manager rules
        srv.tick = 150
        srv._range_progress()   # within the 200-tick resend window
        assert len([m for m in srv.ctrl.sent
                    if m.kind == "range_expire"]) == 1
        srv.tick = 301
        srv._range_progress()   # resend after the window
        assert len([m for m in srv.ctrl.sent
                    if m.kind == "range_expire"]) == 2

    def test_zero_ttl_disables_expiry(self):
        srv = self._sealed_server(ttl=0)
        srv.tick = 10_000
        srv._range_progress()
        assert not any(m.kind == "range_expire" for m in srv.ctrl.sent)

    def test_granted_change_never_expires(self):
        srv = self._sealed_server(ttl=100)
        srv._range_adopt_granted.add(5)
        srv.tick = 10_000
        srv._range_progress()
        assert not any(m.kind == "range_expire" for m in srv.ctrl.sent)

    def test_expired_announce_unseals_and_blocks_reseal(self):
        from summerset_tpu.host.messages import CtrlMsg

        srv = self._sealed_server()
        srv.ctrl.inbox.append(CtrlMsg("install_ranges", {
            "seq": 1, "installed": [], "pending": [], "expired": [5],
        }))
        assert srv._handle_ctrl() is None
        assert 5 not in srv._range_sealed
        assert 5 in srv._range_expired
        assert srv.metrics.counters.get("reshard_seal_expired") == 1
        assert any(k == "range_unseal" and kw["rc_id"] == 5
                   for k, kw in srv.flight.events)
        # a straggling seal fan-out for the rolled-back change must not
        # re-seal (the rc_id is burned)
        srv._range_begin(dict(self.CH))
        assert 5 not in srv._range_sealed
        # and a duplicate expired announce is a no-op
        srv.ctrl.inbox.append(CtrlMsg("install_ranges", {
            "seq": 2, "installed": [], "pending": [], "expired": [5],
        }))
        assert srv._handle_ctrl() is None
        assert srv.metrics.counters.get("reshard_seal_expired") == 1

    def test_adopt_refusal_unseals(self):
        from summerset_tpu.host.messages import CtrlMsg

        srv = self._sealed_server()
        srv.ctrl.inbox.append(
            CtrlMsg("adopt_decision", {"rc_id": 5, "ok": False})
        )
        assert srv._handle_ctrl() is None
        assert 5 not in srv._range_sealed
        assert 5 in srv._range_expired

    def test_unseal_drops_pending_adopt_proposal(self):
        import numpy as np

        from summerset_tpu.host.messages import CtrlMsg

        srv = self._sealed_server()
        srv._is_leader = np.asarray([True, True])
        srv._range_sealed[5]["sealed_ok"] = True
        srv._range_progress()               # sends adopt_intent
        srv.ctrl.inbox.append(
            CtrlMsg("adopt_decision", {"rc_id": 5, "ok": True})
        )
        assert srv._handle_ctrl() is None
        srv._range_progress()
        assert len(srv._range_adopt_ready) == 1
        srv._range_unseal(5, why="test")
        assert srv._range_adopt_ready == []


@pytest.fixture()
def ttl_cluster(tmp_path_factory):
    """A 3-replica cluster with a SHORT seal TTL (~0.75s of ticks) for
    the live escape-hatch test."""
    from test_cluster import Cluster

    c = Cluster(
        "MultiPaxos", 3, tmp_path_factory.mktemp("ttl_cluster"),
        num_groups=GROUPS, config={"seal_ttl_ticks": 150},
    )
    yield c
    c.stop()


class TestLiveSealTtl:
    def test_leaderless_destination_expires_and_source_resumes(
        self, ttl_cluster,
    ):
        """Adopting-leaderless destination: with the leader (and one
        follower) paused, every replica still seals — ctrl is handled
        even while paused — but nobody can adopt, and the one live
        follower cannot elect itself without quorum.  Its ticks carry
        the seal past the TTL, the manager rolls the change back, and
        after resume the range serves from the SOURCE group with zero
        executed cutovers."""
        from summerset_tpu.client.drivers import DriverClosedLoop
        from summerset_tpu.client.endpoint import scrape_metrics

        key = "rs_ttl"
        ep = _ep(ttl_cluster)
        drv = DriverClosedLoop(ep, timeout=10.0)
        drv.checked_put(key, "t0")

        info = ep.ctrl.request(CtrlRequest("query_info"))
        leader = info.leader if info.leader is not None else 0
        followers = [s for s in sorted(info.servers) if s != leader]
        live = followers[-1]
        paused = [s for s in sorted(info.servers) if s != live]
        rep = ep.ctrl.request(
            CtrlRequest("pause_servers", servers=paused), timeout=60.0,
        )
        assert sorted(rep.done or ()) == paused
        try:
            _issue(ttl_cluster, "split", key, away_of(key))
            # the live follower's ticks must walk the seal past the TTL
            # (150 ticks ~ 0.75s) and the manager must expire it
            deadline = time.monotonic() + 30.0
            expired = 0
            while time.monotonic() < deadline and not expired:
                full = scrape_metrics(ttl_cluster.manager_addr) or {}
                expired = max((
                    snap.get("host", {}).get("counters", {})
                        .get("reshard_seal_expired", 0)
                    for snap in full.values()
                ), default=0)
                time.sleep(0.3)
            assert expired >= 1, "seal never expired"
        finally:
            ep.ctrl.request(
                CtrlRequest("resume_servers", servers=paused),
                timeout=60.0,
            )
        time.sleep(1.0)
        # the rolled-back range serves again — from the source group
        _put_until_acked(drv, key, "t1")
        drv.checked_get(key, expect="t1")
        full = scrape_metrics(ttl_cluster.manager_addr) or {}
        for snap in full.values():
            ctr = snap.get("host", {}).get("counters", {})
            assert ctr.get("reshard_splits", 0) == 0
        # ... and the manager no longer advertises the change
        info = ep.ctrl.request(CtrlRequest("query_info"))
        assert not (getattr(info, "ranges", None) or [])
        ep.leave()
