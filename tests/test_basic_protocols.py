"""Tests for the non-consensus protocol kernels: RepNothing, SimplePush,
ChainRep (reference ``src/protocols/{rep_nothing,simple_push,chain_rep}``).
"""

import jax.numpy as jnp
import numpy as np

from smr_helpers import check_agreement, committed_values, run_segment
from summerset_tpu.core import Engine, NetConfig
from summerset_tpu.protocols import make_protocol
from summerset_tpu.protocols.chain_rep import ReplicaConfigChainRep
from summerset_tpu.protocols.rep_nothing import ReplicaConfigRepNothing
from summerset_tpu.protocols.simple_push import ReplicaConfigSimplePush


class TestRepNothing:
    def test_local_commit_flow(self):
        G, R, W, P = 4, 3, 32, 4
        cfg = ReplicaConfigRepNothing(max_proposals_per_tick=P)
        eng = Engine(make_protocol("repnothing", G, R, W, cfg))
        state, ns = eng.init()
        T = 30
        state, ns, fx = run_segment(eng, state, ns, T, n_prop=P)
        st = {k: np.asarray(v) for k, v in state.items()}
        # serving node (0) commits everything instantly; peers stay at 0
        assert (st["commit_bar"][:, 0] == T * P).all()
        assert (st["commit_bar"][:, 1:] == 0).all()
        vals = committed_values(st, 0, 0, W)
        for slot, v in vals.items():
            assert v == slot

    def test_dur_lag_throttles(self):
        G, R, W, P = 2, 1, 32, 4
        cfg = ReplicaConfigRepNothing(max_proposals_per_tick=P, dur_lag=2)
        eng = Engine(make_protocol("repnothing", G, R, W, cfg))
        state, ns = eng.init()
        state, ns, fx = run_segment(eng, state, ns, 20, n_prop=P)
        st = np.asarray(state["commit_bar"])
        # commit bounded by cumulative dur_lag
        assert (st[:, 0] <= 2 * 20).all()
        assert (st[:, 0] > 0).all()


class TestSimplePush:
    def test_all_ack_commit(self):
        G, R, W, P = 4, 3, 32, 4
        cfg = ReplicaConfigSimplePush(max_proposals_per_tick=P)
        eng = Engine(make_protocol("simplepush", G, R, W, cfg))
        state, ns = eng.init()
        T = 40
        state, ns, fx = run_segment(eng, state, ns, T, n_prop=P)
        st = {k: np.asarray(v) for k, v in state.items()}
        # push + ack round trip ~ 2-3 ticks behind the append frontier
        assert (st["commit_bar"][:, 0] >= (T - 5) * P).all()
        # peers received and committed close behind
        assert (st["commit_bar"][:, 1:] >= (T - 8) * P).all()
        check_agreement(st, G, R, W)
        vals = committed_values(st, 0, 0, W)
        for slot, v in vals.items():
            assert v == slot

    def test_rep_degree_subset(self):
        G, R, W, P = 2, 5, 32, 4
        cfg = ReplicaConfigSimplePush(max_proposals_per_tick=P, rep_degree=2)
        eng = Engine(make_protocol("simplepush", G, R, W, cfg))
        state, ns = eng.init()
        state, ns, fx = run_segment(eng, state, ns, 40, n_prop=P)
        st = {k: np.asarray(v) for k, v in state.items()}
        # pushed peers (1, 2) advance; unpushed (3, 4) stay empty
        assert (st["commit_bar"][:, 0] > 0).all()
        assert (st["commit_bar"][:, 1:3] > 0).all()
        assert (st["commit_bar"][:, 3:] == 0).all()
        check_agreement(st, G, R, W)

    def test_loss_recovery_via_retry(self):
        G, R, W, P = 4, 3, 64, 4
        cfg = ReplicaConfigSimplePush(max_proposals_per_tick=P)
        net = NetConfig(drop_rate=0.2, jitter_ticks=1, max_delay_ticks=3)
        eng = Engine(make_protocol("simplepush", G, R, W, cfg), netcfg=net,
                     seed=9)
        state, ns = eng.init()
        state, ns, fx = run_segment(eng, state, ns, 200, n_prop=P)
        st = {k: np.asarray(v) for k, v in state.items()}
        assert (st["commit_bar"][:, 0] > 100).all()
        check_agreement(st, G, R, W)


class TestChainRep:
    def test_chain_propagation_and_ack_ripple(self):
        G, R, W, P = 4, 4, 32, 4
        cfg = ReplicaConfigChainRep(max_proposals_per_tick=P)
        eng = Engine(make_protocol("chainrep", G, R, W, cfg))
        state, ns = eng.init()
        T = 60
        state, ns, fx = run_segment(eng, state, ns, T, n_prop=P)
        st = {k: np.asarray(v) for k, v in state.items()}
        # pipeline depth ~ 2 ticks per hop down + back up
        lat = 3 * (R - 1) + 4
        assert (st["commit_bar"][:, -1] >= (T - lat) * P).all(), (
            st["commit_bar"]
        )
        # commit ripples up: head close behind tail
        assert (st["commit_bar"][:, 0] >= st["commit_bar"][:, -1] - 4 * P).all()
        # everyone holds identical values (chain copies)
        check_agreement(st, G, R, W)
        vals = committed_values(st, 0, R - 1, W)
        for slot, v in vals.items():
            assert v == slot

    def test_single_node_chain(self):
        G, R, W, P = 2, 1, 32, 4
        cfg = ReplicaConfigChainRep(max_proposals_per_tick=P)
        eng = Engine(make_protocol("chainrep", G, R, W, cfg))
        state, ns = eng.init()
        state, ns, fx = run_segment(eng, state, ns, 20, n_prop=P)
        st = np.asarray(state["commit_bar"])
        assert (st[:, 0] == 20 * P).all()

    def test_loss_recovery(self):
        G, R, W, P = 2, 3, 64, 4
        cfg = ReplicaConfigChainRep(max_proposals_per_tick=P)
        net = NetConfig(drop_rate=0.2, jitter_ticks=1, max_delay_ticks=3)
        eng = Engine(make_protocol("chainrep", G, R, W, cfg), netcfg=net,
                     seed=13)
        state, ns = eng.init()
        state, ns, fx = run_segment(eng, state, ns, 200, n_prop=P)
        st = {k: np.asarray(v) for k, v in state.items()}
        assert (st["commit_bar"][:, -1] > 100).all()
        check_agreement(st, G, R, W)
