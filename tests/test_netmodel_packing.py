"""Lane-packed transport equivalence: NetConfig(pack_lanes=True) must be
bit-identical to the loose-lane path (it only changes HOW lanes ride the
delay line, not what arrives)."""

import jax.numpy as jnp
import numpy as np
import pytest

from summerset_tpu.core import Engine, NetConfig
from summerset_tpu.protocols import make_protocol


def run_pair(name, ticks=60, G=2, R=3, W=64, P=2):
    outs = []
    for pack in (False, True):
        eng = Engine(
            make_protocol(name, G, R, W),
            netcfg=NetConfig(pack_lanes=pack),
            seed=5,
        )
        state, ns = eng.init()
        seq = {
            "n_proposals": jnp.full((ticks, G), P, jnp.int32),
            "value_base": jnp.broadcast_to(
                (1 + jnp.arange(ticks, dtype=jnp.int32) * P)[:, None],
                (ticks, G),
            ),
        }
        state, ns, _ = eng.run_ticks(state, ns, seq)
        outs.append({k: np.asarray(v) for k, v in state.items()})
    return outs


@pytest.mark.parametrize("name", ["multipaxos", "raft", "quorumleases"])
def test_packed_equals_loose(name):
    loose, packed = run_pair(name)
    assert sorted(loose) == sorted(packed)
    for k in loose:
        np.testing.assert_array_equal(
            loose[k], packed[k], err_msg=f"state leaf {k} diverged"
        )


def test_pack_requires_depth_one():
    with pytest.raises(ValueError):
        NetConfig(pack_lanes=True, delay_ticks=2, max_delay_ticks=2)


def test_packed_netstate_shards_onto_mesh():
    """The packed buffers' stacked-lane axis must be replicated, not
    sharded (netstate_sharding special-cases __pair__/__bcast__)."""
    import jax

    from summerset_tpu.core.engine import _tick
    from summerset_tpu.core.sharding import (
        make_mesh,
        shard_netstate,
        shard_pytree,
    )

    eng = Engine(
        make_protocol("multipaxos", 16, 4, 64),
        netcfg=NetConfig(pack_lanes=True),
    )
    mesh = make_mesh(4, 2, devices=jax.devices()[:8])
    state, ns = eng.init()
    state = shard_pytree(mesh, state)
    ns = shard_netstate(mesh, ns)
    inputs = {
        "n_proposals": jnp.full((16,), 2, jnp.int32),
        "value_base": jnp.ones((16,), jnp.int32),
    }
    fn = jax.jit(
        lambda st, n, i: _tick(
            eng.kernel, eng.net, eng._boot, None, st, n, i
        )
    )
    for _ in range(3):
        state, ns, fx = fn(state, ns, inputs)
    jax.block_until_ready(fx.commit_bar)
