"""Analytical-model pillar tests (parity role: reference models/)."""

import sys
import os

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "models")
)

from crossword_model import (  # noqa: E402
    best_assignment,
    shard_loss_tolerance,
    valid_assignments,
)
from bodega_wan import RingWorld, mean_latency_ms, site_latencies  # noqa: E402


class TestCrosswordModel:
    def test_constraint_frontier(self):
        va = dict(
            (spr, q)
            for q, spr in valid_assignments(5, 3, fault_tolerance=1)
        )
        # full copies commit at a bare majority; narrower shards need
        # bigger quorums (coverage under f losses)
        assert va[3] == 3
        assert va[1] > va[3]

    def test_loss_tolerance_monotone_in_spr(self):
        f = [shard_loss_tolerance(5, 3, spr) for spr in (1, 2, 3)]
        assert f == sorted(f)
        assert shard_loss_tolerance(5, 3, 3) == 2  # full copy: majority

    def test_bandwidth_bound_prefers_narrow_shards(self):
        # huge instance on a thin link: shipping 1/d each wins
        q, spr = best_assignment(5, 3, size_kb=4096, delay_ms=1,
                                 bw_gbps=0.5, trials=300)
        assert spr == 1
        # tiny instance on a fat link: latency-bound — the smaller
        # quorum (wider shards) wins over the bandwidth saving
        q2, spr2 = best_assignment(5, 3, size_kb=8, delay_ms=50,
                                   bw_gbps=100, trials=300)
        assert spr2 > 1 and q2 == 3


class TestBodegaWan:
    def test_lease_local_reads_beat_leader_reads(self):
        w = RingWorld()
        lease = mean_latency_ms(w, "lease_local", put_ratio=0.0)
        leader = mean_latency_ms(w, "leader_reads", put_ratio=0.0)
        assert lease < leader

    def test_lease_writes_pay_coverage(self):
        w = RingWorld()
        lease = site_latencies(w, "lease_local")
        leader = site_latencies(w, "leader_reads")
        for c in w.clients:
            assert lease[c]["write_ms"] >= leader[c]["write_ms"]

    def test_read_at_responder_site_is_free(self):
        w = RingWorld()
        per = site_latencies(w, "lease_local")
        on_site = [c for c in w.clients if c in w.servers]
        for c in on_site:
            assert per[c]["read_ms"] == 0.0
