"""Vectorized Bodega kernel tests: config leases, always-local reads at
roster responders, the all-responders write barrier, conf changes with the
revoke-then-adopt install barrier, and conf-based failover (reference
behaviors: ``bodega/conflease.rs:10-47``, ``localread.rs:8-56``,
``heartbeat.rs:85-108``, ``durability.rs:137-175``).
"""

import jax.numpy as jnp
import numpy as np

from smr_helpers import check_agreement, committed_values, run_segment
from summerset_tpu.core import Engine
from summerset_tpu.protocols import make_protocol
from summerset_tpu.protocols.bodega import ReplicaConfigBodega
import pytest


def make_kernel(G, R, W, P, **kw):
    cfg = ReplicaConfigBodega(max_proposals_per_tick=P, **kw)
    return make_protocol("bodega", G, R, W, cfg)


def np_state(state):
    return {k: np.asarray(v) for k, v in state.items()}


def run_with_conf(eng, state, ns, ticks, n_prop, conf=None, alive=None,
                  base_start=0):
    """Segment runner that can carry a conf-change input on the first tick.

    ``conf`` = (init_replica, leader_target, resp_bitmap, bucket or -1).
    """
    G = eng.kernel.G
    P = eng.kernel.config.max_proposals_per_tick
    t = jnp.arange(ticks, dtype=jnp.int32)
    seq = {
        "n_proposals": jnp.full((ticks, G), n_prop, jnp.int32),
        "value_base": jnp.broadcast_to(
            ((base_start + t) * P)[:, None], (ticks, G)
        ),
    }
    if conf is not None:
        init, lead, resp, bucket = conf
        first = (t == 0).astype(jnp.int32)
        seq["conf_init"] = jnp.broadcast_to(
            jnp.where(first, init, -1)[:, None], (ticks, G)
        )
        seq["conf_leader_target"] = jnp.full((ticks, G), lead, jnp.int32)
        seq["conf_resp_target"] = jnp.full((ticks, G), resp, jnp.int32)
        seq["conf_bucket"] = jnp.full((ticks, G), bucket, jnp.int32)
    if alive is not None:
        seq["alive"] = jnp.broadcast_to(alive, (ticks,) + alive.shape)
    return eng.run_ticks(state, ns, seq)


class TestSteadyState:
    def test_commit_flow_and_values(self):
        G, R, W, P = 4, 5, 32, 4
        k = make_kernel(G, R, W, P)
        eng = Engine(k)
        state, ns = eng.init()
        T = 50
        state, ns, _ = run_segment(eng, state, ns, T, n_prop=P)
        st = np_state(state)
        assert (st["commit_bar"][:, 0] >= (T - 6) * P).all(), st["commit_bar"]
        for g in range(G):
            vals = committed_values(st, g, 0, W)
            assert vals
            for slot, v in vals.items():
                assert v == slot
        check_agreement(st, G, R, W)

    def test_sparse_heartbeats_no_spurious_failover(self):
        # AN beacons keep conf_alive fresh every tick, so sparse heartbeats
        # (interval near the conf timeout) cause no spurious conf failover
        G, R, W, P = 2, 5, 32, 4
        k = make_kernel(
            G, R, W, P, hb_send_interval=8, conf_timeout=12,
            hear_timeout_lo=60, hear_timeout_hi=90,
        )
        eng = Engine(k)
        state, ns = eng.init()
        state, ns, _ = run_segment(eng, state, ns, 60, n_prop=P)
        st = np_state(state)
        bal0 = (1 << 8) | 0
        assert (st["conf_bal"] == bal0).all(), st["conf_bal"]
        assert (st["conf_leader"] == 0).all()


class TestConfLeases:
    def test_roster_grants_and_local_reads(self):
        # install a conf (leader 0, responders {0,1,2} on all buckets);
        # after grants propagate, responders serve local reads on all
        # buckets once drained (no pending writes)
        G, R, W, P = 2, 5, 32, 4
        k = make_kernel(G, R, W, P)
        eng = Engine(k)
        state, ns = eng.init()
        resp = 0b00111
        state, ns, _ = run_with_conf(
            eng, state, ns, 60, n_prop=P, conf=(0, 0, resp, -1)
        )
        # drain writes, keep ticking so leases refresh
        state, ns, fx = run_segment(eng, state, ns, 40, n_prop=0,
                                    collect=True)
        st = np_state(state)
        K = k.config.num_key_buckets
        assert (st["conf_leader"] == 0).all()
        assert (st["conf_resp"] == resp).all()
        fxe = {kk: np.asarray(v) for kk, v in fx.extra.items()}
        last_buckets = fxe["local_read_buckets"][-1]
        full = (1 << K) - 1
        for r in range(3):
            assert (last_buckets[:, r] == full).all(), (r, last_buckets)
        for r in range(3, R):
            assert (last_buckets[:, r] == 0).all(), (r, last_buckets)
        assert fxe["stable_leader"][-1][:, 0].all()

    @pytest.mark.slow
    def test_write_barrier_blocks_on_dead_responder_then_conf_heals(self):
        # responder 4 dies: writes must stop committing (its ack is
        # required); after conf failover drops it from the roster, commits
        # resume
        G, R, W, P = 2, 5, 64, 2
        k = make_kernel(G, R, W, P, conf_timeout=12)
        eng = Engine(k)
        state, ns = eng.init()
        resp = 0b11000  # responders {3, 4}
        state, ns, _ = run_with_conf(
            eng, state, ns, 40, n_prop=P, conf=(0, 0, resp, -1)
        )
        st = np_state(state)
        assert (st["conf_resp"] == resp).all()
        pre_cb = st["commit_bar"][:, 0].copy()
        assert (pre_cb > 0).all()

        alive = jnp.ones((G, R), jnp.bool_).at[:, 4].set(False)
        # short window: barrier blocks before failover kicks in
        state, ns, _ = run_segment(
            eng, state, ns, 10, n_prop=P, alive=alive, base_start=1000
        )
        mid = np_state(state)
        assert (mid["commit_bar"][:, 0] <= pre_cb + 3 * P).all(), (
            pre_cb, mid["commit_bar"][:, 0],
        )
        # long window: conf failover drops 4, commits resume
        state, ns, _ = run_segment(
            eng, state, ns, 150, n_prop=P, alive=alive, base_start=2000
        )
        post = np_state(state)
        assert (post["conf_resp"][:, 0] & (1 << 4) == 0).all(), (
            post["conf_resp"][:, 0],
        )
        assert (post["commit_bar"][:, 0] > mid["commit_bar"][:, 0] + 5).all()
        check_agreement(post, G, R, W)

    def test_per_bucket_conf_change(self):
        # responders set on one bucket only
        G, R, W, P = 2, 5, 32, 2
        k = make_kernel(G, R, W, P)
        eng = Engine(k)
        state, ns = eng.init()
        state, ns, _ = run_with_conf(
            eng, state, ns, 50, n_prop=P, conf=(0, 0, 0b00110, 3)
        )
        st = np_state(state)
        K = k.config.num_key_buckets
        for b in range(K):
            want = 0b00110 if b == 3 else 0
            assert (st["conf_resp"][:, :, b] == want).all(), (b, st["conf_resp"])


class TestConfFailover:
    @pytest.mark.slow
    def test_leader_death_conf_takeover(self):
        # conf leader dies; a live replica volunteers via a filtered conf
        # at a higher ballot and steps up through the campaign path
        G, R, W, P = 2, 5, 64, 2
        k = make_kernel(G, R, W, P, conf_timeout=12)
        eng = Engine(k, seed=7)
        state, ns = eng.init()
        state, ns, _ = run_segment(eng, state, ns, 30, n_prop=P)
        pre = np_state(state)
        pre_committed = [committed_values(pre, g, 1, W) for g in range(G)]

        alive = jnp.ones((G, R), jnp.bool_).at[:, 0].set(False)
        state, ns, _ = run_segment(
            eng, state, ns, 300, n_prop=P, alive=alive, base_start=1000
        )
        post = np_state(state)
        # some live replica is the new conf leader and committed new slots
        for g in range(G):
            lead = post["conf_leader"][g, 1:]
            assert (lead >= 1).all(), post["conf_leader"][g]
        assert (
            post["commit_bar"][:, 1:].max(axis=1)
            > pre["commit_bar"][:, 1:].max(axis=1)
        ).all()
        # previously committed values survive
        for g in range(G):
            for r in range(1, R):
                if int(post["leader"][g, r]) == r:
                    vals = committed_values(post, g, r, W)
                    for slot, v in pre_committed[g].items():
                        if slot in vals:
                            assert vals[slot] == v
        check_agreement(post, G, R, W)


class TestInstallBarrier:
    @pytest.mark.slow
    def test_conf_install_waits_for_outgoing_leases(self):
        # a replica with outgoing grants must wait out (or actively revoke)
        # them before installing a pending conf: conf_bal stays until then
        G, R, W, P = 2, 3, 32, 2
        k = make_kernel(
            G, R, W, P, lease_len=20, lease_margin=6, grant_interval=4
        )
        eng = Engine(k)
        state, ns = eng.init()
        # let leases get granted at the initial conf
        state, ns, _ = run_segment(eng, state, ns, 12, n_prop=P)
        st0 = np_state(state)
        bal0 = st0["conf_bal"][0, 0]
        assert (st0["lease_out"].max(axis=2) > 0).any()

        # stage a conf change; with active revoke it installs well before
        # the full lease_len + margin wait, but not instantly
        state, ns, _ = run_with_conf(
            eng, state, ns, 3, n_prop=P, conf=(1, 1, 0b011, -1),
            base_start=100,
        )
        mid = np_state(state)
        # install happened (revoke round trips are fast) or is pending
        state, ns, _ = run_segment(eng, state, ns, 40, n_prop=P,
                                   base_start=200)
        fin = np_state(state)
        assert (fin["conf_bal"] > bal0).all()
        assert (fin["conf_leader"] == 1).all()
        check_agreement(fin, G, R, W)
