"""Pipelined tick loop: the durability fence and the loop-mode A/B.

The software pipeline (``ServerReplica._tick_pipelined``) overlaps the
device step with the host's WAL group-commit, apply/reply, and frame
exchange.  Its one correctness obligation is the durability fence: no
vote/ack computed by step N may leave the process — peer tick frame or
client reply — before step N's WAL records are fsynced, and a failed
fsync must crash the replica with everything gated on the fence still
unsent.  This file pins that contract at three scales:

1. ``StorageHub`` background group commit: fire-and-forget appends +
   token-stamped sync points, error latching (a failed fsync OR a failed
   background append is sticky and re-raised at ``wait_flush``);
2. the egress seams themselves: ``TransportHub.send_tick`` and
   ``ExternalApi.send_replies`` run their ``fence`` argument BEFORE the
   first byte leaves, and a raising fence aborts the whole send;
3. a live pipelined cluster: an injected fsync failure (EIO) and a torn
   background append each crash the replica before any ack escapes —
   the acked prefix survives restart, the in-flight op is only acked
   after recovery made it durable — and the same sequential client
   history produces byte-identical applied state in both loop modes.

The soak-scale half of the contract (wal_torn/wal_fsync schedule events
landing between a step and its fence, pipelined vs serial with
byte-identical FaultPlan digests) is the committed NEMESIS.json
``pipeline_ab`` row, enforced by scripts/nemesis_gate.py.
"""

import os
import time

import pytest

from summerset_tpu.host.storage import LogAction, StorageHub
from summerset_tpu.utils.errors import SummersetError


# ---------------------------------------------------------------- storage --
class TestBackgroundGroupCommit:
    def test_token_covers_prior_appends(self, tmp_path):
        hub = StorageHub(str(tmp_path / "a.wal"), prefer_native=False)
        try:
            for i in range(8):
                hub.append_nowait(("e", i))
            tok = hub.flush_token()
            hub.wait_flush(tok, timeout=10.0)
            # the logger thread is a FIFO: the fsync point covered every
            # append enqueued before the token was minted
            assert hub.backend.size > 0
            # replay sees all 8 records (durability, not just buffering)
            entries, off = [], 0
            while True:
                res = hub.do_sync_action(LogAction("read", offset=off))
                if not res.offset_ok or res.entry is None:
                    break
                entries.append(res.entry)
                off = res.end_offset
            assert entries == [("e", i) for i in range(8)]
        finally:
            hub.stop()

    def test_tokens_are_monotonic_and_reusable(self, tmp_path):
        hub = StorageHub(str(tmp_path / "b.wal"), prefer_native=False)
        try:
            hub.append_nowait("x")
            t1 = hub.flush_token()
            hub.append_nowait("y")
            t2 = hub.flush_token()
            assert t2 > t1
            # waiting on the newer token implies the older completed;
            # a later wait on the older returns immediately
            hub.wait_flush(t2, timeout=10.0)
            hub.wait_flush(t1, timeout=0.1)
        finally:
            hub.stop()

    def test_fsync_failure_raises_at_fence_and_latches(self, tmp_path):
        """An EIO-style group-commit failure surfaces at ``wait_flush``
        (the fence the pipelined loop blocks on before anything
        escapes) and is STICKY: the records the token covered never
        became durable, so every later fence must fail too — the
        replica crashes rather than resuming on a silently-lossy
        log."""
        hub = StorageHub(str(tmp_path / "c.wal"), prefer_native=False)
        try:
            hub.append_nowait("doomed")
            hub.set_faults({"fsync_fail": 1})
            tok = hub.flush_token()
            with pytest.raises(SummersetError, match="group commit"):
                hub.wait_flush(tok, timeout=10.0)
            # sticky: a fresh token cannot outrun the latched error
            hub.set_faults(None)
            tok2 = hub.flush_token()
            with pytest.raises(SummersetError, match="group commit"):
                hub.wait_flush(tok2, timeout=10.0)
        finally:
            hub.stop()

    def test_failed_background_append_surfaces_at_next_fence(
        self, tmp_path
    ):
        """A torn background append (crash mid-record write) delivers no
        result — its failure must latch and re-raise at the NEXT fence,
        before any frame/reply gated on that fence can leave."""
        hub = StorageHub(str(tmp_path / "d.wal"), prefer_native=False)
        try:
            hub.set_faults({"torn": 1})
            hub.append_nowait("torn-victim")
            tok = hub.flush_token()
            with pytest.raises(SummersetError, match="group commit"):
                hub.wait_flush(tok, timeout=10.0)
        finally:
            hub.stop()

    def test_wait_flush_timeout_is_typed(self, tmp_path):
        hub = StorageHub(str(tmp_path / "e.wal"), prefer_native=False)
        try:
            # a token that was never minted by flush_token can never
            # complete; the wait fails loudly instead of hanging
            with pytest.raises(SummersetError, match="timed out"):
                hub.wait_flush(10_000, timeout=0.05)
        finally:
            hub.stop()


# ------------------------------------------------------------ egress seams --
class TestFenceGatesEgress:
    def test_send_replies_runs_fence_before_first_reply(self):
        from summerset_tpu.host.external import ExternalApi

        api = ExternalApi.__new__(ExternalApi)
        calls = []
        api.send_reply = lambda reply, client: calls.append(
            ("reply", client)
        )
        api.send_replies(
            [(1, "r1"), (2, "r2")],
            fence=lambda: calls.append(("fence",)),
        )
        assert calls == [("fence",), ("reply", 1), ("reply", 2)]

    def test_send_replies_raising_fence_sends_nothing(self):
        from summerset_tpu.host.external import ExternalApi

        api = ExternalApi.__new__(ExternalApi)
        sent = []
        api.send_reply = lambda reply, client: sent.append(client)

        def bad_fence():
            raise SummersetError("fsync failed")

        with pytest.raises(SummersetError):
            api.send_replies([(1, "r1"), (2, "r2")], fence=bad_fence)
        assert sent == []

    def test_send_tick_raising_fence_sends_no_frame(self):
        """The fence runs before the first byte of any peer frame: a
        failing fence aborts ``send_tick`` with zero egress (checked on
        a live socket pair)."""
        import socket

        from summerset_tpu.host.transport import TransportHub
        from summerset_tpu.utils import safetcp

        a, b = socket.socketpair()
        hub = TransportHub.__new__(TransportHub)
        # minimal live-send state: one connected peer, no faults
        hub._conns = {1: a}
        hub._faults = None

        def bad_fence():
            raise SummersetError("fsync failed")

        with pytest.raises(SummersetError):
            hub.send_tick(7, {1: {"msg": {}}}, fence=bad_fence)
        b.setblocking(False)
        with pytest.raises(BlockingIOError):
            b.recv(1)  # nothing escaped
        a.close()
        b.close()
        del safetcp  # imported for parity with the hub's framing deps


# --------------------------------------------------------------- live loop --
def _mk_cluster(tmpdir, n=1, config=None, tick=0.004, groups=2):
    import sys

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from test_cluster import Cluster

    return Cluster("MultiPaxos", n, str(tmpdir), config=config or {},
                   tick=tick, num_groups=groups)


def _driver(cluster, timeout=20.0):
    from summerset_tpu.client.drivers import DriverClosedLoop
    from summerset_tpu.client.endpoint import GenericEndpoint

    ep = GenericEndpoint(cluster.manager_addr)
    ep.connect()
    return ep, DriverClosedLoop(ep, timeout=timeout)


class TestFenceCrashSafety:
    """Crash windows between step N and its fsync completion: the fence
    must turn them into crash-before-ack, never ack-then-lose."""

    def test_fsync_failure_is_fatal_before_any_ack(self, tmp_path):
        c = _mk_cluster(tmp_path)
        ep = None
        try:
            ep, drv = _driver(c)
            drv.checked_put("pre", "durable")
            rep = c.replicas[0]
            assert rep.pipeline  # the default mode under test
            rep.wal.set_faults({"fsync_fail": 2})
            # the write's vote/apply records hit the failing group
            # commit: the fence raises before the reply leaves, the
            # replica crashes, and the single attempt fails client-side
            r = drv.put("k", "v1")
            assert r.kind != "success"
            deadline = time.monotonic() + 30
            while not c.crash_reports and time.monotonic() < deadline:
                time.sleep(0.1)
            assert c.crash_reports, "replica should have crashed"
            assert "group commit" in c.crash_reports[0]["error"]
            # post-restart (fresh StorageHub, faults cleared): the
            # acked prefix survived, and the op is only ever acked
            # after recovery made it durable
            assert drv.checked_put("k", "v2") is None or True
            g = drv.get("pre")
            assert g.kind == "success"
            assert g.result.value == "durable"
        finally:
            if ep is not None:
                ep.leave()
            c.stop()

    def test_torn_background_append_is_fatal_before_any_ack(
        self, tmp_path
    ):
        """A crash mid-record write (torn append) during the background
        group commit: the fence raises at the next sync point, the
        replica crashes with the reply unsent, and recovery truncates
        the tear — no acked write is lost."""
        c = _mk_cluster(tmp_path)
        ep = None
        try:
            ep, drv = _driver(c)
            drv.checked_put("pre", "durable")
            rep = c.replicas[0]
            rep.wal.set_faults({"torn": 1})
            r = drv.put("k", "v1")
            assert r.kind != "success"
            deadline = time.monotonic() + 30
            while not c.crash_reports and time.monotonic() < deadline:
                time.sleep(0.1)
            assert c.crash_reports, "replica should have crashed"
            # the cluster serves writes again (checked_put retries
            # through the restart window)...
            drv.checked_put("post", "recovered")
            g2 = drv.get("post")
            assert g2.result.value == "recovered"
            # ...and recovery replayed the pre-tear acked prefix
            g = drv.get("pre")
            assert g.kind == "success"
            assert g.result.value == "durable"
        finally:
            if ep is not None:
                ep.leave()
            c.stop()


# cross-parametrization digest stash for the loop-mode equivalence
# class below (pytest runs the two modes as separate tests)
_MODE_DIGESTS: dict = {}


class TestLoopModeEquivalence:
    """pipeline=False compiles the exact old serial order; the same
    sequential client history must land byte-identical applied state in
    both modes, and each mode's telemetry must be honestly labeled."""

    @staticmethod
    def _durable_digest(rep) -> str:
        """sha256 over the replica's durable state leaves — on a
        single-replica cluster after a strictly sequential history,
        these are a pure function of the op stream (no elections, no
        frame-timing races), so the two loop modes must match BYTE FOR
        BYTE."""
        import hashlib

        import numpy as np

        h = hashlib.sha256()
        ker = rep.kernel
        for k in sorted(
            tuple(ker.DURABLE_SCALARS or ())
            + tuple(ker.DURABLE_WINDOWS or ())
        ):
            a = np.asarray(rep.state[k])
            h.update(k.encode())
            h.update(a.tobytes())
        return h.hexdigest()

    @pytest.mark.parametrize("pipeline", [False, True])
    def test_same_history_same_applied_state(self, tmp_path, pipeline):
        c = _mk_cluster(
            tmp_path / ("pl" if pipeline else "ser"),
            config={"pipeline": pipeline},
        )
        ep = None
        try:
            ep, drv = _driver(c)
            # strictly sequential ops: one in flight at a time, so the
            # proposal stream is identical regardless of tick timing
            for i in range(24):
                drv.checked_put(f"k{i % 7}", f"v{i}")
            rep = c.replicas[0]
            assert rep.pipeline is pipeline
            # every acked write applied: 24 one-op batches + the floors
            assert sum(rep.applied) == 24
            items = dict(rep.statemach.snapshot_items())
            assert items == {
                f"k{j}": f"v{max(i for i in range(24) if i % 7 == j)}"
                for j in range(7)
            }
            # cross-mode durable-state digest: stash per mode; the
            # second parametrization compares against the first (the
            # state/effects byte-identity half of the A/B contract)
            dig = self._durable_digest(rep)
            seen = _MODE_DIGESTS.setdefault("seq24", {})
            seen[pipeline] = dig
            if len(seen) == 2:
                assert seen[True] == seen[False]
            # loop-mode telemetry honesty: the serial loop never emits
            # the pipeline stages, the pipelined loop never emits the
            # fused step stage (the A/B gates lean on these labels)
            hist = rep.metrics.hist("loop_stage_us", stage="overlap")
            step = rep.metrics.hist("loop_stage_us", stage="step")
            if pipeline:
                assert hist is not None and hist.count > 0
                assert step is None or step.count == 0
            else:
                assert hist is None or hist.count == 0
                assert step is not None and step.count > 0
            # the mode is stamped into every scrape row
            assert rep.metrics_snapshot()["pipeline"] is pipeline
        finally:
            if ep is not None:
                ep.leave()
            c.stop()


class TestPipelineFlush:
    def test_graceful_stop_settles_inflight_step(self, tmp_path):
        """A pipelined replica stopping mid-flight must drain the
        dispatched step, fsync its records, and release gated replies
        before teardown — already-acked ops stay acked, the WAL carries
        everything the drained step logged."""
        c = _mk_cluster(tmp_path)
        ep = None
        try:
            ep, drv = _driver(c)
            for i in range(6):
                drv.checked_put(f"s{i}", str(i))
            rep = c.replicas[0]
            wal_before = rep.wal.backend.size
            assert wal_before > 0
        finally:
            if ep is not None:
                ep.leave()
            c.stop()
        # the stop path ran _pipeline_flush: no in-flight registers left
        assert rep._pl is None
        assert rep._fence_token is None
        assert rep._reply_queue == []
