"""Sharded-vs-unsharded equivalence of the mesh path (per-tick form).

The multi-chip design claim is that sharding the [G groups, R replicas]
state over a ``jax.sharding.Mesh`` changes WHERE the lockstep tick runs,
never WHAT it computes (reference analog: the TransportHub mesh delivers
the same messages whatever the process placement, transport.rs:258-276).
This drives the same fault schedule tick-by-tick through the plain
single-device engine and through the engine's sharded compile mode
(``Engine(mesh=...)``) on the 8-virtual-device CPU mesh (conftest),
asserting bit-identical state trajectories at nontrivial shapes —
including a mesh whose REPLICA axis is truly sharded, where in-group
delivery must lower to a cross-device collective.

The scan-path (windowed, donated) twin of this gate lives in
``tests/test_mesh_engine.py``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from summerset_tpu.core import Engine, NetConfig
from summerset_tpu.core.netmodel import ControlInputs
from summerset_tpu.core.sharding import make_mesh
from summerset_tpu.protocols import make_protocol
from summerset_tpu.protocols.multipaxos import ReplicaConfigMultiPaxos


def _run_equivalence(G, R, W, P, group_shards, replica_shards, ticks):
    if len(jax.devices()) < group_shards * replica_shards:
        pytest.skip("needs the 8-virtual-device CPU mesh")
    cfg = ReplicaConfigMultiPaxos(max_proposals_per_tick=P)
    kernel = make_protocol("multipaxos", G, R, W, cfg)
    net = NetConfig(delay_ticks=1, jitter_ticks=1, drop_rate=0.05,
                    max_delay_ticks=3)

    # deterministic fault schedule: per-tick pauses and a symmetric cut
    rng = np.random.default_rng(42)
    schedule = []
    for _ in range(ticks):
        alive = np.ones((G, R), bool)
        for r in range(R):
            if rng.random() < 0.2:
                alive[:, r] = False
        if rng.random() < 0.3:
            cut = int(rng.integers(R))
            link = np.asarray(ControlInputs.isolate_links(G, R, cut))
        else:
            link = np.ones((G, R, R), bool)
        schedule.append((alive, link))

    def inputs_at(t):
        alive, link = schedule[t]
        return {
            "n_proposals": jnp.full((G,), P, jnp.int32),
            "value_base": jnp.full((G,), (1 + t) * P, jnp.int32),
            "alive": jnp.asarray(alive),
            "link_up": jnp.asarray(link),
        }

    # unsharded baseline
    eng = Engine(kernel, netcfg=net, seed=7)
    s0, n0 = eng.init()
    base_states = []
    s, n = s0, n0
    for t in range(ticks):
        s, n, _ = eng.tick(s, n, inputs_at(t))
        base_states.append({k: np.asarray(v) for k, v in s.items()})

    # sharded run from the same seed over the mesh: the engine's own
    # sharded per-tick path (serving shape — host feeds every tick's
    # inputs, so the single-tick jit must keep the carry on its shards)
    mesh = make_mesh(group_shards, replica_shards,
                     devices=jax.devices()[:group_shards * replica_shards])
    eng2 = Engine(kernel, netcfg=net, seed=7, mesh=mesh)
    s2, n2 = eng2.init()
    assert all(
        len(v.sharding.device_set) >= group_shards
        for v in s2.values() if v.ndim >= 1 and v.shape[0] == G
    ), "init() did not place the state on the mesh"
    for t in range(ticks):
        s2, n2, _ = eng2.tick(s2, n2, inputs_at(t))
        got = {k: np.asarray(v) for k, v in s2.items()}
        for k, ref in base_states[t].items():
            assert (got[k] == ref).all(), (
                f"tick {t}: state[{k!r}] diverges sharded vs unsharded "
                f"(max |d| = "
                f"{np.abs(got[k].astype(np.int64) - ref.astype(np.int64)).max()})"
            )
    # the run must have actually done consensus work under faults
    cb = base_states[-1]["commit_bar"]
    assert cb.max() > 0, "nothing committed during the equivalence run"


def test_group_and_replica_sharded_equivalence():
    """4x2 mesh: the replica axis is genuinely sharded, so in-group
    delivery lowers to cross-device collectives — and must still be
    bit-identical to the single-device run."""
    _run_equivalence(G=64, R=4, W=16, P=4,
                     group_shards=4, replica_shards=2, ticks=24)


@pytest.mark.slow
def test_group_sharded_equivalence_r5():
    """8x1 mesh at R=5 (odd population: replica axis unsharded)."""
    _run_equivalence(G=64, R=5, W=16, P=4,
                     group_shards=8, replica_shards=1, ticks=30)
