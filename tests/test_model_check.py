"""Exhaustive small-model checking of protocol kernels (TLA+ pillar).

Drives :mod:`models.explore` — breadth-first exhaustion of every fault
schedule (kill / isolate / all-up per round) at G=1, R=3, W=4 with the
real jitted kernels, asserting agreement + decision durability at every
reached node (reference analog: ``tla+/tlc_model_check.sh`` runs TLC
over MultiPaxos/Crossword/Bodega specs at tiny constants).

The default tier runs depth 3 (~400 expansions per kernel); the slow
tier runs depth 6 for MultiPaxos/Raft/RSPaxos.
Committed run logs live in MODELCHECK.json; regenerate them with
``python models/explore.py --out MODELCHECK.json`` (the --protocols
default carries the per-protocol depths and config presets).
"""

import os
import sys

import pytest

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.dirname(__file__)), "models")
)

from explore import explore  # noqa: E402


@pytest.mark.parametrize("protocol", ["multipaxos", "raft"])
def test_exhaustive_depth3(protocol):
    r = explore(protocol, depth=3)
    assert not r.violations, r.violations
    assert r.nodes_expanded >= 7 + 7 * 7, r  # full fan-out at least 2 deep
    assert r.max_committed_slots > 0, "nothing ever committed"


@pytest.mark.slow
@pytest.mark.parametrize("protocol", ["multipaxos", "raft"])
def test_exhaustive_depth6(protocol):
    r = explore(protocol, depth=6)
    assert not r.violations, r.violations
    assert r.max_committed_slots > 0


def test_exhaustive_collective_tally_quick():
    """The collective quorum-tally transport (core/quorum.py) under
    exhaustion, quick tier: MultiPaxos depth 3 and Crossword depth 2
    with ``tally="collective"`` — the per-source [G, R] tally lanes
    must uphold agreement + decision durability under every fault
    schedule exactly like the pairwise lanes (the committed
    MODELCHECK.json carries the depth-5 rows)."""
    r = explore("multipaxos", depth=3, tally="collective")
    assert not r.violations, r.violations
    assert r.tally == "collective"
    assert r.max_committed_slots > 0
    r = explore("crossword", depth=2, tally="collective",
                config_overrides={"fault_tolerance": 0,
                                  "assignment_adaptive": False})
    assert not r.violations, r.violations
    assert r.max_committed_slots > 0


def test_exhaustive_crossword_depth2():
    """Crossword under exhaustion, quick tier: the coded kernel with
    diagonal shard slicing (spr pinned — assignment_adaptive off — so
    the enumerated fault alphabet is the only nondeterminism source).
    The committed MODELCHECK.json row runs the same preset at depth 5."""
    r = explore("crossword", depth=2,
                config_overrides={"fault_tolerance": 0,
                                  "assignment_adaptive": False})
    assert not r.violations, r.violations
    assert r.max_committed_slots > 0


@pytest.mark.slow
def test_exhaustive_crossword_depth5():
    """The MODELCHECK.json crossword row, reproduced: depth 5 covers an
    election + window-wrap + reconstruction round under every schedule;
    depth 6 exceeds the tier budget (largest per-node state of the
    family — per-slot shard tallies)."""
    r = explore("crossword", depth=5,
                config_overrides={"fault_tolerance": 0,
                                  "assignment_adaptive": False})
    assert not r.violations, r.violations
    assert r.max_committed_slots > 0


@pytest.mark.slow
def test_exhaustive_rspaxos_depth6():
    """RSPaxos under exhaustion — the kernel whose lagging-exec step-up
    bug the randomized sweep caught.  fault_tolerance=1 (not the
    degenerate default 0) so the commit tally really requires
    quorum + ft acks and the R - ft prepare shortcut is live.  Depth 6
    reaches one more full election + window-wrap round than the depth-5
    run that shipped with round 5."""
    r = explore("rspaxos", depth=6,
                config_overrides={"fault_tolerance": 1})
    assert not r.violations, r.violations
    assert r.max_committed_slots > 0
