"""graftlint test suite: the verifier's negative paths + the clean tree.

Three layers:

1. **Broken-kernel fixtures** (``tests/graftlint_fixtures``): each
   deliberately violates exactly one contract rule and must produce
   exactly its expected finding fingerprint — the fingerprints are
   hardcoded hex literals, so any change to the fingerprint scheme (or
   to what a rule reports) shows up here before it invalidates the
   committed LINT.json baseline.
2. **Host AST lint units**: synthetic sources through ``scan_file``
   covering each H-rule and the suppression-comment format.
3. **The acceptance property**: every registered protocol kernel
   verifies clean (contract + ranges + taint), and the host lint over
   the real tree is finding-free modulo annotated suppressions — the
   same invariant CI tier 2e pins via ``scripts/graftlint.py --check``.

The range prover's own decision tables and fixpoint units live in
``tests/test_ranges.py``; this file holds its R2 fingerprint and the
proven-vs-optimistic gate accounting the interval channel feeds T1.
"""

import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from graftlint_fixtures import make_fixture  # noqa: E402

from summerset_tpu import protocols  # noqa: E402
from summerset_tpu.analysis import hostlint  # noqa: E402
from summerset_tpu.analysis.contract import verify_kernel  # noqa: E402
from summerset_tpu.analysis.report import (  # noqa: E402
    Finding,
    assemble_report,
    dumps_report,
)
from summerset_tpu.analysis.ranges import (  # noqa: E402
    verify_kernel_ranges,
)
from summerset_tpu.analysis.taint import verify_kernel_taint  # noqa: E402


# ------------------------------------------------------------- fixtures --
def _fingerprints(res):
    assert res.error is None, res.error
    return sorted(f.fingerprint for f in res.findings)


def test_good_fixture_is_clean():
    assert verify_kernel(make_fixture, "fixturegood").ok
    assert verify_kernel_taint(make_fixture, "fixturegood").ok


@pytest.mark.parametrize(
    "name,passfn,expected",
    [
        # each broken kernel -> exactly its one expected fingerprint
        ("fixtureunflagged", verify_kernel_taint, ["229c835e7ed6"]),
        # the inverted gate: flags-derived predicate, wrong polarity —
        # the dead-link branch selects the lane (polarity lattice)
        ("fixtureinvertedgate", verify_kernel_taint, ["93543304ce05"]),
        ("fixtureunflaggedeffects", verify_kernel_taint,
         ["670193535ccb"]),
        # the ungated relay hop: outbox leaves are sinks too
        ("fixturebrokenforwarder", verify_kernel_taint,
         ["6ffff174820c"]),
        ("fixturestaleallow", verify_kernel_taint, ["c6fab01b5c86"]),
        # an author range claim the transfer refutes: holds at init,
        # one abstract step escapes the ceiling — R2, not a crash
        ("fixturerangeunsound", verify_kernel_ranges, ["4772bac7adcd"]),
        ("fixturefloatstate", verify_kernel, ["aec22b6e38a8"]),
        ("fixturemissingflags", verify_kernel, ["c746d187a51b"]),
        ("fixtureundeclaredbroadcast", verify_kernel, ["43ec345af97e"]),
        ("fixturebogusdurable", verify_kernel, ["0438a08b7ffd"]),
        ("fixtureundeclaredinput", verify_kernel, ["fb44c6558984"]),
        # the ungated collective tally: the [G, R] tally lane rides the
        # psum into state/effects with no flags gate — four sinks, and
        # the dead-world class propagating THROUGH the segmented
        # reduction is what keeps the taint alive to all of them
        ("fixtureungatedcollective", verify_kernel_taint,
         ["26d8ef536b84", "327be3169de1", "a72c76cdfd2d",
          "cbf23d22f878"]),
        # a collective outside the quorum_tally scope: C6's one
        # sanctioned cross-replica aggregation point is the tally plane
        ("fixturecollectiveoutsidescope", verify_kernel,
         ["8079fc1552c4"]),
    ],
)
def test_broken_fixture_fingerprint(name, passfn, expected):
    res = passfn(make_fixture, name)
    assert _fingerprints(res) == expected, [
        f.render() for f in res.findings
    ]


def test_broken_fixtures_fail_only_their_rule():
    """The planted violation is the only one: the other pass stays clean."""
    assert verify_kernel(make_fixture, "fixtureunflagged").ok
    assert verify_kernel(make_fixture, "fixtureinvertedgate").ok
    assert verify_kernel(make_fixture, "fixtureunflaggedeffects").ok
    assert verify_kernel(make_fixture, "fixturebrokenforwarder").ok
    assert verify_kernel(make_fixture, "fixtureungatedcollective").ok
    assert verify_kernel_taint(
        make_fixture, "fixturecollectiveoutsidescope"
    ).ok
    assert verify_kernel_taint(make_fixture, "fixturefloatstate").ok
    assert verify_kernel_taint(make_fixture, "fixturebogusdurable").ok
    assert verify_kernel_taint(make_fixture, "fixtureundeclaredinput").ok
    assert verify_kernel(make_fixture, "fixturerangeunsound").ok
    assert verify_kernel_taint(make_fixture, "fixturerangeunsound").ok


def test_range_entangled_gate_is_proven_only_with_intervals():
    """The fixture whose gate ONLY the interval prover clears: the
    dead-world predicate compares a known ``-1`` sentinel against a
    state leaf, undecidable in the polarity lattice alone.  With the
    range pass live the select is a PROVEN gate (and the kernel is
    clean); without it the identical select is the legacy optimistic
    clearing — the counter pair is the whole point of the tentpole."""
    with_rng = verify_kernel_taint(
        make_fixture, "fixturerangeentangled", use_ranges=True
    )
    without = verify_kernel_taint(
        make_fixture, "fixturerangeentangled", use_ranges=False
    )
    assert with_rng.ok and without.ok
    assert with_rng.extra["gates_proven"] == 2
    assert with_rng.extra["gates_optimistic"] == 0
    assert with_rng.extra["residuals"] == []
    assert without.extra["gates_proven"] == 1
    assert without.extra["gates_optimistic"] == 1
    assert [r["prim"] for r in without.extra["residuals"]] == ["select_n"]
    # the enabling invariant is on record: prep_bal proven nonnegative
    dev = verify_kernel_ranges(
        make_fixture, "fixturerangeentangled"
    ).extra["variants"]["device"]
    assert dev["invariants"]["prep_bal"][0] == 0


def test_collective_in_tally_scope_is_clean():
    """The control: a flags-gated psum INSIDE the quorum_tally phase
    scope passes both passes — collectives are allowed-in-tally-scope,
    not forbidden outright."""
    assert verify_kernel(make_fixture, "fixturegoodcollective").ok
    assert verify_kernel_taint(make_fixture, "fixturegoodcollective").ok


def test_allowed_forwarder_suppresses_outbox_sink():
    """A TAINT_ALLOW entry naming an ``outbox.*`` sink suppresses the
    relay-hop T1 — and is live (no stale-suppression T9)."""
    res = verify_kernel_taint(make_fixture, "fixtureallowedforwarder")
    assert res.ok, [f.render() for f in res.findings]
    assert len(res.suppressed) == 1
    f, reason = res.suppressed[0]
    assert f.scope == "data->outbox.data"
    assert "relay" in reason


def test_taint_double_negation_gate_is_clean():
    """``jnp.where(~valid, fallback, lane)`` is a CORRECT gate — the
    dead-link case (``~valid`` nonzero) selects the fallback.  The
    polarity lattice must track the ``~`` instead of flagging every
    negated predicate."""
    import jax.numpy as jnp

    from graftlint_fixtures import GoodKernel
    from summerset_tpu.core.protocol import StepEffects

    class DoubleNeg(GoodKernel):
        name = "FixtureDoubleNeg"

        def step(self, state, inbox, inputs):
            s = dict(state)
            valid = (inbox["flags"] & jnp.uint32(1)) != 0
            best = jnp.max(
                jnp.where(~valid, 0, inbox["data"]), axis=2
            )
            s["commit_bar"] = jnp.maximum(s["commit_bar"], best)
            s["exec_bar"] = s["commit_bar"]
            return s, self.zero_outbox(), StepEffects(
                commit_bar=s["commit_bar"], exec_bar=s["exec_bar"]
            )

    res = verify_kernel_taint(
        lambda _n, *a, **k: DoubleNeg(*a, **k), "fixturedoubleneg"
    )
    assert res.ok, [f.render() for f in res.findings]


def test_taint_inverted_mask_is_caught():
    """``lane * ~valid`` passes the lane exactly on dead links — a
    provably-inverted mask-multiply must not clear taint."""
    import jax.numpy as jnp

    from graftlint_fixtures import GoodKernel
    from summerset_tpu.core.protocol import StepEffects

    class InvMask(GoodKernel):
        name = "FixtureInvMask"

        def step(self, state, inbox, inputs):
            s = dict(state)
            valid = (inbox["flags"] & jnp.uint32(1)) != 0
            masked = inbox["data"] * (~valid).astype(jnp.int32)
            s["commit_bar"] = jnp.maximum(
                s["commit_bar"], jnp.max(masked, axis=2)
            )
            s["exec_bar"] = s["commit_bar"]
            return s, self.zero_outbox(), StepEffects(
                commit_bar=s["commit_bar"], exec_bar=s["exec_bar"]
            )

    res = verify_kernel_taint(
        lambda _n, *a, **k: InvMask(*a, **k), "fixtureinvmask"
    )
    assert not res.ok
    assert "data->commit_bar" in {f.scope for f in res.findings}


def test_taint_while_cond_is_an_implicit_flow():
    """A lax.while_loop bound derived from an ungated inbox lane taints
    the carried state (iteration count is a flow, same as a cond
    predicate)."""
    import jax
    import jax.numpy as jnp

    from summerset_tpu.core.protocol import StepEffects

    from graftlint_fixtures import GoodKernel

    class WhileBound(GoodKernel):
        name = "FixtureWhileBound"

        def step(self, state, inbox, inputs):
            s = dict(state)
            bound = jnp.max(inbox["data"])  # ungated

            def cond(c):
                return c[0] < bound

            def body(c):
                return c[0] + 1, c[1] + 1

            _, bumped = jax.lax.while_loop(
                cond, body,
                (jnp.zeros((), jnp.int32), s["commit_bar"]),
            )
            s["commit_bar"] = bumped
            s["exec_bar"] = s["commit_bar"]
            return s, self.zero_outbox(), StepEffects(
                commit_bar=s["commit_bar"], exec_bar=s["exec_bar"]
            )

    res = verify_kernel_taint(
        lambda _n, *a, **k: WhileBound(*a, **k), "fixturewhilebound"
    )
    assert res.error is None, res.error
    assert ("data", "commit_bar") in {
        tuple(f.scope.split("->")) for f in res.findings
    }, [f.render() for f in res.findings]


def test_taint_allow_suppresses_with_reason():
    """An allowlisted flow moves to `suppressed` and carries its reason."""

    from graftlint_fixtures import UnflaggedInboxReadKernel

    class Allowed(UnflaggedInboxReadKernel):
        name = "FixtureAllowed"
        TAINT_ALLOW = (
            ("data", "shadow", "diagnostic mirror, never consumed"),
        )

    res = verify_kernel_taint(
        lambda _n, *a, **k: Allowed(*a, **k), "fixtureallowed"
    )
    assert res.ok
    assert [(f.scope, r) for f, r in res.suppressed] == [
        ("data->shadow", "diagnostic mirror, never consumed")
    ]


# ------------------------------------------------------ host lint units --
_LOCKED_FSYNC = """
import os, threading

class Hub:
    def __init__(self):
        self._lock = threading.Lock()

    def flush(self, f):
        with self._lock:
            os.fsync(f.fileno())
"""

_SUPPRESSED = """
import os, threading

class Hub:
    def __init__(self):
        self._lock = threading.Lock()

    def flush(self, f):
        # graftlint: disable=H104 -- fixture reason
        with self._lock:  # graftlint: disable=H101 -- fixture reason
            os.fsync(f.fileno())
"""

_STACKED_SUPPRESS = """
import os, threading

class Hub:
    def __init__(self):
        self._lock = threading.Lock()

    def flush(self, f):
        with self._lock:
            # graftlint: disable=H101 -- reason A

            # graftlint: disable=H104 -- reason B
            os.fsync(f.fileno())
"""

_NON_LOCK_WITH = """
import os

class Hub:
    def flush(self, f, sock, buf):
        with self._block:
            sock.sendall(buf)
        with nonblocking_io():
            sock.sendall(buf)
        with self._wlocks[0]:
            sock.sendall(buf)
"""

_NON_DAEMON = """
import threading

def go(fn):
    t = threading.Thread(target=fn)
    t.start()
"""

_SEEDED_SCOPE = """
import time, random

class FaultPlan:
    def generate(self):
        t0 = time.time()
        rng = random.Random()
        return t0, rng.random()

class NemesisRunner:
    def play(self):
        return time.time()  # pacing: outside the seeded scope
"""

_SEEDED_SCOPE_SPELLINGS = """
import time, datetime

class FaultPlan:
    def generate(self):
        return (
            time.time_ns(),
            datetime.datetime.now(),
        )
"""

_WORKLOAD_SEEDED_SCOPE = """
import time, random

class WorkloadPlan:
    def generate(self):
        t0 = time.monotonic()      # any clock read: schedules must be
        rng = random.Random()      # a pure function of the seed
        return t0, rng.random()

class OpStream:
    def next(self):
        return random.random()     # global (unseeded) RNG draw


def runner_pacing():
    return time.time()             # module-level: outside the scope
"""

_AUTOPILOT_SEEDED_SCOPE = """
import time, random

class AutopilotPolicy:
    def evaluate(self):
        t0 = time.monotonic()      # any clock read inside the policy:
        rng = random.Random()      # decision traces must replay from
        return t0, rng.random()    # the seed alone

class AutopilotDriver:
    def play(self):
        return time.time()         # scrape pacing: outside the scope
"""

# trace normalization rides the same seeded scope: from_trace is a
# WorkloadPlan classmethod, so a clock read or unseeded draw while
# parsing/striding trace rows breaks byte-reproducible replay exactly
# like a dirty generate() would
_FROM_TRACE_SEEDED_SCOPE = """
import time, random

class WorkloadPlan:
    @classmethod
    def from_trace(cls, path):
        stamp = time.time()        # wallclock in the normalizer: the
        jitter = random.random()   # same trace would yield different
        return stamp, jitter       # plans run-to-run


def tail_trace_file(path):
    return time.monotonic()        # module-level I/O helper: exempt
"""

# H105 both-direction fixtures: every egress shape the rule must
# decide — dominated by a straight-line fence wait (clean), carrying
# the fence down as a kwarg (clean), fence only inside a conditional
# (fires: not straight-line), and no fence at all (fires)
_FENCED_EGRESS = """
class Replica:
    def drain(self):
        self._fence_wait()
        self.external.send_replies(self.queue)

    def exchange(self):
        self.transport.send_tick(self.tick, frames,
                                 fence=self._fence_wait)
"""

_UNFENCED_EGRESS = """
class Replica:
    def exchange(self):
        self.transport.send_tick(self.tick, frames)

    def drain(self, ready):
        if ready:
            self._fence_wait()
        self.external.send_replies(self.queue)
"""

# H106 both-direction fixtures: every handler shape the rule must
# decide — swallowing broad/bare excepts (fire), re-raising / recording
# / reading the bound exception (clean), narrow types (out of scope)
_H106_EXCEPTS = """
class Hub:
    def swallow(self):
        try:
            self.pump()
        except Exception:
            pass

    def bare(self):
        try:
            self.pump()
        except:
            pass

    def tuple_broad(self):
        try:
            self.pump()
        except (ValueError, Exception):
            self.retries += 1

    def reraises(self):
        try:
            self.pump()
        except Exception:
            raise

    def records(self):
        try:
            self.pump()
        except Exception:
            pf_warn(logger, "pump failed")

    def flight_records(self):
        try:
            self.pump()
        except Exception:
            self.flight.record("pump_fail")

    def reads_the_exception(self):
        try:
            self.pump()
        except Exception as e:
            self.last_error = repr(e)

    def narrow(self):
        try:
            self.pump()
        except OSError:
            pass
"""

_H106_WAIVED = """
class Hub:
    def swallow(self):
        try:
            self.pump()
        # graftlint: disable=H106 -- fixture: unwind must not mask
        except Exception:
            pass
"""

_MONO_SCOPE = """
import time

class FlightRecorder:
    def record(self):
        return time.monotonic()   # the sanctioned stamp family

    def bad_stamp(self):
        return time.time()        # wallclock in the recorder: fires


def module_level_helper():
    return time.time()            # "*" scope covers the whole module
"""


def _scan(tmp_path, src, rel):
    p = tmp_path / "mod.py"
    p.write_text(src)
    return hostlint.scan_file(str(p), rel)


def test_hostlint_lock_held_fsync(tmp_path):
    findings, suppressed = _scan(tmp_path, _LOCKED_FSYNC, "host/x.py")
    codes = sorted(f.code for f in findings)
    assert codes == ["H101", "H104"]
    assert not suppressed


def test_hostlint_suppression_comment(tmp_path):
    findings, suppressed = _scan(tmp_path, _SUPPRESSED, "host/x.py")
    assert not findings
    assert sorted(f.code for f, _ in suppressed) == ["H101", "H104"]
    assert all(r == "fixture reason" for _, r in suppressed)


def test_hostlint_stacked_standalone_suppressions(tmp_path):
    """Stacked standalone waivers (even blank-separated) all reach the
    next statement line instead of the first landing on the second
    comment and getting dropped."""
    findings, suppressed = _scan(
        tmp_path, _STACKED_SUPPRESS, "host/x.py"
    )
    assert not findings
    assert sorted((f.code, r) for f, r in suppressed) == [
        ("H101", "reason A"), ("H104", "reason B")
    ]


def test_hostlint_fsync_allowed_in_storage_owner(tmp_path):
    findings, _ = _scan(tmp_path, _LOCKED_FSYNC, "host/storage.py")
    assert sorted(f.code for f in findings) == ["H101"]  # H104 waived


def test_hostlint_scans_subpackages(tmp_path):
    """A future host/ subpackage cannot silently escape the lint."""
    sub = tmp_path / "host" / "replication"
    sub.mkdir(parents=True)
    (sub / "wal.py").write_text(_LOCKED_FSYNC)
    res, n_files = hostlint.lint_host(str(tmp_path))
    assert n_files == 1
    assert sorted(f.code for f in res.findings) == ["H101", "H104"]
    assert res.findings[0].where == "host/replication/wal.py"


def test_hostlint_lock_name_needs_word_boundary(tmp_path):
    """'lock' inside another word (`_block`, `nonblocking_io`) is not a
    lock; `_wlocks[i]` is."""
    findings, _ = _scan(tmp_path, _NON_LOCK_WITH, "host/x.py")
    assert [(f.code, f.line) for f in findings] == [("H101", 11)]


def test_hostlint_non_daemon_thread(tmp_path):
    findings, _ = _scan(tmp_path, _NON_DAEMON, "host/x.py")
    assert [f.code for f in findings] == ["H102"]


def test_hostlint_seeded_scope(tmp_path):
    findings, _ = _scan(tmp_path, _SEEDED_SCOPE, "host/nemesis.py")
    assert sorted(f.code for f in findings) == ["H103", "H103"]
    scopes = sorted(f.scope for f in findings)
    # time.time + unseeded Random inside FaultPlan; NemesisRunner exempt
    assert scopes == [
        "FaultPlan.generate:random.Random",
        "FaultPlan.generate:time.time",
    ]


def test_hostlint_workload_plan_joins_seeded_scope(tmp_path):
    """The workload plane's plan/stream classes are in the H103 seeded
    scope: clock reads (monotonic included — schedules are a pure
    function of the seed) and unseeded/global RNG draws fire, while the
    module-level wall pacing helper stays exempt."""
    findings, _ = _scan(
        tmp_path, _WORKLOAD_SEEDED_SCOPE, "host/workload.py"
    )
    assert sorted(f.scope for f in findings) == [
        "OpStream.next:random.random",
        "WorkloadPlan.generate:random.Random",
        "WorkloadPlan.generate:time.monotonic",
    ]
    assert all(f.code == "H103" for f in findings)


def test_hostlint_workload_scope_is_module_keyed(tmp_path):
    """The same source OUTSIDE host/workload.py keeps today's behavior
    (no seeded-scope rule applies) — the scope is the module, not the
    class names."""
    findings, _ = _scan(
        tmp_path, _WORKLOAD_SEEDED_SCOPE, "host/other.py"
    )
    assert findings == []


def test_hostlint_from_trace_joins_seeded_scope(tmp_path):
    """Trace normalization is inside the workload seeded scope: a
    wallclock read or unseeded RNG draw in ``WorkloadPlan.from_trace``
    fires H103 (same trace file must always yield the same plan),
    while a module-level file helper stays exempt."""
    findings, _ = _scan(
        tmp_path, _FROM_TRACE_SEEDED_SCOPE, "host/workload.py"
    )
    assert sorted(f.scope for f in findings) == [
        "WorkloadPlan.from_trace:random.random",
        "WorkloadPlan.from_trace:time.time",
    ]
    assert all(f.code == "H103" for f in findings)


def test_hostlint_from_trace_scope_is_module_keyed(tmp_path):
    """The same from_trace source outside host/workload.py is
    untouched — the seeded scope is keyed on the module path."""
    findings, _ = _scan(
        tmp_path, _FROM_TRACE_SEEDED_SCOPE, "host/other.py"
    )
    assert findings == []


def test_hostlint_autopilot_policy_joins_seeded_scope(tmp_path):
    """The autopilot's decision tier is in the H103 seeded scope:
    clock reads (monotonic included) and unseeded RNG draws inside
    AutopilotPolicy fire, while the AutopilotDriver's wallclock scrape
    pacing stays exempt (it is the I/O loop, like NemesisRunner)."""
    findings, _ = _scan(
        tmp_path, _AUTOPILOT_SEEDED_SCOPE, "host/autopilot.py"
    )
    assert sorted(f.scope for f in findings) == [
        "AutopilotPolicy.evaluate:random.Random",
        "AutopilotPolicy.evaluate:time.monotonic",
    ]
    assert all(f.code == "H103" for f in findings)


def test_hostlint_autopilot_scope_is_module_keyed(tmp_path):
    """The same source outside host/autopilot.py is untouched — the
    seeded scope is keyed on the module path, not the class names."""
    findings, _ = _scan(
        tmp_path, _AUTOPILOT_SEEDED_SCOPE, "host/other.py"
    )
    assert findings == []


def test_hostlint_fenced_egress_is_clean(tmp_path):
    """H105 negative direction: an egress call dominated by a
    straight-line ``_fence_wait()`` earlier in the same function, or
    passing ``fence=..._fence_wait`` down to the seam, is clean."""
    findings, _ = _scan(tmp_path, _FENCED_EGRESS, "host/server.py")
    assert findings == []


def test_hostlint_unfenced_egress_fires(tmp_path):
    """H105 positive direction: an egress call with no fence at all
    fires, and a fence wait INSIDE a conditional does not dominate —
    the frames/replies could still leave on the branch that skipped
    it."""
    findings, _ = _scan(tmp_path, _UNFENCED_EGRESS, "host/server.py")
    assert sorted((f.code, f.scope) for f in findings) == [
        ("H105", "Replica.drain:send_replies"),
        ("H105", "Replica.exchange:send_tick"),
    ]


def test_hostlint_fence_rule_is_module_keyed(tmp_path):
    """The fence contract is owned by host/server.py — the same source
    elsewhere (e.g. the transport hub's own internals, the test
    harnesses) is not in scope."""
    findings, _ = _scan(tmp_path, _UNFENCED_EGRESS, "host/other.py")
    assert findings == []


def test_hostlint_real_server_fence_sites():
    """The live host/server.py holds the fence contract: the pipelined
    loop's egress seams are all fenced (no H105 findings), and the
    serial loop's send site carries its reasoned waiver on record."""
    import summerset_tpu

    pkg = os.path.dirname(summerset_tpu.__file__)
    findings, suppressed = hostlint.scan_file(
        os.path.join(pkg, "host", "server.py"), "host/server.py"
    )
    assert [f for f in findings if f.code == "H105"] == []
    waived = [
        (f.scope, r) for f, r in suppressed if f.code == "H105"
    ]
    assert len(waived) == 1
    assert waived[0][0] == "ServerReplica._tick_serial:send_tick"
    assert "fence" in waived[0][1]


def test_hostlint_real_workload_module_is_clean():
    """The live host/workload.py passes its own seeded scope."""
    import summerset_tpu

    pkg = os.path.dirname(summerset_tpu.__file__)
    findings, suppressed = hostlint.scan_file(
        os.path.join(pkg, "host", "workload.py"), "host/workload.py"
    )
    assert findings == [] and suppressed == []


def test_hostlint_broad_except_must_record(tmp_path):
    """H106 both directions in a hub-thread module: broad/bare excepts
    that swallow fire (a tuple containing Exception is broad too); the
    handlers that re-raise, call a recording helper, or at least read
    the bound exception are clean, and narrow types are out of scope."""
    findings, suppressed = _scan(
        tmp_path, _H106_EXCEPTS, "host/server.py"
    )
    assert not suppressed
    assert sorted((f.code, f.scope) for f in findings) == [
        ("H106", "Hub.bare:except#0"),
        ("H106", "Hub.swallow:except#0"),
        ("H106", "Hub.tuple_broad:except#0"),
    ]


def test_hostlint_broad_except_waiver(tmp_path):
    """The standalone waiver comment above the except line suppresses
    H106 and keeps the reason on record."""
    findings, suppressed = _scan(
        tmp_path, _H106_WAIVED, "host/server.py"
    )
    assert findings == []
    assert [(f.code, r) for f, r in suppressed] == [
        ("H106", "fixture: unwind must not mask")
    ]


def test_hostlint_broad_except_is_module_keyed(tmp_path):
    """The same handlers outside the hub-thread modules are untouched —
    H106 is scoped to the modules whose worker loops must survive
    poison input, not a repo-wide style rule."""
    findings, _ = _scan(tmp_path, _H106_EXCEPTS, "host/metrics.py")
    assert findings == []


def test_hostlint_monotonic_scope_allows_monotonic_flags_wallclock(
    tmp_path,
):
    """The tracing plane's H103 coverage is a SCOPED allow, not a
    blanket waiver: time.monotonic() in host/tracing.py is clean, but
    time.time() there still fires — for the whole module ("*" scope),
    functions included."""
    findings, suppressed = _scan(tmp_path, _MONO_SCOPE, "host/tracing.py")
    assert not suppressed
    assert sorted((f.code, f.scope) for f in findings) == [
        ("H103", "FlightRecorder.bad_stamp:time.time"),
        ("H103", "module_level_helper:time.time"),
    ]


def test_hostlint_monotonic_scope_is_module_keyed(tmp_path):
    """The same source outside the tracing module keeps today's
    behavior: no monotonic-scope rule applies."""
    findings, _ = _scan(tmp_path, _MONO_SCOPE, "host/other.py")
    assert findings == []


def test_hostlint_seeded_scope_wallclock_spellings(tmp_path):
    """`import datetime; datetime.datetime.now()` and `time.time_ns()`
    are wallclock reads too, not just the from-imported spellings."""
    findings, _ = _scan(
        tmp_path, _SEEDED_SCOPE_SPELLINGS, "host/nemesis.py"
    )
    assert sorted(f.scope for f in findings) == [
        "FaultPlan.generate:datetime.datetime.now",
        "FaultPlan.generate:time.time_ns",
    ]


# --------------------------------------------------- the clean-tree gate --
# slow: `scripts/graftlint.py --check` (CI tier 2e) already traces every
# registered kernel and pins the identical invariant in the same tier —
# running these in the fast pass would pay the full 11-kernel x 2-variant
# tracing cost a second time in a process that can't share _TRACE_CACHE.
@pytest.mark.slow
@pytest.mark.parametrize("name", protocols.protocol_names())
def test_registered_kernel_contract_clean(name):
    res = verify_kernel(protocols.make_protocol, name)
    assert res.ok, [f.render() for f in res.findings] or res.error


@pytest.mark.slow
@pytest.mark.parametrize("name", protocols.protocol_names())
def test_registered_kernel_taint_clean(name):
    res = verify_kernel_taint(protocols.make_protocol, name)
    assert res.ok, [f.render() for f in res.findings] or res.error
    # the proof surface: wherever the kernel gates at all, the interval
    # channel decided real gates, and every remaining optimistic clear
    # is on record as a residual
    n_gates = res.extra["gates_proven"] + res.extra["gates_optimistic"]
    if n_gates:
        assert res.extra["gates_proven"] > 0
    assert res.extra["gates_optimistic"] == len(res.extra["residuals"])


@pytest.mark.slow
@pytest.mark.parametrize("name", protocols.protocol_names())
def test_registered_kernel_ranges_clean(name):
    res = verify_kernel_ranges(protocols.make_protocol, name)
    assert res.ok, [f.render() for f in res.findings] or res.error
    inv = res.extra["variants"]["device"]["invariants"]
    assert inv, "no proven invariants for a real kernel"


def test_host_tree_lint_clean():
    pkg_root = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "summerset_tpu",
    )
    res, n_files = hostlint.lint_host(pkg_root)
    assert n_files > 20
    assert res.ok, [f.render() for f in res.findings]
    # the three annotated waivers (control/transport writer locks,
    # snapshot fsync) stay on record in LINT.json
    assert len(res.suppressed) >= 3


def test_report_is_deterministic():
    host, n = hostlint.lint_host(os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "summerset_tpu",
    ))
    kres = {"Fixture": {"contract": verify_kernel(
        make_fixture, "fixturegood"
    )}}
    a = dumps_report(assemble_report(kres, host, n))
    b = dumps_report(assemble_report(kres, host, n))
    assert a == b
    assert '"version": 1' in a


def test_fingerprint_excludes_line_numbers():
    f1 = Finding("H104", "host/x.py", "Hub.flush:os.fsync", "m", line=10)
    f2 = Finding("H104", "host/x.py", "Hub.flush:os.fsync", "m", line=99)
    assert f1.fingerprint == f2.fingerprint


def test_kernel_contract_table_is_authoritative():
    """Kernel passes mint findings through ``rule_finding``, so a check
    can only emit codes the SPI's ``KERNEL_CONTRACT`` table declares."""
    from summerset_tpu.analysis.contract import rule_finding
    from summerset_tpu.core.protocol import KERNEL_CONTRACT

    codes = [code for code, _, _ in KERNEL_CONTRACT]
    assert codes == sorted(set(codes)), "table codes unsorted/duplicated"
    assert codes == [
        "C1", "C10", "C2", "C3", "C4", "C5", "C6", "C7", "C8", "C9",
        "R2", "T1", "T9",
    ]
    assert rule_finding("C1", "K", "leaf", "m").code == "C1"
    with pytest.raises(KeyError):
        rule_finding("Z1", "K", "leaf", "undeclared rule code")
