"""Linearizability checker unit tests (the executable TLA+ stand-in,
SURVEY.md §4 tier 4; utils/linearize.py).  Cluster-level history checks
under live fault schedules live in test_cluster.py — here the checker
itself is proven able to catch the violations the harness exists for,
including the seeded stale local read a broken lease margin produces."""

from summerset_tpu.utils.linearize import (
    Op,
    check_history,
    record_get,
    record_put,
    record_scan,
    record_shed_put,
)


class TestCheckerAccepts:
    def test_sequential_history(self):
        ops = [
            record_put(0, "k", "a", 0.0, 1.0, True),
            record_get(0, "k", "a", 2.0, 3.0),
            record_put(0, "k", "b", 4.0, 5.0, True),
            record_get(0, "k", "b", 6.0, 7.0),
        ]
        ok, diag = check_history(ops)
        assert ok, diag

    def test_concurrent_overlap_reads_either_value(self):
        # put(b) overlaps both gets: one may see "a", the other "b"
        ops = [
            record_put(0, "k", "a", 0.0, 1.0, True),
            record_put(0, "k", "b", 2.0, 6.0, True),
            record_get(1, "k", "a", 2.5, 3.0),
            record_get(2, "k", "b", 3.5, 4.0),
        ]
        ok, diag = check_history(ops)
        assert ok, diag
        # ... but once a get returned "b", a LATER get may not see "a"
        ops_bad = ops + [record_get(1, "k", "a", 4.5, 5.0)]
        ok, _ = check_history(ops_bad)
        assert not ok

    def test_unacked_put_may_or_may_not_apply(self):
        # the timeout put's effect is allowed to surface...
        ops = [
            record_put(0, "k", "a", 0.0, 1.0, True),
            record_put(0, "k", "b", 2.0, None, False),  # timed out
            record_get(1, "k", "b", 5.0, 6.0),
        ]
        ok, diag = check_history(ops)
        assert ok, diag
        # ...or never surface
        ops2 = [
            record_put(0, "k", "a", 0.0, 1.0, True),
            record_put(0, "k", "b", 2.0, None, False),
            record_get(1, "k", "a", 5.0, 6.0),
        ]
        ok, diag = check_history(ops2)
        assert ok, diag

    def test_many_unobserved_unacked_puts_check_fast(self):
        """Nemesis-soak histories leave dozens of timed-out (unacked)
        puts per key; each would double the Wing&Gong search space.  The
        unobserved-unacked prune (sound under unique put values) must
        keep the check effectively linear — this history explodes
        (2^40 placements) without it."""
        import time as _time

        ops = [record_put(0, "k", "base", 0.0, 0.5, True)]
        # 40 concurrent unacked puts nobody ever reads
        for i in range(40):
            ops.append(
                record_put(1 + (i % 3), "k", f"lost-{i}", 1.0, None,
                           False)
            )
        # a long healthy tail of acked writes + matching reads
        for i in range(10):
            t = 10.0 + i
            ops.append(record_put(0, "k", f"w{i}", t, t + 0.2, True))
            ops.append(record_get(4, "k", f"w{i}", t + 0.3, t + 0.4))
        t0 = _time.monotonic()
        ok, diag = check_history(ops)
        assert ok, diag
        assert _time.monotonic() - t0 < 5.0

    def test_observed_unacked_put_survives_prune(self):
        # an unacked put whose value IS read must still be placeable...
        ops = [
            record_put(0, "k", "a", 0.0, 1.0, True),
            record_put(1, "k", "b", 2.0, None, False),
            record_put(2, "k", "c", 2.0, None, False),  # never read
            record_get(3, "k", "b", 5.0, 6.0),
        ]
        ok, diag = check_history(ops)
        assert ok, diag
        # ...and a stale read AFTER observing it is still caught
        ops_bad = ops + [record_get(3, "k", "a", 7.0, 8.0)]
        ok, _ = check_history(ops_bad)
        assert not ok

    def test_shed_mix_with_unacked_and_acked(self):
        """Workload-soak regression: a history mixing sheds, unacked
        puts, and acks.  Shed puts are negatively acked — the server
        guaranteed they never entered the queue — so the checker must
        EXCLUDE them like the unacked prune does, without losing the
        unacked puts' may-have-run semantics."""
        ops = [
            record_put(0, "k", "a", 0.0, 1.0, True),
            record_shed_put(1, "k", "s0", 1.5, 1.6),     # overload
            record_put(2, "k", "u0", 1.5, None, False),  # timed out
            record_shed_put(1, "k", "s1", 2.0, 2.1),
            record_put(0, "k", "b", 3.0, 4.0, True),
            record_get(3, "k", "b", 5.0, 6.0),
            # the unacked put's effect is still allowed to surface
            record_get(3, "k", "u0", 7.0, 8.0),
        ]
        ok, diag = check_history(ops)
        assert ok, diag

    def test_many_sheds_check_fast(self):
        """An overload burst sheds dozens of puts per key; excluded
        outright, they must cost the search nothing (placed like
        unacked ops they would double the space each)."""
        import time as _time

        ops = [record_put(0, "k", "base", 0.0, 0.5, True)]
        for i in range(60):
            ops.append(record_shed_put(
                1 + (i % 3), "k", f"shed-{i}", 1.0, 1.1
            ))
        for i in range(10):
            t = 10.0 + i
            ops.append(record_put(0, "k", f"w{i}", t, t + 0.2, True))
            ops.append(record_get(4, "k", f"w{i}", t + 0.3, t + 0.4))
        t0 = _time.monotonic()
        ok, diag = check_history(ops)
        assert ok, diag
        assert _time.monotonic() - t0 < 5.0

    def test_keys_are_independent(self):
        ops = [
            record_put(0, "x", "1", 0.0, 1.0, True),
            record_put(0, "y", "2", 0.5, 1.5, True),
            record_get(1, "x", "1", 2.0, 3.0),
            record_get(1, "y", "2", 2.0, 3.0),
        ]
        ok, diag = check_history(ops)
        assert ok, diag


class TestCheckerCatches:
    def test_broken_lease_margin_stale_read_caught(self):
        """The seeded stale read (VERDICT r3 #6 'done' criterion): with a
        lease margin shorter than the network delay, a grantee can keep
        serving the old value after a write committed without its ack —
        exactly this observable history, which the checker must reject."""
        ops = [
            record_put(0, "k", "v1", 0.0, 1.0, True),
            record_put(0, "k", "v2", 2.0, 3.0, True),   # committed write
            record_get(1, "k", "v1", 4.0, 5.0),          # stale local read
        ]
        ok, diag = check_history(ops)
        assert not ok
        assert "not linearizable" in diag

    def test_lost_update_caught(self):
        ops = [
            record_put(0, "k", "a", 0.0, 1.0, True),
            record_put(1, "k", "b", 2.0, 3.0, True),
            record_get(2, "k", "a", 3.5, 4.0),
            record_get(2, "k", "b", 4.5, 5.0),
        ]
        # a then b read order would need b's effect to both precede and
        # follow a's read — impossible
        ok, _ = check_history(ops)
        assert not ok

    def test_read_of_never_written_value_caught(self):
        ops = [
            record_put(0, "k", "a", 0.0, 1.0, True),
            record_get(1, "k", "ghost", 2.0, 3.0),
        ]
        ok, _ = check_history(ops)
        assert not ok

    def test_observed_shed_value_caught(self):
        """A get observing a SHED put's value is a violation: the shed
        reply guaranteed the put never executed, so the checker must
        not legalize the observation by placing it (an unacked put in
        the same position WOULD be placeable — that asymmetry is the
        whole point of the negative ack)."""
        ops = [
            record_put(0, "k", "a", 0.0, 1.0, True),
            record_shed_put(1, "k", "s0", 2.0, 2.1),
            record_get(2, "k", "s0", 3.0, 4.0),
        ]
        ok, _ = check_history(ops)
        assert not ok
        # the identical history with an UNACKED put instead passes
        ops_unacked = [
            record_put(0, "k", "a", 0.0, 1.0, True),
            record_put(1, "k", "s0", 2.0, None, False),
            record_get(2, "k", "s0", 3.0, 4.0),
        ]
        ok, diag = check_history(ops_unacked)
        assert ok, diag

    def test_fresh_read_before_any_write_is_none_only(self):
        ops = [record_get(0, "k", None, 0.0, 1.0)]
        ok, diag = check_history(ops)
        assert ok, diag
        ops = [
            record_get(0, "k", None, 0.0, 1.0),
            record_put(0, "k", "a", 2.0, 3.0, True),
            record_get(0, "k", None, 4.0, 5.0),
        ]
        ok, _ = check_history(ops)
        assert not ok


class TestScanDecisionTable:
    """Ordered range reads through the checker: every row of the scan
    semantics the serving planes promise.  A scan is one atomic cut —
    each returned (key, value) must be legal at a single point inside
    the scan window, and each ABSENT in-span key must be legally absent
    at that same point (unless the scan was limit-truncated)."""

    def test_clean_scan_cut(self):
        ops = [
            record_put(0, "a", "1", 0.0, 1.0, True),
            record_put(0, "b", "2", 1.5, 2.5, True),
            record_scan(1, "a", None, [("a", "1"), ("b", "2")],
                        3.0, 4.0),
        ]
        ok, diag = check_history(ops)
        assert ok, diag

    def test_scan_observing_shed_put_caught(self):
        """A scan item carrying a SHED put's value is a violation —
        same negative-ack asymmetry as the point-read row."""
        ops = [
            record_put(0, "a", "1", 0.0, 1.0, True),
            record_shed_put(1, "a", "s0", 2.0, 2.1),
            record_scan(2, "a", None, [("a", "s0")], 3.0, 4.0),
        ]
        ok, _ = check_history(ops)
        assert not ok

    def test_scan_observing_unacked_put_allowed(self):
        """The same shape with a timed-out (unacked) put passes: the
        put's effect is allowed to have surfaced."""
        ops = [
            record_put(0, "a", "1", 0.0, 1.0, True),
            record_put(1, "a", "u0", 2.0, None, False),
            record_scan(2, "a", None, [("a", "u0")], 3.0, 4.0),
        ]
        ok, diag = check_history(ops)
        assert ok, diag

    def test_scan_missing_committed_key_caught(self):
        """An acked put wholly BEFORE the scan window, to a key inside
        the scanned span, must appear in an untruncated result — its
        absence is a lost write, not a legal cut."""
        ops = [
            record_put(0, "a", "1", 0.0, 1.0, True),
            record_put(0, "b", "2", 1.5, 2.5, True),
            record_scan(1, "a", None, [("a", "1")], 3.0, 4.0),
        ]
        ok, _ = check_history(ops)
        assert not ok

    def test_truncated_scan_absence_allowed(self):
        """The identical absence under a LIMIT-capped scan proves
        nothing past the last returned key: the cut stops at "a"."""
        ops = [
            record_put(0, "a", "1", 0.0, 1.0, True),
            record_put(0, "b", "2", 1.5, 2.5, True),
            record_scan(1, "a", None, [("a", "1")], 3.0, 4.0,
                        truncated=True),
        ]
        ok, diag = check_history(ops)
        assert ok, diag

    def test_absence_outside_span_proves_nothing(self):
        """A bounded scan [a, b) says nothing about keys >= b: the
        committed put to "c" may be absent without violation."""
        ops = [
            record_put(0, "a", "1", 0.0, 1.0, True),
            record_put(0, "c", "3", 1.5, 2.5, True),
            record_scan(1, "a", "b", [("a", "1")], 3.0, 4.0),
        ]
        ok, diag = check_history(ops)
        assert ok, diag

    def test_cross_key_single_point_violation_caught(self):
        """The cut must be ONE point: put(a=2) completed before
        put(b=2) even started, so a scan observing the NEW b=2 next to
        the OLD a=1 has no single legal linearization point."""
        ops = [
            record_put(0, "a", "1", 0.0, 1.0, True),
            record_put(0, "b", "1", 0.0, 1.0, True),
            record_put(1, "a", "2", 2.0, 3.0, True),
            record_put(1, "b", "2", 4.0, 5.0, True),
            record_scan(2, "a", None, [("a", "1"), ("b", "2")],
                        6.0, 7.0),
        ]
        ok, _ = check_history(ops)
        assert not ok
        # the consistent cut over the same history passes
        ops_ok = ops[:-1] + [
            record_scan(2, "a", None, [("a", "2"), ("b", "2")],
                        6.0, 7.0),
        ]
        ok, diag = check_history(ops_ok)
        assert ok, diag

    def test_scan_concurrent_with_put_reads_either(self):
        """A put overlapping the scan window may or may not be in the
        cut — both results pass."""
        base = [
            record_put(0, "a", "1", 0.0, 1.0, True),
            record_put(0, "a", "2", 2.0, 6.0, True),
        ]
        old = base + [record_scan(1, "a", None, [("a", "1")],
                                  3.0, 4.0)]
        new = base + [record_scan(1, "a", None, [("a", "2")],
                                  3.0, 4.0)]
        ok, diag = check_history(old)
        assert ok, diag
        ok, diag = check_history(new)
        assert ok, diag

    def test_scan_of_never_written_value_caught(self):
        ops = [
            record_put(0, "a", "1", 0.0, 1.0, True),
            record_scan(1, "a", None, [("a", "ghost")], 2.0, 3.0),
        ]
        ok, _ = check_history(ops)
        assert not ok

    def test_empty_scan_before_any_write_allowed(self):
        ops = [
            record_scan(0, "a", None, [], 0.0, 1.0),
            record_put(0, "a", "1", 2.0, 3.0, True),
        ]
        ok, diag = check_history(ops)
        assert ok, diag
