"""Decision-table coverage for the autopilot policy tier
(host/autopilot.py): hysteresis/cooldown anti-flap, quorum gating,
per-window actuation budget, observe-mode zero-mutation, seeded
determinism of the decision trace, each actuator's lowering against a
fake ctrl endpoint, and the satellite regression that reshard decisions
share the same budget as every other actuator."""

from typing import Any, Dict, List, Optional

from summerset_tpu.host.autopilot import (
    ACTUATORS, AutopilotDriver, AutopilotPolicy, Decision, build_senses,
)
from summerset_tpu.host.resharding import ResharderPolicy


def base_senses(**over) -> Dict[str, Any]:
    """A healthy, quiet 3-replica cluster's senses."""
    s = {
        "population": 3, "alive": 3, "leader": 0,
        "health": {0: 1.0, 1: 1.0, 2: 1.0},
        "ingress": {0: 50.0, 1: 10.0, 2: 10.0},
        "shed_rate": 0.0, "queue_depth": 0.0,
        "api_max_batch": 2, "pipeline": False,
        "heat": {}, "lease_protocol": False, "responders": None,
        "sids": [0, 1, 2],
    }
    s.update(over)
    return s


def pol(**over) -> AutopilotPolicy:
    kw = dict(seed=7, population=3, streak_need=3, cooldown_rounds=10,
              window_rounds=8, budget_per_window=2)
    kw.update(over)
    return AutopilotPolicy(**kw)


class TestHysteresisAndCooldown:
    def test_oscillating_shed_never_flaps_inside_cooldown(self):
        """A shed signal that flips every round must never build a
        streak; a sustained one fires ONCE and then sits out the
        cooldown even if the signal keeps screaming."""
        p = pol(shed_alpha=1.0)  # no EWMA smoothing: raw oscillation
        fired: List[Decision] = []
        for i in range(40):
            fired += p.evaluate(base_senses(
                shed_rate=0.5 if i % 2 == 0 else 0.0,
            ))
        assert fired == []  # oscillation flaps the streak, not the knob

        p2 = pol()
        fired2: List[Decision] = []
        for _ in range(12):
            fired2 += p2.evaluate(base_senses(shed_rate=0.5))
        batch = [d for d in fired2 if d.actuator == "batch"]
        # streak_need=3 ⇒ first fire at round 2; cooldown(10) holds the
        # next until round >= 13 — within 12 rounds exactly one fire
        assert len(batch) == 1
        assert batch[0].arg == 4  # 2 -> 4 on the doubling ladder

    def test_sub_threshold_signal_never_fires(self):
        p = pol()
        fired = []
        for _ in range(30):
            fired += p.evaluate(base_senses(shed_rate=0.001))
        assert fired == []


class TestQuorumGate:
    def test_no_quorum_actuates_nothing_and_resets_streaks(self):
        p = pol()
        # bank 2 rounds of streak, then lose quorum with the same
        # screaming signals — nothing may fire, and the banked streak
        # must NOT carry across the churn window
        for _ in range(2):
            p.evaluate(base_senses(shed_rate=0.5))
        for _ in range(10):
            out = p.evaluate(base_senses(shed_rate=0.5, alive=1))
            assert out == []
        assert not p.last_quorum
        # quorum returns: the streak restarts from zero (needs 3 fresh
        # rounds, so rounds 1..2 after return fire nothing)
        assert p.evaluate(base_senses(shed_rate=0.5)) == []
        assert p.evaluate(base_senses(shed_rate=0.5)) == []
        assert len(p.evaluate(base_senses(shed_rate=0.5))) == 1

    def test_leaderless_counts_as_no_quorum(self):
        p = pol()
        for _ in range(10):
            assert p.evaluate(base_senses(
                shed_rate=0.5, leader=None,
            )) == []


class TestBudget:
    def test_window_budget_never_exceeded(self):
        """Every signal screaming every round: per-window actuation
        spend must stay <= budget_per_window."""
        p = pol(streak_need=1, cooldown_rounds=0, budget_per_window=2,
                window_rounds=8)
        per_window: Dict[int, int] = {}
        for i in range(64):
            out = p.evaluate(base_senses(
                shed_rate=0.5,
                health={0: 0.1, 1: 1.0, 2: 1.0},   # leader unhealthy
                api_max_batch=2,
            ))
            per_window[i // 8] = per_window.get(i // 8, 0) + len(
                [d for d in out if d.actuator != "recommend"]
            )
        assert per_window and all(n <= 2 for n in per_window.values())

    def test_reshard_and_lead_move_share_group_budget(self):
        """Satellite regression: a simultaneous heat spike + leader
        health indictment actuates at most ONE change per group per
        window — ResharderPolicy decisions flow through the same
        budget via budget_gate."""
        rp = ResharderPolicy(2, lambda k: 1, hot_frac=0.25,
                             cold_frac=0.02, min_total=10)
        p = pol(streak_need=1, cooldown_rounds=0, budget_per_window=8,
                window_rounds=6, num_groups=2, resharder=rp)
        assert rp.budget_gate is not None  # installed by the ctor
        senses = base_senses(
            health={0: 0.1, 1: 1.0, 2: 1.0},       # indicted leader
            heat={"hot": 90, "cold": 10},           # splittable spike
        )
        # hot's hash-home is group 1 ⇒ split dst = (1+1)%2 = group 0,
        # the same group lead_move targets
        per_group_window: Dict[tuple, int] = {}
        for i in range(18):
            for d in p.evaluate(dict(senses)):
                if d.actuator == "recommend":
                    continue
                k = (d.group, i // 6)
                per_group_window[k] = per_group_window.get(k, 0) + 1
        assert per_group_window
        assert all(n <= 1 for n in per_group_window.values())

    def test_budget_refused_reshard_keeps_candidate(self):
        """A budget-refused split must leave ResharderPolicy._moved
        untouched so the same decision stays available later."""
        rp = ResharderPolicy(2, lambda k: 1, min_total=10,
                             budget_gate=lambda g: False)
        assert rp.decide({"hot": 90, "cold": 10}) is None
        assert rp._moved == {}
        rp.budget_gate = lambda g: True
        ch = rp.decide({"hot": 90, "cold": 10})
        assert ch is not None and ch.op == "split"


class TestDeterminism:
    def _feed(self, p: AutopilotPolicy) -> None:
        seq = (
            [base_senses()] * 2
            + [base_senses(shed_rate=0.4)] * 6
            + [base_senses(alive=1)] * 3
            + [base_senses(health={0: 0.2, 1: 1.0, 2: 1.0})] * 8
            + [base_senses()] * 4
        )
        for s in seq:
            p.evaluate(dict(s))

    def test_same_seed_same_senses_identical_timeline(self):
        a, b = pol(seed=42), pol(seed=42)
        self._feed(a)
        self._feed(b)
        assert a.timeline() == b.timeline()
        assert a.digest() == b.digest()
        assert a.decisions()  # the sequence actually fired something

    def test_config_digest_tracks_knobs_only(self):
        a, b = pol(seed=42), pol(seed=42)
        self._feed(a)        # decisions fired
        assert a.config_digest() == b.config_digest()
        assert pol(seed=43).config_digest() != a.config_digest()


class _FakeCtrl:
    """Records every CtrlRequest the driver sends; replies like a
    manager that applied everything."""

    def __init__(self, info=None):
        self.requests: list = []
        self.info = info

    def __call__(self, req):
        self.requests.append(req)
        if req.kind == "query_info":
            return self.info
        return {"ok": True}

    def mutating(self) -> list:
        return [r for r in self.requests if r.kind != "query_info"]


class TestDriver:
    def test_observe_mode_sends_zero_ctrl_mutations(self):
        """The byte-identical-to-off contract: an observing driver may
        scrape but never mutate, even while decisions fire."""
        ctrl = _FakeCtrl()
        p = pol(streak_need=1, cooldown_rounds=0)
        drv = AutopilotDriver(
            None, p, mode="observe", ctrl=ctrl,
            sense_fn=lambda: base_senses(shed_rate=0.5),
        )
        for _ in range(10):
            drv.step()
        assert drv.decision_log          # decisions were made ...
        assert drv.actuation_log == []   # ... but nothing was sent
        assert ctrl.requests == []       # not even an announce

    def test_act_mode_lowers_each_actuator(self):
        """Each actuator's ctrl lowering against the fake endpoint."""
        ctrl = _FakeCtrl()
        conf_calls: List[List[int]] = []
        # shed_alpha=1.0: the EWMA is the instantaneous shed rate, so a
        # batch signal in one round cannot linger and starve a later
        # round's actuator through the one-change-per-group window; the
        # hash-home of 0 puts the split's dst on group 1, away from the
        # group-0 lead/batch/conf actuations
        p = pol(streak_need=1, cooldown_rounds=0, budget_per_window=99,
                window_rounds=1, num_groups=2, shed_alpha=1.0,
                resharder=ResharderPolicy(2, lambda k: 0, min_total=10))
        drv = AutopilotDriver(
            None, p, mode="act", ctrl=ctrl,
            conf_ctl=conf_calls.append,
            sense_fn=lambda: None,
        )
        rounds = [
            # lead_move: unhealthy leader
            base_senses(health={0: 0.1, 1: 1.0, 2: 1.0}),
            # batch: shed with headroom
            base_senses(shed_rate=0.6),
            # pipeline: shed at batch_max, serial loop
            base_senses(shed_rate=0.6, api_max_batch=16),
            # conf_resize: concentrated heat on a lease protocol
            base_senses(lease_protocol=True, responders=[0, 1, 2],
                        heat={"hk": 95, "x": 5}),
            # reshard: splittable heat spike
            base_senses(heat={"hk2": 90, "y": 10}),
        ]
        it = iter(rounds)
        drv._sense_fn = lambda: next(it, None)
        for _ in rounds:
            drv.step()
        kinds = [(r.kind, (r.payload or {}).get("act"))
                 for r in ctrl.mutating()]
        assert ("autopilot_ctl", "demote") in kinds
        assert ("autopilot_ctl", "retune") in kinds
        assert ("range_change", None) in kinds
        assert ("autopilot_ctl", "announce") in kinds
        retunes = [r.payload for r in ctrl.mutating()
                   if (r.payload or {}).get("act") == "retune"]
        assert any("api_max_batch" in p_ for p_ in retunes)
        assert any(p_.get("pipeline") is True for p_ in retunes)
        demotes = [r for r in ctrl.mutating()
                   if (r.payload or {}).get("act") == "demote"]
        assert demotes[0].servers == [0]  # targeted at the leader
        assert conf_calls == [[0]]        # shrink to {leader}∪{top}
        reshards = [r for r in ctrl.mutating()
                    if r.kind == "range_change"]
        assert reshards and reshards[0].payload["op"] == "split"

    def test_recommend_is_log_only(self):
        ctrl = _FakeCtrl()
        p = pol(streak_need=1, cooldown_rounds=0)
        drv = AutopilotDriver(
            None, p, mode="act", ctrl=ctrl,
            sense_fn=lambda: base_senses(
                shed_rate=0.6, api_max_batch=16, pipeline=True,
            ),
        )
        for _ in range(6):
            drv.step()
        recs = [d for d in p.decisions() if d.actuator == "recommend"]
        assert len(recs) == 1  # once-ever
        assert all(r.kind == "autopilot_ctl"
                   and (r.payload or {}).get("act") == "announce"
                   for r in ctrl.mutating())


def _burn_row(alerting=True, fast=5.0, slow=5.0):
    return {"burn": fast, "fast": fast, "slow": slow,
            "alerting": alerting}


class TestSloBurnSense:
    """graftwatch burn alerts as an autopilot sense: inert when the
    sense key is absent (pre-graftwatch byte-identity), and each
    latched objective lowers through SLO_ACTUATORS under the same
    streak/admission gates as native signals."""

    def test_absent_sense_key_is_inert(self):
        a, b = pol(seed=42), pol(seed=42)
        seq = (
            [base_senses()] * 3
            + [base_senses(shed_rate=0.4)] * 6
            + [base_senses()] * 4
        )
        for s in seq:
            a.evaluate(dict(s))
        for s in seq:
            # a non-alerting burn payload must change nothing either
            s = dict(s)
            s["slo_burn"] = {"reply_p99": _burn_row(alerting=False)}
            b.evaluate(s)
        assert a.timeline() == b.timeline()
        assert a.digest() == b.digest()
        assert a.config_digest() == b.config_digest()

    def test_reply_burn_streak_escalates_batch(self):
        p = pol()
        fired = []
        for _ in range(5):
            fired += p.evaluate(base_senses(
                slo_burn={"reply_p99": _burn_row()},
            ))
        batch = [d for d in fired if d.actuator == "batch"]
        assert len(batch) == 1
        assert batch[0].arg == 4  # 2 -> 4 on the doubling ladder
        assert batch[0].reason.startswith("slo:reply_p99")

    def test_flapping_alert_never_fires(self):
        p = pol()
        fired = []
        for i in range(30):
            fired += p.evaluate(base_senses(
                slo_burn={"reply_p99": _burn_row(alerting=i % 2 == 0)},
            ))
        assert fired == []  # latch must PERSIST a full streak

    def test_wal_burn_demotes_the_leader(self):
        p = pol()
        fired = []
        for _ in range(5):
            fired += p.evaluate(base_senses(
                slo_burn={"wal_fsync_lag": _burn_row()},
            ))
        moves = [d for d in fired if d.actuator == "lead_move"]
        assert len(moves) == 1
        assert moves[0].target == 0   # the sensed leader
        assert moves[0].arg is None   # successor left to the kernel
        assert moves[0].reason.startswith("slo:wal_fsync_lag")

    def test_scan_burn_recommends_once_ever(self):
        p = pol(cooldown_rounds=0)
        fired = []
        for _ in range(20):
            fired += p.evaluate(base_senses(
                slo_burn={"scan_starvation": _burn_row()},
            ))
        recs = [d for d in fired if d.actuator == "recommend"]
        assert len(recs) == 1
        assert recs[0].arg == {"scan_tier": "learner"}


class TestBuildSenses:
    def _snap(self, sid, req=0, shed=0, heat=(), score=1.0, batch=4):
        gauges = {"health_score": score, "api_queue_depth": 0.0}
        for k, n in heat:
            gauges[f"range_heat{{key={k}}}"] = n
        return {
            "protocol": "MultiPaxos", "pipeline": False,
            "api_max_batch": batch,
            "host": {
                "counters": {"api_requests_total": req,
                             "api_shed": shed},
                "gauges": gauges,
            },
        }

    def test_deltas_against_previous_cursor(self):
        class _Info:
            leader = 0
            servers = {0: None, 1: None, 2: None}

        snaps1 = {str(s): self._snap(s, req=100, shed=0,
                                     heat=[("hk", 50)])
                  for s in range(3)}
        s1, cur = build_senses(snaps1, _Info(), None)
        assert s1["alive"] == 3 and s1["leader"] == 0
        assert s1["api_max_batch"] == 4
        assert s1["lease_protocol"] is False
        snaps2 = {str(s): self._snap(s, req=150, shed=25,
                                     heat=[("hk", 80)])
                  for s in range(3)}
        s2, _ = build_senses(snaps2, _Info(), cur)
        assert s2["ingress"] == {0: 50, 1: 50, 2: 50}
        assert abs(s2["shed_rate"] - 75 / 150) < 1e-9
        assert s2["heat"] == {"hk": 90}  # (80-50) summed over 3 sids
