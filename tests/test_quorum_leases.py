"""Vectorized QuorumLeases kernel tests: conf changes through the log,
leased-responder local reads with quiescence, the all-responders write
barrier, lease expiry restoring write availability, and leader leases
(reference behaviors: ``quorum_leases/quorumconf.rs``,
``quorumlease.rs:10-42``, ``leaderlease.rs:10-21``).
"""

import jax.numpy as jnp
import numpy as np

from smr_helpers import check_agreement, run_segment
from summerset_tpu.core import Engine
from summerset_tpu.core.netmodel import ControlInputs
from summerset_tpu.protocols import make_protocol
from summerset_tpu.protocols.quorum_leases import ReplicaConfigQuorumLeases
import pytest


def make_kernel(G, R, W, P, **kw):
    cfg = ReplicaConfigQuorumLeases(max_proposals_per_tick=P, **kw)
    return make_protocol("quorumleases", G, R, W, cfg)


def run_with_conf(eng, state, ns, ticks, n_prop, conf, alive=None,
                  base_start=0, collect=False):
    G = eng.kernel.G
    P = eng.kernel.config.max_proposals_per_tick
    t = jnp.arange(ticks, dtype=jnp.int32)
    seq = {
        "n_proposals": jnp.full((ticks, G), n_prop, jnp.int32),
        "value_base": jnp.broadcast_to(
            ((base_start + t) * P)[:, None], (ticks, G)
        ),
        "conf_target": jnp.full((ticks, G), conf, jnp.int32),
    }
    if alive is not None:
        seq["alive"] = jnp.broadcast_to(alive, (ticks,) + alive.shape)
    return eng.run_ticks(state, ns, seq, collect=collect)


class TestConfChanges:
    def test_conf_applies_via_log(self):
        G, R, W, P = 2, 5, 32, 4
        k = make_kernel(G, R, W, P)
        eng = Engine(k)
        state, ns = eng.init()
        conf = 0b00110  # responders {1, 2}
        state, ns, _ = run_with_conf(eng, state, ns, 30, P, conf)
        st = {k_: np.asarray(v) for k_, v in state.items()}
        # every replica applied the conf through execution
        assert (st["conf_cur"] == conf).all(), st["conf_cur"]
        assert (st["conf_slot"] >= 0).all()
        check_agreement(st, G, R, W)


class TestLocalReads:
    def test_responders_hold_leases_and_serve_quiet_buckets(self):
        G, R, W, P = 2, 5, 32, 2
        k = make_kernel(G, R, W, P, num_key_buckets=8)
        eng = Engine(k)
        state, ns = eng.init()
        conf = 0b00110
        state, ns, _ = run_with_conf(eng, state, ns, 30, P, conf)
        # quiesce: stop writes, keep ticking (grants continue)
        state, ns, fx = run_with_conf(
            eng, state, ns, 20, 0, conf, base_start=100, collect=True
        )
        lease = np.asarray(fx.extra["lease_held"])[-1]
        nloc = np.asarray(fx.extra["n_local_buckets"])[-1]
        for r in (1, 2):
            assert lease[:, r].all(), (r, lease)
            assert (nloc[:, r] == 8).all(), (r, nloc)
        # non-responders never serve locally
        for r in (0, 3, 4):
            assert (nloc[:, r] == 0).all(), (r, nloc)

    @pytest.mark.slow
    def test_pending_writes_block_their_bucket_only(self):
        G, R, W, P = 2, 5, 32, 2
        k = make_kernel(G, R, W, P, num_key_buckets=8)
        eng = Engine(k)
        state, ns = eng.init()
        conf = 0b00110
        state, ns, _ = run_with_conf(eng, state, ns, 30, P, conf)
        state, ns, _ = run_with_conf(eng, state, ns, 20, 0, conf)
        # under write load some buckets are pending at responders, so the
        # locally servable bucket count drops below the full set
        state, ns, fx = run_with_conf(
            eng, state, ns, 10, P, conf, base_start=500, collect=True
        )
        nloc = np.asarray(fx.extra["n_local_buckets"])
        assert (nloc[:, :, 1] < 8).any()
        assert (nloc[:, :, 1] > 0).any()


class TestWriteBarrier:
    @pytest.mark.slow
    def test_dead_responder_stalls_writes_until_lease_expiry(self):
        G, R, W, P = 2, 5, 48, 2
        k = make_kernel(G, R, W, P, lease_len=16, lease_margin=4,
                        hear_timeout_lo=40, hear_timeout_hi=70)
        eng = Engine(k)
        state, ns = eng.init()
        conf = 0b00110
        state, ns, _ = run_with_conf(eng, state, ns, 30, P, conf)
        pre = np.asarray(state["commit_bar"])[:, 0].copy()

        # kill responder 2: writes must stall while its lease may be live
        alive = jnp.ones((G, R), jnp.bool_).at[:, 2].set(False)
        state, ns, _ = run_with_conf(
            eng, state, ns, 8, P, conf, alive=alive, base_start=1000
        )
        mid = np.asarray(state["commit_bar"])[:, 0]
        assert (mid <= pre + 2 * P).all(), (pre, mid)

        # after lease_len + margin ticks the barrier lifts (no refresh to a
        # dead peer) and commits resume with the remaining majority
        state, ns, _ = run_with_conf(
            eng, state, ns, 60, P, conf, alive=alive, base_start=2000
        )
        fin = {k_: np.asarray(v) for k_, v in state.items()}
        assert (fin["commit_bar"][:, 0] > mid + 10 * P).all(), (
            mid, fin["commit_bar"][:, 0],
        )
        check_agreement(fin, G, R, W)


class TestPartitionSafety:
    def test_minority_partitioned_responder_loses_lease(self):
        # regression: a deposed leader partitioned together with a
        # responder must NOT be able to keep that responder serving local
        # reads while the majority side commits new writes.  With
        # majority-grantor leases the minority responder's lease count
        # falls below quorum (only the old leader + itself refresh), so
        # lease_held drops; the majority side's writes stay safe.
        G, R, W, P = 2, 5, 48, 2
        k = make_kernel(G, R, W, P, lease_len=12, lease_margin=4,
                        hear_timeout_lo=30, hear_timeout_hi=50)
        eng = Engine(k, seed=3)
        state, ns = eng.init()
        conf = 0b00110  # grantees {1, 2}
        state, ns, _ = run_with_conf(eng, state, ns, 30, P, conf)

        # partition {0, 1} | {2, 3, 4}
        link = ControlInputs.split_links(G, R, (0, 1))
        seq_ticks = 200
        t = jnp.arange(seq_ticks, dtype=jnp.int32)
        seq = {
            "n_proposals": jnp.full((seq_ticks, G), P, jnp.int32),
            "value_base": jnp.broadcast_to(
                ((1000 + t) * P)[:, None], (seq_ticks, G)
            ),
            "conf_target": jnp.full((seq_ticks, G), conf, jnp.int32),
            "link_up": jnp.broadcast_to(
                jnp.asarray(link), (seq_ticks, G, R, R)
            ),
        }
        state, ns, fx = eng.run_ticks(state, ns, seq, collect=True)
        st = {k_: np.asarray(v) for k_, v in state.items()}
        lease = np.asarray(fx.extra["lease_held"])[-1]
        # responder 1 (minority side) lost its majority lease
        assert not lease[:, 1].any(), lease
        # majority side elected a leader and kept committing
        assert (st["commit_bar"][:, 2:].max(axis=1) > 30).all(), (
            st["commit_bar"]
        )
        # responder 2 (majority side) still holds a majority lease
        assert lease[:, 2].all(), lease
        check_agreement(st, G, R, W)


class TestClockSkew:
    def test_local_reads_stay_safe_under_skew(self):
        """Nemesis clock-skew regression (ROADMAP open item): one
        responder's tick clock runs at half rate (duty-cycled alive —
        its lease countdowns crawl, exactly the dangerous direction:
        the holder believes its lease longer than the grantors do).

        Safety invariant checked at EVERY collected tick: whenever the
        skewed responder could serve ALL buckets locally (lease held +
        fully quiescent), no replica anywhere has committed a slot the
        responder has not executed — the write barrier (every leased
        write needs the responder's applied ack) must hold under skew,
        or a local read would return a stale value.  Liveness: commits
        still advance, and agreement holds at the end."""
        G, R, W, P = 2, 5, 48, 2
        k = make_kernel(G, R, W, P, lease_len=12, lease_margin=4,
                        num_key_buckets=8,
                        hear_timeout_lo=40, hear_timeout_hi=70)
        eng = Engine(k, seed=5)
        state, ns = eng.init()
        conf = 0b00110  # responders {1, 2}
        state, ns, _ = run_with_conf(eng, state, ns, 30, P, conf)
        pre = int(np.asarray(state["commit_bar"]).max())

        T = 160
        skew = ControlInputs.skew_alive(G, R, T, {2: 0.5})
        t = jnp.arange(T, dtype=jnp.int32)
        seq = {
            "n_proposals": jnp.full((T, G), P, jnp.int32),
            "value_base": jnp.broadcast_to(
                ((1000 + t) * P)[:, None], (T, G)
            ),
            "conf_target": jnp.full((T, G), conf, jnp.int32),
            "alive": skew,
        }
        state, ns, fx = eng.run_ticks(state, ns, seq, collect=True)
        st = {k_: np.asarray(v) for k_, v in state.items()}
        lease = np.asarray(fx.extra["lease_held"])        # [T, G, R]
        nloc = np.asarray(fx.extra["n_local_buckets"])    # [T, G, R]
        cb = np.asarray(fx.commit_bar)                    # [T, G, R]
        eb = np.asarray(fx.exec_bar)
        servable = lease[:, :, 2] & (nloc[:, :, 2] == 8)
        stale = servable & (cb.max(axis=2) > eb[:, :, 2])
        assert not stale.any(), (
            "skewed responder could serve a local read while lagging "
            f"committed state at ticks {np.nonzero(stale.any(axis=1))[0]}"
        )
        # liveness under skew: the write plane keeps committing
        assert int(st["commit_bar"].max()) > pre + 20, (
            pre, st["commit_bar"],
        )
        check_agreement(st, G, R, W)


class TestLeaderLease:
    def test_leader_reads_and_stability(self):
        G, R, W, P = 2, 5, 32, 2
        k = make_kernel(G, R, W, P)
        eng = Engine(k)
        state, ns = eng.init()
        state, ns, fx = run_with_conf(
            eng, state, ns, 30, P, -1, collect=True
        )
        ok = np.asarray(fx.extra["leader_read_ok"])[-1]
        assert ok[:, 0].all(), ok
        assert not ok[:, 1:].any()

    @pytest.mark.slow
    def test_failover_still_happens_after_lease_expiry(self):
        G, R, W, P = 4, 5, 32, 2
        k = make_kernel(G, R, W, P)
        eng = Engine(k, seed=9)
        state, ns = eng.init()
        state, ns, _ = run_with_conf(eng, state, ns, 20, P, -1)
        alive = jnp.ones((G, R), jnp.bool_).at[:, 0].set(False)
        state, ns, _ = run_with_conf(
            eng, state, ns, 300, P, -1, alive=alive, base_start=1000
        )
        st = {k_: np.asarray(v) for k_, v in state.items()}
        assert (st["commit_bar"][:, 1:].max(axis=1) > 20 * P).all()
        check_agreement(st, G, R, W)
