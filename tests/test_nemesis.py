"""Nemesis engine tests: seeded schedule determinism, the device-plane
compiler, the FrameFaults transport shim, the WAL fault injector, and a
device-plane soak (kernel survives a whole compiled schedule + heals).

The live-cluster soak (schedule through the manager control plane +
linearizability check) runs as tier 2c (scripts/nemesis_soak.py); the
slow-marked test here is its single-seed pytest form.
"""

import os
import socket
import threading
import time

import numpy as np
import pytest

from smr_helpers import check_agreement, run_segment
from summerset_tpu.core import Engine
from summerset_tpu.host.nemesis import (
    ALL_CLASSES,
    HOST_ONLY,
    FaultPlan,
)
from summerset_tpu.host.storage import LogAction, StorageHub
from summerset_tpu.protocols import make_protocol
from summerset_tpu.protocols.multipaxos import ReplicaConfigMultiPaxos
from summerset_tpu.utils import safetcp


class TestPlanDeterminism:
    def test_same_seed_byte_identical(self):
        for seed in (0, 1, 7, 123):
            a = FaultPlan.generate(seed, 5, 200)
            b = FaultPlan.generate(seed, 5, 200)
            assert a.timeline() == b.timeline()
            assert a.digest() == b.digest()
            assert a.events == b.events

    def test_different_seeds_differ(self):
        a = FaultPlan.generate(1, 5, 200)
        b = FaultPlan.generate(2, 5, 200)
        assert a.timeline() != b.timeline()

    def test_compiled_masks_deterministic(self):
        a = FaultPlan.generate(9, 3, 120).compile_device(2)
        b = FaultPlan.generate(9, 3, 120).compile_device(2)
        assert (a["alive"] == b["alive"]).all()
        assert (a["link_up"] == b["link_up"]).all()

    def test_events_heal_before_horizon(self):
        for seed in range(5):
            p = FaultPlan.generate(seed, 5, 200)
            tail = max(10, 200 // 4)
            for ev in p.events:
                assert ev.tick + ev.duration < 200 - tail, ev

    def test_victims_capped_below_quorum(self):
        for seed in range(8):
            p = FaultPlan.generate(seed, 5, 300)
            for ev in p.events:
                if ev.kind in ("crash", "device_reset", "pause",
                               "isolate", "wal_torn", "wal_fsync"):
                    assert len(ev.targets) <= 2, ev

    def test_unknown_class_rejected(self):
        with pytest.raises(ValueError):
            FaultPlan.generate(1, 3, 50, classes=("nope",))


class TestDeviceCompile:
    def test_partition_window_and_heal(self):
        from summerset_tpu.host.nemesis import FaultEvent

        p = FaultPlan(
            seed=0, population=5, ticks=30,
            events=(FaultEvent(5, "partition", (0, 1), 10),),
        )
        m = p.compile_device(2)
        link = m["link_up"]
        assert link.shape == (30, 2, 5, 5)
        # inside the window: cross-cut links down both ways, intra up
        assert not link[7, :, 0, 2].any()
        assert not link[7, :, 3, 1].any()
        assert link[7, :, 0, 1].all() and link[7, :, 2, 4].all()
        # before and after: fully healed
        assert link[4].all() and link[15:].all()
        assert m["alive"].all()

    def test_crash_freezes_alive(self):
        from summerset_tpu.host.nemesis import FaultEvent

        p = FaultPlan(
            seed=0, population=3, ticks=20,
            events=(FaultEvent(3, "crash", (1,), 6),),
        )
        m = p.compile_device(1)
        assert not m["alive"][3:9, :, 1].any()
        assert m["alive"][9:].all() and m["alive"][:3].all()
        assert m["link_up"].all()

    def test_one_way_is_asymmetric(self):
        from summerset_tpu.host.nemesis import FaultEvent

        p = FaultPlan(
            seed=0, population=3, ticks=10,
            events=(FaultEvent(2, "one_way", (0, 2), 4),),
        )
        link = p.compile_device(1)["link_up"]
        assert not link[3, :, 0, 2].any()
        assert link[3, :, 2, 0].all()

    def test_drop_masks_only_target_egress_and_keep_self(self):
        from summerset_tpu.host.nemesis import FaultEvent

        p = FaultPlan(
            seed=4, population=4, ticks=40,
            events=(FaultEvent(0, "drop", (1,), 40, 0.5),),
        )
        link = p.compile_device(2)["link_up"]
        # non-target rows untouched; self-links always up
        assert link[:, :, 0, :].all() and link[:, :, 2, :].all()
        assert link[:, :, 1, 1].all()
        # the target's egress actually loses frames (0.5 over 40 ticks)
        downs = (~link[:, :, 1, :]).sum()
        assert downs > 0

    def test_host_only_classes_no_device_effect(self):
        from summerset_tpu.host.nemesis import FaultEvent

        for kind in HOST_ONLY:
            p = FaultPlan(
                seed=0, population=3, ticks=10,
                events=(FaultEvent(2, kind, (0,), 4, 0.5),),
            )
            m = p.compile_device(1)
            assert m["alive"].all() and m["link_up"].all()


class TestDeviceReset:
    """The durable device-crash model: a ``device_reset`` victim loses
    every volatile state row (rebuilt from only the kernel's declared
    durable leaves) yet the group re-converges — the device analog of a
    host crash-restart's WAL replay."""

    def test_compile_device_lowers_reset_at_thaw(self):
        from summerset_tpu.host.nemesis import FaultEvent

        p = FaultPlan(
            seed=0, population=3, ticks=20,
            events=(FaultEvent(3, "device_reset", (1,), 6),),
        )
        m = p.compile_device(1)
        # down for the duration, like a crash...
        assert not m["alive"][3:9, :, 1].any()
        assert m["alive"][9:].all() and m["alive"][:3].all()
        # ...then exactly one reset pulse on the thaw tick
        assert m["reset"][9, :, 1].all()
        assert m["reset"].sum() == m["reset"][9, :, 1].size
        # plain crash stays freeze-and-thaw: no reset pulse
        pc = FaultPlan(
            seed=0, population=3, ticks=20,
            events=(FaultEvent(3, "crash", (1,), 6),),
        )
        assert not pc.compile_device(1)["reset"].any()

    def test_host_actions_for_long_lived_classes(self):
        from summerset_tpu.host.nemesis import FaultEvent

        p = FaultPlan(
            seed=0, population=3, ticks=40,
            events=(
                FaultEvent(2, "device_reset", (1,), 8),
                FaultEvent(15, "conf_change", (0, 2), 0),
                FaultEvent(20, "take_snapshot", (0,), 0, 1.0),
                FaultEvent(25, "take_snapshot", (1,), 0, 0.0),
            ),
        )
        acts = {a[0]: (a[1], a[3]) for a in p.host_actions()}
        # device_reset lowers to a durable manager reset on the host
        assert acts[2] == ("reset", {"servers": [1]})
        assert acts[15] == ("conf_change", {"responders": [0, 2]})
        assert acts[20] == ("take_snapshot",
                            {"servers": [0], "crash": True})
        assert acts[25] == ("take_snapshot",
                            {"servers": [1], "crash": False})

    def test_reset_loses_volatile_keeps_durable_then_reconverges(self):
        """Acceptance regression: after a reset tick the victim's
        volatile leaves (commit_bar, telem) are zeroed while durable
        leaves (bal_max, win_val) survive verbatim; the group then
        re-converges with agreement under fault-free ticks."""
        import jax.numpy as jnp

        G, R, W, P = 2, 3, 32, 2
        cfg = ReplicaConfigMultiPaxos(max_proposals_per_tick=P)
        kernel = make_protocol("multipaxos", G, R, W, cfg)
        eng = Engine(kernel, seed=3)
        state, ns = eng.init()
        state, ns, _ = run_segment(eng, state, ns, 40, n_prop=P)
        pre = {k: np.asarray(v) for k, v in state.items()}
        assert (pre["commit_bar"][:, 1] > 0).all()
        assert (pre["bal_max"][:, 1] > 0).all()

        # the reset tick: victim 1 is dead AND reborn-from-durable, so
        # the tick's freeze leaves exactly the post-crash state visible
        alive = jnp.ones((G, R), bool).at[:, 1].set(False)
        reset = jnp.zeros((G, R), bool).at[:, 1].set(True)
        state, ns, _ = eng.tick(state, ns, {
            "n_proposals": jnp.zeros((G,), jnp.int32),
            "value_base": jnp.zeros((G,), jnp.int32),
            "alive": alive, "reset": reset,
        })
        st = {k: np.asarray(v) for k, v in state.items()}
        # volatile rows rewound to boot — the crash demonstrably lost
        # state (commit_bar/telem boot at zero)
        assert (st["commit_bar"][:, 1] == 0).all()
        assert (st["telem"][:, 1] == 0).all()
        # ...and rewound means the BOOT template, not zeros: the
        # randomized heartbeat timeout returns to its freshly-booted
        # draw (zeroing it would instead fire an instant election storm,
        # and zeroing lease holdoffs would break lease safety)
        boot = {k: np.asarray(v) for k, v in eng._boot.items()}
        assert (st["hb_cnt"][:, 1] == boot["hb_cnt"][:, 1]).all()
        assert (boot["hb_cnt"][:, 1] > 0).all()
        # durable rows survive verbatim (the in-kernel WAL analog)
        assert (st["bal_max"][:, 1] == pre["bal_max"][:, 1]).all()
        assert (st["win_val"][:, 1] == pre["win_val"][:, 1]).all()
        # survivors keep stepping (alive that tick) — never regress
        for r in (0, 2):
            assert (st["commit_bar"][:, r] >=
                    pre["commit_bar"][:, r]).all()

        # fault-free heal: the group must re-converge, the victim's
        # commit bar re-advancing past its pre-crash point
        state, ns, _ = run_segment(
            eng, state, ns, 200, n_prop=P, base_start=5000
        )
        fin = {k: np.asarray(v) for k, v in state.items()}
        check_agreement(fin, G, R, W)
        assert (fin["commit_bar"][:, 1] > pre["commit_bar"][:, 1]).all()
        spread = (
            fin["commit_bar"].max(axis=1) - fin["commit_bar"].min(axis=1)
        )
        assert (spread <= 4 * P).all(), fin["commit_bar"]

    def test_generated_device_reset_schedule_runs_under_scan(self):
        """A generated schedule containing device_reset events compiles
        and the whole scan survives it (masks thread through
        Engine.run_ticks via the new ``reset`` input)."""
        import jax.numpy as jnp

        G, R, W, P = 1, 3, 32, 2
        ticks = 120
        plan = FaultPlan.generate(
            21, R, ticks, classes=("device_reset", "partition"),
        )
        assert any(e.kind == "device_reset" for e in plan.events)
        masks = plan.compile_device(G)
        cfg = ReplicaConfigMultiPaxos(max_proposals_per_tick=P)
        eng = Engine(make_protocol("multipaxos", G, R, W, cfg), seed=7)
        state, ns = eng.init()
        t = jnp.arange(ticks, dtype=jnp.int32)
        seq = {
            "n_proposals": jnp.full((ticks, G), P, jnp.int32),
            "value_base": jnp.broadcast_to((t * P)[:, None], (ticks, G)),
            "alive": jnp.asarray(masks["alive"]),
            "link_up": jnp.asarray(masks["link_up"]),
            "reset": jnp.asarray(masks["reset"]),
        }
        state, ns, _ = eng.run_ticks(state, ns, seq)
        state, ns, _ = run_segment(
            eng, state, ns, 200, n_prop=P, base_start=9000
        )
        fin = {k: np.asarray(v) for k, v in state.items()}
        check_agreement(fin, G, R, W)
        assert (fin["commit_bar"].max(axis=1) > 0).all()


class TestClockSkew:
    def test_duty_cycle_matches_rate_and_is_deterministic(self):
        from summerset_tpu.core.netmodel import ControlInputs

        for rate in (0.3, 0.5, 0.75, 1.0):
            a = np.asarray(ControlInputs.skew_alive(2, 3, 200, {1: rate}))
            b = np.asarray(ControlInputs.skew_alive(2, 3, 200, {1: rate}))
            assert (a == b).all()
            # victim steps at ~rate; everyone else every tick
            frac = a[:, 0, 1].mean()
            assert abs(frac - rate) < 0.02, (rate, frac)
            assert a[:, :, [0, 2]].all()
        # offset phases continuously: [lo, hi) window == slice of full
        full = np.asarray(ControlInputs.skew_alive(1, 3, 100, {2: 0.4}))
        win = np.asarray(
            ControlInputs.skew_alive(1, 3, 30, {2: 0.4}, offset=50)
        )
        assert (win == full[50:80]).all()

    def test_compile_device_lowers_skew(self):
        from summerset_tpu.host.nemesis import FaultEvent

        p = FaultPlan(
            seed=0, population=3, ticks=40,
            events=(FaultEvent(10, "clock_skew", (1,), 20, 0.5),),
        )
        m = p.compile_device(2)
        alive = np.asarray(m["alive"])
        assert alive[:10].all() and alive[30:].all()  # healthy outside
        frac = alive[10:30, :, 1].mean()
        assert 0.4 <= frac <= 0.6, frac
        assert alive[10:30, :, [0, 2]].all()  # only the victim skews

    def test_host_actions_emit_skew_and_heal(self):
        from summerset_tpu.host.nemesis import FaultEvent

        p = FaultPlan(
            seed=0, population=3, ticks=40,
            events=(FaultEvent(5, "clock_skew", (2,), 10, 0.4),),
        )
        acts = [a for a in p.host_actions() if a[1] == "skew"]
        assert len(acts) == 2
        (t0, _, _, s0), (t1, _, _, s1) = acts
        assert (t0, t1) == (5, 15)
        assert s0 == {"servers": [2], "factor": 2.5}
        assert s1["factor"] is None  # heal restores the tick clock

    def test_generated_plans_include_skew_deterministically(self):
        a = FaultPlan.generate(11, 5, 300, classes=("clock_skew",))
        b = FaultPlan.generate(11, 5, 300, classes=("clock_skew",))
        assert a.timeline() == b.timeline()
        assert all(e.kind == "clock_skew" for e in a.events)
        assert all(0.3 <= e.arg <= 0.8 for e in a.events)


class TestHostActions:
    def test_duration_events_emit_heals(self):
        p = FaultPlan.generate(3, 5, 200, classes=ALL_CLASSES)
        acts = p.host_actions()
        ticks = [a[0] for a in acts]
        assert ticks == sorted(ticks)
        n_net = sum(1 for a in acts if a[1] == "net")
        n_clear = sum(1 for a in acts if a[1] == "net_clear")
        assert n_net == n_clear  # every message fault heals
        n_pause = sum(1 for a in acts if a[1] == "pause")
        n_resume = sum(1 for a in acts if a[1] == "resume")
        assert n_pause == n_resume
        for ev in p.events:
            if ev.kind == "crash":
                assert any(
                    a[1] == "reset" and a[3]["servers"] == list(ev.targets)
                    for a in acts
                )

    def test_partition_spec_cuts_both_directions_at_one_side(self):
        from summerset_tpu.host.nemesis import FaultEvent

        p = FaultPlan(
            seed=0, population=3, ticks=20,
            events=(FaultEvent(2, "partition", (0,), 5),),
        )
        acts = p.host_actions()
        net = next(a for a in acts if a[1] == "net")
        spec = net[3]["per"][0]
        assert sorted(spec["mute"]) == [1, 2]
        assert sorted(spec["deaf"]) == [1, 2]
        clear = next(a for a in acts if a[1] == "net_clear")
        assert clear[0] == 7 and clear[3]["servers"] == [0]


class TestFrameFaults:
    def test_mute_and_deaf(self):
        f = safetcp.FrameFaults({"mute": [1], "deaf": [2]}, seed=0)
        assert f.egress(1) == "drop"
        assert f.egress(2) == "send"
        assert f.ingress_drop(2) and not f.ingress_drop(1)

    def test_verdict_sequence_deterministic(self):
        spec = {"drop": {"*": 0.3}, "dup": {"2": 0.2}}
        a = safetcp.FrameFaults(spec, seed=42)
        b = safetcp.FrameFaults(spec, seed=42)
        seq_a = [a.egress(p % 3) for p in range(200)]
        seq_b = [b.egress(p % 3) for p in range(200)]
        assert seq_a == seq_b
        assert "drop" in seq_a and "send" in seq_a

    def test_rates_roughly_respected(self):
        f = safetcp.FrameFaults({"drop": {"*": 0.5}}, seed=7)
        drops = sum(f.egress(0) == "drop" for _ in range(2000))
        assert 800 < drops < 1200

    def test_delay_lookup(self):
        f = safetcp.FrameFaults({"delay": {"1": 0.05}}, seed=0)
        assert f.ingress_delay(1) == 0.05
        assert f.ingress_delay(0) == 0.0


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class TestTransportFaults:
    @pytest.fixture()
    def hub_pair(self):
        from summerset_tpu.host.transport import TransportHub

        a0 = ("127.0.0.1", _free_port())
        a1 = ("127.0.0.1", _free_port())
        h0 = TransportHub(0, 2, a0)
        h1 = TransportHub(1, 2, a1)
        h1.connect_to_peer(0, a0)
        h0.wait_for_group(timeout=10)
        h1.wait_for_group(timeout=10)
        yield h0, h1
        h0.close()
        h1.close()

    def _recv_frames(self, hub, timeout=1.0):
        got = hub.recv_tick(0, time.monotonic() + timeout)
        return got[1 if hub.me == 0 else 0]

    def test_mute_drops_egress(self, hub_pair):
        h0, h1 = hub_pair
        h1.set_faults({"mute": [0]})
        h1.send_tick(0, {0: {"x": 1}})
        assert self._recv_frames(h0, timeout=0.4) is None
        h1.set_faults(None)
        h1.send_tick(1, {0: {"x": 2}})
        frames = self._recv_frames(h0)
        assert frames and frames[-1] == {"x": 2}

    def test_dup_duplicates_frames(self, hub_pair):
        h0, h1 = hub_pair
        h1.set_faults({"dup": {"*": 1.0}})
        h1.send_tick(0, {0: {"x": 3}})
        time.sleep(0.3)
        frames = self._recv_frames(h0)
        assert frames == [{"x": 3}, {"x": 3}]

    def test_deaf_drops_ingress(self, hub_pair):
        h0, h1 = hub_pair
        h0.set_faults({"deaf": [1]})
        h1.send_tick(0, {0: {"x": 4}})
        assert self._recv_frames(h0, timeout=0.4) is None
        h0.set_faults(None)
        h1.send_tick(1, {0: {"x": 5}})
        frames = self._recv_frames(h0)
        assert frames and frames[-1] == {"x": 5}

    def test_delay_defers_delivery(self, hub_pair):
        h0, h1 = hub_pair
        h0.set_faults({"delay": {"*": 0.3}})
        t0 = time.monotonic()
        h1.send_tick(0, {0: {"x": 6}})
        frames = self._recv_frames(h0, timeout=2.0)
        elapsed = time.monotonic() - t0
        assert frames and frames[-1] == {"x": 6}
        assert elapsed >= 0.25, elapsed


class TestWalFaults:
    def test_fsync_fail_surfaces_error(self, tmp_path):
        hub = StorageHub(str(tmp_path / "f.wal"), prefer_native=False)
        hub.do_sync_action(LogAction("append", entry="a", sync=False))
        hub.set_faults({"fsync_fail": 1})
        res = hub.do_sync_action(LogAction("sync"))
        assert not res.offset_ok and isinstance(res.entry, OSError)
        # the armed count is consumed: the next sync succeeds
        assert hub.do_sync_action(LogAction("sync")).offset_ok
        hub.stop()

    def test_torn_append_goes_sticky_dead(self, tmp_path):
        path = str(tmp_path / "t.wal")
        hub = StorageHub(path, prefer_native=False)
        good = hub.do_sync_action(
            LogAction("append", entry="good", sync=True)
        )
        hub.set_faults({"torn": 1})
        res = hub.do_sync_action(LogAction("append", entry="torn-victim"))
        assert not res.offset_ok
        # the device is dead: every later action fails too (the replica's
        # group-commit fsync raises -> it crashes before acks leave)
        assert not hub.do_sync_action(LogAction("sync")).offset_ok
        assert not hub.do_sync_action(
            LogAction("append", entry="x")
        ).offset_ok
        hub.stop()
        # on-disk: the good record plus a partial tail
        size = os.path.getsize(path)
        assert size > good.end_offset


@pytest.mark.slow
class TestDevicePlaneSoak:
    def test_multipaxos_survives_compiled_schedule(self):
        """The whole seeded schedule runs inside one lax.scan; after the
        heal tail the group must have converged with agreement and made
        commit progress — the device-plane half of the soak contract."""
        import jax.numpy as jnp

        G, R, W, P = 2, 3, 32, 2
        ticks = 160
        plan = FaultPlan.generate(
            11, R, ticks,
            classes=("crash", "device_reset", "pause", "partition",
                     "isolate", "one_way", "drop"),
        )
        masks = plan.compile_device(G)
        cfg = ReplicaConfigMultiPaxos(max_proposals_per_tick=P)
        eng = Engine(make_protocol("multipaxos", G, R, W, cfg), seed=5)
        state, ns = eng.init()
        t = jnp.arange(ticks, dtype=jnp.int32)
        seq = {
            "n_proposals": jnp.full((ticks, G), P, jnp.int32),
            "value_base": jnp.broadcast_to(
                (t * P)[:, None], (ticks, G)
            ),
            "alive": jnp.asarray(masks["alive"]),
            "link_up": jnp.asarray(masks["link_up"]),
            "reset": jnp.asarray(masks["reset"]),
        }
        state, ns, _ = eng.run_ticks(state, ns, seq)
        st = {k: np.asarray(v) for k, v in state.items()}
        check_agreement(st, G, R, W)
        assert (st["commit_bar"].max(axis=1) > 0).all()
        # extended fault-free heal: everyone must converge (a replica
        # frozen past the window catches up via backfill/jump)
        state, ns, _ = run_segment(
            eng, state, ns, 200, n_prop=P, base_start=1000
        )
        fin = {k: np.asarray(v) for k, v in state.items()}
        check_agreement(fin, G, R, W)
        spread = (
            fin["commit_bar"].max(axis=1) - fin["commit_bar"].min(axis=1)
        )
        assert (spread <= 4 * P).all(), fin["commit_bar"]
        assert (
            fin["commit_bar"].max(axis=1) > st["commit_bar"].max(axis=1)
        ).all()


@pytest.mark.slow
class TestLiveNemesisSoak:
    def test_single_seed_multipaxos(self, tmp_path):
        """One live soak seed (the tier-2c matrix runs 3 seeds x 3
        protocols): schedule through the manager control plane, recorded
        history linearizable, bounded recovery after the final heal."""
        from test_cluster import Cluster

        from summerset_tpu.client.drivers import DriverClosedLoop
        from summerset_tpu.client.endpoint import GenericEndpoint
        from summerset_tpu.client.tester import start_recorded_clients
        from summerset_tpu.host.nemesis import NemesisRunner
        from summerset_tpu.utils.linearize import check_history

        plan = FaultPlan.generate(
            1, 3, 48,
            classes=("crash", "device_reset", "partition", "pause",
                     "drop", "wal_torn", "take_snapshot"),
        )
        cluster = Cluster("MultiPaxos", 3, str(tmp_path))
        stop = threading.Event()
        ops: list = []
        threads: list = []
        try:
            wep = GenericEndpoint(cluster.manager_addr)
            wep.connect()
            DriverClosedLoop(wep, timeout=10.0).checked_put("warm", "1")
            wep.leave()
            threads = start_recorded_clients(
                cluster.manager_addr, 3, ["nk0", "nk1"], stop, ops,
                seed=1,
            )
            runner = NemesisRunner(
                cluster.manager_addr, plan, tick_len=0.2
            )
            runner.play()
            runner.heal_all()
            # bounded recovery: a checked write within the tick budget
            rep = GenericEndpoint(cluster.manager_addr)
            rep.connect()
            drv = DriverClosedLoop(rep, timeout=5.0)
            t_heal = time.monotonic()
            drv.checked_put("nem_rec", "ok", retries=10)
            assert time.monotonic() - t_heal < 20.0
            rep.leave()
            runner.close()
            deadline = time.monotonic() + 20
            while len(ops) <= 20 and time.monotonic() < deadline:
                time.sleep(0.5)
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=30)
            cluster.stop()
        assert len(ops) > 20, f"history too small: {len(ops)}"
        ok, diag = check_history(ops)
        assert ok, diag
