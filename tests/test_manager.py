"""Manager orchestration unit tests (VERDICT r3 weak #5: the serialized
reset logic had no coverage outside the full cluster suite).

Parity model: reference ``src/manager/clusman.rs:382-438`` reset
orchestration and ``reigner.rs``/``reactor.rs`` hub tests, which exercise
the control flows against in-process fakes.
"""

import asyncio

import pytest

from summerset_tpu.host.messages import CtrlRequest
from summerset_tpu.manager.clusman import ClusterManager, _ServerConn


class FakeWriter:
    def __init__(self):
        self.closed = False
        self.frames = []

    def write(self, b):
        self.frames.append(b)

    async def drain(self):
        pass

    def close(self):
        self.closed = True

    def is_closing(self):
        return self.closed


def make_manager(n=3):
    man = ClusterManager(
        "MultiPaxos", ("127.0.0.1", 0), ("127.0.0.1", 0), n
    )
    man.ack_timeout = 0.5
    man.rejoin_timeout = 2.0
    man.settle_delay = 0.01
    return man


def add_server(man, sid):
    conn = _ServerConn(sid, None, FakeWriter())
    conn.joined = True
    conn.api_addr = ("127.0.0.1", 7000 + sid)
    conn.p2p_addr = ("127.0.0.1", 8000 + sid)
    man.servers[sid] = conn
    return conn


async def _ack_and_rejoin(man, sid, old_conn, delay=0.02):
    """Simulate the victim: wait for its reset_state frame, ack it,
    drop, restart, rejoin."""
    deadline = asyncio.get_event_loop().time() + 5.0
    while not old_conn.writer.frames:
        if asyncio.get_event_loop().time() > deadline:
            return
        await asyncio.sleep(0.005)
    await asyncio.sleep(delay)
    for q in man._pending_replies.get("reset_reply", ()):
        q.put_nowait((sid, {}))  # (sid, reply payload) — clusman protocol
    await asyncio.sleep(delay)
    if man.servers.get(sid) is old_conn:
        del man.servers[sid]
    add_server(man, sid)
    man._join_event.set()


class TestResetServers:
    def test_serialized_reset_success(self):
        async def run():
            man = make_manager()
            conns = {sid: add_server(man, sid) for sid in range(3)}
            for sid in range(3):
                asyncio.get_event_loop().call_soon(
                    asyncio.ensure_future,
                    _ack_and_rejoin(man, sid, conns[sid],
                                    delay=0.02 + 0.05 * sid),
                )
            rep = await man._reset_servers(
                CtrlRequest("reset_servers", servers=None)
            )
            assert sorted(rep.done) == [0, 1, 2]
            # every victim got exactly one reset_state frame
            for sid, conn in conns.items():
                assert len(conn.writer.frames) == 1

        asyncio.run(run())

    def test_ack_timeout_still_frees_id(self):
        """ADVICE r3 (medium): a victim that never acks must still have
        its id freed so the restarting process can reclaim it."""
        async def run():
            man = make_manager()
            conn = add_server(man, 0)
            add_server(man, 1)

            async def silent_rejoin():
                # acks nothing; rejoins during the short grace window
                await asyncio.sleep(0.7)
                if man.servers.get(0) is conn:
                    del man.servers[0]
                add_server(man, 0)
                man._join_event.set()

            asyncio.ensure_future(silent_rejoin())
            rep = await man._reset_servers(
                CtrlRequest("reset_servers", servers=[0])
            )
            # id was freed (the rejoin replaced the conn) ...
            assert man.servers[0] is not conn
            # ... but an un-acked victim is NOT reported as reset
            assert rep.done == []

        asyncio.run(run())

    def test_unacked_victim_waits_only_short_rejoin_window(self):
        """The un-acked branch (clusman.py:281): a victim whose control
        connection died after (maybe) receiving reset_state gets only
        rejoin_timeout/8 to come back, not the full budget — a genuinely
        dead server must not stall the serialized reset queue."""
        async def run():
            man = make_manager()
            add_server(man, 0)  # never acks, never rejoins
            t0 = asyncio.get_event_loop().time()
            rep = await man._reset_servers(
                CtrlRequest("reset_servers", servers=[0])
            )
            elapsed = asyncio.get_event_loop().time() - t0
            assert rep.done == []
            # ack_timeout (0.5) + short window (2.0/8) + settle, well
            # under the acked-victim budget (0.5 + 2.0 + settle)
            assert elapsed < man.ack_timeout + man.rejoin_timeout / 2, (
                elapsed
            )
            # the id was freed regardless, so a late restart can reclaim
            assert 0 not in man.servers

        asyncio.run(run())

    def test_concurrent_restart_id_reclamation_stays_serialized(self):
        """Concurrent-restart reclamation (the ISSUE.md:281 gap): victim
        0's connection dies without an ack but its restart reclaims the
        freed id inside the short window; victim 1 acks and rejoins
        normally.  The serialized loop must finish 0 (unreported), then
        still reset 1 — ids never collide and the late queue never
        wedges."""
        async def run():
            man = make_manager()
            conn0 = add_server(man, 0)
            conn1 = add_server(man, 1)
            add_server(man, 2)

            async def silent_restart_0():
                # conn dies (no ack); the restarted process reclaims id 0
                # during the short rejoin window
                await asyncio.sleep(man.ack_timeout + 0.05)
                conn0.writer.close()
                if man.servers.get(0) is conn0:
                    del man.servers[0]
                add_server(man, 0)
                man._join_event.set()

            asyncio.ensure_future(silent_restart_0())
            asyncio.ensure_future(
                _ack_and_rejoin(man, 1, conn1, delay=0.02)
            )
            rep = await man._reset_servers(
                CtrlRequest("reset_servers", servers=[0, 1])
            )
            # only the acked+rejoined victim is reported done ...
            assert rep.done == [1]
            # ... but both slots hold fresh connections under their ids
            assert man.servers[0] is not conn0
            assert man.servers[1] is not conn1
            assert not man.servers[0].writer.is_closing()
            assert not man.servers[1].writer.is_closing()

        asyncio.run(run())

    def test_never_rejoined_not_reported_done(self):
        """ADVICE r3 (low): a victim that acks but never rejoins must not
        be reported as successfully reset."""
        async def run():
            man = make_manager()
            conn = add_server(man, 0)

            async def ack_only():
                await asyncio.sleep(0.05)
                for q in man._pending_replies.get("reset_reply", ()):
                    q.put_nowait((0, {}))

            asyncio.ensure_future(ack_only())
            rep = await man._reset_servers(
                CtrlRequest("reset_servers", servers=[0])
            )
            assert rep.done == []
            assert 0 not in man.servers or man.servers[0] is not conn

        asyncio.run(run())


class TestFanout:
    def test_concurrent_waiters_both_see_acks(self):
        """The pending-reply registry is multi-waiter: two concurrent
        control clients must not steal each other's acks (r3 weak: the
        single-slot dict raced)."""
        async def run():
            man = make_manager()
            add_server(man, 0)
            add_server(man, 1)

            async def acks():
                await asyncio.sleep(0.05)
                for q in man._pending_replies.get("pause_reply", ()):
                    q.put_nowait((0, {}))
                    q.put_nowait((1, {}))

            asyncio.ensure_future(acks())
            r1, r2 = await asyncio.gather(
                man._fanout_wait(
                    "pause", "pause_reply",
                    CtrlRequest("pause_servers", servers=[0, 1]),
                ),
                man._fanout_wait(
                    "pause", "pause_reply",
                    CtrlRequest("pause_servers", servers=[0, 1]),
                ),
            )
            assert sorted(r1.done) == [0, 1]
            assert sorted(r2.done) == [0, 1]

        asyncio.run(run())


class TestLeaderStaleness:
    def test_lost_leader_cleared_after_grace(self):
        async def run():
            man = make_manager()
            man.leader = 2
            man._leader_lost = 2
            man._leader_timer.kickoff(0.05)
            await asyncio.sleep(0.15)
            assert man.leader is None

        asyncio.run(run())

    def test_step_up_cancels_staleness(self):
        async def run():
            man = make_manager()
            man.leader = 2
            man._leader_lost = 2
            man._leader_timer.kickoff(0.05)
            # a successor steps up before the grace expires
            man.leader = 1
            man._leader_timer.cancel()
            man._leader_lost = None
            await asyncio.sleep(0.15)
            assert man.leader == 1

        asyncio.run(run())


def _decode_frames(writer: FakeWriter):
    """Each FakeWriter.write() call carries exactly one encoded frame
    (safetcp.send_msg writes encode_frame(obj) in one call)."""
    import pickle

    from summerset_tpu.utils.safetcp import _LEN

    return [pickle.loads(f[_LEN.size:]) for f in writer.frames]


class TestConfReannounce:
    """ConfChange re-announce total order (_conf_seq): a server that
    joins AFTER a ConfChange was relayed must still observe it — a
    crash-restarted replica rejoining mid-soak would otherwise run at a
    stale conf forever (newest-seq-wins makes the replay idempotent)."""

    def test_late_joiner_receives_last_relayed_conf(self):
        from summerset_tpu.host.messages import CtrlMsg

        async def run():
            man = make_manager()
            relayer = add_server(man, 0)
            add_server(man, 1)
            # two racing relays: the LAST assigned seq must win the
            # catch-up replay, not the first
            await man._handle_ctrl(relayer, CtrlMsg(
                "conf_forward", {"delta": {"responders": [0]}}))
            await man._handle_ctrl(relayer, CtrlMsg(
                "conf_forward", {"delta": {"responders": [0, 1, 2]}}))
            assert man._conf_seq == 2

            # a server joining after the relays (e.g. a restarted
            # replica reclaiming its id) announces itself...
            conn = add_server(man, 2)
            conn.joined = False
            await man._handle_ctrl(conn, CtrlMsg(
                "new_server_join",
                {"api_addr": ("127.0.0.1", 7002),
                 "p2p_addr": ("127.0.0.1", 8002)},
            ))
            msgs = _decode_frames(conn.writer)
            kinds = [m.kind for m in msgs]
            assert "connect_to_peers" in kinds
            installs = [m for m in msgs if m.kind == "install_conf"]
            assert len(installs) == 1
            assert installs[0].payload["seq"] == 2
            assert installs[0].payload["delta"] == {
                "responders": [0, 1, 2]
            }

        asyncio.run(run())

    def test_range_seq_reseeds_monotone_across_restart(self):
        """Regression (REVIEW r16): _range_seq restarting at 0 reused
        rc_ids that surviving servers already hold in their adopted
        idempotency sets (the seal was silently skipped yet acked) and
        regressed re-announce seqs below their newest-seq-seen
        watermarks — resharding silently stopped converging after a
        manager restart.  The wall-clock seed keeps both monotone."""
        import time as _time

        man_a = make_manager()
        base = man_a._range_seq
        assert base > 0
        man_a._range_seq += 3  # three RangeChanges minted this lifetime
        _time.sleep(0.01)
        man_b = make_manager()  # the restarted manager
        assert man_b._range_seq > man_a._range_seq
        assert man_b._range_seq > base + 3

    def test_joiner_before_any_conf_gets_no_install(self):
        from summerset_tpu.host.messages import CtrlMsg

        async def run():
            man = make_manager()
            conn = add_server(man, 0)
            conn.joined = False
            await man._handle_ctrl(conn, CtrlMsg(
                "new_server_join",
                {"api_addr": ("127.0.0.1", 7000),
                 "p2p_addr": ("127.0.0.1", 8000)},
            ))
            kinds = [m.kind for m in _decode_frames(conn.writer)]
            assert "install_conf" not in kinds

        asyncio.run(run())


class TestRangeSealTwoPhase:
    """Two-phase cutover (REVIEW r16): the manager grants seal-complete
    (the flag _range_progress gates the adopt proposal on) only once
    EVERY member of the population acked the seal fan-out — a partial
    fan-out leaves an unreached server admitting writes to the range,
    which the adopting leader's local vote window cannot see."""

    PAYLOAD = {"op": "split", "start": "k", "end": "k\x00",
               "dst_group": 1}

    @staticmethod
    async def _ack_range(man, sids, delay=0.05):
        await asyncio.sleep(delay)
        for q in man._pending_replies.get("range_reply", ()):
            for sid in sids:
                q.put_nowait((sid, {}))

    def test_partial_fanout_withholds_seal_complete(self):
        async def run():
            man = make_manager(3)
            add_server(man, 0)
            add_server(man, 1)  # server 2 is down
            asyncio.ensure_future(self._ack_range(man, (0, 1)))
            rep = await man._handle_request(
                CtrlRequest("range_change", payload=dict(self.PAYLOAD))
            )
            rc_id = (rep.conf or {}).get("rc_id")
            assert rc_id in man._ranges_pending
            # sealed everywhere reachable, but NOT cluster-wide: held
            assert not man._ranges_pending[rc_id].get("sealed_ok")

            # the downed server rejoins: the retry fan-out re-drives the
            # seal and, on a full-population ack, grants the flag and
            # re-announces it to every server
            add_server(man, 2)
            asyncio.ensure_future(self._ack_range(man, (0, 1, 2)))
            seq_before = man._range_seq
            await man._retry_pending_seals()
            assert man._ranges_pending[rc_id].get("sealed_ok") is True
            assert man._range_seq == seq_before + 1
            for sid in (0, 1, 2):
                msgs = _decode_frames(man.servers[sid].writer)
                anns = [m for m in msgs if m.kind == "install_ranges"]
                assert anns, f"server {sid} never got the re-announce"
                pend = anns[-1].payload["pending"]
                assert len(pend) == 1 and pend[0]["rc_id"] == rc_id
                assert pend[0]["sealed_ok"] is True

        asyncio.run(run())

    def test_full_fanout_grants_seal_complete_inline(self):
        async def run():
            man = make_manager(3)
            for sid in range(3):
                add_server(man, sid)
            asyncio.ensure_future(self._ack_range(man, (0, 1, 2)))
            rep = await man._handle_request(
                CtrlRequest("range_change", payload=dict(self.PAYLOAD))
            )
            rc_id = (rep.conf or {}).get("rc_id")
            assert man._ranges_pending[rc_id].get("sealed_ok") is True
            # retry is a no-op once granted
            seq = man._range_seq
            await man._retry_pending_seals()
            assert man._range_seq == seq

        asyncio.run(run())


class TestSealTtlExpiry:
    """Seal-TTL escape hatch (PR 17): a sealed range whose destination
    stays leaderless past seal_ttl_ticks is rolled back via a server's
    range_expire request — but ONLY while no adopt intent was granted.
    Grant and expiry both resolve on the manager's single event loop,
    so adopt-vs-expire can never both win."""

    CH = {"rc_id": 9, "op": "split", "start": "k", "end": "k\x00",
          "dst_group": 1, "sealed_ok": True}

    @staticmethod
    def _msg(kind, **payload):
        from summerset_tpu.host.messages import CtrlMsg

        return CtrlMsg(kind, payload)

    def test_expire_before_grant_rolls_back_and_announces(self):
        async def run():
            man = make_manager(3)
            conns = {sid: add_server(man, sid) for sid in range(3)}
            man._ranges_pending[9] = dict(self.CH)
            await man._handle_ctrl(
                conns[0], self._msg("range_expire", rc_id=9)
            )
            assert 9 not in man._ranges_pending
            assert 9 in man._ranges_expired
            for sid in range(3):
                anns = [m for m in _decode_frames(conns[sid].writer)
                        if m.kind == "install_ranges"]
                assert anns and anns[-1].payload["expired"] == [9]
                assert anns[-1].payload["pending"] == []
            # a duplicate expire report is a no-op
            seq = man._range_seq
            await man._handle_ctrl(
                conns[1], self._msg("range_expire", rc_id=9)
            )
            assert man._range_seq == seq

        asyncio.run(run())

    def test_granted_intent_pins_change_against_expiry(self):
        async def run():
            man = make_manager(3)
            conns = {sid: add_server(man, sid) for sid in range(3)}
            man._ranges_pending[9] = dict(self.CH)
            await man._handle_ctrl(
                conns[1], self._msg("adopt_intent", rc_id=9)
            )
            dec = [m for m in _decode_frames(conns[1].writer)
                   if m.kind == "adopt_decision"]
            assert dec and dec[-1].payload == {"rc_id": 9, "ok": True}
            # a straggling expire report is now refused
            await man._handle_ctrl(
                conns[0], self._msg("range_expire", rc_id=9)
            )
            assert 9 in man._ranges_pending
            assert 9 not in man._ranges_expired
            # a new destination leader re-asking is granted again
            await man._handle_ctrl(
                conns[2], self._msg("adopt_intent", rc_id=9)
            )
            dec2 = [m for m in _decode_frames(conns[2].writer)
                    if m.kind == "adopt_decision"]
            assert dec2 and dec2[-1].payload["ok"] is True

        asyncio.run(run())

    def test_intent_on_expired_or_unsealed_change_is_refused(self):
        async def run():
            man = make_manager(3)
            conns = {sid: add_server(man, sid) for sid in range(3)}
            # expired change: refuse (the server rolls its seal back)
            man._ranges_expired[9] = dict(self.CH)
            await man._handle_ctrl(
                conns[0], self._msg("adopt_intent", rc_id=9)
            )
            dec = [m for m in _decode_frames(conns[0].writer)
                   if m.kind == "adopt_decision"]
            assert dec and dec[-1].payload == {"rc_id": 9, "ok": False}
            # pending but NOT seal-confirmed: refuse (the two-phase
            # barrier has not cleared cluster-wide)
            man._ranges_pending[11] = dict(self.CH, rc_id=11,
                                           sealed_ok=False)
            await man._handle_ctrl(
                conns[1], self._msg("adopt_intent", rc_id=11)
            )
            dec2 = [m for m in _decode_frames(conns[1].writer)
                    if m.kind == "adopt_decision"]
            assert dec2 and dec2[-1].payload["ok"] is False
            assert 11 not in man._adopt_granted

        asyncio.run(run())

    def test_rejoiner_learns_expired_set(self):
        from summerset_tpu.host.messages import CtrlMsg

        async def run():
            man = make_manager(3)
            man._ranges_expired[9] = dict(self.CH)
            conn = add_server(man, 0)
            conn.joined = False
            await man._handle_ctrl(conn, CtrlMsg(
                "new_server_join",
                {"api_addr": ("127.0.0.1", 7000),
                 "p2p_addr": ("127.0.0.1", 8000)},
            ))
            anns = [m for m in _decode_frames(conn.writer)
                    if m.kind == "install_ranges"]
            # a rejoiner whose WAL replays the seal must still unseal:
            # the expired set alone forces the re-announce
            assert anns and anns[-1].payload["expired"] == [9]

        asyncio.run(run())
