"""Vectorized Crossword kernel tests: the quorum-size vs. shards-per-replica
commit tradeoff, adaptive assignment widening under peer stall, gossip-based
follower catch-up, and shard-aware failover (reference behaviors:
``crossword/messages.rs:15-62,481-560``, ``adaptive.rs:274+``,
``gossiping.rs:14-193``).
"""

import jax.numpy as jnp
import numpy as np

from smr_helpers import check_agreement, committed_values, run_segment
from summerset_tpu.core import Engine, NetConfig
from summerset_tpu.protocols import make_protocol
from summerset_tpu.protocols.crossword import ReplicaConfigCrossword
import pytest


def make_kernel(G, R, W, P, **kw):
    cfg = ReplicaConfigCrossword(max_proposals_per_tick=P, **kw)
    return make_protocol("crossword", G, R, W, cfg)


def np_state(state):
    return {k: np.asarray(v) for k, v in state.items()}


class TestSteadyState:
    def test_commit_flow_and_values(self):
        G, R, W, P = 4, 5, 32, 4
        k = make_kernel(G, R, W, P, fault_tolerance=1)
        eng = Engine(k)
        state, ns = eng.init()
        T = 50
        state, ns, _ = run_segment(eng, state, ns, T, n_prop=P)
        st = np_state(state)
        assert (st["commit_bar"][:, 0] >= (T - 6) * P).all(), st["commit_bar"]
        for g in range(G):
            vals = committed_values(st, g, 0, W)
            assert vals
            for slot, v in vals.items():
                assert v == slot
        check_agreement(st, G, R, W)

    def test_diagonal_assignment_needs_rspaxos_quorum(self):
        # spr = 1 (diagonal), f = 1, R = 5, d = 3: per-slot commit need is
        # max(3, 1+1+(3-1)) = 4 acks — same threshold as RSPaxos; with only
        # 3 alive the leader must stall commits
        G, R, W, P = 2, 5, 32, 4
        k = make_kernel(
            G, R, W, P, fault_tolerance=1, assignment_adaptive=False
        )
        eng = Engine(k)
        state, ns = eng.init()
        state, ns, _ = run_segment(eng, state, ns, 20, n_prop=P)
        pre = np.asarray(state["commit_bar"]).copy()

        alive = (
            jnp.ones((G, R), jnp.bool_).at[:, 3].set(False).at[:, 4].set(False)
        )
        state, ns, _ = run_segment(
            eng, state, ns, 80, n_prop=P, alive=alive, base_start=1000
        )
        mid = np_state(state)
        assert (mid["commit_bar"][:, 0] <= pre[:, 0] + 4 * P).all()
        check_agreement(mid, G, R, W)

    def test_full_copy_commits_at_majority(self):
        # spr = d = 3: full-copy assignment commits at plain majority (3 of
        # 5) even with 2 replicas down — the MultiPaxos end of the tradeoff
        G, R, W, P = 2, 5, 32, 4
        k = make_kernel(
            G, R, W, P, fault_tolerance=1, init_spr=3,
            assignment_adaptive=False,
        )
        eng = Engine(k)
        state, ns = eng.init()
        state, ns, _ = run_segment(eng, state, ns, 20, n_prop=P)
        pre = np.asarray(state["commit_bar"]).copy()

        alive = (
            jnp.ones((G, R), jnp.bool_).at[:, 3].set(False).at[:, 4].set(False)
        )
        state, ns, _ = run_segment(
            eng, state, ns, 60, n_prop=P, alive=alive, base_start=1000
        )
        mid = np_state(state)
        assert (mid["commit_bar"][:, 0] > pre[:, 0] + 2 * P).all(), (
            pre[:, 0],
            mid["commit_bar"][:, 0],
        )
        check_agreement(mid, G, R, W)


class TestAdaptive:
    @pytest.mark.slow
    def test_widens_on_peer_stall_and_recovers(self):
        # adaptive: with all peers live the leader uses the bandwidth-optimal
        # diagonal (spr=1); after 2 peers stall it widens to spr=2 — the
        # minimal width whose coverage bound (3-1-1)*1 + 2 = 3 >= d holds
        # with only 3 ack frontiers.  Pre-stall narrow slots keep their
        # fixed assignment (reference: per-instance assignment is set at
        # propose time), so the ordered commit frontier wedges behind them
        # until peers heal; then everything drains at the narrow width again
        G, R, W, P = 2, 5, 64, 4
        k = make_kernel(G, R, W, P, fault_tolerance=1, lag_threshold=6)
        eng = Engine(k)
        state, ns = eng.init()
        state, ns, _ = run_segment(eng, state, ns, 30, n_prop=P)
        st = np_state(state)
        assert (st["cur_spr"][:, 0] == 1).all(), st["cur_spr"]
        pre_cb = st["commit_bar"][:, 0].copy()

        alive = (
            jnp.ones((G, R), jnp.bool_).at[:, 3].set(False).at[:, 4].set(False)
        )
        state, ns, _ = run_segment(
            eng, state, ns, 120, n_prop=P, alive=alive, base_start=1000
        )
        mid = np_state(state)
        assert (mid["cur_spr"][:, 0] == 2).all(), mid["cur_spr"]
        # pre-stall narrow slots wedge the ordered frontier (bounded creep
        # from in-flight acks only)
        assert (mid["commit_bar"][:, 0] <= pre_cb + 6 * P).all(), (
            pre_cb,
            mid["commit_bar"][:, 0],
        )
        check_agreement(mid, G, R, W)

        # heal -> narrows back to diagonal and the backlog drains
        state, ns, _ = run_segment(
            eng, state, ns, 120, n_prop=P, base_start=2000
        )
        fin = np_state(state)
        assert (fin["cur_spr"][:, 0] == 1).all(), fin["cur_spr"]
        assert (fin["commit_bar"][:, 0] > mid["commit_bar"][:, 0] + 20 * P
                ).all(), (mid["commit_bar"][:, 0], fin["commit_bar"][:, 0])
        check_agreement(fin, G, R, W)

    def test_host_override_input(self):
        # the host perf-model plane may force a width per group
        G, R, W, P = 2, 5, 32, 2
        k = make_kernel(G, R, W, P, fault_tolerance=1)
        eng = Engine(k)
        state, ns = eng.init()
        T = 20
        t = jnp.arange(T, dtype=jnp.int32)
        seq = {
            "n_proposals": jnp.full((T, G), P, jnp.int32),
            "value_base": jnp.broadcast_to((t * P)[:, None], (T, G)),
            "spr_override": jnp.full((T, G), 2, jnp.int32),
        }
        state, ns, _ = eng.run_ticks(state, ns, seq)
        st = np_state(state)
        assert (st["cur_spr"][:, 0] == 2).all(), st["cur_spr"]
        check_agreement(st, G, R, W)


class TestGossip:
    def test_followers_catch_up_via_gossip(self):
        # diagonal assignment: followers hold 1 shard each and need 3 covers
        # (d - spr + 1 = 3) to rebuild; exec/full bars catch up via gossip
        G, R, W, P = 2, 5, 32, 2
        k = make_kernel(
            G, R, W, P, fault_tolerance=1, recon_interval=2,
            assignment_adaptive=False,
        )
        eng = Engine(k)
        state, ns = eng.init()
        state, ns, _ = run_segment(eng, state, ns, 40, n_prop=P)
        state, ns, _ = run_segment(eng, state, ns, 30, n_prop=0)
        st = np_state(state)
        assert (st["commit_bar"][:, 0] > 0).all()
        cb = st["commit_bar"].max(axis=1, keepdims=True)
        assert (st["full_bar"] >= cb).all(), (st["full_bar"], cb)
        assert (st["exec_bar"] >= cb).all()

    def test_gossip_tail_ignores(self):
        # with a tail margin, gossip stops short of the commit frontier
        # while proposals keep arriving
        G, R, W, P = 2, 5, 32, 2
        tail = 8
        k = make_kernel(
            G, R, W, P, fault_tolerance=1, recon_interval=2,
            assignment_adaptive=False, gossip_tail_ignores=tail,
        )
        eng = Engine(k)
        state, ns = eng.init()
        state, ns, _ = run_segment(eng, state, ns, 60, n_prop=P)
        st = np_state(state)
        cb = st["commit_bar"][:, 0]
        # followers' full bars trail by at most the tail margin (+ inflight)
        for r in range(1, R):
            assert (st["full_bar"][:, r] >= cb - tail - 6 * P).all(), (
                st["full_bar"],
                cb,
            )


class TestFailover:
    @pytest.mark.slow
    def test_leader_crash_recovers_committed_values(self):
        G, R, W, P = 4, 5, 32, 4
        k = make_kernel(G, R, W, P, fault_tolerance=1)
        eng = Engine(k, seed=5)
        state, ns = eng.init()
        state, ns, _ = run_segment(eng, state, ns, 30, n_prop=P)
        pre = np_state(state)
        pre_committed = [committed_values(pre, g, 1, W) for g in range(G)]
        assert all(len(c) > 0 for c in pre_committed)

        alive = jnp.ones((G, R), jnp.bool_).at[:, 0].set(False)
        state, ns, _ = run_segment(
            eng, state, ns, 400, n_prop=P, alive=alive, base_start=1000
        )
        post = np_state(state)
        live_cb = post["commit_bar"][:, 1:]
        assert (
            live_cb.max(axis=1) > pre["commit_bar"][:, 1:].max(axis=1)
        ).all(), (pre["commit_bar"], post["commit_bar"])
        for g in range(G):
            live = [r for r in range(1, R) if int(post["leader"][g, r]) == r]
            for r in live:
                vals = committed_values(post, g, r, W)
                for slot, v in pre_committed[g].items():
                    if slot in vals:
                        assert vals[slot] == v, (g, r, slot, v, vals[slot])
        check_agreement(post, G, R, W)


class TestLossyNetwork:
    def test_agreement_under_drops(self):
        G, R, W, P = 2, 5, 64, 4
        cfg = ReplicaConfigCrossword(
            max_proposals_per_tick=P,
            fault_tolerance=1,
            hear_timeout_lo=40,
            hear_timeout_hi=80,
        )
        k = make_protocol("crossword", G, R, W, cfg)
        net = NetConfig(
            delay_ticks=1, jitter_ticks=2, drop_rate=0.2, max_delay_ticks=4
        )
        eng = Engine(k, netcfg=net, seed=23)
        state, ns = eng.init()
        state, ns, _ = run_segment(eng, state, ns, 400, n_prop=P)
        st = np_state(state)
        assert (st["commit_bar"].max(axis=1) > 50).all()
        check_agreement(st, G, R, W)
