"""Test config: force a hermetic 8-device virtual CPU platform.

The environment injects an axon TPU site hook (via PYTHONPATH
sitecustomize) that imports jax at interpreter startup with
JAX_PLATFORMS=axon; first use of that backend dials the TPU tunnel.  Env
vars are therefore too late here — but no *backend* has been initialized
yet when conftest loads, so flipping the jax config programmatically pins
the whole test session to 8 virtual CPU devices, immune to TPU tunnel
state.

Multi-chip sharding (mesh over group/replica axes) is exercised on the
virtual CPU mesh per the driver contract; real-TPU runs happen in bench.py.

Compile cache: kernel compiles (~8-10s each on this 1-core box) dominate
the suite; the persistent XLA cache under .jax_cache turns warm-run
compiles into ~1s loads.  The feature-mismatch E-logs it prints are
harmless (pseudo-features prefer-no-scatter/gather) and silenced via
TF_CPP_MIN_LOG_LEVEL.

Markers: ``slow`` tags long fault-scenario kernel tests; the default run
(`pytest tests/`) excludes them via addopts (see pytest.ini) to stay
inside the CI time budget — `pytest tests/ -m ""` runs everything.
"""

import os
import sys

os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

import jax

from summerset_tpu.utils.jaxcompat import set_cpu_devices

jax.config.update("jax_platforms", "cpu")
set_cpu_devices(8)  # jax>=0.5 config knob, or the XLA env flag before that
jax.config.update(
    "jax_compilation_cache_dir", os.path.join(_REPO, ".jax_cache")
)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
