"""Test config: force a hermetic 8-device virtual CPU platform.

The environment injects an axon TPU site hook (via PYTHONPATH
sitecustomize) that imports jax at interpreter startup with
JAX_PLATFORMS=axon; first use of that backend dials the TPU tunnel.  Env
vars are therefore too late here — but no *backend* has been initialized
yet when conftest loads, so flipping the jax config programmatically pins
the whole test session to 8 virtual CPU devices, immune to TPU tunnel
state.

Multi-chip sharding (mesh over group/replica axes) is exercised on the
virtual CPU mesh per the driver contract; real-TPU runs happen in bench.py.
"""

import os
import sys

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
