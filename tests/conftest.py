"""Test config: force an 8-device virtual CPU platform before jax imports.

Multi-chip sharding (mesh over group/replica axes) is exercised on a virtual
8-device CPU mesh, per the driver contract; real-TPU runs happen in bench.py.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
