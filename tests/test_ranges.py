"""Range-prover test suite: the interval transfer's decision tables +
the kernel-level fixpoint machinery.

Three layers:

1. **Decision-table units**: each interval-transfer primitive class
   (arithmetic with dtype saturation, comparisons, selects, bitwise,
   div/rem, reductions/index makers) through ``prim_intervals`` with a
   synthetic eqn — the table is pure, so no tracing is needed.
2. **Fixpoint units**: tiny kernels through ``analyze_kernel_ranges``
   pinning widening convergence on loop carries, comparison-guarded
   select refinement, and the octagon-lite pair facts.
3. **Claims**: RANGE_CLAIMS inductiveness, positive direction here (the
   violated-claim fingerprint lives in test_graftlint.py with the other
   broken fixtures).
"""

import os
import sys
from types import SimpleNamespace

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from graftlint_fixtures import GoodKernel, make_fixture  # noqa: E402

from summerset_tpu.analysis.ranges import (  # noqa: E402
    _cmp_interval,
    analyze_kernel_ranges,
    aval_bounds,
    check_claims,
    iv_clamp,
    iv_join,
    iv_leq,
    iv_meet,
    literal_interval,
    prim_intervals,
    verify_kernel_ranges,
)
from summerset_tpu.analysis.contract import build_kernel  # noqa: E402

I32 = (-(2 ** 31), 2 ** 31 - 1)


def _aval(dtype="int32", shape=()):
    return SimpleNamespace(dtype=np.dtype(dtype), shape=shape)


def _eqn(out="int32", ins=(), params=None, n_out=1):
    """Synthetic eqn carrying just what ``prim_intervals`` reads: output
    avals (dtype saturation), input avals (reduction cardinalities) and
    the params dict."""
    return SimpleNamespace(
        outvars=[SimpleNamespace(aval=_aval(out)) for _ in range(n_out)],
        invars=[SimpleNamespace(aval=_aval(*i)) for i in ins],
        params=dict(params or {}),
    )


def _run(name, ivs, **kw):
    outs = prim_intervals(name, _eqn(**kw), list(ivs))
    assert outs is not None, f"{name} unmodeled"
    return outs[0]


# ---------------------------------------------------- interval algebra --
def test_interval_algebra():
    assert iv_join((0, 3), (5, 9)) == (0, 9)
    assert iv_meet((0, 5), (3, 9)) == (3, 5)
    assert iv_meet((0, 2), (5, 9)) is None
    assert iv_leq((1, 2), (0, 3)) and not iv_leq((0, 3), (1, 2))
    assert iv_clamp((-10, 10), (0, 7)) == (0, 7)
    assert aval_bounds(_aval("int32")) == I32
    assert aval_bounds(_aval("uint32")) == (0, 2 ** 32 - 1)
    assert aval_bounds(_aval("bool")) == (0, 1)


def test_literal_interval_spans_nonuniform_arrays():
    assert literal_interval(
        SimpleNamespace(val=np.array([3, -1, 7], np.int32))
    ) == (-1, 7)
    assert literal_interval(SimpleNamespace(val=np.uint32(5))) == (5, 5)


@pytest.mark.parametrize(
    "name,a,b,expected",
    [
        # decided-true, decided-false, undecided for each comparison
        ("lt", (0, 4), (5, 9), (1, 1)),
        ("lt", (5, 9), (0, 5), (0, 0)),
        ("lt", (0, 5), (5, 9), (0, 1)),
        ("le", (0, 5), (5, 9), (1, 1)),
        ("le", (6, 9), (0, 5), (0, 0)),
        ("gt", (6, 9), (0, 5), (1, 1)),
        ("gt", (0, 5), (5, 9), (0, 0)),
        # the ROADMAP exemplar shape: dead-world -1 vs proven-nonneg
        ("gt", (-1, -1), (0, 2 ** 31 - 1), (0, 0)),
        ("ge", (5, 9), (0, 5), (1, 1)),
        ("ge", (0, 4), (5, 9), (0, 0)),
        ("eq", (0, 4), (5, 9), (0, 0)),
        ("eq", (3, 3), (3, 3), (1, 1)),
        ("eq", (0, 4), (4, 9), (0, 1)),
        ("ne", (0, 4), (5, 9), (1, 1)),
        ("ne", (3, 3), (3, 3), (0, 0)),
    ],
)
def test_cmp_decision_table(name, a, b, expected):
    assert _cmp_interval(name, a, b) == expected
    assert _run(name, [a, b], out="bool") == expected


# ---------------------------------------------------------- arithmetic --
def test_add_sub_saturate_at_dtype_bounds():
    """The documented no-wrap abstraction: results saturate into the
    output dtype instead of wrapping."""
    top = 2 ** 31 - 1
    assert _run("add", [(top, top), (1, 1)]) == (top, top)
    assert _run("add", [(0, 5), (10, 20)]) == (10, 25)
    assert _run("sub", [(I32[0], I32[0]), (1, 1)]) == (I32[0], I32[0])
    assert _run("sub", [(0, 5), (1, 2)]) == (-2, 4)


def test_mul_neg_abs_sign_corners():
    assert _run("mul", [(-2, 3), (-5, 4)]) == (-15, 12)
    assert _run("neg", [(-2, 3)]) == (-3, 2)
    assert _run("abs", [(-5, 3)]) == (0, 5)
    assert _run("abs", [(-5, -2)]) == (2, 5)
    assert _run("sign", [(-5, 3)]) == (-1, 1)
    assert _run("sign", [(2, 9)]) == (1, 1)
    assert _run("max", [(0, 5), (3, 9)]) == (3, 9)
    assert _run("min", [(0, 5), (3, 9)]) == (0, 5)
    assert _run("clamp", [(0, 0), (-9, 99), (7, 7)]) == (0, 7)


def test_div_rem():
    # a divisor interval straddling zero is dtype-top (possible /0)
    assert _run("div", [(0, 100), (-1, 1)]) == I32
    assert _run("div", [(0, 100), (8, 8)]) == (0, 12)
    assert _run("div", [(-7, 7), (2, 2)]) == (-3, 3)  # C truncation
    # positive divisor: |r| < divisor, sign follows the dividend
    assert _run("rem", [(0, 100), (8, 8)]) == (0, 7)
    assert _run("rem", [(-100, 100), (8, 8)]) == (-7, 7)
    assert _run("rem", [(0, 3), (8, 8)]) == (0, 3)


# -------------------------------------------------------------- bitwise --
def test_bitwise_uint32():
    assert _run("and", [(0, 12), (0, 300)], out="uint32") == (0, 12)
    # or >= both operands for nonnegatives, bounded by the joint mask
    assert _run("or", [(5, 12), (3, 9)], out="uint32") == (5, 15)
    assert _run("xor", [(0, 12), (0, 9)], out="uint32") == (0, 15)
    # a possibly-negative operand falls back to dtype bounds
    assert _run("and", [(-1, 12), (0, 300)]) == I32
    assert _run(
        "shift_right_logical", [(64, 256), (3, 4)], out="uint32"
    ) == (4, 32)
    assert _run("shift_left", [(1, 1), (0, 4)], out="uint32") == (1, 16)
    assert _run("not", [(0, 1)], out="bool") == (0, 1)
    assert _run("not", [(1, 1)], out="bool") == (0, 0)


# ----------------------------------------------- selects / reductions --
def test_select_n_joins_only_reachable_cases():
    cases = {"ins": (("int32",), ("int32",), ("int32",))}
    # decided predicate: only the selected case flows through
    assert _run("select_n", [(0, 0), (3, 5), (70, 90)], **cases) == (3, 5)
    assert _run("select_n", [(1, 1), (3, 5), (70, 90)], **cases) == (70, 90)
    # undecided: the join
    assert _run("select_n", [(0, 1), (3, 5), (70, 90)], **cases) == (3, 90)


def test_reductions_and_index_makers():
    shp = (("int32", (2, 3, 8)),)
    assert _run("reduce_max", [(0, 9)], ins=shp) == (0, 9)
    assert _run("reduce_sum", [(0, 9)], ins=shp,
                params={"axes": (2,)}) == (0, 72)
    assert _run("reduce_sum", [(-2, 9)], ins=shp,
                params={"axes": (1, 2)}) == (-48, 216)
    assert _run("argmax", [(0, 9)], ins=shp,
                params={"axes": (2,)}) == (0, 7)
    assert _run("iota", [], params={"dimension": 0, "shape": (8,)}) \
        == (0, 7)
    assert _run("concatenate", [(0, 3), (10, 12)],
                ins=shp + shp) == (0, 12)


def test_unmodeled_primitive_returns_none():
    assert prim_intervals("custom_call", _eqn(), [(0, 1)]) is None


# ------------------------------------------------- kernel-level fixpoint --
def _kernel_of(cls):
    return build_kernel(lambda _n, *a, **kw: cls(*a, **kw),
                        cls.name.lower())


def test_scan_carry_widening_converges():
    """A clamped scan carry stabilizes at a widening-ladder threshold —
    NOT at the dtype top — and the analysis terminates in bounded
    rounds."""
    import jax
    import jax.numpy as jnp

    from summerset_tpu.core.protocol import StepEffects

    class ScanCarry(GoodKernel):
        name = "FixtureScanCarry"

        def step(self, state, inbox, inputs):
            s = dict(state)
            self._fold(s, inbox)

            def body(c, _):
                return jnp.minimum(c + 1, jnp.int32(7)), None

            c, _ = jax.lax.scan(body, jnp.int32(0), None, length=5)
            s["exec_bar"] = jnp.minimum(s["commit_bar"], c)
            return s, self.zero_outbox(), StepEffects(
                commit_bar=s["commit_bar"], exec_bar=s["exec_bar"]
            )

    ra = analyze_kernel_ranges(_kernel_of(ScanCarry))
    assert ra.invariants["exec_bar"][0] == 0
    assert ra.invariants["exec_bar"][1] <= 255  # ladder, not 2**31-1
    assert ra.iterations < 64


def test_select_refinement_narrows_the_taken_branch():
    """``where(x < 5, x, 0)``: inside the taken branch the comparison
    refines x's interval, so the select's result is [0, 4] even though
    x itself is unbounded above."""
    import jax.numpy as jnp

    from summerset_tpu.core.protocol import StepEffects

    class Refined(GoodKernel):
        name = "FixtureRefined"

        def step(self, state, inbox, inputs):
            s = dict(state)
            self._fold(s, inbox)
            s["exec_bar"] = jnp.where(
                s["commit_bar"] < 5, s["commit_bar"], 0
            )
            return s, self.zero_outbox(), StepEffects(
                commit_bar=s["commit_bar"], exec_bar=s["exec_bar"]
            )

    ra = analyze_kernel_ranges(_kernel_of(Refined))
    assert ra.invariants["commit_bar"] == (0, 2 ** 31 - 1)
    assert ra.invariants["exec_bar"] == (0, 4)


def test_pair_facts_on_aliased_bars():
    """``exec_bar = commit_bar`` proves BOTH octagon-lite directions;
    untouched window leaves pin at their init interval."""
    ra = analyze_kernel_ranges(_kernel_of(GoodKernel))
    assert ("commit_bar", "exec_bar") in ra.pairs
    assert ("exec_bar", "commit_bar") in ra.pairs
    assert ra.invariants["win_val"] == (0, 0)
    assert ra.invariants["commit_bar"][0] == 0  # nonneg is proven


# --------------------------------------------------------------- claims --
def test_inductive_claim_passes():
    class Claimed(GoodKernel):
        name = "FixtureClaimed"
        RANGE_CLAIMS = (("win_val", 0, 0), ("commit_bar", 0, 2 ** 31 - 1))

    k = _kernel_of(Claimed)
    assert check_claims(k, analyze_kernel_ranges(k)) == []


def test_claim_on_missing_leaf_is_reported():
    class Ghost(GoodKernel):
        name = "FixtureGhostClaim"
        RANGE_CLAIMS = (("no_such_leaf", 0, 1),)

    k = _kernel_of(Ghost)
    bad = check_claims(k, analyze_kernel_ranges(k))
    assert [leaf for leaf, _ in bad] == ["no_such_leaf"]
    assert "not a state leaf" in bad[0][1]


def test_verify_pass_serializes_variants_deterministically():
    res = verify_kernel_ranges(make_fixture, "fixturegood")
    assert res.ok, res.error or [f.render() for f in res.findings]
    dev = res.extra["variants"]["device"]
    assert set(dev) == {"invariants", "pairs", "iterations"}
    assert dev["invariants"]["win_val"] == [0, 0]
    res2 = verify_kernel_ranges(make_fixture, "fixturegood")
    assert res.extra == res2.extra
