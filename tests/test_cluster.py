"""In-process cluster integration: a real manager + N ServerReplica event
loops over localhost TCP, driven by the reference tester suite semantics
(reset / pause / resume through the manager control plane — SURVEY.md §4
tier 2).  All replicas share one process (and thus one jit cache); the
sockets, WALs, and control flows are the real ones.
"""

import asyncio
import shutil
import socket
import threading
import time

import pytest

from summerset_tpu.client.tester import ClientTester
from summerset_tpu.host.server import ServerReplica
from summerset_tpu.manager import ClusterManager


def free_ports(n):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


class Cluster:
    def __init__(self, protocol, n, tmpdir, config=None, tick=0.005):
        self.protocol = protocol
        self.n = n
        self.tmpdir = str(tmpdir)
        self.config = config or {}
        self.tick = tick
        ports = free_ports(2 + 2 * n)
        self.srv_port, self.cli_port = ports[0], ports[1]
        self.api_ports = ports[2:2 + n]
        self.p2p_ports = ports[2 + n:]
        self.manager_addr = ("127.0.0.1", self.cli_port)
        self.replicas = {}
        self._threads = []
        self._man_loop = None

        man = ClusterManager(
            protocol, ("127.0.0.1", self.srv_port),
            ("127.0.0.1", self.cli_port), n,
        )

        def run_man():
            loop = asyncio.new_event_loop()
            self._man_loop = loop
            asyncio.set_event_loop(loop)
            try:
                loop.run_until_complete(man.run())
            except Exception:
                pass

        t = threading.Thread(target=run_man, daemon=True)
        t.start()
        self._threads.append(t)
        time.sleep(0.3)

        # replicas must come up concurrently (mesh barrier)
        for r in range(n):
            t = threading.Thread(
                target=self._replica_loop, args=(r,), daemon=True
            )
            t.start()
            self._threads.append(t)
        deadline = time.monotonic() + 120
        while len(self.replicas) < n:
            assert time.monotonic() < deadline, "cluster failed to start"
            time.sleep(0.1)
        time.sleep(1.0)  # let the warm-start leader settle

    def _replica_loop(self, slot: int) -> None:
        """Crash-restart loop (parity: summerset_server main loop)."""
        while True:
            rep = ServerReplica(
                self.protocol,
                ("127.0.0.1", self.api_ports[slot]),
                ("127.0.0.1", self.p2p_ports[slot]),
                ("127.0.0.1", self.srv_port),
                config=self.config,
                tick_interval=self.tick,
                window=32,
                backer_dir=self.tmpdir,
            )
            self.replicas[rep.me] = rep
            restart = rep.run()
            rep.shutdown()
            self.replicas.pop(rep.me, None)
            if not restart:
                return
            time.sleep(0.2)

    def stop(self):
        for rep in list(self.replicas.values()):
            rep.stopping = True
        time.sleep(3 * self.tick + 0.2)
        for rep in list(self.replicas.values()):
            try:
                rep.shutdown()
            except Exception:
                pass
        if self._man_loop is not None:
            self._man_loop.call_soon_threadsafe(self._man_loop.stop)


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    """One shared cluster for the whole tester suite — the reference CI
    shape (workflow_test.py runs the full tester against one live
    3-replica cluster) and the only way the suite fits the time budget
    (bring-up with jit compile dominates)."""
    c = Cluster("MultiPaxos", 3, tmp_path_factory.mktemp("mp_cluster"))
    yield c
    c.stop()


def _check(cluster, results):
    if not all(v == "PASS" for v in results.values()):
        dumps = {
            me: rep.debug_state()
            for me, rep in sorted(cluster.replicas.items())
        }
        raise AssertionError(f"{results}\nreplica states: {dumps}")


class TestClusterMultiPaxos:
    def test_tester_suite_basic(self, cluster):
        t = ClientTester(cluster.manager_addr, settle=1.5)
        results = t.run_tests([
            "primitive_ops",
            "client_reconnect",
            "node_pause_resume",
        ])
        _check(cluster, results)

    def test_tester_suite_faults(self, cluster):
        t = ClientTester(cluster.manager_addr, settle=2.5)
        results = t.run_tests([
            "non_leader_pause",
            "leader_node_pause",
            "non_leader_reset",
        ])
        _check(cluster, results)

    def test_tester_suite_resets(self, cluster):
        """The hard crash-restart cases: they pass only because acceptor
        state (ballots, vote runs, window content + payloads) is WAL-logged
        before acks leave and rebuilt into the kernel row on restart, and
        because the manager serializes resets (one victim down at a time,
        id freed and re-join awaited — clusman.rs:382-438)."""
        t = ClientTester(cluster.manager_addr, settle=2.5)
        results = t.run_tests([
            "leader_node_reset",
            "two_nodes_reset",
            "all_nodes_reset",
        ])
        _check(cluster, results)
