"""In-process cluster integration: a real manager + N ServerReplica event
loops over localhost TCP, driven by the reference tester suite semantics
(reset / pause / resume through the manager control plane — SURVEY.md §4
tier 2).  All replicas share one process (and thus one jit cache); the
sockets, WALs, and control flows are the real ones.
"""

import asyncio
import os
import shutil
import socket
import threading
import time

import pytest

from summerset_tpu.client.tester import ClientTester
from summerset_tpu.host.server import ServerReplica
from summerset_tpu.manager import ClusterManager


def free_ports(n):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


class Cluster:
    def __init__(self, protocol, n, tmpdir, config=None, tick=0.005,
                 num_groups=1, config_per_slot=None):
        self.protocol = protocol
        self.n = n
        self.tmpdir = str(tmpdir)
        self.config = config or {}
        # per-slot config overlays (slot -> dict), merged over `config`:
        # heterogeneous clusters (e.g. the wire-codec mixed-mesh test
        # runs one pickle replica among codec replicas)
        self.config_per_slot = config_per_slot or {}
        self.tick = tick
        self.num_groups = num_groups
        ports = free_ports(2 + 2 * n)
        self.srv_port, self.cli_port = ports[0], ports[1]
        self.api_ports = ports[2:2 + n]
        self.p2p_ports = ports[2 + n:]
        self.manager_addr = ("127.0.0.1", self.cli_port)
        self.replicas = {}
        self._threads = []
        self._man_loop = None
        self._stopping = False
        # supervisor crash reports: {me, error, flight_tail} per crash —
        # the flight-recorder tail says what the replica was doing in
        # its final ticks, not just which exception killed it
        self.crash_reports = []

        man = ClusterManager(
            protocol, ("127.0.0.1", self.srv_port),
            ("127.0.0.1", self.cli_port), n,
        )
        self.manager = man  # tests tune orchestration budgets directly

        def run_man():
            from summerset_tpu.utils.loops import drain_and_close

            loop = asyncio.new_event_loop()
            self._man_loop = loop
            asyncio.set_event_loop(loop)
            try:
                loop.run_until_complete(man.run())
            except Exception:
                pass
            finally:
                drain_and_close(loop)

        t = threading.Thread(target=run_man, daemon=True)
        t.start()
        self._threads.append(t)
        time.sleep(0.3)

        # replicas must come up concurrently (mesh barrier)
        for r in range(n):
            t = threading.Thread(
                target=self._replica_loop, args=(r,), daemon=True
            )
            t.start()
            self._threads.append(t)
        deadline = time.monotonic() + 120
        while len(self.replicas) < n:
            assert time.monotonic() < deadline, "cluster failed to start"
            time.sleep(0.1)
        time.sleep(1.0)  # let the warm-start leader settle

    def _replica_loop(self, slot: int) -> None:
        """Crash-restart loop (parity: summerset_server main loop under a
        process supervisor).  An exception out of run() is a crash — e.g.
        an injected WAL fault failing the group-commit fsync raises
        rather than ack unsynced writes — and the supervisor restarts the
        replica so recovery replays whatever actually reached the disk."""
        while not self._stopping:
            try:
                rep = ServerReplica(
                    self.protocol,
                    ("127.0.0.1", self.api_ports[slot]),
                    ("127.0.0.1", self.p2p_ports[slot]),
                    ("127.0.0.1", self.srv_port),
                    config={
                        **self.config,
                        **self.config_per_slot.get(slot, {}),
                    },
                    tick_interval=self.tick,
                    window=32,
                    num_groups=self.num_groups,
                    backer_dir=self.tmpdir,
                )
            except Exception as e:
                # bring-up can fail transiently when a peer is itself
                # mid-crash-restart (nemesis finding); the supervisor
                # retries instead of leaving the slot dead forever
                print(f"replica slot {slot} bring-up failed: {e!r}; "
                      "retrying", flush=True)
                time.sleep(0.5)
                continue
            self.replicas[rep.me] = rep
            try:
                restart = rep.run()
            except Exception as e:
                try:
                    # stamp the crash into the ring first, so the tail
                    # (and any later flight_dump of a kept recorder)
                    # carries the terminal marker itself
                    rep.flight.record("crash", error=repr(e))
                    tail = rep.flight.tail(48)
                except Exception:
                    tail = []
                self.crash_reports.append({
                    "me": rep.me, "error": repr(e), "flight_tail": tail,
                })
                print(
                    f"replica {rep.me} crashed: {e!r}; restarting\n"
                    "  last flight events:\n" + "\n".join(
                        f"    {line}" for line in tail[-12:]
                    ),
                    flush=True,
                )
                restart = True
            rep.shutdown()
            self.replicas.pop(rep.me, None)
            if not restart or rep.stopping:
                return
            time.sleep(0.2)

    def stop(self):
        self._stopping = True
        for rep in list(self.replicas.values()):
            rep.stopping = True
        time.sleep(3 * self.tick + 0.2)
        for rep in list(self.replicas.values()):
            try:
                rep.shutdown()
            except Exception:
                pass
        if self._man_loop is not None:
            self._man_loop.call_soon_threadsafe(self._man_loop.stop)


@pytest.fixture(scope="module", params=["MultiPaxos", "Raft"])
def cluster(request, tmp_path_factory):
    """One shared cluster per protocol for the whole tester suite — the
    reference CI shape (workflow_test.py runs the full tester against one
    live 3-replica cluster, for MultiPaxos AND Raft per
    tests_proc.yml:28-33) and the only way the suite fits the time budget
    (bring-up with jit compile dominates)."""
    c = Cluster(
        request.param, 3,
        tmp_path_factory.mktemp(f"{request.param.lower()}_cluster"),
    )
    yield c
    c.stop()


def _assert_recovers(cluster, expectations, servers=None):
    """Crash-restart (durable reset) then verify every key recovers."""
    from summerset_tpu.client.drivers import DriverClosedLoop
    from summerset_tpu.client.endpoint import GenericEndpoint
    from summerset_tpu.host.messages import CtrlRequest

    ep = GenericEndpoint(cluster.manager_addr)
    ep.connect()
    ep.ctrl.request(
        CtrlRequest("reset_servers", servers=servers, durable=True),
        timeout=180,
    )
    ep.leave()
    time.sleep(2.0)
    ep2 = GenericEndpoint(cluster.manager_addr)
    ep2.connect()
    drv = DriverClosedLoop(ep2)
    try:
        for key, val in expectations.items():
            drv.checked_get(key, expect=val)
    except AssertionError as e:
        dumps = {
            me: rep.debug_state()
            for me, rep in sorted(cluster.replicas.items())
        }
        raise AssertionError(f"{e}\nreplica states: {dumps}") from e
    ep2.leave()


def _check(cluster, results):
    if not all(v == "PASS" for v in results.values()):
        dumps = {
            me: rep.debug_state()
            for me, rep in sorted(cluster.replicas.items())
        }
        raise AssertionError(f"{results}\nreplica states: {dumps}")


class TestClusterTesterSuite:
    def test_tester_suite_basic(self, cluster):
        t = ClientTester(cluster.manager_addr, settle=1.5)
        results = t.run_tests([
            "primitive_ops",
            "client_reconnect",
            "node_pause_resume",
        ])
        _check(cluster, results)

    def test_tester_suite_faults(self, cluster):
        t = ClientTester(cluster.manager_addr, settle=2.5)
        results = t.run_tests([
            "non_leader_pause",
            "leader_node_pause",
            "non_leader_reset",
        ])
        _check(cluster, results)

    def test_tester_suite_resets(self, cluster):
        """The hard crash-restart cases: they pass only because acceptor
        state (ballots, vote runs, window content + payloads) is WAL-logged
        before acks leave and rebuilt into the kernel row on restart, and
        because the manager serializes resets (one victim down at a time,
        id freed and re-join awaited — clusman.rs:382-438)."""
        t = ClientTester(cluster.manager_addr, settle=2.5)
        results = t.run_tests([
            "leader_node_reset",
            "two_nodes_reset",
            "all_nodes_reset",
        ])
        _check(cluster, results)

    def test_linearizable_history_under_faults(self, cluster):
        """VERDICT r3 #6: record real client-observed histories while a
        random fault schedule (pause/resume through the manager) runs,
        then check linearizability per key (utils/linearize.py — the
        executable TLA+ stand-in).  Runs for MultiPaxos AND Raft via the
        cluster param."""
        import random as _random

        import threading as _threading

        from summerset_tpu.client.drivers import DriverClosedLoop
        from summerset_tpu.client.endpoint import GenericEndpoint
        from summerset_tpu.host.messages import CtrlRequest
        from summerset_tpu.utils.linearize import (
            check_history, record_get, record_put,
        )

        ops = []
        stop = _threading.Event()

        def worker(ci):
            rng = _random.Random(100 + ci)
            ep = GenericEndpoint(cluster.manager_addr)
            ep.connect()
            drv = DriverClosedLoop(ep, timeout=3.0)
            seq = 0
            while not stop.is_set():
                key = f"lin{seq % 3}"
                t0 = time.monotonic()
                if rng.random() < 0.5:
                    val = f"c{ci}-{seq}"
                    rep = drv.put(key, val)
                    t1 = time.monotonic()
                    if rep.kind == "success":
                        ops.append(record_put(ci, key, val, t0, t1, True))
                    elif rep.kind in ("timeout", "failure", "disconnect"):
                        # may or may not have executed
                        ops.append(record_put(ci, key, val, t0, None,
                                              False))
                        drv._failover(rep)
                    # redirect: server refused without proposing — no op
                else:
                    rep = drv.get(key)
                    t1 = time.monotonic()
                    if rep.kind == "success":
                        val = rep.result.value if rep.result else None
                        ops.append(record_get(ci, key, val, t0, t1))
                    elif rep.kind in ("timeout", "failure", "disconnect"):
                        drv._failover(rep)
                seq += 1
            try:
                ep.leave()
            except Exception:
                pass

        threads = [
            _threading.Thread(target=worker, args=(ci,), daemon=True)
            for ci in range(3)
        ]
        for t in threads:
            t.start()
        # fault schedule: pause a random victim mid-run, resume, repeat
        ctl = GenericEndpoint(cluster.manager_addr)
        ctl.connect()
        rng = _random.Random(7)
        try:
            for _ in range(2):
                time.sleep(1.5)
                victim = rng.choice(sorted(ctl.servers))
                ctl.ctrl.request(CtrlRequest(
                    "pause_servers", servers=[victim]), timeout=30)
                time.sleep(1.5)
                ctl.ctrl.request(CtrlRequest(
                    "resume_servers", servers=[victim]), timeout=30)
            # slow boxes: ops trickle under jit pauses + full-suite load;
            # keep the healthy tail running until the history is big
            # enough to be worth checking (bounded)
            deadline = time.monotonic() + 30
            while len(ops) <= 20 and time.monotonic() < deadline:
                time.sleep(0.5)
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=30)
            ctl.leave()
        assert len(ops) > 20, f"history too small: {len(ops)}"
        ok, diag = check_history(ops)
        assert ok, diag

    def test_snapshot_gc_and_recovery(self, cluster):
        """Snapshot + WAL GC + crash recovery (VERDICT r3 #3; parity:
        multipaxos/snapshot.rs:121-303): write enough to grow the WAL,
        take a snapshot through the manager (WAL must measurably shrink),
        crash-restart every node, and verify recovery from snapshot+tail
        serves the correct values."""
        from summerset_tpu.client.drivers import DriverClosedLoop
        from summerset_tpu.client.endpoint import GenericEndpoint
        from summerset_tpu.host.messages import CtrlRequest

        ep = GenericEndpoint(cluster.manager_addr)
        ep.connect()
        drv = DriverClosedLoop(ep)
        for i in range(12):
            drv.checked_put(f"snapk{i}", f"v{i}")
        time.sleep(1.0)  # let followers execute + log the tail
        before = {
            me: rep.wal.size for me, rep in cluster.replicas.items()
        }
        rep = ep.ctrl.request(
            CtrlRequest("take_snapshot"), timeout=60
        )
        assert sorted(rep.done) == sorted(before), rep
        shrunk = {
            me: r.wal.size for me, r in cluster.replicas.items()
        }
        assert any(shrunk[me] < before[me] for me in shrunk), (
            f"WAL did not shrink: {before} -> {shrunk}"
        )
        ep.leave()
        # crash-restart everyone: recovery = snapshot + WAL tail
        _assert_recovers(
            cluster, {f"snapk{i}": f"v{i}" for i in range(12)}
        )



    def test_metrics_dump_scrape(self, cluster):
        """Telemetry plane end-to-end (runs for MultiPaxos AND Raft via
        the cluster param): a live 3-replica cluster answers the
        ``metrics_dump`` ctrl scrape with nonzero device commit lanes, a
        request-latency histogram, fsync latency, loop-stage breakdown,
        and a sampled ticks-to-commit distribution."""
        from summerset_tpu.client.drivers import DriverClosedLoop
        from summerset_tpu.client.endpoint import GenericEndpoint
        from summerset_tpu.host.messages import CtrlRequest

        ep = GenericEndpoint(cluster.manager_addr)
        ep.connect()
        drv = DriverClosedLoop(ep)
        for i in range(8):
            drv.checked_put(f"mtk{i}", f"v{i}")
        time.sleep(0.5)  # let followers apply + fsync the tail
        # the manager waits <=15s per fan-out reply; a follower stalled
        # behind a concurrent test's JIT recompile on this 2-core box can
        # miss one window, so re-scrape until every replica answers
        for _ in range(4):
            rep = ep.ctrl.request(CtrlRequest("metrics_dump"), timeout=30)
            if rep.payloads and len(rep.payloads) == 3:
                break
            time.sleep(2.0)
        ep.leave()
        assert rep.payloads and len(rep.payloads) == 3, rep
        lanes = {
            sid: s["device"]["lanes"] for sid, s in rep.payloads.items()
        }
        assert sum(l["commits"] for l in lanes.values()) > 0, lanes
        hists = {
            sid: s["host"]["histograms"] for sid, s in rep.payloads.items()
        }
        # the serving replica has the client-facing + commit-path metrics
        assert any(
            h.get("ticks_to_commit", {"count": 0})["count"] > 0
            for h in hists.values()
        ), hists.keys()
        assert any(
            v["count"] > 0
            for h in hists.values()
            for k, v in h.items()
            if k.startswith("api_request_latency_us")
        )
        # every replica fsyncs its WAL and times its loop stages
        for sid, h in hists.items():
            assert any(k.startswith("wal_fsync_us") for k in h), (sid, h)
            assert any(k.startswith("loop_stage_us") for k in h), sid
        # host counters mirror the device commit lanes
        for sid, s in rep.payloads.items():
            if lanes[sid]["commits"] > 0:
                assert s["host"]["counters"].get(
                    "commits_applied_total", 0
                ) > 0, (sid, s["host"]["counters"])

    def test_flight_dump_scrape_with_restarted_replica(self, cluster):
        """graftscope end-to-end: a live cluster answers the
        ``flight_dump`` ctrl scrape from every replica — INCLUDING one
        that was crash-restarted mid-test (its fresh recorder carries
        the ``restart`` recovery marker) — and the merged dumps pair at
        least one transport frame's tx/rx across two replicas and
        export to a schema-valid Chrome trace with a connected
        api→propose→commit→apply→reply chain.  Runs only for the
        MultiPaxos param: the Raft cluster exercises the identical
        host-plane code paths, and the extra reset would spend tier-1
        budget re-proving it."""
        if cluster.protocol != "MultiPaxos":
            pytest.skip("host-plane path identical; save the reset cost")
        import os as _os
        import sys as _sys

        _sys.path.insert(0, _os.path.join(
            _os.path.dirname(_os.path.abspath(__file__)), "..", "scripts",
        ))
        import trace_export

        from summerset_tpu.client.drivers import DriverClosedLoop
        from summerset_tpu.client.endpoint import (
            GenericEndpoint, scrape_flight,
        )
        from summerset_tpu.host.messages import CtrlRequest

        ep = GenericEndpoint(cluster.manager_addr)
        ep.connect()
        # crash-restart one replica so its dump is a post-recovery ring
        victim = sorted(cluster.replicas)[0]
        ep.ctrl.request(
            CtrlRequest("reset_servers", servers=[victim], durable=True),
            timeout=180,
        )
        time.sleep(1.0)
        ep.reconnect()
        drv = DriverClosedLoop(ep)
        # trace_sample defaults to 8: enough writes that at least one
        # batch lands a sampled propose event on some replica
        for i in range(20):
            drv.checked_put(f"fltk{i}", f"v{i}")
        time.sleep(0.5)  # let followers apply + fsync the tail
        for _ in range(4):
            dumps = scrape_flight(cluster.manager_addr)
            if len(dumps) == 3:
                break
            time.sleep(2.0)
        ep.leave()
        assert len(dumps) == 3, dumps.keys()
        for sid, d in dumps.items():
            assert d["count"] >= len(d["events"]) > 0, (sid, d["count"])
            assert d["dropped"] == d["count"] - len(d["events"])
        # the restarted victim's ring began at recovery: a NON-cold
        # restart marker (durable state predated the boot) — every
        # replica records a cold restart at first bring-up, so the bare
        # event type would not prove the reset actually happened
        assert any(
            ev["type"] == "restart" and ev.get("cold") is False
            for ev in dumps[str(victim)]["events"]
        ), [ev for ev in dumps[str(victim)]["events"]
            if ev["type"] == "restart"]
        # tx/rx pairing across two different replicas' dumps
        pairs = trace_export.paired_frames(dumps)
        assert pairs and any(p["src"] != p["dst"] for p in pairs)
        # merged export is schema-valid and carries a connected chain
        doc = trace_export.export_chrome(dumps)
        assert trace_export.validate_chrome(doc) == []
        assert trace_export.find_request_chains(dumps), (
            "no connected request chain in the merged dumps"
        )

    def test_conf_rejected_without_conf_plane(self, cluster):
        """No request kind is ever silently dropped: a conf request to a
        conf-less protocol gets an explicit failure reply."""
        from summerset_tpu.client.endpoint import GenericEndpoint

        ep = GenericEndpoint(cluster.manager_addr)
        ep.connect()
        ep.send_conf(0, {"responders": [0]})
        rep = ep.recv_reply(timeout=10)
        while rep.req_id != 0 or rep.kind == "redirect":
            rep = ep.recv_reply(timeout=10)
        assert rep.kind == "conf" and not rep.success
        ep.leave()


@pytest.fixture(scope="class")
def ql_cluster(tmp_path_factory):
    c = Cluster(
        "QuorumLeases", 3, tmp_path_factory.mktemp("ql_cluster"),
    )
    yield c
    c.stop()


@pytest.fixture(scope="class")
def nqr_cluster(tmp_path_factory):
    c = Cluster(
        "MultiPaxos", 3, tmp_path_factory.mktemp("nqr_cluster"),
        config={"near_quorum_reads": True},
    )
    yield c
    c.stop()


class TestClusterNearQuorumReads:
    def test_follower_serves_quorum_read(self, nqr_cluster):
        """Near-quorum reads (parity: multipaxos/quorumread.rs): a
        follower answers a GET by sampling a majority's (value, write
        slot) instead of redirecting; an in-flight write to the key
        falls back to the leader path (rq_retry redirect)."""
        from summerset_tpu.client.drivers import DriverClosedLoop
        from summerset_tpu.client.endpoint import GenericEndpoint
        from summerset_tpu.host.messages import CtrlRequest

        ep = GenericEndpoint(nqr_cluster.manager_addr)
        ep.connect()
        drv = DriverClosedLoop(ep)
        drv.checked_put("nqr_key", "v1")
        time.sleep(0.5)  # let followers apply
        leader = ep.ctrl.request(CtrlRequest("query_info")).leader or 0
        follower = next(s for s in sorted(ep.servers) if s != leader)
        ep2 = GenericEndpoint(nqr_cluster.manager_addr,
                              server_id=follower)
        ep2.connect()
        drv2 = DriverClosedLoop(ep2)
        got = None
        for _ in range(20):
            r = drv2.get("nqr_key")
            if r.kind == "success" and r.local:
                got = r
                break
            ep2.reconnect(follower)
            time.sleep(0.2)
        assert got is not None, "follower never served a quorum read"
        assert got.result.value == "v1"
        ep2.leave()
        ep.leave()

    def test_quorum_read_history_linearizable(self, nqr_cluster):
        """Writer streams unique values while follower-pinned readers
        use the quorum-read path; the combined history must check out
        (the tail-hit fallback is what keeps in-flight writes safe)."""
        import threading as _threading

        from summerset_tpu.client.drivers import DriverClosedLoop
        from summerset_tpu.client.endpoint import GenericEndpoint
        from summerset_tpu.host.messages import CtrlRequest
        from summerset_tpu.utils.linearize import (
            check_history, record_get, record_put,
        )

        ops = []
        stop = _threading.Event()
        ep = GenericEndpoint(nqr_cluster.manager_addr)
        ep.connect()
        drv = DriverClosedLoop(ep)
        leader = ep.ctrl.request(CtrlRequest("query_info")).leader or 0
        followers = [s for s in sorted(ep.servers) if s != leader][:2]

        def reader(ci, sid):
            ep2 = GenericEndpoint(nqr_cluster.manager_addr,
                                  server_id=sid)
            ep2.connect()
            drv2 = DriverClosedLoop(ep2, timeout=3.0)
            while not stop.is_set():
                t0 = time.monotonic()
                r = drv2.get("nqr_hist")
                t1 = time.monotonic()
                if r.kind == "success":
                    val = r.result.value if r.result else None
                    ops.append(record_get(ci, "nqr_hist", val, t0, t1))
                else:
                    ep2.reconnect(sid)
                    time.sleep(0.05)
            try:
                ep2.leave()
            except Exception:
                pass

        threads = [
            _threading.Thread(target=reader, args=(10 + i, sid),
                              daemon=True)
            for i, sid in enumerate(followers)
        ]
        for t in threads:
            t.start()
        for seq in range(12):
            val = f"w-{seq}"
            t0 = time.monotonic()
            rep = drv.put("nqr_hist", val)
            t1 = time.monotonic()
            if rep.kind == "success":
                ops.append(record_put(0, "nqr_hist", val, t0, t1, True))
            elif rep.kind in ("timeout", "failure", "disconnect"):
                ops.append(record_put(0, "nqr_hist", val, t0, None,
                                      False))
                drv._failover(rep)
            time.sleep(0.25)
        # slow boxes: let the readers accumulate a checkable history
        deadline = time.monotonic() + 20
        while (
            sum(1 for o in ops if o.kind == "get") <= 8
            and time.monotonic() < deadline
        ):
            time.sleep(0.5)
        time.sleep(0.8)
        stop.set()
        for t in threads:
            t.join(timeout=15)
        ep.leave()
        reads = [o for o in ops if o.kind == "get"]
        assert len(reads) > 8, f"too few reads: {len(reads)}"
        ok, diag = check_history(ops)
        assert ok, diag


@pytest.fixture(scope="class")
def ql8_cluster(tmp_path_factory):
    c = Cluster(
        "QuorumLeases", 3, tmp_path_factory.mktemp("ql8_cluster"),
        num_groups=8,
    )
    yield c
    c.stop()


@pytest.mark.slow
class TestClusterMultiGroupConf:
    def test_conf_installs_under_split_leadership(self, ql8_cluster):
        """Manager-mediated ConfChange (COVERAGE known-gap closure): with
        8 groups whose leaderships split across replicas after a fault,
        no single server leads every group — the receiving server relays
        the delta through the manager, every group's leader proposes it,
        and the original server replies once conf_cur reaches the target
        in ALL groups."""
        import numpy as np

        from summerset_tpu.client.drivers import DriverClosedLoop
        from summerset_tpu.client.endpoint import GenericEndpoint
        from summerset_tpu.host.messages import CtrlRequest

        ep = GenericEndpoint(ql8_cluster.manager_addr)
        ep.connect()
        drv = DriverClosedLoop(ep)
        drv.checked_put("mgc_key", "v1")
        # split leadership: pause the warm leader so every group elects
        # independently (jittered per-group timeouts scatter the winners)
        ep.ctrl.request(
            CtrlRequest("pause_servers", servers=[0]), timeout=60
        )
        time.sleep(2.5)
        ep.ctrl.request(
            CtrlRequest("resume_servers", servers=[0]), timeout=60
        )
        time.sleep(1.0)

        def leaders():
            reps = ql8_cluster.replicas
            out = set()
            for g in range(8):
                for me, rep in reps.items():
                    if bool(rep._is_leader[g]):
                        out.add(me)
            return out

        # (don't assert a split strictly — elections are randomized —
        # but log it; the relay path is exercised either way whenever
        # the serving endpoint doesn't lead all groups)
        ep.rotate()
        rep = drv.conf_change({"responders": [0, 1, 2]}, retries=30)
        assert rep.kind == "success"
        deadline = time.monotonic() + 20
        ok = False
        while time.monotonic() < deadline and not ok:
            ok = all(
                (np.asarray(r.state["conf_cur"])[:, me] == 7).all()
                for me, r in ql8_cluster.replicas.items()
            )
            time.sleep(0.3)
        assert ok, {
            me: np.asarray(r.state["conf_cur"])[:, me].tolist()
            for me, r in ql8_cluster.replicas.items()
        }
        assert len(leaders()) >= 1
        ep.leave()


@pytest.fixture(scope="class")
def ep_cluster(tmp_path_factory):
    c = Cluster("EPaxos", 3, tmp_path_factory.mktemp("ep_cluster"))
    yield c
    c.stop()


@pytest.fixture(scope="class")
def sp_cluster(tmp_path_factory):
    c = Cluster("SimplePush", 3, tmp_path_factory.mktemp("sp_cluster"))
    yield c
    c.stop()


class TestClusterBasics:
    def test_simple_push_serving_node_restart(self, sp_cluster):
        """The basic-protocol family serves over the host runtime too:
        SimplePush pushes batches to peers and replies; crash-restarting
        the serving node must recover its appended log from the durable
        record (the generalized contract on the basics kernels)."""
        from summerset_tpu.client.drivers import DriverClosedLoop
        from summerset_tpu.client.endpoint import GenericEndpoint
        from summerset_tpu.host.messages import CtrlRequest

        ep = GenericEndpoint(sp_cluster.manager_addr)
        ep.connect()
        drv = DriverClosedLoop(ep)
        for i in range(5):
            drv.checked_put(f"spk{i}", f"v{i}")
        ep.ctrl.request(
            CtrlRequest("reset_servers", servers=[0], durable=True),
            timeout=120,
        )
        time.sleep(1.5)
        ep2 = GenericEndpoint(sp_cluster.manager_addr)
        ep2.connect()
        drv2 = DriverClosedLoop(ep2)
        for i in range(5):
            drv2.checked_get(f"spk{i}", expect=f"v{i}")
        drv2.checked_put("spk_post", "after")
        drv2.checked_get("spk_post", expect="after")
        ep2.leave()
        ep.leave()


@pytest.fixture(scope="class")
def autosnap_cluster(tmp_path_factory):
    c = Cluster(
        "MultiPaxos", 3, tmp_path_factory.mktemp("autosnap_cluster"),
        config={"snapshot_interval": 300},
    )
    yield c
    c.stop()


@pytest.mark.slow
class TestClusterAutoSnapshot:
    def test_interval_snapshot_compacts_wal(self, autosnap_cluster):
        """The snapshot_interval tick trigger (parity: the reference's
        snapshot_interval timer, multipaxos/mod.rs:921-929): without any
        manager request, the WAL compacts once writes accumulate and a
        crash-restart recovers from snapshot + tail."""
        from summerset_tpu.client.drivers import DriverClosedLoop
        from summerset_tpu.client.endpoint import GenericEndpoint
        from summerset_tpu.host.messages import CtrlRequest

        ep = GenericEndpoint(autosnap_cluster.manager_addr)
        ep.connect()
        drv = DriverClosedLoop(ep)
        t_base = time.time()
        for i in range(15):
            drv.checked_put(f"ask{i}", f"v{i}")
        ep.leave()
        # detect a trigger firing AFTER the writes via the snapshot
        # file's mtime (probing files, not the live StorageHub: the
        # replica swaps/closes its hub mid-snapshot, and poking it from
        # another thread races that swap).  300 ticks x 5ms = 1.5s
        # between triggers.
        snaps = [
            os.path.join(autosnap_cluster.tmpdir, f"r{me}.snap")
            for me in autosnap_cluster.replicas
        ]
        deadline = time.monotonic() + 25
        fired = False
        while time.monotonic() < deadline and not fired:
            time.sleep(0.5)
            fired = any(
                os.path.exists(p) and os.path.getmtime(p) > t_base
                for p in snaps
            )
        assert fired, f"no auto-snapshot fired: {snaps}"
        # compaction left the WAL small: a handful of acceptor records,
        # not 15 batched apply records (file probe, same reason)
        wals = sorted(
            os.path.getsize(
                os.path.join(autosnap_cluster.tmpdir, f"r{me}.wal")
            )
            for me in autosnap_cluster.replicas
        )
        assert wals[0] < 32 * 1024, f"WALs not compacted: {wals}"
        # recovery from the auto snapshot + tail
        _assert_recovers(
            autosnap_cluster,
            {f"ask{i}": f"v{i}" for i in range(15)},
        )


@pytest.fixture(
    scope="class", params=["RSPaxos", "CRaft", "Crossword"]
)
def rs_cluster(request, tmp_path_factory):
    c = Cluster(
        request.param, 3,
        tmp_path_factory.mktemp(f"{request.param.lower()}_cluster"),
        config={"fault_tolerance": 0},
    )
    yield c
    c.stop()


@pytest.mark.slow
class TestClusterRSFamily:
    def test_serve_and_reset(self, rs_cluster):
        """The erasure-coded family serves over the host runtime: the
        kernel runs the coded control plane (shard availability tallies,
        commit_k = majority + FT) while the host payload plane ships
        batches; a non-leader crash-restart must recover through the
        durable contract (win_spr / win_full marker lanes included)."""
        t = ClientTester(rs_cluster.manager_addr, settle=2.0)
        results = t.run_tests([
            "primitive_ops",
            "client_reconnect",
            "non_leader_reset",
        ])
        _check(rs_cluster, results)


@pytest.fixture(scope="class")
def bodega_cluster(tmp_path_factory):
    # long leases relative to the refresh period: tick-rate skew between
    # replicas under full-suite load otherwise lapses holds faster than
    # refreshes land, starving the local-read condition for long spells
    c = Cluster(
        "Bodega", 3, tmp_path_factory.mktemp("bodega_cluster"),
        config={"lease_len": 40, "lease_margin": 8, "grant_interval": 4,
                "conf_timeout": 80},
    )
    yield c
    c.stop()


class TestClusterBodega:
    def test_roster_conf_and_local_read(self, bodega_cluster):
        """Bodega end-to-end: a client announces a roster conf through
        the data plane (any replica may announce — conflease.rs
        heard_new_conf), the config leases install after the
        revoke-then-adopt barrier, and a responder then serves an
        always-local read (localread.rs:8-26)."""
        from summerset_tpu.client.drivers import DriverClosedLoop
        from summerset_tpu.client.endpoint import GenericEndpoint
        from summerset_tpu.host.messages import CtrlRequest

        ep = GenericEndpoint(bodega_cluster.manager_addr)
        ep.connect()
        drv = DriverClosedLoop(ep)
        drv.checked_put("bod_key", "v1")
        rep = drv.conf_change({"responders": [0, 1, 2]})
        assert rep.kind == "success"
        conf = None
        for _ in range(50):
            conf = ep.ctrl.request(CtrlRequest("query_conf"), timeout=10)
            if conf.conf:
                break
            time.sleep(0.1)
        assert conf.conf and sorted(conf.conf["responders"]) == [0, 1, 2]
        leader = ep.ctrl.request(CtrlRequest("query_info")).leader or 0
        follower = next(s for s in sorted(ep.servers) if s != leader)
        ep2 = GenericEndpoint(
            bodega_cluster.manager_addr, server_id=follower
        )
        ep2.connect()
        drv2 = DriverClosedLoop(ep2)
        # generous: config leases install only after outgoing leases at
        # the old conf lapse, and ticks stretch under full-suite load —
        # on a 2-core box a cold-cache suite run stretches ticks ~10x
        # (observed: 75s intermittently misses the install exactly when
        # kernel recompiles land mid-test; 150s has headroom)
        deadline = time.monotonic() + 150
        got = None
        while time.monotonic() < deadline:
            r = drv2.get("bod_key")
            if r.kind == "success" and r.local:
                got = r
                break
            ep2.reconnect(follower)
            time.sleep(0.3)
        assert got is not None, "responder never served a local read"
        assert got.result.value == "v1"
        ep2.leave()
        ep.leave()




class TestClusterQuorumLeases:
    def test_conf_change_and_local_read(self, ql_cluster):
        """A client installs a grantee conf through the data plane and a
        non-leader then serves a leased LOCAL read (VERDICT r3 #2;
        parity: quorumconf.rs conf flow + quorumlease.rs:10-17
        is_local_reader)."""
        from summerset_tpu.client.drivers import DriverClosedLoop
        from summerset_tpu.client.endpoint import GenericEndpoint
        from summerset_tpu.host.messages import CtrlRequest

        ep = GenericEndpoint(ql_cluster.manager_addr)
        ep.connect()
        drv = DriverClosedLoop(ep)
        drv.checked_put("lease_key", "v1")
        rep = drv.conf_change({"responders": [0, 1, 2]})
        assert rep.kind == "success"
        # the manager learned the new conf (reigner RespondersConf); the
        # server->manager ctrl frame races the client's query, so poll
        conf = None
        for _ in range(50):
            conf = ep.ctrl.request(CtrlRequest("query_conf"), timeout=10)
            if conf.conf:
                break
            time.sleep(0.1)
        assert conf.conf and sorted(conf.conf["responders"]) == [0, 1, 2]
        leader = ep.ctrl.request(CtrlRequest("query_info")).leader or 0
        follower = next(s for s in sorted(ep.servers) if s != leader)
        ep2 = GenericEndpoint(ql_cluster.manager_addr, server_id=follower)
        ep2.connect()
        drv2 = DriverClosedLoop(ep2)
        # leases need a few grant rounds to establish; a redirect means
        # the follower can't serve locally yet
        deadline = time.monotonic() + 30
        got = None
        while time.monotonic() < deadline:
            r = drv2.get("lease_key")
            if r.kind == "success" and r.local:
                got = r
                break
            ep2.reconnect(follower)  # redirects bounce us off; come back
            time.sleep(0.3)
        assert got is not None, "follower never served a local read"
        assert got.result.value == "v1"
        ep2.leave()
        ep.leave()

    def test_linearizable_local_reads(self, ql_cluster):
        """Lease local reads are the point of the linearizability harness
        (VERDICT r3 #6): a writer streams unique values while readers
        pinned to followers issue gets (served locally once leases are
        quiescent); the combined observed history must check out."""
        import threading as _threading

        from summerset_tpu.client.drivers import DriverClosedLoop
        from summerset_tpu.client.endpoint import GenericEndpoint
        from summerset_tpu.utils.linearize import (
            check_history, record_get, record_put,
        )

        ops = []
        stop = _threading.Event()

        ep = GenericEndpoint(ql_cluster.manager_addr)
        ep.connect()
        drv = DriverClosedLoop(ep)
        drv.conf_change({"responders": [0, 1, 2]})

        def reader(ci, sid):
            ep2 = GenericEndpoint(ql_cluster.manager_addr, server_id=sid)
            ep2.connect()
            drv2 = DriverClosedLoop(ep2, timeout=2.0)
            while not stop.is_set():
                t0 = time.monotonic()
                rep = drv2.get("lr_key")
                t1 = time.monotonic()
                if rep.kind == "success":
                    val = rep.result.value if rep.result else None
                    ops.append(record_get(ci, "lr_key", val, t0, t1))
                else:
                    # bounced (not quiescent / not leased): come back
                    ep2.reconnect(sid)
                    time.sleep(0.05)
            try:
                ep2.leave()
            except Exception:
                pass

        followers = sorted(ep.servers)[-2:]
        threads = [
            _threading.Thread(target=reader, args=(10 + i, sid),
                              daemon=True)
            for i, sid in enumerate(followers)
        ]
        for t in threads:
            t.start()
        for seq in range(10):
            val = f"w-{seq}"
            t0 = time.monotonic()
            rep = drv.put("lr_key", val)
            t1 = time.monotonic()
            if rep.kind == "success":
                ops.append(record_put(0, "lr_key", val, t0, t1, True))
            elif rep.kind in ("timeout", "failure", "disconnect"):
                ops.append(record_put(0, "lr_key", val, t0, None, False))
                drv._failover(rep)
            time.sleep(0.4)  # leases need quiescence to serve locally
        deadline = time.monotonic() + 20
        while (
            sum(1 for o in ops if o.kind == "get") <= 5
            and time.monotonic() < deadline
        ):
            time.sleep(0.5)
        time.sleep(1.0)
        stop.set()
        for t in threads:
            t.join(timeout=15)
        ep.leave()
        reads = [o for o in ops if o.kind == "get"]
        assert len(reads) > 5, f"too few reads observed: {len(reads)}"
        ok, diag = check_history(ops)
        assert ok, diag

class TestClusterEPaxos:
    def test_epaxos_cluster_multi_leader(self, ep_cluster):
        """EPaxos host integration (VERDICT r3 #7): leaderless serving —
        two clients pinned to DIFFERENT servers write/read interleaved;
        commits flow through PreAccept/Accept, execution through the host
        Tarjan applier; the combined history must be linearizable and a
        crash-restart must recover through the eapply WAL records."""
        import threading as _threading

        from summerset_tpu.client.drivers import DriverClosedLoop
        from summerset_tpu.client.endpoint import GenericEndpoint
        from summerset_tpu.host.messages import CtrlRequest
        from summerset_tpu.utils.linearize import (
            check_history, record_get, record_put,
        )

        ops = []

        def worker(ci, sid, n):
            ep = GenericEndpoint(ep_cluster.manager_addr, server_id=sid)
            ep.connect()
            drv = DriverClosedLoop(ep, timeout=5.0)
            for seq in range(n):
                key = f"ep{seq % 2}"
                t0 = time.monotonic()
                if seq % 2 == ci % 2:
                    val = f"c{ci}-{seq}"
                    rep = drv.put(key, val)
                    t1 = time.monotonic()
                    if rep.kind == "success":
                        ops.append(record_put(ci, key, val, t0, t1, True))
                    elif rep.kind in ("timeout", "failure", "disconnect"):
                        ops.append(record_put(ci, key, val, t0, None,
                                              False))
                else:
                    rep = drv.get(key)
                    t1 = time.monotonic()
                    if rep.kind == "success":
                        val = rep.result.value if rep.result else None
                        ops.append(record_get(ci, key, val, t0, t1))
            ep.leave()

        threads = [
            _threading.Thread(target=worker, args=(ci, sid, 12),
                              daemon=True)
            for ci, sid in enumerate((0, 1))
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert len(ops) > 12, f"history too small: {len(ops)}"
        ok, diag = check_history(ops)
        assert ok, diag

        # crash-restart a server; recovery must replay eapply records
        ep = GenericEndpoint(ep_cluster.manager_addr)
        ep.connect()
        drv = DriverClosedLoop(ep)
        drv.checked_put("ep_stable", "keep")
        ep.ctrl.request(
            CtrlRequest("reset_servers", servers=[0], durable=True),
            timeout=120,
        )
        time.sleep(1.5)
        ep2 = GenericEndpoint(ep_cluster.manager_addr, server_id=0)
        ep2.connect()
        DriverClosedLoop(ep2).checked_get("ep_stable", expect="keep")
        ep2.leave()
        ep.leave()


@pytest.fixture(scope="class")
def ll_cluster(tmp_path_factory):
    c = Cluster(
        "MultiPaxos", 3, tmp_path_factory.mktemp("ll_cluster"),
        config={"leader_leases": True},
    )
    yield c
    c.stop()


class TestClusterLeaderLease:
    def test_leader_serves_local_read(self, ll_cluster):
        """Stable-leader lease local reads (parity: multipaxos/
        leaderlease.rs:10-21): once the lease quorum is confirmed the
        leader answers GETs from applied state without a log round."""
        from summerset_tpu.client.drivers import DriverClosedLoop
        from summerset_tpu.client.endpoint import GenericEndpoint

        ep = GenericEndpoint(ll_cluster.manager_addr)
        ep.connect()
        drv = DriverClosedLoop(ep)
        drv.checked_put("llk", "v1")
        got = None
        for _ in range(30):
            r = drv.get("llk")
            if r.kind == "success" and r.local:
                got = r
                break
            time.sleep(0.1)
        assert got is not None, "leader never served a leased local read"
        assert got.result.value == "v1"
        ep.leave()

    def test_leader_lease_history_linearizable_under_leader_kill(
            self, ll_cluster):
        """Writer + readers (leader-preferring) stream while the leader
        is crash-restarted mid-run; the merged history must linearize —
        the lease veto is what prevents a split-brain serving window."""
        import threading as _threading

        from summerset_tpu.client.drivers import DriverClosedLoop
        from summerset_tpu.client.endpoint import GenericEndpoint
        from summerset_tpu.host.messages import CtrlRequest
        from summerset_tpu.utils.linearize import (
            check_history, record_get, record_put,
        )

        ops = []
        stop = _threading.Event()
        ep = GenericEndpoint(ll_cluster.manager_addr)
        ep.connect()
        drv = DriverClosedLoop(ep)
        leader = ep.ctrl.request(CtrlRequest("query_info")).leader or 0

        def reader(ci):
            ep2 = GenericEndpoint(ll_cluster.manager_addr)
            ep2.connect()
            drv2 = DriverClosedLoop(ep2, timeout=3.0)
            while not stop.is_set():
                t0 = time.monotonic()
                r = drv2.get("ll_hist")
                t1 = time.monotonic()
                if r.kind == "success":
                    val = r.result.value if r.result else None
                    ops.append(record_get(ci, "ll_hist", val, t0, t1))
                else:
                    drv2._failover(r)
                    time.sleep(0.05)
            try:
                ep2.leave()
            except Exception:
                pass

        threads = [
            _threading.Thread(target=reader, args=(10 + i,), daemon=True)
            for i in range(2)
        ]
        for t in threads:
            t.start()
        for seq in range(14):
            if seq == 5:
                # crash-restart the lease-holding leader mid-stream
                ep.ctrl.request(
                    CtrlRequest("reset_servers", servers=[leader]),
                    timeout=120,
                )
            val = f"w-{seq}"
            t0 = time.monotonic()
            rep = drv.put("ll_hist", val)
            t1 = time.monotonic()
            if rep.kind == "success":
                ops.append(record_put(0, "ll_hist", val, t0, t1, True))
            elif rep.kind in ("timeout", "failure", "disconnect"):
                ops.append(record_put(0, "ll_hist", val, t0, None, False))
                drv._failover(rep)
            time.sleep(0.25)
        deadline = time.monotonic() + 20
        while (
            sum(1 for o in ops if o.kind == "get") <= 8
            and time.monotonic() < deadline
        ):
            time.sleep(0.5)
        time.sleep(0.8)
        stop.set()
        for t in threads:
            t.join(timeout=15)
        ep.leave()
        reads = [o for o in ops if o.kind == "get"]
        assert len(reads) > 8, f"too few reads: {len(reads)}"
        ok, diag = check_history(ops)
        assert ok, diag


class TestClusterEPaxosMultiBucket:
    def test_mixed_key_batch_proposes_in_one_tick(self, ep_cluster):
        """Multi-bucket intake (dependency.rs:180-240 concurrency): a
        concurrent burst of puts to DIFFERENT key buckets is proposed in
        one tick — one vid per bucket in the same prop_vids list — not
        deferred bucket-by-bucket across ticks."""
        import threading as _threading

        from summerset_tpu.client.drivers import DriverClosedLoop
        from summerset_tpu.client.endpoint import GenericEndpoint

        # warm the path so the burst isn't absorbed by settling retries
        ep = GenericEndpoint(ep_cluster.manager_addr)
        ep.connect()
        DriverClosedLoop(ep).checked_put("mbwarm", "1")

        srv0 = next(iter(ep_cluster.replicas.values()))
        # pick keys in 4 distinct buckets via the server's OWN hash
        keys, want = [], 4
        i = 0
        while len(keys) < want:
            k = f"mb{i}"
            i += 1
            if srv0._key_bucket(k) not in {
                srv0._key_bucket(x) for x in keys
            }:
                keys.append(k)

        # record the per-tick proposed-vid counts on every replica
        seen: list = []

        def wrap(srv):
            orig = srv._intake_epaxos

            def wrapped(by_group, n_prop, vbase, piggy):
                r = orig(by_group, n_prop, vbase, piggy)
                nz = int((srv._ep_prop_vids != 0).sum())
                if nz:
                    seen.append(nz)
                return r

            srv._intake_epaxos = wrapped

        for srv in ep_cluster.replicas.values():
            wrap(srv)

        # pre-connect every endpoint so the burst threads only ISSUE the
        # put — connect-time skew on a loaded box would otherwise spread
        # the puts across ticks and void the same-tick assertion
        eps = []
        for _ in keys:
            e = GenericEndpoint(ep_cluster.manager_addr)
            e.connect()
            eps.append(e)

        def put(e, k):
            DriverClosedLoop(e).checked_put(k, f"v-{k}")
            e.leave()

        threads = [
            _threading.Thread(target=put, args=(e, k), daemon=True)
            for e, k in zip(eps, keys)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)

        drv = DriverClosedLoop(ep)
        for k in keys:
            r = drv.checked_get(k, expect=f"v-{k}")
            assert r.kind == "success"
        ep.leave()
        assert seen and max(seen) >= 2, (
            f"burst never proposed multiple buckets in one tick: {seen}"
        )
