"""Deliberately-broken protocol kernels: graftlint's negative paths.

Each kernel here violates exactly one rule of the machine-readable
kernel contract (``core/protocol.py KERNEL_CONTRACT``), so the test
suite can assert the verifier catches each violation with its expected
finding fingerprint — and nothing else.  None of these are registered
in the global protocol registry; :func:`make_fixture` is the
registry-shaped factory the analysis passes take.

``GoodKernel`` is the control: a minimal contract-clean kernel proving
the fixtures fail for their planted reason, not for boilerplate.
"""

from __future__ import annotations

from types import SimpleNamespace

import jax
import jax.numpy as jnp

from summerset_tpu.core import quorum as quorum_lib
from summerset_tpu.core.protocol import ProtocolKernel, StepEffects


class GoodKernel(ProtocolKernel):
    """Minimal contract-clean kernel: one flags-gated inbox fold."""

    name = "FixtureGood"
    DURABLE_SCALARS = ("commit_bar",)
    DURABLE_WINDOWS = ("win_val",)
    VALUE_WINDOW = "win_val"

    def init_state(self, seed: int = 0):
        G, R, W = self.G, self.R, self.W
        i32 = jnp.int32
        return {
            "commit_bar": jnp.zeros((G, R), i32),
            "exec_bar": jnp.zeros((G, R), i32),
            "win_val": jnp.zeros((G, R, W), i32),
        }

    def zero_outbox(self):
        G, R = self.G, self.R
        return {
            "flags": jnp.zeros((G, R, R), jnp.uint32),
            "data": jnp.zeros((G, R, R), jnp.int32),
        }

    def _fold(self, s, inbox):
        valid = (inbox["flags"] & jnp.uint32(1)) != 0
        best = jnp.max(jnp.where(valid, inbox["data"], 0), axis=2)
        s["commit_bar"] = jnp.maximum(s["commit_bar"], best)

    def step(self, state, inbox, inputs):
        s = dict(state)
        self._fold(s, inbox)
        s["exec_bar"] = s["commit_bar"]
        self._accumulate_telemetry(state, s, SimpleNamespace())
        return s, self.zero_outbox(), StepEffects(
            commit_bar=s["commit_bar"], exec_bar=s["exec_bar"]
        )


class UnflaggedInboxReadKernel(GoodKernel):
    """T1: folds the inbox data lane into state without a flags gate."""

    name = "FixtureUnflagged"

    def step(self, state, inbox, inputs):
        s = dict(state)
        self._fold(s, inbox)
        # the violation: raw lane max lands in a state leaf ungated
        s["shadow"] = jnp.max(inbox["data"], axis=2)
        s["exec_bar"] = s["commit_bar"]
        return s, self.zero_outbox(), StepEffects(
            commit_bar=s["commit_bar"], exec_bar=s["exec_bar"]
        )

    def init_state(self, seed: int = 0):
        st = super().init_state(seed)
        st["shadow"] = jnp.zeros((self.G, self.R), jnp.int32)
        return st


class UnflaggedEffectsKernel(GoodKernel):
    """T1: folds an ungated inbox lane into an effects output (the host
    serves effects to clients, so they are sinks like state)."""

    name = "FixtureUnflaggedEffects"

    def step(self, state, inbox, inputs):
        s = dict(state)
        self._fold(s, inbox)
        s["exec_bar"] = s["commit_bar"]
        return s, self.zero_outbox(), StepEffects(
            commit_bar=s["commit_bar"], exec_bar=s["exec_bar"],
            extra={"raw_peek": jnp.max(inbox["data"], axis=2)},
        )


class InvertedGateKernel(GoodKernel):
    """T1 (polarity): gates the inbox lane on a flags-DERIVED predicate
    but selects the lane in the dead-link branch —
    ``jnp.where(valid, 0, lane)`` — a gate with the right provenance and
    the wrong polarity.  A polarity-insensitive pass laundered this; the
    dead-world lattice catches it because ``valid`` is dead-world zero,
    so the dead case selects the lane."""

    name = "FixtureInvertedGate"

    def step(self, state, inbox, inputs):
        s = dict(state)
        self._fold(s, inbox)
        valid = (inbox["flags"] & jnp.uint32(1)) != 0
        # the violation: the fallback/lane arms are swapped, so the
        # dead-link (valid == False) case consumes the raw lane
        s["shadow"] = jnp.max(
            jnp.where(valid, 0, inbox["data"]), axis=2
        )
        s["exec_bar"] = s["commit_bar"]
        return s, self.zero_outbox(), StepEffects(
            commit_bar=s["commit_bar"], exec_bar=s["exec_bar"]
        )

    def init_state(self, seed: int = 0):
        st = super().init_state(seed)
        st["shadow"] = jnp.zeros((self.G, self.R), jnp.int32)
        return st


class RangeUnsoundKernel(GoodKernel):
    """R2: an author-claimed ceiling the transfer refutes.  The claim
    holds at ``init_state`` (commit_bar starts 0), but ``_fold`` maxes
    an unbounded inbox lane into commit_bar, so one abstract step from
    the claimed ``[0, 100]`` escapes to the dtype ceiling — the check
    is *inductiveness*, not just the init snapshot."""

    name = "FixtureRangeUnsound"
    RANGE_CLAIMS = (("commit_bar", 0, 100),)


class RangeEntangledKernel(GoodKernel):
    """The state-entangled gate only the interval prover clears: the
    dead-world select predicate ``bal > s["prep_bal"]`` compares a
    dead-world-known ``-1`` sentinel against a *state* leaf, so the
    flags polarity lattice alone cannot decide it — but the proven
    inductive invariant ``prep_bal >= 0`` does (``-1 > prep_bal`` is
    False in every reachable dead world).  With the range pass live the
    gate is a PROVEN clear; without it the same select is the legacy
    optimistic clearing — the pair of counters is the fixture's
    assertion surface."""

    name = "FixtureRangeEntangled"

    def init_state(self, seed: int = 0):
        st = super().init_state(seed)
        st["prep_bal"] = jnp.zeros((self.G, self.R), jnp.int32)
        return st

    def step(self, state, inbox, inputs):
        s = dict(state)
        valid = (inbox["flags"] & jnp.uint32(1)) != 0
        # dead world: valid is zero, so bal collapses to the -1 sentinel
        bal = jnp.max(jnp.where(valid, inbox["data"], -1), axis=2)
        payload = jnp.max(inbox["data"], axis=2)  # raw: stays tainted
        # the entangled gate: decidable only via prep_bal's invariant
        ok = bal > s["prep_bal"]
        s["commit_bar"] = jnp.where(ok, payload, s["commit_bar"])
        s["prep_bal"] = jnp.maximum(s["prep_bal"], bal)
        s["exec_bar"] = s["commit_bar"]
        return s, self.zero_outbox(), StepEffects(
            commit_bar=s["commit_bar"], exec_bar=s["exec_bar"]
        )


class StaleAllowKernel(GoodKernel):
    """T9: declares a suppression for a flow that never occurs."""

    name = "FixtureStaleAllow"
    TAINT_ALLOW = (
        ("data", "commit_bar", "declared but the flow is actually gated"),
    )


class FloatStateKernel(GoodKernel):
    """C2: a float32 leaf in protocol state."""

    name = "FixtureFloatState"

    def init_state(self, seed: int = 0):
        st = super().init_state(seed)
        st["score"] = jnp.zeros((self.G, self.R), jnp.float32)
        return st


class MissingFlagsKernel(GoodKernel):
    """C3: outbox without the uint32 flags pair-field."""

    name = "FixtureMissingFlags"

    def zero_outbox(self):
        G, R = self.G, self.R
        return {"data": jnp.zeros((G, R, R), jnp.int32)}

    def step(self, state, inbox, inputs):
        s = dict(state)
        s["exec_bar"] = s["commit_bar"]
        return s, self.zero_outbox(), StepEffects(
            commit_bar=s["commit_bar"], exec_bar=s["exec_bar"]
        )


class UndeclaredBroadcastKernel(GoodKernel):
    """C3: a [G, R_src, W] window lane not named in broadcast_lanes."""

    name = "FixtureUndeclaredBroadcast"

    def zero_outbox(self):
        out = super().zero_outbox()
        out["bw_extra"] = jnp.zeros(
            (self.G, self.R, self.W), jnp.int32
        )
        return out


class BogusDurableKernel(GoodKernel):
    """C5: DURABLE_WINDOWS names an array that is not a state leaf."""

    name = "FixtureBogusDurable"
    DURABLE_WINDOWS = ("win_val", "win_ghost")


class UndeclaredInputKernel(GoodKernel):
    """C10: an optional ``.get()``-style step-input read that
    EXTRA_INPUTS never declares — the honor-system gap: the trace sees
    no such input, so the branch silently drops from the verified
    surface instead of KeyError-ing like a direct subscript would."""

    name = "FixtureUndeclaredInput"

    def step(self, state, inbox, inputs):
        s = dict(state)
        self._fold(s, inbox)
        ghost = inputs.get("ghost_lane")  # the violation: undeclared
        if ghost is not None:
            s["commit_bar"] = s["commit_bar"] + ghost[:, None]
        s["exec_bar"] = s["commit_bar"]
        return s, self.zero_outbox(), StepEffects(
            commit_bar=s["commit_bar"], exec_bar=s["exec_bar"]
        )


class BrokenForwarderKernel(GoodKernel):
    """T1 (outbox sink): relays an inbox lane verbatim into an outbox
    lane without a flags gate — the ungated relay hop.  The receiver's
    own flags gate only vouches for ITS inbound link, so dead-link
    garbage from one partition upstream would transit this forwarder
    invisibly; making outbox leaves sinks is what catches it."""

    name = "FixtureBrokenForwarder"

    def step(self, state, inbox, inputs):
        s = dict(state)
        self._fold(s, inbox)
        s["exec_bar"] = s["commit_bar"]
        out = self.zero_outbox()
        # the violation: store-and-forward without the store (the
        # gated-window relay the real chain/push kernels do); raw
        # inbound bytes go straight back onto the wire
        out["data"] = jnp.swapaxes(inbox["data"], 1, 2)
        out["flags"] = jnp.full(
            (self.G, self.R, self.R), 1, jnp.uint32
        )
        return s, out, StepEffects(
            commit_bar=s["commit_bar"], exec_bar=s["exec_bar"]
        )


class AllowedForwarderKernel(BrokenForwarderKernel):
    """The same relay hop, declared: a TAINT_ALLOW entry naming the
    outbox sink suppresses the T1 (and is NOT stale, so no T9) —
    proving the allowlist covers ``outbox.*`` sinks like it covers
    state and effects."""

    name = "FixtureAllowedForwarder"
    TAINT_ALLOW = (
        ("data", "outbox.data",
         "fixture: deliberate relay lane, receiver re-validates"),
    )


class GoodCollectiveKernel(GoodKernel):
    """Control for the collective-tally rules: a per-source [G, R]
    tally lane reduced with an explicit mesh collective (``lax.psum``
    over the verifier-bound tally axis) INSIDE the quorum_tally phase
    scope, with the lane flags-gated per source — clean under both C6
    (collectives allowed in tally scope) and T1 (gate present)."""

    name = "FixtureGoodCollective"
    broadcast_lanes = frozenset({"tlane"})
    TALLY_LANES = ("tlane",)

    def zero_outbox(self):
        out = super().zero_outbox()
        out["tlane"] = jnp.zeros((self.G, self.R), jnp.int32)
        return out

    def _tally(self, s, inbox, gated: bool):
        contrib = inbox["tlane"]
        if gated:
            # a source's record counts only where some link from it was
            # alive this tick (flags zeroed per-link by the netmodel)
            valid_src = jnp.any((inbox["flags"] & jnp.uint32(1)) != 0,
                                axis=1)
            contrib = jnp.where(valid_src, contrib, 0)
        agg = jax.lax.psum(contrib, quorum_lib.TALLY_AXIS)
        s["commit_bar"] = jnp.maximum(
            s["commit_bar"], agg.sum(axis=1)[:, None]
        )

    def step(self, state, inbox, inputs):
        s = dict(state)
        self._fold(s, inbox)
        with quorum_lib.tally_scope():
            self._tally(s, inbox, gated=True)
        s["exec_bar"] = s["commit_bar"]
        return s, self.zero_outbox(), StepEffects(
            commit_bar=s["commit_bar"], exec_bar=s["exec_bar"]
        )


class UngatedCollectiveTallyKernel(GoodCollectiveKernel):
    """T1: the collective tally consumes the raw [G, R] tally lane with
    no flags-derived gate — dead-link garbage rides the psum into
    commit_bar.  The dead-world class propagates THROUGH the segmented
    reduction (psum of dead-zeros is zero, so no accidental clearing),
    and the lane's sources survive to the state sink."""

    name = "FixtureUngatedCollective"

    def step(self, state, inbox, inputs):
        s = dict(state)
        self._fold(s, inbox)
        with quorum_lib.tally_scope():
            self._tally(s, inbox, gated=False)  # the violation
        s["exec_bar"] = s["commit_bar"]
        return s, self.zero_outbox(), StepEffects(
            commit_bar=s["commit_bar"], exec_bar=s["exec_bar"]
        )


class CollectiveOutsideScopeKernel(GoodCollectiveKernel):
    """C6: the same (gated) collective tally OUTSIDE the quorum_tally
    phase scope — cross-replica aggregation anywhere else in a step is
    a sharding leak, sanctioned only inside the in-mesh tally plane."""

    name = "FixtureCollectiveOutsideScope"

    def step(self, state, inbox, inputs):
        s = dict(state)
        self._fold(s, inbox)
        self._tally(s, inbox, gated=True)  # the violation: no scope
        s["exec_bar"] = s["commit_bar"]
        return s, self.zero_outbox(), StepEffects(
            commit_bar=s["commit_bar"], exec_bar=s["exec_bar"]
        )


FIXTURES = {
    "fixturegood": GoodKernel,
    "fixturegoodcollective": GoodCollectiveKernel,
    "fixtureungatedcollective": UngatedCollectiveTallyKernel,
    "fixturecollectiveoutsidescope": CollectiveOutsideScopeKernel,
    "fixturebrokenforwarder": BrokenForwarderKernel,
    "fixtureallowedforwarder": AllowedForwarderKernel,
    "fixtureinvertedgate": InvertedGateKernel,
    "fixtureunflagged": UnflaggedInboxReadKernel,
    "fixtureunflaggedeffects": UnflaggedEffectsKernel,
    "fixturerangeunsound": RangeUnsoundKernel,
    "fixturerangeentangled": RangeEntangledKernel,
    "fixturestaleallow": StaleAllowKernel,
    "fixturefloatstate": FloatStateKernel,
    "fixturemissingflags": MissingFlagsKernel,
    "fixtureundeclaredbroadcast": UndeclaredBroadcastKernel,
    "fixturebogusdurable": BogusDurableKernel,
    "fixtureundeclaredinput": UndeclaredInputKernel,
}


def make_fixture(name: str, *args, **kwargs) -> ProtocolKernel:
    """Registry-shaped factory over the fixture kernels."""
    return FIXTURES[name.lower()](*args, **kwargs)
