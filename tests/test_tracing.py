"""graftscope tests: the flight-recorder ring (overflow + drop
accounting, taxonomy enforcement, enabled gating), the Chrome-trace
exporter (tx/rx pairing, clock alignment, request-chain stitching,
schema validation incl. its negative paths), and the nemesis repro
bundle carrying per-replica flight tails.
"""

import os
import sys
import threading

import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "scripts",
))

import trace_export  # noqa: E402

from summerset_tpu.host.telemetry import MetricsRegistry, SlotTraces  # noqa: E402
from summerset_tpu.host.tracing import (  # noqa: E402
    EVENT_TYPES,
    FlightRecorder,
)


# ------------------------------------------------------------- recorder ----
class TestFlightRecorder:
    def test_ring_overflow_drops_oldest_and_counts(self):
        fr = FlightRecorder(capacity=16, me=1)
        for i in range(100):
            fr.record("tick", tick=i)
        d = fr.dump()
        assert d["me"] == 1
        assert d["count"] == 100
        assert len(d["events"]) == 16
        assert d["dropped"] == 84
        # oldest dropped: the retained window is the NEWEST 16
        assert [ev["tick"] for ev in d["events"]] == list(range(84, 100))
        # stamps are monotone within the ring
        ts = [ev["t_us"] for ev in d["events"]]
        assert ts == sorted(ts)

    def test_last_n_trim_is_visible_as_dropped(self):
        fr = FlightRecorder(capacity=64)
        for i in range(10):
            fr.record("wal_append", sync=False)
        d = fr.dump(last_n=3)
        assert len(d["events"]) == 3
        assert d["count"] == 10 and d["dropped"] == 7

    def test_last_n_zero_means_metadata_only(self):
        """events[-0:] is ALL of them — last_n=0 must mean none (and
        tail(0) likewise), so a metadata-only scrape stays tiny."""
        fr = FlightRecorder(capacity=64)
        for i in range(10):
            fr.record("tick", tick=i)
        d = fr.dump(last_n=0)
        assert d["events"] == [] and d["dropped"] == 10
        assert fr.tail(0) == []

    def test_undeclared_event_type_fails_loudly(self):
        fr = FlightRecorder()
        with pytest.raises(KeyError):
            fr.record("not_an_event", x=1)
        assert set(EVENT_TYPES) >= {"api_ingress", "propose", "commit",
                                    "frame_tx", "frame_rx", "wal_fsync",
                                    "crash", "restart"}

    def test_disabled_recorder_is_a_noop(self):
        fr = FlightRecorder(enabled=False)
        fr.record("tick", tick=0)
        assert fr.dump()["count"] == 0
        fr.enabled = True
        fr.record("tick", tick=1)
        assert fr.dump()["count"] == 1

    def test_tail_renders_last_events(self):
        fr = FlightRecorder()
        for i in range(5):
            fr.record("commit", g=0, vid=i, slot=i, tick=i)
        lines = fr.tail(2)
        assert len(lines) == 2
        assert "commit" in lines[-1] and "vid=4" in lines[-1]

    def test_concurrent_writers_keep_accounting_consistent(self):
        fr = FlightRecorder(capacity=128)

        def hammer(n):
            for i in range(200):
                fr.record("frame_rx", peer=n, seq=i, nbytes=1)

        ts = [threading.Thread(target=hammer, args=(n,)) for n in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        d = fr.dump()
        assert d["count"] == 800
        assert len(d["events"]) == 128 and d["dropped"] == 672
        seqs = [ev["seq"] for ev in d["events"]]
        assert len(seqs) == 128  # no torn/partial records
        # stamps are taken INSIDE the ring lock, so the retained window
        # is stamp-ordered even under contention
        ts_ = [ev["t_us"] for ev in d["events"]]
        assert ts_ == sorted(ts_)


# --------------------------------------- per-type reserves + drop ledger ----
class TestDropAccounting:
    """v2 recorder: rare types survive floods via per-type reserve
    rings, and the per-type drop ledger always reconciles against the
    scalar drop count (the trace_export accounting gate)."""

    def test_reserve_keeps_rare_type_through_flood(self):
        fr = FlightRecorder(capacity=16, reserve_per_type=4)
        for _ in range(3):
            fr.record("crash", reason="nemesis")
        for i in range(500):
            fr.record("tick", tick=i)
        d = fr.dump()
        kinds = [ev["type"] for ev in d["events"]]
        # all 3 crash events washed out of the main ring long ago, yet
        # the dump still carries them (reserve union), oldest-first
        assert kinds.count("crash") == 3
        assert "crash" not in d["dropped_by_type"]
        ns = [ev["n"] for ev in d["events"]]
        assert ns == sorted(ns)

    def test_reserve_itself_overflows_honestly(self):
        fr = FlightRecorder(capacity=8, reserve_per_type=2)
        for i in range(10):
            fr.record("crash", reason=str(i))
        for i in range(100):
            fr.record("tick", tick=i)
        d = fr.dump()
        kinds = [ev["type"] for ev in d["events"]]
        assert kinds.count("crash") == 2  # reserve maxlen, not all 10
        assert d["dropped_by_type"]["crash"] == 8

    def test_ledger_reconciles_with_and_without_trim(self):
        fr = FlightRecorder(capacity=16, reserve_per_type=2)
        for i in range(40):
            fr.record("tick", tick=i)
        for i in range(40):
            fr.record("frame_tx", peer=0, seq=i, nbytes=1)
        for d in (fr.dump(), fr.dump(last_n=5), fr.dump(last_n=0)):
            assert sum(d["recorded_by_type"].values()) == d["count"]
            assert sum(d["dropped_by_type"].values()) == d["dropped"]
            retained = {}
            for ev in d["events"]:
                retained[ev["type"]] = retained.get(ev["type"], 0) + 1
            for t, rec in d["recorded_by_type"].items():
                assert rec - retained.get(t, 0) == \
                    d["dropped_by_type"].get(t, 0)

    def test_validate_dumps_passes_clean_and_catches_tamper(self):
        fr = FlightRecorder(capacity=16)
        for i in range(100):
            fr.record("tick", tick=i)
        d = fr.dump()
        assert trace_export.validate_dumps({0: d}) == []
        bad = dict(d)
        bad["dropped_by_type"] = {"tick": d["dropped"] - 1}
        errs = trace_export.validate_dumps({0: bad})
        assert errs and any("tick" in e for e in errs)

    def test_publish_drops_is_delta_cursored(self):
        fr = FlightRecorder(capacity=8, reserve_per_type=1, me=0)
        reg = MetricsRegistry()
        for i in range(20):
            fr.record("tick", tick=i)
        fr.publish_drops(reg)
        first = reg.counter_value("trace_dropped_total", type="tick")
        assert first > 0
        # no new drops -> repeated scrapes add nothing
        fr.publish_drops(reg)
        assert reg.counter_value(
            "trace_dropped_total", type="tick") == first
        for i in range(10):
            fr.record("tick", tick=i)
        fr.publish_drops(reg)
        assert reg.counter_value(
            "trace_dropped_total", type="tick") == first + 10

    def test_publish_drops_counts_reserve_survivors_as_retained(self):
        fr = FlightRecorder(capacity=8, reserve_per_type=4)
        for _ in range(4):
            fr.record("crash", reason="x")
        for i in range(50):
            fr.record("tick", tick=i)
        reg = MetricsRegistry()
        fr.publish_drops(reg)
        # every crash event still rides dumps via its reserve -> no
        # crash drops published, only the tick evictions
        assert reg.counter_value(
            "trace_dropped_total", type="crash") == 0
        assert reg.counter_value(
            "trace_dropped_total", type="tick") > 0


# ----------------------------------------------- SlotTraces lock regression
class TestSlotTracesLocking:
    def test_concurrent_marks_never_double_observe(self):
        """Regression for the `_open` locking hole: `mark_committed` /
        `mark_applied` used to read-modify `_open` without the lock
        while `maybe_start` could `clear()` it under the lock, so two
        racing markers could both see 'not yet committed' and
        double-feed the histogram.  All `_open` access now holds the
        lock: every sampled trace contributes EXACTLY one
        ticks_to_commit sample no matter how many threads mark it."""
        reg = MetricsRegistry()
        tr = SlotTraces(reg, sample_every=1)
        n_traces = 200
        for vid in range(1, n_traces + 1):
            tr.maybe_start(0, vid, tick=0, arrival_s=0.0)

        barrier = threading.Barrier(4)

        def mark_all():
            barrier.wait()
            for vid in range(1, n_traces + 1):
                tr.mark_committed(0, vid, tick=3)
                tr.mark_applied(0, vid, tick=4)

        threads = [threading.Thread(target=mark_all) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert reg.hist("ticks_to_commit").count == n_traces
        assert reg.hist("ticks_to_apply").count == n_traces

    def test_concurrent_start_and_mark_do_not_corrupt(self):
        """maybe_start's overflow clear() racing the markers: no
        exception, and the histograms only ever see samples from traces
        that were actually open."""
        reg = MetricsRegistry()
        tr = SlotTraces(reg, sample_every=1)
        stop = threading.Event()

        def starter():
            vid = 0
            while not stop.is_set():
                vid += 1
                tr.maybe_start(0, vid, tick=vid, arrival_s=0.0)

        def marker():
            vid = 0
            while not stop.is_set():
                vid += 1
                tr.mark_committed(0, vid, tick=vid + 1)
                tr.mark_replied(0, vid, now_s=1.0)

        threads = [threading.Thread(target=starter),
                   threading.Thread(target=marker)]
        for t in threads:
            t.start()
        import time
        time.sleep(0.3)
        stop.set()
        for t in threads:
            t.join()
        h = reg.hist("ticks_to_commit")
        assert h is None or h.count > 0  # survived; samples are sane

    def test_sampled_trace_carries_span_identity(self):
        """The span-builder promotion: a sampled trace records the
        representative (client, req_id) and, when a flight recorder is
        attached, logs the propose event that joins the request span to
        the slot span."""
        reg = MetricsRegistry()
        fr = FlightRecorder()
        tr = SlotTraces(reg, sample_every=1, flight=fr)
        tr.maybe_start(2, 9, tick=5, arrival_s=1.0, client=77, req_id=3)
        tr.mark_committed(2, 9, tick=7)
        tr.mark_applied(2, 9, tick=7)
        tr.mark_replied(2, 9, now_s=1.25)
        done = tr.sampled()
        assert done[0]["client"] == 77 and done[0]["req_id"] == 3
        ev = [e for e in fr.dump()["events"] if e["type"] == "propose"]
        assert ev and ev[0]["g"] == 2 and ev[0]["vid"] == 9
        assert ev[0]["client"] == 77 and ev[0]["req_id"] == 3


# ------------------------------------------------------------- exporter ----
def _dump(me, events, t0=1_000_000, protocol="MultiPaxos"):
    evs = []
    for i, (dt, etype, fields) in enumerate(events):
        evs.append({"n": i, "t_us": t0 + dt, "type": etype, **fields})
    return {
        "v": 1, "me": me, "t_start_us": t0, "count": len(evs),
        "dropped": 0, "t_dump_us": t0 + 10_000_000, "events": evs,
        "protocol": protocol, "tick": 100, "applied": [1],
        "device_lanes": {"commits": 1},
    }


def _two_server_dumps():
    """Server 0 proposes/commits/replies; frames flow 0->1 and 1->0."""
    d0 = _dump(0, [
        (0, "api_ingress", {"client": 9, "req_id": 1, "kind": "req"}),
        (10, "propose",
         {"g": 0, "vid": 4, "tick": 7, "client": 9, "req_id": 1}),
        (12, "frame_tx", {"peer": 1, "seq": 7, "nbytes": 100}),
        (30, "frame_rx", {"peer": 1, "seq": 6, "nbytes": 90}),
        (40, "wal_append", {"sync": False}),
        (55, "wal_fsync", {"dur_us": 10, "batch": 2}),
        (60, "commit", {"g": 0, "vid": 4, "slot": 0, "tick": 8}),
        (61, "apply", {"g": 0, "vid": 4, "slot": 0, "tick": 8}),
        (70, "api_reply", {"client": 9, "req_id": 1, "kind": "reply"}),
        (80, "tick",
         {"tick": 8, "intake": 5, "exchange": 10, "step": 20,
          "log": 3, "apply": 4}),
    ])
    d1 = _dump(1, [
        (5, "frame_tx", {"peer": 0, "seq": 6, "nbytes": 90}),
        (20, "frame_rx", {"peer": 0, "seq": 7, "nbytes": 100}),
        (65, "commit", {"g": 0, "vid": 4, "slot": 0, "tick": 9}),
        (90, "restart", {"wal_size": 0, "applied": 0}),
    ])
    return {"0": d0, "1": d1}


class TestExporter:
    def test_paired_frames_cross_replica(self):
        pairs = trace_export.paired_frames(_two_server_dumps())
        keys = {(p["src"], p["dst"], p["seq"]) for p in pairs}
        assert keys == {(0, 1, 7), (1, 0, 6)}
        for p in pairs:
            assert p["t_rx_us"] >= p["t_tx_us"] - 50  # same test clock

    def test_unpaired_frames_tolerated(self):
        """An ingress-dropped frame leaves its tx unmatched — pairing
        must not desync the later frames (seq pairing, not counting)."""
        dumps = _two_server_dumps()
        # server 1 never received seq 7 (drop); a later seq 8 still pairs
        dumps["1"]["events"] = [
            ev for ev in dumps["1"]["events"]
            if not (ev["type"] == "frame_rx" and ev["seq"] == 7)
        ]
        dumps["0"]["events"].append(
            {"t_us": 1_000_100, "type": "frame_tx",
             "peer": 1, "nbytes": 10, "seq": 8},
        )
        dumps["1"]["events"].append(
            {"t_us": 1_000_120, "type": "frame_rx",
             "peer": 0, "nbytes": 10, "seq": 8},
        )
        pairs = trace_export.paired_frames(dumps)
        keys = {(p["src"], p["dst"], p["seq"]) for p in pairs}
        assert (0, 1, 8) in keys and (0, 1, 7) not in keys

    def test_stale_incarnation_rx_not_paired(self):
        """A crash-restarted sender resets its tick counter, reusing
        wire seqs; the peer's ring still holds the OLD incarnation's rx
        for those seqs.  Pairing them would mint rx-before-tx pairs and
        drive the clock-offset minima negative by the restart gap — the
        sender's recorder birth stamp (t_start_us) is the guard."""
        # victim (server 0) restarted at t=5_000_000: fresh ring, fresh
        # recorder, tx seq 3 REUSED from its previous incarnation
        d0 = _dump(0, [
            (10, "restart", {"cold": False, "wal_size": 4, "applied": 2}),
            (100, "frame_tx", {"peer": 1, "seq": 3, "nbytes": 50}),
        ], t0=5_000_000)
        # peer (server 1) never restarted: its ring holds BOTH the old
        # incarnation's rx of seq 3 (t=1_000_040, before the victim's
        # rebirth) and the new one (t=5_000_150)
        d1 = _dump(1, [
            (40, "frame_rx", {"peer": 0, "seq": 3, "nbytes": 50}),
            (4_000_150, "frame_rx", {"peer": 0, "seq": 3, "nbytes": 50}),
        ], t0=1_000_000)
        pairs = trace_export.paired_frames({"0": d0, "1": d1})
        assert len(pairs) == 1
        assert pairs[0]["t_rx_us"] == 5_000_150
        assert pairs[0]["t_rx_us"] >= pairs[0]["t_tx_us"]
        # and the offsets stay sane (shared clock => ~0), instead of
        # being dragged negative by a bogus cross-incarnation pair
        offs = trace_export.clock_offsets({"0": d0, "1": d1})
        assert all(abs(o) < 1_000 for o in offs.values())

    def test_find_request_chains_connects_all_stages(self):
        chains = trace_export.find_request_chains(_two_server_dumps())
        assert len(chains) == 1
        c = chains[0]
        assert (c["client"], c["req_id"], c["g"], c["vid"]) == (9, 1, 0, 4)
        assert (c["t_ingress_us"] <= c["t_propose_us"]
                <= c["t_commit_us"] <= c["t_apply_us"]
                <= c["t_reply_us"])

    def test_reused_req_id_pairs_by_occurrence(self):
        """(client, req_id) is NOT unique across a session — driver
        instances restart req ids at 0 on one shared endpoint.  A
        first-ingress/last-reply join would fuse two different requests
        into one fictitious multi-second span; occurrence pairing keeps
        each request's own ingress→reply window and the chain must bind
        to the occurrence enclosing its slot's propose→apply."""
        d0 = _dump(0, [
            # occurrence 1 of (9, 0): a whole earlier request
            (0, "api_ingress", {"client": 9, "req_id": 0, "kind": "req"}),
            (50, "api_reply", {"client": 9, "req_id": 0, "kind": "reply"}),
            # occurrence 2 of the SAME key: the sampled request
            (1_000, "api_ingress",
             {"client": 9, "req_id": 0, "kind": "req"}),
            (1_010, "propose",
             {"g": 0, "vid": 4, "tick": 7, "client": 9, "req_id": 0}),
            (1_060, "commit", {"g": 0, "vid": 4, "slot": 0, "tick": 8}),
            (1_061, "apply", {"g": 0, "vid": 4, "slot": 0, "tick": 8}),
            (1_070, "api_reply",
             {"client": 9, "req_id": 0, "kind": "reply"}),
        ])
        dumps = {"0": d0}
        chains = trace_export.find_request_chains(dumps)
        assert len(chains) == 1
        c = chains[0]
        # the chain's span is occurrence 2's own window, not a stitch of
        # occurrence 1's ingress with occurrence 2's reply
        assert c["t_reply_us"] - c["t_ingress_us"] == 70
        # the export emits one req span PER occurrence, distinct ids
        doc = trace_export.export_chrome(dumps)
        assert trace_export.validate_chrome(doc) == []
        req_b = [e for e in doc["traceEvents"]
                 if e.get("cat") == "req" and e["ph"] == "b"]
        assert len(req_b) == 2
        assert len({e["id"] for e in req_b}) == 2

    def test_chain_requires_every_stage(self):
        dumps = _two_server_dumps()
        dumps["0"]["events"] = [
            ev for ev in dumps["0"]["events"] if ev["type"] != "commit"
        ]
        assert trace_export.find_request_chains(dumps) == []

    def test_export_is_schema_valid(self):
        doc = trace_export.export_chrome(_two_server_dumps())
        assert trace_export.validate_chrome(doc) == []
        evs = doc["traceEvents"]
        # one process per replica, named plane tracks
        names = {
            (e["pid"], e["args"]["name"])
            for e in evs if e["ph"] == "M" and e["name"] == "thread_name"
        }
        assert (0, "device scan") in names and (1, "transport") in names
        # request span pair + slot span pair + fsync X span all present
        phs = [e["ph"] for e in evs]
        assert phs.count("b") == phs.count("e") >= 2
        assert any(
            e["ph"] == "X" and e["name"] == "fsync (group commit)"
            for e in evs
        )
        # the step stage exports as the device scan tick span
        assert any(
            e["ph"] == "X" and e["name"] == "device scan tick"
            for e in evs
        )
        # flow arrows pair across pids
        flows = [e for e in evs if e["ph"] in ("s", "f")]
        assert flows and len(
            [e for e in flows if e["ph"] == "s"]
        ) == len([e for e in flows if e["ph"] == "f"])

    def test_clock_offsets_align_skewed_server(self):
        dumps = _two_server_dumps()
        # shift server 1's monotonic base by +1s: offsets must recover
        # roughly -1s for it (NTP midpoint over the two directions)
        for ev in dumps["1"]["events"]:
            ev["t_us"] += 1_000_000
        offs = trace_export.clock_offsets(dumps)
        assert offs[0] == 0
        assert -1_000_100 <= offs[1] <= -999_900
        doc = trace_export.export_chrome(dumps)
        assert trace_export.validate_chrome(doc) == []

    def test_validate_rejects_unmatched_span_end(self):
        doc = {"traceEvents": [
            {"ph": "E", "name": "x", "pid": 0, "tid": 0, "ts": 5},
        ]}
        errors = trace_export.validate_chrome(doc)
        assert any("without matching B" in e for e in errors)
        doc = {"traceEvents": [
            {"ph": "b", "cat": "req", "id": "r1", "name": "x",
             "pid": 0, "tid": 0, "ts": 5},
        ]}
        errors = trace_export.validate_chrome(doc)
        assert any("unmatched async b" in e for e in errors)

    def test_validate_rejects_non_monotone_stamps(self):
        doc = {"traceEvents": [
            {"ph": "i", "s": "t", "name": "a", "pid": 0, "tid": 0,
             "ts": 10},
            {"ph": "i", "s": "t", "name": "b", "pid": 0, "tid": 0,
             "ts": 3},
        ]}
        errors = trace_export.validate_chrome(doc)
        assert any("non-monotone" in e for e in errors)

    def test_validate_rejects_negative_duration(self):
        doc = {"traceEvents": [
            {"ph": "X", "name": "a", "pid": 0, "tid": 0, "ts": 1,
             "dur": -5},
        ]}
        assert any(
            "negative dur" in e
            for e in trace_export.validate_chrome(doc)
        )


# ---------------------------------------------------- nemesis repro bundle
def test_fail_bundle_carries_flight_tails():
    """A nemesis soak failure bundle includes per-replica flight tails
    alongside the seed + timeline + history (the run collects
    result['flight'] via NemesisRunner.flight_tails before teardown)."""
    import nemesis_soak

    from summerset_tpu.host.nemesis import FaultPlan
    from summerset_tpu.utils.linearize import record_put

    plan = FaultPlan.generate(1, 3, 40)
    ops = [record_put(0, "k", "v", 0.0, 1.0, True)]

    class StubRunner:
        executed = [(3, "@00003 crash targets=[1]")]

    fr = FlightRecorder(me=1)
    fr.record("crash", error="injected")
    result = {
        "ok": False, "seed": 1, "error": "injected assertion",
        "flight": {"1": fr.dump()},
    }
    doc = nemesis_soak.fail_bundle_doc(result, plan, StubRunner(), ops)
    assert doc["timeline"].startswith("# FaultPlan v1 seed=1")
    assert doc["executed"] and doc["history"][0]["key"] == "k"
    tails = doc["flight"]
    assert tails["1"]["events"][0]["type"] == "crash"
    assert "dropped" in tails["1"]
