"""Unit tests for the utils layer (parity with reference inline mod tests)."""

import asyncio
import dataclasses
import math

import pytest

from summerset_tpu.utils import (
    Bitmap,
    KeyRangeMap,
    LinearRegressor,
    PerfModel,
    QdiscInfo,
    RespondersConf,
    SummersetError,
    Timer,
    parsed_config,
)
from summerset_tpu.utils.config import config_to_str


# ---------------------------------------------------------------- bitmap ----
class TestBitmap:
    def test_set_get_count(self):
        bm = Bitmap(5)
        assert bm.count() == 0
        bm.set(0)
        bm.set(3)
        assert bm.get(0) and bm.get(3) and not bm.get(1)
        assert bm.count() == 2

    def test_ones_flip_union(self):
        bm = Bitmap(4, ones=True)
        assert bm.count() == 4
        bm.flip()
        assert bm.count() == 0
        other = Bitmap.from_ids(4, [1, 2])
        bm.union(other)
        assert sorted(bm.iter_ones()) == [1, 2]

    def test_bounds(self):
        bm = Bitmap(3)
        with pytest.raises(SummersetError):
            bm.set(3)
        with pytest.raises(SummersetError):
            Bitmap(0)

    def test_u32_roundtrip(self):
        bm = Bitmap.from_ids(7, [0, 2, 6])
        assert Bitmap.from_u32(7, bm.to_u32()) == bm

    def test_device_helpers(self):
        import jax.numpy as jnp

        from summerset_tpu.utils.bitmap import bit_get, bit_set, popcount

        lane = jnp.zeros((4,), jnp.uint32)
        lane = bit_set(lane, jnp.array([0, 1, 2, 3]))
        assert popcount(lane).tolist() == [1, 1, 1, 1]
        lane = bit_set(lane, jnp.array([3, 3, 3, 3]))
        assert popcount(lane).tolist() == [2, 2, 2, 1]
        assert bit_get(lane, 3).tolist() == [True, True, True, True]
        assert bit_get(lane, 0).tolist() == [True, False, False, False]


# ---------------------------------------------------------------- config ----
@dataclasses.dataclass
class _Cfg:
    batch_interval_ms: float = 1.0
    max_batch_size: int = 5000
    logger_sync: bool = False
    backer_path: str = "/tmp/x.wal"


class TestConfig:
    def test_defaults(self):
        cfg = parsed_config(_Cfg, None)
        assert cfg.max_batch_size == 5000

    def test_overrides_plus_sep(self):
        cfg = parsed_config(_Cfg, "max_batch_size=10+logger_sync=true+backer_path='/a'")
        assert cfg.max_batch_size == 10
        assert cfg.logger_sync is True
        assert cfg.backer_path == "/a"
        assert cfg.batch_interval_ms == 1.0

    def test_int_to_float_coercion(self):
        cfg = parsed_config(_Cfg, "batch_interval_ms=2")
        assert cfg.batch_interval_ms == 2.0

    def test_unknown_field_rejected(self):
        with pytest.raises(SummersetError):
            parsed_config(_Cfg, "nope=1")

    def test_type_mismatch_rejected(self):
        with pytest.raises(SummersetError):
            parsed_config(_Cfg, "max_batch_size='abc'")
        with pytest.raises(SummersetError):
            parsed_config(_Cfg, "logger_sync=3")

    def test_roundtrip(self):
        cfg = parsed_config(_Cfg, "max_batch_size=7")
        cfg2 = parsed_config(_Cfg, config_to_str(cfg))
        assert cfg2 == cfg

    def test_plus_inside_quoted_value(self):
        cfg = parsed_config(_Cfg, "backer_path='/tmp/run+1/x.wal'+max_batch_size=9")
        assert cfg.backer_path == "/tmp/run+1/x.wal"
        assert cfg.max_batch_size == 9
        # and the roundtrip survives it
        assert parsed_config(_Cfg, config_to_str(cfg)) == cfg


# -------------------------------------------------------------- keyrange ----
class TestKeyRange:
    def test_full_and_point_lookup(self):
        m = KeyRangeMap()
        m.full_range("all")
        assert m.get("anything") == "all"
        m.insert("b", "d", "mid")
        assert m.get("a") == "all"
        assert m.get("b") == "mid"
        assert m.get("c") == "mid"
        assert m.get("d") == "all"

    def test_overwrite_splits(self):
        m = KeyRangeMap()
        m.insert("a", "z", 1)
        m.insert("f", "h", 2)
        assert m.get("e") == 1
        assert m.get("f") == 2
        assert m.get("g") == 2
        assert m.get("h") == 1
        assert m.get("z") is None

    def test_unbounded_end(self):
        m = KeyRangeMap()
        m.insert("m", None, "hi")
        assert m.get("zzz") == "hi"
        assert m.get("a") is None
        m.insert("p", "q", "mid")
        assert m.get("o") == "hi"
        assert m.get("p") == "mid"
        assert m.get("q") == "hi"

    def test_responders_conf(self):
        rc = RespondersConf(5)
        rc.set_leader(1)
        assert rc.is_leader(1) and not rc.is_leader(0)
        rc.set_responders(("a", "m"), Bitmap.from_ids(5, [1, 2]))
        assert rc.is_responder_by_key("b", 2)
        assert not rc.is_responder_by_key("z", 2)
        with pytest.raises(SummersetError):
            rc.set_responders(None, Bitmap.from_ids(4, [0]))


# ----------------------------------------------------------------- timer ----
class TestTimer:
    def test_kickoff_explode(self):
        async def run():
            t = Timer()
            t.kickoff(0.05)
            assert not t.exploded
            await asyncio.sleep(0.1)
            assert t.exploded

        asyncio.run(run())

    def test_cancel_prevents(self):
        async def run():
            t = Timer()
            t.kickoff(0.05)
            t.cancel()
            await asyncio.sleep(0.1)
            assert not t.exploded

        asyncio.run(run())

    def test_restart_resets(self):
        async def run():
            t = Timer()
            t.kickoff(0.08)
            await asyncio.sleep(0.05)
            t.kickoff(0.08)
            await asyncio.sleep(0.05)
            assert not t.exploded
            await asyncio.sleep(0.06)
            assert t.exploded

        asyncio.run(run())

    def test_extend_adds_to_deadline(self):
        async def run():
            t = Timer()
            t.kickoff(0.06)
            await asyncio.sleep(0.01)
            t.extend(0.05)  # deadline now ~0.11 from start
            await asyncio.sleep(0.07)
            assert not t.exploded
            await asyncio.sleep(0.05)
            assert t.exploded

        asyncio.run(run())

    def test_callback(self):
        fired = []

        async def run():
            t = Timer(explode_callback=lambda: fired.append(1))
            t.kickoff(0.03)
            await asyncio.sleep(0.08)

        asyncio.run(run())
        assert fired == [1]


# ---------------------------------------------------------------- linreg ----
class TestLinReg:
    def test_perfect_fit(self):
        lr = LinearRegressor()
        for x in range(10):
            lr.append_sample(float(x), float(x), 3.0 + 2.0 * x)
        alpha, beta = lr.calc_model()
        assert alpha == pytest.approx(3.0)
        assert beta == pytest.approx(2.0)
        pm = PerfModel()
        pm.update(alpha, beta)
        assert pm.predict(10.0) == pytest.approx(23.0)

    def test_underdetermined(self):
        lr = LinearRegressor()
        assert lr.calc_model() is None
        lr.append_sample(0.0, 1.0, 1.0)
        assert lr.calc_model() is None

    def test_discard(self):
        lr = LinearRegressor()
        for x in range(5):
            lr.append_sample(float(x), float(x), float(x))
        lr.discard_before(3.0)
        assert len(lr._samples) == 2


# ----------------------------------------------------------------- qdisc ----
class TestQdisc:
    def test_parse_netem(self):
        out = (
            "qdisc netem 8001: root refcnt 2 limit 1000 "
            "delay 25ms 5ms rate 10Gbit\n"
        )
        qi = QdiscInfo()
        assert qi.parse_output(out)
        assert qi.delay_ms == pytest.approx(25.0)
        assert qi.jitter_ms == pytest.approx(5.0)
        assert qi.rate_gbps == pytest.approx(10.0)

    def test_parse_absent(self):
        qi = QdiscInfo()
        assert not qi.parse_output("qdisc mq 0: root\n")


# --------------------------------------------------------------- safetcp ----
class TestSafeTcp:
    def test_roundtrip(self):
        from summerset_tpu.utils.safetcp import recv_msg, send_msg

        async def run():
            got = []
            done = asyncio.Event()

            async def handler(reader, writer):
                got.append(await recv_msg(reader))
                await send_msg(writer, {"reply": got[0]})
                done.set()

            server = await asyncio.start_server(handler, "127.0.0.1", 0)
            port = server.sockets[0].getsockname()[1]
            reader, writer = await asyncio.open_connection("127.0.0.1", port)
            await send_msg(writer, ("put", "k", "v" * 1000))
            reply = await recv_msg(reader)
            await done.wait()
            writer.close()
            server.close()
            return got, reply

        got, reply = asyncio.run(run())
        assert got == [("put", "k", "v" * 1000)]
        assert reply == {"reply": ("put", "k", "v" * 1000)}

    def test_sync_timeout_at_frame_boundary_is_retryable(self):
        import socket as _socket

        from summerset_tpu.utils.safetcp import recv_msg_sync

        a, b = _socket.socketpair()
        try:
            a.settimeout(0.05)
            # nothing sent: zero bytes consumed -> socket.timeout (the
            # retry-in-place TIMEOUT kind in client/drivers.py)
            with pytest.raises(_socket.timeout):
                recv_msg_sync(a)
        finally:
            a.close()
            b.close()

    def test_sync_timeout_mid_frame_is_fatal(self):
        import socket as _socket

        from summerset_tpu.utils.errors import SummersetError
        from summerset_tpu.utils.safetcp import encode_frame, recv_msg_sync

        a, b = _socket.socketpair()
        try:
            a.settimeout(0.1)
            frame = encode_frame({"k": "v" * 100})
            b.sendall(frame[: len(frame) - 7])  # truncated mid-body
            # partial bytes consumed -> the stream is no longer
            # frame-aligned; must NOT surface as a retryable timeout
            with pytest.raises(SummersetError):
                recv_msg_sync(a)
        finally:
            a.close()
            b.close()
