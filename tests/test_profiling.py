"""graftprof tests: the kernel phase registry (declarations resolve,
named scopes land in the traced jaxpr, the scope-ablated variant still
satisfies the kernel contract), the HLO phase-attribution parsers, the
perf_gate strict-analytic vs variance-aware-wall-clock split (incl. the
re-measure escalation), and the device-phase merge into the graftscope
Chrome trace (schema-gated via validate_chrome).
"""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "scripts",
))

import trace_export  # noqa: E402

from summerset_tpu import protocols  # noqa: E402
from summerset_tpu.analysis import contract  # noqa: E402
from summerset_tpu.analysis.contract import (  # noqa: E402
    build_kernel, trace_step,
)
from summerset_tpu.core.protocol import (  # noqa: E402
    PHASE_SCOPE_PREFIX,
    phase_scopes_enabled,
    set_phase_scopes,
)
from summerset_tpu.host import profiling  # noqa: E402


def _scoped_phases(kernel):
    """Phase names whose named scope actually appears in the traced
    step jaxpr's name stacks."""
    closed, *_ = trace_step(kernel)
    stacks = {str(e.source_info.name_stack) for e in closed.jaxpr.eqns}
    return {
        ph for ph, _ in kernel.PHASES
        if any(PHASE_SCOPE_PREFIX + ph in s for s in stacks)
    }


# ------------------------------------------------------ phase registry ----
class TestPhaseRegistry:
    @pytest.mark.parametrize("name", protocols.protocol_names())
    def test_every_kernel_declares_resolvable_phases(self, name):
        k = build_kernel(protocols.make_protocol, name)
        assert len(k.PHASES) >= 1, f"{name}: no declared phases"
        names = [ph for ph, _ in k.PHASES]
        assert len(set(names)) == len(names), f"{name}: duplicate phase"
        for ph, meth in k.PHASES:
            assert callable(getattr(k, meth, None)), (
                f"{name}: phase {ph!r} method {meth!r} does not resolve"
            )

    @pytest.mark.parametrize("name", protocols.protocol_names())
    def test_declared_phases_appear_as_named_scopes(self, name):
        """Every declared phase's scope shows up in the traced jaxpr
        (union over both config variants: a phase may compile to zero
        equations in one variant, e.g. repnothing's bar advance with
        exec_follows_commit on), and no UNdeclared graftphase scope
        exists — the registry is the single source of phase names."""
        k = build_kernel(protocols.make_protocol, name)
        declared = {ph for ph, _ in k.PHASES}
        seen = _scoped_phases(k)
        if contract.host_variant_differs(k):
            seen |= _scoped_phases(
                build_kernel(protocols.make_protocol, name, "host")
            )
        assert seen == declared, (
            f"{name}: declared={sorted(declared)} scoped={sorted(seen)}"
        )

    def test_scope_ablation_still_satisfies_kernel_contract(self):
        """The profiling ablation (phase scopes compiled away) is still
        a contract-clean kernel: C1-C10 and the flags-taint pass hold
        for the scope-free variant too."""
        from summerset_tpu.analysis import (
            verify_kernel, verify_kernel_taint,
        )

        assert phase_scopes_enabled()
        set_phase_scopes(False)
        # the verifier caches traces by (class, geometry, config) —
        # drop them so the scope-free variant actually re-traces
        contract._TRACE_CACHE.clear()
        try:
            for name in ("multipaxos", "raft", "chainrep"):
                res = verify_kernel(protocols.make_protocol, name)
                assert res.ok, (name, [f.render() for f in res.findings])
                res = verify_kernel_taint(protocols.make_protocol, name)
                assert res.ok, (name, [f.render() for f in res.findings])
            k = build_kernel(protocols.make_protocol, "multipaxos")
            assert not _scoped_phases(k), "ablation left scopes behind"
        finally:
            set_phase_scopes(True)
            contract._TRACE_CACHE.clear()

    def test_step_semantics_identical_with_and_without_scopes(self):
        """named_scope is metadata only: the ablated step computes the
        byte-identical state (the A/B overhead gate compares equals)."""
        k = build_kernel(protocols.make_protocol, "multipaxos")
        state = k.init_state(seed=0)
        inbox = k.zero_outbox()
        inputs = contract.build_inputs(k)
        s_on, out_on, _ = k.step(state, inbox, inputs)
        set_phase_scopes(False)
        try:
            s_off, out_off, _ = k.step(state, inbox, inputs)
        finally:
            set_phase_scopes(True)
        for key in s_on:
            assert (s_on[key] == s_off[key]).all(), key
        for key in out_on:
            assert (out_on[key] == out_off[key]).all(), key


# ------------------------------------------------- attribution parsers ----
_FAKE_HLO = """\
HloModule jit_tick_abc123, entry_computation_layout={()->()}

%fused_a (p: s32[4]) -> s32[4] {
  %p = s32[4] parameter(0)
  %m = s32[4] multiply(%p, %p), metadata={op_name="jit(f)/jit(main)/graftphase__ingest_accept/mul"}
  ROOT %a = s32[4] add(%m, %p), metadata={op_name="jit(f)/jit(main)/graftphase__ingest_accept/add"}
}

ENTRY %main () -> s32[4] {
  %x = s32[4] parameter(0)
  %fusion.1 = s32[4] fusion(%x), kind=kLoop, calls=%fused_a, metadata={op_name="jit(f)/jit(main)/graftphase__ingest_accept/add"}
  %sel = s32[4] select(%x, %x, %fusion.1), metadata={op_name="jit(f)/jit(main)/graftphase__election/select_n"}
  ROOT %out = s32[4] copy(%sel)
}
"""


class TestHloAttribution:
    def test_hlo_phase_ops_counts_per_phase(self):
        total, per_phase = profiling.hlo_phase_ops(_FAKE_HLO)
        assert total == 7
        assert per_phase == {"ingest_accept": 3, "election": 1}

    def test_op_phase_map_and_event_attribution(self):
        module, opmap = profiling.hlo_op_phase_map(_FAKE_HLO)
        assert module == "jit_tick_abc123"
        assert opmap["fusion.1"] == "ingest_accept"
        assert opmap["sel"] == "election"
        events = [
            {"ph": "X", "dur": 10.0,
             "args": {"hlo_op": "fusion.1",
                      "hlo_module": "jit_tick_abc123"}},
            {"ph": "X", "dur": 4.0,
             "args": {"hlo_op": "sel",
                      "hlo_module": "jit_tick_abc123"}},
            {"ph": "X", "dur": 2.0,
             "args": {"hlo_op": "out",
                      "hlo_module": "jit_tick_abc123"}},
            # other module: skipped
            {"ph": "X", "dur": 99.0,
             "args": {"hlo_op": "fusion.1", "hlo_module": "other"}},
            # not a complete event: skipped
            {"ph": "i", "dur": 99.0, "args": {"hlo_op": "sel"}},
        ]
        acc = profiling.attribute_trace_events(
            events, opmap, module="jit_tick_abc123"
        )
        assert acc == {
            "ingest_accept": 10.0, "election": 4.0, "unattributed": 2.0,
        }

    def test_real_tick_compile_attributes_every_heavy_phase(self):
        """End-to-end on a tiny real kernel: the compiled tick's HLO
        carries per-phase op counts for the load-bearing phases."""
        block = profiling.analytic_block(
            build_kernel(protocols.make_protocol, "multipaxos")
        )
        by_phase = block["analytic"]["hlo_ops_by_phase"]
        for ph in ("ingest_accept", "build_outbox", "election"):
            assert by_phase.get(ph, 0) > 0, (ph, by_phase)
        assert block["analytic"]["hlo_instructions"] > sum(
            by_phase.values()
        ) * 0.5
        assert block["memory"]["argument_bytes"] > 0


# ------------------------------------------------------- perf_gate logic ----
def _cell(s_per_tick=1e-4, ok=True):
    return {
        "protocol": "multipaxos", "variant": "device",
        "shape": {"G": 2, "R": 3, "W": 8, "P": 1},
        "phases": ["a"],
        "analytic": {"flops": 10.0, "hlo_instructions": 5,
                     "hlo_ops_by_phase": {"a": 3}},
        "memory": {"argument_bytes": 64},
        "ok": ok,
        "wall": {"s_per_tick": s_per_tick, "ticks": 8, "reps": 1,
                 "committed_slots_per_s": 100.0},
    }


class TestPerfGateLogic:
    def test_analytic_drift_detected(self, monkeypatch):
        import perf_gate

        committed = _cell()
        drifted = json.loads(json.dumps(committed))
        drifted["analytic"]["flops"] = 11.0
        monkeypatch.setattr(
            perf_gate.profiling, "profile_cell",
            lambda *a, **k: drifted,
        )
        errors = []
        perf_gate.check_analytic_cell(committed, errors)
        assert len(errors) == 1 and "analytic" in errors[0]

    def test_analytic_match_passes(self, monkeypatch):
        import perf_gate

        committed = _cell()
        monkeypatch.setattr(
            perf_gate.profiling, "profile_cell",
            lambda *a, **k: json.loads(json.dumps(committed)),
        )
        errors = []
        perf_gate.check_analytic_cell(committed, errors)
        assert errors == []

    def test_wall_within_tolerance_passes_first_round(self, monkeypatch):
        import perf_gate

        calls = []
        monkeypatch.setattr(
            perf_gate, "wall_measure",
            lambda c, t, r: calls.append(1) or 1.2e-4,
        )
        errors, notes = [], []
        perf_gate.check_wall_cell(_cell(), 0.5, 3, errors, notes)
        assert errors == [] and len(calls) == 1

    def test_wall_regression_escalates_then_fails(self, monkeypatch):
        import perf_gate

        calls = []
        monkeypatch.setattr(
            perf_gate, "wall_measure",
            lambda c, t, r: calls.append(1) or 5e-4,
        )
        errors, notes = [], []
        perf_gate.check_wall_cell(_cell(), 0.5, 3, errors, notes)
        assert len(calls) == 3, "no re-measure escalation"
        assert len(errors) == 1 and "regressed" in errors[0]

    def test_wall_escalation_recovers_on_quieter_round(self, monkeypatch):
        import perf_gate

        seq = iter([5e-4, 1.1e-4])
        monkeypatch.setattr(
            perf_gate, "wall_measure", lambda c, t, r: next(seq)
        )
        errors, notes = [], []
        perf_gate.check_wall_cell(_cell(), 0.5, 3, errors, notes)
        assert errors == [], "best-of escalation must win over noise"

    def test_wall_improvement_notes_not_fails(self, monkeypatch):
        import perf_gate

        monkeypatch.setattr(
            perf_gate, "wall_measure", lambda c, t, r: 0.2e-4
        )
        errors, notes = [], []
        perf_gate.check_wall_cell(_cell(), 0.5, 3, errors, notes)
        assert errors == []
        assert notes and "IMPROVED" in notes[0]

    def test_committed_profile_reproduces(self):
        """The real committed PROFILE.json is structurally complete:
        all 3 protocols x both variants with per-phase wall breakdown +
        analytic + memory + compile blocks (the acceptance shape)."""
        path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "PROFILE.json",
        )
        with open(path) as f:
            doc = json.load(f)
        assert set(doc["protocols"]) >= {"MultiPaxos", "Raft", "RSPaxos"}
        for proto, per in doc["protocols"].items():
            assert set(per) == {"device", "host"}, proto
            for variant, cell in per.items():
                where = f"{proto}[{variant}]"
                assert cell["ok"], where
                assert cell["analytic"]["hlo_instructions"] > 0, where
                assert cell["analytic"]["hlo_ops_by_phase"], where
                assert cell["memory"]["argument_bytes"] > 0, where
                assert cell["compile"]["tick_compile_s"] >= 0, where
                assert cell["wall"]["committed_slots_per_s"] > 0, where
                if doc.get("profiler_available"):
                    pw = cell["phase_wall_us_per_tick"]
                    assert pw and any(
                        k != "unattributed" and v > 0
                        for k, v in pw.items()
                    ), where
        assert doc["scope_overhead"]["pct"] < 5.0
        # the pod-scale mesh trajectory is committed and self-judging:
        # at least one genuinely multi-device point, every point donated
        ms = doc["mesh_sweep"]
        assert any(p["devices"] > 1 for p in ms["points"])
        for p in ms["points"]:
            assert p["ok"] and p["donated"], p["mesh"]
            assert p["donation"]["aliased_buffers"] == \
                p["donation"]["carry_leaves"], p["mesh"]
            assert p["committed_slots"] > 0, p["mesh"]


# --------------------------------------------------- mesh-sweep gate ----
def _mesh_doc(points):
    return {"mesh_sweep": {
        "protocol": "multipaxos",
        "variant": "device",
        "shape": {"G": 8, "R": 4, "W": 8, "ticks": 4},
        "points": points,
        "skipped": [],
    }}


def _mesh_point(spec="2x1", devices=2, ok=True, donated=True, slots=100):
    gs, rs = (int(x) for x in spec.split("x"))
    return {
        "mesh": spec, "group_shards": gs, "replica_shards": rs,
        "devices": devices, "groups_per_device": 8 // gs,
        "analytic": {"flops": 10.0, "hlo_instructions": 5},
        "memory": {"argument_bytes": 64},
        "donation": {"aliased_buffers": 52 if donated else 0,
                     "carry_leaves": 52},
        "donated": donated, "committed_slots": slots, "ok": ok,
    }


class TestMeshSweepGate:
    def _run(self, doc, cur_points=None, monkeypatch=None):
        import perf_gate

        if cur_points is not None:
            monkeypatch.setattr(
                perf_gate.profiling, "mesh_sweep",
                lambda *a, **k: {"points": cur_points, "skipped": []},
            )
        errors = []
        perf_gate.check_mesh_sweep(doc, errors)
        return errors

    def test_match_passes(self, monkeypatch):
        pts = [_mesh_point("1x1", 1), _mesh_point("2x1", 2)]
        errors = self._run(
            _mesh_doc(pts), json.loads(json.dumps(pts)), monkeypatch
        )
        assert errors == []

    def test_no_multi_device_point_fails(self, monkeypatch):
        errors = self._run(_mesh_doc([_mesh_point("1x1", 1)]))
        assert len(errors) == 1 and "no multi-device" in errors[0]

    def test_undonated_committed_point_fails(self, monkeypatch):
        errors = self._run(
            _mesh_doc([_mesh_point("2x1", 2, donated=False)])
        )
        assert any("undonated" in e for e in errors)

    def test_dead_committed_capture_fails(self, monkeypatch):
        errors = self._run(
            _mesh_doc([_mesh_point("2x1", 2, ok=False, slots=0)])
        )
        assert any("ok=false" in e for e in errors)
        assert any("no progress" in e for e in errors)

    def test_analytic_drift_fails(self, monkeypatch):
        pts = [_mesh_point("2x1", 2)]
        cur = json.loads(json.dumps(pts))
        cur[0]["analytic"]["flops"] = 11.0
        errors = self._run(_mesh_doc(pts), cur, monkeypatch)
        assert len(errors) == 1 and "drift in 'analytic'" in errors[0]

    def test_donation_regression_fails(self, monkeypatch):
        pts = [_mesh_point("2x1", 2)]
        cur = json.loads(json.dumps(pts))
        cur[0]["donation"]["aliased_buffers"] = 0
        cur[0]["donated"] = False
        cur[0]["ok"] = False
        errors = self._run(_mesh_doc(pts), cur, monkeypatch)
        assert any("lost carry donation" in e for e in errors)

    def test_too_few_devices_fails(self, monkeypatch):
        import perf_gate

        pts = [_mesh_point("4x2", 8)]
        monkeypatch.setattr(
            perf_gate.profiling, "mesh_sweep",
            lambda *a, **k: {
                "points": [],
                "skipped": [{"mesh": "4x2", "reason": "needs 8"}],
            },
        )
        errors = []
        perf_gate.check_mesh_sweep(_mesh_doc(pts), errors)
        assert len(errors) == 1 and "fewer devices" in errors[0]


# -------------------------------------------------- tally-sweep gate ----
def _tally_doc(points):
    return {"tally_sweep": {
        "shape": {"G": 8, "R": 4, "W": 8, "ticks": 4},
        "points": points,
        "skipped": [],
    }}


def _tally_point(proto="multipaxos", mesh="1x1", tally="pairwise",
                 slots=100, ok=True):
    gs, rs = (int(x) for x in mesh.split("x"))
    coll = tally == "collective"
    lane_shape = [1, 8, 4] if coll else [1, 8, 4, 4]
    return {
        "protocol": proto, "tally": tally, "mesh": mesh,
        "group_shards": gs, "replica_shards": rs, "devices": gs * rs,
        "groups_per_device": 8 // gs,
        "analytic": {
            "flops": 50.0 if coll else 100.0,
            "bytes_accessed": 500.0 if coll else 1000.0,
            "hlo_instructions": 40 if coll else 50,
            "tally_phase_ops": 10 if coll else 30,
        },
        "hlo_ops_by_phase": {"quorum_tally": 10 if coll else 30},
        "memory": {"argument_bytes": 64},
        "tally_lane_shapes": {"ar_f": lane_shape},
        "committed_slots": slots, "ok": ok,
    }


class TestTallySweepGate:
    def _run(self, doc, cur_points=None, monkeypatch=None):
        import perf_gate

        if cur_points is not None:
            monkeypatch.setattr(
                perf_gate.profiling, "tally_sweep",
                lambda *a, **k: {"points": cur_points, "skipped": []},
            )
        errors = []
        perf_gate.check_tally_sweep(doc, errors)
        return errors

    def _pair(self):
        return [_tally_point(tally="pairwise"),
                _tally_point(tally="collective")]

    def test_match_passes(self, monkeypatch):
        pts = self._pair()
        errors = self._run(
            _tally_doc(pts), json.loads(json.dumps(pts)), monkeypatch
        )
        assert errors == []

    def test_missing_sweep_fails(self):
        errors = []
        import perf_gate

        perf_gate.check_tally_sweep({}, errors)
        assert len(errors) == 1 and "ungated" in errors[0]

    def test_missing_mode_fails(self):
        errors = self._run(_tally_doc([_tally_point()]))
        assert any("missing a tally mode" in e for e in errors)

    def test_unreduced_collective_fails(self):
        pts = self._pair()
        # the collective cell stops paying for itself on every metric
        pts[1]["analytic"] = dict(pts[0]["analytic"])
        errors = self._run(_tally_doc(pts))
        assert sum("not strictly below" in e for e in errors) == 3

    def test_progress_divergence_fails(self):
        pts = self._pair()
        pts[1]["committed_slots"] = 99
        errors = self._run(_tally_doc(pts))
        assert any("semantically identical" in e for e in errors)

    def test_pairwise_shaped_collective_lane_fails(self):
        pts = self._pair()
        pts[1]["tally_lane_shapes"]["ar_f"] = [1, 8, 4, 4]
        errors = self._run(_tally_doc(pts))
        assert any("still pairwise-shaped" in e for e in errors)

    def test_dead_committed_point_fails(self):
        pts = self._pair()
        pts[1]["committed_slots"] = 0
        pts[1]["ok"] = False
        errors = self._run(_tally_doc(pts))
        assert any("no progress" in e for e in errors)

    def test_rederive_drift_fails(self, monkeypatch):
        pts = self._pair()
        cur = json.loads(json.dumps(pts))
        cur[1]["analytic"]["tally_phase_ops"] = 11
        errors = self._run(_tally_doc(pts), cur, monkeypatch)
        assert any("drift in 'analytic'" in e for e in errors)


def test_tally_cell_live_small():
    """One real collective tally_cell vs its pairwise twin on the
    virtual CPU mesh: strictly fewer tally-phase ops and flops, the
    same committed slots, [D, G, R] lanes."""
    import jax

    if len(jax.devices()) < 4:
        pytest.skip("needs the virtual CPU mesh")
    pw = profiling.tally_cell("multipaxos", "pairwise", "2x2",
                              G=8, R=4, W=8, ticks=8)
    co = profiling.tally_cell("multipaxos", "collective", "2x2",
                              G=8, R=4, W=8, ticks=8)
    assert co["analytic"]["tally_phase_ops"] < \
        pw["analytic"]["tally_phase_ops"]
    assert co["analytic"]["flops"] < pw["analytic"]["flops"]
    assert co["committed_slots"] == pw["committed_slots"] > 0
    assert all(len(s) == 3 for s in co["tally_lane_shapes"].values())
    assert all(len(s) == 4 for s in pw["tally_lane_shapes"].values())


def test_committed_tally_sweep_shape():
    """The committed PROFILE.json carries the quorum-tally before/after
    for MultiPaxos AND Crossword, with every collective cell strictly
    below its pairwise twin (the acceptance criterion, audited off the
    committed artifact)."""
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "PROFILE.json",
    )
    with open(path) as f:
        doc = json.load(f)
    ts = doc["tally_sweep"]
    protos = {p["protocol"] for p in ts["points"]}
    assert protos >= {"multipaxos", "crossword"}
    by_key = {}
    for p in ts["points"]:
        assert p["ok"] and p["committed_slots"] > 0
        by_key.setdefault((p["protocol"], p["mesh"]), {})[p["tally"]] = p
    assert any(m != "1x1" for _, m in by_key), "no multi-device point"
    for key, modes in by_key.items():
        pw, co = modes["pairwise"], modes["collective"]
        assert co["analytic"]["tally_phase_ops"] < \
            pw["analytic"]["tally_phase_ops"], key
        assert co["analytic"]["flops"] < pw["analytic"]["flops"], key
        assert co["committed_slots"] == pw["committed_slots"], key


def test_mesh_cell_live_small():
    """One real mesh_cell on the virtual CPU mesh: donated carry,
    deterministic analytic block, consensus progress."""
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs the virtual CPU mesh")
    cell = profiling.mesh_cell("multipaxos", "2x1", G=8, R=3, W=8,
                               ticks=8)
    assert cell["devices"] == 2 and cell["groups_per_device"] == 4
    assert cell["donated"] and cell["ok"]
    assert cell["analytic"]["hlo_instructions"] > 0
    assert cell["committed_slots"] > 0


# ------------------------------------------------- device-phase merge ----
def _tick_dump(me=0, protocol="MultiPaxos"):
    t0 = 1_000_000
    evs = [
        {"n": 0, "t_us": t0 + 100, "type": "tick",
         "tick": 8, "intake": 5, "exchange": 10, "step": 40,
         "log": 3, "apply": 2},
        {"n": 1, "t_us": t0 + 400, "type": "tick",
         "tick": 9, "intake": 4, "exchange": 8, "step": 50,
         "log": 2, "apply": 1},
    ]
    return {
        "v": 1, "me": me, "t_start_us": t0, "count": len(evs),
        "dropped": 0, "t_dump_us": t0 + 10_000_000, "events": evs,
        "protocol": protocol, "tick": 9, "applied": [1],
    }


def _profile_doc(with_wall=True):
    cell = {
        "protocol": "multipaxos", "variant": "host",
        "phases": ["ingest_accept", "election", "build_outbox"],
        "analytic": {"hlo_instructions": 10, "hlo_ops_by_phase": {
            "ingest_accept": 6, "election": 2, "build_outbox": 2,
        }},
    }
    if with_wall:
        cell["phase_wall_us_per_tick"] = {
            "ingest_accept": 30.0, "election": 5.0,
            "build_outbox": 15.0, "unattributed": 7.0,
        }
    return {"protocols": {"MultiPaxos": {"host": cell}}}


class TestDevicePhaseMerge:
    def test_phase_fractions_prefer_measured_wall(self):
        fr = trace_export.phase_fractions(_profile_doc(), "MultiPaxos")
        assert [p for p, _ in fr] == [
            "ingest_accept", "election", "build_outbox",
        ]
        assert abs(sum(f for _, f in fr) - 1.0) < 1e-9
        assert fr[0][1] == pytest.approx(0.6)  # 30 / 50 attributed

    def test_phase_fractions_fall_back_to_hlo_ops(self):
        fr = trace_export.phase_fractions(
            _profile_doc(with_wall=False), "MultiPaxos"
        )
        assert fr[0] == ("ingest_accept", pytest.approx(0.6))

    def test_phase_fractions_unknown_protocol_empty(self):
        assert trace_export.phase_fractions(_profile_doc(), "Nope") == []

    def test_merge_emits_named_spans_inside_step_and_validates(self):
        dumps = {"0": _tick_dump()}
        doc = trace_export.export_chrome(
            dumps, phase_profile=_profile_doc()
        )
        assert trace_export.validate_chrome(doc) == []
        phase = [e for e in doc["traceEvents"]
                 if str(e.get("name", "")).startswith("phase:")]
        steps = [e for e in doc["traceEvents"]
                 if e.get("name") == "device scan tick"]
        assert steps and phase
        # children nest inside their measured step span, never escape
        for st in steps:
            inside = [p for p in phase
                      if st["ts"] <= p["ts"]
                      and p["ts"] + p["dur"] <= st["ts"] + st["dur"]]
            assert inside, "step span has no phase children"
        assert {str(p["name"]) for p in phase} <= {
            "phase:ingest_accept", "phase:election",
            "phase:build_outbox",
        }
        assert all(
            p["args"]["projected_from"] == "PROFILE.json" for p in phase
        )

    def test_merge_without_profile_unchanged(self):
        dumps = {"0": _tick_dump()}
        doc = trace_export.export_chrome(dumps)
        assert trace_export.validate_chrome(doc) == []
        assert not [e for e in doc["traceEvents"]
                    if str(e.get("name", "")).startswith("phase:")]


# ----------------------------------------------------------- slow smoke ----
@pytest.mark.slow
def test_profile_cell_end_to_end():
    """One full cell at tiny shape: analytic + wall + (when the backend
    profiler cooperates) measured per-phase device time."""
    cell = profiling.profile_cell(
        "multipaxos", "device", G=8, R=3, W=16, ticks=16, reps=1,
    )
    assert cell["ok"]
    assert cell["wall"]["committed_slots_per_s"] > 0
    assert cell["analytic"]["hlo_ops_by_phase"]["ingest_accept"] > 0
    pw = cell.get("phase_wall_us_per_tick")
    if pw is not None:
        assert sum(v for k, v in pw.items() if k != "unattributed") > 0


@pytest.mark.slow
def test_scope_overhead_ablation_under_budget():
    ov = profiling.measure_scope_overhead(
        G=16, W=16, ticks=32, pairs=1, max_pairs=3
    )
    assert ov["pct"] < 5.0, ov
