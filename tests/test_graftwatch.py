"""graftwatch tests: the server-side delta-frame emitter (losslessness
of the counter/histogram stream), the manager-side fleet ring
(ingest/retention/export determinism), fleet-window alignment (merge
semantics, partial windows, tier filtering), and the multi-window SLO
burn-rate policy (latch/clear hysteresis, ratio and quantile
objectives, pure-fold re-derivation).
"""

import json

from summerset_tpu.host.graftwatch import (
    DEFAULT_OBJECTIVES,
    FleetSeries,
    SloPolicy,
    WatchEmitter,
    base_name,
    evaluate_series,
    windows,
)
from summerset_tpu.host.telemetry import Histogram, MetricsRegistry


def _mk_emitter(me=0, span=10, **kw):
    reg = MetricsRegistry()
    return reg, WatchEmitter(reg, me=me, span_ticks=span, **kw)


# ------------------------------------------------------------- emitter ----
class TestWatchEmitter:
    def test_first_frame_is_cumulative(self):
        reg, em = _mk_emitter()
        reg.counter_add("api_requests_total", 7)
        reg.observe("api_request_latency_us", 1000)
        fr = em.frame(tick=25)
        assert fr["widx"] == 2 and fr["span_ticks"] == 10
        assert fr["counters"]["api_requests_total"] == 7
        assert fr["hists"]["api_request_latency_us"]["count"] == 1

    def test_frames_are_deltas_with_zeros_elided(self):
        reg, em = _mk_emitter()
        reg.counter_add("api_requests_total", 5)
        reg.counter_add("api_shed", 2)
        em.frame(tick=10)
        reg.counter_add("api_requests_total", 3)  # api_shed unchanged
        fr = em.frame(tick=20)
        assert fr["counters"] == {"api_requests_total": 3}
        assert fr["hists"] == {}  # no new samples -> no window entry

    def test_stream_is_lossless(self):
        """Merging every frame of a series reproduces the cumulative
        registry — counters by summing deltas, histograms by merging
        the window snapshots.  This is the invariant that lets the
        committed SLO.json re-derive totals from the frames alone."""
        reg, em = _mk_emitter()
        frames = []
        for t in range(1, 6):
            reg.counter_add("commits_applied_total", t)
            for v in (t * 10, t * 1000):
                reg.observe("api_request_latency_us", v)
            frames.append(em.frame(tick=t * 10))
        total = sum(
            fr["counters"].get("commits_applied_total", 0)
            for fr in frames
        )
        assert total == reg.counter_value("commits_applied_total")
        rebuilt = Histogram()
        for fr in frames:
            snap = fr["hists"].get("api_request_latency_us")
            if snap:
                rebuilt.merge(Histogram.from_snapshot(snap))
        cum = reg.hist("api_request_latency_us")
        assert rebuilt.count == cum.count
        assert rebuilt.total == cum.total
        assert rebuilt.buckets == cum.buckets

    def test_widx_is_tick_derived_not_wallclock(self):
        _, em = _mk_emitter(span=40)
        assert em.frame(tick=0)["widx"] == 0
        assert em.frame(tick=39)["widx"] == 0
        assert em.frame(tick=40)["widx"] == 1
        assert em.frame(tick=805)["widx"] == 20


# -------------------------------------------------------- fleet series ----
def _frame(sid, widx, counters=None, hists=None, tier="shard",
           group=0, gauges=None, span=10):
    return {
        "v": 1, "sid": sid, "tier": tier, "group": group,
        "widx": widx, "tick": widx * span, "span_ticks": span,
        "counters": counters or {}, "gauges": gauges or {},
        "hists": hists or {},
    }


def _lat_snap(values):
    h = Histogram()
    for v in values:
        h.observe(v)
    return h.snapshot()


class TestFleetSeries:
    def test_ingest_retention_and_export_determinism(self):
        fs = FleetSeries(retain=8)
        for w in range(20):
            fs.ingest(1, _frame(1, w))
        fs.ingest(0, _frame(0, 19))
        ex = fs.export()
        assert ex["frames_ingested"] == 21
        assert fs.sids() == [0, 1]
        by_sid = {s["sid"]: s for s in ex["series"]}
        # bounded: only the newest `retain` frames survive per key
        assert [f["widx"] for f in by_sid[1]["frames"]] == list(
            range(12, 20))
        # deterministic: series sorted by key, export JSON-able
        assert [s["sid"] for s in ex["series"]] == [0, 1]
        json.dumps(ex)

    def test_windows_merge_counters_and_hists(self):
        fs = FleetSeries()
        fs.ingest(0, _frame(0, 5, counters={"api_requests_total": 10},
                            hists={"api_request_latency_us":
                                   _lat_snap([100, 200])}))
        fs.ingest(1, _frame(1, 5, counters={"api_requests_total": 4},
                            hists={"api_request_latency_us":
                                   _lat_snap([300_000])}))
        rows = windows(fs.export())
        assert len(rows) == 1
        w = rows[0]
        assert w["widx"] == 5 and w["sids"] == [0, 1]
        assert w["counters"]["api_requests_total"] == 14
        h = w["hists"]["api_request_latency_us"]
        assert h.count == 3  # fleet-merged window histogram
        assert h.quantile(1.0) >= 200_000

    def test_partial_windows_expose_missing_sids(self):
        fs = FleetSeries()
        fs.ingest(0, _frame(0, 1))
        fs.ingest(1, _frame(1, 1))
        fs.ingest(0, _frame(0, 2))  # sid 1 crashed: no frame for widx 2
        rows = windows(fs.export())
        assert [w["widx"] for w in rows] == [1, 2]
        assert rows[0]["sids"] == [0, 1]
        assert rows[1]["sids"] == [0]

    def test_tier_filter_and_label_folding(self):
        fs = FleetSeries()
        fs.ingest(0, _frame(0, 3, counters={
            "api_requests_total{g=0}": 2,
            "api_requests_total{g=1}": 3,
        }))
        fs.ingest(9, _frame(9, 3, tier="proxy",
                            counters={"proxy_routed": 8}))
        assert base_name("api_requests_total{g=0}") == \
            "api_requests_total"
        all_rows = windows(fs.export())
        # labeled counters fold into their base name fleet-wide
        assert all_rows[0]["counters"]["api_requests_total"] == 5
        assert all_rows[0]["counters"]["proxy_routed"] == 8
        shard_only = windows(fs.export(), tier="shard")
        assert "proxy_routed" not in shard_only[0]["counters"]
        assert shard_only[0]["sids"] == [0]


# ---------------------------------------------------------- SLO policy ----
def _win(widx, lat=None, counters=None):
    hists = {}
    if lat:
        h = Histogram()
        for v in lat:
            h.observe(v)
        hists["api_request_latency_us"] = h
    return {"widx": widx, "span_ticks": 10, "sids": [0],
            "counters": counters or {}, "gauges": {}, "hists": hists}


class TestSloPolicy:
    def test_quantile_burn_zero_when_healthy_or_idle(self):
        pol = SloPolicy(DEFAULT_OBJECTIVES)
        row = pol.observe_window(_win(0, lat=[1000] * 100))
        assert row["reply_p99"]["burn"] == 0.0
        row = pol.observe_window(_win(1))  # idle window: no samples
        assert row["reply_p99"]["burn"] == 0.0
        assert not pol.status()["reply_p99"]["alerting"]

    def test_alert_latches_on_sustained_burn_and_clears(self):
        pol = SloPolicy(DEFAULT_OBJECTIVES, fast_windows=2,
                        slow_windows=4, burn_hi=2.0, burn_clear=1.0)
        good = [1000] * 100
        # 3% of samples over the 250ms threshold: burn = .03/.01 = 3
        bad = [400_000] * 3 + [1000] * 97
        # steady-state first so the slow deque is full of zeros
        for w in range(4):
            pol.observe_window(_win(w, lat=good))
        # one bad window must NOT latch: fast = (0+3)/2 < burn_hi
        pol.observe_window(_win(4, lat=bad))
        assert not pol.status()["reply_p99"]["alerting"]
        # sustained burn: fast AND slow both cross burn_hi -> latch
        pol.observe_window(_win(5, lat=bad))
        assert not pol.status()["reply_p99"]["alerting"]  # slow 1.5
        pol.observe_window(_win(6, lat=bad))
        assert pol.status()["reply_p99"]["alerting"]
        # stays latched while fast is between clear and hi thresholds…
        pol.observe_window(_win(7, lat=good))
        assert pol.status()["reply_p99"]["alerting"]  # fast 1.5
        # …and clears once the fast mean drops below burn_clear
        pol.observe_window(_win(8, lat=good))
        assert not pol.status()["reply_p99"]["alerting"]

    def test_ratio_objective_with_den_excludes_num(self):
        pol = SloPolicy(DEFAULT_OBJECTIVES)
        # 10 shed / (90 served + 10 shed) = 10% vs 5% budget -> burn 2
        row = pol.observe_window(_win(0, counters={
            "scan_shed": 10, "scan_served": 90,
        }))
        assert abs(row["scan_starvation"]["burn"] - 2.0) < 1e-6
        # shed_rate's den already includes the num (requests_total)
        row = pol.observe_window(_win(1, counters={
            "api_shed": 5, "api_requests_total": 100,
        }))
        assert abs(row["shed_rate"]["burn"] - 1.0) < 1e-6

    def test_ratio_zero_denominator_burns_zero(self):
        pol = SloPolicy(DEFAULT_OBJECTIVES)
        row = pol.observe_window(_win(0, counters={"scan_shed": 0}))
        assert row["scan_starvation"]["burn"] == 0.0

    def test_evaluate_series_is_deterministic_pure_fold(self):
        fs = FleetSeries()
        for w in range(6):
            lat = [900_000] * 50 if 2 <= w <= 4 else [1000] * 50
            fs.ingest(0, _frame(0, w, hists={
                "api_request_latency_us": _lat_snap(lat)}))
        ex = fs.export()
        a = evaluate_series(ex, DEFAULT_OBJECTIVES)
        b = evaluate_series(ex, DEFAULT_OBJECTIVES)
        assert a == b  # same frames in => same verdicts out
        assert a["n_windows"] == 6
        burns = [r["reply_p99"]["burn"] for r in a["history"]]
        assert burns[2] > 1.0 and burns[0] == 0.0
