"""Vectorized RSPaxos kernel tests: erasure-coded commit threshold, follower
reconstruction reads, shard-aware failover recovery (reference behaviors:
``rspaxos/messages.rs:211-256,435``, ``rspaxos/leadership.rs:142-165``).
"""

import jax.numpy as jnp
import numpy as np

from smr_helpers import check_agreement, committed_values, run_segment
from summerset_tpu.core import Engine, NetConfig
from summerset_tpu.protocols import make_protocol
from summerset_tpu.protocols.rspaxos import ReplicaConfigRSPaxos
import pytest


def make_kernel(G, R, W, P, **kw):
    cfg = ReplicaConfigRSPaxos(max_proposals_per_tick=P, **kw)
    return make_protocol("rspaxos", G, R, W, cfg)


class TestSteadyState:
    def test_commit_flow_and_values(self):
        G, R, W, P = 4, 5, 32, 4
        k = make_kernel(G, R, W, P, fault_tolerance=1)
        eng = Engine(k)
        state, ns = eng.init()
        T = 50
        state, ns, _ = run_segment(eng, state, ns, T, n_prop=P)
        st = {k_: np.asarray(v) for k_, v in state.items()}
        assert (st["commit_bar"][:, 0] >= (T - 6) * P).all(), st["commit_bar"]
        for g in range(G):
            vals = committed_values(st, g, 0, W)
            assert vals
            for slot, v in vals.items():
                assert v == slot
        check_agreement(st, G, R, W)

    def test_scheme_r3_ft0(self):
        G, R, W, P = 2, 3, 32, 4
        k = make_kernel(G, R, W, P, fault_tolerance=0)
        eng = Engine(k)
        state, ns = eng.init()
        state, ns, _ = run_segment(eng, state, ns, 40, n_prop=P)
        st = {k_: np.asarray(v) for k_, v in state.items()}
        assert (st["commit_bar"][:, 0] >= (40 - 6) * P).all()
        check_agreement(st, G, R, W)

    def test_follower_exec_catches_up_via_recon(self):
        # followers hold only their own shard; exec must be gated on the
        # full-data frontier and catch up through Reconstruct read rounds
        G, R, W, P = 2, 5, 32, 2
        k = make_kernel(G, R, W, P, fault_tolerance=1, recon_interval=2)
        eng = Engine(k)
        state, ns = eng.init()
        state, ns, _ = run_segment(eng, state, ns, 40, n_prop=P)
        # drain: stop proposing, let recon finish
        state, ns, _ = run_segment(eng, state, ns, 30, n_prop=0)
        st = {k_: np.asarray(v) for k_, v in state.items()}
        assert (st["commit_bar"][:, 0] > 0).all()
        # every replica's exec/full frontier reaches the group commit bar
        cb = st["commit_bar"].max(axis=1, keepdims=True)
        assert (st["full_bar"] >= cb).all(), (st["full_bar"], cb)
        assert (st["exec_bar"] >= cb).all()


class TestCommitThreshold:
    @pytest.mark.slow
    def test_majority_alone_does_not_commit(self):
        # R=5, ft=1 -> commit needs 4 acks; with only 3 alive the leader
        # must stall commits (MultiPaxos would keep committing here)
        G, R, W, P = 2, 5, 32, 4
        k = make_kernel(G, R, W, P, fault_tolerance=1)
        eng = Engine(k)
        state, ns = eng.init()
        state, ns, _ = run_segment(eng, state, ns, 20, n_prop=P)
        pre = np.asarray(state["commit_bar"]).copy()

        alive = jnp.ones((G, R), jnp.bool_).at[:, 3].set(False).at[:, 4].set(
            False
        )
        state, ns, _ = run_segment(
            eng, state, ns, 80, n_prop=P, alive=alive, base_start=1000
        )
        mid = {k_: np.asarray(v) for k_, v in state.items()}
        # commit bar may only advance by what was already acked in flight
        assert (mid["commit_bar"][:, 0] <= pre[:, 0] + 4 * P).all(), (
            pre[:, 0],
            mid["commit_bar"][:, 0],
        )
        check_agreement(mid, G, R, W)

        # heal -> commits resume
        state, ns, _ = run_segment(
            eng, state, ns, 80, n_prop=P, base_start=2000
        )
        fin = {k_: np.asarray(v) for k_, v in state.items()}
        assert (fin["commit_bar"][:, 0] > mid["commit_bar"][:, 0] + P).all()
        check_agreement(fin, G, R, W)


class TestFailover:
    def test_leader_crash_recovers_committed_values(self):
        G, R, W, P = 4, 5, 32, 4
        k = make_kernel(G, R, W, P, fault_tolerance=1)
        eng = Engine(k, seed=5)
        state, ns = eng.init()
        state, ns, _ = run_segment(eng, state, ns, 30, n_prop=P)
        pre = {k_: np.asarray(v) for k_, v in state.items()}
        pre_committed = [committed_values(pre, g, 1, W) for g in range(G)]
        assert all(len(c) > 0 for c in pre_committed)

        alive = jnp.ones((G, R), jnp.bool_).at[:, 0].set(False)
        state, ns, _ = run_segment(
            eng, state, ns, 400, n_prop=P, alive=alive, base_start=1000
        )
        post = {k_: np.asarray(v) for k_, v in state.items()}
        # someone took over and committed new slots
        live_cb = post["commit_bar"][:, 1:]
        assert (
            live_cb.max(axis=1) > pre["commit_bar"][:, 1:].max(axis=1)
        ).all(), (pre["commit_bar"], post["commit_bar"])
        # previously committed values survive (recoverable from >= d shards)
        for g in range(G):
            live = [
                r
                for r in range(1, R)
                if int(post["leader"][g, r]) == r
            ]
            for r in live:
                vals = committed_values(post, g, r, W)
                for slot, v in pre_committed[g].items():
                    if slot in vals:
                        assert vals[slot] == v, (g, r, slot, v, vals[slot])
        check_agreement(post, G, R, W)


class TestLossyNetwork:
    def test_agreement_under_drops(self):
        G, R, W, P = 2, 5, 64, 4
        cfg = ReplicaConfigRSPaxos(
            max_proposals_per_tick=P,
            fault_tolerance=1,
            hear_timeout_lo=40,
            hear_timeout_hi=80,
        )
        k = make_protocol("rspaxos", G, R, W, cfg)
        net = NetConfig(
            delay_ticks=1, jitter_ticks=2, drop_rate=0.2, max_delay_ticks=4
        )
        eng = Engine(k, netcfg=net, seed=23)
        state, ns = eng.init()
        state, ns, _ = run_segment(eng, state, ns, 400, n_prop=P)
        st = {k_: np.asarray(v) for k_, v in state.items()}
        assert (st["commit_bar"].max(axis=1) > 50).all()
        check_agreement(st, G, R, W)
