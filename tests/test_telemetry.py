"""Telemetry plane tests: the host metrics registry (counter / gauge /
histogram bucket math, snapshot determinism), sampled slot traces, and
the in-kernel device metric lanes (core/telemetry.py) — accumulation
semantics, netmodel drop accounting, freeze behavior, and the lane-free
ablation variant.
"""

import json

import jax.numpy as jnp
import numpy as np
import pytest

from summerset_tpu.core import Engine
from summerset_tpu.core import telemetry as dev
from summerset_tpu.core.netmodel import ControlInputs
from summerset_tpu.host.telemetry import (
    DECLARED,
    Histogram,
    MetricsRegistry,
    SlotTraces,
)
from summerset_tpu.protocols import make_protocol
from summerset_tpu.protocols.multipaxos import ReplicaConfigMultiPaxos


# ------------------------------------------------------------- registry ----
class TestHistogram:
    def test_bucket_math(self):
        h = Histogram()
        for v in (0, 1, 2, 3, 4, 7, 8, 1023, 1024):
            h.observe(v)
        assert h.count == 9
        assert h.total == 0 + 1 + 2 + 3 + 4 + 7 + 8 + 1023 + 1024
        assert h.vmin == 0 and h.vmax == 1024
        # power-of-two buckets by bit_length: 0->b0, 1->b1, 2,3->b2,
        # 4..7->b3, 8->b4, 1023->b10, 1024->b11
        assert h.buckets[0] == 1
        assert h.buckets[1] == 1
        assert h.buckets[2] == 2
        assert h.buckets[3] == 2
        assert h.buckets[4] == 1
        assert h.buckets[10] == 1
        assert h.buckets[11] == 1

    def test_quantiles_monotone_and_bounded(self):
        h = Histogram()
        for v in range(1, 1000):
            h.observe(v)
        q = [h.quantile(x) for x in (0.0, 0.25, 0.5, 0.9, 0.99, 1.0)]
        assert q == sorted(q)
        assert q[-1] <= h.vmax
        # p50 of 1..999 sits in the right bucket neighborhood
        assert 256 <= h.quantile(0.5) <= 1023

    def test_negative_clamped(self):
        h = Histogram()
        h.observe(-5)
        assert h.vmin == 0 and h.buckets[0] == 1

    def test_windowed_since_reflects_recent_samples_only(self):
        h = Histogram()
        for _ in range(1000):
            h.observe(10)       # long healthy history
        prev = h.copy()
        for _ in range(50):
            h.observe(100000)   # fresh regression
        win = h.since(prev)
        assert win.count == 50
        # lifetime p50 stays pinned at history; the window sees the stall
        assert h.quantile(0.5) < 20
        assert win.quantile(0.5) > 10000
        assert h.since(None) is h

    def test_snapshot_sparse_buckets(self):
        h = Histogram()
        h.observe(1 << 20)
        snap = h.snapshot()
        assert snap["buckets"] == {21: 1}
        assert snap["count"] == 1 and snap["sum"] == 1 << 20


class TestHistogramWindowEdges:
    """Edge cases of the windowed (since/merge/snapshot) views — the
    delta frames graftwatch streams are exactly these objects, so the
    inverses must hold at the boundaries, not just mid-distribution."""

    def test_empty_window_after_no_new_samples(self):
        # a tick with no traffic produces an all-zero window; quantile
        # and frac_over must read as "nothing", not divide by zero
        h = Histogram()
        for v in (5, 9, 14):
            h.observe(v)
        win = h.since(h.copy())
        assert win.count == 0 and win.total == 0
        assert not any(win.buckets)
        assert win.quantile(0.99) == 0.0
        assert win.frac_over(0) == 0.0

    def test_single_sample_window(self):
        h = Histogram()
        for _ in range(100):
            h.observe(8)
        prev = h.copy()
        h.observe(5000)
        win = h.since(prev)
        assert win.count == 1
        # every quantile of a one-sample window is that sample's
        # bucket, clamped into the inherited [vmin, vmax]
        assert win.quantile(0.0) == win.quantile(1.0)
        assert 2048 <= win.quantile(0.5) <= 8191

    def test_quantile_clamps_at_bucket_extremes(self):
        h = Histogram()
        h.observe(1000)     # bucket 10 spans 512..1023
        h.observe(1000)
        # interpolation inside the bucket would sweep 512..1023, but
        # the observed range is exactly [1000, 1000]
        assert h.quantile(0.0) == 1000.0
        assert h.quantile(1.0) == 1000.0
        lo = Histogram()
        lo.observe(0)
        assert lo.quantile(0.5) == 0.0

    def test_delta_snapshot_round_trip(self):
        # prev.copy().merge(cur.since(prev)) == cur for count/sum/
        # buckets — the graftwatch stream invariant: merging every
        # delta frame of a series reproduces the cumulative registry
        cur = Histogram()
        for v in (3, 17, 900, 70000):
            cur.observe(v)
        prev = cur.copy()
        for v in (1, 2, 1 << 22):
            cur.observe(v)
        rebuilt = prev.copy().merge(cur.since(prev))
        assert rebuilt.count == cur.count
        assert rebuilt.total == cur.total
        assert rebuilt.buckets == cur.buckets

    def test_snapshot_round_trip_through_json_keys(self):
        h = Histogram()
        for v in (6, 6, 300):
            h.observe(v)
        snap = json.loads(json.dumps(h.snapshot()))  # str bucket keys
        back = Histogram.from_snapshot(snap)
        assert back.count == h.count and back.total == h.total
        assert back.buckets == h.buckets
        assert back.vmin == h.vmin and back.vmax == h.vmax
        empty = Histogram.from_snapshot({"count": 0, "sum": 0})
        assert empty.vmin is None and empty.quantile(0.5) == 0.0

    def test_merge_empty_window_is_noop(self):
        h = Histogram()
        h.observe(42)
        before = h.snapshot()
        h.merge(Histogram())
        h.merge(None)
        assert h.snapshot() == before

    def test_frac_over_interpolates_and_saturates(self):
        h = Histogram()
        for _ in range(10):
            h.observe(1000)  # bucket 512..1023
        assert h.frac_over(1 << 20) == 0.0     # far above: none
        assert h.frac_over(0) == 1.0           # below everything: all
        mid = h.frac_over(512)                 # bucket lower bound
        assert 0.0 < mid <= 1.0


class TestRegistry:
    def _fill(self, reg):
        reg.counter_add("reqs")
        reg.counter_add("reqs", 4)
        reg.counter_add("frames", 2, peer=1)
        reg.counter_add("frames", 3, peer=0)
        reg.gauge_set("depth", 7.5)
        for v in (10, 20, 400):
            reg.observe("lat_us", v, stage="step")
        reg.observe_s("lat_s", 0.001)

    def test_counters_and_labels(self):
        reg = MetricsRegistry()
        self._fill(reg)
        assert reg.counter_value("reqs") == 5
        assert reg.counter_value("frames", peer=1) == 2
        assert reg.counter_value("frames", peer=0) == 3
        assert reg.counter_value("missing") == 0
        assert reg.hist("lat_us", stage="step").count == 3
        assert reg.hist("lat_s").total == 1000

    def test_names_strip_labels(self):
        reg = MetricsRegistry()
        self._fill(reg)
        assert reg.names() == {
            "reqs", "frames", "depth", "lat_us", "lat_s"
        }

    def test_snapshot_deterministic(self):
        a, b = MetricsRegistry(), MetricsRegistry()
        self._fill(a)
        self._fill(b)
        # identical recorded ops -> byte-identical serialized snapshot
        assert json.dumps(a.snapshot()) == json.dumps(b.snapshot())

    def test_declared_names_are_unique(self):
        assert len(DECLARED) == len(set(DECLARED))


class TestSlotTraces:
    def test_trace_lifecycle_feeds_histograms(self):
        reg = MetricsRegistry()
        tr = SlotTraces(reg, sample_every=1)
        tr.maybe_start(0, 5, tick=10, arrival_s=1.0)
        tr.mark_committed(0, 5, tick=14)
        tr.mark_committed(0, 5, tick=15)  # idempotent: first wins
        tr.mark_applied(0, 5, tick=14)
        tr.mark_replied(0, 5, now_s=1.5)
        h = reg.hist("ticks_to_commit")
        assert h.count == 1 and h.total == 4
        done = tr.sampled()
        assert len(done) == 1
        assert done[0]["tick_committed"] == 14
        assert done[0]["latency_ms"] == pytest.approx(500.0)

    def test_sampling_rate(self):
        reg = MetricsRegistry()
        tr = SlotTraces(reg, sample_every=4)
        for vid in range(1, 17):
            tr.maybe_start(0, vid, tick=0, arrival_s=0.0)
        assert len(tr._open) == 4  # every 4th
        tr0 = SlotTraces(reg, sample_every=0)
        tr0.maybe_start(0, 1, tick=0, arrival_s=0.0)
        assert not tr0._open

    def test_unknown_marks_are_noops(self):
        reg = MetricsRegistry()
        tr = SlotTraces(reg, sample_every=1)
        tr.mark_committed(3, 9, tick=1)
        tr.mark_replied(3, 9, now_s=1.0)
        assert reg.hist("ticks_to_commit") is None


# ----------------------------------------------------------- device lanes --
class TestDeviceLanes:
    def test_accumulate_counters_add_and_gauges_max(self):
        t = dev.zero_block(2, 3)
        one = jnp.ones((2, 3), jnp.int32)
        t = dev.accumulate(t, {"commits": one * 2, "win_occupancy_hw": one * 5})
        t = dev.accumulate(t, {"commits": one, "win_occupancy_hw": one * 3})
        blk = np.asarray(t)
        assert (blk[:, :, dev.LANE_IDX["commits"]] == 3).all()
        assert (blk[:, :, dev.LANE_IDX["win_occupancy_hw"]] == 5).all()

    def test_unknown_lane_rejected(self):
        t = dev.zero_block(1, 1)
        with pytest.raises(KeyError):
            dev.accumulate(t, {"not_a_lane": jnp.ones((1, 1), jnp.int32)})

    def test_bool_contributions_coerce(self):
        t = dev.zero_block(1, 2)
        t = dev.bump(t, "heartbeats", jnp.array([[True, False]]))
        assert np.asarray(t)[0, :, dev.LANE_IDX["heartbeats"]].tolist() \
            == [1, 0]

    def _engine(self, G=2, R=3, W=16):
        cfg = ReplicaConfigMultiPaxos(max_proposals_per_tick=2)
        return Engine(make_protocol("multipaxos", G, R, W, cfg))

    def _seq(self, T, G, P=2, **extra):
        t = jnp.arange(T, dtype=jnp.int32)
        seq = {
            "n_proposals": jnp.full((T, G), P, jnp.int32),
            "value_base": jnp.broadcast_to(((t) * P)[:, None], (T, G)),
        }
        seq.update(extra)
        return seq

    def test_lanes_track_commits_and_occupancy(self):
        eng = self._engine()
        state, ns = eng.init()
        assert "telem" in state
        state, ns, _ = eng.run_ticks(state, ns, self._seq(30, 2))
        blk = np.asarray(state["telem"])
        cb = np.asarray(state["commit_bar"])
        # the commits lane is exactly the committed-slot count (from 0)
        assert (blk[:, :, dev.LANE_IDX["commits"]] == cb).all()
        # occupancy high-water is bounded by the window
        assert (blk[:, :, dev.LANE_IDX["win_occupancy_hw"]] <= 16).all()
        # leader proposed; followers heard heartbeats
        assert blk[:, 0, dev.LANE_IDX["proposals"]].sum() > 0
        assert blk[:, 1:, dev.LANE_IDX["heartbeats"]].sum() > 0

    def test_net_drop_lane_counts_masked_sends(self):
        eng = self._engine(G=1)
        state, ns = eng.init()
        T = 16
        link = ControlInputs.one_way_down(1, 3, 0, 1)
        seq = self._seq(
            T, 1,
            alive=jnp.broadcast_to(jnp.ones((1, 3), jnp.bool_), (T, 1, 3)),
            link_up=jnp.broadcast_to(link, (T, 1, 3, 3)),
        )
        state, ns, _ = eng.run_ticks(state, ns, seq)
        blk = np.asarray(state["telem"])
        # src 0 loses its 0->1 sends; a dead link is a drop, every tick
        assert blk[0, 0, dev.LANE_IDX["net_drops"]] > 0
        assert blk[0, 2, dev.LANE_IDX["net_drops"]] == 0

    def test_paused_replica_lanes_freeze(self):
        eng = self._engine(G=1)
        state, ns = eng.init()
        T = 16
        alive = jnp.ones((1, 3), jnp.bool_).at[:, 2].set(False)
        seq = self._seq(
            T, 1,
            alive=jnp.broadcast_to(alive, (T, 1, 3)),
            link_up=jnp.broadcast_to(
                ControlInputs.links_all_up(1, 3), (T, 1, 3, 3)
            ),
        )
        state, ns, _ = eng.run_ticks(state, ns, seq)
        assert np.asarray(state["telem"])[0, 2].sum() == 0

    def test_ablation_variant_runs_without_lanes(self):
        eng = self._engine(G=1)
        state, ns = eng.init()
        state.pop("telem")
        state, ns, _ = eng.run_ticks(state, ns, self._seq(10, 1))
        assert "telem" not in state
        assert int(np.asarray(state["commit_bar"]).max()) > 0

    def test_snapshot_row_decodes_block(self):
        t = dev.zero_block(2, 3)
        t = t.at[:, 1, dev.LANE_IDX["commits"]].set(jnp.int32(7))
        t = t.at[0, 1, dev.LANE_IDX["win_occupancy_hw"]].set(jnp.int32(9))
        snap = dev.snapshot_row(t, 1)
        assert snap["lanes"]["commits"] == 14          # counters sum over G
        assert snap["lanes"]["win_occupancy_hw"] == 9  # high-water maxes
        assert snap["per_group"]["commits"] == [7, 7]
