"""Vectorized EPaxos kernel tests: leaderless commit flow, interference
ordering agreement across replicas, row failover through the ExpPrepare
ladder, self-heal of wedged rows, and loss tolerance (reference behaviors:
``epaxos/messages.rs:95-200``, ``dependency.rs:180-330``,
``execution.rs:11-87``).
"""

import jax.numpy as jnp
import numpy as np

from summerset_tpu.core import Engine, NetConfig
from summerset_tpu.protocols import make_protocol
from summerset_tpu.protocols.epaxos import COMMITTED, ReplicaConfigEPaxos
import pytest


def make_kernel(G, R, W, P, **kw):
    cfg = ReplicaConfigEPaxos(max_proposals_per_tick=P, **kw)
    return make_protocol("epaxos", G, R, W, cfg)


def np_state(state):
    return {k: np.asarray(v) for k, v in state.items()}


def run(eng, state, ns, ticks, n_prop, alive=None, base_start=1,
        collect=False):
    G = eng.kernel.G
    t = jnp.arange(ticks, dtype=jnp.int32)
    seq = {
        "n_proposals": jnp.full((ticks, G), n_prop, jnp.int32),
        "value_base": jnp.broadcast_to(
            (base_start + t * max(n_prop, 1))[:, None], (ticks, G)
        ),
    }
    if alive is not None:
        seq["alive"] = jnp.broadcast_to(alive, (ticks,) + alive.shape)
    return eng.run_ticks(state, ns, seq, collect=collect)


def committed_instances(st, g, r):
    """{(row, col): (val, seq)} of committed instances in r's window."""
    out = {}
    R, W = st["st2"].shape[2], st["st2"].shape[3]
    for row in range(R):
        for w in range(W):
            if st["st2"][g, r, row, w] == COMMITTED:
                col = int(st["abs2"][g, r, row, w])
                if col >= 0:
                    out[(row, col)] = (
                        int(st["val2"][g, r, row, w]),
                        int(st["seq2"][g, r, row, w]),
                    )
    return out


def check_agreement(st, G, R):
    """No two replicas commit different values for the same instance."""
    for g in range(G):
        merged = {}
        for r in range(R):
            for slot, v in committed_instances(st, g, r).items():
                if slot in merged:
                    assert merged[slot][0] == v[0], (g, r, slot, merged[slot], v)
                else:
                    merged[slot] = v
    return True


def exec_orders(fx, G, R, K):
    """Per (group, replica, bucket): executed value sequence — pass order
    first, then (seq, row) within a pass (the kernel's own tie-break)."""
    go = np.asarray(fx.extra["exec_go"])      # [T, G, R, row, pass]
    seqs = np.asarray(fx.extra["exec_seq"])
    vals = np.asarray(fx.extra["exec_val"])
    T, n_pass = go.shape[0], go.shape[-1]
    orders = {}
    for g in range(G):
        for r in range(R):
            per_bucket = {b: [] for b in range(K)}
            for t in range(T):
                for p in range(n_pass):
                    evs = [
                        (int(seqs[t, g, r, row, p]), row,
                         int(vals[t, g, r, row, p]))
                        for row in range(R)
                        if go[t, g, r, row, p]
                    ]
                    for sq, row, v in sorted(evs):
                        per_bucket[v % K].append(v)
            orders[(g, r)] = per_bucket
    return orders


class TestSteadyState:
    def test_commit_flow_all_rows(self):
        G, R, W, P = 4, 5, 32, 5
        eng = Engine(make_kernel(G, R, W, P))
        state, ns = eng.init()
        T = 40
        state, ns, _ = run(eng, state, ns, T, n_prop=P)
        st = np_state(state)
        # every row proposes and commits (leaderless): each row's commit
        # frontier moves well past half the proposals
        assert (st["cmt_row"] >= (T - 10)).all(), st["cmt_row"][0]
        assert (st["exec_row"] >= (T - 12)).all()
        check_agreement(st, G, R)

    def test_no_conflict_throughput(self):
        # distinct buckets -> fast path dominates; commit lag stays small
        G, R, W, P = 2, 5, 32, 5
        eng = Engine(make_kernel(G, R, W, P, num_key_buckets=25))
        state, ns = eng.init()
        T = 40
        state, ns, _ = run(eng, state, ns, T, n_prop=P)
        st = np_state(state)
        assert (st["own_next"] >= T - 2).all()
        assert (st["cmt_row"] >= st["own_next"][:, None, :] - 8).all(), (
            st["cmt_row"][0]
        )


class TestInterference:
    def test_conflicting_execution_order_agrees(self):
        # few buckets -> heavy cross-row interference; every replica must
        # execute same-bucket commands in the same order
        G, R, W, P = 2, 5, 32, 5
        K = 2
        eng = Engine(make_kernel(G, R, W, P, num_key_buckets=K))
        state, ns = eng.init()
        state, ns, fx = run(eng, state, ns, 60, n_prop=P, collect=True)
        st = np_state(state)
        check_agreement(st, G, R)
        orders = exec_orders(fx, G, R, K)
        for g in range(G):
            ref = orders[(g, 0)]
            for r in range(1, R):
                got = orders[(g, r)]
                for b in range(K):
                    n = min(len(ref[b]), len(got[b]))
                    assert ref[b][:n] == got[b][:n], (
                        g, r, b, ref[b][:n], got[b][:n]
                    )
                    assert n > 10, (g, r, b, n)


@pytest.mark.slow
class TestFailover:
    def test_dead_row_recovered_by_successor(self):
        G, R, W, P = 2, 5, 32, 5
        eng = Engine(make_kernel(G, R, W, P, alive_timeout=10))
        state, ns = eng.init()
        state, ns, _ = run(eng, state, ns, 20, n_prop=P)
        pre = np_state(state)

        alive = jnp.ones((G, R), jnp.bool_).at[:, 0].set(False)
        state, ns, _ = run(
            eng, state, ns, 120, n_prop=P, alive=alive, base_start=1000
        )
        post = np_state(state)
        # surviving rows keep committing
        assert (post["cmt_row"][:, 1:, 1:] > pre["cmt_row"][:, 1:, 1:]).all()
        # row 0's tail was resolved at the survivors: their commit frontier
        # for row 0 reaches everything row 0 ever proposed
        for g in range(G):
            ext0 = post["ext_row"][g, 1:, 0].max()
            for r in range(1, R):
                assert post["cmt_row"][g, r, 0] >= ext0, (
                    g, r, post["cmt_row"][g, :, 0], ext0
                )
        check_agreement(post, G, R)
        # previously committed row-0 instances survive recovery
        for g in range(G):
            before = committed_instances(pre, g, 1)
            after = committed_instances(post, g, 1)
            for slot, v in before.items():
                if slot[0] == 0 and slot in after:
                    assert after[slot][0] == v[0], (g, slot, v, after[slot])

    def test_execution_proceeds_past_recovered_row(self):
        # after recovery (committed or no-op), execution frontiers of
        # surviving replicas keep advancing for all rows
        G, R, W, P = 2, 5, 32, 4
        eng = Engine(make_kernel(G, R, W, P, alive_timeout=10,
                                 num_key_buckets=2))
        state, ns = eng.init()
        state, ns, _ = run(eng, state, ns, 20, n_prop=P)
        alive = jnp.ones((G, R), jnp.bool_).at[:, 0].set(False)
        state, ns, _ = run(
            eng, state, ns, 150, n_prop=P, alive=alive, base_start=1000
        )
        post = np_state(state)
        for r in range(1, R):
            assert (post["exec_row"][:, r, :] >= post["cmt_row"][:, r, :] - 2
                    ).all(), (r, post["exec_row"][0], post["cmt_row"][0])
        check_agreement(post, G, R)


@pytest.mark.slow
class TestAdjacentFailures:
    def test_two_adjacent_dead_rows_both_recovered(self):
        # regression: replicas 2 and 3 die together (simple_q survivors
        # remain); the successor must recover row 3 AND then row 2, or
        # dependent execution stalls forever
        G, R, W, P = 2, 5, 32, 5
        eng = Engine(make_kernel(G, R, W, P, alive_timeout=10,
                                 num_key_buckets=2))
        state, ns = eng.init()
        state, ns, _ = run(eng, state, ns, 20, n_prop=P)

        alive = (
            jnp.ones((G, R), jnp.bool_).at[:, 2].set(False).at[:, 3].set(False)
        )
        state, ns, _ = run(
            eng, state, ns, 250, n_prop=P, alive=alive, base_start=1000
        )
        post = np_state(state)
        live = [0, 1, 4]
        for dead_row in (2, 3):
            ext = post["ext_row"][:, live, dead_row].max(axis=1)
            for r in live:
                assert (post["cmt_row"][:, r, dead_row] >= ext).all(), (
                    dead_row, r, post["cmt_row"][0, :, dead_row], ext
                )
        # execution keeps pace everywhere that's alive
        for r in live:
            assert (
                post["exec_row"][:, r, :] >= post["cmt_row"][:, r, :] - 2
            ).all(), (r, post["exec_row"][0], post["cmt_row"][0])
        check_agreement(post, G, R)


@pytest.mark.slow
class TestConcurrentRecoverers:
    def test_recoverer_dies_midway_successor_uses_higher_ballot(self):
        """Regression for the r2 recovery fix (VERDICT r3 #8): two
        recoverers touch the same dead row at DIFFERENT ERP ballots — the
        first successor starts the campaign and then dies itself; the
        next-in-ring successor must re-campaign at a strictly higher
        ballot and finish, with every survivor agreeing on the outcome
        (reference ladder: dependency.rs:249-330)."""
        G, R, W, P = 1, 5, 32, 5
        eng = Engine(make_kernel(G, R, W, P, alive_timeout=10))
        state, ns = eng.init()
        state, ns, _ = run(eng, state, ns, 20, n_prop=P)
        pre = np_state(state)

        # kill row 0's owner; run just past the alive timeout so the
        # first successor (r1) has STARTED recovering row 0
        alive1 = jnp.ones((G, R), jnp.bool_).at[:, 0].set(False)
        state, ns, _ = run(
            eng, state, ns, 14, n_prop=0, alive=alive1, base_start=1000
        )
        mid = np_state(state)
        bal1 = int(mid["rec_bal"][0, 1]) if mid["rec_row"][0, 1] == 0 else 0

        # now the first recoverer dies mid-flight too: r2 takes over
        alive2 = alive1.at[:, 1].set(False)
        state, ns, _ = run(
            eng, state, ns, 200, n_prop=P, alive=alive2, base_start=2000
        )
        post = np_state(state)
        live = [2, 3, 4]
        # rows 0 and 1 fully resolved at every survivor
        for dead_row in (0, 1):
            ext = post["ext_row"][:, live, dead_row].max(axis=1)
            for r in live:
                assert (post["cmt_row"][:, r, dead_row] >= ext).all(), (
                    dead_row, r, post["cmt_row"][0, :, dead_row], ext
                )
        # the second campaign outbid the first (per-row ballot monotone)
        if bal1 > 0:
            assert int(post["rbm"][0, 2:, 0].max()) > bal1
        check_agreement(post, G, R)
        # nothing committed before the failures was lost or changed
        before = committed_instances(pre, 0, 1)
        for r in live:
            after = committed_instances(post, 0, r)
            for slot, v in before.items():
                if slot in after:
                    assert after[slot][0] == v[0], (r, slot, v, after[slot])


@pytest.mark.slow
class TestLossyNetwork:
    def test_agreement_under_drops(self):
        G, R, W, P = 2, 5, 32, 5
        k = make_kernel(G, R, W, P, alive_timeout=25)
        net = NetConfig(
            delay_ticks=1, jitter_ticks=2, drop_rate=0.15, max_delay_ticks=4
        )
        eng = Engine(k, netcfg=net, seed=11)
        state, ns = eng.init()
        state, ns, _ = run(eng, state, ns, 300, n_prop=P)
        st = np_state(state)
        assert (st["cmt_row"].max(axis=1) > 30).all()
        check_agreement(st, G, R)


class TestMultiBucketIntake:
    """Host-mode per-tick vid LISTS (prop_vids): one tick proposes
    several key buckets at once — the one-bucket-per-tick deferral is
    gone (reference: EPaxos commits interfering and non-interfering
    commands concurrently, dependency.rs:180-240)."""

    def test_vid_list_proposes_all_buckets_one_tick(self):
        G, R, W = 1, 3, 32
        K = 4
        eng = Engine(make_kernel(G, R, W, P=4, num_key_buckets=K))
        state, ns = eng.init()
        me = 0
        # vids in residue classes for buckets 1, 3, 0 of replica `me`
        vids = [1 + K * me, 3 + K * me, 0 + K * me + K * R]
        pv = np.zeros((G, 4), np.int32)
        pv[0, :3] = vids
        inputs = {
            "n_proposals": jnp.asarray([3], jnp.int32),
            "value_base": jnp.asarray([vids[0]], jnp.int32),
            "prop_replica": jnp.asarray([me], jnp.int32),
            "prop_vids": jnp.asarray(pv),
        }
        state, ns, _ = eng.tick(state, ns, inputs)
        st = np_state(state)
        assert int(st["own_next"][0, me]) == 3
        got = [int(st["val2"][0, me, me, p]) for p in range(3)]
        assert got == vids, got
        # distinct buckets: no intra-batch dependency chaining between
        # them (deps on own row stay at the instance's own column bar)
        buckets = [v % K for v in got]
        assert len(set(buckets)) == 3, buckets

    def test_multi_bucket_commits_under_run(self):
        # drive several ticks of 2-bucket vid lists and confirm commits
        # cover every proposed vid with agreement across replicas
        G, R, W = 2, 3, 32
        K = 4
        eng = Engine(make_kernel(G, R, W, P=4, num_key_buckets=K))
        state, ns = eng.init()
        me = 0
        proposed = []
        next_res = [1, 1]  # per-bucket residue counters (buckets 0, 1)
        for t in range(30):
            pv = np.zeros((G, 4), np.int32)
            n = 0
            if t < 10:
                for b in range(2):
                    vid = b + K * me + K * R * next_res[b]
                    next_res[b] += 1
                    pv[:, n] = vid
                    n += 1
                    proposed.append(vid)
            inputs = {
                "n_proposals": jnp.full((G,), n, jnp.int32),
                "value_base": jnp.full((G,), int(pv[0, 0]), jnp.int32),
                "prop_replica": jnp.full((G,), me, jnp.int32),
                "prop_vids": jnp.asarray(pv),
            }
            state, ns, _ = eng.tick(state, ns, inputs)
        st = np_state(state)
        check_agreement(st, G, R)
        for g in range(G):
            committed_vids = {
                v for (_rc, (v, _s)) in
                committed_instances(st, g, 0).items()
            }
            assert set(proposed) <= committed_vids, (
                sorted(set(proposed) - committed_vids)
            )
