"""Compartmentalized serving plane (host/ingress.py): routing-table
units, learner read-tier logic, proxy-hop trace export, and live
cluster-behind-proxies integration — accept/dedupe/batch/route through
real sockets, proxy crash + rediscovery, and the commit-feed
subscribe/note/probe seam the read tier rides."""

import os
import socket
import sys
import threading
import time

import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "scripts",
))

import trace_export  # noqa: E402

from summerset_tpu.host.ingress import (  # noqa: E402
    LEARNER_ID_OFFSET, LearnerReadTier, RoutingTable, ServingPlane,
)
from summerset_tpu.host.messages import ApiReply, ApiRequest  # noqa: E402
from summerset_tpu.host.statemach import Command  # noqa: E402
from summerset_tpu.host.telemetry import (  # noqa: E402
    MetricsRegistry, PROXY_DECLARED,
)
from summerset_tpu.host.tracing import FlightRecorder  # noqa: E402
from summerset_tpu.utils import safetcp  # noqa: E402


# ---------------------------------------------------------------- units --
class TestRoutingTable:
    def test_default_full_range_to_leader(self):
        rt = RoutingTable()
        rt.update({0: ("h", 1), 1: ("h", 2), 2: ("h", 3)}, leader=1)
        assert rt.owner_for("") == 1
        assert rt.owner_for("zzz") == 1
        assert rt.write_target() == 1

    def test_no_leader_falls_back_to_lowest_sid(self):
        rt = RoutingTable()
        rt.update({2: ("h", 3), 0: ("h", 1)}, leader=None)
        assert rt.owner_for("k") == 0

    def test_note_leader_rebuilds_but_keeps_overrides(self):
        rt = RoutingTable()
        rt.update({0: ("h", 1), 1: ("h", 2)}, leader=0)
        rt.set_owner("a", "m", 1)
        assert rt.owner_for("b") == 1 and rt.owner_for("x") == 0
        rt.note_leader(1)
        assert rt.owner_for("x") == 1
        assert rt.owner_for("b") == 1  # override survives
        assert rt.version >= 3

    def test_negative_hint_ignored(self):
        rt = RoutingTable()
        rt.update({0: ("h", 1)}, leader=0)
        v = rt.version
        rt.note_leader(-1)
        assert rt.leader == 0 and rt.version == v

    def test_reader_prefers_non_leader_responder(self):
        rt = RoutingTable()
        rt.update({0: ("h", 1), 1: ("h", 2), 2: ("h", 3)},
                  leader=0, responders=[0, 2])
        assert rt.reader_sid() == 2  # responder, not the leader
        rt.update({0: ("h", 1), 1: ("h", 2), 2: ("h", 3)},
                  leader=0, responders=[])
        assert rt.reader_sid() in (1, 2)  # any non-leader
        rt.update({0: ("h", 1)}, leader=0, responders=[])
        assert rt.reader_sid() is None  # never the proposer

    def test_declared_proxy_series_unique(self):
        assert len(PROXY_DECLARED) == len(set(PROXY_DECLARED))

    def test_set_owner_same_span_replaces(self):
        rt = RoutingTable()
        rt.update({0: ("h", 1), 1: ("h", 2)}, leader=0)
        rt.set_owner("a", "b", 1)
        rt.set_owner("a", "b", 0)
        rt.set_owner("a", "b", 1)
        # re-setting the same span replaces the entry instead of
        # growing the override list without bound
        assert len(rt._overrides) == 1
        assert rt.owner_for("a") == 1

    def test_dead_sid_override_falls_back(self):
        rt = RoutingTable()
        rt.update({0: ("h", 1), 1: ("h", 2)}, leader=0)
        rt.set_owner("a", "b", 1)
        assert rt.owner_for("a") == 1
        # the override's owner drops out of the address book: its range
        # must fall back to the default, not wedge on an unreachable sid
        rt.update({0: ("h", 1)}, leader=0)
        assert rt.owner_for("a") == 0
        # ...and come back once the owner rejoins
        rt.update({0: ("h", 1), 1: ("h", 2)}, leader=0)
        assert rt.owner_for("a") == 1

    def test_installed_ranges_below_manual_overrides(self):
        rt = RoutingTable()
        rt.update({0: ("h", 1), 1: ("h", 2), 2: ("h", 3)}, leader=0)
        rt.set_ranges([("a", "c", 1), ("c", "d", 2)])
        assert rt.owner_for("b") == 1 and rt.owner_for("c") == 2
        rt.set_owner("a", "b", 2)  # manual override wins
        assert rt.owner_for("a") == 2 and rt.owner_for("b") == 1
        v = rt.version
        rt.set_ranges([("a", "c", 1), ("c", "d", 2)])  # unchanged
        assert rt.version == v  # refresh loop must not churn versions

    def test_scan_start_key_routes_to_range_owner(self):
        # scans route by their start key: the proxy forwards the whole
        # span to the owner of [start, ...) and the learner/fused serve
        # path bounds the slice — boundary keys land on the RIGHT range
        rt = RoutingTable()
        rt.update({0: ("h", 1), 1: ("h", 2), 2: ("h", 3)}, leader=0)
        rt.set_ranges([("a", "c", 1), ("c", "d", 2)])
        assert rt.owner_for("a") == 1    # scan starting at range head
        assert rt.owner_for("b\x00") == 1
        assert rt.owner_for("c") == 2    # exact split point: new owner
        assert rt.owner_for("d") == 0    # past installed ranges: leader


class _FakeProxy:
    """Duck-typed IngressProxy core for LearnerReadTier unit tests."""

    def __init__(self):
        import collections

        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._requeue = collections.deque()
        self._pends = {}
        self.cid = 1234
        self.metrics = MetricsRegistry()
        self.flight = FlightRecorder(enabled=True, me=1234)
        self.routing = RoutingTable()  # empty: learner thread idles
        self.replies = []

    def _pop_pend(self, prid):
        return self._pends.pop(prid, None)

    def _drop_pend(self, prid):
        self._pop_pend(prid)

    def _reply_client(self, pend, reply, cache=True):
        self.replies.append((pend["client"], reply))


class TestLearnerUnit:
    def _mk(self):
        p = _FakeProxy()
        lt = LearnerReadTier(p)
        return p, lt

    def test_not_ready_refuses_probe(self):
        p, lt = self._mk()
        assert not lt.try_probe(1, Command("get", "k"))
        p._stop.set()

    def test_probe_reply_serves_from_learned_state(self):
        p, lt = self._mk()
        lt.kv = {"k": "v7"}
        lt.seq = 5
        p._pends[9] = {"client": 3, "req_id": 40,
                       "cmd": Command("get", "k")}
        with p._lock:
            lt._probes[9] = time.monotonic() + 2
        lt._on_probe_reply(ApiReply("probe", req_id=9, success=True,
                                    seq=5))
        assert p.replies and p.replies[0][0] == 3
        rep = p.replies[0][1]
        assert rep.kind == "reply" and rep.result.value == "v7"
        assert rep.local
        assert p.metrics.counter_value("read_tier_served") == 1
        p._stop.set()

    def test_refused_probe_falls_back_and_backs_off(self):
        p, lt = self._mk()
        lt.kv = {}
        lt.seq = 5
        lt.ready = True
        lt._sock = object()  # never used: refusal path only
        p._pends[9] = {"client": 3, "req_id": 40,
                       "cmd": Command("get", "k")}
        with p._lock:
            lt._probes[9] = time.monotonic() + 2
        lt._on_probe_reply(ApiReply("probe", req_id=9, success=False,
                                    seq=5))
        assert list(p._requeue) == [9]          # owner path takes over
        assert not p.replies
        # refusal backoff: the next probe is suppressed entirely
        assert not lt.try_probe(10, Command("get", "k"))
        p._stop.set()

    def test_stale_seq_falls_back(self):
        p, lt = self._mk()
        lt.seq = 3                              # learned stream behind
        p._pends[9] = {"client": 3, "req_id": 40,
                       "cmd": Command("get", "k")}
        with p._lock:
            lt._probes[9] = time.monotonic() + 2
        lt._on_probe_reply(ApiReply("probe", req_id=9, success=True,
                                    seq=8))
        assert list(p._requeue) == [9]
        p._stop.set()

    def test_expired_probe_drops_pend(self):
        p, lt = self._mk()
        p._pends[9] = {"client": 3, "req_id": 40,
                       "cmd": Command("get", "k")}
        with p._lock:
            lt._probes[9] = time.monotonic() - 1
        lt.expire_probes(time.monotonic())
        assert 9 not in p._pends and not lt._probes
        p._stop.set()

    def _seed_scan_state(self, lt):
        lt.kv = {"w1": "v1", "w2": "v2", "w3": "v3", "x9": "z"}
        lt._keys = sorted(lt.kv)
        lt.seq = 5

    def test_probe_reply_serves_scan_from_ordered_index(self):
        p, lt = self._mk()
        self._seed_scan_state(lt)
        p._pends[9] = {"client": 3, "req_id": 40,
                       "cmd": Command("scan", "w1", end="w4", limit=2)}
        with p._lock:
            lt._probes[9] = time.monotonic() + 2
        lt._on_probe_reply(ApiReply("probe", req_id=9, success=True,
                                    seq=5))
        assert p.replies and p.replies[0][0] == 3
        rep = p.replies[0][1]
        assert rep.kind == "reply" and rep.local
        assert rep.result.kind == "scan"
        # limit clips the ordered slice; "x9" excluded by end="w4"
        assert rep.result.items == (("w1", "v1"), ("w2", "v2"))
        assert p.metrics.counter_value("read_tier_served") == 1
        assert p.metrics.counter_value("read_tier_scans") == 1
        assert any(e["type"] == "scan_serve"
                   for e in p.flight.dump()["events"])
        p._stop.set()

    def test_scan_stale_seq_falls_back_to_owner_path(self):
        p, lt = self._mk()
        self._seed_scan_state(lt)
        lt.seq = 3  # learned stream behind the probe verdict
        p._pends[9] = {"client": 3, "req_id": 40,
                       "cmd": Command("scan", "w1", end="w4", limit=8)}
        with p._lock:
            lt._probes[9] = time.monotonic() + 2
        lt._on_probe_reply(ApiReply("probe", req_id=9, success=True,
                                    seq=8))
        assert list(p._requeue) == [9]
        assert not p.replies
        assert p.metrics.counter_value("read_tier_scans") == 0
        p._stop.set()

    def test_scan_learned_open_end_and_no_limit(self):
        p, lt = self._mk()
        self._seed_scan_state(lt)
        # open end runs to the index tail; limit=0 means unbounded
        assert lt.scan_learned("w2", None, 0) == (
            ("w2", "v2"), ("w3", "v3"), ("x9", "z"))
        assert lt.scan_learned("w2", "w3", 0) == (("w2", "v2"),)
        assert lt.scan_learned("zz", None, 0) == ()
        p._stop.set()


# -------------------------------------------------- proxy-hop export --
def _proxy_hop_dumps():
    """Synthetic proxy + shard flight dumps forming one forwarded op:
    client -> proxy (api_ingress) -> shard (proxy_fwd/api_ingress) ->
    reply (api_reply/proxy_rcv) -> client (api_reply)."""
    t = [1000 * i for i in range(1, 9)]
    proxy = {
        "v": 1, "me": 1001, "tier": "proxy", "count": 4, "dropped": 0,
        "t_start_us": 0, "t_dump_us": 99999,
        "events": [
            {"n": 0, "t_us": t[0], "type": "api_ingress",
             "client": 2000, "req_id": 7, "kind": "req"},
            {"n": 1, "t_us": t[1], "type": "proxy_fwd", "sid": 0,
             "prid": 55, "n": 1, "fwd_id": 1001},
            {"n": 2, "t_us": t[5], "type": "proxy_rcv", "sid": 0,
             "prid": 56, "kind": "reply"},
            {"n": 3, "t_us": t[6], "type": "api_reply",
             "client": 2000, "req_id": 7, "kind": "reply"},
            {"n": 4, "t_us": t[6] + 10, "type": "read_serve",
             "client": 2001, "req_id": 9, "seq": 3},
        ],
    }
    shard = {
        "v": 1, "me": 0, "protocol": "MultiPaxos", "count": 3,
        "dropped": 0, "t_start_us": 0, "t_dump_us": 99999,
        "events": [
            {"n": 0, "t_us": t[2], "type": "api_ingress",
             "client": 1001, "req_id": 55, "kind": "batch"},
            {"n": 1, "t_us": t[3], "type": "commit", "g": 0, "vid": 1,
             "slot": 0, "tick": 3},
            {"n": 2, "t_us": t[4], "type": "api_reply",
             "client": 1001, "req_id": 56, "kind": "reply"},
        ],
    }
    return {"p0": proxy, "0": shard}


class TestProxyHopExport:
    def test_flow_arrows_and_schema(self):
        doc = trace_export.export_chrome(_proxy_hop_dumps(), align=False)
        assert trace_export.validate_chrome(doc) == []
        evs = doc["traceEvents"]
        hops = [e for e in evs if e.get("cat") == "proxyhop"]
        # one forward arrow (proxy_fwd -> shard api_ingress) and one
        # reply arrow (shard api_reply -> proxy_rcv), each s+f
        fwd = [e for e in hops if e["id"] == "phop-1001-55"]
        rep = [e for e in hops if e["id"] == "prep-1001-56"]
        assert sorted(e["ph"] for e in fwd) == ["f", "s"]
        assert sorted(e["ph"] for e in rep) == ["f", "s"]
        # arrows start at the proxy / shard respectively
        assert {e["pid"] for e in fwd} == {1001, 0}
        names = {e.get("name") for e in evs}
        assert "read_serve" in names
        # proxy process labeled as a proxy, not a replica
        procs = [e for e in evs
                 if e.get("ph") == "M" and e["name"] == "process_name"]
        assert any("proxy 1001" in e["args"]["name"] for e in procs)

    def test_no_arrows_without_proxy_dumps(self):
        dumps = _proxy_hop_dumps()
        del dumps["p0"]
        doc = trace_export.export_chrome(dumps, align=False)
        assert trace_export.validate_chrome(doc) == []
        assert not [
            e for e in doc["traceEvents"]
            if e.get("cat") == "proxyhop"
        ]


# ------------------------------------------------------------ muxfleet --
class TestMuxFleet:
    """The selector-multiplexed closed-loop fleet against a bare
    ExternalApi echo tier: framing, closed-loop pacing, shed parking,
    concurrency accounting — no consensus cluster needed."""

    @pytest.fixture()
    def echo_api(self):
        from summerset_tpu.host.external import ExternalApi

        import socket as socket_mod

        s = socket_mod.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        api = ExternalApi(("127.0.0.1", port), max_pending=64)
        stop = threading.Event()

        def pump():
            from summerset_tpu.host.statemach import CommandResult

            while not stop.is_set():
                for client, req in api.get_req_batch(timeout=0.05):
                    if req.kind in ("req",):
                        api.send_reply(ApiReply(
                            "reply", req_id=req.req_id,
                            result=CommandResult("get", value="x"),
                        ), client)
        t = threading.Thread(target=pump, daemon=True)
        t.start()
        yield ("127.0.0.1", port)
        stop.set()
        api.stop()

    def test_closed_loop_fleet(self, echo_api):
        from summerset_tpu.client.muxfleet import run_fleet

        out = run_fleet([echo_api], clients=50, secs=1.5, seed=3)
        assert out["connected_peak"] == 50
        assert out["acked"] > 50          # multiple rounds per client
        assert out["issued"] >= out["acked"]
        assert out["timeouts"] == 0
        assert out["lat_p50_ms"] > 0

    def test_think_time_paces_offered_rate(self, echo_api):
        from summerset_tpu.client.muxfleet import run_fleet

        out = run_fleet(
            [echo_api], clients=40, secs=2.0, seed=3, think=1.0,
        )
        assert out["connected_peak"] == 40
        # staggered first ops: ~secs/think * clients ops total, far
        # below the unpaced hot loop
        assert 0 < out["acked"] < 40 * 6


# ----------------------------------------------------------- live tier --
@pytest.fixture(scope="module")
def proxied_cluster(tmp_path_factory):
    """One MultiPaxos cluster with a 2-proxy serving plane in front."""
    from test_cluster import Cluster

    c = Cluster(
        "MultiPaxos", 3, tmp_path_factory.mktemp("ingress_cluster"),
    )
    plane = ServingPlane(c.manager_addr, proxies=2).start()
    yield c, plane
    plane.stop()
    c.stop()


def _fresh_ep(cluster, **kw):
    from summerset_tpu.client.endpoint import GenericEndpoint

    ep = GenericEndpoint(cluster.manager_addr, **kw)
    ep.connect()
    return ep


class TestLiveProxyServing:
    def test_roundtrips_through_proxy(self, proxied_cluster):
        from summerset_tpu.client.drivers import DriverClosedLoop

        cluster, plane = proxied_cluster
        ep = _fresh_ep(cluster)
        assert ep.proxy_mode, "client must auto-discover the proxy tier"
        drv = DriverClosedLoop(ep, timeout=10.0)
        for i in range(8):
            drv.checked_put(f"ik{i}", f"iv{i}")
        for i in range(8):
            drv.checked_get(f"ik{i}", expect=f"iv{i}")
        routed = sum(
            p.metrics.counter_value("proxy_routed")
            for p in plane.proxies if p is not None
        )
        assert routed > 0
        ep.leave()

    def test_direct_server_pin_bypasses_proxies(self, proxied_cluster):
        cluster, _plane = proxied_cluster
        ep = _fresh_ep(cluster, server_id=0)
        assert not ep.proxy_mode  # byte-compatible fused path
        ep.leave()

    def test_dedupe_replays_cached_reply(self, proxied_cluster):
        cluster, plane = proxied_cluster
        ep = _fresh_ep(cluster)
        assert ep.proxy_mode
        before = sum(
            p.metrics.counter_value("proxy_dedupe_hits")
            for p in plane.proxies if p is not None
        )
        ep.api.send_req(ApiRequest(
            "req", req_id=1, cmd=Command("put", "ded", "v1"),
        ))
        rep1 = ep.recv_reply(timeout=10)
        assert rep1.kind == "reply"
        # client retransmit of the SAME (client, req_id): the proxy
        # replays its cached reply without re-proposing
        ep.api.send_req(ApiRequest(
            "req", req_id=1, cmd=Command("put", "ded", "v1"),
        ))
        rep2 = ep.recv_reply(timeout=10)
        assert rep2.kind == "reply" and rep2.req_id == 1
        after = sum(
            p.metrics.counter_value("proxy_dedupe_hits")
            for p in plane.proxies if p is not None
        )
        assert after == before + 1
        ep.leave()

    def test_range_override_steers_forwarded_batches(
        self, proxied_cluster,
    ):
        """A per-range owner override must actually steer forwarded
        batches — live: the op forwards to the overridden (follower)
        sid first, survives the redirect retry, AND the override holds
        across the 0.5s routing refresh (which rebuilds the table and
        folds in manager-announced ranges below manual overrides)."""
        from summerset_tpu.client.drivers import DriverClosedLoop
        from summerset_tpu.host.messages import CtrlRequest

        cluster, plane = proxied_cluster
        ep = _fresh_ep(cluster)
        assert ep.proxy_mode
        info = ep.ctrl.request(CtrlRequest("query_info"))
        leader = info.leader if info.leader is not None else 0
        follower = next(
            s for s in sorted(info.servers) if s != leader
        )
        live = [p for p in plane.proxies if p is not None]

        def fwd_to(sid):
            return sum(
                1 for p in live
                for e in p.flight.dump()["events"]
                if e["type"] == "proxy_fwd" and e.get("sid") == sid
            )

        before = fwd_to(follower)
        for p in live:
            p.routing.set_owner("ovq", "ovr", follower)
        # cross at least one refresh cycle: the refresher rebuilds the
        # table (leader + installed ranges) and must NOT flush the
        # manual override — the dormant-override regression
        time.sleep(0.8)
        assert all(
            p.routing.owner_for("ovq1") == follower for p in live
        )
        drv = DriverClosedLoop(ep, timeout=10.0)
        drv.checked_put("ovq1", "steered")   # in ["ovq", "ovr")
        drv.checked_get("ovq1", expect="steered")
        # the forward went to the overridden sid (then the shard's
        # redirect hint bounced it to the leader — op still completed)
        assert fwd_to(follower) > before
        for p in live:   # steer back: later tests use default routing
            p.routing.set_owner("ovq", "ovr", leader)
        ep.leave()

    def test_commit_feed_subscribe_note_probe(self, proxied_cluster):
        """The read-tier seam raw: subscribe to a replica's commit
        feed, watch an applied put stream as a note, and probe (refused
        on MultiPaxos — no leases — but carrying the feed seq)."""
        from summerset_tpu.client.drivers import DriverClosedLoop

        cluster, _plane = proxied_cluster
        # seed a write through the normal path
        ep = _fresh_ep(cluster)
        drv = DriverClosedLoop(ep, timeout=10.0)
        drv.checked_put("feedk", "feedv0")

        # raw learner connection straight to a follower replica
        info = ep.ctrl.request(
            __import__(
                "summerset_tpu.host.messages", fromlist=["CtrlRequest"]
            ).CtrlRequest("query_info")
        )
        leader = info.leader if info.leader is not None else 0
        sid = next(s for s in sorted(info.servers) if s != leader)
        addr = tuple(info.servers[sid][0])
        sock = socket.create_connection(addr, timeout=5)
        sock.settimeout(10)
        safetcp.send_msg_sync(sock, 999_999 + LEARNER_ID_OFFSET)
        safetcp.send_msg_sync(sock, ApiRequest("sub", req_id=3))
        sub = safetcp.recv_msg_sync(sock)
        assert sub.kind == "sub" and sub.success
        seq0 = sub.seq
        learned = dict(sub.notes or {})
        # the ack rides the LEADER's apply; this follower may apply the
        # put a tick later — in which case it arrives as a note > seq0
        # (the exact snapshot-plus-stream contract the read tier uses)
        if learned.get("feedk") != "feedv0":
            deadline = time.monotonic() + 20
            while learned.get("feedk") != "feedv0":
                assert time.monotonic() < deadline, \
                    "snapshot catch-up note never arrived"
                rep = safetcp.recv_msg_sync(sock)
                if rep.kind == "note":
                    for _s, k, v in rep.notes:
                        learned[k] = v

        # a new applied put must stream as a note, after durability
        drv.checked_put("feedk", "feedv1")
        deadline = time.monotonic() + 20
        seen = None
        while time.monotonic() < deadline:
            rep = safetcp.recv_msg_sync(sock)
            if rep.kind == "note":
                for s, k, v in rep.notes:
                    if k == "feedk" and v == "feedv1":
                        seen = (s, rep.seq)
                if seen:
                    break
        assert seen is not None, "commit note never arrived"
        assert seen[0] > seq0 and seen[1] >= seen[0]

        # probes refuse without leases but answer with the current seq
        safetcp.send_msg_sync(sock, ApiRequest(
            "probe", req_id=4, cmd=Command("get", "feedk"),
        ))
        probe = None
        while probe is None:
            rep = safetcp.recv_msg_sync(sock)
            if rep.kind == "probe":
                probe = rep
        assert not probe.success         # MultiPaxos: no lease plane
        assert probe.seq >= seen[0]
        sock.close()
        ep.leave()

    def test_proxy_crash_rediscovery_and_restart(self, proxied_cluster):
        from summerset_tpu.client.drivers import DriverClosedLoop
        from summerset_tpu.host.messages import CtrlRequest

        cluster, plane = proxied_cluster
        ep = _fresh_ep(cluster)
        assert ep.proxy_mode
        drv = DriverClosedLoop(ep, timeout=10.0)
        drv.checked_put("ck", "cv")
        victim = plane.ports.index(ep.api.sock.getpeername()[1])
        plane.crash_proxy(victim)
        # the dead proxy deregisters with its ctrl connection; the
        # client's rotate/backoff machinery rides to the survivor
        drv.checked_put("ck", "cv2")
        drv.checked_get("ck", expect="cv2")
        info = ep.ctrl.request(CtrlRequest("query_info"))
        assert len(info.proxies or {}) == 1
        plane.restart_proxy(victim)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            info = ep.ctrl.request(CtrlRequest("query_info"))
            if len(info.proxies or {}) == 2:
                break
            time.sleep(0.2)
        assert len(info.proxies or {}) == 2
        ep.leave()


@pytest.mark.slow
class TestLiveReadTierQuorumLeases:
    def test_lease_local_learner_reads(self, tmp_path):
        """QuorumLeases: the learner read tier serves gets from its
        learned state (probe-gated) and stays fresh across writes."""
        from test_cluster import Cluster

        from summerset_tpu.client.drivers import DriverClosedLoop

        c = Cluster("QuorumLeases", 3, tmp_path)
        plane = ServingPlane(c.manager_addr, proxies=1).start()
        try:
            ep = _fresh_ep(c)
            drv = DriverClosedLoop(ep, timeout=10.0)
            # grant read leases everywhere: lease-LOCAL reads need an
            # installed responders conf (the learner's probes refuse,
            # harmlessly, until the grant lands)
            drv.conf_change({"responders": [0, 1, 2]})
            time.sleep(2.0)  # learner subscribe + lease grants settle
            for i in range(3):
                drv.checked_put(f"qk{i}", f"qv{i}")
            time.sleep(1.5)
            for _ in range(3):
                for i in range(3):
                    drv.checked_get(f"qk{i}", expect=f"qv{i}")
            served = plane.proxies[0].metrics.counter_value(
                "read_tier_served"
            )
            assert served > 0, "no learner-local reads served"
            # freshness: write-then-read interleave must never serve
            # a stale learned value
            for i in range(6):
                drv.checked_put("qhot", f"qh{i}")
                drv.checked_get("qhot", expect=f"qh{i}")
            ep.leave()
        finally:
            plane.stop()
            c.stop()
