"""Vectorized CRaft kernel tests: erasure-coded commit thresholds, full-copy
fallback on peer death, mixed-mode commit frontier, follower reconstruction
(reference behaviors: ``craft/messages.rs:307-312``,
``craft/leadership.rs:75-137, 280-287``).
"""

import jax.numpy as jnp
import numpy as np

from smr_helpers import check_agreement, committed_values, run_segment
from summerset_tpu.core import Engine, NetConfig
from summerset_tpu.protocols import make_protocol
from summerset_tpu.protocols.craft import ReplicaConfigCRaft
import pytest


def make_kernel(G, R, W, P, **kw):
    cfg = ReplicaConfigCRaft(max_proposals_per_tick=P, **kw)
    return make_protocol("craft", G, R, W, cfg)


class TestSteadyState:
    def test_commit_flow_and_values(self):
        G, R, W, P = 4, 5, 32, 4
        k = make_kernel(G, R, W, P, fault_tolerance=1)
        eng = Engine(k)
        state, ns = eng.init()
        T = 50
        state, ns, _ = run_segment(eng, state, ns, T, n_prop=P)
        st = {k_: np.asarray(v) for k_, v in state.items()}
        assert (st["commit_bar"][:, 0] >= (T - 6) * P).all(), st["commit_bar"]
        for g in range(G):
            vals = committed_values(st, g, 0, W)
            assert vals
            for slot, v in vals.items():
                assert v == slot
        check_agreement(st, G, R, W)
        # healthy cluster stays in coded mode
        assert not st["win_full"][:, 0].any()

    def test_follower_exec_catches_up_via_recon(self):
        G, R, W, P = 2, 5, 32, 2
        k = make_kernel(G, R, W, P, fault_tolerance=1, recon_interval=2)
        eng = Engine(k)
        state, ns = eng.init()
        state, ns, _ = run_segment(eng, state, ns, 40, n_prop=P)
        state, ns, _ = run_segment(eng, state, ns, 30, n_prop=0)
        st = {k_: np.asarray(v) for k_, v in state.items()}
        cb = st["commit_bar"].max(axis=1, keepdims=True)
        assert (cb > 0).all()
        assert (st["full_bar"] >= cb).all(), (st["full_bar"], cb)
        assert (st["exec_bar"] >= cb).all()


class TestFullCopyFallback:
    @pytest.mark.slow
    def test_fallback_keeps_committing_where_coded_stalls(self):
        # R=5, ft=1: coded commits need 4 acks. Kill 2 replicas: after the
        # liveness countdown expires the leader stamps new entries full-copy
        # (threshold 3) and commits keep flowing — the CRaft headline
        # behavior vs RSPaxos, which stalls in the same scenario.
        G, R, W, P = 2, 5, 48, 2
        k = make_kernel(
            G, R, W, P, fault_tolerance=1, alive_timeout=10, recon_interval=2
        )
        eng = Engine(k)
        state, ns = eng.init()
        state, ns, _ = run_segment(eng, state, ns, 20, n_prop=P)
        pre = np.asarray(state["commit_bar"]).copy()

        alive = (
            jnp.ones((G, R), jnp.bool_).at[:, 3].set(False).at[:, 4].set(False)
        )
        state, ns, _ = run_segment(
            eng, state, ns, 120, n_prop=P, alive=alive, base_start=1000
        )
        mid = {k_: np.asarray(v) for k_, v in state.items()}
        # commits resumed well past what in-flight coded acks could explain
        assert (mid["commit_bar"][:, 0] > pre[:, 0] + 12 * P).all(), (
            pre[:, 0],
            mid["commit_bar"][:, 0],
        )
        # new entries are stamped full-copy
        assert mid["win_full"][:, 0].any()
        check_agreement(mid, G, R, W)

        # heal: revived peers catch up; later appends flip back to coded
        state, ns, _ = run_segment(
            eng, state, ns, 120, n_prop=P, base_start=2000
        )
        fin = {k_: np.asarray(v) for k_, v in state.items()}
        assert (fin["commit_bar"][:, 0] > mid["commit_bar"][:, 0]).all()
        spread = fin["commit_bar"].max(axis=1) - fin["commit_bar"].min(axis=1)
        assert (spread <= 6 * P).all(), fin["commit_bar"]
        check_agreement(fin, G, R, W)


class TestFailover:
    def test_leader_crash_recovers_committed_values(self):
        G, R, W, P = 4, 5, 32, 4
        k = make_kernel(G, R, W, P, fault_tolerance=1, recon_interval=2)
        eng = Engine(k, seed=5)
        state, ns = eng.init()
        state, ns, _ = run_segment(eng, state, ns, 30, n_prop=P)
        pre = {k_: np.asarray(v) for k_, v in state.items()}
        pre_committed = [committed_values(pre, g, 1, W) for g in range(G)]
        assert all(len(cv) > 0 for cv in pre_committed)

        alive = jnp.ones((G, R), jnp.bool_).at[:, 0].set(False)
        state, ns, _ = run_segment(
            eng, state, ns, 400, n_prop=P, alive=alive, base_start=1000
        )
        post = {k_: np.asarray(v) for k_, v in state.items()}
        live_cb = post["commit_bar"][:, 1:]
        assert (
            live_cb.max(axis=1) > pre["commit_bar"][:, 1:].max(axis=1)
        ).all()
        for g in range(G):
            for r in range(1, R):
                vals = committed_values(post, g, r, W)
                for slot, v in pre_committed[g].items():
                    if slot in vals:
                        assert vals[slot] == v, (g, r, slot, v, vals[slot])
        check_agreement(post, G, R, W)


class TestLossyNetwork:
    def test_agreement_under_drops(self):
        G, R, W, P = 2, 5, 64, 4
        cfg = ReplicaConfigCRaft(
            max_proposals_per_tick=P,
            fault_tolerance=1,
            hear_timeout_lo=40,
            hear_timeout_hi=80,
        )
        k = make_protocol("craft", G, R, W, cfg)
        net = NetConfig(
            delay_ticks=1, jitter_ticks=2, drop_rate=0.2, max_delay_ticks=4
        )
        eng = Engine(k, netcfg=net, seed=23)
        state, ns = eng.init()
        state, ns, _ = run_segment(eng, state, ns, 400, n_prop=P)
        st = {k_: np.asarray(v) for k_, v in state.items()}
        assert (st["commit_bar"].max(axis=1) > 50).all()
        check_agreement(st, G, R, W)
