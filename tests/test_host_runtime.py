"""Host runtime unit tests: native WAL semantics (against the reference's
storage-action contract, storage.rs:511+), state machine semantics with a
random oracle (statemach.rs:222-409 pattern), and the payload store."""

import os
import random

import pytest

from summerset_tpu.host import (
    Command,
    LogAction,
    PayloadStore,
    StateMachine,
    StorageHub,
)
from summerset_tpu.host.statemach import apply_command
from summerset_tpu.native import load_wal


@pytest.fixture(params=["native", "python"])
def hub(request, tmp_path):
    if request.param == "native" and load_wal() is None:
        pytest.skip("native WAL backend unavailable")
    h = StorageHub(
        str(tmp_path / "test.wal"),
        prefer_native=(request.param == "native"),
    )
    yield h
    h.stop()


class TestStorage:
    def test_append_read_roundtrip(self, hub):
        entries = [("put", f"k{i}", "v" * i) for i in range(10)]
        offs = [0]
        for e in entries:
            res = hub.do_sync_action(LogAction("append", entry=e))
            offs.append(res.end_offset)
        for i, e in enumerate(entries):
            res = hub.do_sync_action(LogAction("read", offset=offs[i]))
            assert res.offset_ok and res.entry == e
            assert res.end_offset == offs[i + 1]
        # read past end fails cleanly
        res = hub.do_sync_action(LogAction("read", offset=offs[-1]))
        assert not res.offset_ok

    def test_write_truncate(self, hub):
        a = hub.do_sync_action(LogAction("append", entry="one"))
        b = hub.do_sync_action(LogAction("append", entry="two"))
        # overwrite entry 2 in place
        res = hub.do_sync_action(
            LogAction("write", entry="TWO", offset=a.end_offset, sync=True)
        )
        assert res.end_offset >= b.end_offset - 1
        got = hub.do_sync_action(LogAction("read", offset=a.end_offset))
        assert got.entry == "TWO"
        # truncate back to entry 1
        res = hub.do_sync_action(
            LogAction("truncate", offset=a.end_offset)
        )
        assert res.offset_ok and res.now_size == a.end_offset
        assert not hub.do_sync_action(
            LogAction("read", offset=a.end_offset)
        ).offset_ok

    def test_discard_keeps_header(self, hub):
        head = hub.do_sync_action(LogAction("append", entry="header"))
        mid = hub.do_sync_action(LogAction("append", entry="old"))
        hub.do_sync_action(LogAction("append", entry="new"))
        res = hub.do_sync_action(
            LogAction("discard", offset=mid.end_offset,
                      keep=head.end_offset)
        )
        assert res.offset_ok
        assert hub.do_sync_action(
            LogAction("read", offset=0)
        ).entry == "header"
        assert hub.do_sync_action(
            LogAction("read", offset=head.end_offset)
        ).entry == "new"

    def test_reopen_preserves_log(self, tmp_path):
        path = str(tmp_path / "re.wal")
        h1 = StorageHub(path)
        h1.do_sync_action(LogAction("append", entry={"x": 1}, sync=True))
        end = h1.size
        h1.stop()
        h2 = StorageHub(path)
        assert h2.size == end
        assert h2.do_sync_action(
            LogAction("read", offset=0)
        ).entry == {"x": 1}
        h2.stop()

    def test_torn_tail_detected_and_truncatable(self, tmp_path):
        """Crash mid-group-commit leaves a partial record: reads must
        fail cleanly at the torn frame (not past it), and truncating the
        tail restores appendability — the recovery path's contract
        (server._recover_from_wal torn-tail truncation)."""
        path = str(tmp_path / "torn.wal")
        h = StorageHub(path)
        a = h.do_sync_action(LogAction("append", entry="good", sync=True))
        h.stop()
        with open(path, "ab") as f:  # torn frame: header, missing body
            f.write((999999).to_bytes(8, "little") + b"par")
        h2 = StorageHub(path)
        ok = h2.do_sync_action(LogAction("read", offset=0))
        assert ok.offset_ok and ok.entry == "good"
        torn = h2.do_sync_action(LogAction("read", offset=a.end_offset))
        assert not torn.offset_ok
        res = h2.do_sync_action(
            LogAction("truncate", offset=a.end_offset, sync=True)
        )
        assert res.offset_ok
        h2.do_sync_action(LogAction("append", entry="after", sync=False))
        assert h2.do_sync_action(LogAction("sync")).offset_ok
        back = h2.do_sync_action(LogAction("read", offset=a.end_offset))
        assert back.offset_ok and back.entry == "after"
        h2.stop()

    def test_pywal_crash_recovery_truncates_torn_tail(self, tmp_path):
        """_PyWal crash-recovery contract (ISSUE 2 satellite): a torn /
        partial tail record left by a crash mid-write — injected through
        the nemesis WAL fault plane, which persists a header + body
        prefix exactly like an interrupted write — is detected by the
        recovery scan and truncated, never parsed as garbage; records
        before the tear survive, and post-recovery appends land cleanly
        where the tear was cut."""
        path = str(tmp_path / "crash.wal")
        hub = StorageHub(path, prefer_native=False)
        hub.do_sync_action(LogAction("append", entry=("vote", 0, {"a": 1}),
                                     sync=True))
        good = hub.do_sync_action(
            LogAction("append", entry=(0, 5, 7, [("c", "put")]),
                      sync=True)
        )
        hub.set_faults({"torn": 1})
        res = hub.do_sync_action(
            LogAction("append", entry=("vote", 0, {"a": 2}))
        )
        assert not res.offset_ok  # the "crash": nothing past here acked
        hub.stop()
        assert os.path.getsize(path) > good.end_offset  # partial tail

        # restart: replay the WAL the way server._recover_from_wal does
        rec = StorageHub(path, prefer_native=False)
        off, entries = 0, []
        while True:
            r = rec.do_sync_action(LogAction("read", offset=off))
            if not r.offset_ok or r.entry is None:
                break
            entries.append(r.entry)
            off = r.end_offset
        # both intact records replayed; the torn tail is NOT parsed
        assert entries == [
            ("vote", 0, {"a": 1}), (0, 5, 7, [("c", "put")]),
        ]
        assert off == good.end_offset
        # torn-tail condition detected and truncated (recovery contract)
        assert off < rec.size
        t = rec.do_sync_action(
            LogAction("truncate", offset=off, sync=True)
        )
        assert t.offset_ok and rec.size == good.end_offset
        # post-recovery appends land where the tear was cut
        after = rec.do_sync_action(
            LogAction("append", entry="post", sync=True)
        )
        assert after.end_offset > good.end_offset
        back = rec.do_sync_action(LogAction("read", offset=off))
        assert back.offset_ok and back.entry == "post"
        rec.stop()

    def test_pywal_garbage_length_tail_not_parsed(self, tmp_path):
        """A tail whose 8-byte length prefix is garbage (huge) must read
        as end-of-log, not allocate/parse past the file."""
        path = str(tmp_path / "garb.wal")
        hub = StorageHub(path, prefer_native=False)
        good = hub.do_sync_action(
            LogAction("append", entry="keep", sync=True)
        )
        hub.stop()
        with open(path, "ab") as f:
            f.write((1 << 60).to_bytes(8, "little") + b"\xff" * 16)
        rec = StorageHub(path, prefer_native=False)
        assert rec.do_sync_action(
            LogAction("read", offset=0)
        ).entry == "keep"
        torn = rec.do_sync_action(
            LogAction("read", offset=good.end_offset)
        )
        assert not torn.offset_ok and torn.entry is None
        assert rec.do_sync_action(
            LogAction("truncate", offset=good.end_offset, sync=True)
        ).offset_ok
        rec.stop()

    def test_native_backend_used_when_available(self, tmp_path):
        if load_wal() is None:
            pytest.skip("no toolchain")
        h = StorageHub(str(tmp_path / "n.wal"))
        assert h.native
        h.stop()


class TestStateMachine:
    def test_semantics(self):
        sm = StateMachine()
        assert sm.do_sync_cmd(Command("get", "a")).value is None
        assert sm.do_sync_cmd(Command("put", "a", "1")).old_value is None
        assert sm.do_sync_cmd(Command("get", "a")).value == "1"
        assert sm.do_sync_cmd(Command("put", "a", "2")).old_value == "1"
        assert sm.do_sync_cmd(Command("get", "a")).value == "2"
        sm.stop()

    def test_random_against_dict_oracle(self):
        sm = StateMachine()
        oracle = {}
        rng = random.Random(7)
        for _ in range(500):
            key = f"k{rng.randrange(10)}"
            if rng.random() < 0.5:
                val = str(rng.randrange(1000))
                res = sm.do_sync_cmd(Command("put", key, val))
                assert res.old_value == oracle.get(key)
                oracle[key] = val
            else:
                res = sm.do_sync_cmd(Command("get", key))
                assert res.value == oracle.get(key)
        assert sm.snapshot_items() == oracle
        sm.stop()

    def test_async_queue_ordering(self):
        sm = StateMachine()
        for i in range(100):
            sm.submit_cmd(i, Command("put", "k", str(i)))
        for i in range(100):
            cid, res = sm.get_result(timeout=5)
            assert cid == i
        assert sm.do_sync_cmd(Command("get", "k")).value == "99"
        sm.stop()

    def test_apply_command_pure(self):
        kv = {}
        assert apply_command(kv, Command("put", "x", "1")).old_value is None
        assert apply_command(kv, Command("get", "x")).value == "1"


class TestPayloadStore:
    def test_ids_and_gc(self):
        ps = PayloadStore(2)
        v1 = ps.put(0, ["a"])
        v2 = ps.put(0, ["b"])
        w1 = ps.put(1, ["c"])
        assert (v1, v2, w1) == (1, 2, 1)
        assert ps.get(0, v1) == ["a"]
        assert ps.get(0, 0) is None  # no-op sentinel
        assert ps.gc_below(0, v2) == 1
        assert ps.get(0, v1) is None
        assert ps.get(0, v2) == ["b"]
        assert ps.get(1, w1) == ["c"]


class TestTailWritesKey:
    """Regression: the voted-tail scan behind near-quorum reads must not
    bound its window scan by vote_bar/next_slot — a higher-ballot accept
    run-reset rewinds vote_bar WITHOUT zeroing win_bal above it, and a
    committed write voted at the old ballot above the rewound bar used to
    be invisible (hit=False), letting a fast read return an older value
    (parity role: quorumread.rs refresh_highest_slot survives resets)."""

    @staticmethod
    def _bare_server(win_abs, win_bal, win_val):
        import numpy as np

        from summerset_tpu.host.server import ServerReplica as Server

        srv = Server.__new__(Server)
        srv.me = 0
        srv.applied = [0]
        srv.payloads = PayloadStore(1)
        srv.state = {
            "win_abs": np.asarray([[win_abs]], dtype=np.int32),
            "win_bal": np.asarray([[win_bal]], dtype=np.int32),
            "win_val": np.asarray([[win_val]], dtype=np.int32),
            "vote_bar": np.asarray([[1]], dtype=np.int32),
            "next_slot": np.asarray([[1]], dtype=np.int32),
        }

        class _Ker:
            VALUE_WINDOW = "win_val"

        srv.kernel = _Ker()
        return srv

    def test_vote_above_rewound_bar_still_blocks_fast_read(self):
        from summerset_tpu.host.server import ApiRequest

        # slot 2 holds a voted put("k") at vid 7, but vote_bar/next_slot
        # were rewound to 1 by a ballot reset
        srv = self._bare_server(
            win_abs=[0, 1, 2, 3], win_bal=[0, 0, 5, 0],
            win_val=[0, 0, 7, 0],
        )
        srv.payloads._data[0][7] = [
            (0, ApiRequest("req", 0, Command("put", "k", "v2")))
        ]
        assert srv._tail_writes_key(0, "k") is True
        # a different key in the same tail does not block
        assert srv._tail_writes_key(0, "other") is False

    def test_unresolvable_payload_is_conservative(self):
        srv = self._bare_server(
            win_abs=[0, 1, 2, 3], win_bal=[0, 0, 5, 0],
            win_val=[0, 0, 9, 0],
        )
        # vid 9 payload is unknown locally: must count as a hit
        assert srv._tail_writes_key(0, "k") is True

    def test_applied_slots_do_not_block(self):
        srv = self._bare_server(
            win_abs=[0, 1, 2, 3], win_bal=[3, 3, 0, 0],
            win_val=[4, 5, 0, 0],
        )
        srv.applied = [2]  # both voted slots already executed
        assert srv._tail_writes_key(0, "k") is False


class TestUniqueWindowVids:
    def test_matches_python_reference(self):
        import numpy as np

        from summerset_tpu.host.server import _unique_window_vids

        rng = np.random.default_rng(7)
        G, W = 37, 16
        win = rng.integers(-2, 9, size=(G, W)).astype(np.int32)
        groups = np.asarray([0, 3, 5, 36, 12])
        got = _unique_window_vids(win, groups)
        for g in groups:
            ref = sorted(
                {int(x) for x in win[int(g)].ravel() if int(x) > 0}
            )
            assert got.get(int(g), []) == ref, g
        assert set(got) <= {int(g) for g in groups}

    def test_empty_inputs(self):
        import numpy as np

        from summerset_tpu.host.server import _unique_window_vids

        assert _unique_window_vids(np.zeros((4, 8)), np.asarray([])) == {}
        assert _unique_window_vids(
            np.zeros((4, 8), np.int32), np.asarray([1, 2])
        ) == {}
