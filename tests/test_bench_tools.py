"""Bench toolkit units: YCSB trace loading, value-size schedules, and
the external-system adapters' pure mapping + gating."""

import os
import sys

import pytest

# scripts/ modules (utils_net) are imported by several test classes; the
# insert lives at module scope so every test passes in isolation
sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "scripts",
))

from summerset_tpu.client.bench import load_ycsb_trace, parse_value_schedule
from summerset_tpu.client.external_systems import (
    decode_value,
    encode_value,
    zk_path,
)
from summerset_tpu.utils.errors import SummersetError


class TestValueSchedule:
    def test_bare_int(self):
        assert parse_value_schedule("128") == [(0.0, 128)]

    def test_schedule(self):
        assert parse_value_schedule("0:64/5:1024") == [
            (0.0, 64), (5.0, 1024),
        ]


class TestYcsbTrace:
    def test_load(self, tmp_path):
        p = tmp_path / "run.log"
        p.write_text(
            "READ usertable user1 [ field0 ]\n"
            "UPDATE usertable user2 [ field0=hello ]\n"
            "INSERT usertable user3 [ field0=init ]\n"
            "SCAN usertable user4 17 [ field0 ]\n"
            "OVERALL, RunTime(ms), 123\n"
            "short\n"
        )
        trace = load_ycsb_trace(str(p))
        assert trace == [
            ("get", "user1", None),
            ("put", "user2", "field0=hello"),
            ("put", "user3", "field0=init"),
            # SCAN rows replay as range reads: slot 3 = YCSB count
            ("scan", "user4", "17"),
        ]


class TestExternalAdapters:
    def test_zk_path_mapping(self):
        assert zk_path("/summerset", "a/b") == "/summerset/a_b"
        assert zk_path("/summerset/", "k") == "/summerset/k"

    def test_value_roundtrip(self):
        assert decode_value(encode_value("héllo")) == "héllo"
        assert decode_value(None) is None

    def test_zookeeper_gated_without_kazoo(self):
        from summerset_tpu.client.external_systems import ZooKeeperSession

        with pytest.raises((SummersetError, Exception)):
            ZooKeeperSession("127.0.0.1:2181", timeout=0.1)

    def test_etcd_gated_without_etcd3(self):
        from summerset_tpu.client.external_systems import EtcdKvClient

        with pytest.raises((SummersetError, Exception)):
            EtcdKvClient(("127.0.0.1", 2379), timeout=0.1)


class TestNetemCmds:
    def test_command_construction(self):
        import os
        import sys

        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "scripts",
        ))
        from utils_net import clear_cmd, netem_cmd

        cmd = netem_cmd("veth0", delay_ms=10, jitter_ms=2,
                        rate_gbps=1, loss_pct=0.5)
        assert cmd[:7] == [
            "tc", "qdisc", "replace", "dev", "veth0", "root", "netem",
        ]
        assert "delay" in cmd and "10ms" in cmd and "2ms" in cmd
        assert "loss" in cmd and "0.5%" in cmd
        assert "rate" in cmd and "1gbit" in cmd
        assert clear_cmd("veth0") == [
            "tc", "qdisc", "del", "dev", "veth0", "root",
        ]

    def test_graceful_degradation_probe(self):
        import os
        import sys

        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "scripts",
        ))
        from utils_net import netem_available

        # must not raise regardless of kernel capabilities
        assert netem_available("lo") in (True, False)


class TestNetnsVeth:
    """netns/veth orchestration (parity: reference local_cluster.py
    --use-veth + scripts/utils/net.py): command construction is pure and
    checked here; live application is gated on netns_available()."""

    def test_command_construction(self):
        from utils_net import (
            BRIDGE, bridge_cmds, bridge_ip, netns_cmds,
            netns_exec_prefix, netns_name, netns_teardown_cmds,
            replica_ip,
        )

        assert netns_name(2) == "smtpu2"
        assert replica_ip(0) == "10.77.0.10"
        assert bridge_ip() == "10.77.0.1"
        bc = bridge_cmds()
        assert bc[0][:4] == ["ip", "link", "add", BRIDGE]
        nc = netns_cmds(1)
        assert ["ip", "netns", "add", "smtpu1"] in nc
        # veth peer lands inside the namespace
        assert any("netns" in c and "veth" in " ".join(c) for c in nc)
        # every namespace gets lo up (servers dial themselves on it)
        assert ["ip", "-n", "smtpu1", "link", "set", "lo", "up"] in nc
        td = netns_teardown_cmds(2)
        assert ["ip", "netns", "del", "smtpu0"] in td
        assert td[-1] == ["ip", "link", "del", BRIDGE]
        assert netns_exec_prefix(0) == ["ip", "netns", "exec", "smtpu0"]

    def test_probe_and_graceful_setup(self):
        from utils_net import netns_available, setup_veth_cluster

        avail = netns_available()
        assert avail in (True, False)
        if not avail:
            # setup must fail with a message, never raise, and leave no
            # state behind (teardown best-effort runs inside)
            err = setup_veth_cluster(2)
            assert err is None or isinstance(err, str)
        else:  # pragma: no cover - needs CAP_NET_ADMIN
            from utils_net import teardown_veth_cluster

            assert setup_veth_cluster(2) is None
            teardown_veth_cluster(2)

    def test_local_cluster_flag_parses(self):
        import subprocess
        import sys

        # --help must show the flag (arg wiring sanity without launching)
        r = subprocess.run(
            [sys.executable, "scripts/local_cluster.py", "--help"],
            capture_output=True, text=True, timeout=60,
        )
        assert r.returncode == 0
        assert "--use-veth" in r.stdout and "--netem" in r.stdout


class TestBenchBackendFallback:
    def test_dead_backend_degrades_to_labeled_cpu_run(self):
        """bench.py must not exit rc=1 when the TPU tunnel is down
        (BENCH_r05 recorded 0 slots/s): a failing backend probe degrades
        to the CPU-mesh path with an explicit backend label, so a
        degraded artifact can never masquerade as a TPU measurement."""
        import json
        import os
        import subprocess
        import sys

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)     # not an explicit CPU run
        env["BENCH_BACKEND_TIMEOUT"] = "0"  # probe can never pass
        env["BENCH_GROUPS"] = "8"
        env["BENCH_TICKS"] = "32"
        env["BENCH_RUNS"] = "1"
        env["BENCH_PROPS"] = "8"
        proc = subprocess.run(
            [sys.executable, os.path.join(repo, "bench.py")],
            env=env, capture_output=True, text=True, timeout=300,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        doc = json.loads(proc.stdout.strip().splitlines()[-1])
        assert doc["backend"] == "cpu"
        assert "fallback" in doc["backend_note"]
        assert doc["value"] > 0
        # the artifact judges itself (graftprof satellite): a live
        # capture carries ok=true and the analytic stamp at its shape
        assert doc["ok"] is True
        gp = doc["graftprof"]
        assert gp["shape"]["G"] == 8
        assert gp["analytic"]["hlo_instructions"] > 0
        assert gp["analytic"]["hlo_ops_by_phase"]["ingest_accept"] > 0

    @pytest.mark.parametrize("tally", ["pairwise", "collective"])
    def test_mesh_survives_fallback_and_stamps_donation(self, tally):
        """`bench.py --mesh GxR` through the dead-backend fallback: the
        re-exec'd CPU child rebuilds the SAME mesh shape as a virtual
        CPU mesh (spec carried via BENCH_MESH), and the artifact stamps
        the mesh block with a fully-donated carry — a mesh capture that
        lost donation would fail its own ok verdict.  Parametrized over
        both quorum-tally modes: the default pairwise fallback path
        stays covered, and the collective mode must survive the re-exec
        (env BENCH_TALLY) and stamp next to the mesh block."""
        import json
        import os
        import subprocess
        import sys

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)     # not an explicit CPU run
        env["BENCH_BACKEND_TIMEOUT"] = "0"  # probe can never pass
        env["BENCH_GROUPS"] = "8"
        env["BENCH_TICKS"] = "32"
        env["BENCH_RUNS"] = "1"
        env["BENCH_PROPS"] = "8"
        args = [sys.executable, os.path.join(repo, "bench.py"),
                "--mesh", "2x1"]
        if tally != "pairwise":
            args += ["--tally", tally]
        proc = subprocess.run(
            args, env=env, capture_output=True, text=True, timeout=300,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        doc = json.loads(proc.stdout.strip().splitlines()[-1])
        assert doc["backend"] == "cpu"
        assert doc["ok"] is True and doc["value"] > 0
        mesh = doc["mesh"]
        assert mesh["mesh"] == "2x1"
        assert mesh["devices"] == 2
        assert mesh["groups_per_device"] == 4
        don = mesh["donation"]
        assert don["aliased_buffers"] == don["carry_leaves"] > 0
        assert "mesh 2x1" in doc["metric"]
        assert doc["tally"] == tally
