"""Bench toolkit units: YCSB trace loading, value-size schedules, and
the external-system adapters' pure mapping + gating."""

import pytest

from summerset_tpu.client.bench import load_ycsb_trace, parse_value_schedule
from summerset_tpu.client.external_systems import (
    decode_value,
    encode_value,
    zk_path,
)
from summerset_tpu.utils.errors import SummersetError


class TestValueSchedule:
    def test_bare_int(self):
        assert parse_value_schedule("128") == [(0.0, 128)]

    def test_schedule(self):
        assert parse_value_schedule("0:64/5:1024") == [
            (0.0, 64), (5.0, 1024),
        ]


class TestYcsbTrace:
    def test_load(self, tmp_path):
        p = tmp_path / "run.log"
        p.write_text(
            "READ usertable user1 [ field0 ]\n"
            "UPDATE usertable user2 [ field0=hello ]\n"
            "INSERT usertable user3 [ field0=init ]\n"
            "SCAN usertable user4 17 [ field0 ]\n"
            "OVERALL, RunTime(ms), 123\n"
            "short\n"
        )
        trace = load_ycsb_trace(str(p))
        assert trace == [
            ("get", "user1", None),
            ("put", "user2", "field0=hello"),
            ("put", "user3", "field0=init"),
            ("get", "user4", None),
        ]


class TestExternalAdapters:
    def test_zk_path_mapping(self):
        assert zk_path("/summerset", "a/b") == "/summerset/a_b"
        assert zk_path("/summerset/", "k") == "/summerset/k"

    def test_value_roundtrip(self):
        assert decode_value(encode_value("héllo")) == "héllo"
        assert decode_value(None) is None

    def test_zookeeper_gated_without_kazoo(self):
        from summerset_tpu.client.external_systems import ZooKeeperSession

        with pytest.raises((SummersetError, Exception)):
            ZooKeeperSession("127.0.0.1:2181", timeout=0.1)

    def test_etcd_gated_without_etcd3(self):
        from summerset_tpu.client.external_systems import EtcdKvClient

        with pytest.raises((SummersetError, Exception)):
            EtcdKvClient(("127.0.0.1", 2379), timeout=0.1)


class TestNetemCmds:
    def test_command_construction(self):
        import os
        import sys

        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "scripts",
        ))
        from utils_net import clear_cmd, netem_cmd

        cmd = netem_cmd("veth0", delay_ms=10, jitter_ms=2,
                        rate_gbps=1, loss_pct=0.5)
        assert cmd[:7] == [
            "tc", "qdisc", "replace", "dev", "veth0", "root", "netem",
        ]
        assert "delay" in cmd and "10ms" in cmd and "2ms" in cmd
        assert "loss" in cmd and "0.5%" in cmd
        assert "rate" in cmd and "1gbit" in cmd
        assert clear_cmd("veth0") == [
            "tc", "qdisc", "del", "dev", "veth0", "root",
        ]

    def test_graceful_degradation_probe(self):
        import os
        import sys

        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "scripts",
        ))
        from utils_net import netem_available

        # must not raise regardless of kernel capabilities
        assert netem_available("lo") in (True, False)
